"""Flash attention kernel vs dense reference (interpret mode on CPU)."""
import jax
import jax.numpy as jnp
import pytest

from nos_tpu.ops import flash_attention
from tests.parallel.test_ring_attention import dense_reference, random_qkv


def dense_4d(q, k, v, causal=True):
    out = dense_reference(q, k, v, causal=causal)  # [B, S, Hq*hd]
    b, s, hq, hd = q.shape
    return out.reshape(b, s, hq, hd)


class TestFlashAttention:
    @pytest.mark.parametrize("causal", [True, False])
    def test_matches_dense(self, causal):
        q, k, v = random_qkv(jax.random.key(0), b=2, s=64, hq=4, hkv=4, hd=16)
        got = flash_attention(q, k, v, causal=causal, blk_q=16, blk_k=16, interpret=True)
        want = dense_4d(q, k, v, causal=causal)
        assert got.shape == q.shape
        assert jnp.allclose(got, want, atol=1e-5), float(jnp.abs(got - want).max())

    def test_gqa_grouping(self):
        q, k, v = random_qkv(jax.random.key(1), b=1, s=32, hq=8, hkv=2, hd=8)
        got = flash_attention(q, k, v, blk_q=8, blk_k=8, interpret=True)
        want = dense_4d(q, k, v)
        assert jnp.allclose(got, want, atol=1e-5)

    def test_single_block(self):
        q, k, v = random_qkv(jax.random.key(2), b=1, s=8, hq=2, hkv=2, hd=8)
        got = flash_attention(q, k, v, interpret=True)  # blocks clamp to S
        want = dense_4d(q, k, v)
        assert jnp.allclose(got, want, atol=1e-5)

    def test_bfloat16_inputs(self):
        q, k, v = random_qkv(jax.random.key(3), b=1, s=32, hq=2, hkv=2, hd=8)
        q, k, v = (x.astype(jnp.bfloat16) for x in (q, k, v))
        got = flash_attention(q, k, v, blk_q=16, blk_k=16, interpret=True)
        want = dense_4d(q, k, v).astype(jnp.bfloat16)
        assert got.dtype == jnp.bfloat16
        assert jnp.allclose(
            got.astype(jnp.float32), want.astype(jnp.float32), atol=3e-2
        )

    def test_llama_flash_forward_matches_dense(self):
        from nos_tpu.models.llama import init_llama_params, llama_forward, tiny_config

        dense_cfg = tiny_config()
        flash_cfg = tiny_config(attention="flash")
        params = init_llama_params(jax.random.key(0), dense_cfg)
        tokens = jax.random.randint(jax.random.key(1), (2, 16), 0, dense_cfg.vocab_size)
        a = llama_forward(params, tokens, dense_cfg)
        b = llama_forward(params, tokens, flash_cfg)
        # bf16 activations: the dense path rounds softmax probs to bf16
        # before the PV matmul, flash accumulates in f32 — logits agree to
        # bf16 noise, and the predicted distributions match closely.
        assert jnp.allclose(a, b, atol=1e-1), float(jnp.abs(a - b).max())
        pa = jax.nn.softmax(a, axis=-1)
        pb = jax.nn.softmax(b, axis=-1)
        assert float(jnp.abs(pa - pb).max()) < 3e-3

    def test_rejects_bad_head_grouping(self):
        q, k, v = random_qkv(jax.random.key(4), b=1, s=24, hq=3, hkv=2, hd=8)
        with pytest.raises(ValueError):
            flash_attention(q, k, v, interpret=True)

    def test_odd_sequence_length_clamps_blocks(self):
        # 24 is not a multiple of the 16-block request: blocks clamp to the
        # largest divisor (12/8), no padding needed from the caller.
        q, k, v = random_qkv(jax.random.key(5), b=1, s=24, hq=4, hkv=2, hd=8)
        got = flash_attention(q, k, v, blk_q=16, blk_k=16, interpret=True)
        want = dense_4d(q, k, v)
        assert jnp.allclose(got, want, atol=1e-5)


class TestFlashBackward:
    """The custom_vjp recompute backward vs autodiff-through-dense."""

    @pytest.mark.parametrize("causal", [True, False])
    def test_grads_match_dense(self, causal):
        q, k, v = random_qkv(jax.random.key(10), b=2, s=32, hq=4, hkv=4, hd=16)
        do_seed = jax.random.normal(jax.random.key(11), q.shape)

        def f_flash(q, k, v):
            out = flash_attention(
                q, k, v, causal=causal, blk_q=16, blk_k=16, interpret=True
            )
            return jnp.sum(out * do_seed)

        def f_dense(q, k, v):
            return jnp.sum(dense_4d(q, k, v, causal=causal) * do_seed)

        g_flash = jax.grad(f_flash, argnums=(0, 1, 2))(q, k, v)
        g_dense = jax.grad(f_dense, argnums=(0, 1, 2))(q, k, v)
        for name, a, b in zip("qkv", g_flash, g_dense):
            assert jnp.allclose(a, b, atol=1e-4), (
                name,
                float(jnp.abs(a - b).max()),
            )

    def test_gqa_grads_sum_over_group(self):
        # dk/dv must aggregate all query heads in each kv head's group.
        q, k, v = random_qkv(jax.random.key(12), b=1, s=32, hq=8, hkv=2, hd=8)

        def f_flash(q, k, v):
            return jnp.sum(
                flash_attention(q, k, v, blk_q=8, blk_k=8, interpret=True) ** 2
            )

        def f_dense(q, k, v):
            return jnp.sum(dense_4d(q, k, v) ** 2)

        g_flash = jax.grad(f_flash, argnums=(0, 1, 2))(q, k, v)
        g_dense = jax.grad(f_dense, argnums=(0, 1, 2))(q, k, v)
        for name, a, b in zip("qkv", g_flash, g_dense):
            assert jnp.allclose(a, b, atol=1e-4), (
                name,
                float(jnp.abs(a - b).max()),
            )

    def test_uneven_blocks(self):
        q, k, v = random_qkv(jax.random.key(13), b=1, s=48, hq=2, hkv=2, hd=8)

        def f(blk_q, blk_k):
            return jax.grad(
                lambda q: jnp.sum(
                    flash_attention(
                        q, k, v, blk_q=blk_q, blk_k=blk_k, interpret=True
                    )
                    ** 2
                )
            )(q)

        # blk_q != blk_k exercises the rectangular causal frontier.
        assert jnp.allclose(f(16, 8), f(48, 48), atol=1e-4)

    def test_llama_flash_loss_grads_match_dense(self):
        """attention="flash" is trainable end to end (the round-2 landmine:
        grad-of-flash used to die inside Pallas AD)."""
        from nos_tpu.models.llama import init_llama_params, llama_loss, tiny_config

        dense_cfg = tiny_config()
        flash_cfg = tiny_config(attention="flash")
        params = init_llama_params(jax.random.key(0), dense_cfg)
        tokens = jax.random.randint(jax.random.key(1), (2, 16), 0, dense_cfg.vocab_size)

        l_d, g_d = jax.jit(
            jax.value_and_grad(lambda p: llama_loss(p, tokens, dense_cfg))
        )(params)
        l_f, g_f = jax.jit(
            jax.value_and_grad(lambda p: llama_loss(p, tokens, flash_cfg))
        )(params)
        assert abs(float(l_d) - float(l_f)) < 2e-2
        wq_d = jnp.asarray(g_d["layers"][0]["wq"], jnp.float32)
        wq_f = jnp.asarray(g_f["layers"][0]["wq"], jnp.float32)
        # bf16 model: dense rounds probs to bf16 pre-PV, flash stays f32.
        assert jnp.allclose(wq_d, wq_f, atol=3e-2), float(jnp.abs(wq_d - wq_f).max())

    def test_remat_grads_match_no_remat(self):
        from nos_tpu.models.llama import init_llama_params, llama_loss, tiny_config

        cfg = tiny_config()
        cfg_r = tiny_config(remat=True)
        params = init_llama_params(jax.random.key(0), cfg)
        tokens = jax.random.randint(jax.random.key(1), (2, 16), 0, cfg.vocab_size)
        l, g = jax.jit(jax.value_and_grad(lambda p: llama_loss(p, tokens, cfg)))(params)
        l_r, g_r = jax.jit(
            jax.value_and_grad(lambda p: llama_loss(p, tokens, cfg_r))
        )(params)
        assert abs(float(l) - float(l_r)) < 1e-4
        a = jnp.asarray(g["layers"][0]["wq"], jnp.float32)
        b = jnp.asarray(g_r["layers"][0]["wq"], jnp.float32)
        # Under jit, XLA fuses the remat recomputation differently from
        # the primal pass, so bf16 grads differ by a few ulps (the exact
        # 1e-6 match only held op-by-op on the eager path).
        assert jnp.allclose(a, b, atol=2e-3), float(jnp.abs(a - b).max())


class TestBlockPartials:
    """The ring-attention engine: block partials + exact merge + per-block
    gradients must reconstruct full attention."""

    def test_two_blocks_merge_to_full(self):
        from nos_tpu.ops.flash_attention import (
            flash_attention_block,
            merge_flash_partials,
        )

        q, k, v = random_qkv(jax.random.key(20), b=2, s=32, hq=4, hkv=2, hd=16)
        half = 16
        o1, l1 = flash_attention_block(
            q, k[:, :half], v[:, :half], 0, 0, interpret=True
        )
        o2, l2 = flash_attention_block(
            q, k[:, half:], v[:, half:], 0, half, interpret=True
        )
        out, _ = merge_flash_partials(o1, l1, o2, l2)
        want = flash_attention(q, k, v, interpret=True)
        assert jnp.allclose(out, want, atol=1e-5), float(jnp.abs(out - want).max())

    def test_future_block_contributes_nothing(self):
        from nos_tpu.ops.flash_attention import flash_attention_block

        q, k, v = random_qkv(jax.random.key(21), b=1, s=16, hq=2, hkv=2, hd=8)
        # kv block entirely in the future of every q row
        out, lse = flash_attention_block(q, k, v, 0, 1000, interpret=True)
        assert jnp.all(out == 0)
        assert jnp.all(jnp.isneginf(lse))

    def test_traced_offsets(self):
        from nos_tpu.ops.flash_attention import flash_attention_block

        q, k, v = random_qkv(jax.random.key(22), b=1, s=16, hq=2, hkv=2, hd=8)

        @jax.jit
        def with_offset(off):
            return flash_attention_block(q, k, v, off, 0, interpret=True)[0]

        a = with_offset(jnp.asarray(1000))  # all keys in the past: full attn
        b_ = flash_attention_block(q, k, v, 1000, 0, interpret=True)[0]
        assert jnp.allclose(a, b_, atol=1e-6)

    def test_block_grads_sum_to_full(self):
        from nos_tpu.ops.flash_attention import (
            flash_attention_block,
            flash_block_grads,
            merge_flash_partials,
        )

        q, k, v = random_qkv(jax.random.key(23), b=1, s=32, hq=2, hkv=2, hd=8)
        do = jax.random.normal(jax.random.key(24), q.shape)
        half = 16
        o1, l1 = flash_attention_block(q, k[:, :half], v[:, :half], 0, 0, interpret=True)
        o2, l2 = flash_attention_block(q, k[:, half:], v[:, half:], 0, half, interpret=True)
        out, lse = merge_flash_partials(o1, l1, o2, l2)

        dq1, dk1, dv1 = flash_block_grads(
            q, k[:, :half], v[:, :half], out, lse, do, 0, 0, interpret=True)
        dq2, dk2, dv2 = flash_block_grads(
            q, k[:, half:], v[:, half:], out, lse, do, 0, half, interpret=True)

        def f(q, k, v):
            return jnp.sum(flash_attention(q, k, v, interpret=True) * do)

        gq, gk, gv = jax.grad(f, argnums=(0, 1, 2))(q, k, v)
        assert jnp.allclose(dq1 + dq2, gq, atol=1e-4)
        assert jnp.allclose(jnp.concatenate([dk1, dk2], axis=1), gk, atol=1e-4)
        assert jnp.allclose(jnp.concatenate([dv1, dv2], axis=1), gv, atol=1e-4)


class TestSlidingWindowKernel:
    """The banded (Mistral) mask inside the kernel: forward and gradients
    vs the dense windowed oracle, plus the contract checks."""

    def dense_windowed(self, q, k, v, window):
        b, s, hq, hd = q.shape
        hkv = k.shape[2]
        g = hq // hkv
        qg = q.reshape(b, s, hkv, g, hd)
        scores = jnp.einsum(
            "bsKgh,btKh->bKgst", qg, k, preferred_element_type=jnp.float32
        ) / (hd ** 0.5)
        pos = jnp.arange(s)
        mask = (pos[None, :] <= pos[:, None]) & (
            pos[:, None] - pos[None, :] < window
        )
        scores = jnp.where(mask[None, None, None], scores, -jnp.inf)
        probs = jax.nn.softmax(scores, axis=-1).astype(q.dtype)
        return jnp.einsum("bKgst,btKh->bsKgh", probs, v).reshape(b, s, hq, hd)

    def qkv(self, key, b=1, s=64, hq=4, hkv=2, hd=16):
        kq, kk, kv = jax.random.split(key, 3)
        return (
            jax.random.normal(kq, (b, s, hq, hd), jnp.float32),
            jax.random.normal(kk, (b, s, hkv, hd), jnp.float32),
            jax.random.normal(kv, (b, s, hkv, hd), jnp.float32),
        )

    def test_forward_matches_dense_window(self):
        from nos_tpu.ops import flash_attention

        q, k, v = self.qkv(jax.random.key(60))
        for window in (3, 16, 100):  # partial band, block-sized, > S
            got = flash_attention(
                q, k, v, window=window, blk_q=16, blk_k=16, interpret=True
            )
            want = self.dense_windowed(q, k, v, window)
            assert jnp.allclose(got, want, atol=1e-5), (
                window, float(jnp.abs(got - want).max())
            )

    def test_gradients_match_dense_window(self):
        from nos_tpu.ops import flash_attention

        q, k, v = self.qkv(jax.random.key(61), s=32)
        seed = jax.random.normal(jax.random.key(62), (1, 32, 4, 16))

        def f_flash(q, k, v):
            return jnp.sum(
                flash_attention(
                    q, k, v, window=5, blk_q=8, blk_k=8, interpret=True
                ) * seed
            )

        def f_dense(q, k, v):
            return jnp.sum(self.dense_windowed(q, k, v, 5) * seed)

        g_f = jax.grad(f_flash, argnums=(0, 1, 2))(q, k, v)
        g_d = jax.grad(f_dense, argnums=(0, 1, 2))(q, k, v)
        for a, b_ in zip(g_f, g_d):
            assert jnp.allclose(a, b_, atol=1e-5), float(jnp.abs(a - b_).max())

    def test_window_requires_causal(self):
        from nos_tpu.ops import flash_attention

        q, k, v = self.qkv(jax.random.key(63), s=16)
        with pytest.raises(ValueError, match="causal"):
            flash_attention(q, k, v, causal=False, window=4, interpret=True)
        with pytest.raises(ValueError, match=">= 1"):
            flash_attention(q, k, v, window=0, interpret=True)


class TestCompactGridSpans:
    """The compact grid's span math must cover exactly the blocks
    _block_needed marks needed (zero offsets) — a missed block is a
    silently wrong output, an extra block only wasted DMA. Exhaustive
    check over block/window geometries."""

    @pytest.mark.parametrize("blk_q,blk_k,window", [
        (8, 8, None), (8, 16, None), (16, 8, None),
        (8, 8, 5), (8, 16, 12), (16, 8, 3), (8, 32, 9), (32, 8, 40),
    ])
    def test_kv_span_covers_needed_blocks(self, blk_q, blk_k, window):
        from nos_tpu.ops.flash_attention import (
            _block_needed,
            _compact_kv_steps,
            _kv_block_span,
        )

        s = 128
        n_q, n_k = s // blk_q, s // blk_k
        steps = _compact_kv_steps(n_k, blk_q, blk_k, window)
        for qi in range(n_q):
            lo, hi = jax.tree.map(int, _kv_block_span(qi, blk_q, blk_k, window))
            visited = {min(lo + t, hi) for t in range(steps) if lo + t <= hi}
            needed = {
                ki for ki in range(n_k)
                if bool(_block_needed(
                    blk_q, blk_k, qi * blk_q, ki * blk_k, True, window
                ))
            }
            assert needed <= visited, (
                f"qi={qi}: needed {sorted(needed)} not covered by "
                f"visited {sorted(visited)} (lo={lo} hi={hi} steps={steps})"
            )
            # clamped duplicates beyond hi never enter the span
            assert all(lo <= b_ <= hi for b_ in visited)

    @pytest.mark.parametrize("blk_q,blk_k,window", [
        (8, 8, None), (8, 16, 12), (16, 8, 3), (8, 32, 9), (32, 8, 40),
    ])
    def test_q_span_covers_needed_blocks(self, blk_q, blk_k, window):
        from nos_tpu.ops.flash_attention import (
            _block_needed,
            _compact_q_steps,
            _q_block_span,
        )

        s = 128
        n_q, n_k = s // blk_q, s // blk_k
        steps = _compact_q_steps(n_q, blk_q, blk_k, window)
        for kb in range(n_k):
            lo, hi = jax.tree.map(
                int, _q_block_span(kb, blk_q, blk_k, window, n_q)
            )
            visited = {min(lo + t, hi) for t in range(steps) if lo + t <= hi}
            needed = {
                qi for qi in range(n_q)
                if bool(_block_needed(
                    blk_q, blk_k, qi * blk_q, kb * blk_k, True, window
                ))
            }
            assert needed <= visited, (
                f"kb={kb}: needed {sorted(needed)} not covered by "
                f"visited {sorted(visited)}"
            )

    def test_traced_offsets_disable_compact(self):
        """Block partials (ring attention) pass traced offsets; the
        compact precondition (zero global offsets) must gate off."""
        from nos_tpu.ops.flash_attention import _static_zero

        assert _static_zero(0)
        assert not _static_zero(64)
        assert _static_zero(jnp.asarray(0))  # concrete zero IS static
        seen = []
        jax.jit(lambda off: seen.append(_static_zero(off)))(jnp.asarray(0))
        assert seen == [False]  # a tracer can never qualify
