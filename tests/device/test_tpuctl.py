"""Native tpuctl library tests: build it, then exercise the C++ slice
placement and state management through the ctypes binding."""
import threading

import pytest

from nos_tpu.device.tpuctl import (
    TpuctlDeviceClient,
    TpuctlError,
    TpuctlUnavailableError,
    build_library,
)


@pytest.fixture(scope="module")
def lib_built():
    try:
        build_library()
    except TpuctlUnavailableError as e:
        pytest.skip(f"native toolchain unavailable: {e}")


@pytest.fixture
def client(lib_built, tmp_path):
    return TpuctlDeviceClient(
        base_dir=str(tmp_path), board_topologies={"n1": ["2x4"], "n2": ["2x2x1"]}
    )


class TestCreateDelete:
    def test_create_and_list(self, client):
        client.create_slices("n1", 0, "2x2", 2)
        devices = client.get_slices("n1")
        assert [(d.board_index, d.profile) for d in devices] == [(0, "2x2"), (0, "2x2")]
        assert len({d.device_id for d in devices}) == 2

    def test_chip_assignment_is_contiguous_and_disjoint(self, client):
        client.create_slices("n1", 0, "2x2", 2)
        chips = client.chip_assignment("n1")
        all_chips = [c for chips_list in chips.values() for c in chips_list]
        assert sorted(all_chips) == list(range(8))  # exact cover of 2x4
        for chips_list in chips.values():
            assert len(chips_list) == 4

    def test_delete_frees_chips(self, client):
        client.create_slices("n1", 0, "2x4", 1)
        device = client.get_slices("n1")[0]
        with pytest.raises(TpuctlError):
            client.create_slices("n1", 0, "1x1", 1)  # board full
        client.delete_slice("n1", device.device_id)
        client.create_slices("n1", 0, "1x1", 8)
        assert len(client.get_slices("n1")) == 8

    def test_delete_missing_raises(self, client):
        with pytest.raises(TpuctlError, match="not found"):
            client.delete_slice("n1", "ghost")

    def test_overfull_create_rejected_atomically(self, client):
        with pytest.raises(TpuctlError, match="placement"):
            client.create_slices("n1", 0, "2x2", 3)  # only 2 fit
        assert client.get_slices("n1") == []

    def test_3d_board(self, client):
        client.create_slices("n2", 0, "1x2x1", 2)
        chips = client.chip_assignment("n2")
        all_chips = sorted(c for lst in chips.values() for c in lst)
        assert all_chips == list(range(4))

    def test_orientation_aware_placement(self, client):
        # 1x2 dominoes must tile the 2x4 board in any orientation mix.
        client.create_slices("n1", 0, "1x2", 4)
        assert len(client.get_slices("n1")) == 4

    def test_unknown_board_rejected(self, client):
        with pytest.raises(TpuctlError, match="unknown board"):
            client.create_slices("n1", 5, "1x1", 1)

    def test_delete_all_except(self, client):
        client.create_slices("n1", 0, "1x1", 4)
        keep = [d.device_id for d in client.get_slices("n1")[:2]]
        client.delete_all_except("n1", keep)
        assert sorted(d.device_id for d in client.get_slices("n1")) == sorted(keep)

    def test_state_survives_new_client(self, client, tmp_path):
        client.create_slices("n1", 0, "2x2", 1)
        fresh = TpuctlDeviceClient(
            base_dir=str(tmp_path), board_topologies={"n1": ["2x4"]}
        )
        assert len(fresh.get_slices("n1")) == 1


class TestFragmentation:
    def test_fragmented_board_rejects_big_slice(self, client):
        """The C++ layer models chips, not multisets: a fragmented board
        can fail a placement the profile arithmetic would allow."""
        client.create_slices("n1", 0, "1x1", 8)
        devices = client.get_slices("n1")
        chips = client.chip_assignment("n1")
        # free chips 0 and 7 (opposite corners) -> 2 free chips but no 1x2
        for d in devices:
            if chips[d.device_id] in ([0], [7]):
                client.delete_slice("n1", d.device_id)
        with pytest.raises(TpuctlError, match="placement"):
            client.create_slices("n1", 0, "1x2", 1)


class TestConcurrency:
    def test_parallel_creates_are_serialized(self, client):
        errors = []

        def create(i):
            try:
                client.create_slices("n1", 0, "1x1", 1)
            except TpuctlError as e:
                errors.append(e)

        threads = [threading.Thread(target=create, args=(i,)) for i in range(8)]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        assert not errors
        chips = client.chip_assignment("n1")
        all_chips = sorted(c for lst in chips.values() for c in lst)
        assert all_chips == list(range(8))  # no double-assignment


class TestEnumerate:
    def test_enumerate_fake_dev(self, client, tmp_path):
        dev = tmp_path / "dev"
        dev.mkdir()
        for i in range(4):
            (dev / f"accel{i}").touch()
        (dev / "null").touch()
        info = client.enumerate_host(str(dev))
        assert info["device_count"] == 4
        assert sorted(info["devices"]) == [f"accel{i}" for i in range(4)]


class TestBatchPlacement:
    def test_mixed_batch_is_order_independent(self, client):
        """Sequential first-fit would place 1x1s first and fragment the
        board; the batch backtracking must place the mixed set regardless
        of order (the NVML creation-order problem, solved exactly)."""
        client.create_slices_batch("n1", 0, {"1x1": 2, "1x2": 1, "2x2": 1})
        chips = client.chip_assignment("n1")
        all_chips = sorted(c for lst in chips.values() for c in lst)
        assert all_chips == list(range(8))

    def test_batch_atomic_on_failure(self, client):
        client.create_slices("n1", 0, "2x2", 1)
        with pytest.raises(TpuctlError, match="placement"):
            client.create_slices_batch("n1", 0, {"2x2": 1, "1x2": 3})  # 4+6 > 4 free
        assert len(client.get_slices("n1")) == 1

    def test_batch_respects_existing_slices(self, client):
        client.create_slices("n1", 0, "2x2", 1)
        client.create_slices_batch("n1", 0, {"1x1": 4})
        chips = client.chip_assignment("n1")
        all_chips = sorted(c for lst in chips.values() for c in lst)
        assert all_chips == list(range(8))
