"""Kubelet pod-resources gRPC client against an in-process gRPC server.

Mirrors the reference's client tests (pkg/resource/client_test.go pattern):
a real server on a unix socket, the real wire protocol, no shortcuts.
"""
import concurrent.futures
import os

import grpc
import pytest

from nos_tpu.device.podresources import (
    LIST_METHOD,
    KubeletPodResourcesClient,
)
from nos_tpu.device.proto import podresources_pb2 as pb


def make_response(entries):
    """entries: [(resource_name, [device_ids])]"""
    response = pb.ListPodResourcesResponse()
    pod = response.pod_resources.add()
    pod.name, pod.namespace = "train", "ml"
    container = pod.containers.add()
    container.name = "main"
    for resource_name, ids in entries:
        device = container.devices.add()
        device.resource_name = resource_name
        device.device_ids.extend(ids)
    return response


@pytest.fixture
def lister_server(tmp_path):
    """Real gRPC server on a unix socket; yields (socket_path, set_response)."""
    state = {"response": pb.ListPodResourcesResponse()}

    def handle_list(request, context):
        assert isinstance(request, pb.ListPodResourcesRequest)
        return state["response"]

    service = LIST_METHOD.strip("/").rsplit("/", 1)
    handler = grpc.method_handlers_generic_handler(
        service[0],
        {
            service[1]: grpc.unary_unary_rpc_method_handler(
                handle_list,
                request_deserializer=pb.ListPodResourcesRequest.FromString,
                response_serializer=pb.ListPodResourcesResponse.SerializeToString,
            )
        },
    )
    server = grpc.server(concurrent.futures.ThreadPoolExecutor(max_workers=2))
    server.add_generic_rpc_handlers((handler,))
    socket_path = os.path.join(tmp_path, "kubelet.sock")
    server.add_insecure_port(f"unix://{socket_path}")
    server.start()
    yield socket_path, lambda r: state.update(response=r)
    server.stop(grace=None)


class TestKubeletPodResourcesClient:
    def test_lists_tpu_device_ids(self, lister_server):
        socket_path, set_response = lister_server
        set_response(make_response([
            ("google.com/tpu-slice-2x2", ["tpu-0-slice-0", "tpu-0-slice-1"]),
            ("google.com/tpu", ["tpu-0-chip-3"]),
            ("nvidia.com/gpu", ["gpu-7"]),  # foreign resource: ignored
        ]))
        client = KubeletPodResourcesClient(socket_path=socket_path, timeout_seconds=5)
        try:
            assert client.get_used_device_ids("any-node") == [
                "tpu-0-chip-3",
                "tpu-0-slice-0",
                "tpu-0-slice-1",
            ]
        finally:
            client.close()

    def test_empty_allocation(self, lister_server):
        socket_path, _ = lister_server
        client = KubeletPodResourcesClient(socket_path=socket_path, timeout_seconds=5)
        try:
            assert client.get_used_device_ids() == []
        finally:
            client.close()

    def test_deduplicates_across_containers(self, lister_server):
        socket_path, set_response = lister_server
        response = make_response([("google.com/tpu-slice-1x1", ["d0"])])
        second = response.pod_resources[0].containers.add()
        second.name = "sidecar"
        device = second.devices.add()
        device.resource_name = "google.com/tpu-slice-1x1"
        device.device_ids.append("d0")
        set_response(response)
        client = KubeletPodResourcesClient(socket_path=socket_path, timeout_seconds=5)
        try:
            assert client.get_used_device_ids() == ["d0"]
        finally:
            client.close()

    def test_unreachable_socket_raises(self, tmp_path):
        client = KubeletPodResourcesClient(
            socket_path=os.path.join(tmp_path, "nope.sock"), timeout_seconds=0.5
        )
        try:
            with pytest.raises(grpc.RpcError):
                client.get_used_device_ids()
        finally:
            client.close()
