"""EventRecorder: dedup (count bumps), rate limiting (token bucket),
reason whitelisting, and the wire/store paths Events ride."""
import pytest

from nos_tpu.api.v1alpha1 import constants
from nos_tpu.kube.events import EventRecorder
from nos_tpu.kube.objects import Event, Node, ObjectMeta
from nos_tpu.kube.serde import from_wire, to_wire
from nos_tpu.kube.store import KubeStore

from tests.factory import build_pod


class FakeClock:
    def __init__(self, now=1000.0):
        self.now = now

    def __call__(self):
        return self.now

    def advance(self, seconds):
        self.now += seconds


@pytest.fixture()
def store():
    return KubeStore()


@pytest.fixture()
def clock():
    return FakeClock()


@pytest.fixture()
def recorder(store, clock):
    return EventRecorder(store, component="test", clock=clock)


class TestRecord:
    def test_first_record_creates_event(self, store, recorder):
        pod = build_pod("train", {constants.RESOURCE_TPU: 4}, ns="ml")
        ev = recorder.record(
            pod, constants.EVENT_REASON_FAILED_SCHEDULING, "no nodes", type="Warning"
        )
        assert ev is not None
        stored = store.list("Event", namespace="ml")
        assert len(stored) == 1
        assert stored[0].involved_kind == "Pod"
        assert stored[0].involved_namespace == "ml"
        assert stored[0].involved_name == "train"
        assert stored[0].reason == "FailedScheduling"
        assert stored[0].message == "no nodes"
        assert stored[0].type == "Warning"
        assert stored[0].count == 1
        assert stored[0].source_component == "test"

    def test_unknown_reason_raises(self, recorder):
        pod = build_pod("train", {})
        with pytest.raises(ValueError, match="EVENT_REASONS"):
            recorder.record(pod, "MadeUpReason", "msg")

    def test_cluster_scoped_object_lands_in_default_namespace(self, store, recorder):
        node = Node(metadata=ObjectMeta(name="tpu-1"))
        recorder.record(node, constants.EVENT_REASON_PARTITIONING_APPLIED, "carved")
        stored = store.list("Event", namespace="default")
        assert len(stored) == 1
        assert stored[0].involved_kind == "Node"
        assert stored[0].involved_namespace == ""

    def test_events_for_filters_and_sorts(self, store, recorder, clock):
        pod = build_pod("train", {}, ns="ml")
        other = build_pod("other", {}, ns="ml")
        recorder.record(pod, constants.EVENT_REASON_FAILED_SCHEDULING, "b")
        clock.advance(1.0)
        recorder.record(other, constants.EVENT_REASON_FAILED_SCHEDULING, "x")
        clock.advance(1.0)
        recorder.record(pod, constants.EVENT_REASON_SCHEDULED, "a")
        events = recorder.events_for(pod)
        assert [e.message for e in events] == ["b", "a"]


class TestDedup:
    def test_identical_event_bumps_count(self, store, recorder, clock):
        pod = build_pod("train", {}, ns="ml")
        first = recorder.record(pod, constants.EVENT_REASON_FAILED_SCHEDULING, "m")
        clock.advance(7.0)
        second = recorder.record(pod, constants.EVENT_REASON_FAILED_SCHEDULING, "m")
        assert second.metadata.name == first.metadata.name
        assert len(store.list("Event", namespace="ml")) == 1
        assert second.count == 2
        assert second.first_timestamp == first.first_timestamp
        assert second.last_timestamp == first.last_timestamp + 7.0

    def test_different_message_is_a_new_event(self, store, recorder):
        pod = build_pod("train", {}, ns="ml")
        recorder.record(pod, constants.EVENT_REASON_FAILED_SCHEDULING, "m1")
        recorder.record(pod, constants.EVENT_REASON_FAILED_SCHEDULING, "m2")
        assert len(store.list("Event", namespace="ml")) == 2

    def test_dedup_survives_a_second_recorder(self, store, recorder, clock):
        """Deterministic names: a restarted component keeps bumping the
        same Event object instead of writing a duplicate."""
        pod = build_pod("train", {}, ns="ml")
        recorder.record(pod, constants.EVENT_REASON_FAILED_SCHEDULING, "m")
        restarted = EventRecorder(store, component="test", clock=clock)
        ev = restarted.record(pod, constants.EVENT_REASON_FAILED_SCHEDULING, "m")
        assert ev.count == 2
        assert len(store.list("Event", namespace="ml")) == 1


class TestRateLimit:
    def test_burst_then_drop(self, store, clock):
        recorder = EventRecorder(
            store, burst=2, refill_per_second=1.0, clock=clock
        )
        pod = build_pod("train", {}, ns="ml")
        assert recorder.record(pod, constants.EVENT_REASON_FAILED_SCHEDULING, "m")
        assert recorder.record(pod, constants.EVENT_REASON_FAILED_SCHEDULING, "m")
        # Bucket exhausted: the third record is dropped, not raised.
        assert (
            recorder.record(pod, constants.EVENT_REASON_FAILED_SCHEDULING, "m")
            is None
        )
        assert recorder.dropped == 1
        assert store.list("Event", namespace="ml")[0].count == 2

    def test_refill_restores_tokens(self, store, clock):
        recorder = EventRecorder(
            store, burst=1, refill_per_second=1.0, clock=clock
        )
        pod = build_pod("train", {}, ns="ml")
        assert recorder.record(pod, constants.EVENT_REASON_FAILED_SCHEDULING, "m")
        assert (
            recorder.record(pod, constants.EVENT_REASON_FAILED_SCHEDULING, "m")
            is None
        )
        clock.advance(1.0)
        assert recorder.record(pod, constants.EVENT_REASON_FAILED_SCHEDULING, "m")
        assert store.list("Event", namespace="ml")[0].count == 2

    def test_buckets_are_per_object(self, store, clock):
        recorder = EventRecorder(
            store, burst=1, refill_per_second=0.0, clock=clock
        )
        a = build_pod("a", {}, ns="ml")
        b = build_pod("b", {}, ns="ml")
        assert recorder.record(a, constants.EVENT_REASON_FAILED_SCHEDULING, "m")
        # a's bucket is empty, b's is untouched.
        assert recorder.record(a, constants.EVENT_REASON_FAILED_SCHEDULING, "m") is None
        assert recorder.record(b, constants.EVENT_REASON_FAILED_SCHEDULING, "m")


class TestEventsOverApiserver:
    def test_record_and_dedup_through_the_api_store(self):
        """The recorder's create + merge-patch flow works over real HTTP
        against the sim apiserver (the envtest analogue): Events are a
        served resource, and the count bump is a plain main-resource
        PATCH."""
        import time as _time

        from nos_tpu.kube.apiclient import ClusterCredentials, KubeApiClient
        from nos_tpu.kube.apistore import KubeApiStore
        from tests.kube.stub_apiserver import StubApiServer

        with StubApiServer() as server:
            api_store = KubeApiStore(
                KubeApiClient(ClusterCredentials(server=server.url), timeout=5.0),
                kinds=("Pod", "Event"),
            )
            api_store.start(sync_timeout_s=10.0)
            try:
                recorder = EventRecorder(api_store, component="test")
                pod = build_pod("train", {}, ns="ml")
                first = recorder.record(
                    pod, constants.EVENT_REASON_FAILED_SCHEDULING, "m"
                )
                assert first is not None and first.count == 1
                second = recorder.record(
                    pod, constants.EVENT_REASON_FAILED_SCHEDULING, "m"
                )
                assert second.count == 2
                assert second.metadata.name == first.metadata.name

                # The informer cache converges to the single deduped Event.
                deadline = _time.monotonic() + 5.0
                while _time.monotonic() < deadline:
                    cached = api_store.list("Event", namespace="ml")
                    if cached and cached[0].count == 2:
                        break
                    _time.sleep(0.02)
                cached = api_store.list("Event", namespace="ml")
                assert len(cached) == 1
                assert cached[0].count == 2
                assert cached[0].reason == "FailedScheduling"
            finally:
                api_store.stop()


class TestEventWire:
    def test_round_trip(self):
        ev = Event(
            metadata=ObjectMeta(name="train.abc", namespace="ml"),
            involved_kind="Pod",
            involved_namespace="ml",
            involved_name="train",
            reason="FailedScheduling",
            message="0/3 nodes are available: ...",
            type="Warning",
            count=4,
            first_timestamp=1000.0,
            last_timestamp=1007.0,
            source_component="nos-scheduler",
        )
        wire = to_wire(ev)
        # Mutable dedup fields are TOP-LEVEL on the wire (no status
        # subresource), so the recorder's merge-patch path works against
        # a real apiserver.
        assert wire["count"] == 4
        assert wire["involvedObject"] == {
            "kind": "Pod",
            "namespace": "ml",
            "name": "train",
        }
        back = from_wire(wire)
        assert back.reason == ev.reason
        assert back.message == ev.message
        assert back.count == 4
        assert back.type == "Warning"
        assert back.involved_name == "train"
        assert back.source_component == "nos-scheduler"
        assert abs(back.first_timestamp - ev.first_timestamp) < 1.0
        assert abs(back.last_timestamp - ev.last_timestamp) < 1.0
