"""Compatibility shim: the stub apiserver graduated into the sim
subsystem (nos_tpu/sim/apiserver.py) so non-test harnesses
(hack/incluster_e2e.py) can boot it without importing tests/."""
from nos_tpu.sim.apiserver import *  # noqa
from nos_tpu.sim.apiserver import StubApiServer  # noqa: F401
