"""KubeApiStore against a real HTTP apiserver stub.

The envtest analogue for this suite (reference boots etcd+apiserver in
suite_int_test.go:56-63): every test talks through real sockets, chunked
watch streams, and resourceVersion conflicts — the exact code path a
production cluster exercises.
"""
import threading
import time

import pytest

from nos_tpu.api.v1alpha1 import labels
from nos_tpu.api.v1alpha1.constants import RESOURCE_TPU_CHIPS
from nos_tpu.api.v1alpha1.elasticquota import ElasticQuota, ElasticQuotaSpec
from nos_tpu.kube import serde
from nos_tpu.kube.apiclient import ApiError, ClusterCredentials, KubeApiClient
from nos_tpu.kube.apistore import KubeApiStore
from nos_tpu.kube.objects import (
    ConfigMap,
    Container,
    ObjectMeta,
    Pod,
    PodPhase,
    PodSpec,
)
from nos_tpu.kube.store import ConflictError, NotFoundError
from tests.kube.stub_apiserver import StubApiServer


@pytest.fixture()
def api():
    with StubApiServer() as server:
        yield server


def make_client(server: StubApiServer) -> KubeApiClient:
    return KubeApiClient(ClusterCredentials(server=server.url), timeout=5.0)


@pytest.fixture()
def store(api):
    s = KubeApiStore(make_client(api), kinds=("Pod", "Node", "ConfigMap", "ElasticQuota"))
    s.start(sync_timeout_s=10.0)
    yield s
    s.stop()


def wait_for(pred, timeout=5.0):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if pred():
            return True
        time.sleep(0.02)
    return False


def make_pod(name="p1", ns="default", chips=4) -> Pod:
    return Pod(
        metadata=ObjectMeta(name=name, namespace=ns),
        spec=PodSpec(containers=[Container(requests={RESOURCE_TPU_CHIPS: chips})]),
    )


class TestApiClient:
    def test_crud_roundtrip(self, api):
        client = make_client(api)
        path = serde.resource_path("Pod", "default")
        wire = serde.to_wire(make_pod())
        created = client.create(path, wire)
        assert created["metadata"]["uid"]  # stub keeps client uid or mints one
        got = client.get(serde.resource_path("Pod", "default", "p1"))
        assert got["spec"]["containers"][0]["resources"]["requests"][RESOURCE_TPU_CHIPS] == "4"
        items, rv = client.list(serde.resource_path("Pod"))
        assert len(items) == 1 and int(rv) >= 1
        client.delete(serde.resource_path("Pod", "default", "p1"))
        with pytest.raises(ApiError) as ei:
            client.get(serde.resource_path("Pod", "default", "p1"))
        assert ei.value.status == 404

    def test_put_conflict_on_stale_rv(self, api):
        client = make_client(api)
        path = serde.resource_path("Pod", "default")
        created = client.create(path, serde.to_wire(make_pod()))
        item_path = serde.resource_path("Pod", "default", "p1")
        client.replace(item_path, created)  # rv still fresh: ok
        with pytest.raises(ApiError) as ei:
            client.replace(item_path, created)  # now stale
        assert ei.value.status == 409

    def test_watch_streams_events(self, api):
        client = make_client(api)
        _, rv = client.list(serde.resource_path("Pod"))
        seen = []
        done = threading.Event()

        def consume():
            for event in client.watch(serde.resource_path("Pod"), rv, timeout_seconds=5):
                if event["type"] == "BOOKMARK":
                    continue
                seen.append((event["type"], event["object"]["metadata"]["name"]))
                if len(seen) >= 2:
                    break
            done.set()

        t = threading.Thread(target=consume, daemon=True)
        t.start()
        client.create(serde.resource_path("Pod", "default"), serde.to_wire(make_pod()))
        client.delete(serde.resource_path("Pod", "default", "p1"))
        assert done.wait(5.0)
        assert seen == [("ADDED", "p1"), ("DELETED", "p1")]


class TestKubeApiStore:
    def test_read_your_writes(self, store):
        store.create(make_pod())
        pod = store.get("Pod", "p1", "default")
        assert pod.spec.containers[0].requests[RESOURCE_TPU_CHIPS] == 4

    def test_informer_sees_external_objects(self, api, store):
        # An "external client" (kubectl analogue) writes directly to the
        # apiserver; the informer must surface it.
        api.inject("configmaps", serde.to_wire(
            ConfigMap(metadata=ObjectMeta(name="ext", namespace="kube-system"),
                      data={"k": "v"})
        ))
        assert wait_for(lambda: store.try_get("ConfigMap", "ext", "kube-system"))
        assert store.get("ConfigMap", "ext", "kube-system").data == {"k": "v"}

    def test_watch_events_flow_through_store(self, store):
        q = store.watch(kinds={"Pod"})
        store.create(make_pod())
        event = q.get(timeout=5.0)
        assert event.type == "ADDED" and event.object.metadata.name == "p1"

    def test_patch_merge_persists_to_apiserver(self, api, store):
        store.create(make_pod())

        def set_phase(p):
            p.status.phase = PodPhase.RUNNING

        store.patch_merge("Pod", "p1", "default", set_phase)
        wire = api.read("pods", "default", "p1")
        assert wire["status"]["phase"] == "Running"

    def test_patch_merge_retries_conflicts(self, api, store):
        store.create(make_pod())
        client = make_client(api)
        item_path = serde.resource_path("Pod", "default", "p1")
        raced = {"done": False}

        def mutate(p):
            # Simulate a concurrent writer racing the first attempt: bump
            # the object behind patch_merge's back exactly once.
            if not raced["done"]:
                raced["done"] = True
                live = client.get(item_path)
                live["metadata"]["labels"] = {"raced": "yes"}
                client.replace(item_path, live)
            p.metadata.annotations["patched"] = "true"

        out = store.patch_merge("Pod", "p1", "default", mutate)
        assert out.metadata.annotations["patched"] == "true"
        # the racer's write survived too (retry re-read the live object)
        assert api.read("pods", "default", "p1")["metadata"]["labels"] == {"raced": "yes"}

    def test_delete_and_not_found(self, store):
        store.create(make_pod())
        store.delete("Pod", "p1", "default")
        with pytest.raises(NotFoundError):
            store.get("Pod", "p1", "default")
        with pytest.raises(NotFoundError):
            store.delete("Pod", "p1", "default")

    def test_update_conflict_surface(self, api, store):
        store.create(make_pod())
        stale = store.get("Pod", "p1", "default")

        def relabel(p):
            p.metadata.labels["touched"] = "yes"

        store.patch_merge("Pod", "p1", "default", relabel)  # bumps rv
        with pytest.raises(ConflictError):
            store.update(stale, check_version=True)

    def test_noop_patch_sends_nothing(self, api, store):
        store.create(make_pod())
        before = api.read("pods", "default", "p1")["metadata"]["resourceVersion"]
        store.patch_merge("Pod", "p1", "default", lambda p: None)
        after = api.read("pods", "default", "p1")["metadata"]["resourceVersion"]
        assert before == after  # empty diff -> no write at all

    def test_bind_goes_through_binding_subresource(self, api, store):
        store.create(make_pod())

        def bind(p):
            p.spec.node_name = "tpu-7"

        store.patch_merge("Pod", "p1", "default", bind)
        wire = api.read("pods", "default", "p1")
        assert wire["spec"]["nodeName"] == "tpu-7"
        # the stub rejects nodeName via plain PATCH (422), so reaching here
        # proves the /binding subresource path was used

    def test_status_goes_through_status_subresource(self, api, store):
        store.create(make_pod())

        def run_and_label(p):
            p.status.phase = PodPhase.RUNNING
            p.metadata.labels["state"] = "live"

        store.patch_merge("Pod", "p1", "default", run_and_label)
        wire = api.read("pods", "default", "p1")
        assert wire["status"]["phase"] == "Running"
        assert wire["metadata"]["labels"] == {"state": "live"}

    def test_patch_preserves_unmodeled_fields(self, api, store):
        """Fields outside the suite's model (volumes, serviceAccount, …)
        must survive a patch_merge — the merge diff only mentions modeled
        fields it changed."""
        wire = serde.to_wire(make_pod("rich"))
        wire["spec"]["serviceAccountName"] = "train-sa"
        wire["spec"]["volumes"] = [{"name": "data", "emptyDir": {}}]
        api.inject("pods", wire)
        assert wait_for(lambda: store.try_get("Pod", "rich", "default"))
        store.patch_merge(
            "Pod", "rich", "default",
            lambda p: p.metadata.annotations.update({"x": "y"}),
        )
        after = api.read("pods", "default", "rich")
        assert after["spec"]["serviceAccountName"] == "train-sa"
        assert after["spec"]["volumes"] == [{"name": "data", "emptyDir": {}}]
        assert after["metadata"]["annotations"]["x"] == "y"

    def test_indexers_work_over_cache(self, store):
        store.add_indexer("Pod", "phase", lambda p: [p.status.phase])
        store.create(make_pod("a"))
        store.create(make_pod("b"))
        assert len(store.list_by_index("Pod", "phase", PodPhase.PENDING)) == 2


class TestOperatorAgainstApi:
    def test_eq_overquota_labels_on_real_api_objects(self, api):
        """The VERDICT done-criterion shape: `operator` reconciles real EQ
        CRDs end to end — over-quota labels land on objects living in the
        (stub) apiserver, via watches, not in-process shortcuts."""
        from nos_tpu.api.config import OperatorConfig
        from nos_tpu.cmd.operator import build_operator
        from nos_tpu.kube.controller import Manager

        store = KubeApiStore(
            make_client(api), kinds=("Pod", "ElasticQuota", "CompositeElasticQuota")
        )
        store.start(sync_timeout_s=10.0)
        manager = Manager(store=store)
        build_operator(manager, OperatorConfig())
        manager.start()
        try:
            store.create(
                ElasticQuota(
                    metadata=ObjectMeta(name="eq-a", namespace="team-a"),
                    spec=ElasticQuotaSpec(
                        min={RESOURCE_TPU_CHIPS: 4}, max={RESOURCE_TPU_CHIPS: 8}
                    ),
                )
            )
            pod = make_pod("train", ns="team-a", chips=6)  # over min -> over-quota
            pod.spec.node_name = "tpu-0"
            pod.status.phase = PodPhase.RUNNING
            store.create(pod)

            def quota_used():
                wire = api.read("elasticquotas", "team-a", "eq-a")
                used = ((wire or {}).get("status") or {}).get("used") or {}
                return used.get(RESOURCE_TPU_CHIPS) == "6"

            assert wait_for(quota_used, timeout=10.0), api.read(
                "elasticquotas", "team-a", "eq-a"
            )
            wire_pod = api.read("pods", "team-a", "train")
            assert (
                wire_pod["metadata"]["labels"].get(labels.CAPACITY_LABEL)
                == labels.CAPACITY_OVER_QUOTA
            ), wire_pod["metadata"].get("labels")
        finally:
            manager.stop()
            store.stop()


class TestOperatorProcess:
    def test_operator_binary_with_kubeconfig_store(self, api, tmp_path):
        """`python -m nos_tpu operator --config …` with `store: kubeconfig`
        connects to an apiserver over real sockets and reconciles EQ CRDs
        it did not create — the deploy-artifact path, end to end."""
        import os
        import pathlib
        import signal
        import socket
        import subprocess
        import sys
        import urllib.request

        import yaml

        repo = pathlib.Path(__file__).resolve().parents[2]
        kubeconfig = tmp_path / "kubeconfig"
        kubeconfig.write_text(yaml.safe_dump({
            "current-context": "stub",
            "contexts": [{"name": "stub",
                          "context": {"cluster": "stub", "user": "stub"}}],
            "clusters": [{"name": "stub", "cluster": {"server": api.url}}],
            "users": [{"name": "stub", "user": {}}],
        }))
        cfg = tmp_path / "operator.yaml"
        cfg.write_text(yaml.safe_dump({
            "store": {
                "type": "kubeconfig",
                "kubeconfig": str(kubeconfig),
                "kinds": ["Pod", "ElasticQuota", "CompositeElasticQuota"],
            }
        }))
        with socket.socket() as s:
            s.bind(("127.0.0.1", 0))
            port = s.getsockname()[1]
        proc = subprocess.Popen(
            [sys.executable, "-m", "nos_tpu", "operator",
             "--config", str(cfg), "--health-port", str(port)],
            cwd=repo,
            env={**os.environ, "PYTHONPATH": str(repo)},
            stdout=subprocess.DEVNULL,
            stderr=subprocess.PIPE,
        )
        try:
            def healthy():
                try:
                    with urllib.request.urlopen(
                        f"http://127.0.0.1:{port}/healthz", timeout=1
                    ) as resp:
                        return resp.status == 200
                except OSError:
                    return False

            assert wait_for(healthy, timeout=20.0)
            api.inject("elasticquotas", serde.to_wire(ElasticQuota(
                metadata=ObjectMeta(name="eq-x", namespace="team-x"),
                spec=ElasticQuotaSpec(min={RESOURCE_TPU_CHIPS: 4},
                                      max={RESOURCE_TPU_CHIPS: 8}),
            )))
            pod = make_pod("train", ns="team-x", chips=2)
            pod.spec.node_name = "tpu-0"
            pod.status.phase = PodPhase.RUNNING
            api.inject("pods", serde.to_wire(pod))

            def quota_used():
                wire = api.read("elasticquotas", "team-x", "eq-x")
                used = ((wire or {}).get("status") or {}).get("used") or {}
                return used.get(RESOURCE_TPU_CHIPS) == "2"

            assert wait_for(quota_used, timeout=15.0), api.read(
                "elasticquotas", "team-x", "eq-x")
        finally:
            proc.send_signal(signal.SIGTERM)
            try:
                proc.wait(timeout=10)
            except subprocess.TimeoutExpired:
                proc.kill()


class TestInformerDegradation:
    def test_missing_crd_serves_empty_and_boots(self):
        """A cluster without the nos CRDs must not wedge component boot:
        the informer reports synced-empty for the unavailable kind."""
        with StubApiServer(disabled_plurals={"elasticquotas"}) as api:
            store = KubeApiStore(
                make_client(api), kinds=("Pod", "ElasticQuota"), relist_backoff_s=0.2
            )
            store.start(sync_timeout_s=10.0)  # must NOT raise TimeoutError
            try:
                assert store.list("ElasticQuota") == []
                store.create(make_pod())  # the available kind still works
                assert store.get("Pod", "p1", "default")
            finally:
                store.stop()
