"""bench_store smoke: every bench function runs on a tiny store and
returns well-formed JSON-able rows (tier-1; the committed
BENCH_store.json carries the real 10k-node / 100k-pod numbers)."""
import json

import bench_store


class TestBenchStoreSmoke:
    def test_all_benches_produce_rows(self):
        rows = bench_store.run_config(20, 100, n_watchers=2, quick=True)
        benches = {r["bench"] for r in rows}
        assert benches == {
            "store_seed",
            "store_list",
            "store_list_by_index",
            "store_patch",
            "store_watch_fanout",
            "store_apply_event",
        }
        for row in rows:
            json.dumps(row)  # every row is a JSON line
            assert row["nodes"] == 20
            assert row["pods"] == 100

    def test_index_rows_carry_before_after_pair(self):
        rows = bench_store.run_config(10, 50, n_watchers=1, quick=True)
        variants = {
            r["variant"]: r for r in rows if r["bench"] == "store_list_by_index"
        }
        assert set(variants) == {"indexed", "scan"}
        assert variants["indexed"]["lookups_per_sec"] > 0
        assert variants["scan"]["lookups_per_sec"] > 0

    def test_watch_fanout_delivers_to_every_watcher(self):
        rows = bench_store.run_config(5, 20, n_watchers=3, quick=True)
        fanout = next(r for r in rows if r["bench"] == "store_watch_fanout")
        assert fanout["events_delivered"] == fanout["writes"] * 3

    def test_seeded_store_matches_config(self):
        store = bench_store.seed_store(5, 30)
        assert len(store.list("Node", copy=False)) == 5
        assert len(store.list("Pod", copy=False)) == 30
        pending = store.list_by_index("Pod", "status.phase", "Pending", copy=False)
        assert len(pending) == 3  # every 10th pod is a Pending straggler
