"""Maintained index maps, watch-queue telemetry, and drain-lag stamps.

The index maps must be behavior-identical to the full-scan list_by_index
they replaced: same membership, same (namespace, name) sort, same
``copy=False`` identity contract — across create/update/patch/delete,
late indexer registration, and the apistore's reflector mutation paths.
"""
import queue
import time

import pytest

from nos_tpu.kube.objects import (
    Container,
    ObjectMeta,
    Pod,
    PodSpec,
    PodStatus,
)
from nos_tpu.kube.store import ADDED, KubeStore, WatchEvent
from nos_tpu.util import metrics


def make_pod(name: str, node: str = "", phase: str = "Pending", ns: str = "default") -> Pod:
    return Pod(
        metadata=ObjectMeta(name=name, namespace=ns),
        spec=PodSpec(containers=[Container(requests={"cpu": 1})], node_name=node),
        status=PodStatus(phase=phase),
    )


def make_store() -> KubeStore:
    s = KubeStore()
    s.add_indexer("Pod", "status.phase", lambda p: [p.status.phase])
    s.add_indexer("Pod", "spec.nodeName", lambda p: [p.spec.node_name])
    return s


def scan_equivalent(store, kind, fn, value, copy=True):
    """The pre-index behavior list_by_index must stay identical to."""
    return store.list(kind, filter_fn=lambda o: value in fn(o), copy=copy)


class TestIndexMaps:
    def test_matches_full_scan_after_mixed_mutations(self):
        s = make_store()
        for i in range(10):
            s.create(make_pod(f"p{i}", node=f"n{i % 3}", phase="Pending"))
        # update moves p1, patch flips p2's phase, delete removes p3
        moved = s.get("Pod", "p1", "default")
        moved.spec.node_name = "n9"
        s.update(moved)
        s.patch_merge(
            "Pod", "p2", "default", lambda p: setattr(p.status, "phase", "Running")
        )
        s.delete("Pod", "p3", "default")
        for index_name, fn in (
            ("status.phase", lambda p: [p.status.phase]),
            ("spec.nodeName", lambda p: [p.spec.node_name]),
        ):
            for value in ("Pending", "Running", "n0", "n1", "n2", "n9", "missing"):
                got = [
                    (o.metadata.namespace, o.metadata.name)
                    for o in s.list_by_index("Pod", index_name, value)
                ]
                want = [
                    (o.metadata.namespace, o.metadata.name)
                    for o in scan_equivalent(s, "Pod", fn, value)
                ]
                assert got == want, (index_name, value)

    def test_sorted_by_namespace_then_name(self):
        s = make_store()
        s.create(make_pod("zz", node="n1", ns="aaa"))
        s.create(make_pod("aa", node="n1", ns="zzz"))
        s.create(make_pod("mm", node="n1", ns="aaa"))
        got = [
            (o.metadata.namespace, o.metadata.name)
            for o in s.list_by_index("Pod", "spec.nodeName", "n1")
        ]
        assert got == [("aaa", "mm"), ("aaa", "zz"), ("zzz", "aa")]

    def test_copy_false_identity_stable_across_calls(self):
        s = make_store()
        s.create(make_pod("p1", node="n1"))
        a = s.list_by_index("Pod", "spec.nodeName", "n1", copy=False)
        b = s.list_by_index("Pod", "spec.nodeName", "n1", copy=False)
        assert a[0] is b[0]
        # copy=True hands out fresh objects
        c = s.list_by_index("Pod", "spec.nodeName", "n1")
        assert c[0] is not a[0]

    def test_unknown_indexer_raises_keyerror(self):
        s = make_store()
        with pytest.raises(KeyError, match="no indexer"):
            s.list_by_index("Pod", "nope", "x")

    def test_late_indexer_registration_backfills(self):
        s = KubeStore()
        s.create(make_pod("p1", node="n1"))
        s.create(make_pod("p2", node="n2"))
        s.add_indexer("Pod", "spec.nodeName", lambda p: [p.spec.node_name])
        assert [o.metadata.name for o in s.list_by_index("Pod", "spec.nodeName", "n1")] == ["p1"]

    def test_apply_event_maintains_index(self):
        s = make_store()
        s.create(make_pod("p1", node="n1"))
        moved = s.get("Pod", "p1", "default")
        moved.spec.node_name = "n2"
        moved.metadata.resource_version += 1
        s.apply_event("MODIFIED", moved)
        assert s.list_by_index("Pod", "spec.nodeName", "n1") == []
        assert [o.metadata.name for o in s.list_by_index("Pod", "spec.nodeName", "n2")] == ["p1"]
        s.apply_event("DELETED", moved)
        assert s.list_by_index("Pod", "spec.nodeName", "n2") == []


class TestWatchTelemetry:
    def test_named_watcher_has_queue_depth_gauge(self):
        s = make_store()
        s.create(make_pod("p0"))
        q = s.watch({"Pod"}, name="depth-test-watcher")
        try:
            rendered = metrics.REGISTRY.render()
            assert 'nos_tpu_watch_queue_depth{kind_set="depth-test-watcher"} 1' in rendered
            s.create(make_pod("p1"))
            rendered = metrics.REGISTRY.render()
            assert 'nos_tpu_watch_queue_depth{kind_set="depth-test-watcher"} 2' in rendered
        finally:
            s.stop_watch(q)
        # stop_watch zeroes the gauge so dead subscribers don't alert
        assert 'kind_set="depth-test-watcher"} 0' in metrics.REGISTRY.render()

    def test_anonymous_watcher_labeled_by_kind_set(self):
        s = make_store()
        q = s.watch({"Pod", "Node"})
        try:
            assert 'kind_set="Node|Pod"' in metrics.REGISTRY.render()
        finally:
            s.stop_watch(q)

    def test_watch_all_kinds_labeled_star(self):
        s = make_store()
        q = s.watch()
        try:
            assert "*" in s.watch_stats()
            assert s.watch_stats()["*"]["kinds"] == ["*"]
        finally:
            s.stop_watch(q)

    def test_watch_stats_reports_depth(self):
        s = make_store()
        q = s.watch({"Pod"}, name="stats-watcher")
        try:
            s.create(make_pod("p1"))
            s.create(make_pod("p2"))
            stats = s.watch_stats()
            assert stats["stats-watcher"]["depth"] == 2
            assert stats["stats-watcher"]["kinds"] == ["Pod"]
        finally:
            s.stop_watch(q)

    def test_slow_watcher_warning_rate_limited(self, caplog):
        s = make_store()
        s.WATCH_QUEUE_WARN_DEPTH = 3
        q = s.watch({"Pod"}, name="slow-watcher")
        try:
            with caplog.at_level("WARNING", logger="nos_tpu.kube.store"):
                for i in range(6):
                    s.create(make_pod(f"p{i}"))
            warnings = [r for r in caplog.records if "events behind" in r.message]
            # Depth crosses 3 on the third event; later events are inside
            # the rate-limit interval so exactly one warning fires.
            assert len(warnings) == 1
            assert "slow-watcher" in warnings[0].getMessage()
        finally:
            s.stop_watch(q)


class TestDrainLag:
    def test_events_carry_monotonic_enqueue_stamp(self):
        s = make_store()
        q = s.watch({"Pod"}, name="lag-watcher")
        try:
            before = time.monotonic()
            s.create(make_pod("p1"))
            event = q.get_nowait()
            assert event.type == ADDED
            assert before <= event.enqueued <= time.monotonic()
        finally:
            s.stop_watch(q)

    def test_replayed_added_events_stamped_too(self):
        s = make_store()
        s.create(make_pod("p1"))
        q = s.watch({"Pod"}, name="replay-watcher")
        try:
            event = q.get_nowait()
            assert event.enqueued > 0
        finally:
            s.stop_watch(q)

    def test_controller_pump_observes_drain_lag(self):
        from nos_tpu.kube.controller import Controller, Manager, Watch

        store = make_store()
        seen = []
        manager = Manager(store=store)
        controller = Controller(
            name="lag-test-controller",
            store=store,
            reconciler=lambda req: seen.append(req) or None,
            watches=[Watch(kind="Pod")],
        )
        manager.add(controller)
        manager.start()
        try:
            store.create(make_pod("p1"))
            assert manager.wait_idle(timeout=5.0)
            snap = metrics.REGISTRY.snapshot()
            key = 'nos_tpu_watch_drain_lag_seconds_count{consumer="lag-test-controller"}'
            assert snap.get(key, 0) >= 1, sorted(
                k for k in snap if "drain_lag" in k
            )
        finally:
            manager.stop()

    def test_controller_registers_loop_stats(self):
        from nos_tpu.kube.controller import Controller, Manager, Watch
        from nos_tpu.util.loop_health import LOOPS

        store = make_store()
        manager = Manager(store=store)
        controller = Controller(
            name="stats-test-controller",
            store=store,
            reconciler=lambda req: None,
            watches=[Watch(kind="Pod")],
        )
        manager.add(controller)
        manager.start()
        try:
            assert "stats-test-controller" in LOOPS.names()
            doc = LOOPS.payload(store=store)
            stats = doc["loops"]["stats-test-controller"]
            assert "busy_fraction" in stats
            assert "event_queue_depth" in stats
        finally:
            manager.stop()
        assert "stats-test-controller" not in LOOPS.names()


class TestLockContention:
    def test_contended_acquire_meters_wait(self):
        import threading

        s = make_store()
        before = metrics.REGISTRY.snapshot().get(
            "nos_tpu_store_lock_contention_total", 0
        )
        entered, release = threading.Event(), threading.Event()

        def holder():
            with s._lock:
                entered.set()
                release.wait(2.0)

        t = threading.Thread(target=holder)
        t.start()
        entered.wait(2.0)
        waiter = threading.Thread(target=lambda: s.list("Pod"))
        waiter.start()
        time.sleep(0.05)  # let the waiter block on the held lock
        release.set()
        t.join()
        waiter.join()
        after = metrics.REGISTRY.snapshot().get(
            "nos_tpu_store_lock_contention_total", 0
        )
        assert after >= before + 1

    def test_uncontended_fast_path_meters_nothing(self):
        s = make_store()
        before = metrics.REGISTRY.snapshot().get(
            "nos_tpu_store_lock_contention_total", 0
        )
        for i in range(20):
            s.create(make_pod(f"fast-{i}"))
        after = metrics.REGISTRY.snapshot().get(
            "nos_tpu_store_lock_contention_total", 0
        )
        assert after == before


class TestWatchEventCompat:
    def test_enqueued_defaults_to_zero(self):
        event = WatchEvent(ADDED, make_pod("p"))
        assert event.enqueued == 0.0

    def test_stale_event_queue_still_works(self):
        # Events hand-built by tests (no enqueued stamp) must flow through
        # the pump without producing lag observations.
        q: "queue.Queue[WatchEvent]" = queue.Queue()
        q.put(WatchEvent(ADDED, make_pod("p")))
        assert q.get_nowait().enqueued == 0.0
