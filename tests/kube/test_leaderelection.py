"""Leader election: one holder at a time, renewal, takeover, handover —
including over the API-backed store where the lock is a real
resourceVersion race on the apiserver."""
import threading
import time


from nos_tpu.kube.leaderelection import LeaderElector
from nos_tpu.kube.store import KubeStore


def make_elector(store, ident, **kw):
    events = []
    elector = LeaderElector(
        store,
        name="nos-tpu-test",
        identity=ident,
        lease_duration_s=kw.pop("lease", 0.5),
        renew_period_s=kw.pop("renew", 0.1),
        on_started_leading=lambda: events.append(f"{ident}-up"),
        on_stopped_leading=lambda: events.append(f"{ident}-down"),
        **kw,
    )
    return elector, events


class TestLeaderElection:
    def test_single_elector_leads_and_renews(self):
        store = KubeStore()
        elector, events = make_elector(store, "a")
        elector.start()
        try:
            assert elector.wait_for_leadership(5.0)
            time.sleep(0.6)  # several renew periods > lease duration
            assert elector.is_leader  # renewal kept the lease alive
            assert events == ["a-up"]
        finally:
            elector.stop()

    def test_second_elector_waits_then_takes_over(self):
        store = KubeStore()
        first, _ = make_elector(store, "a")
        second, events = make_elector(store, "b")
        first.start()
        assert first.wait_for_leadership(5.0)
        second.start()
        try:
            time.sleep(0.3)
            assert not second.is_leader  # lease held and renewed by a
            first.stop()  # clean shutdown releases the lease
            assert second.wait_for_leadership(5.0)
            assert "b-up" in events
        finally:
            second.stop()

    def test_crashed_leader_expires(self):
        store = KubeStore()
        first, _ = make_elector(store, "a")
        first.start()
        assert first.wait_for_leadership(5.0)
        # simulate a crash: stop renewing WITHOUT releasing
        first._stop.set()
        first._thread.join(timeout=2.0)
        # undo run()'s clean release to model a hard crash
        store.patch_annotations(
            "ConfigMap", "nos-tpu-test", "nos-system",
            {"nos.nebuly.com/leader-holder": "a",
             "nos.nebuly.com/leader-renew-time": str(time.time())},
        )
        second, _ = make_elector(store, "b")
        second.start()
        try:
            assert second.wait_for_leadership(5.0)  # after lease expiry
        finally:
            second.stop()

    def test_over_api_store(self):
        from nos_tpu.kube.apiclient import ClusterCredentials, KubeApiClient
        from nos_tpu.kube.apistore import KubeApiStore
        from tests.kube.stub_apiserver import StubApiServer

        with StubApiServer() as api:
            stores = [
                KubeApiStore(
                    KubeApiClient(ClusterCredentials(server=api.url), timeout=5.0),
                    kinds=("ConfigMap",),
                )
                for _ in range(2)
            ]
            for s in stores:
                s.start(sync_timeout_s=10.0)
            a, _ = make_elector(stores[0], "a")
            b, _ = make_elector(stores[1], "b")
            a.start()
            b.start()
            try:
                deadline = time.monotonic() + 5
                while time.monotonic() < deadline:
                    if a.is_leader or b.is_leader:
                        break
                    time.sleep(0.02)
                time.sleep(0.3)
                # exactly one leader, racing through the real apiserver
                assert a.is_leader != b.is_leader
            finally:
                a.stop()
                b.stop()
                for s in stores:
                    s.stop()


class TestElectorRobustness:
    def test_store_errors_do_not_kill_elector_and_demote_after_deadline(self):
        store = KubeStore()
        elector, events = make_elector(store, "a", lease=0.4, renew=0.1)
        elector.start()
        try:
            assert elector.wait_for_leadership(5.0)
            # apiserver "outage": every patch raises
            original = store.patch_merge

            def broken(*a, **k):
                raise OSError("connection refused")

            store.patch_merge = broken
            time.sleep(0.2)
            assert elector.is_leader  # within the renew deadline: retained
            time.sleep(0.5)
            assert not elector.is_leader  # deadline passed: stepped down
            assert "a-down" in events
            store.patch_merge = original
            assert elector.wait_for_leadership(5.0)  # recovers
        finally:
            elector.stop()

    def test_clock_skew_cannot_steal_a_live_lease(self):
        """The holder's wall-clock timestamps are garbage (epoch 0); the
        challenger must still honor the lease as long as renewals keep
        CHANGING — expiry is timed locally from observed transitions."""
        store = KubeStore()
        from nos_tpu.kube.leaderelection import (
            HOLDER_ANNOTATION,
            RENEW_ANNOTATION,
        )
        from nos_tpu.kube.objects import ConfigMap, ObjectMeta

        store.create(ConfigMap(metadata=ObjectMeta(
            name="nos-tpu-test", namespace="nos-system",
            annotations={HOLDER_ANNOTATION: "skewed", RENEW_ANNOTATION: "1"})))
        stop = threading.Event()

        def keep_renewing():
            i = 2
            while not stop.is_set():
                store.patch_annotations(
                    "ConfigMap", "nos-tpu-test", "nos-system",
                    {RENEW_ANNOTATION: str(i)})  # ancient-looking but changing
                i += 1
                time.sleep(0.05)

        t = threading.Thread(target=keep_renewing, daemon=True)
        t.start()
        challenger, _ = make_elector(store, "b", lease=0.4, renew=0.1)
        challenger.start()
        try:
            time.sleep(1.0)  # several lease durations of live renewals
            assert not challenger.is_leader
            stop.set()
            t.join()
            assert challenger.wait_for_leadership(5.0)  # holder went silent
        finally:
            stop.set()
            challenger.stop()
