"""Leader election under chaos-harness fault conditions: lease loss
mid-cycle, reacquire after a flap, and the mutual-exclusion invariant the
chaos driver's monitor asserts — two contenders must never both report
leadership, however the lease ConfigMap is flapped."""
import threading
import time

from nos_tpu.kube.leaderelection import (
    HOLDER_ANNOTATION,
    LeaderElector,
)
from nos_tpu.kube.store import ConflictError, KubeStore

LEASE = "chaos-lease-test"


def make_elector(store, ident, events=None, lease=0.5, renew=0.1):
    return LeaderElector(
        store,
        name=LEASE,
        identity=ident,
        lease_duration_s=lease,
        renew_period_s=renew,
        on_started_leading=(
            (lambda: events.append(f"{ident}-up")) if events is not None else None
        ),
        on_stopped_leading=(
            (lambda: events.append(f"{ident}-down")) if events is not None else None
        ),
    )


class TestLeaseLossMidCycle:
    def test_conflict_on_renew_demotes_within_deadline(self):
        """Injected write conflicts (the chaos conflict-writes fault) on
        every renew: the leader must step down once its renew deadline
        passes, never wedge, and recover when writes heal."""
        store = KubeStore()
        events = []
        elector = make_elector(store, "a", events, lease=0.4, renew=0.1)
        elector.start()
        try:
            assert elector.wait_for_leadership(5.0)
            original = store.patch_merge

            def conflicted(*a, **k):
                raise ConflictError("chaos: injected resource version conflict")

            store.patch_merge = conflicted
            deadline = time.monotonic() + 5.0
            while elector.is_leader and time.monotonic() < deadline:
                time.sleep(0.02)
            assert not elector.is_leader
            assert "a-down" in events
            store.patch_merge = original
            assert elector.wait_for_leadership(5.0)
        finally:
            elector.stop()

    def test_hijacked_lease_demotes_current_leader(self):
        """The lease annotation is overwritten out from under the leader
        (stale-rv world): the next renew observes the foreign holder and
        steps down instead of splitting the brain."""
        store = KubeStore()
        elector = make_elector(store, "a", lease=0.4, renew=0.1)
        elector.start()
        try:
            assert elector.wait_for_leadership(5.0)
            store.patch_annotations(
                "ConfigMap", LEASE, "nos-system",
                {HOLDER_ANNOTATION: "usurper"},
            )
            deadline = time.monotonic() + 5.0
            while elector.is_leader and time.monotonic() < deadline:
                time.sleep(0.02)
            assert not elector.is_leader
        finally:
            elector.stop()


class TestReacquireAfterFlap:
    def test_release_hands_over_without_lease_wait(self):
        """The chaos leader-flap fault is a release(): some contender must
        hold the lease again well before a full lease duration elapses."""
        store = KubeStore()
        a = make_elector(store, "a", lease=5.0, renew=0.1)
        b = make_elector(store, "b", lease=5.0, renew=0.1)
        a.start()
        b.start()
        try:
            deadline = time.monotonic() + 5.0
            while not (a.is_leader or b.is_leader):
                assert time.monotonic() < deadline
                time.sleep(0.01)
            leader = a if a.is_leader else b
            flapped = time.monotonic()
            leader.release()
            assert not leader.is_leader  # demoted synchronously
            deadline = time.monotonic() + 5.0
            while not (a.is_leader or b.is_leader):
                assert time.monotonic() < deadline
                time.sleep(0.01)
            # Reacquired far faster than lease expiry (5s) would allow.
            assert time.monotonic() - flapped < 2.0
        finally:
            a.stop()
            b.stop()

    def test_repeated_flaps_never_overlap(self):
        """The chaos driver's monitor, in miniature: flap the leader many
        times while sampling both contenders — is_leader must never be
        True on both, and leadership must keep being reacquired."""
        store = KubeStore()
        a = make_elector(store, "a", lease=1.0, renew=0.05)
        b = make_elector(store, "b", lease=1.0, renew=0.05)
        overlaps = []
        acquisitions = []
        stop = threading.Event()

        def monitor():
            while not stop.is_set():
                if a.is_leader and b.is_leader:
                    overlaps.append(time.monotonic())
                time.sleep(0.002)

        t = threading.Thread(target=monitor, daemon=True)
        a.start()
        b.start()
        t.start()
        try:
            for _ in range(6):
                deadline = time.monotonic() + 5.0
                while not (a.is_leader or b.is_leader):
                    assert time.monotonic() < deadline, "leadership never reacquired"
                    time.sleep(0.005)
                leader = a if a.is_leader else b
                acquisitions.append(leader.identity)
                leader.release()
        finally:
            stop.set()
            t.join(timeout=2.0)
            a.stop()
            b.stop()
        assert not overlaps, f"contenders overlapped {len(overlaps)} time(s)"
        assert len(acquisitions) == 6
