"""Revision semantics the flight recorder depends on.

The recorder's replay reconstructs cluster history by sorting recorded
deltas on the resource version the store stamped, and pauses at each
decision's revision watermark. That only works if (a) every write —
including deletes — advances the revision and stamps it on the emitted
object, (b) `apply_event` rebuilds a store that preserves the recorded
versions, and (c) the sim apiserver's event log replays in the same
stable revision order a live watch saw.
"""
import json
import time
import urllib.request

from nos_tpu.kube import serde
from nos_tpu.kube.objects import Container, ObjectMeta, Pod, PodSpec
from nos_tpu.kube.store import KubeStore
from nos_tpu.sim.apiserver import StubApiServer


def make_pod(name, ns="default"):
    return Pod(
        metadata=ObjectMeta(name=name, namespace=ns),
        spec=PodSpec(containers=[Container(requests={"cpu": 1})]),
    )


class TestKubeStoreRevisions:
    def test_every_write_kind_advances_revision(self):
        s = KubeStore()
        assert s.revision == 0
        created = s.create(make_pod("p1"))
        rv_create = created.metadata.resource_version
        assert rv_create == s.revision > 0

        created.metadata.labels["a"] = "b"
        updated = s.update(created)
        assert updated.metadata.resource_version > rv_create
        assert s.revision == updated.metadata.resource_version

        s.patch_labels("Pod", "p1", "default", {"c": "d"})
        rv_patch = s.revision
        assert rv_patch > updated.metadata.resource_version

        s.delete("Pod", "p1", "default")
        assert s.revision > rv_patch

    def test_delete_stamps_revision_on_watch_event(self):
        # A delete that did not bump would make the recorder's deltas
        # unsortable: the DELETED event would carry the last write's rv.
        s = KubeStore()
        q = s.watch(["Pod"])
        s.create(make_pod("p1"))
        s.delete("Pod", "p1", "default")
        added = q.get(timeout=2)
        deleted = q.get(timeout=2)
        assert added.type == "ADDED"
        assert deleted.type == "DELETED"
        assert (
            deleted.object.metadata.resource_version
            > added.object.metadata.resource_version
        )

    def test_revisions_strictly_monotonic_across_objects(self):
        s = KubeStore()
        seen = []
        for i in range(5):
            seen.append(s.create(make_pod(f"p{i}")).metadata.resource_version)
            s.delete("Pod", f"p{i}", "default")
            seen.append(s.revision)
        assert seen == sorted(seen)
        assert len(set(seen)) == len(seen)

    def test_apply_event_preserves_recorded_versions(self):
        live = KubeStore()
        events = []
        q = live.watch(["Pod"])
        live.create(make_pod("p1"))
        p = live.get("Pod", "p1", "default")
        p.metadata.labels["x"] = "y"
        live.update(p)
        live.create(make_pod("p2"))
        live.delete("Pod", "p1", "default")
        for _ in range(4):
            events.append(q.get(timeout=2))

        replayed = KubeStore()
        for e in events:
            replayed.apply_event(e.type, e.object)
        assert replayed.try_get("Pod", "p1", "default") is None
        survivor = replayed.get("Pod", "p2", "default")
        assert (
            survivor.metadata.resource_version
            == live.get("Pod", "p2", "default").metadata.resource_version
        )
        # The replayed store's clock catches up to the last applied rv so
        # post-replay writes keep advancing past the recorded history.
        assert replayed.revision == max(
            e.object.metadata.resource_version for e in events
        )

    def test_apply_event_is_idempotent(self):
        s = KubeStore()
        q = s.watch(["Pod"])
        s.create(make_pod("p1"))
        event = q.get(timeout=2)
        replayed = KubeStore()
        replayed.apply_event(event.type, event.object)
        replayed.apply_event(event.type, event.object)
        assert len(replayed.list("Pod")) == 1
        assert replayed.revision == event.object.metadata.resource_version


class TestStubApiServerRevisions:
    def _client_write(self, server, method, path, payload=None):
        data = json.dumps(payload).encode() if payload is not None else None
        req = urllib.request.Request(
            server.url + path,
            data=data,
            method=method,
            headers={"Content-Type": "application/json"},
        )
        with urllib.request.urlopen(req, timeout=5) as resp:
            return json.loads(resp.read())

    def test_monotonic_across_create_update_delete(self):
        with StubApiServer() as server:
            path = serde.resource_path("Pod", "default")
            wire = serde.to_wire(make_pod("p1"))
            created = self._client_write(server, "POST", path, wire)
            rv1 = int(created["metadata"]["resourceVersion"])
            created["metadata"]["labels"] = {"a": "b"}
            updated = self._client_write(
                server, "PUT", serde.resource_path("Pod", "default", "p1"), created
            )
            rv2 = int(updated["metadata"]["resourceVersion"])
            deleted = self._client_write(
                server, "DELETE", serde.resource_path("Pod", "default", "p1")
            )
            rv3 = int(deleted["metadata"]["resourceVersion"])
            assert rv1 < rv2 < rv3

    def test_event_log_replays_in_stable_revision_order(self):
        # The recorder sorts deltas by revision; the sim apiserver's watch
        # must hand history back in that same order however many times it
        # is replayed from rv=0.
        with StubApiServer() as server:
            path = serde.resource_path("Pod", "default")
            for i in range(4):
                self._client_write(
                    server, "POST", path, serde.to_wire(make_pod(f"p{i}"))
                )
            self._client_write(
                server, "DELETE", serde.resource_path("Pod", "default", "p1")
            )
            time.sleep(0.05)
            rvs = [rv for rv, _, plural, _ in server.state.events if plural == "pods"]
            assert rvs == sorted(rvs)
            assert len(set(rvs)) == len(rvs)
            # Two replays from scratch see identical (rv, type, name) runs.
            def replay():
                return [
                    (rv, et, o["metadata"]["name"])
                    for rv, et, plural, o in server.state.events
                    if plural == "pods" and rv > 0
                ]

            assert replay() == replay()
