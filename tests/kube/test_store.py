import queue

import pytest

from nos_tpu.kube import (
    AlreadyExistsError,
    Container,
    Controller,
    KubeStore,
    Manager,
    Node,
    NotFoundError,
    ObjectMeta,
    Pod,
    PodPhase,
    PodSpec,
    Request,
    Result,
)
from nos_tpu.kube.controller import Watch
from nos_tpu.kube.objects import PodCondition
from nos_tpu.kube.store import ADDED, DELETED, MODIFIED


def make_pod(name, ns="default", phase=PodPhase.PENDING, node="", requests=None):
    return Pod(
        metadata=ObjectMeta(name=name, namespace=ns),
        spec=PodSpec(containers=[Container(requests=requests or {})], node_name=node),
    )


class TestCrud:
    def test_create_get_roundtrip_is_isolated(self):
        s = KubeStore()
        pod = make_pod("p1")
        s.create(pod)
        got = s.get("Pod", "p1", "default")
        got.metadata.labels["x"] = "y"
        assert s.get("Pod", "p1", "default").metadata.labels == {}

    def test_create_duplicate_raises(self):
        s = KubeStore()
        s.create(make_pod("p1"))
        with pytest.raises(AlreadyExistsError):
            s.create(make_pod("p1"))

    def test_get_missing_raises(self):
        s = KubeStore()
        with pytest.raises(NotFoundError):
            s.get("Pod", "nope", "default")

    def test_update_bumps_resource_version(self):
        s = KubeStore()
        created = s.create(make_pod("p1"))
        created.status.phase = PodPhase.RUNNING
        updated = s.update(created)
        assert updated.metadata.resource_version > created.metadata.resource_version

    def test_delete(self):
        s = KubeStore()
        s.create(make_pod("p1"))
        s.delete("Pod", "p1", "default")
        assert s.try_get("Pod", "p1", "default") is None

    def test_list_with_label_selector_and_namespace(self):
        s = KubeStore()
        p = make_pod("p1", ns="a")
        p.metadata.labels["team"] = "x"
        s.create(p)
        s.create(make_pod("p2", ns="a"))
        s.create(make_pod("p3", ns="b"))
        assert len(s.list("Pod")) == 3
        assert len(s.list("Pod", namespace="a")) == 2
        assert [o.metadata.name for o in s.list("Pod", label_selector={"team": "x"})] == ["p1"]


class TestPatch:
    def test_patch_annotations_set_and_remove(self):
        s = KubeStore()
        s.create(Node(metadata=ObjectMeta(name="n1", annotations={"old": "1"})))
        s.patch_annotations("Node", "n1", "", {"new": "2", "old": None})
        got = s.get("Node", "n1")
        assert got.metadata.annotations == {"new": "2"}

    def test_patch_merge_read_modify_write(self):
        s = KubeStore()
        s.create(make_pod("p1"))

        def mutate(pod):
            pod.status.phase = PodPhase.RUNNING

        s.patch_merge("Pod", "p1", "default", mutate)
        assert s.get("Pod", "p1", "default").status.phase == PodPhase.RUNNING


class TestIndexers:
    def test_list_by_index(self):
        s = KubeStore()
        s.add_indexer("Pod", "status.phase", lambda p: [p.status.phase])
        s.add_indexer("Pod", "spec.nodeName", lambda p: [p.spec.node_name])
        s.create(make_pod("p1"))
        running = make_pod("p2", node="n1")
        running.status.phase = PodPhase.RUNNING
        s.create(running)
        assert [p.metadata.name for p in s.list_by_index("Pod", "status.phase", "Pending")] == ["p1"]
        assert [p.metadata.name for p in s.list_by_index("Pod", "spec.nodeName", "n1")] == ["p2"]


class TestWatch:
    def test_watch_replays_existing_then_streams(self):
        s = KubeStore()
        s.create(make_pod("p1"))
        q = s.watch({"Pod"})
        ev = q.get(timeout=1)
        assert (ev.type, ev.object.metadata.name) == (ADDED, "p1")
        s.create(make_pod("p2"))
        assert q.get(timeout=1).type == ADDED
        s.delete("Pod", "p2", "default")
        assert q.get(timeout=1).type == DELETED

    def test_watch_filters_kinds(self):
        s = KubeStore()
        q = s.watch({"Node"})
        s.create(make_pod("p1"))
        s.create(Node(metadata=ObjectMeta(name="n1")))
        ev = q.get(timeout=1)
        assert ev.object.kind == "Node"
        with pytest.raises(queue.Empty):
            q.get(timeout=0.05)


class TestController:
    def test_reconcile_driven_by_watch_events(self):
        s = KubeStore()
        seen = []

        def reconcile(req: Request):
            seen.append(req.name)
            return Result()

        c = Controller("test", s, reconcile, [Watch(kind="Pod")])
        mgr = Manager(store=s)
        mgr.add(c)
        mgr.start()
        try:
            s.create(make_pod("p1"))
            assert mgr.wait_idle(timeout=5)
            assert "p1" in seen
        finally:
            mgr.stop()

    def test_predicate_filters_events(self):
        s = KubeStore()
        seen = []

        def reconcile(req: Request):
            seen.append(req.name)
            return None

        only_modified = Watch(kind="Pod", predicate=lambda e: e.type == MODIFIED)
        c = Controller("test", s, reconcile, [only_modified])
        mgr = Manager(store=s)
        mgr.add(c)
        mgr.start()
        try:
            pod = s.create(make_pod("p1"))
            assert mgr.wait_idle(timeout=5)
            assert seen == []
            pod.status.phase = PodPhase.RUNNING
            s.update(pod)
            assert mgr.wait_idle(timeout=5)
            assert seen == ["p1"]
        finally:
            mgr.stop()

    def test_requeue_after(self):
        s = KubeStore()
        calls = []

        def reconcile(req: Request):
            calls.append(req.name)
            if len(calls) < 3:
                return Result(requeue_after=0.01)
            return Result()

        c = Controller("test", s, reconcile, [Watch(kind="Pod")])
        c.start()
        try:
            s.create(make_pod("p1"))
            import time

            deadline = time.monotonic() + 5
            while len(calls) < 3 and time.monotonic() < deadline:
                time.sleep(0.01)
            assert len(calls) >= 3
        finally:
            c.stop()


class TestPodHelpers:
    def test_unschedulable_condition(self):
        pod = make_pod("p")
        assert not pod.unschedulable()
        pod.status.conditions.append(
            PodCondition(type="PodScheduled", status="False", reason="Unschedulable")
        )
        assert pod.unschedulable()
