"""Validating webhook server over real TLS: AdmissionReview in,
allow/deny out — the transport a production apiserver uses."""
import json
import ssl
import urllib.request

import pytest

from nos_tpu.api.v1alpha1.constants import RESOURCE_TPU_CHIPS
from nos_tpu.api.v1alpha1.elasticquota import ElasticQuota, ElasticQuotaSpec
from nos_tpu.kube import serde
from nos_tpu.kube.objects import ObjectMeta
from nos_tpu.kube.store import KubeStore
from nos_tpu.kube.webhook import (
    PATH_COMPOSITEELASTICQUOTA,
    PATH_ELASTICQUOTA,
    build_elasticquota_webhook_server,
    generate_self_signed_cert,
)


@pytest.fixture
def webhook():
    store = KubeStore()
    server = build_elasticquota_webhook_server(store, port=0, host="127.0.0.1")
    server.start()
    yield store, server
    server.stop()


def post_review(server, path, wire_obj, uid="review-1"):
    """POST an AdmissionReview the way the apiserver does, verifying the
    server's certificate like a configured caBundle would."""
    ctx = ssl.create_default_context(cadata=server.cert_pem.decode())
    ctx.check_hostname = False  # cert SAN is localhost; we dial 127.0.0.1
    body = json.dumps(
        {
            "apiVersion": "admission.k8s.io/v1",
            "kind": "AdmissionReview",
            "request": {"uid": uid, "object": wire_obj},
        }
    ).encode()
    req = urllib.request.Request(
        f"https://127.0.0.1:{server.port}{path}",
        data=body,
        headers={"Content-Type": "application/json"},
    )
    with urllib.request.urlopen(req, context=ctx, timeout=5) as resp:
        return json.loads(resp.read())


def eq_wire(name="eq", ns="team-a", mn=4, mx=8):
    return serde.to_wire(
        ElasticQuota(
            metadata=ObjectMeta(name=name, namespace=ns),
            spec=ElasticQuotaSpec(
                min={RESOURCE_TPU_CHIPS: mn}, max={RESOURCE_TPU_CHIPS: mx}
            ),
        )
    )


class TestWebhookServer:
    def test_allows_valid_elasticquota(self, webhook):
        _, server = webhook
        review = post_review(server, PATH_ELASTICQUOTA, eq_wire())
        assert review["response"]["allowed"] is True
        assert review["response"]["uid"] == "review-1"

    def test_denies_min_over_max(self, webhook):
        _, server = webhook
        review = post_review(server, PATH_ELASTICQUOTA, eq_wire(mn=9, mx=8))
        assert review["response"]["allowed"] is False
        assert "below spec.min" in review["response"]["status"]["message"]

    def test_denies_second_quota_in_namespace(self, webhook):
        store, server = webhook
        store.create(serde.from_wire(eq_wire(name="existing")))
        review = post_review(server, PATH_ELASTICQUOTA, eq_wire(name="another"))
        assert review["response"]["allowed"] is False
        assert "already has ElasticQuota" in review["response"]["status"]["message"]

    def test_denies_overlapping_composite(self, webhook):
        store, server = webhook
        from nos_tpu.api.v1alpha1.elasticquota import (
            CompositeElasticQuota,
            CompositeElasticQuotaSpec,
        )

        store.create(
            CompositeElasticQuota(
                metadata=ObjectMeta(name="ceq-1", namespace="default"),
                spec=CompositeElasticQuotaSpec(namespaces=["team-a", "team-b"]),
            )
        )
        wire = serde.to_wire(
            CompositeElasticQuota(
                metadata=ObjectMeta(name="ceq-2", namespace="default"),
                spec=CompositeElasticQuotaSpec(namespaces=["team-b", "team-c"]),
            )
        )
        review = post_review(server, PATH_COMPOSITEELASTICQUOTA, wire)
        assert review["response"]["allowed"] is False
        assert "already covered" in review["response"]["status"]["message"]

    def test_unknown_path_404s(self, webhook):
        _, server = webhook
        with pytest.raises(urllib.error.HTTPError) as ei:
            post_review(server, "/validate-nothing", eq_wire())
        assert ei.value.code == 404

    def test_malformed_review_denies(self, webhook):
        _, server = webhook
        review = post_review(server, PATH_ELASTICQUOTA, {"kind": "Garbage"})
        assert review["response"]["allowed"] is False

    def test_self_signed_cert_has_sans(self):
        cert_pem, key_pem = generate_self_signed_cert(sans=("localhost", "10.0.0.1"))
        assert b"BEGIN CERTIFICATE" in cert_pem
        assert b"BEGIN RSA PRIVATE KEY" in key_pem or b"BEGIN PRIVATE KEY" in key_pem
