"""Wire-codec round trips: obj -> K8s JSON -> obj is identity for every
field the suite reads, and quantities follow the documented convention."""
import pytest

from nos_tpu.api.v1alpha1.elasticquota import (
    CompositeElasticQuota,
    CompositeElasticQuotaSpec,
    ElasticQuota,
    ElasticQuotaSpec,
)
from nos_tpu.kube import serde
from nos_tpu.kube.objects import (
    Container,
    NodeAffinity,
    NodeSelectorRequirement,
    NodeSelectorTerm,
    Node,
    NodeSpec,
    NodeStatus,
    ObjectMeta,
    Pod,
    PodDisruptionBudget,
    PodDisruptionBudgetSpec,
    PodSpec,
    Taint,
    Toleration,
)


class TestQuantity:
    @pytest.mark.parametrize(
        "wire,value",
        [
            ("4", 4.0),
            ("500m", 0.5),
            (8, 8.0),
        ],
    )
    def test_parse_counts(self, wire, value):
        assert serde.parse_quantity(wire) == pytest.approx(value)

    @pytest.mark.parametrize(
        "wire,gi",
        [
            ("16Gi", 16.0),
            ("512Mi", 0.5),
            ("1G", 1e9 / 2**30),
            (str(2**30), 1.0),  # plain bytes
            (2**31, 2.0),
        ],
    )
    def test_parse_memory_normalizes_every_spelling_to_gi(self, wire, gi):
        assert serde.parse_quantity(wire, memory=True) == pytest.approx(gi)

    def test_mixed_spellings_compare_on_one_scale(self):
        # a pod asking "1G" fits a node advertising "16Gi" (the review
        # scenario: raw-unit parsing made this reject every node)
        req = serde._resources_from_wire({"memory": "1G"})
        alloc = serde._resources_from_wire({"memory": "16Gi"})
        assert req["memory"] < alloc["memory"]

    def test_format_roundtrip(self):
        assert serde.format_quantity("google.com/tpu", 8) == "8"
        assert serde.format_quantity("memory", 16.0) == "16Gi"
        assert serde.format_quantity("memory", 0.5) == "512Mi"
        assert serde.format_quantity("cpu", 0.5) == "500m"


class TestRoundTrips:
    def test_pod_full(self):
        pod = Pod(
            metadata=ObjectMeta(
                name="p", namespace="ns", labels={"a": "b"},
                annotations={"x": "y"},
            ),
            spec=PodSpec(
                containers=[Container(requests={"google.com/tpu": 8, "memory": 2.0},
                                      env={"NOS_TPU_PROCESS_ID": "2"})],
                node_name="n1",
                priority=100,
                tolerations=[Toleration(key="tpu", operator="Exists", effect="NoSchedule")],
                node_selector={"pool": "tpu"},
                affinity=NodeAffinity(required_terms=[
                    NodeSelectorTerm(match_expressions=[
                        NodeSelectorRequirement(key="topo", operator="In", values=["2x4"]),
                    ]),
                ]),
            ),
        )
        back = serde.from_wire(serde.to_wire(pod))
        assert back.spec.containers[0].requests == {"google.com/tpu": 8, "memory": 2.0}
        assert back.spec.containers[0].env == {"NOS_TPU_PROCESS_ID": "2"}
        assert back.spec.tolerations[0].operator == "Exists"
        assert back.spec.affinity.required_terms[0].match_expressions[0].values == ["2x4"]
        assert back.spec.node_selector == {"pool": "tpu"}
        assert back.metadata.labels == {"a": "b"}

    def test_pod_topology_spread(self):
        from nos_tpu.kube.objects import TopologySpreadConstraint

        pod = Pod(
            metadata=ObjectMeta(name="p", namespace="ns"),
            spec=PodSpec(
                containers=[Container()],
                topology_spread_constraints=[
                    TopologySpreadConstraint(
                        topology_key="topology.kubernetes.io/zone",
                        max_skew=2,
                        when_unsatisfiable="DoNotSchedule",
                        match_labels={"app": "web"},
                    )
                ],
            ),
        )
        back = serde.from_wire(serde.to_wire(pod))
        c = back.spec.topology_spread_constraints[0]
        assert c.topology_key == "topology.kubernetes.io/zone"
        assert c.max_skew == 2
        assert c.when_unsatisfiable == "DoNotSchedule"
        assert c.match_labels == {"app": "web"}

    def test_pod_affinity_terms_roundtrip(self):
        from nos_tpu.kube.objects import PodAffinityTerm

        pod = Pod(
            metadata=ObjectMeta(name="p", namespace="ns"),
            spec=PodSpec(
                containers=[Container()],
                pod_affinity=[PodAffinityTerm(
                    topology_key="topology.kubernetes.io/zone",
                    match_labels={"app": "cache"},
                )],
                pod_anti_affinity=[PodAffinityTerm(
                    topology_key="kubernetes.io/hostname",
                    match_labels={"app": "web"},
                    namespaces=["ns", "other"],
                )],
            ),
        )
        wire = serde.to_wire(pod)
        assert "podAffinity" in wire["spec"]["affinity"]
        back = serde.from_wire(wire)
        aff = back.spec.pod_affinity[0]
        assert (aff.topology_key, aff.match_labels) == (
            "topology.kubernetes.io/zone", {"app": "cache"},
        )
        anti = back.spec.pod_anti_affinity[0]
        assert anti.namespaces == ["ns", "other"]
        assert anti.match_labels == {"app": "web"}

    def test_topology_spread_empty_selector_omitted_on_wire(self):
        # labelSelector:{} means match-ALL to the k8s API — the opposite of
        # the modeled nil-selector no-op — so it must not be emitted.
        from nos_tpu.kube.objects import TopologySpreadConstraint

        pod = Pod(
            metadata=ObjectMeta(name="p", namespace="ns"),
            spec=PodSpec(
                containers=[Container()],
                topology_spread_constraints=[
                    TopologySpreadConstraint(topology_key="zone")
                ],
            ),
        )
        wire = serde.to_wire(pod)
        assert "labelSelector" not in wire["spec"]["topologySpreadConstraints"][0]
        back = serde.from_wire(wire)
        assert back.spec.topology_spread_constraints[0].match_labels == {}

    def test_node_with_taints(self):
        node = Node(
            metadata=ObjectMeta(name="n1", labels={"t": "v"}),
            spec=NodeSpec(taints=[Taint(key="tpu", value="yes", effect="NoSchedule")],
                          unschedulable=True),
            status=NodeStatus(capacity={"google.com/tpu": 8},
                              allocatable={"google.com/tpu": 8, "memory": 128.0}),
        )
        back = serde.from_wire(serde.to_wire(node))
        assert back.spec.taints[0].key == "tpu"
        assert back.spec.unschedulable is True
        assert back.status.allocatable == {"google.com/tpu": 8, "memory": 128.0}

    def test_pdb_eq_ceq(self):
        pdb = PodDisruptionBudget(
            metadata=ObjectMeta(name="pdb", namespace="ns"),
            spec=PodDisruptionBudgetSpec(selector={"app": "x"}, min_available=2),
        )
        back = serde.from_wire(serde.to_wire(pdb))
        assert back.spec.selector == {"app": "x"} and back.spec.min_available == 2

        eq = ElasticQuota(
            metadata=ObjectMeta(name="eq", namespace="ns"),
            spec=ElasticQuotaSpec(min={"google.com/tpu": 4}, max={"google.com/tpu": 8}),
        )
        back = serde.from_wire(serde.to_wire(eq))
        assert back.spec.min == {"google.com/tpu": 4}

        ceq = CompositeElasticQuota(
            metadata=ObjectMeta(name="ceq", namespace="default"),
            spec=CompositeElasticQuotaSpec(namespaces=["a", "b"],
                                           min={"google.com/tpu": 8}),
        )
        back = serde.from_wire(serde.to_wire(ceq))
        assert back.spec.namespaces == ["a", "b"]

    def test_toleration_taint_matching(self):
        t = Toleration(key="tpu", operator="Equal", value="yes", effect="NoSchedule")
        assert t.tolerates(Taint(key="tpu", value="yes", effect="NoSchedule"))
        assert not t.tolerates(Taint(key="tpu", value="no", effect="NoSchedule"))
        wildcard = Toleration(operator="Exists")
        assert wildcard.tolerates(Taint(key="anything", effect="NoExecute"))


class TestPodAffinityExpressions:
    def test_match_expressions_roundtrip(self):
        from nos_tpu.kube.objects import NodeSelectorRequirement, PodAffinityTerm

        pod = Pod(
            metadata=ObjectMeta(name="p", namespace="ns"),
            spec=PodSpec(
                containers=[Container()],
                pod_anti_affinity=[PodAffinityTerm(
                    topology_key="zone",
                    match_expressions=[NodeSelectorRequirement(
                        key="app", operator="In", values=["web", "api"],
                    )],
                )],
            ),
        )
        back = serde.from_wire(serde.to_wire(pod))
        term = back.spec.pod_anti_affinity[0]
        assert term.match_expressions[0].key == "app"
        assert term.match_expressions[0].values == ["web", "api"]
        # the term must actually select by expression
        assert term.selects({"app": "api"}, "ns", "ns")
        assert not term.selects({"app": "db"}, "ns", "ns")


class TestModelServingWire:
    def test_roundtrip_is_identity(self):
        from nos_tpu.api.v1alpha1.modelserving import (
            ModelServing,
            ModelServingSpec,
            ModelServingStatus,
        )

        ms = ModelServing(
            metadata=ObjectMeta(name="chat", namespace="serving"),
            spec=ModelServingSpec(
                model="llama-70b",
                slice_profile="2x4",
                min_replicas=1,
                max_replicas=3,
                slos=["p95 ttft < 300ms", "availability 99.9%"],
                scale_to_zero_idle_seconds=120.0,
                cold_start_grace_seconds=45.0,
                target_queue_depth=6,
                scale_down_budget_surplus=0.4,
            ),
            status=ModelServingStatus(
                replicas=2,
                ready_replicas=1,
                desired_replicas=2,
                last_verdict="scale-up",
                last_transition_t=123.5,
                cold_starts=1,
            ),
        )
        wire = serde.to_wire(ms)
        assert wire["kind"] == "ModelServing"
        assert wire["apiVersion"] == "nos.nebuly.com/v1alpha1"
        back = serde.from_wire(wire)
        assert back.spec == ms.spec
        assert back.status == ms.status
        assert back.metadata.name == "chat"
        assert back.spec.chips_per_replica == 8

    def test_validate_rejects_bad_specs(self):
        from nos_tpu.api.v1alpha1.modelserving import ModelServingSpec

        with pytest.raises(ValueError):
            ModelServingSpec(model="m", slice_profile="9z9").validate()
        with pytest.raises(ValueError):
            ModelServingSpec(model="m", min_replicas=3, max_replicas=1).validate()
        with pytest.raises(ValueError):
            ModelServingSpec(model="", max_replicas=1).validate()
        with pytest.raises(ValueError):
            ModelServingSpec(model="m", slos=["p95 nonsense"]).validate()
