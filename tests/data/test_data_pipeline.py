"""Input pipeline: packing, determinism, multi-process striding, device
prefetch sharding, and end-to-end training consumption."""
import numpy as np
import pytest

import jax

from nos_tpu.data import BatchLoader, pack_documents, prefetch_to_device


class TestPackDocuments:
    def test_packs_across_documents(self):
        docs = [np.arange(5), np.arange(3), np.arange(7)]
        windows = list(pack_documents(docs, seq_len=4, eos_id=99))
        flat = np.concatenate(windows)
        want = np.concatenate([
            np.arange(5), [99], np.arange(3), [99], np.arange(7), [99],
        ])[:len(flat)]
        np.testing.assert_array_equal(flat, want)
        assert all(w.shape == (4,) for w in windows)

    def test_tail_shorter_than_window_dropped(self):
        windows = list(pack_documents([np.arange(5)], seq_len=4, eos_id=9))
        assert len(windows) == 1  # 6 tokens -> one window, 2-token tail dropped


class TestBatchLoader:
    def test_deterministic_and_resumable(self):
        corpus = np.arange(10_000, dtype=np.int32)
        a = BatchLoader(corpus, batch=4, seq_len=16, seed=7,
                        process_index=0, process_count=1)
        b = BatchLoader(corpus, batch=4, seq_len=16, seed=7,
                        process_index=0, process_count=1)
        first = [next(iter(a)) for _ in range(5)]
        b.skip(3)  # resume at step 3
        resumed = next(iter(b))
        np.testing.assert_array_equal(resumed, first[3])

    def test_processes_stride_one_global_batch(self):
        corpus = np.arange(10_000, dtype=np.int32)
        whole = BatchLoader(corpus, batch=8, seq_len=8, seed=1,
                            process_index=0, process_count=1)
        parts = [
            BatchLoader(corpus, batch=8, seq_len=8, seed=1,
                        process_index=i, process_count=4)
            for i in range(4)
        ]
        global_batch = next(iter(whole))
        local = [next(iter(p)) for p in parts]
        assert all(lb.shape == (2, 8) for lb in local)
        # interleaving the strides reconstructs the global batch exactly
        rebuilt = np.zeros_like(global_batch)
        for i, lb in enumerate(local):
            rebuilt[i::4] = lb
        np.testing.assert_array_equal(rebuilt, global_batch)

    def test_rejects_tiny_corpus_and_odd_batch(self):
        with pytest.raises(ValueError):
            BatchLoader(np.arange(4), batch=2, seq_len=16)
        with pytest.raises(ValueError):
            BatchLoader(np.arange(1000), batch=3, seq_len=8,
                        process_index=0, process_count=2)


class TestPrefetchToDevice:
    def test_batches_arrive_sharded(self):
        from nos_tpu.parallel.mesh import mesh_from_devices
        from nos_tpu.parallel.sharding import llama_data_sharding

        mesh = mesh_from_devices((4, 2), ("dp", "tp"), jax.devices()[:8])
        sharding = llama_data_sharding(mesh)
        corpus = np.arange(10_000, dtype=np.int32)
        loader = BatchLoader(corpus, batch=8, seq_len=16, seed=0,
                             process_index=0, process_count=1)
        stream = prefetch_to_device(iter(loader), sharding)
        batch = next(stream)
        assert batch.shape == (8, 16)
        assert batch.sharding == sharding
        # 4 dp shards of 2 rows each
        assert len(batch.addressable_shards) == 8
        assert batch.addressable_shards[0].data.shape == (2, 16)

    def test_finite_stream_terminates_and_propagates_errors(self):
        from nos_tpu.parallel.mesh import mesh_from_devices
        from nos_tpu.parallel.sharding import llama_data_sharding

        mesh = mesh_from_devices((1, 1), ("dp", "tp"), jax.devices()[:1])
        sharding = llama_data_sharding(mesh)
        batches = [np.zeros((2, 4), np.int32)] * 3
        assert len(list(prefetch_to_device(iter(batches), sharding))) == 3

        def broken():
            yield np.zeros((2, 4), np.int32)
            raise RuntimeError("corpus IO failed")

        stream = prefetch_to_device(broken(), sharding)
        next(stream)
        with pytest.raises(RuntimeError, match="corpus IO failed"):
            list(stream)

    def test_feeds_the_train_step(self):
        from nos_tpu.models.llama import init_llama_params, tiny_config
        from nos_tpu.parallel.mesh import mesh_from_devices
        from nos_tpu.parallel.sharding import llama_data_sharding
        from nos_tpu.parallel.train import make_train_step

        config = tiny_config()
        mesh = mesh_from_devices((4, 2), ("dp", "tp"), jax.devices()[:8])
        step, shard_state = make_train_step(mesh, config)
        state = shard_state(init_llama_params(jax.random.key(0), config), donate=True)
        corpus = np.random.default_rng(0).integers(
            0, config.vocab_size, size=50_000
        ).astype(np.int32)
        loader = BatchLoader(corpus, batch=8, seq_len=16, seed=0,
                             process_index=0, process_count=1)
        stream = prefetch_to_device(iter(loader), llama_data_sharding(mesh))
        losses = []
        for _, batch in zip(range(3), stream):
            state, loss = step(state, batch)
            losses.append(float(loss))
        assert all(np.isfinite(l) for l in losses)
