from nos_tpu.api.v1alpha1 import annotations as annot


class TestParse:
    def test_roundtrip_spec(self):
        ann = annot.spec_from_geometries({0: {"2x2": 2}, 1: {"1x1": 4}})
        spec, status = annot.parse_node_annotations(ann)
        assert status == []
        assert annot.spec_geometries(spec) == {0: {"2x2": 2}, 1: {"1x1": 4}}

    def test_roundtrip_status(self):
        ann = annot.status_from_devices(
            free={0: {"2x2": 1}}, used={0: {"2x2": 1, "1x1": 2}}
        )
        spec, status = annot.parse_node_annotations(ann)
        assert spec == []
        assert annot.status_geometries(status) == {0: {"2x2": 2, "1x1": 2}}

    def test_malformed_values_skipped(self):
        ann = {
            "nos.nebuly.com/spec-tpu-0-2x2": "nope",
            "nos.nebuly.com/spec-tpu-0-1x1": "3",
            "unrelated/annotation": "1",
        }
        spec, _ = annot.parse_node_annotations(ann)
        assert [(s.profile, s.quantity) for s in spec] == [("1x1", 3)]

    def test_3d_profiles(self):
        ann = annot.spec_from_geometries({0: {"2x2x1": 1}})
        spec, _ = annot.parse_node_annotations(ann)
        assert spec[0].profile == "2x2x1"

    def test_zero_quantities_omitted(self):
        assert annot.spec_from_geometries({0: {"2x2": 0}}) == {}


class TestSpecMatchesStatus:
    def test_match_ignores_free_used_split(self):
        spec_ann = annot.spec_from_geometries({0: {"2x2": 2}})
        status_ann = annot.status_from_devices(
            free={0: {"2x2": 1}}, used={0: {"2x2": 1}}
        )
        spec, _ = annot.parse_node_annotations(spec_ann)
        _, status = annot.parse_node_annotations(status_ann)
        assert annot.spec_matches_status(spec, status)

    def test_mismatch(self):
        spec, _ = annot.parse_node_annotations(
            annot.spec_from_geometries({0: {"2x4": 1}})
        )
        _, status = annot.parse_node_annotations(
            annot.status_from_devices(free={0: {"2x2": 2}}, used={})
        )
        assert not annot.spec_matches_status(spec, status)


class TestStrip:
    def test_strip_spec_only(self):
        ann = {
            **annot.spec_from_geometries({0: {"2x2": 1}}),
            **annot.status_from_devices(free={0: {"2x2": 1}}, used={}),
            annot.SPEC_PARTITIONING_PLAN: "123",
        }
        removal = annot.strip_spec_annotations(ann)
        assert list(removal.values()) == [None]
        assert "nos.nebuly.com/spec-tpu-0-2x2" in removal
        assert annot.SPEC_PARTITIONING_PLAN not in removal


class TestQuantityValidation:
    def test_negative_and_zero_quantities_skipped(self):
        ann = {
            "nos.nebuly.com/status-tpu-0-2x2-free": "-1",
            "nos.nebuly.com/status-tpu-0-1x1-used": "0",
            "nos.nebuly.com/status-tpu-0-1x2-free": "2",
        }
        _, status = annot.parse_node_annotations(ann)
        assert [(s.profile, s.quantity) for s in status] == [("1x2", 2)]
