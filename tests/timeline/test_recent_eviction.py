"""Detector-cache hygiene at fleet churn (ISSUE satellite S1): the
per-series deque cache holds only watched series, evicts series absent
for ``recent_evict_frames`` consecutive samples, and publishes its own
size as a leak-visible ``size.timeline.recent_series`` series — so 1k
nodes created and deleted leave no residue in the sampler itself."""
from nos_tpu.timeline.sizes import SizeRegistry
from nos_tpu.timeline.store import TimelineStore
from nos_tpu.timeline.watchdog import WedgeWatchdog


class Clock:
    def __init__(self, now=1000.0):
        self.now = now

    def __call__(self):
        return self.now

    def advance(self, seconds=1.0):
        self.now += seconds


def make_store(values, clock, **kw):
    return TimelineStore(
        clock=clock,
        vitals=False,
        metrics_fn=lambda: dict(values),
        sizes=SizeRegistry(),
        watchdog=WedgeWatchdog(),
        **kw,
    )


class TestRecentCacheEviction:
    def test_only_watched_series_get_detector_windows(self):
        values = {"size.ring": 1.0, "nos_tpu_pods_scheduled_total": 5.0}
        clock = Clock()
        store = make_store(values, clock)
        store.sample_once()
        # the unwatched metric family is in the ring but not the cache
        assert "nos_tpu_pods_scheduled_total" in store.names()
        assert "nos_tpu_pods_scheduled_total" not in store._recent
        assert "size.ring" in store._recent

    def test_thousand_node_create_delete_leaves_no_residue(self):
        values = {}
        clock = Clock()
        store = make_store(values, clock, recent_evict_frames=3)
        # 1k nodes' worth of per-node size series appear...
        for i in range(1000):
            values[f"size.node.{i:04d}"] = float(i)
        store.sample_once()
        assert len(store._recent) == 1000 + 1  # + the cache's own size series
        # ...then every node is deleted
        values.clear()
        for _ in range(3):
            clock.advance()
            store.sample_once()
        assert len(store._recent) == 1  # only size.timeline.recent_series
        assert store._recent_absent == {}

    def test_eviction_needs_consecutive_absences(self):
        values = {"size.blink": 1.0}
        clock = Clock()
        store = make_store(values, clock, recent_evict_frames=3)
        store.sample_once()
        del values["size.blink"]
        clock.advance()
        store.sample_once()  # absent x1
        values["size.blink"] = 2.0  # back before the threshold
        clock.advance()
        store.sample_once()
        assert "size.blink" in store._recent
        assert store._recent_absent.get("size.blink") is None

    def test_cache_size_is_leak_visible_as_a_series(self):
        values = {"size.ring": 1.0}
        clock = Clock()
        store = make_store(values, clock, recent_evict_frames=2)
        store.sample_once()
        clock.advance()
        store.sample_once()  # the size series reflects the previous frame
        points = store.series("size.timeline.recent_series")
        assert points and points[-1][1] >= 1.0

    def test_evicted_series_window_is_rebuilt_on_return(self):
        values = {"size.back": 1.0}
        clock = Clock()
        store = make_store(values, clock, recent_evict_frames=2)
        store.sample_once()
        del values["size.back"]
        for _ in range(2):
            clock.advance()
            store.sample_once()
        assert "size.back" not in store._recent
        values["size.back"] = 7.0
        clock.advance()
        store.sample_once()
        assert list(store._recent["size.back"])[-1][1] == 7.0
