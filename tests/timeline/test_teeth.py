"""Teeth tests: each detector family gets a deliberately injected fault
and must emit all three ways at once — a finding in the store, a
``HealthDegraded`` Event through the EventRecorder, and a
``timeline.finding`` flight record that a ReplaySession recomputes
bit-exactly after a full JSONL round-trip. If the detectors ever stop
detecting (or the emission wiring silently breaks), these fail."""
import json

from nos_tpu.api.v1alpha1 import constants
from nos_tpu.kube.events import EventRecorder
from nos_tpu.kube.objects import ConfigMap, ObjectMeta
from nos_tpu.kube.store import KubeStore
from nos_tpu.record.recorder import FlightRecorder
from nos_tpu.record.replay import ReplaySession
from nos_tpu.timeline.sizes import SizeRegistry
from nos_tpu.timeline.store import DetectorPolicy, TimelineStore
from nos_tpu.timeline.watchdog import WedgeWatchdog


class Clock:
    def __init__(self, now=1000.0):
        self.now = now

    def __call__(self):
        return self.now

    def advance(self, seconds=1.0):
        self.now += seconds


class Harness:
    """One wired timeline: isolated collectors, a KubeStore for Events,
    a FlightRecorder for timeline.finding records."""

    def __init__(self, policy, metrics_fn=lambda: {}):
        self.clock = Clock()
        self.sizes = SizeRegistry()
        self.watchdog = WedgeWatchdog()
        self.kube = KubeStore()
        self.flight = FlightRecorder(seed=17)
        self.recorder = EventRecorder(
            self.kube, component="timeline", clock=self.clock
        )
        self.event_obj = ConfigMap(
            metadata=ObjectMeta(name="nos-health", namespace="nos-system")
        )
        self.timeline = TimelineStore(
            capacity=256,
            interval_seconds=1.0,
            clock=self.clock,
            policy=policy,
            vitals=False,
            metrics_fn=metrics_fn,
            sizes=self.sizes,
            watchdog=self.watchdog,
        )
        self.timeline.attach(
            flight=self.flight, recorder=self.recorder, event_obj=self.event_obj
        )

    def tick(self):
        self.clock.advance()
        return self.timeline.tick()

    def assert_emitted(self, detector, series):
        """The three-way emission contract plus bit-exact replay."""
        # 1. the Event, against the health ConfigMap
        events = self.kube.list("Event", namespace="nos-system")
        assert len(events) == 1
        event = events[0]
        assert event.reason == constants.EVENT_REASON_HEALTH_DEGRADED
        assert event.type == "Warning"
        assert event.involved_kind == "ConfigMap"
        assert f"{detector} finding on {series}" in event.message
        # 2. the flight record carries the exact detector inputs
        records = [
            r for r in self.flight.records() if r["kind"] == "timeline.finding"
        ]
        assert len(records) == 1
        record = records[0]
        assert record["detector"] == detector
        assert record["series"] == series
        assert record["window"] and record["verdict"]
        # 3. replay after a JSONL round-trip recomputes the verdict
        wire = [json.loads(line) for line in self.flight.to_jsonl().splitlines()]
        report = ReplaySession(wire).run()
        assert report.timeline_findings == 1
        assert report.drifts == []
        assert report.ok()


def test_leak_teeth():
    """A genuinely unbounded structure under a size watch must produce a
    leak finding once its growth passes the budget."""
    harness = Harness(
        DetectorPolicy(leak_budget=10.0, leak_min_points=4)
    )
    blob = []
    harness.sizes.register("leaky.cache", lambda: len(blob))
    findings = []
    for _ in range(10):
        blob.extend(range(5))
        findings.extend(harness.tick())
    assert [f["detector"] for f in findings] == ["leak"]
    finding = findings[0]
    assert finding["series"] == "size.leaky.cache"
    assert finding["verdict"]["growth"] > 10.0
    assert finding["verdict"]["slope_per_second"] > 0
    harness.assert_emitted("leak", "size.leaky.cache")


def test_stall_teeth():
    """A periodic loop whose counter goes flat while registered alive
    must produce a wedged-loop finding carrying a stacks payload."""
    harness = Harness(DetectorPolicy(stall_flat_windows=3))
    harness.watchdog.register(
        "heartbeat", periodic=True, thread_name="heartbeat-thread"
    )
    findings = []
    for _ in range(3):  # alive: the counter moves
        harness.watchdog.beat("heartbeat")
        findings.extend(harness.tick())
    for _ in range(4):  # wedged: flat for flat_windows+1 samples
        findings.extend(harness.tick())
    assert [f["detector"] for f in findings] == ["stall"]
    finding = findings[0]
    assert finding["series"] == "loop.heartbeat"
    assert finding["verdict"]["last_value"] == 3.0
    assert isinstance(finding["stacks"], list)
    harness.assert_emitted("stall", "loop.heartbeat")


def test_regression_teeth():
    """A watched latency series whose recent median rises past ratio ×
    its baseline median must produce a regression finding."""
    latency = {"nos_tpu_replan_p95": 10.0}
    harness = Harness(
        DetectorPolicy(
            regression_series=("nos_tpu_replan_p95",),
            regression_baseline_points=4,
            regression_recent_points=4,
            regression_ratio=1.5,
        ),
        metrics_fn=lambda: dict(latency),
    )
    findings = []
    for _ in range(4):
        findings.extend(harness.tick())
    latency["nos_tpu_replan_p95"] = 30.0  # the regression lands
    for _ in range(4):
        findings.extend(harness.tick())
    assert [f["detector"] for f in findings] == ["regression"]
    finding = findings[0]
    assert finding["series"] == "nos_tpu_replan_p95"
    assert finding["verdict"]["baseline"] == 10.0
    assert finding["verdict"]["recent"] == 30.0
    assert finding["verdict"]["ratio"] == 3.0
    harness.assert_emitted("regression", "nos_tpu_replan_p95")


def test_refire_after_clear_emits_again():
    """Hysteresis clears, the same fault re-fires: the second finding
    emits a second flight record (distinct window, distinct verdict) and
    both replay cleanly in one session."""
    harness = Harness(DetectorPolicy(stall_flat_windows=3, clear_samples=2))
    harness.watchdog.register("pump", periodic=True)
    harness.watchdog.beat("pump")
    for _ in range(5):
        harness.tick()
    for _ in range(2):  # recover long enough to clear
        harness.watchdog.beat("pump")
        harness.tick()
    for _ in range(4):  # wedge again
        harness.tick()
    records = [
        r for r in harness.flight.records() if r["kind"] == "timeline.finding"
    ]
    assert len(records) == 2
    # distinct verdicts (different flat_since) -> distinct Events
    events = harness.kube.list("Event", namespace="nos-system")
    assert len(events) == 2
    wire = [json.loads(line) for line in harness.flight.to_jsonl().splitlines()]
    report = ReplaySession(wire).run()
    assert report.timeline_findings == 2
    assert report.drifts == []
