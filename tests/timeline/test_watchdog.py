"""WedgeWatchdog: loop registration, beats, external counters, and the
debug payload the timeline samples from."""
from nos_tpu.timeline.watchdog import WedgeWatchdog


class TestRegistration:
    def test_registered_loop_starts_at_zero(self):
        wd = WedgeWatchdog()
        wd.register("pump")
        assert wd.counters() == {"pump": 0.0}

    def test_beat_increments(self):
        wd = WedgeWatchdog()
        wd.register("pump")
        wd.beat("pump")
        wd.beat("pump")
        assert wd.counters() == {"pump": 2.0}

    def test_beat_auto_registers_as_event_driven(self):
        wd = WedgeWatchdog()
        wd.beat("surprise")
        assert wd.counters() == {"surprise": 1.0}
        assert wd.periodic_loops() == []

    def test_reregister_resets_and_retunes(self):
        wd = WedgeWatchdog()
        wd.register("pump", periodic=True)
        wd.beat("pump")
        wd.register("pump", periodic=False)
        assert wd.counters() == {"pump": 0.0}
        assert wd.periodic_loops() == []

    def test_unregister_removes(self):
        wd = WedgeWatchdog()
        wd.register("pump")
        wd.unregister("pump")
        wd.unregister("never-registered")  # no-op
        assert wd.counters() == {}


class TestCounters:
    def test_counter_fn_wins_over_beats(self):
        wd = WedgeWatchdog()
        wd.register("planner", counter_fn=lambda: 42)
        wd.beat("planner")
        assert wd.counters() == {"planner": 42.0}

    def test_erroring_counter_fn_is_skipped_that_sample(self):
        wd = WedgeWatchdog()
        wd.register("bad", counter_fn=lambda: 1 / 0)
        wd.register("good")
        wd.beat("good")
        assert wd.counters() == {"good": 1.0}
        # the loop stays registered — next sample may succeed
        assert wd.thread_name("bad") is None
        assert [l["name"] for l in wd.debug_payload()["loops"]] == ["bad", "good"]

    def test_periodic_loops_sorted(self):
        wd = WedgeWatchdog()
        wd.register("z-beat", periodic=True)
        wd.register("a-beat", periodic=True)
        wd.register("event", periodic=False)
        assert wd.periodic_loops() == ["a-beat", "z-beat"]


class TestStacks:
    def test_no_thread_name_means_no_stacks(self):
        wd = WedgeWatchdog()
        wd.register("pump")
        assert wd.stacks_for("pump") == []
        assert wd.stacks_for("unknown") == []

    def test_thread_name_recorded(self):
        wd = WedgeWatchdog()
        wd.register("pump", thread_name="pump-thread")
        assert wd.thread_name("pump") == "pump-thread"
        # no profiler samples for that thread in this test -> empty list,
        # but the lookup path must not raise
        assert isinstance(wd.stacks_for("pump"), list)


class TestDebugPayload:
    def test_shape(self):
        wd = WedgeWatchdog()
        wd.register("pump", periodic=True, thread_name="pump-thread")
        wd.register("planner", counter_fn=lambda: 7)
        wd.beat("pump")
        payload = wd.debug_payload()
        assert payload == {
            "loops": [
                {
                    "name": "planner",
                    "periodic": False,
                    "thread": None,
                    "external_counter": True,
                    "beats": 0.0,
                },
                {
                    "name": "pump",
                    "periodic": True,
                    "thread": "pump-thread",
                    "external_counter": False,
                    "beats": 1.0,
                },
            ]
        }
