"""/debug endpoints vs. concurrent metric registration: hammering
/debug/vars and /debug/timeline while writer threads mint new labeled
series and observe histograms must never tear (half-written families),
raise in a handler (a 500), or deadlock. This is the race the timeline
sampler lives with in production — it snapshots the registry on its own
thread while every controller loop keeps registering and bumping."""
import http.client
import json
import threading

from nos_tpu.timeline.sizes import SizeRegistry
from nos_tpu.timeline.store import TimelineStore
from nos_tpu.timeline.watchdog import WedgeWatchdog
from nos_tpu.util.health import HealthServer
from nos_tpu.util.metrics import REGISTRY

TOKEN = "s3cret"
WRITERS = 4
ROUNDS = 40


def _get(port, path):
    conn = http.client.HTTPConnection("127.0.0.1", port, timeout=5)
    try:
        conn.request("GET", path, headers={"Authorization": f"Bearer {TOKEN}"})
        resp = conn.getresponse()
        return resp.status, resp.read().decode()
    finally:
        conn.close()


def test_debug_endpoints_survive_concurrent_registration():
    timeline = TimelineStore(
        capacity=64,
        interval_seconds=3600.0,  # ticked by hand below, never by thread
        sizes=SizeRegistry(),
        watchdog=WedgeWatchdog(),
        vitals=False,
    )
    server = HealthServer(
        port=0,
        metrics_token=TOKEN,
        timeline_fn=lambda window: timeline.debug_payload(window),
    )
    port = server.start()
    stop = threading.Event()
    errors = []

    def writer(worker):
        try:
            i = 0
            while not stop.is_set():
                counter = REGISTRY.counter(
                    f"nos_tpu_test_debug_churn_total_{worker}"
                )
                counter.labels(shard=str(i % 16)).inc()
                hist = REGISTRY.histogram(
                    f"nos_tpu_test_debug_churn_seconds_{worker}"
                )
                hist.labels(shard=str(i % 16)).observe(0.001 * (i % 7))
                i += 1
        except Exception as exc:  # pragma: no cover - the failure mode
            errors.append(f"writer {worker}: {exc!r}")

    threads = [
        threading.Thread(target=writer, args=(w,), daemon=True)
        for w in range(WRITERS)
    ]
    for thread in threads:
        thread.start()
    try:
        for round_no in range(ROUNDS):
            status, body = _get(port, "/debug/vars")
            assert status == 200, body
            snapshot = json.loads(body)  # a torn write would break parse
            assert all(isinstance(v, (int, float)) for v in snapshot.values())
            # the sampler path: snapshot the (mutating) registry into the
            # ring, then serve the payload built from it
            timeline.sample_once(now=1000.0 + round_no)
            status, body = _get(port, "/debug/timeline")
            assert status == 200, body
            payload = json.loads(body)
            assert payload["samples"] == round_no + 1
    finally:
        stop.set()
        for thread in threads:
            thread.join(timeout=5)
        server.stop()
    assert errors == []
