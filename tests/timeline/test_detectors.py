"""Pure detector units: stall/leak/regression verdicts and the
``run_detector`` dispatch the live engine and flight-recorder replay
share. The JSON round-trip tests are the bit-exactness contract: a
recorded window fed back through the same detector must land on the
recorded verdict with plain ``==``."""
import json

import pytest

from nos_tpu.timeline import detectors


def ramp(n, start=0.0, step=1.0, t0=0.0, dt=5.0):
    return [(t0 + i * dt, start + i * step) for i in range(n)]


def flat(n, value, t0=0.0, dt=5.0):
    return [(t0 + i * dt, value) for i in range(n)]


class TestMedianAndSlope:
    def test_median_odd_even(self):
        assert detectors.median([3.0, 1.0, 2.0]) == 2.0
        assert detectors.median([4.0, 1.0, 2.0, 3.0]) == 2.5

    def test_theil_sen_is_robust_to_one_spike(self):
        points = ramp(9, step=2.0, dt=1.0)
        points[4] = (points[4][0], 1000.0)  # one wild outlier
        slope = detectors.theil_sen_slope(points)
        assert 1.0 < slope < 4.0

    def test_theil_sen_degenerate_windows(self):
        assert detectors.theil_sen_slope([]) == 0.0
        assert detectors.theil_sen_slope([(1.0, 5.0)]) == 0.0
        assert detectors.theil_sen_slope([(1.0, 5.0), (1.0, 9.0)]) == 0.0


class TestStall:
    def test_too_few_points_is_healthy(self):
        assert detectors.detect_stall(flat(5, 7.0), flat_windows=5) is None

    def test_moving_counter_is_healthy(self):
        assert detectors.detect_stall(ramp(10, step=1.0), flat_windows=5) is None

    def test_never_ran_is_not_a_stall(self):
        # A counter pinned at zero is a wiring problem, not a wedge.
        assert detectors.detect_stall(flat(10, 0.0), flat_windows=5) is None

    def test_moved_then_flat_is_a_stall(self):
        points = ramp(4, step=1.0, dt=5.0) + flat(6, 3.0, t0=20.0, dt=5.0)
        verdict = detectors.detect_stall(points, flat_windows=5)
        assert verdict is not None
        assert verdict["detector"] == detectors.STALL
        assert verdict["flat_windows"] == 5
        assert verdict["last_value"] == 3.0
        # flat_since is the first point of the flat tail
        assert verdict["flat_since"] == points[-6][0]

    def test_one_bump_inside_the_tail_resets(self):
        points = flat(5, 3.0) + [(25.0, 4.0)] + flat(3, 4.0, t0=30.0)
        assert detectors.detect_stall(points, flat_windows=4) is None


class TestLeak:
    def test_below_min_points_is_healthy(self):
        assert detectors.detect_leak(ramp(4, step=100.0), min_points=8) is None

    def test_growth_within_budget_is_healthy(self):
        # A bounded ring filling to capacity then plateauing.
        points = ramp(8, step=10.0) + flat(20, 70.0, t0=40.0)
        assert detectors.detect_leak(points, budget=256.0) is None

    def test_churning_cache_is_healthy(self):
        # Big net growth but a sawtooth: monotonic fraction too low.
        points = [(float(i), 100.0 * i * (1 if i % 2 else -1)) for i in range(12)]
        assert (
            detectors.detect_leak(points, budget=10.0, monotonic_fraction=0.9)
            is None
        )

    def test_steady_climb_past_budget_fires(self):
        points = ramp(12, step=50.0, dt=5.0)
        verdict = detectors.detect_leak(points, budget=256.0)
        assert verdict is not None
        assert verdict["detector"] == detectors.LEAK
        assert verdict["growth"] == 550.0
        assert verdict["budget"] == 256.0
        assert verdict["slope_per_second"] == pytest.approx(10.0)
        assert verdict["window_seconds"] == 55.0

    def test_negative_slope_is_healthy(self):
        # Growth between endpoints but the robust trend is downhill.
        points = [(0.0, 0.0)] + [(float(i), 500.0 - i) for i in range(1, 12)]
        assert detectors.detect_leak(points, budget=256.0) is None


class TestRegression:
    def test_insufficient_points_is_healthy(self):
        assert (
            detectors.detect_regression(
                flat(10, 5.0), baseline_points=8, recent_points=8
            )
            is None
        )

    def test_within_ratio_is_healthy(self):
        points = flat(8, 10.0) + flat(8, 12.0, t0=40.0)
        assert detectors.detect_regression(points, ratio=1.5) is None

    def test_zero_baseline_is_healthy(self):
        points = flat(8, 0.0) + flat(8, 100.0, t0=40.0)
        assert detectors.detect_regression(points) is None

    def test_abs_floor_suppresses_noise_ratio(self):
        points = flat(8, 0.001) + flat(8, 0.01, t0=40.0)
        assert detectors.detect_regression(points, abs_floor=0.1) is None

    def test_sustained_rise_fires(self):
        points = flat(8, 10.0) + flat(8, 30.0, t0=40.0)
        verdict = detectors.detect_regression(points, ratio=1.5)
        assert verdict == {
            "detector": detectors.REGRESSION,
            "baseline": 10.0,
            "recent": 30.0,
            "ratio": 3.0,
            "threshold_ratio": 1.5,
        }


class TestRunDetector:
    def test_dispatch_matches_direct_call(self):
        points = ramp(12, step=50.0)
        assert detectors.run_detector(
            detectors.LEAK, points, {"budget": 256.0}
        ) == detectors.detect_leak(points, budget=256.0)

    def test_unknown_detector_raises(self):
        with pytest.raises(KeyError):
            detectors.run_detector("made-up", [], {})

    def test_normalized_fast_path_matches(self):
        points = ramp(12, step=50.0)
        assert detectors.run_detector(
            detectors.LEAK, points, {"budget": 256.0}, normalized=True
        ) == detectors.run_detector(detectors.LEAK, points, {"budget": 256.0})

    @pytest.mark.parametrize(
        "detector,points,params",
        [
            (
                detectors.STALL,
                ramp(3, step=1.0) + flat(6, 2.0, t0=15.0),
                {"flat_windows": 5},
            ),
            (detectors.LEAK, ramp(12, step=50.0), {"budget": 256.0}),
            (
                detectors.REGRESSION,
                flat(8, 10.0) + flat(8, 30.0, t0=40.0),
                {"ratio": 1.5},
            ),
        ],
    )
    def test_json_round_trip_is_bit_exact(self, detector, points, params):
        """The replay contract: window + params through JSON and back
        recompute the identical verdict (floats round-trip exactly)."""
        verdict = detectors.run_detector(detector, points, params)
        assert verdict is not None
        wire = json.dumps(
            {"window": [[t, v] for t, v in points], "params": params},
            sort_keys=True,
        )
        decoded = json.loads(wire)
        assert (
            detectors.run_detector(
                detector, decoded["window"], decoded["params"]
            )
            == verdict
        )
