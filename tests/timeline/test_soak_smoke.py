"""Tier-1 soak smoke: a seconds-long 64-node slice of the full soak
bench (`make bench-soak` runs the committed 1024-node / 220-cycle
version). Two in-process runs must produce zero leak/stall findings and
byte-identical verdicts — the determinism contract BENCH_soak.json
relies on, checked at a size CI can afford every commit."""
import json

import bench_soak

SMOKE = dict(nodes=64, pools=4, pending_pods=24, cycles=30)


def test_soak_smoke_two_runs_byte_identical():
    report1, records1, timeline1 = bench_soak.run_soak(**SMOKE)
    report2, records2, timeline2 = bench_soak.run_soak(**SMOKE)

    for report in (report1, report2):
        # a healthy soak: every cycle incremental, merges clean, no
        # leak/stall after the final heal, replay drift-free
        assert report["planning"]["incremental_cycles"] == SMOKE["cycles"]
        assert report["planning"]["merge_violations"] == 0
        assert report["timeline"]["clean_after_final_heal"] is True
        assert report["timeline"]["leak_stall_findings"] == 0
        assert report["replay"]["ok"] is True
        assert report["replay"]["drifts"] == 0
        assert report["timeline"]["samples"] > 0

    # verdict byte-identity across the two runs
    payload1 = json.dumps(timeline1.findings_payload(), sort_keys=True)
    payload2 = json.dumps(timeline2.findings_payload(), sort_keys=True)
    assert payload1 == payload2

    # whole-report identity minus the wall-clock overhead section (its
    # booleans depend on host timing at smoke scale; the committed
    # 1024-node bench is where they are load-bearing)
    stable1 = {k: v for k, v in report1.items() if k != "overhead"}
    stable2 = {k: v for k, v in report2.items() if k != "overhead"}
    assert json.dumps(stable1, sort_keys=True) == json.dumps(stable2, sort_keys=True)

    # the recorded streams agree on shape (timestamps differ)
    kinds1 = sorted({r["kind"] for r in records1})
    kinds2 = sorted({r["kind"] for r in records2})
    assert kinds1 == kinds2
    assert len(records1) == len(records2)
