"""Lint: every module in nos_tpu/ that spawns a thread must wire its
loops into the observability stack — profiler thread registration
(``PROFILER.register_thread``) so wedged-loop findings can ship stacks,
AND wedge-watchdog registration/beats so the timeline samples a
``loop.*`` progress counter. A thread outside both is invisible exactly
when it wedges.

Grep-based on purpose (the partitioner no-deepcopy lint's idiom): the
contract is per-module and textual, so a new ``threading.Thread(`` in a
module with neither marker fails here, not in code review. Modules whose
threads legitimately sit outside the contract carry a written
justification below — an exemption without one doesn't parse.

Process spawners get the same treatment with a narrower contract: a
worker process cannot register with the in-process profiler (sampled
stacks don't cross the boundary), so the parent module must instead own
a wedge-watchdog series fed by worker progress — procpool beats
``loop.poolworker.<pool>`` on every cycle reply — or carry a written
justification."""
import pathlib
import re

NOS_TPU = pathlib.Path(__file__).resolve().parents[2] / "nos_tpu"

# Module -> why its threads are exempt from the register-both contract.
EXEMPT = {
    "chaos/driver.py": (
        "chaos monitor/heal threads live and die inside one harness run; "
        "the driver itself is the observer and its oracles are the alarm"
    ),
    "cmd/run.py": (
        "metrics-snapshot writer: best-effort periodic file dump; a wedge "
        "surfaces as a stale snapshot mtime, and the component loops the "
        "CLI hosts are watchdog-covered in their own modules"
    ),
    "data/pipeline.py": (
        "per-step prefetch workers are short-lived and throughput-covered "
        "by the pipeline's own gauges"
    ),
    "kube/apistore.py": (
        "HTTP watch pump mirrors the apiserver watch contract; staleness "
        "surfaces as resourceVersion lag on reconnect, not a local wedge"
    ),
    "kube/leaderelection.py": (
        "elector renew loop: a wedge loses the lease and triggers "
        "failover — losing leadership IS the detection mechanism"
    ),
    "kube/webhook.py": "stdlib ThreadingHTTPServer request threads",
    "record/recorder.py": (
        "flight-recorder drain thread: the ring it feeds is leak-watched "
        "via the size.record.flight_ring series instead"
    ),
    "sim/apiserver.py": "sim-harness stdlib HTTP server threads",
    "util/batcher.py": "one-shot flush timer per batch window, not a loop",
    "util/health.py": (
        "stdlib ThreadingHTTPServer serving /debug — the surface the "
        "timeline is read FROM; observing it with itself would recurse"
    ),
    "util/profiling.py": (
        "the profiler's own sampler thread cannot meaningfully register "
        "with itself"
    ),
}

PROFILER_MARK = "register_thread"
WATCHDOG_MARK = re.compile(r"(?:WATCHDOG|watchdog)\.(?:register|beat)\(")


def spawner_files():
    return sorted(
        str(path.relative_to(NOS_TPU)).replace("\\", "/")
        for path in NOS_TPU.rglob("*.py")
        if "threading.Thread(" in path.read_text()
    )


def test_every_thread_spawner_registers_profiler_and_watchdog():
    problems = []
    for rel in spawner_files():
        if rel in EXEMPT:
            continue
        text = (NOS_TPU / rel).read_text()
        if PROFILER_MARK not in text:
            problems.append(
                f"{rel}: spawns a thread but never calls "
                "PROFILER.register_thread — wedge findings there would "
                "ship without stacks"
            )
        if not WATCHDOG_MARK.search(text):
            problems.append(
                f"{rel}: spawns a thread but never registers with or "
                "beats the wedge watchdog — no loop.* series to "
                "stall-check"
            )
    assert problems == [], "\n".join(problems)


def test_exemptions_are_justified_and_live():
    """Every exemption names a real thread-spawning module (stale
    entries rot into blanket waivers) and carries a non-trivial
    justification string."""
    spawners = set(spawner_files())
    stale = sorted(set(EXEMPT) - spawners)
    assert stale == [], f"exempt modules no longer spawn threads: {stale}"
    thin = sorted(rel for rel, why in EXEMPT.items() if len(why) < 20)
    assert thin == [], f"exemptions without a real justification: {thin}"


# ------------------------------------------------------ process spawners

# Module -> why its worker processes are exempt from the watchdog-series
# contract. (No profiler requirement for processes: stacks can't cross
# the boundary, so the watchdog series IS the whole observability story
# — an exemption here means a worker process that can wedge invisibly.)
PROCESS_EXEMPT: dict = {}

PROCESS_SPAWN = re.compile(r"\.Process\(")


def process_spawner_files():
    return sorted(
        str(path.relative_to(NOS_TPU)).replace("\\", "/")
        for path in NOS_TPU.rglob("*.py")
        if PROCESS_SPAWN.search(path.read_text())
    )


def test_every_process_spawner_registers_watchdog():
    problems = []
    for rel in process_spawner_files():
        if rel in PROCESS_EXEMPT:
            continue
        text = (NOS_TPU / rel).read_text()
        if not WATCHDOG_MARK.search(text):
            problems.append(
                f"{rel}: spawns a worker process but never registers a "
                "wedge-watchdog series for it — a dead or wedged worker "
                "would be invisible until its cycle times out"
            )
    assert problems == [], "\n".join(problems)


def test_procpool_beats_poolworker_series_per_cycle_reply():
    """The process pool backend's specific contract: each worker owns a
    ``loop.poolworker.<pool>`` series, registered at spawn and beaten on
    every successful cycle reply — the only cross-process progress signal
    the timeline gets."""
    text = (NOS_TPU / "partitioning" / "core" / "procpool.py").read_text()
    assert 'poolworker.' in text, "procpool lost its poolworker.* series"
    assert "WATCHDOG.register(" in text
    assert "WATCHDOG.beat(" in text
    assert "WATCHDOG.unregister(" in text, (
        "dropped workers must unregister or dead series accumulate"
    )


def test_process_exemptions_are_justified_and_live():
    spawners = set(process_spawner_files())
    stale = sorted(set(PROCESS_EXEMPT) - spawners)
    assert stale == [], f"exempt modules no longer spawn processes: {stale}"
    thin = sorted(
        rel for rel, why in PROCESS_EXEMPT.items() if len(why) < 20
    )
    assert thin == [], f"exemptions without a real justification: {thin}"
