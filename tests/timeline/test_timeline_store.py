"""TimelineStore ring mechanics: delta encoding, eviction folding,
carry-forward series reads, rollups/sparklines/debug payload, JSONL
export, and detector hysteresis (fire once, clear after quiet, re-fire).

Every store here is fully isolated — fake clock, private SizeRegistry
and WedgeWatchdog, explicit metrics_fn, vitals off — so samples are a
pure function of the test's own mutations."""
import json

from nos_tpu.timeline.detectors import STALL
from nos_tpu.timeline.sizes import SizeRegistry
from nos_tpu.timeline.store import DetectorPolicy, TimelineStore
from nos_tpu.timeline.watchdog import WedgeWatchdog


class Clock:
    def __init__(self, now=1000.0):
        self.now = now

    def __call__(self):
        return self.now

    def advance(self, seconds=1.0):
        self.now += seconds


def make_store(values, *, clock=None, policy=None, sizes=None, watchdog=None, **kw):
    """Store sampling a mutable dict the test owns."""
    return TimelineStore(
        clock=clock or Clock(),
        policy=policy,
        vitals=False,
        metrics_fn=lambda: dict(values),
        sizes=sizes or SizeRegistry(),
        watchdog=watchdog or WedgeWatchdog(),
        **kw,
    )


# The store's own detector-cache size series (leak-visible by design);
# ring-mechanics assertions strip it to stay a pure function of the
# test's mutations.
SELF_SERIES = "size.timeline.recent_series"


def frames(store):
    """Parsed JSONL export: (base_frame, [delta_frames]), with the
    store's self-bookkeeping series stripped."""
    lines = [json.loads(line) for line in store.to_jsonl().splitlines()]
    assert lines[0]["kind"] == "timeline.base"
    lines[0]["base"].pop(SELF_SERIES, None)
    for frame in lines[1:]:
        frame["d"].pop(SELF_SERIES, None)
    return lines[0], lines[1:]


class TestDeltaRing:
    def test_first_sample_records_every_series(self):
        values = {"a": 1.0, "b": 2.0}
        store = make_store(values)
        store.sample_once()
        _, deltas = frames(store)
        assert deltas == [{"t": 1000.0, "d": {"a": 1.0, "b": 2.0}}]

    def test_unchanged_sample_is_an_empty_delta(self):
        values = {"a": 1.0}
        clock = Clock()
        store = make_store(values, clock=clock)
        store.sample_once()
        clock.advance()
        store.sample_once()
        _, deltas = frames(store)
        assert deltas[1]["d"] == {}

    def test_delta_holds_only_the_changed_series(self):
        values = {"a": 1.0, "b": 2.0}
        clock = Clock()
        store = make_store(values, clock=clock)
        store.sample_once()
        values["b"] = 5.0
        clock.advance()
        store.sample_once()
        _, deltas = frames(store)
        assert deltas[1]["d"] == {"b": 5.0}

    def test_removed_series_writes_the_sentinel(self):
        values = {"a": 1.0, "gone": 9.0}
        clock = Clock()
        store = make_store(values, clock=clock)
        store.sample_once()
        del values["gone"]
        clock.advance()
        store.sample_once()
        _, deltas = frames(store)
        assert deltas[1]["d"] == {"gone": None}
        assert store.names() == ["a", SELF_SERIES]
        # the removed series' points stop at the removal sample
        assert len(store.series("gone")) == 1

    def test_eviction_folds_into_the_base_frame(self):
        values = {"ctr": 0.0}
        clock = Clock()
        store = make_store(values, clock=clock, capacity=3)
        for i in range(5):
            values["ctr"] = float(i)
            store.sample_once()
            clock.advance()
        assert len(store) == 3
        assert store.samples == 5
        base, deltas = frames(store)
        # two evicted samples folded: base carries the last evicted value
        assert base == {"kind": "timeline.base", "base": {"ctr": 1.0}, "samples": 5}
        # full per-sample values still reconstructible for retained samples
        assert store.series("ctr") == [(1002.0, 2.0), (1003.0, 3.0), (1004.0, 4.0)]

    def test_eviction_folds_removal_out_of_the_base(self):
        values = {"a": 1.0, "gone": 9.0}
        clock = Clock()
        store = make_store(values, clock=clock, capacity=2)
        store.sample_once()
        del values["gone"]
        for _ in range(3):
            clock.advance()
            store.sample_once()
        base, _ = frames(store)
        assert base["base"] == {"a": 1.0}


class TestSeriesReads:
    def test_carry_forward_through_unchanged_samples(self):
        values = {"a": 1.0}
        clock = Clock()
        store = make_store(values, clock=clock)
        store.sample_once()
        clock.advance()
        store.sample_once()  # unchanged
        values["a"] = 3.0
        clock.advance()
        store.sample_once()
        assert store.series("a") == [(1000.0, 1.0), (1001.0, 1.0), (1002.0, 3.0)]

    def test_window_filter_keeps_the_recent_tail(self):
        values = {"a": 0.0}
        clock = Clock()
        store = make_store(values, clock=clock)
        for i in range(10):
            values["a"] = float(i)
            store.sample_once()
            clock.advance()
        points = store.series("a", window_seconds=3.0)
        assert [v for _, v in points] == [6.0, 7.0, 8.0, 9.0]

    def test_series_many_matches_per_series_reads(self):
        values = {"a": 1.0, "b": 2.0, "c": 3.0}
        clock = Clock()
        store = make_store(values, clock=clock)
        for i in range(6):
            values["a"] = float(i)
            if i == 3:
                del values["c"]
            store.sample_once()
            clock.advance()
        names = ["a", "b", "c", "missing"]
        many = store.series_many(names)
        assert many == {name: store.series(name) for name in names}

    def test_rollups_summarize_each_series(self):
        values = {"a": 5.0}
        clock = Clock()
        store = make_store(values, clock=clock)
        for v in (5.0, 9.0, 3.0, 7.0):
            values["a"] = v
            store.sample_once()
            clock.advance()
        roll = store.rollups()["a"]
        assert roll == {
            "first": 5.0,
            "last": 7.0,
            "min": 3.0,
            "max": 9.0,
            "delta": 2.0,
            "points": 4,
        }

    def test_sparkline_resamples_long_series(self):
        values = {"a": 0.0}
        clock = Clock()
        store = make_store(values, clock=clock)
        for i in range(100):
            values["a"] = float(i)
            store.sample_once()
            clock.advance()
        spark = store.sparkline("a", points=8)
        assert len(spark) == 8
        assert spark[0] == 0.0 and spark[-1] == 99.0
        assert spark == sorted(spark)

    def test_sparkline_short_series_passes_through(self):
        values = {"a": 1.0}
        store = make_store(values)
        store.sample_once()
        assert store.sparkline("a", points=8) == [1.0]
        assert store.sparkline("missing") == []


class TestDebugPayload:
    def test_payload_shape(self):
        values = {"a": 1.0}
        wd = WedgeWatchdog()
        wd.register("pump", periodic=True)
        store = make_store(values, watchdog=wd)
        store.tick()
        payload = store.debug_payload()
        assert payload["samples"] == 1
        assert payload["retained"] == 1
        assert payload["capacity"] == store.capacity
        assert payload["series_count"] == len(payload["rollups"])
        assert set(payload["sparklines"]) == set(payload["rollups"])
        assert payload["active_findings"] == []
        assert payload["findings"] == []
        assert payload["watchdog"]["loops"][0]["name"] == "pump"
        json.dumps(payload)  # must be wire-serializable as-is

    def test_payload_is_json_clean_with_findings(self):
        values = {}
        clock = Clock()
        wd = WedgeWatchdog()
        wd.register("pump", periodic=True)
        policy = DetectorPolicy(stall_flat_windows=3, clear_samples=2)
        store = make_store(values, clock=clock, policy=policy, watchdog=wd)
        wd.beat("pump")
        for _ in range(6):
            store.tick()
            clock.advance()
        payload = store.debug_payload()
        assert payload["active_findings"] == ["stall:loop.pump"]
        assert [f["detector"] for f in payload["findings"]] == [STALL]
        json.dumps(payload)


class TestHysteresis:
    def test_stall_fires_once_clears_then_refires(self):
        values = {}
        clock = Clock()
        wd = WedgeWatchdog()
        wd.register("pump", periodic=True)
        policy = DetectorPolicy(stall_flat_windows=3, clear_samples=2)
        store = make_store(values, clock=clock, policy=policy, watchdog=wd)

        def tick():
            clock.advance()
            return store.tick()

        wd.beat("pump")
        tick()
        wd.beat("pump")
        tick()
        # freeze: needs a 4-point flat tail after the last move
        new = []
        for _ in range(4):
            new.extend(tick())
        assert [f["detector"] for f in new] == [STALL]
        assert new[0]["series"] == "loop.pump"
        assert "stacks" in new[0]
        # still wedged: the active finding refreshes silently
        assert tick() == []
        # recover: two beating ticks clear the finding (clear_samples=2)
        wd.beat("pump")
        assert tick() == []
        wd.beat("pump")
        assert tick() == []
        # wedge again: a NEW finding fires
        refires = []
        for _ in range(4):
            refires.extend(tick())
        assert [f["detector"] for f in refires] == [STALL]
        assert len(store.findings()) == 2

    def test_findings_payload_elides_windows_and_stacks(self):
        values = {}
        clock = Clock()
        wd = WedgeWatchdog()
        wd.register("pump", periodic=True)
        policy = DetectorPolicy(stall_flat_windows=3)
        store = make_store(values, clock=clock, policy=policy, watchdog=wd)
        wd.beat("pump")
        for _ in range(6):
            clock.advance()
            store.tick()
        payload = store.findings_payload()
        (finding,) = payload["findings"]
        assert set(finding) == {"t", "detector", "series", "verdict"}
        json.dumps(payload, sort_keys=True)
