"""FlightRecorder unit behavior: ring bound, session meta, delta capture,
JSONL round-trip."""
import threading

from nos_tpu.kube.objects import Container, ObjectMeta, Pod, PodSpec
from nos_tpu.kube.store import KubeStore
from nos_tpu.record import FlightRecorder
from nos_tpu.record.recorder import load_jsonl


def make_pod(name, ns="default"):
    return Pod(
        metadata=ObjectMeta(name=name, namespace=ns),
        spec=PodSpec(containers=[Container(requests={"cpu": 1})]),
    )


class TestRing:
    def test_capacity_bounds_the_ring(self):
        fr = FlightRecorder(capacity=8)
        for i in range(50):
            fr.record_scheduler_cycle(
                pod=f"default/p{i}", revision=i, decision="fail"
            )
        records = fr.records()
        assert len(records) == 8
        # Oldest records (including session.start) were evicted; the tail
        # survives in order.
        assert [r["pod"] for r in records] == [f"default/p{i}" for i in range(42, 50)]

    def test_seq_strictly_increasing(self):
        fr = FlightRecorder(capacity=16)
        for i in range(5):
            fr.record_actuation(kind="tpu", plan_id=str(i), revision=i, applied=0)
        seqs = [r["seq"] for r in fr.records()]
        assert seqs == sorted(seqs)
        assert len(set(seqs)) == len(seqs)


class TestSessionMeta:
    def test_meta_folds_into_session_start(self):
        fr = FlightRecorder(seed=7)
        fr.record_session_meta(scheduler_name="nos", gang_timeout_seconds=3.0)
        fr.record_session_meta(aging_chips_per_second=2.0)
        start = fr.records()[0]
        assert start["kind"] == "session.start"
        assert start["seed"] == 7
        assert start["scheduler_name"] == "nos"
        assert start["gang_timeout_seconds"] == 3.0
        assert start["aging_chips_per_second"] == 2.0


class TestDeltaCapture:
    def test_attach_records_store_writes_with_revisions(self):
        fr = FlightRecorder()
        store = KubeStore()
        fr.attach(store)
        try:
            store.create(make_pod("p1"))
            p = store.get("Pod", "p1", "default")
            p.status.phase = "Running"
            store.update(p)
            store.delete("Pod", "p1", "default")
        finally:
            fr.detach()
        deltas = [r for r in fr.records() if r["kind"] == "delta"]
        assert [d["type"] for d in deltas] == ["ADDED", "MODIFIED", "DELETED"]
        revisions = [d["revision"] for d in deltas]
        assert revisions == sorted(revisions)
        assert len(set(revisions)) == len(revisions)
        assert deltas[0]["object"]["metadata"]["name"] == "p1"

    def test_detach_drains_pending_events(self):
        fr = FlightRecorder()
        store = KubeStore()
        fr.attach(store)
        barrier = threading.Barrier(2)

        def writer():
            barrier.wait()
            for i in range(20):
                store.create(make_pod(f"w{i}"))

        t = threading.Thread(target=writer)
        t.start()
        barrier.wait()
        t.join()
        fr.detach()
        deltas = [r for r in fr.records() if r["kind"] == "delta"]
        assert len(deltas) == 20


class TestJsonl:
    def test_export_load_round_trip(self, tmp_path):
        fr = FlightRecorder()
        fr.record_scheduler_cycle(
            pod="default/p1",
            revision=3,
            decision="bind",
            node="n1",
            bound=[["default/p1", "n1"]],
        )
        fr.record_plan(
            kind="tpu",
            revision=4,
            pending=["default/p1"],
            pending_ages={"default/p1": 1.5},
            plan_id="42-1",
            desired={"n1": {"0": {"2x4": 1}}},
            unserved={},
            applied=1,
        )
        path = tmp_path / "rec.jsonl"
        count = fr.export_jsonl(str(path))
        loaded = load_jsonl(str(path))
        assert count == len(loaded) == 3  # session.start + 2
        assert loaded == fr.records()
        assert loaded[1]["decision"] == "bind"
        assert loaded[2]["partitioner_kind"] == "tpu"
        assert loaded[2]["pending_ages"] == {"default/p1": 1.5}
