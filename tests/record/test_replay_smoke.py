"""The `make replay-smoke` loop: record a short sim run through the real
`run` CLI, replay it through the real `replay` CLI, and require zero
decision drift and zero audit violations (exit code 0)."""
import time

from nos_tpu.cmd.replay import main as replay_main
from nos_tpu.cmd.run import main as run_main
from nos_tpu.record.recorder import load_jsonl

CONFIG = """
partitioner:
  batchWindowTimeoutSeconds: 1.0
  batchWindowIdleSeconds: 0.05
  auditSampleRate: 1.0
scheduler:
  retrySeconds: 0.2
agent:
  reportConfigIntervalSeconds: 0.2
nodes:
  - name: smoke-node
    chips: 8
    topology: 2x4
pods:
  - name: smoke-w1
    chips: 4
  - name: smoke-w2
    chips: 4
"""


def test_record_then_replay_exits_zero(tmp_path, capsys):
    cfg = tmp_path / "smoke.yaml"
    cfg.write_text(CONFIG)
    record = tmp_path / "smoke-record.jsonl"

    start = time.monotonic()
    rc = run_main(
        [
            "--config",
            str(cfg),
            "--record",
            str(record),
            "--run-seconds",
            "6",
            "--health-port",
            "0",
        ]
    )
    assert rc == 0
    assert time.monotonic() - start < 60

    records = load_jsonl(str(record))
    kinds = {r["kind"] for r in records}
    assert "scheduler.cycle" in kinds, f"no decisions recorded: {sorted(kinds)}"
    assert "planner.plan" in kinds, f"no plans recorded: {sorted(kinds)}"

    rc = replay_main([str(record)])
    out = capsys.readouterr().out
    assert rc == 0, out
    assert "0 drift(s), 0 audit violation(s)" in out
