"""/debug/record endpoint and quota-reconcile recording."""
import http.client
import json

from nos_tpu.controllers.elasticquota import ElasticQuotaReconciler
from nos_tpu.kube.controller import Request
from nos_tpu.kube.store import KubeStore
from nos_tpu.record import FlightRecorder
from nos_tpu.util.health import HealthServer


def _get(port, path, token=None):
    conn = http.client.HTTPConnection("127.0.0.1", port, timeout=2)
    headers = {"Authorization": f"Bearer {token}"} if token else {}
    conn.request("GET", path, headers=headers)
    resp = conn.getresponse()
    return resp.status, resp.read().decode()


class TestDebugRecordEndpoint:
    def test_serves_ring_json_and_jsonl(self):
        fr = FlightRecorder()
        fr.record_scheduler_cycle(pod="default/p1", revision=1, decision="bind", node="n1")
        server = HealthServer(port=0, record_fn=fr.records)
        port = server.start()
        try:
            status, body = _get(port, "/debug/record")
            assert status == 200
            records = json.loads(body)
            assert records[0]["kind"] == "session.start"
            assert records[1]["decision"] == "bind"

            status, body = _get(port, "/debug/record?format=jsonl")
            assert status == 200
            lines = [json.loads(line) for line in body.splitlines() if line]
            assert lines == records  # same ring, replay-ready framing
        finally:
            server.stop()

    def test_shares_the_metrics_bearer_gate(self):
        fr = FlightRecorder()
        server = HealthServer(port=0, metrics_token="s3cret", record_fn=fr.records)
        port = server.start()
        try:
            assert _get(port, "/debug/record")[0] == 401
            assert _get(port, "/debug/record", "wrong")[0] == 401
            assert _get(port, "/debug/record", "s3cret")[0] == 200
        finally:
            server.stop()

    def test_404_when_recording_is_off(self):
        server = HealthServer(port=0)
        port = server.start()
        try:
            assert _get(port, "/debug/record")[0] == 404
        finally:
            server.stop()


class TestQuotaReconcileRecording:
    def test_reconcile_emits_decision_record_with_flips(self):
        from tests.factory import build_pod
        from nos_tpu.api.v1alpha1.constants import RESOURCE_TPU_CHIPS
        from nos_tpu.api.v1alpha1.elasticquota import (
            ElasticQuota,
            ElasticQuotaSpec,
        )
        from nos_tpu.kube.objects import ObjectMeta, PodPhase

        store = KubeStore()
        fr = FlightRecorder()
        store.create(
            ElasticQuota(
                metadata=ObjectMeta(name="q", namespace="default"),
                spec=ElasticQuotaSpec(min={RESOURCE_TPU_CHIPS: 4}),
            )
        )
        store.create(
            build_pod("in-quota", {RESOURCE_TPU_CHIPS: 4}, phase=PodPhase.RUNNING)
        )
        store.create(
            build_pod("over-quota", {RESOURCE_TPU_CHIPS: 4}, phase=PodPhase.RUNNING)
        )
        reconciler = ElasticQuotaReconciler(store, flight_recorder=fr)
        reconciler.reconcile(Request(name="q", namespace="default"))

        records = [r for r in fr.records() if r["kind"] == "quota.reconcile"]
        assert len(records) == 1
        record = records[0]
        assert record["quota"] == "default/q"
        # used accumulates every running pod's request (the over-quota pod
        # is labeled, not excluded) — 4 + 4.
        assert record["used"] == {RESOURCE_TPU_CHIPS: 8}
        flipped = dict(record["flips"])
        assert set(flipped) == {"default/in-quota", "default/over-quota"}
        # The watermark precedes the reconcile's own label writes.
        assert record["revision"] <= store.revision
