"""Invariant auditor: a clean plan audits clean; a deliberately poisoned
verdict-cache entry is flagged by exactly the verdict_cache check; the
live sampling stride is deterministic."""
import random

import pytest

from nos_tpu.api.v1alpha1 import annotations as annot
from nos_tpu.partitioning.core import ClusterSnapshot, Planner, SnapshotNode
from nos_tpu.record.audit import InvariantAuditor, build_auditor
from nos_tpu.scheduler.framework import (
    Framework,
    NodeAffinityFit,
    NodeResourcesFit,
    NodeSelectorFit,
    TaintTolerationFit,
)
from nos_tpu.tpu.node import TpuNode

from tests.factory import build_pod, build_tpu_node, slice_res


def build_snapshot(n=4):
    rng = random.Random(42)
    nodes = {}
    for i in range(n):
        style = rng.random()
        if style < 0.5:
            annotations = None
        else:
            annotations = annot.status_from_devices(
                free={0: {"2x2": 1}}, used={}
            )
        node = build_tpu_node(name=f"n{i}", annotations=annotations)
        nodes[f"n{i}"] = SnapshotNode(partitionable=TpuNode(node))
    return ClusterSnapshot(nodes)


def build_planner():
    return Planner(
        Framework(
            filter_plugins=[
                NodeResourcesFit(),
                NodeSelectorFit(),
                NodeAffinityFit(),
                TaintTolerationFit(),
            ]
        )
    )


def planned(planner, snapshot, n_pods=6):
    planner.plan(
        snapshot,
        [build_pod(f"p{i}", {slice_res("1x1"): 1}) for i in range(n_pods)],
    )


class TestCleanPlan:
    def test_no_violations_on_untampered_state(self):
        snapshot = build_snapshot()
        planner = build_planner()
        planned(planner, snapshot)
        auditor = InvariantAuditor(sample_rate=1.0)
        assert auditor.audit_plan(planner, snapshot, exhaustive=True) == []
        assert auditor.violations_total == 0


class TestPoisonedVerdictCache:
    def _poison_one_live_entry(self, planner, snapshot):
        """Insert (or flip) a verdict-cache entry keyed at a node's CURRENT
        version — the only kind of entry a future trial could consult."""
        node_name = sorted(snapshot.get_nodes())[0]
        pod = build_pod("poison-probe", {slice_res("1x1"): 1})
        # Route one probe through the cache layer so the entry and its
        # signature's sim pod both exist, then flip the verdict.
        planner._can_schedule(snapshot, node_name, pod)
        node = snapshot.get_nodes()[node_name]
        for key in list(planner._verdict_cache.entries):
            signature, name, version = key
            if name == node_name and version == node.version:
                planner._verdict_cache.entries[key] = (
                    not planner._verdict_cache.entries[key]
                )
                return key
        pytest.fail("no live verdict-cache entry to poison")

    def test_flags_exactly_the_verdict_cache_check(self):
        snapshot = build_snapshot()
        planner = build_planner()
        planned(planner, snapshot)
        auditor = InvariantAuditor(sample_rate=1.0)
        assert auditor.audit_plan(planner, snapshot, exhaustive=True) == []

        poisoned_key = self._poison_one_live_entry(planner, snapshot)
        violations = auditor.audit_plan(planner, snapshot, exhaustive=True)
        assert violations, "poisoned entry went undetected"
        assert {v.check for v in violations} == {"verdict_cache"}
        assert all(v.node == poisoned_key[1] for v in violations)
        assert auditor.violations_total == len(violations)

    def test_stale_version_entries_are_skipped(self):
        # An entry keyed at a version the node has moved past is
        # unreachable — poisoning it must NOT fire the auditor.
        snapshot = build_snapshot()
        planner = build_planner()
        planned(planner, snapshot)
        node_name = sorted(snapshot.get_nodes())[0]
        node = snapshot.get_nodes()[node_name]
        pod = build_pod("stale-probe", {slice_res("1x1"): 1})
        planner._can_schedule(snapshot, node_name, pod)
        signature = planner._sim_pod_cache[(id(pod), "tpu-v5-lite-podslice")][2]
        planner._verdict_cache.entries[(signature, node_name, node.version + 999)] = (
            False
        )
        auditor = InvariantAuditor(sample_rate=1.0)
        assert auditor.check_verdict_cache(planner, snapshot, exhaustive=True) == []


class TestSampling:
    def test_zero_rate_builds_no_auditor(self):
        assert build_auditor(sample_rate=0.0) is None
        assert build_auditor(sample_rate=0.5) is not None

    def test_counter_stride_density_and_determinism(self):
        a = InvariantAuditor(sample_rate=0.25)
        b = InvariantAuditor(sample_rate=0.25)
        decisions_a = [a.should_audit() for _ in range(100)]
        decisions_b = [b.should_audit() for _ in range(100)]
        assert decisions_a == decisions_b  # replay sees identical sampling
        assert sum(decisions_a) == 25

    def test_full_rate_audits_every_plan(self):
        a = InvariantAuditor(sample_rate=1.0)
        assert all(a.should_audit() for _ in range(10))
