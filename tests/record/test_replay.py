"""Record → replay round trip: an untampered recording replays with zero
decision drift and zero audit violations; perturbing a recorded input
produces a nonzero drift diff."""
import copy
import time

import pytest

from nos_tpu.api.config import GpuPartitionerConfig, SchedulerConfig, TpuAgentConfig
from nos_tpu.cmd.cluster import build_cluster
from nos_tpu.cmd.run import seed_node, seed_pod
from nos_tpu.record import FlightRecorder, ReplaySession
from nos_tpu.record.replay import drift_exit_code


def record_session():
    """Run a short sim-cluster session under the recorder: one 8-chip node,
    two 4-chip pods — one carve plan, two binds."""
    fr = FlightRecorder()
    cluster = build_cluster(
        partitioner_config=GpuPartitionerConfig(
            batch_window_timeout_seconds=1.0,
            batch_window_idle_seconds=0.05,
            audit_sample_rate=1.0,
        ),
        scheduler_config=SchedulerConfig(retry_seconds=0.2),
        flight_recorder=fr,
    )
    fr.attach(cluster.store)
    agent_cfg = TpuAgentConfig(report_config_interval_seconds=0.2)
    cluster.add_tpu_node(
        seed_node({"name": "node-1", "chips": 8, "topology": "2x4"}), agent_cfg
    )
    cluster.store.create(seed_pod({"name": "w1", "chips": 4}))
    cluster.store.create(seed_pod({"name": "w2", "chips": 4}))
    cluster.start()
    deadline = time.time() + 30
    while time.time() < deadline:
        pods = cluster.store.list("Pod")
        if pods and all(
            p.spec.node_name and p.status.phase == "Running" for p in pods
        ):
            break
        time.sleep(0.2)
    cluster.wait_idle(10)
    cluster.stop()
    fr.detach()
    pods = cluster.store.list("Pod")
    assert all(p.spec.node_name for p in pods), "session never bound its pods"
    return fr.records()


@pytest.fixture(scope="module")
def recording():
    return record_session()


class TestFaithfulReplay:
    def test_zero_drift_zero_violations(self, recording):
        kinds = {r["kind"] for r in recording}
        assert "scheduler.cycle" in kinds and "planner.plan" in kinds
        report = ReplaySession(copy.deepcopy(recording)).run()
        assert report.cycles > 0 and report.plans > 0
        assert report.drifts == [], report.render()
        assert report.violations == [], report.render()
        assert report.ok()
        assert drift_exit_code(report) == 0

    def test_replay_is_itself_deterministic(self, recording):
        first = ReplaySession(copy.deepcopy(recording)).run()
        second = ReplaySession(copy.deepcopy(recording)).run()
        assert first.drifts == second.drifts
        assert first.violations == second.violations
        assert (first.cycles, first.plans) == (second.cycles, second.plans)


class TestPerturbedReplay:
    def test_shrunken_node_produces_decision_drift(self, recording):
        # Strip the TPU capacity out of every recorded Node delta: the
        # replayed scheduler/planner now see a chipless cluster, so the
        # recorded binds and carve plan cannot reproduce.
        records = copy.deepcopy(recording)
        perturbed = 0
        for r in records:
            if r.get("kind") == "delta" and r["object"].get("kind") == "Node":
                status = r["object"].setdefault("status", {})
                for field in ("capacity", "allocatable"):
                    status[field] = {"cpu": "8", "memory": "128"}
                annotations = r["object"].get("metadata", {}).get("annotations")
                if annotations:
                    # Drop reported slice status too, or the replayed
                    # snapshot still sees free boards.
                    r["object"]["metadata"]["annotations"] = {}
                perturbed += 1
        assert perturbed > 0, "recording held no node deltas to perturb"
        report = ReplaySession(records).run()
        assert report.drifts, report.render()
        assert not report.ok()
        assert drift_exit_code(report) == 1

    def test_flipped_recorded_decision_is_drift(self, recording):
        records = copy.deepcopy(recording)
        cycle = next(r for r in records if r["kind"] == "scheduler.cycle")
        cycle["decision"] = "fail" if cycle["decision"] != "fail" else "bind"
        cycle["node"] = ""
        cycle["bound"] = []
        report = ReplaySession(records).run()
        assert any(
            d["kind"] == "scheduler.cycle" and d["seq"] == cycle["seq"]
            for d in report.drifts
        ), report.render()
