import json

from nos_tpu.cmd.metricsexporter import collect_metrics, export
from nos_tpu.cmd.run import configs_from, load_config, seed_node
from nos_tpu.kube.store import KubeStore

from tests.factory import build_tpu_node


class TestMetricsExporter:
    def test_collect_from_cluster(self):
        store = KubeStore()
        store.create(build_tpu_node(name="n1", chips=8))
        store.create(build_tpu_node(name="n2", chips=8))
        m = collect_metrics(store)
        assert m.node_count == 2
        assert m.tpu_node_count == 2
        assert m.total_tpu_chips == 16
        assert m.partitioning_modes == ["tpu"]

    def test_export_writes_json(self, tmp_path):
        store = KubeStore()
        out = tmp_path / "metrics.json"
        payload = export(collect_metrics(store), str(out))
        data = json.loads(out.read_text())
        assert data == json.loads(payload)
        assert "version" in data and "domain_metrics" in data


class TestRunConfig:
    def test_load_and_build_configs(self, tmp_path):
        cfg_file = tmp_path / "config.yaml"
        cfg_file.write_text(
            """
partitioner:
  batchWindowTimeoutSeconds: 5
  batchWindowIdleSeconds: 1
scheduler:
  retrySeconds: 0.2
agent:
  reportConfigIntervalSeconds: 2
nodes:
  - name: tpu-0
    chips: 8
"""
        )
        config = load_config(str(cfg_file))
        partitioner, scheduler, agent, autoscaler = configs_from(config)
        assert partitioner.batch_window_timeout_seconds == 5
        assert scheduler.retry_seconds == 0.2
        assert agent.report_config_interval_seconds == 2
        assert autoscaler is None  # no `autoscaler:` section -> component off
        node = seed_node(config["nodes"][0])
        assert node.metadata.name == "tpu-0"
        assert node.status.capacity["google.com/tpu"] == 8

    def test_empty_config(self):
        partitioner, scheduler, agent, autoscaler = configs_from({})
        assert partitioner.batch_window_timeout_seconds == 60.0
        assert autoscaler is None

    def test_autoscaler_section(self, tmp_path):
        cfg = tmp_path / "c.yaml"
        cfg.write_text(
            """
autoscaler:
  scaleUpBurnThreshold: 2.0
  resyncSeconds: 1.5
"""
        )
        _, _, _, autoscaler = configs_from(load_config(str(cfg)))
        assert autoscaler is not None
        assert autoscaler.scale_up_burn_threshold == 2.0
        assert autoscaler.resync_seconds == 1.5

    def test_seed_modelserving(self):
        from nos_tpu.cmd.run import seed_modelserving

        ms = seed_modelserving(
            {
                "name": "chat",
                "model": "llama-70b",
                "sliceProfile": "2x4",
                "minReplicas": 1,
                "maxReplicas": 3,
                "slos": ["p95 ttft < 500ms"],
            }
        )
        assert ms.spec.chips_per_replica == 8
        assert ms.spec.max_replicas == 3


class TestExporterCli:
    def test_forwards_snapshot_file(self, tmp_path, capsys):
        from nos_tpu.cmd.metricsexporter import main
        snap = tmp_path / "snap.json"
        store = KubeStore()
        store.create(build_tpu_node(name="n1", chips=8))
        export(collect_metrics(store), str(snap))
        assert main(["--input", str(snap)]) == 0
        out = json.loads(capsys.readouterr().out)
        assert out["total_tpu_chips"] == 8

    def test_missing_snapshot_errors(self, tmp_path):
        from nos_tpu.cmd.metricsexporter import main
        assert main(["--input", str(tmp_path / "nope.json")]) == 1

    def test_empty_yaml_sections_use_defaults(self, tmp_path):
        cfg = tmp_path / "c.yaml"
        cfg.write_text("partitioner:\nscheduler:\nagent:\nautoscaler:\n")
        partitioner, scheduler, agent, autoscaler = configs_from(load_config(str(cfg)))
        assert partitioner.batch_window_timeout_seconds == 60.0
        assert autoscaler is not None  # bare section -> defaults, component on
