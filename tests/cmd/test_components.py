"""Standalone component binaries + packaging manifests.

The reference ships six binaries, each `--config <file>` (SURVEY.md §2.1);
here each subcommand must start, serve health probes, and shut down
cleanly on SIGTERM. Manifest tests parse the kustomize config tree and the
helm chart's static files (templates with Go-template syntax are checked
for existence + component coverage, not YAML-parsed).
"""
import os
import pathlib
import signal
import socket
import subprocess
import sys
import time
import urllib.request

import pytest
import yaml

REPO = pathlib.Path(__file__).resolve().parents[2]


def free_port() -> int:
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


def wait_health(port: int, timeout: float = 15.0) -> bool:
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        try:
            with urllib.request.urlopen(
                f"http://127.0.0.1:{port}/healthz", timeout=1
            ) as resp:
                if resp.status == 200:
                    return True
        except OSError:
            time.sleep(0.1)
    return False


@pytest.mark.parametrize(
    "component,env",
    [
        ("operator", {}),
        ("partitioner", {}),
        ("scheduler", {}),
        ("tpuagent", {"NODE_NAME": "test-node"}),
        ("sharingagent", {"NODE_NAME": "test-node"}),
    ],
)
def test_component_starts_serves_health_and_stops(component, env):
    port = free_port()
    proc = subprocess.Popen(
        [sys.executable, "-m", "nos_tpu", component, "--health-port", str(port)],
        cwd=REPO,
        env={**os.environ, "PYTHONPATH": str(REPO), **env},
        stdout=subprocess.DEVNULL,
        stderr=subprocess.PIPE,
    )
    try:
        assert wait_health(port), (
            f"{component} never became healthy: "
            + proc.stderr.read1().decode(errors="replace")
        )
        proc.send_signal(signal.SIGTERM)
        assert proc.wait(timeout=10) == 0
    finally:
        if proc.poll() is None:
            proc.kill()
            proc.wait()


def test_agents_require_node_name():
    for component in ("tpuagent", "sharingagent"):
        proc = subprocess.run(
            [sys.executable, "-m", "nos_tpu", component],
            cwd=REPO,
            env={k: v for k, v in os.environ.items() if k != "NODE_NAME"},
            capture_output=True,
            timeout=30,
        )
        assert proc.returncode == 1
        assert b"NODE_NAME" in proc.stderr


class TestManifests:
    def test_config_tree_is_valid_yaml(self):
        files = sorted((REPO / "config").rglob("*.yaml"))
        assert len(files) >= 8
        for f in files:
            for doc in yaml.safe_load_all(f.read_text()):
                assert doc is None or isinstance(doc, dict), f

    def test_crds_match_api_types(self):
        eq = yaml.safe_load(
            (REPO / "config/crd/bases/nos.nebuly.com_elasticquotas.yaml").read_text()
        )
        assert eq["spec"]["group"] == "nos.nebuly.com"
        assert eq["spec"]["names"]["kind"] == "ElasticQuota"
        assert eq["spec"]["names"]["shortNames"] == ["eq", "eqs"]
        version = eq["spec"]["versions"][0]
        props = version["schema"]["openAPIV3Schema"]["properties"]
        assert set(props["spec"]["properties"]) == {"min", "max"}
        assert "used" in props["status"]["properties"]

        ceq = yaml.safe_load(
            (
                REPO / "config/crd/bases/nos.nebuly.com_compositeelasticquotas.yaml"
            ).read_text()
        )
        assert ceq["spec"]["names"]["kind"] == "CompositeElasticQuota"
        spec_props = ceq["spec"]["versions"][0]["schema"]["openAPIV3Schema"][
            "properties"
        ]["spec"]
        assert set(spec_props["properties"]) == {"namespaces", "min", "max"}
        assert spec_props["required"] == ["namespaces"]

    def test_chart_static_files_parse(self):
        chart = REPO / "helm-charts/nos-tpu"
        meta = yaml.safe_load((chart / "Chart.yaml").read_text())
        assert meta["name"] == "nos-tpu"
        values = yaml.safe_load((chart / "values.yaml").read_text())
        for component in (
            "operator",
            "partitioner",
            "scheduler",
            "tpuagent",
            "sharingagent",
            "metricsexporter",
        ):
            assert "enabled" in values[component], component
        # CRDs in the chart stay in sync with the kustomize copies.
        for crd in (chart / "crds").glob("*.yaml"):
            assert (
                crd.read_text()
                == (REPO / "config/crd/bases" / crd.name).read_text()
            ), f"{crd.name} diverged from config/crd/bases"

    def test_chart_covers_every_component(self):
        templates = REPO / "helm-charts/nos-tpu/templates"
        rendered = "\n".join(
            p.read_text() for p in templates.rglob("*.yaml")
        ) + (templates / "NOTES.txt").read_text()
        for component in (
            "operator",
            "partitioner",
            "scheduler",
            "tpuagent",
            "sharingagent",
            "metricsexporter",
        ):
            assert component in rendered, f"chart misses {component}"

    def test_dockerfiles_exist_per_component(self):
        for component in (
            "operator",
            "partitioner",
            "scheduler",
            "tpuagent",
            "sharingagent",
            "metricsexporter",
        ):
            dockerfile = REPO / "build" / component / "Dockerfile"
            assert dockerfile.is_file(), component
            assert "ENTRYPOINT" in dockerfile.read_text()

    def test_chart_template_includes_resolve(self):
        """Every `include "x"` in the chart has a matching `define "x"` —
        the closest thing to `helm lint` this image can run."""
        import re

        templates = REPO / "helm-charts/nos-tpu/templates"
        sources = [p.read_text() for p in templates.rglob("*")
                   if p.is_file() and p.suffix in (".yaml", ".tpl", ".txt")]
        text = "\n".join(sources)
        defined = set(re.findall(r'\{\{-?\s*define\s+"([^"]+)"', text))
        included = set(re.findall(r'include\s+"([^"]+)"', text))
        missing = included - defined
        assert not missing, f"chart includes without defines: {missing}"

    def test_values_cover_template_references(self):
        """Top-level .Values.<key> references in templates exist in
        values.yaml (catches renamed/missing value blocks)."""
        import re

        chart = REPO / "helm-charts/nos-tpu"
        values = yaml.safe_load((chart / "values.yaml").read_text())
        text = "\n".join(
            p.read_text() for p in (chart / "templates").rglob("*")
            if p.is_file() and p.suffix in (".yaml", ".tpl", ".txt")
        )
        roots = set(re.findall(r"\.Values\.([A-Za-z0-9_]+)", text))
        missing = {r for r in roots if r not in values}
        assert not missing, f"templates reference undefined values: {missing}"
