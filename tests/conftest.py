"""Test bootstrap: force an 8-device virtual CPU platform before jax imports.

Mirrors the reference's test strategy of running everything without real
hardware (nos runs NVML-free via mocks + envtest; we run TPU-free via a
virtual CPU mesh). See SURVEY.md §4.
"""
import os
import sys

os.environ["JAX_PLATFORMS"] = "cpu"
_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (_flags + " --xla_force_host_platform_device_count=8").strip()

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

# The environment may ship a TPU PJRT plugin whose registration (via
# sitecustomize) outranks JAX_PLATFORMS; force the cpu platform through the
# config API as well so tests always see the 8-device virtual CPU mesh.
import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")

# Persistent compilation cache: the ML tier's wall time is dominated by
# XLA compiles of the same programs every run (the 8-stage pipeline tests
# alone cost minutes). Cache survives across runs (and is keyed by HLO,
# so shape/code changes miss safely). Override with
# NOS_TEST_CC_DIR="" to disable.
#
# The dir is suffixed with a host-CPU fingerprint: XLA:CPU caches AOT
# executables whose machine features must match the loading host — a
# cache written on a different machine (shared /tmp images, CI runners)
# reloads with "feature mismatch ... could lead to SIGILL" errors.
_cc_dir = os.environ.get("NOS_TEST_CC_DIR", "/tmp/nos-tpu-test-jax-cache")
if _cc_dir and "NOS_TEST_CC_DIR" not in os.environ:
    import hashlib
    import platform

    try:
        # x86 lists CPU features under "flags", ARM under "Features";
        # volatile lines (cpu MHz) must stay out or the cache splits
        # on every boot.
        with open("/proc/cpuinfo") as fh:
            flags = "".join(
                ln for ln in fh
                if ln.lower().startswith(("flags", "features"))
            )
    except OSError:
        flags = ""
    flags = flags or platform.processor() or platform.machine()
    _cc_dir += "-" + hashlib.sha256(str(flags).encode()).hexdigest()[:12]
if _cc_dir:
    jax.config.update("jax_compilation_cache_dir", _cc_dir)
    jax.config.update("jax_persistent_cache_min_compile_time_secs", 1.0)
