import urllib.request

from nos_tpu.util.health import HealthServer
from nos_tpu.util.metrics import MetricsRegistry


class TestRegistry:
    def test_counter_and_gauge(self):
        r = MetricsRegistry()
        c = r.counter("test_total", "help me")
        c.inc()
        c.inc(2)
        g = r.gauge("test_gauge")
        g.set(7)
        text = r.render()
        assert "test_total 3.0" in text
        assert "test_gauge 7.0" in text
        assert "# TYPE test_total counter" in text

    def test_same_name_returns_same_metric(self):
        r = MetricsRegistry()
        assert r.counter("x") is r.counter("x")

    def test_histogram_buckets_and_percentile(self):
        r = MetricsRegistry()
        h = r.histogram("lat_seconds", buckets=(0.1, 1.0))
        for v in (0.05, 0.5, 0.7, 5.0):
            h.observe(v)
        assert h.count == 4
        assert h.percentile(50) == 0.7
        text = r.render()
        assert 'lat_seconds_bucket{le="0.1"} 1' in text
        assert 'lat_seconds_bucket{le="1.0"} 3' in text
        assert 'lat_seconds_bucket{le="+Inf"} 4' in text

    def test_snapshot(self):
        r = MetricsRegistry()
        r.counter("a").inc(4)
        h = r.histogram("b")
        h.observe(1.0)
        snap = r.snapshot()
        assert snap["a"] == 4
        assert snap["b_count"] == 1
        assert snap["b_p50"] == 1.0


class TestHealthServer:
    def test_endpoints(self):
        ready = {"ok": False}
        server = HealthServer(port=0, ready_check=lambda: ready["ok"])
        port = server.start()
        try:
            def get(path):
                try:
                    with urllib.request.urlopen(f"http://127.0.0.1:{port}{path}") as resp:
                        return resp.status, resp.read().decode()
                except urllib.error.HTTPError as e:
                    return e.code, ""

            assert get("/healthz")[0] == 200
            assert get("/readyz")[0] == 503
            ready["ok"] = True
            assert get("/readyz")[0] == 200
            status, body = get("/metrics")
            assert status == 200
            assert "nos_tpu" in body
            assert get("/nope")[0] == 404
        finally:
            server.stop()


class TestSubsystemCounters:
    """The round-3 subsystems feed the domain registry too."""

    def test_multihost_expansion_counts(self):
        from nos_tpu.api.v1alpha1 import constants
        from nos_tpu.controllers.partitioner.multihost import MultihostExpander
        from nos_tpu.kube.controller import Request
        from nos_tpu.kube.store import KubeStore
        from nos_tpu.util import metrics
        from tests.factory import build_pod, build_tpu_node

        before = metrics.MULTIHOST_EXPANSIONS.value
        store = KubeStore()
        store.create(build_tpu_node(name="tpu-0"))
        store.create(build_pod("big", {constants.RESOURCE_TPU: 16}))
        MultihostExpander(store).reconcile(Request(name="big", namespace="default"))
        assert metrics.MULTIHOST_EXPANSIONS.value == before + 1

    def test_webhook_denial_counts(self):
        from nos_tpu.kube.store import KubeStore
        from nos_tpu.kube.webhook import WebhookServer
        from nos_tpu.util import metrics

        before = metrics.WEBHOOK_DENIALS.value
        server = WebhookServer.__new__(WebhookServer)  # review logic only
        server.store = KubeStore()

        def deny(obj, store):
            from nos_tpu.kube.store import AdmissionError

            raise AdmissionError("nope")

        review = {"request": {"uid": "u", "object": {
            "kind": "ElasticQuota", "metadata": {"name": "x", "namespace": "ns"},
            "spec": {}}}}
        out = server._review(review, deny)
        assert out["response"]["allowed"] is False
        assert metrics.WEBHOOK_DENIALS.value == before + 1

    def test_leader_transition_counts(self):
        from nos_tpu.kube.leaderelection import LeaderElector
        from nos_tpu.kube.store import KubeStore
        from nos_tpu.util import metrics

        before = metrics.LEADER_TRANSITIONS.value
        elector = LeaderElector(
            KubeStore(), name="m", identity="a",
            lease_duration_s=0.3, renew_period_s=0.05,
        )
        elector.start()
        try:
            assert elector.wait_for_leadership(5.0)
            assert metrics.LEADER_TRANSITIONS.value == before + 1
        finally:
            elector.stop()


class TestMetricsAuth:
    def test_metrics_token_enforced(self):
        import http.client

        from nos_tpu.util.health import HealthServer

        server = HealthServer(port=0, metrics_token="s3cret")
        port = server.start()
        try:
            def get(path, token=None):
                conn = http.client.HTTPConnection("127.0.0.1", port, timeout=2)
                headers = {"Authorization": f"Bearer {token}"} if token else {}
                conn.request("GET", path, headers=headers)
                return conn.getresponse().status

            assert get("/metrics") == 401           # no token
            assert get("/metrics", "wrong") == 401  # bad token
            assert get("/metrics", "s3cret") == 200
            assert get("/healthz") == 200           # probes stay open
            assert get("/readyz") == 200
        finally:
            server.stop()

    def test_empty_token_provider_fails_closed(self):
        import http.client

        from nos_tpu.util.health import HealthServer

        server = HealthServer(port=0, metrics_token=lambda: "")
        port = server.start()
        try:
            conn = http.client.HTTPConnection("127.0.0.1", port, timeout=2)
            conn.request("GET", "/metrics")
            assert conn.getresponse().status == 401  # degraded secret != open
        finally:
            server.stop()

    def test_split_metrics_listener(self):
        import http.client

        from nos_tpu.util.health import HealthServer

        server = HealthServer(port=0, metrics_loopback_port=0)
        # port 0 for the loopback listener too: pick free ports
        health_port = server.start()
        metrics_port = server._servers[1].server_address[1]
        try:
            def get(port, path):
                conn = http.client.HTTPConnection("127.0.0.1", port, timeout=2)
                conn.request("GET", path)
                return conn.getresponse().status

            assert get(health_port, "/healthz") == 200
            assert get(health_port, "/metrics") == 404  # moved off probes port
            assert get(metrics_port, "/metrics") == 200
            assert get(metrics_port, "/healthz") == 404
        finally:
            server.stop()
