import urllib.request

from nos_tpu.util.health import HealthServer
from nos_tpu.util.metrics import MetricsRegistry


class TestRegistry:
    def test_counter_and_gauge(self):
        r = MetricsRegistry()
        c = r.counter("test_total", "help me")
        c.inc()
        c.inc(2)
        g = r.gauge("test_gauge")
        g.set(7)
        text = r.render()
        assert "test_total 3.0" in text
        assert "test_gauge 7.0" in text
        assert "# TYPE test_total counter" in text

    def test_same_name_returns_same_metric(self):
        r = MetricsRegistry()
        assert r.counter("x") is r.counter("x")

    def test_histogram_buckets_and_percentile(self):
        r = MetricsRegistry()
        h = r.histogram("lat_seconds", buckets=(0.1, 1.0))
        for v in (0.05, 0.5, 0.7, 5.0):
            h.observe(v)
        assert h.count == 4
        assert h.percentile(50) == 0.7
        text = r.render()
        assert 'lat_seconds_bucket{le="0.1"} 1' in text
        assert 'lat_seconds_bucket{le="1.0"} 3' in text
        assert 'lat_seconds_bucket{le="+Inf"} 4' in text

    def test_snapshot(self):
        r = MetricsRegistry()
        r.counter("a").inc(4)
        h = r.histogram("b")
        h.observe(1.0)
        snap = r.snapshot()
        assert snap["a"] == 4
        assert snap["b_count"] == 1
        assert snap["b_p50"] == 1.0


class TestHealthServer:
    def test_endpoints(self):
        ready = {"ok": False}
        server = HealthServer(port=0, ready_check=lambda: ready["ok"])
        port = server.start()
        try:
            def get(path):
                try:
                    with urllib.request.urlopen(f"http://127.0.0.1:{port}{path}") as resp:
                        return resp.status, resp.read().decode()
                except urllib.error.HTTPError as e:
                    return e.code, ""

            assert get("/healthz")[0] == 200
            assert get("/readyz")[0] == 503
            ready["ok"] = True
            assert get("/readyz")[0] == 200
            status, body = get("/metrics")
            assert status == 200
            assert "nos_tpu" in body
            assert get("/nope")[0] == 404
        finally:
            server.stop()
