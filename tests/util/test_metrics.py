import json
import re
import urllib.request

import pytest

from nos_tpu.util.health import HealthServer
from nos_tpu.util.metrics import MetricsRegistry, escape_label_value


class TestRegistry:
    def test_counter_and_gauge(self):
        r = MetricsRegistry()
        c = r.counter("test_total", "help me")
        c.inc()
        c.inc(2)
        g = r.gauge("test_gauge")
        g.set(7)
        text = r.render()
        assert "test_total 3.0" in text
        assert "test_gauge 7.0" in text
        assert "# TYPE test_total counter" in text

    def test_same_name_returns_same_metric(self):
        r = MetricsRegistry()
        assert r.counter("x") is r.counter("x")

    def test_histogram_buckets_and_percentile(self):
        r = MetricsRegistry()
        h = r.histogram("lat_seconds", buckets=(0.1, 1.0))
        for v in (0.05, 0.5, 0.7, 5.0):
            h.observe(v)
        assert h.count == 4
        assert h.percentile(50) == 0.7
        text = r.render()
        assert 'lat_seconds_bucket{le="0.1"} 1' in text
        assert 'lat_seconds_bucket{le="1.0"} 3' in text
        assert 'lat_seconds_bucket{le="+Inf"} 4' in text

    def test_snapshot(self):
        r = MetricsRegistry()
        r.counter("a").inc(4)
        h = r.histogram("b")
        h.observe(1.0)
        snap = r.snapshot()
        assert snap["a"] == 4
        assert snap["b_count"] == 1
        assert snap["b_p50"] == 1.0

    def test_snapshot_sum_and_high_percentiles(self):
        r = MetricsRegistry()
        h = r.histogram("lat")
        for i in range(100):
            h.observe(i / 100.0)
        snap = r.snapshot()
        assert snap["lat_count"] == 100
        assert snap["lat_sum"] == pytest.approx(sum(i / 100.0 for i in range(100)))
        assert snap["lat_p50"] == pytest.approx(0.5, abs=0.02)
        assert snap["lat_p95"] == pytest.approx(0.95, abs=0.02)
        assert snap["lat_p99"] == pytest.approx(0.99, abs=0.02)


class TestLabeledMetrics:
    def test_counter_labels_render_as_series(self):
        r = MetricsRegistry()
        c = r.counter("slices_total", "h")
        c.labels(profile="2x2x1").inc(3)
        c.labels(profile="1x1").inc()
        text = r.render()
        assert 'slices_total{profile="2x2x1"} 3.0' in text
        assert 'slices_total{profile="1x1"} 1.0' in text
        # HELP/TYPE once per family, not per child
        assert text.count("# TYPE slices_total counter") == 1
        # family never incremented bare: no unlabeled sample
        assert "\nslices_total 0" not in text

    def test_labels_get_or_create_same_child(self):
        r = MetricsRegistry()
        c = r.counter("x_total")
        assert c.labels(a="1") is c.labels(a="1")
        assert c.labels(a="1") is not c.labels(a="2")
        with pytest.raises(ValueError):
            c.labels(a="1").labels(b="2")

    def test_family_total_aggregates_children(self):
        r = MetricsRegistry()
        c = r.counter("y_total")
        c.labels(ns="a").inc(2)
        c.labels(ns="b").inc(3)
        assert c.total == 5.0
        c.inc()  # bare sample still works alongside children
        assert c.total == 6.0
        assert "y_total 1.0" in r.render()

    def test_gauge_labels(self):
        r = MetricsRegistry()
        g = r.gauge("depth")
        g.labels(queue="q1").set(7)
        text = r.render()
        assert 'depth{queue="q1"} 7.0' in text
        assert "# TYPE depth gauge" in text

    def test_histogram_labels_render_buckets_per_series(self):
        r = MetricsRegistry()
        h = r.histogram("lat_seconds", buckets=(0.1, 1.0))
        h.labels(ns="ml").observe(0.5)
        text = r.render()
        assert 'lat_seconds_bucket{le="0.1",ns="ml"} 0' in text
        assert 'lat_seconds_bucket{le="1.0",ns="ml"} 1' in text
        assert 'lat_seconds_bucket{le="+Inf",ns="ml"} 1' in text
        assert 'lat_seconds_sum{ns="ml"} 0.5' in text
        assert 'lat_seconds_count{ns="ml"} 1' in text
        assert text.count("# TYPE lat_seconds histogram") == 1
        snap = r.snapshot()
        assert snap['lat_seconds_count{ns="ml"}'] == 1

    def test_label_value_escaping(self):
        assert escape_label_value('a\\b"c\nd') == 'a\\\\b\\"c\\nd'
        r = MetricsRegistry()
        c = r.counter("esc_total")
        c.labels(ns='we"ird\\ns\nx').inc()
        text = r.render()
        assert 'esc_total{ns="we\\"ird\\\\ns\\nx"} 1.0' in text
        # escaped newline must not split the sample line
        line = next(l for l in text.splitlines() if l.startswith("esc_total{"))
        assert line.endswith("1.0")


class TestHealthServer:
    def test_endpoints(self):
        ready = {"ok": False}
        server = HealthServer(port=0, ready_check=lambda: ready["ok"])
        port = server.start()
        try:
            def get(path):
                try:
                    with urllib.request.urlopen(f"http://127.0.0.1:{port}{path}") as resp:
                        return resp.status, resp.read().decode()
                except urllib.error.HTTPError as e:
                    return e.code, ""

            assert get("/healthz")[0] == 200
            assert get("/readyz")[0] == 503
            ready["ok"] = True
            assert get("/readyz")[0] == 200
            status, body = get("/metrics")
            assert status == 200
            assert "nos_tpu" in body
            assert get("/nope")[0] == 404
        finally:
            server.stop()


class TestSubsystemCounters:
    """The round-3 subsystems feed the domain registry too."""

    def test_multihost_expansion_counts(self):
        from nos_tpu.api.v1alpha1 import constants
        from nos_tpu.controllers.partitioner.multihost import MultihostExpander
        from nos_tpu.kube.controller import Request
        from nos_tpu.kube.store import KubeStore
        from nos_tpu.util import metrics
        from tests.factory import build_pod, build_tpu_node

        before = metrics.MULTIHOST_EXPANSIONS.value
        store = KubeStore()
        store.create(build_tpu_node(name="tpu-0"))
        store.create(build_pod("big", {constants.RESOURCE_TPU: 16}))
        MultihostExpander(store).reconcile(Request(name="big", namespace="default"))
        assert metrics.MULTIHOST_EXPANSIONS.value == before + 1

    def test_webhook_denial_counts(self):
        from nos_tpu.kube.store import KubeStore
        from nos_tpu.kube.webhook import WebhookServer
        from nos_tpu.util import metrics

        before = metrics.WEBHOOK_DENIALS.value
        server = WebhookServer.__new__(WebhookServer)  # review logic only
        server.store = KubeStore()

        def deny(obj, store):
            from nos_tpu.kube.store import AdmissionError

            raise AdmissionError("nope")

        review = {"request": {"uid": "u", "object": {
            "kind": "ElasticQuota", "metadata": {"name": "x", "namespace": "ns"},
            "spec": {}}}}
        out = server._review(review, deny)
        assert out["response"]["allowed"] is False
        assert metrics.WEBHOOK_DENIALS.value == before + 1

    def test_leader_transition_counts(self):
        from nos_tpu.kube.leaderelection import LeaderElector
        from nos_tpu.kube.store import KubeStore
        from nos_tpu.util import metrics

        before = metrics.LEADER_TRANSITIONS.value
        elector = LeaderElector(
            KubeStore(), name="m", identity="a",
            lease_duration_s=0.3, renew_period_s=0.05,
        )
        elector.start()
        try:
            assert elector.wait_for_leadership(5.0)
            assert metrics.LEADER_TRANSITIONS.value == before + 1
        finally:
            elector.stop()


class TestMetricsAuth:
    def test_metrics_token_enforced(self):
        import http.client

        from nos_tpu.util.health import HealthServer

        server = HealthServer(port=0, metrics_token="s3cret")
        port = server.start()
        try:
            def get(path, token=None):
                conn = http.client.HTTPConnection("127.0.0.1", port, timeout=2)
                headers = {"Authorization": f"Bearer {token}"} if token else {}
                conn.request("GET", path, headers=headers)
                return conn.getresponse().status

            assert get("/metrics") == 401           # no token
            assert get("/metrics", "wrong") == 401  # bad token
            assert get("/metrics", "s3cret") == 200
            assert get("/healthz") == 200           # probes stay open
            assert get("/readyz") == 200
        finally:
            server.stop()

    def test_empty_token_provider_fails_closed(self):
        import http.client

        from nos_tpu.util.health import HealthServer

        server = HealthServer(port=0, metrics_token=lambda: "")
        port = server.start()
        try:
            conn = http.client.HTTPConnection("127.0.0.1", port, timeout=2)
            conn.request("GET", "/metrics")
            assert conn.getresponse().status == 401  # degraded secret != open
        finally:
            server.stop()

    def test_split_metrics_listener(self):
        import http.client

        from nos_tpu.util.health import HealthServer

        server = HealthServer(port=0, metrics_loopback_port=0)
        # port 0 for the loopback listener too: pick free ports
        health_port = server.start()
        metrics_port = server._servers[1].server_address[1]
        try:
            def get(port, path):
                conn = http.client.HTTPConnection("127.0.0.1", port, timeout=2)
                conn.request("GET", path)
                return conn.getresponse().status

            assert get(health_port, "/healthz") == 200
            assert get(health_port, "/metrics") == 404  # moved off probes port
            assert get(metrics_port, "/metrics") == 200
            assert get(metrics_port, "/healthz") == 404
        finally:
            server.stop()


def _get(port, path, token=None):
    import http.client

    conn = http.client.HTTPConnection("127.0.0.1", port, timeout=2)
    headers = {"Authorization": f"Bearer {token}"} if token else {}
    conn.request("GET", path, headers=headers)
    resp = conn.getresponse()
    return resp.status, resp.read().decode()


class TestDebugEndpoints:
    """/debug/traces and /debug/vars share the /metrics bearer auth."""

    def test_debug_endpoints_require_token(self):
        server = HealthServer(port=0, metrics_token="s3cret")
        port = server.start()
        try:
            assert _get(port, "/debug/traces")[0] == 401
            assert _get(port, "/debug/vars")[0] == 401
            assert _get(port, "/debug/traces", "wrong")[0] == 401
            assert _get(port, "/debug/traces", "s3cret")[0] == 200
            assert _get(port, "/debug/vars", "s3cret")[0] == 200
        finally:
            server.stop()

    def test_debug_traces_summaries_and_full_export(self):
        from nos_tpu.util.tracing import TRACER

        TRACER.reset()
        server = HealthServer(port=0)
        port = server.start()
        try:
            with TRACER.span("pod.journey", pod="ns/p"):
                with TRACER.span("scheduler.cycle"):
                    pass
            status, body = _get(port, "/debug/traces")
            assert status == 200
            summaries = json.loads(body)["traces"]
            assert summaries[0]["root"] == "pod.journey"
            assert summaries[0]["stages"]["scheduler.cycle"]["count"] == 1
            trace_id = summaries[0]["trace_id"]
            status, body = _get(port, f"/debug/traces?id={trace_id}")
            assert status == 200
            chrome = json.loads(body)
            assert chrome["otherData"]["trace_id"] == trace_id
            assert {e["name"] for e in chrome["traceEvents"] if e["ph"] == "X"} == {
                "pod.journey",
                "scheduler.cycle",
            }
            assert _get(port, "/debug/traces?id=nope")[0] == 404
        finally:
            server.stop()
            TRACER.reset()

    def test_debug_vars_is_the_registry_snapshot(self):
        from nos_tpu.util import metrics

        metrics.PLANS_APPLIED.inc()
        server = HealthServer(port=0)
        port = server.start()
        try:
            status, body = _get(port, "/debug/vars")
            assert status == 200
            snap = json.loads(body)
            assert snap["nos_tpu_partitioning_plans_applied_total"] >= 1
        finally:
            server.stop()


# One sample line of the Prometheus text exposition format: metric name,
# optional {labels} with escaped values, then a number.
_SAMPLE_RE = re.compile(
    r"^[a-zA-Z_:][a-zA-Z0-9_:]*"
    r"(\{[a-zA-Z_][a-zA-Z0-9_]*=\"(?:[^\"\\\n]|\\\\|\\\"|\\n)*\""
    r"(,[a-zA-Z_][a-zA-Z0-9_]*=\"(?:[^\"\\\n]|\\\\|\\\"|\\n)*\")*\})?"
    r" ([+-]?Inf|[0-9]+(\.[0-9]+)?([eE][+-]?[0-9]+)?)$"
)


class TestTextFormatConformance:
    def test_served_metrics_parse(self):
        from nos_tpu.util import metrics

        # Ensure at least one labeled family is present in the scrape.
        metrics.SLICES_CREATED.labels(profile="2x2x1").inc()
        metrics.SCHEDULE_LATENCY.labels(namespace="ml").observe(0.05)
        server = HealthServer(port=0)
        port = server.start()
        try:
            status, body = _get(port, "/metrics")
        finally:
            server.stop()
        assert status == 200
        seen_types = {}
        samples = 0
        for line in body.splitlines():
            if not line:
                continue
            if line.startswith("# HELP "):
                continue
            if line.startswith("# TYPE "):
                _, _, name, mtype = line.split(" ", 3)
                assert mtype in ("counter", "gauge", "histogram"), line
                assert name not in seen_types, f"duplicate TYPE for {name}"
                seen_types[name] = mtype
                continue
            assert _SAMPLE_RE.match(line), f"malformed sample line: {line!r}"
            samples += 1
        assert samples > 0
        assert 'nos_tpu_slices_created_total{profile="2x2x1"}' in body
        assert 'nos_tpu_schedule_latency_seconds_count{namespace="ml"}' in body
        assert (
            'nos_tpu_schedule_latency_seconds_bucket{le="0.1",namespace="ml"}'
            in body
        )


class TestExpositionEdgeCases:
    """Edge cases the capacity-ledger series stress: profile strings that
    look like exposition syntax, histogram summaries before any sample,
    and per-node gauges that must not go stale after a node disappears."""

    def test_profile_label_values_with_x_and_quotes(self):
        r = MetricsRegistry()
        c = r.counter("cap_total")
        # Real profile strings contain 'x' (topology) — and a hostile
        # label value with quotes/backslashes must stay one sample line.
        c.labels(profile="2x4", state="busy").inc(2)
        c.labels(profile='2x2"x"', state="busy").inc()
        text = r.render()
        assert 'cap_total{profile="2x4",state="busy"} 2.0' in text
        assert 'cap_total{profile="2x2\\"x\\"",state="busy"} 1.0' in text
        for line in text.splitlines():
            if line.startswith("cap_total{"):
                assert line.endswith(".0"), f"split sample line: {line!r}"

    def test_histogram_sum_and_p95_on_empty_series(self):
        r = MetricsRegistry()
        h = r.histogram("wait_seconds", buckets=(1.0, 10.0))
        # No samples yet: percentile is None (not 0.0 — zero is a real
        # wait), _sum/_count render as exact zeros, nothing crashes.
        assert h.percentile(95) is None
        text = r.render()
        assert "wait_seconds_sum 0.0" in text
        assert "wait_seconds_count 0" in text
        snap = r.snapshot()
        assert snap["wait_seconds_count"] == 0
        assert "wait_seconds_p95" not in snap

    def test_histogram_sum_and_p95_on_single_sample(self):
        r = MetricsRegistry()
        h = r.histogram("wait_seconds", buckets=(1.0, 10.0))
        h.observe(3.5)
        # One sample: every percentile IS that sample and _sum is exact.
        assert h.percentile(50) == 3.5
        assert h.percentile(95) == 3.5
        text = r.render()
        assert "wait_seconds_sum 3.5" in text
        assert "wait_seconds_count 1" in text
        assert 'wait_seconds_bucket{le="10.0"} 1' in text

    def test_node_gauges_reset_when_node_deleted(self):
        import time

        from nos_tpu.capacity import CapacityLedger
        from nos_tpu.kube.store import KubeStore
        from nos_tpu.util.metrics import CAPACITY_NODE_CHIPS, NODE_FRAGMENTATION
        from tests.factory import build_tpu_node

        store = KubeStore()
        ledger = CapacityLedger(store)
        store.create(build_tpu_node(name="ghost-node", chips=8))
        ledger.observe(time.time())
        assert CAPACITY_NODE_CHIPS.labels(node="ghost-node", state="total").value == 8.0
        store.delete("Node", "ghost-node")
        ledger.observe(time.time())
        # A vanished node's series are deleted outright — scrapes would
        # otherwise report phantom capacity (or phantom zeros) forever.
        from nos_tpu.util.metrics import REGISTRY

        text = REGISTRY.render()
        assert 'node="ghost-node"' not in text
        assert not CAPACITY_NODE_CHIPS.remove(node="ghost-node", state="total")
        assert not NODE_FRAGMENTATION.remove(node="ghost-node")
