"""tools/lint.py self-tests: each check fires, and the known
false-positive traps (format specs, closures, class attributes,
subscript-target loads) stay quiet."""
import importlib.util
import os

_spec = importlib.util.spec_from_file_location(
    "nos_lint",
    os.path.join(os.path.dirname(__file__), "..", "..", "tools", "lint.py"),
)
lint = importlib.util.module_from_spec(_spec)
_spec.loader.exec_module(lint)


def findings_for(tmp_path, source):
    path = tmp_path / "case.py"
    path.write_text(source)
    return [(f.code, f.line) for f in lint.lint_file(str(path))]


def codes_for(tmp_path, source):
    return {c for c, _ in findings_for(tmp_path, source)}


class TestChecksFire:
    def test_unused_import(self, tmp_path):
        assert codes_for(tmp_path, "import os\n") == {"F401"}

    def test_unused_from_import(self, tmp_path):
        assert codes_for(tmp_path, "from os import path\n") == {"F401"}

    def test_redefinition(self, tmp_path):
        src = "def f():\n    pass\ndef f():\n    pass\n"
        assert codes_for(tmp_path, src) == {"F811"}

    def test_unused_local(self, tmp_path):
        src = "def f():\n    x = 1\n    return 2\n"
        assert codes_for(tmp_path, src) == {"F841"}

    def test_mutable_default(self, tmp_path):
        assert codes_for(tmp_path, "def f(a=[]):\n    return a\n") == {"B006"}

    def test_bare_except(self, tmp_path):
        src = "try:\n    pass\nexcept:\n    pass\n"
        assert codes_for(tmp_path, src) == {"E722"}

    def test_fstring_no_placeholder(self, tmp_path):
        assert codes_for(tmp_path, 'x = f"plain"\nprint(x)\n') == {"F541"}

    def test_todo_marker(self, tmp_path):
        marker = "TO" + "DO"  # split so this file stays lint-clean
        assert codes_for(tmp_path, f"# {marker}: later\n") == {"T100"}

    def test_syntax_error(self, tmp_path):
        assert codes_for(tmp_path, "def f(:\n") == {"E999"}


class TestNoFalsePositives:
    def test_format_spec_not_f541(self, tmp_path):
        assert codes_for(tmp_path, 'def f(x):\n    return f"{x:.3f}"\n') == set()

    def test_closure_usage_counts(self, tmp_path):
        src = (
            "def f():\n"
            "    mesh = 1\n"
            "    def g():\n"
            "        return mesh\n"
            "    return g\n"
        )
        assert codes_for(tmp_path, src) == set()

    def test_class_attribute_not_local(self, tmp_path):
        src = (
            "def f():\n"
            "    class H:\n"
            "        protocol_version = 'HTTP/1.1'\n"
            "    return H\n"
        )
        assert codes_for(tmp_path, src) == set()

    def test_subscript_target_loads_count(self, tmp_path):
        src = (
            "def f(result):\n"
            "    tag = 'k'\n"
            "    result[f'x_{tag}'] = 1\n"
        )
        assert codes_for(tmp_path, src) == set()

    def test_underscore_local_ignored(self, tmp_path):
        assert codes_for(tmp_path, "def f():\n    _x = 1\n    return 2\n") == set()

    def test_dunder_all_counts_as_usage(self, tmp_path):
        src = "from os import path\n__all__ = ['path']\n"
        assert codes_for(tmp_path, src) == set()

    def test_init_py_exempt_from_f401(self, tmp_path):
        path = tmp_path / "__init__.py"
        path.write_text("from os import path\n")
        assert [f.code for f in lint.lint_file(str(path))] == []

    def test_property_setter_not_f811(self, tmp_path):
        src = (
            "class C:\n"
            "    @property\n"
            "    def x(self):\n"
            "        return 1\n"
            "    @x.setter\n"
            "    def x(self, v):\n"
            "        pass\n"
        )
        assert codes_for(tmp_path, src) == set()


class TestNoqa:
    def test_bare_noqa(self, tmp_path):
        assert codes_for(tmp_path, "import os  # noqa\n") == set()

    def test_coded_noqa_matching(self, tmp_path):
        assert codes_for(tmp_path, "import os  # noqa: F401\n") == set()

    def test_coded_noqa_other_code_still_fires(self, tmp_path):
        assert codes_for(tmp_path, "import os  # noqa: E722\n") == {"F401"}


class TestRepoIsClean:
    def test_repo_lint_clean(self):
        repo = os.path.join(os.path.dirname(__file__), "..", "..")
        findings = []
        for target in lint.DEFAULT_TARGETS:
            full = os.path.join(repo, target)
            for path in lint.iter_py([full]):
                findings.extend(lint.lint_file(path))
        assert not findings, "\n".join(str(f) for f in findings)


class TestHotPathNoDeepcopy:
    """The planner's per-trial simulation path (thousands of calls per
    plan()) must stay deepcopy-free — the CoW journal and the version-keyed
    memos exist precisely so no per-trial code needs a deep copy. The two
    deliberate, amortized deep copies are NOT on the checked list:
    SnapshotNode.plan_clone's fallback for partitionables without a
    plan_clone, and Planner._simulation_pod / TpuNode.to_sim_node, which
    run once per (pod, generation) / (node, version) behind memos."""

    def test_no_deepcopy_on_simulation_hot_path(self):
        import ast
        import inspect
        import textwrap

        from nos_tpu.partitioning.core.planner import Planner
        from nos_tpu.partitioning.core.snapshot import ClusterSnapshot
        from nos_tpu.partitioning.core.tracker import SliceTracker
        from nos_tpu.scheduler.framework import Framework
        from nos_tpu.tpu.node import TpuNode

        hot_path = {
            Planner: [
                "_plan_pass",
                "_try_add_pod",
                "_can_schedule",
                "_run_simulation",
                "_has_lacking",
                "_request_signature",
                "_node_info",
                "_candidate_nodes",
                "_claims_free_slices",
                "_prune_plan_caches",
                "_select_plan_mode",
            ],
            ClusterSnapshot: [
                "fork",
                "commit",
                "revert",
                "_touch",
                "get_node",
                "get_candidate_nodes",
                "_node_free_state",
                "node_has_free_slices",
                "_cand_sort_key",
                "refresh_node",
                "get_lacking_slices",
                "free_slice_resources",
                "_apply_free_delta",
                "has_anti_affinity_pods",
                "take_from_pool",
                "update_geometry_for",
                "add_pod",
            ],
            SliceTracker: [
                "__contains__",
                "_key",
                "_convert_plain",
                "lacking_totals",
                "lacking_for",
                "remove",
            ],
            Framework: ["run_pre_filter_plugins", "run_filter_plugins"],
            TpuNode: ["plan_clone", "add_pod"],
        }
        offenders = []
        for cls, names in hot_path.items():
            for name in names:
                fn = getattr(cls, name)
                tree = ast.parse(textwrap.dedent(inspect.getsource(fn)))
                for node in ast.walk(tree):
                    called = isinstance(node, ast.Attribute) and node.attr == "deepcopy"
                    named = isinstance(node, ast.Name) and node.id == "deepcopy"
                    if called or named:
                        offenders.append(f"{cls.__name__}.{name}")
                        break
        assert not offenders, (
            f"deepcopy reached the simulation hot path: {offenders}"
        )


class TestIncrementalPathNoFullScans:
    """The point of incremental replanning is O(dirty) work per cycle —
    a `get_nodes()` call in the delta-maintenance or cache-pruning path
    silently reintroduces an O(cluster) walk per plan. Per-node reads go
    through node_version()/node_has_free_slices()/refresh_node instead.
    (The full-rebuild path and plan()'s own passes legitimately walk the
    world and are NOT on this list.)"""

    def test_no_get_nodes_in_incremental_path(self):
        import ast
        import inspect
        import textwrap

        from nos_tpu.controllers.partitioner.incremental import (
            IncrementalSnapshotMaintainer,
        )
        from nos_tpu.partitioning.core.planner import Planner
        from nos_tpu.partitioning.core.snapshot import ClusterSnapshot

        incremental_path = {
            Planner: ["_prune_plan_caches", "_select_plan_mode"],
            ClusterSnapshot: ["refresh_node", "node_version", "node_count"],
            IncrementalSnapshotMaintainer: ["_classify", "_refresh", "_drain"],
        }
        offenders = []
        for cls, names in incremental_path.items():
            for name in names:
                fn = getattr(cls, name)
                tree = ast.parse(textwrap.dedent(inspect.getsource(fn)))
                for node in ast.walk(tree):
                    if isinstance(node, ast.Attribute) and node.attr == "get_nodes":
                        offenders.append(f"{cls.__name__}.{name}")
                        break
        assert not offenders, (
            f"full get_nodes() scan on the incremental path: {offenders}"
        )


class TestMetricsDocDrift:
    """Every registered metric is namespaced and documented — a new metric
    that skips docs/en/docs/telemetry.md fails CI here, not in review."""

    @staticmethod
    def _registered_names():
        import re

        repo = os.path.join(os.path.dirname(__file__), "..", "..")
        # Scan the whole package, not just util/metrics.py: a subsystem
        # registering its own series (the capacity ledger pattern) must
        # not dodge the docs check by living in a different file.
        names = []
        for path in lint.iter_py([os.path.join(repo, "nos_tpu")]):
            with open(path) as fh:
                source = fh.read()
            names.extend(
                re.findall(
                    r"REGISTRY\.(?:counter|gauge|histogram)\(\s*\"([^\"]+)\"",
                    source,
                )
            )
        return names

    def test_every_metric_has_namespace_prefix(self):
        names = self._registered_names()
        assert names, "metric extraction regex found nothing"
        bad = [n for n in names if not n.startswith("nos_tpu_")]
        assert not bad, f"metrics missing nos_tpu_ prefix: {bad}"

    def test_every_metric_is_documented(self):
        repo = os.path.join(os.path.dirname(__file__), "..", "..")
        with open(
            os.path.join(repo, "docs", "en", "docs", "telemetry.md")
        ) as fh:
            doc = fh.read()
        missing = [n for n in self._registered_names() if n not in doc]
        assert not missing, (
            f"metrics not mentioned in docs/en/docs/telemetry.md: {missing}"
        )


class TestLabelResetAudit:
    """Every metric family carrying a node=/pool=/model= label — the
    labels whose value sets grow with cluster objects — either registers
    its delete-reset code path in metrics.LABEL_RESET_PATHS or carries a
    written justification in metrics.LABEL_RESET_EXEMPT. A family in
    neither dict is a leak-by-default; an entry for a family that no
    longer uses such a label is stale and fails too."""

    OBJECT_LABELS = {"node", "pool", "model"}

    @classmethod
    def _labeled_families(cls):
        """family name -> set of object labels used at .labels() sites,
        resolved through the package-wide CONSTANT = REGISTRY.gauge("...")
        assignments so call sites via `metrics.FOO` / `m.FOO` all count."""
        import ast
        import re

        repo = os.path.join(os.path.dirname(__file__), "..", "..")
        var_to_family = {}
        sources = {}
        for path in lint.iter_py([os.path.join(repo, "nos_tpu")]):
            with open(path) as fh:
                sources[path] = fh.read()
            for m in re.finditer(
                r"(\w+)\s*=\s*REGISTRY\.(?:counter|gauge|histogram)\(\s*\"([^\"]+)\"",
                sources[path],
            ):
                var_to_family[m.group(1)] = m.group(2)
        labeled = {}
        for path, source in sources.items():
            for node in ast.walk(ast.parse(source)):
                if not (
                    isinstance(node, ast.Call)
                    and isinstance(node.func, ast.Attribute)
                    and node.func.attr in ("labels", "remove")
                ):
                    continue
                receiver = node.func.value
                if isinstance(receiver, ast.Name):
                    var = receiver.id
                elif isinstance(receiver, ast.Attribute):
                    var = receiver.attr
                else:
                    continue
                family = var_to_family.get(var)
                if family is None:
                    continue
                used = {
                    kw.arg for kw in node.keywords
                } & cls.OBJECT_LABELS
                if used:
                    labeled.setdefault(family, set()).update(used)
        return labeled

    def test_extraction_sees_the_known_call_sites(self):
        labeled = self._labeled_families()
        # The audited pair from the ISSUE, plus the ledger's node gauges —
        # if any goes missing the extractor broke, not the registry.
        assert labeled.get("nos_tpu_plan_pool_duration_seconds") == {"pool"}
        assert labeled.get("nos_tpu_autoscaler_replicas") == {"model"}
        assert labeled.get("nos_tpu_capacity_node_chips") == {"node"}

    def test_every_labeled_family_has_a_reset_path_or_justification(self):
        from nos_tpu.util import metrics

        labeled = self._labeled_families()
        covered = set(metrics.LABEL_RESET_PATHS) | set(
            metrics.LABEL_RESET_EXEMPT
        )
        missing = sorted(set(labeled) - covered)
        assert not missing, (
            "metric families with node=/pool=/model= labels but no "
            "registered reset path (LABEL_RESET_PATHS) or written "
            f"justification (LABEL_RESET_EXEMPT): {missing}"
        )

    def test_no_stale_registry_entries(self):
        from nos_tpu.util import metrics

        labeled = set(self._labeled_families())
        stale = sorted(
            (set(metrics.LABEL_RESET_PATHS) | set(metrics.LABEL_RESET_EXEMPT))
            - labeled
        )
        assert not stale, (
            "LABEL_RESET_PATHS/LABEL_RESET_EXEMPT entries whose family no "
            f"longer carries a node=/pool=/model= label: {stale}"
        )

    def test_no_family_is_both_reset_and_exempt(self):
        from nos_tpu.util import metrics

        both = sorted(
            set(metrics.LABEL_RESET_PATHS) & set(metrics.LABEL_RESET_EXEMPT)
        )
        assert not both, f"families both reset and exempt: {both}"

    def test_every_entry_is_justified_with_prose(self):
        from nos_tpu.util import metrics

        for registry in (metrics.LABEL_RESET_PATHS, metrics.LABEL_RESET_EXEMPT):
            for family, why in registry.items():
                assert len(why.split()) >= 4, (
                    f"{family}: reset-path/exemption text must say where "
                    f"or why, got {why!r}"
                )


class TestEventReasonsFromConstants:
    """Every EventRecorder.record call site passes its reason as a
    constants.EVENT_REASON_* attribute — never a string literal — so the
    whitelist in api/v1alpha1/constants.py stays the single source of
    truth dashboards and the recorder's runtime check key on."""

    @staticmethod
    def _recorder_record_calls():
        import ast

        repo = os.path.join(os.path.dirname(__file__), "..", "..")
        calls = []
        for path in lint.iter_py([os.path.join(repo, "nos_tpu")]):
            with open(path) as fh:
                tree = ast.parse(fh.read())
            for node in ast.walk(tree):
                if not (
                    isinstance(node, ast.Call)
                    and isinstance(node.func, ast.Attribute)
                    and node.func.attr == "record"
                ):
                    continue
                receiver = node.func.value
                # Only EventRecorder call sites: the receiver is a
                # `recorder` variable or a `.recorder` attribute (the
                # threading convention) — sim/apiserver's watch-journal
                # `state.record(...)` is a different API.
                is_recorder = (
                    isinstance(receiver, ast.Name) and receiver.id == "recorder"
                ) or (
                    isinstance(receiver, ast.Attribute)
                    and receiver.attr == "recorder"
                )
                if is_recorder:
                    calls.append((os.path.relpath(path, repo), node))
        return calls

    def test_every_reason_argument_is_a_constant(self):
        import ast

        calls = self._recorder_record_calls()
        # The suite emits events from the scheduler (fail + bind), the
        # preemptor, the quota controllers, and the partitioner — if this
        # drops, a call site was lost or renamed out of the check.
        assert len(calls) >= 7, (
            f"expected >=7 EventRecorder.record call sites, found {len(calls)}"
        )
        offenders = []
        for path, call in calls:
            if len(call.args) < 2:
                offenders.append(f"{path}:{call.lineno} (reason not positional)")
                continue
            reason = call.args[1]
            ok = (
                isinstance(reason, ast.Attribute)
                and reason.attr.startswith("EVENT_REASON_")
                and isinstance(reason.value, ast.Name)
                and reason.value.id == "constants"
            )
            if not ok:
                offenders.append(f"{path}:{call.lineno}")
        assert not offenders, (
            "EventRecorder.record call sites whose reason is not a "
            f"constants.EVENT_REASON_* attribute: {offenders}"
        )

    def test_reasons_tuple_covers_every_reason_constant(self):
        from nos_tpu.api.v1alpha1 import constants

        declared = {
            value
            for name, value in vars(constants).items()
            if name.startswith("EVENT_REASON_")
        }
        assert declared == set(constants.EVENT_REASONS)
