"""Tracing subsystem: spans, propagation, ring buffer, exports, logging."""
import io
import json
import logging
import threading

import pytest

from nos_tpu.util.tracing import (
    JsonLogFormatter,
    NOOP_SPAN,
    TraceContextFilter,
    Tracer,
    TRACER,
    configure_logging,
)


@pytest.fixture(autouse=True)
def clean_global_tracer():
    TRACER.reset()
    TRACER.enabled = True
    yield
    TRACER.reset()
    TRACER.enabled = True


class TestSpanNesting:
    def test_child_inherits_trace_and_parent(self):
        tracer = Tracer()
        with tracer.span("root") as root:
            with tracer.span("child") as child:
                assert child.trace_id == root.trace_id
                assert child.parent_id == root.span_id
                assert tracer.current() is child
            assert tracer.current() is root
        assert tracer.current() is None

    def test_trace_finalizes_when_root_ends(self):
        tracer = Tracer()
        with tracer.span("root"):
            with tracer.span("child"):
                pass
            assert len(tracer.store) == 0  # root still open
        assert len(tracer.store) == 1
        trace = tracer.store.list()[0]
        assert {s.name for s in trace.spans} == {"root", "child"}
        assert trace.root.name == "root"

    def test_exception_marks_error_status(self):
        tracer = Tracer()
        with pytest.raises(ValueError):
            with tracer.span("boom"):
                raise ValueError("x")
        trace = tracer.store.list()[0]
        assert trace.root.status == "error"

    def test_disabled_tracer_is_noop(self):
        tracer = Tracer()
        tracer.enabled = False
        with tracer.span("root") as span:
            assert span is NOOP_SPAN
            span.set_attribute("k", "v")  # must not blow up or record
            span.add_event("e")
        assert len(tracer.store) == 0
        assert not NOOP_SPAN.attributes and not NOOP_SPAN.events

    def test_attributes_and_events(self):
        tracer = Tracer()
        with tracer.span("root", pod="ns/p") as span:
            span.set_attributes(extra=1)
            span.add_event("observed", kind="tpu")
        root = tracer.store.list()[0].root
        assert root.attributes == {"pod": "ns/p", "extra": 1}
        assert root.events[0][1] == "observed"


class TestThreadPropagation:
    def test_contextvars_do_not_cross_threads_without_attach(self):
        tracer = Tracer()
        seen = {}

        def worker():
            seen["current"] = tracer.current()

        with tracer.span("root"):
            t = threading.Thread(target=worker)
            t.start()
            t.join()
        assert seen["current"] is None

    def test_attach_propagates_across_threads(self):
        tracer = Tracer()
        done = threading.Event()

        def worker(root):
            with tracer.attach(root):
                with tracer.span("worker-stage"):
                    pass
            done.set()

        with tracer.span("root") as root:
            t = threading.Thread(target=worker, args=(root,))
            t.start()
            done.wait(2.0)
            t.join(2.0)
        trace = tracer.store.list()[0]
        names = {s.name for s in trace.spans}
        assert "worker-stage" in names
        worker_span = next(s for s in trace.spans if s.name == "worker-stage")
        assert worker_span.trace_id == root.trace_id
        assert worker_span.parent_id == root.span_id


class TestRingBuffer:
    def test_store_evicts_oldest(self):
        tracer = Tracer(capacity=3)
        ids = []
        for i in range(5):
            with tracer.span(f"r{i}") as s:
                ids.append(s.trace_id)
        assert len(tracer.store) == 3
        assert tracer.store.get(ids[0]) is None
        assert tracer.store.get(ids[1]) is None
        assert tracer.store.get(ids[4]) is not None
        # newest first
        assert [t.root.name for t in tracer.store.list()] == ["r4", "r3", "r2"]

    def test_span_cap_drops_and_counts(self):
        tracer = Tracer()
        tracer.MAX_SPANS_PER_TRACE = 4
        with tracer.span("root"):
            for i in range(6):
                with tracer.span(f"c{i}"):
                    pass
        trace = tracer.store.list()[0]
        assert len(trace.spans) == 4
        # 6 children + root = 7 ended spans, 4 kept.
        assert trace.dropped_spans == 3


class TestChromeExport:
    def test_chrome_shape(self):
        tracer = Tracer()
        with tracer.span("root", pod="ns/p") as root:
            root.add_event("observed")
            with tracer.span("child"):
                pass
        trace = tracer.store.list()[0]
        out = trace.to_chrome()
        assert out["displayTimeUnit"] == "ms"
        assert out["otherData"]["trace_id"] == trace.trace_id
        events = out["traceEvents"]
        complete = [e for e in events if e["ph"] == "X"]
        instants = [e for e in events if e["ph"] == "i"]
        assert {e["name"] for e in complete} == {"root", "child"}
        assert [e["name"] for e in instants] == ["observed"]
        for e in complete:
            assert set(e) >= {"name", "cat", "ph", "ts", "dur", "pid", "tid", "args"}
            assert e["dur"] >= 0
        root_event = next(e for e in complete if e["name"] == "root")
        assert root_event["args"]["pod"] == "ns/p"
        json.dumps(out)  # must be JSON-serializable

    def test_summary_stage_breakdown(self):
        tracer = Tracer()
        with tracer.span("root"):
            for _ in range(2):
                with tracer.span("stage-a"):
                    pass
            with tracer.span("stage-b"):
                with tracer.span("grandchild"):
                    pass
        summary = tracer.store.list()[0].summary()
        assert summary["root"] == "root"
        assert summary["stages"]["stage-a"]["count"] == 2
        assert summary["stages"]["stage-b"]["count"] == 1
        assert "grandchild" not in summary["stages"]  # direct children only


class TestJourneysAndLinks:
    def test_journey_root_is_get_or_create(self):
        tracer = Tracer()
        a = tracer.journey_root(("pod", "ns/p"), "pod.journey")
        b = tracer.journey_root(("pod", "ns/p"), "pod.journey")
        assert a is b
        tracer.end_journey(("pod", "ns/p"), node="n1")
        assert tracer.journey(("pod", "ns/p")) is None
        trace = tracer.store.get(a.trace_id)
        assert trace.root.attributes["node"] == "n1"

    def test_stage_parents_onto_journey_root(self):
        tracer = Tracer()
        root = tracer.journey_root(("pod", "ns/p"), "pod.journey")
        with tracer.span("scheduler.cycle", parent=root) as cycle:
            assert cycle.parent_id == root.span_id
        tracer.end_journey(("pod", "ns/p"))
        names = {s.name for s in tracer.store.get(root.trace_id).spans}
        assert names == {"pod.journey", "scheduler.cycle"}

    def test_link_carries_trace_across_handoff(self):
        tracer = Tracer()
        root = tracer.journey_root(("pod", "ns/p"), "pod.journey")
        with tracer.span("actuator.apply_node", parent=root) as apply_span:
            tracer.link(("reconfig", "n1", "plan-1"), apply_span)
        parent = tracer.linked(("reconfig", "n1", "plan-1"))
        assert parent is apply_span
        # pop semantics: a second reconcile of the same plan gets nothing
        assert tracer.linked(("reconfig", "n1", "plan-1")) is None
        with tracer.span("tpuagent.reconfig", parent=parent) as reconfig:
            assert reconfig.trace_id == root.trace_id
        tracer.end_journey(("pod", "ns/p"))
        names = {s.name for s in tracer.store.get(root.trace_id).spans}
        assert "tpuagent.reconfig" in names

    def test_late_span_appends_to_stored_trace(self):
        tracer = Tracer()
        root = tracer.journey_root(("pod", "ns/p"), "pod.journey")
        tracer.end_journey(("pod", "ns/p"))  # trace finalized + stored
        with tracer.span("kubelet.admit", parent=root):
            pass
        names = {s.name for s in tracer.store.get(root.trace_id).spans}
        assert "kubelet.admit" in names

    def test_journey_eviction_is_bounded(self):
        tracer = Tracer()
        tracer.MAX_JOURNEYS = 4
        roots = [
            tracer.journey_root(("pod", f"ns/p{i}"), "pod.journey")
            for i in range(7)
        ]
        live = [i for i in range(7) if tracer.journey(("pod", f"ns/p{i}"))]
        assert len(live) <= 4
        assert roots[0].ended  # oldest force-ended as abandoned
        assert roots[0].status == "abandoned"


class TestPluginSpanGating:
    def test_plugin_span_needs_active_cycle(self):
        tracer = Tracer()
        with tracer.plugin_span("plugin.X") as span:
            assert span is NOOP_SPAN  # no cycle open: no root minted
        assert len(tracer.store) == 0

    def test_plugin_span_suppressed_in_simulation(self):
        tracer = Tracer()
        with tracer.span("partitioner.plan"):
            with tracer.suppress_plugins():
                with tracer.plugin_span("plugin.X") as span:
                    assert span is NOOP_SPAN
            with tracer.plugin_span("plugin.Y") as span:
                assert span is not NOOP_SPAN
        names = {s.name for s in tracer.store.list()[0].spans}
        assert names == {"partitioner.plan", "plugin.Y"}


class TestLoggingIntegration:
    def test_filter_injects_trace_id(self):
        tracer = Tracer()
        records = []

        class Capture(logging.Handler):
            def emit(self, record):
                records.append(record)

        logger = logging.getLogger("nos_tpu.test_tracing")
        logger.setLevel(logging.INFO)
        handler = Capture()
        handler.addFilter(TraceContextFilter())
        logger.addHandler(handler)
        try:
            # The global contextvar is tracer-independent, so a local
            # Tracer's span is still visible to the filter.
            with tracer.span("root") as span:
                logger.info("inside")
            logger.info("outside")
        finally:
            logger.removeHandler(handler)
        assert records[0].trace_id == span.trace_id
        assert records[0].span_id == span.span_id
        assert records[1].trace_id == ""

    def test_json_formatter_emits_trace_fields(self):
        stream = io.StringIO()
        handler = configure_logging(
            json_format=True, stream=stream, logger_name="nos_tpu.test_tracing_json"
        )
        logger = logging.getLogger("nos_tpu.test_tracing_json")
        logger.setLevel(logging.INFO)
        tracer = Tracer()
        try:
            with tracer.span("root") as span:
                logger.info("hello %s", "world")
        finally:
            logger.removeHandler(handler)
        entry = json.loads(stream.getvalue().strip())
        assert entry["message"] == "hello world"
        assert entry["level"] == "INFO"
        assert entry["trace_id"] == span.trace_id
        assert entry["span_id"] == span.span_id

    def test_json_formatter_without_span_omits_trace_id(self):
        out = JsonLogFormatter().format(
            logging.LogRecord("n", logging.INFO, "p", 1, "m", (), None)
        )
        entry = json.loads(out)
        assert "trace_id" not in entry
