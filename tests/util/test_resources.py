from nos_tpu.api.v1alpha1 import constants
from nos_tpu.kube.objects import Container, ObjectMeta, Pod, PodSpec
from nos_tpu.util import resources as res

V5E = "tpu-v5-lite-podslice"


class TestPodRequest:
    def test_sum_of_containers(self):
        pod = Pod(
            metadata=ObjectMeta(name="p"),
            spec=PodSpec(
                containers=[
                    Container(requests={"cpu": 1, constants.RESOURCE_TPU: 4}),
                    Container(requests={"cpu": 2}),
                ]
            ),
        )
        assert res.compute_pod_request(pod) == {"cpu": 3, constants.RESOURCE_TPU: 4}

    def test_init_containers_take_max(self):
        pod = Pod(
            metadata=ObjectMeta(name="p"),
            spec=PodSpec(
                containers=[Container(requests={"cpu": 1})],
                init_containers=[Container(requests={"cpu": 4, "memory": 8})],
            ),
        )
        assert res.compute_pod_request(pod) == {"cpu": 4, "memory": 8}


class TestTpuChips:
    def test_plain_and_sliced_sum(self):
        req = {
            constants.RESOURCE_TPU: 2,
            constants.tpu_slice_resource("2x2"): 1,
            constants.tpu_slice_resource("2x2x1"): 2,
            "cpu": 4,
        }
        assert res.tpu_chips_in(req) == 2 + 4 + 8

    def test_aggregate_injection(self):
        out = res.with_aggregate_tpu_chips({constants.RESOURCE_TPU: 4})
        assert out[constants.RESOURCE_TPU_CHIPS] == 4

    def test_no_tpu_no_aggregate(self):
        assert constants.RESOURCE_TPU_CHIPS not in res.with_aggregate_tpu_chips({"cpu": 1})


class TestNormalize:
    def test_exact_profile(self):
        out = res.normalize_tpu_request({constants.RESOURCE_TPU: 8}, V5E)
        assert out == {constants.tpu_slice_resource("2x4"): 1}

    def test_rounds_up(self):
        out = res.normalize_tpu_request({constants.RESOURCE_TPU: 3}, V5E)
        assert out == {constants.tpu_slice_resource("2x2"): 1}

    def test_oversized_request_passes_through(self):
        req = {constants.RESOURCE_TPU: 16}
        assert res.normalize_tpu_request(req, V5E) == req

    def test_slice_request_untouched(self):
        req = {constants.tpu_slice_resource("2x2"): 2}
        assert res.normalize_tpu_request(req, V5E) == req
