"""/debug/profile and /debug/loops endpoints: bearer gate, documents,
runtime profiler control, index entries."""
import http.client
import json

from nos_tpu.kube.store import KubeStore
from nos_tpu.util.health import HealthServer
from nos_tpu.util.loop_health import LoopHealthRegistry
from nos_tpu.util.profiling import StackProfiler
from nos_tpu.util.tracing import TRACER


def _get(port, path, token=None):
    conn = http.client.HTTPConnection("127.0.0.1", port, timeout=2)
    headers = {"Authorization": f"Bearer {token}"} if token else {}
    conn.request("GET", path, headers=headers)
    resp = conn.getresponse()
    return resp.status, resp.read().decode()


def _sampled_profiler() -> StackProfiler:
    prof = StackProfiler()
    prof.register_thread(name="endpoint-test")
    with TRACER.span("endpoint.phase"):
        prof.sample_once()
    return prof


class TestDebugProfileEndpoint:
    def test_json_document_behind_bearer_gate(self):
        prof = _sampled_profiler()
        server = HealthServer(port=0, metrics_token="s3cret", profiler=prof)
        port = server.start()
        try:
            assert _get(port, "/debug/profile")[0] == 401
            assert _get(port, "/debug/profile", "wrong")[0] == 401
            status, body = _get(port, "/debug/profile", "s3cret")
            assert status == 200
            doc = json.loads(body)
            assert doc["total_samples"] == 1
            assert doc["phases"] == {"endpoint.phase": 1}
            assert doc["threads"] == ["endpoint-test"]
            assert doc["top"]
        finally:
            server.stop()

    def test_collapsed_format_is_plain_text(self):
        prof = _sampled_profiler()
        server = HealthServer(port=0, profiler=prof)
        port = server.start()
        try:
            status, body = _get(port, "/debug/profile?format=collapsed")
            assert status == 200
            line = body.strip().splitlines()[0]
            assert line.startswith("endpoint-test;endpoint.phase;")
            assert line.rsplit(" ", 1)[1] == "1"
        finally:
            server.stop()

    def test_action_start_stop_controls_sampler(self):
        prof = StackProfiler(interval_seconds=0.001)
        server = HealthServer(port=0, profiler=prof)
        port = server.start()
        try:
            status, body = _get(port, "/debug/profile?action=start")
            assert status == 200
            assert json.loads(body)["enabled"] is True
            assert prof.enabled
            status, body = _get(port, "/debug/profile?action=stop")
            assert status == 200
            assert json.loads(body)["enabled"] is False
            assert not prof.enabled
            assert _get(port, "/debug/profile?action=bogus")[0] == 400
        finally:
            prof.stop()
            server.stop()

    def test_404_when_no_profiler_wired(self):
        server = HealthServer(port=0)
        port = server.start()
        try:
            assert _get(port, "/debug/profile")[0] == 404
        finally:
            server.stop()


class TestDebugLoopsEndpoint:
    def test_rollup_document_behind_bearer_gate(self):
        reg = LoopHealthRegistry()
        reg.register("ep-loop", lambda: {"busy_fraction": 0.25})
        store = KubeStore()
        q = store.watch({"Pod"}, name="ep-watcher")
        server = HealthServer(
            port=0,
            metrics_token="s3cret",
            loops_fn=lambda: reg.payload(store=store),
        )
        port = server.start()
        try:
            assert _get(port, "/debug/loops")[0] == 401
            status, body = _get(port, "/debug/loops", "s3cret")
            assert status == 200
            doc = json.loads(body)
            assert doc["loops"]["ep-loop"] == {"busy_fraction": 0.25}
            assert doc["watchers"]["ep-watcher"]["kinds"] == ["Pod"]
            assert "metrics" in doc
        finally:
            store.stop_watch(q)
            server.stop()

    def test_404_when_no_loops_fn_wired(self):
        server = HealthServer(port=0)
        port = server.start()
        try:
            assert _get(port, "/debug/loops")[0] == 404
        finally:
            server.stop()


class TestDebugIndex:
    def test_index_lists_both_when_wired(self):
        server = HealthServer(
            port=0,
            profiler=StackProfiler(),
            loops_fn=lambda: {"loops": {}},
        )
        port = server.start()
        try:
            endpoints = json.loads(_get(port, "/debug/")[1])["endpoints"]
            assert "/debug/profile" in endpoints
            assert "/debug/loops" in endpoints
        finally:
            server.stop()

    def test_index_omits_both_when_absent(self):
        server = HealthServer(port=0)
        port = server.start()
        try:
            endpoints = json.loads(_get(port, "/debug/")[1])["endpoints"]
            assert "/debug/profile" not in endpoints
            assert "/debug/loops" not in endpoints
        finally:
            server.stop()
