"""/debug/slo endpoint: bearer gate, burn-rate document, index entry."""
import http.client
import json

from nos_tpu.serve.telemetry import RequestRecord, VirtualServeClock
from nos_tpu.slo.engine import SLOEngine
from nos_tpu.util.health import HealthServer


def _get(port, path, token=None):
    conn = http.client.HTTPConnection("127.0.0.1", port, timeout=2)
    headers = {"Authorization": f"Bearer {token}"} if token else {}
    conn.request("GET", path, headers=headers)
    resp = conn.getresponse()
    return resp.status, resp.read().decode()


def _record(rid, retire_t, ttft, trace_id=""):
    return RequestRecord(
        id=rid, model="m", adapter=0, bucket=8, prompt_tokens=4,
        max_new_tokens=8, submit_t=retire_t - ttft - 0.05,
        trace_id=trace_id, admit_t=retire_t - ttft - 0.05,
        first_token_t=retire_t - 0.05, retire_t=retire_t, tokens=8,
        good=ttft <= 0.1,
    )


def _make_slo():
    # Virtual clock pinned just past the last retire, so the endpoint's
    # evaluate() windows cover the fixture events.
    clock = VirtualServeClock()
    clock.advance_to(12.0)
    slo = SLOEngine(
        ["p90 ttft < 100ms", "availability 99%"],
        clock=clock, fast_window_s=60.0, slow_window_s=600.0,
    )
    slo.record(_record(1, 10.0, ttft=0.05))
    slo.record(_record(2, 11.0, ttft=0.25, trace_id="tr-2"))
    return slo


class TestDebugSLOEndpoint:
    def test_serves_rollup_behind_bearer_gate(self):
        slo = _make_slo()
        server = HealthServer(
            port=0, metrics_token="s3cret", slo_fn=slo.debug_payload
        )
        port = server.start()
        try:
            assert _get(port, "/debug/slo")[0] == 401
            assert _get(port, "/debug/slo", "wrong")[0] == 401
            status, body = _get(port, "/debug/slo", "s3cret")
            assert status == 200
            doc = json.loads(body)
            assert doc["requests_seen"] == 2
            by_name = {s["slo"]: s for s in doc["slos"]}
            ttft = by_name["ttft_p90_lt_100ms"]
            assert ttft["slow"] == {
                "requests": 2, "bad": 1, "bad_fraction": 0.5,
                "burn_rate": 5.0,
            }
            assert ttft["compliant"] is False
            # The violation feed links into /debug/traces by journey id.
            assert doc["recent_violations"][0]["trace"] == (
                "/debug/traces?id=tr-2"
            )
        finally:
            server.stop()

    def test_404_when_no_slo_engine_is_wired(self):
        server = HealthServer(port=0)
        port = server.start()
        try:
            assert _get(port, "/debug/slo")[0] == 404
        finally:
            server.stop()

    def test_debug_index_lists_slo_when_wired(self):
        server = HealthServer(port=0, slo_fn=_make_slo().debug_payload)
        port = server.start()
        try:
            status, body = _get(port, "/debug/")
            assert status == 200
            assert "/debug/slo" in json.loads(body)["endpoints"]
        finally:
            server.stop()

    def test_debug_index_omits_slo_when_absent(self):
        server = HealthServer(port=0)
        port = server.start()
        try:
            endpoints = json.loads(_get(port, "/debug/")[1])["endpoints"]
            assert "/debug/slo" not in endpoints
        finally:
            server.stop()
