"""BusyMeter windowing and the LoopHealthRegistry /debug/loops payload."""
import pytest

from nos_tpu.kube.store import KubeStore
from nos_tpu.util import metrics
from nos_tpu.util.loop_health import LOOPS, BusyMeter, LoopHealthRegistry


class TestBusyMeter:
    def test_gauge_updates_once_window_fills(self):
        meter = BusyMeter("test-loop-a")
        meter.record(0.3, idle_s=0.3)  # window not yet full
        assert meter.snapshot()["busy_fraction"] == 0.0
        meter.record(0.2, idle_s=0.3)  # total 1.1s -> window closes at 0.5/1.1
        snap = meter.snapshot()
        assert snap["busy_fraction"] == pytest.approx(0.4545, abs=1e-3)
        assert snap["iterations"] == 2
        rendered = metrics.REGISTRY.render()
        assert 'nos_tpu_controller_busy_fraction{loop="test-loop-a"}' in rendered

    def test_idle_only_iterations_not_counted(self):
        meter = BusyMeter("test-loop-b")
        meter.record(0.0, idle_s=0.6)
        meter.record(0.0, idle_s=0.6)
        snap = meter.snapshot()
        assert snap["iterations"] == 0
        assert snap["busy_fraction"] == 0.0

    def test_saturated_loop_reads_one(self):
        meter = BusyMeter("test-loop-c")
        meter.record(1.2, idle_s=0.0)
        assert meter.snapshot()["busy_fraction"] == 1.0


class TestLoopHealthRegistry:
    def test_register_payload_unregister(self):
        reg = LoopHealthRegistry()
        reg.register("loop-x", lambda: {"busy_fraction": 0.5})
        assert reg.names() == ["loop-x"]
        doc = reg.payload()
        assert doc["loops"]["loop-x"] == {"busy_fraction": 0.5}
        reg.unregister("loop-x")
        assert reg.names() == []
        assert reg.payload()["loops"] == {}

    def test_failing_stats_fn_reports_error_not_raises(self):
        reg = LoopHealthRegistry()

        def boom():
            raise RuntimeError("dead loop")

        reg.register("loop-y", boom)
        doc = reg.payload()
        assert doc["loops"]["loop-y"] == {"error": "RuntimeError: dead loop"}

    def test_payload_includes_store_watch_stats(self):
        reg = LoopHealthRegistry()
        store = KubeStore()
        q = store.watch({"Pod"}, name="payload-watcher")
        try:
            doc = reg.payload(store=store)
            assert doc["watchers"]["payload-watcher"] == {
                "kinds": ["Pod"],
                "depth": 0,
            }
        finally:
            store.stop_watch(q)

    def test_payload_metrics_filtered_to_saturation_families(self):
        reg = LoopHealthRegistry()
        BusyMeter("filter-loop").record(1.5)  # publish a gauge point
        doc = reg.payload()
        assert any(
            k.startswith("nos_tpu_controller_busy_fraction") for k in doc["metrics"]
        )
        # Unrelated families (e.g. plans applied) stay out of the rollup.
        assert not any(k.startswith("nos_tpu_plans") for k in doc["metrics"])

    def test_module_singleton_exists(self):
        assert isinstance(LOOPS, LoopHealthRegistry)
