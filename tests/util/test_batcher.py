import time

from nos_tpu.util.batcher import Batcher


class TestBatcher:
    def test_idle_window_releases(self):
        b = Batcher(timeout_seconds=5.0, idle_seconds=0.05)
        b.start()
        try:
            b.add(1)
            b.add(2)
            batch = b.ready(timeout=2.0)
            assert batch == [1, 2]
        finally:
            b.stop()

    def test_timeout_window_releases_despite_activity(self):
        b = Batcher(timeout_seconds=0.15, idle_seconds=10.0)
        b.start()
        try:
            deadline = time.monotonic() + 0.5
            b.add(0)
            batch = None
            i = 1
            while batch is None and time.monotonic() < deadline:
                b.add(i)  # keep it busy: idle window never fires
                i += 1
                batch = b.ready(timeout=0.01)
            assert batch is not None and batch[0] == 0
        finally:
            b.stop()

    def test_batches_are_separate(self):
        b = Batcher(timeout_seconds=5.0, idle_seconds=0.03)
        b.start()
        try:
            b.add("a")
            first = b.ready(timeout=2.0)
            b.add("b")
            second = b.ready(timeout=2.0)
            assert (first, second) == (["a"], ["b"])
        finally:
            b.stop()

    def test_no_release_when_empty(self):
        b = Batcher(timeout_seconds=0.01, idle_seconds=0.01)
        b.start()
        try:
            assert b.ready(timeout=0.1) is None
        finally:
            b.stop()

    def test_fire_now_bypasses_windows(self):
        b = Batcher(timeout_seconds=60.0, idle_seconds=60.0)
        b.start()
        try:
            b.add("x")
            b.fire_now()
            assert b.ready(timeout=0.5) == ["x"]
        finally:
            b.stop()

    def test_fire_now_delivers_empty_trigger(self):
        # Consumers treat the batch as a wakeup and re-fetch work
        # themselves, so an empty release must still be delivered.
        b = Batcher(timeout_seconds=60.0, idle_seconds=60.0)
        b.start()
        try:
            b.fire_now()
            assert b.ready(timeout=0.5) == []
        finally:
            b.stop()
