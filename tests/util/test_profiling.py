"""StackProfiler: registration filtering, bounded aggregation, phase
attribution through tracing spans, start/stop lifecycle races."""
import threading
import time

import pytest

from nos_tpu.util.profiling import PROFILER, StackProfiler
from nos_tpu.util.tracing import TRACER


@pytest.fixture(autouse=True)
def clean_tracer():
    TRACER.reset()
    TRACER.enabled = True
    yield
    TRACER.reset()
    TRACER.enabled = True


def _hold(event: threading.Event, ready: threading.Event):
    ready.set()
    event.wait(5.0)


class TestRegistration:
    def test_only_registered_threads_are_sampled(self):
        prof = StackProfiler()
        release, ready = threading.Event(), threading.Event()
        bystander = threading.Thread(target=_hold, args=(release, ready), daemon=True)
        bystander.start()
        ready.wait(2.0)
        try:
            prof.register_thread(name="me")
            sampled = prof.sample_once()
            assert sampled == 1  # the bystander thread is invisible
            collapsed = prof.collapsed()
            assert "me;" in collapsed
            assert threading.current_thread().name in ("MainThread", "me") or True
            # every line belongs to the registered thread
            for line in collapsed.strip().splitlines():
                assert line.startswith("me;")
        finally:
            release.set()
            prof.unregister_thread()

    def test_unregister_stops_sampling(self):
        prof = StackProfiler()
        ident = prof.register_thread(name="gone")
        assert prof.sample_once() == 1
        prof.unregister_thread(ident)
        assert prof.sample_once() == 0

    def test_registered_context_manager(self):
        prof = StackProfiler()
        with prof.registered("scoped"):
            assert prof.sample_once() == 1
        assert prof.sample_once() == 0

    def test_dead_thread_yields_no_sample(self):
        prof = StackProfiler()
        t = threading.Thread(target=lambda: None)
        t.start()
        t.join()
        prof.register_thread(name="dead", ident=t.ident)
        assert prof.sample_once() == 0


class TestBoundedTable:
    def test_overflow_increments_drop_counter_not_table(self):
        prof = StackProfiler()
        prof.max_stacks = 2
        prof.register_thread(name="t")
        # Three distinct stacks: vary the call depth.
        def depth1():
            prof.sample_once()

        def depth2():
            depth1()

        def depth3():
            depth2()

        depth1()
        depth2()
        depth3()
        payload = prof.debug_payload()
        assert payload["stacks"] <= 2
        assert payload["dropped_stacks"] >= 1
        assert "(table-overflow);(dropped)" in prof.collapsed()
        # Existing keys keep counting even at capacity.
        depth1()
        assert prof.total_samples == 4

    def test_max_depth_truncates_stacks(self):
        prof = StackProfiler()
        prof.max_depth = 3
        prof.register_thread(name="t")
        prof.sample_once()
        for line in prof.collapsed().strip().splitlines():
            frames = line.rsplit(" ", 1)[0].split(";")
            assert len(frames) <= 2 + 3  # thread + phase + max_depth frames

    def test_reset_clears_samples_keeps_registration(self):
        prof = StackProfiler()
        prof.register_thread(name="t")
        prof.sample_once()
        assert prof.total_samples == 1
        prof.reset()
        assert prof.total_samples == 0
        assert prof.sample_once() == 1  # still registered


class TestPhaseAttribution:
    def test_sample_inside_span_attributes_to_span_name(self):
        prof = StackProfiler()
        prof.register_thread(name="t")
        with TRACER.span("planner.plan"):
            prof.sample_once()
        report = prof.phase_report()
        assert report["phases"] == {"planner.plan": 1}
        assert report["attributed_fraction"] == 1.0

    def test_innermost_span_wins_and_restores(self):
        prof = StackProfiler()
        prof.register_thread(name="t")
        with TRACER.span("outer"):
            with TRACER.span("inner"):
                prof.sample_once()
            prof.sample_once()
        prof.sample_once()
        phases = prof.phase_report()["phases"]
        assert phases["inner"] == 1
        assert phases["outer"] == 1
        assert phases["(no-phase)"] == 1

    def test_tracing_disabled_means_no_phase(self):
        TRACER.enabled = False
        prof = StackProfiler()
        prof.register_thread(name="t")
        with TRACER.span("invisible"):
            prof.sample_once()
        assert prof.phase_report()["phases"] == {"(no-phase)": 1}
        assert prof.phase_report()["attributed_fraction"] == 0.0

    def test_attach_sets_phase_for_other_thread_work(self):
        prof = StackProfiler()
        results = {}

        def worker(span):
            prof.register_thread(name="w")
            with TRACER.attach(span):
                prof.sample_once()
            results["phases"] = prof.phase_report()["phases"]

        with TRACER.span("journey.root") as span:
            t = threading.Thread(target=worker, args=(span,))
            t.start()
            t.join()
        assert results["phases"] == {"journey.root": 1}


class TestLifecycle:
    def test_start_stop_idempotent(self):
        prof = StackProfiler(interval_seconds=0.001)
        assert prof.start() is True
        assert prof.start() is False  # already running
        assert prof.enabled
        assert prof.stop() is True
        assert prof.stop() is False  # already stopped
        assert not prof.enabled

    def test_background_sampling_collects(self):
        prof = StackProfiler(interval_seconds=0.001)
        prof.register_thread(name="main")
        prof.start()
        try:
            deadline = time.monotonic() + 2.0
            while prof.total_samples < 5 and time.monotonic() < deadline:
                time.sleep(0.01)
        finally:
            prof.stop()
        assert prof.total_samples >= 5
        assert prof.overhead_fraction() < 0.5  # sane accounting

    def test_concurrent_start_stop_races_are_safe(self):
        prof = StackProfiler(interval_seconds=0.001)
        prof.register_thread(name="main")
        errors = []

        def churn():
            try:
                for _ in range(20):
                    prof.start()
                    prof.stop()
            except Exception as exc:  # pragma: no cover - the assertion
                errors.append(exc)

        threads = [threading.Thread(target=churn) for _ in range(4)]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        prof.stop()
        assert not errors
        assert not prof.enabled

    def test_module_singleton_exists(self):
        assert isinstance(PROFILER, StackProfiler)


class TestReporting:
    def test_top_ranks_leaf_frames(self):
        prof = StackProfiler()
        prof.register_thread(name="t")
        for _ in range(3):
            prof.sample_once()
        top = prof.top(5)
        assert top
        assert top[0]["samples"] >= 1
        assert 0 < top[0]["fraction"] <= 1.0

    def test_debug_payload_shape(self):
        prof = StackProfiler()
        prof.register_thread(name="t")
        prof.sample_once()
        payload = prof.debug_payload()
        for key in (
            "enabled",
            "interval_seconds",
            "threads",
            "stacks",
            "dropped_stacks",
            "overhead_fraction",
            "total_samples",
            "attributed_fraction",
            "phases",
            "top",
        ):
            assert key in payload
        assert payload["threads"] == ["t"]
