"""bench_trend keyed flattening: inserting a bench row must not shift
every later row onto the wrong baseline (the old positional flatten
compared row N against old row N, so one added A/B line turned the whole
tail of the artifact into phantom regressions)."""
import importlib.util
import os

_spec = importlib.util.spec_from_file_location(
    "bench_trend",
    os.path.join(
        os.path.dirname(__file__), "..", "..", "tools", "bench_trend.py"
    ),
)
bench_trend = importlib.util.module_from_spec(_spec)
_spec.loader.exec_module(bench_trend)


def row(parallelism, nodes, p50, p95):
    return {
        "bench": "bench_planner_sharded",
        "parallelism": parallelism,
        "nodes": nodes,
        "pending_pods": 800,
        "p50_replan_ms": p50,
        "p95_replan_ms": p95,
    }


class TestKeyedFlatten:
    def test_rows_key_by_identity_not_position(self):
        flat = bench_trend.flatten([row("serial", 16384, 100.0, 400.0)])
        (path,) = [p for p in flat if p.endswith("p50_replan_ms")]
        assert "parallelism=serial" in path
        assert "nodes=16384" in path
        assert not path.startswith("0.")

    def test_inserted_row_does_not_shift_baselines(self):
        old = [row("serial", 16384, 100.0, 400.0), row("thread", 16384, 101.0, 600.0)]
        # A process row lands BETWEEN them: positional flatten would diff
        # serial-vs-serial then process-vs-thread.
        new = [
            row("serial", 16384, 100.0, 400.0),
            row("process", 16384, 90.0, 300.0),
            row("thread", 16384, 101.0, 600.0),
        ]
        rows = bench_trend.diff_reports(old, new, tolerance=0.10)
        verdicts = {r[0] for r in rows}
        assert verdicts == {"added"}, rows

    def test_p95_drift_on_sharded_row_is_a_regression(self):
        old = [row("serial", 16384, 100.0, 400.0), row("thread", 16384, 101.0, 410.0)]
        new = [row("serial", 16384, 100.0, 400.0), row("thread", 16384, 101.0, 620.0)]
        rows = bench_trend.diff_reports(old, new, tolerance=0.10)
        (regressed,) = [r for r in rows if r[0] == "regressed"]
        assert "parallelism=thread" in regressed[1]
        assert regressed[1].endswith("p95_replan_ms")

    def test_repeated_identical_configs_stay_distinct(self):
        doc = [row("serial", 64, 1.0, 2.0), row("serial", 64, 3.0, 4.0)]
        flat = bench_trend.flatten(doc)
        p50s = sorted(v for p, v in flat.items() if p.endswith("p50_replan_ms"))
        assert p50s == [1.0, 3.0]

    def test_measurement_bool_flip_still_classifies_regressed(self):
        old = [{"bench": "bench_planner_sharded_equivalence", "nodes": 256,
                "byte_identical": True}]
        new = [{"bench": "bench_planner_sharded_equivalence", "nodes": 256,
                "byte_identical": False}]
        rows = bench_trend.diff_reports(old, new, tolerance=0.10)
        assert [r[0] for r in rows] == ["regressed"]

    def test_non_bench_lists_keep_positional_paths(self):
        flat = bench_trend.flatten({"xs": [10, 20]})
        assert flat == {"xs.0": 10, "xs.1": 20}


def obs_row(**measurements):
    base = {"bench": "bench_observability", "nodes": 100_000, "pods": 1_000_000}
    base.update(measurements)
    return base


class TestObservabilityDirections:
    """BENCH_observability.json leaves carry direction semantics: series
    ``dropped`` counts regress upward, trace retention ``hit_rate``
    regresses downward, and raw series counts stay direction-neutral."""

    def test_dropped_growth_is_a_regression(self):
        assert bench_trend.direction("rows.bench=bench_observability.dropped") == 1
        rows = bench_trend.diff_reports(
            [obs_row(dropped=100)], [obs_row(dropped=250)], tolerance=0.10
        )
        (r,) = [row for row in rows if row[0] == "regressed"]
        assert r[1].endswith("dropped")

    def test_dropped_shrink_is_an_improvement(self):
        rows = bench_trend.diff_reports(
            [obs_row(dropped=250)], [obs_row(dropped=100)], tolerance=0.10
        )
        assert [row[0] for row in rows] == ["improved"]

    def test_hit_rate_drop_is_a_regression(self):
        assert bench_trend.direction("retention.hit_rate") == -1
        rows = bench_trend.diff_reports(
            [obs_row(hit_rate=1.0)], [obs_row(hit_rate=0.5)], tolerance=0.10
        )
        assert [row[0] for row in rows] == ["regressed"]

    def test_hit_rate_rise_is_an_improvement(self):
        rows = bench_trend.diff_reports(
            [obs_row(hit_rate=0.5)], [obs_row(hit_rate=1.0)], tolerance=0.10
        )
        assert [row[0] for row in rows] == ["improved"]

    def test_series_counts_stay_neutral(self):
        assert bench_trend.direction("governed.active_series") == 0
        rows = bench_trend.diff_reports(
            [obs_row(active_series=1000)],
            [obs_row(active_series=1500)],
            tolerance=0.10,
        )
        assert [row[0] for row in rows] == ["changed"]

    def test_within_budget_flip_regresses(self):
        rows = bench_trend.diff_reports(
            [obs_row(exposition_within_budget=True)],
            [obs_row(exposition_within_budget=False)],
            tolerance=0.10,
        )
        assert [row[0] for row in rows] == ["regressed"]
