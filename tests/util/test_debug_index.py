"""The /debug index is the registry: every debug handler the server can
serve appears in the auto-built index, and every indexed endpoint sits
behind the same bearer gate as /metrics. Lint-style: a new `_serve_*`
handler that skips `_debug_endpoints()` fails here, not in review."""
import http.client
import json

from nos_tpu.util.health import HealthServer
from nos_tpu.util.profiling import StackProfiler


def _get(port, path, token=None):
    conn = http.client.HTTPConnection("127.0.0.1", port, timeout=2)
    headers = {"Authorization": f"Bearer {token}"} if token else {}
    conn.request("GET", path, headers=headers)
    resp = conn.getresponse()
    return resp.status, resp.read().decode()


def _fully_wired(**overrides) -> HealthServer:
    """A server with EVERY optional debug callback wired, so the registry
    is at its maximum surface."""
    kwargs = dict(
        port=0,
        metrics_token="s3cret",
        explain_fn=lambda pod: {"pod": pod},
        record_fn=lambda: [],
        capacity_fn=lambda: {"cluster": {}},
        profiler=StackProfiler(),
        loops_fn=lambda: {"loops": {}},
        slo_fn=lambda: {"slos": {}},
        autoscaler_fn=lambda: {"servings": {}},
        forecast_fn=lambda refresh: {"refreshed": refresh},
        timeline_fn=lambda window: {"window_seconds": window},
    )
    kwargs.update(overrides)
    return HealthServer(**kwargs)


class TestDebugIndexCompleteness:
    def test_every_serve_handler_is_registered(self):
        """Lint: each `_serve_*` method on HealthServer must be the
        handler of some registry entry when all callbacks are wired —
        an endpoint method outside the registry would ship ungated and
        unlisted."""
        server = _fully_wired()
        registered = {
            entry["handle"].__func__
            for entry in server._debug_endpoints().values()
        }
        unregistered = [
            name
            for name in dir(HealthServer)
            if name.startswith("_serve_")
            and getattr(HealthServer, name) not in registered
        ]
        assert unregistered == [], (
            f"debug handlers missing from _debug_endpoints(): {unregistered}"
        )

    def test_index_lists_exactly_the_registry(self):
        server = _fully_wired()
        port = server.start()
        try:
            status, body = _get(port, "/debug/", "s3cret")
            assert status == 200
            index = json.loads(body)["endpoints"]
            assert set(index) == set(server._debug_endpoints())
            assert all(desc for desc in index.values())  # one-liners present
        finally:
            server.stop()

    def test_every_indexed_endpoint_is_bearer_gated(self):
        server = _fully_wired()
        port = server.start()
        try:
            for path in server._debug_endpoints():
                assert _get(port, path)[0] == 401, f"{path} served ungated"
                assert _get(port, path, "wrong")[0] == 401
                status, _ = _get(port, path, "s3cret")
                assert status != 401, f"{path} rejected the valid token"
            # The index itself is gated too: it reveals the wired surface.
            assert _get(port, "/debug/")[0] == 401
        finally:
            server.stop()

    def test_unwired_endpoints_leave_the_index(self):
        server = HealthServer(port=0)
        port = server.start()
        try:
            status, body = _get(port, "/debug/")
            assert status == 200
            index = json.loads(body)["endpoints"]
            # Unconditional surfaces only; nothing indexed 404s.
            assert set(index) == {"/debug/traces", "/debug/vars"}
            assert _get(port, "/debug/forecast")[0] == 404
        finally:
            server.stop()


class TestTimelineEndpoint:
    def test_window_query_passes_through(self):
        seen = []

        def timeline_fn(window):
            seen.append(window)
            return {"window_seconds": window}

        server = _fully_wired(metrics_token="", timeline_fn=timeline_fn)
        port = server.start()
        try:
            status, body = _get(port, "/debug/timeline")
            assert status == 200
            assert json.loads(body) == {"window_seconds": None}
            status, body = _get(port, "/debug/timeline?window=30")
            assert status == 200
            assert json.loads(body) == {"window_seconds": 30.0}
            assert _get(port, "/debug/timeline?window=soon")[0] == 400
            assert seen == [None, 30.0]
        finally:
            server.stop()


class TestForecastEndpoint:
    def test_refresh_query_passes_through(self):
        seen = []

        def forecast_fn(refresh):
            seen.append(refresh)
            return {"refreshed": refresh}

        server = _fully_wired(metrics_token="", forecast_fn=forecast_fn)
        port = server.start()
        try:
            status, body = _get(port, "/debug/forecast")
            assert status == 200 and json.loads(body) == {"refreshed": False}
            status, body = _get(port, "/debug/forecast?refresh=1")
            assert status == 200 and json.loads(body) == {"refreshed": True}
            assert seen == [False, True]
        finally:
            server.stop()
