"""Governor behavior at fleet cardinality (~300k series, ISSUE satellite
S3): the governor-off floor actually materializes the full series set,
governor-on exposition is byte-deterministic across two independent runs
of the same event stream, and the `_other` fold preserves counter sums
exactly at scale. Slow: excluded from tier-1 (-m 'not slow')."""
import pytest

from nos_tpu.util.metrics import MetricsRegistry, OTHER_LABEL

N_SERIES = 300_000
BUDGET = 1_000

pytestmark = pytest.mark.slow


def feed(registry, n=N_SERIES):
    fam = registry.counter("nos_scale_fam")
    for i in range(n):
        # deterministic, non-uniform increments so sum errors can't hide
        fam.labels(node=f"node-{i:06d}").inc(1.0 + (i % 7))
    return fam


class TestGovernorAtScale:
    def test_governor_off_floor_materializes_every_series(self):
        reg = MetricsRegistry()
        feed(reg)
        report = reg.series_report()["nos_scale_fam"]
        assert report["exact"] == N_SERIES
        assert report["overflow"] == 0
        assert report["dropped"] == 0

    def test_governor_on_exposition_is_byte_deterministic(self):
        renders = []
        for _ in range(2):
            reg = MetricsRegistry()
            reg.apply_series_budgets({"nos_scale_fam": BUDGET})
            feed(reg)
            renders.append(reg.render())
        assert renders[0] == renders[1]
        report_reg = MetricsRegistry()
        report_reg.apply_series_budgets({"nos_scale_fam": BUDGET})
        feed(report_reg)
        report = report_reg.series_report()["nos_scale_fam"]
        assert report["exact"] == BUDGET
        assert report["overflow"] == 1
        assert report["dropped"] == N_SERIES - BUDGET

    def test_other_preserves_counter_sums_exactly(self):
        expected = float(sum(1.0 + (i % 7) for i in range(N_SERIES)))
        governed = MetricsRegistry()
        governed.apply_series_budgets({"nos_scale_fam": BUDGET})
        fam = feed(governed)
        # total (parent + exact children + _other) matches the ungoverned
        # arithmetic exactly — floats are sums of small integers, so this
        # is == not approx
        assert fam.total == expected
        other = fam.labels(node=OTHER_LABEL)
        assert other.value == expected - sum(
            fam.labels(node=f"node-{i:06d}").value for i in range(BUDGET)
        )
