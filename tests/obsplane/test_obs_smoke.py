"""obs-smoke gate (ISSUE satellite S5): the small-world observability
plane end to end — governor + tail retention + pagination driven through
the bench's own emission path — must be byte-identical across two
in-process runs. This is the determinism pin the chaos replay and the
committed BENCH_observability.json lean on."""
import importlib.util
import os

_spec = importlib.util.spec_from_file_location(
    "bench_observability",
    os.path.join(
        os.path.dirname(__file__), "..", "..", "bench_observability.py"
    ),
)
bench_obs = importlib.util.module_from_spec(_spec)
_spec.loader.exec_module(bench_obs)

N_NODES, N_PODS = 100, 1000


def small_world():
    store = bench_obs.seed_store(N_NODES, N_PODS)
    return bench_obs.fleet_from_store(store)


class TestObsSmoke:
    def test_two_in_process_runs_render_byte_identical(self):
        fleet, pending = small_world()
        renders = [
            bench_obs.governed_registry(fleet, pending).render()
            for _ in range(2)
        ]
        assert renders[0] == renders[1]

    def test_small_world_stays_under_budget_with_zero_drops(self):
        fleet, pending = small_world()
        registry = bench_obs.governed_registry(fleet, pending)
        fam = registry.series_report()[bench_obs.NODE_FAMILY]
        assert fam["exact"] == 3 * N_NODES
        assert fam["overflow"] == 0 and fam["dropped"] == 0

    def test_pool_rollups_conserve_fleet_chips(self):
        fleet, pending = small_world()
        registry = bench_obs.governed_registry(fleet, pending)
        pool_g = registry.gauge(bench_obs.POOL_FAMILY)
        snapshot = registry.snapshot()
        total_cap = sum(cap for _, cap, _ in fleet)
        rolled = sum(
            v
            for k, v in snapshot.items()
            if k.startswith(bench_obs.POOL_FAMILY)
            and ('state="used"' in k or 'state="free"' in k)
        )
        assert pool_g is not None
        assert rolled == float(total_cap)

    def test_retention_mixture_is_deterministic_and_tail_kept(self):
        stats = [bench_obs.drive_retention(500) for _ in range(2)]
        assert stats[0] == stats[1]
        # every interesting trace in the mixture stays retrievable
        assert stats[0]["hit_rate"] == 1.0
        assert stats[0]["seen"]["error"] == 5
        assert stats[0]["sampled_out"] > 0

    def test_governed_snapshot_pages_deterministically(self):
        from nos_tpu.obsplane.streaming import paginate

        fleet, pending = small_world()
        registry = bench_obs.governed_registry(fleet, pending)
        keys = sorted(registry.snapshot())
        seen, cursor = [], ""
        while True:
            page, cursor = paginate(keys, limit=100, cursor=cursor)
            seen.extend(page)
            if not cursor:
                break
        assert seen == keys
