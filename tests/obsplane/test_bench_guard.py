"""BENCH_observability.json guard (slow): the committed artifact's
deterministic fields must be reproducible from the bench's own code path
(the small row is recomputed here and compared field for field, sha
included — a tampered governor policy or emission order changes the
bytes and fails), every committed ``*_within_budget`` boolean must be
true, and the governed exposition + timeline sample are re-measured at
100k-node cardinality against the 2%-of-cycle budget so the booleans
cannot go stale silently."""
import importlib.util
import json
import os
import time

import pytest

pytestmark = pytest.mark.slow

_ROOT = os.path.join(os.path.dirname(__file__), "..", "..")
_spec = importlib.util.spec_from_file_location(
    "bench_observability", os.path.join(_ROOT, "bench_observability.py")
)
bench_obs = importlib.util.module_from_spec(_spec)
_spec.loader.exec_module(bench_obs)

SMALL = (1000, 10_000)


def committed_report():
    with open(os.path.join(_ROOT, "BENCH_observability.json")) as fh:
        return json.load(fh)


def committed_row(nodes, pods):
    for row in committed_report()["rows"]:
        if (row["nodes"], row["pods"]) == (nodes, pods):
            return row
    raise AssertionError(f"no committed row for {nodes}x{pods}")


class TestCommittedArtifact:
    def test_small_row_is_reproducible_bit_for_bit(self):
        row, _timing = bench_obs.run_config(*SMALL, repeats=2)
        committed = committed_row(*SMALL)
        # wall-clock never reaches the committed file, so the recomputed
        # deterministic sections must match exactly — sha256 included
        for section in ("series", "exposition", "snapshot", "retention"):
            assert row[section] == committed[section], section

    def test_fleet_row_exists_at_the_roadmap_scale(self):
        row = committed_row(100_000, 1_000_000)
        assert row["series"]["dropped"] > 0  # the governor actually bit
        assert row["series"]["governed_exact"] == bench_obs.NODE_BUDGET

    def test_every_committed_budget_boolean_is_true(self):
        for row in committed_report()["rows"]:
            assert row["exposition"]["byte_identical"] is True
            for key, value in row["overhead"].items():
                if key.endswith("_within_budget"):
                    assert value is True, (row["nodes"], key)


class TestBudgetEnforcement:
    def test_governed_paths_hold_the_two_percent_budget_at_fleet_scale(self):
        # 100k nodes, podless: the ~300k-series cardinality is what the
        # governed paths must absorb; pods only shift gauge values.
        store = bench_obs.seed_store(100_000, 0)
        fleet, pending = bench_obs.fleet_from_store(store)
        del store
        registry = bench_obs.governed_registry(fleet, pending)
        limit_s = bench_obs.CYCLE_SECONDS * bench_obs.BUDGET_FRACTION

        t0 = time.perf_counter()
        registry.render()
        render_s = time.perf_counter() - t0
        assert render_s <= limit_s, f"governed render {render_s:.3f}s"

        from nos_tpu.timeline.sizes import SizeRegistry
        from nos_tpu.timeline.store import TimelineStore
        from nos_tpu.timeline.watchdog import WedgeWatchdog

        now = [1000.0]

        def clock():
            now[0] += bench_obs.CYCLE_SECONDS
            return now[0]

        timeline = TimelineStore(
            clock=clock,
            vitals=False,
            registry=registry,
            sizes=SizeRegistry(),
            watchdog=WedgeWatchdog(),
        )
        try:
            timeline.sample_once()  # prime: full snapshot, unbudgeted
            gauge = registry.gauge(bench_obs.NODE_FAMILY)
            bench_obs._touch(gauge, fleet, 1)
            t0 = time.perf_counter()
            timeline.sample_once()
            sample_s = time.perf_counter() - t0
        finally:
            timeline.close()
        assert sample_s <= limit_s, f"timeline sample {sample_s:.3f}s"
