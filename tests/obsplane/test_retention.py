"""Tail-kept trace retention: classification (error > unschedulable >
slow > boring), the pinned reservoir boring bursts cannot evict,
deterministic boring head-sampling with weighted counters, paged
summaries, and policy swap semantics."""
from nos_tpu.util.tracing import (
    RetentionPolicy,
    Span,
    Trace,
    TraceStore,
    classify_trace,
)


def make_trace(trace_id, root_name="pod.journey", status="ok",
               duration=0.1, attributes=None):
    root = Span(
        name=root_name,
        trace_id=trace_id,
        span_id=f"{trace_id}-root",
        parent_id=None,
        duration_s=duration,
        status=status,
        attributes=dict(attributes or {}),
    )
    return Trace(trace_id=trace_id, spans=[root])


def error_trace(trace_id):
    t = make_trace(trace_id)
    t.spans.append(
        Span(
            name="actuator.apply_node",
            trace_id=trace_id,
            span_id=f"{trace_id}-err",
            parent_id=f"{trace_id}-root",
            duration_s=0.01,
            status="error",
        )
    )
    return t


class TestClassification:
    POLICY = RetentionPolicy(slow_thresholds={"pod.journey": 1.0})

    def test_error_span_anywhere_wins(self):
        trace = error_trace("t1")
        trace.spans[0].attributes["diagnosis"] = "also unschedulable"
        assert classify_trace(trace, self.POLICY) == "error"

    def test_diagnosis_on_root_is_unschedulable(self):
        trace = make_trace("t2", attributes={"diagnosis": "0/3 nodes"})
        assert classify_trace(trace, self.POLICY) == "unschedulable"

    def test_slow_by_per_journey_kind_threshold(self):
        assert classify_trace(
            make_trace("t3", duration=1.5), self.POLICY
        ) == "slow"
        # same duration, a journey kind with no threshold: boring
        assert classify_trace(
            make_trace("t4", root_name="scheduler.cycle", duration=1.5),
            self.POLICY,
        ) == "boring"

    def test_fast_clean_trace_is_boring(self):
        assert classify_trace(make_trace("t5"), self.POLICY) == "boring"


class TestTailKeptReservoir:
    def test_boring_burst_cannot_evict_an_interesting_trace(self):
        store = TraceStore(capacity=4, retention=RetentionPolicy(tail_capacity=2))
        store.add(error_trace("bad"))
        for i in range(50):
            store.add(make_trace(f"boring-{i}"))
        assert store.get("bad") is not None
        # the main ring stayed bounded
        assert len(store) <= 4 + 2

    def test_reservoir_is_bounded_oldest_interesting_evicted(self):
        store = TraceStore(capacity=4, retention=RetentionPolicy(tail_capacity=2))
        for i in range(3):
            store.add(error_trace(f"bad-{i}"))
        assert store.get("bad-0") is None
        assert store.get("bad-1") is not None
        assert store.get("bad-2") is not None

    def test_zero_tail_capacity_disables_pinning(self):
        store = TraceStore(capacity=2, retention=RetentionPolicy(tail_capacity=0))
        store.add(error_trace("bad"))
        store.add(make_trace("b1"))
        store.add(make_trace("b2"))
        assert store.get("bad") is None  # competed in the main ring, lost

    def test_list_merges_newest_first_across_rings(self):
        store = TraceStore(capacity=8, retention=RetentionPolicy(tail_capacity=2))
        store.add(make_trace("b1"))
        store.add(error_trace("bad"))
        store.add(make_trace("b2"))
        assert [t.trace_id for t in store.list()] == ["b2", "bad", "b1"]

    def test_pinning_increments_the_retained_counter(self):
        from nos_tpu.util import metrics

        store = TraceStore(capacity=4, retention=RetentionPolicy(tail_capacity=2))
        before = metrics.TRACE_RETAINED.labels(verdict="error").value
        store.add(error_trace("bad"))
        after = metrics.TRACE_RETAINED.labels(verdict="error").value
        assert after == before + 1


class TestBoringSampling:
    def test_head_sampling_keeps_every_nth_arrival(self):
        store = TraceStore(
            capacity=64, retention=RetentionPolicy(boring_sample_n=3)
        )
        for i in range(9):
            store.add(make_trace(f"b{i}"))
        kept = {t.trace_id for t in store.list()}
        assert kept == {"b0", "b3", "b6"}

    def test_weighted_counters_keep_totals_honest(self):
        store = TraceStore(
            capacity=64, retention=RetentionPolicy(boring_sample_n=3)
        )
        for i in range(9):
            store.add(make_trace(f"b{i}"))
        stats = store.retention_stats()
        assert stats["seen"] == {"boring": 9}
        assert stats["kept"] == {"boring": 3}
        assert stats["sampled_out"] == 6
        assert stats["boring_weight"] == 3

    def test_interesting_traces_are_never_sampled_out(self):
        store = TraceStore(
            capacity=64,
            retention=RetentionPolicy(tail_capacity=8, boring_sample_n=100),
        )
        for i in range(5):
            store.add(error_trace(f"bad-{i}"))
        assert len(store.list()) == 5

    def test_hit_rate_counts_retrievable_interesting_traces(self):
        store = TraceStore(capacity=8, retention=RetentionPolicy(tail_capacity=2))
        for i in range(4):
            store.add(error_trace(f"bad-{i}"))
        stats = store.retention_stats()
        assert stats["pinned"] == 2
        assert stats["hit_rate"] == 0.5


class TestPagingAndPolicySwap:
    def test_summaries_page_walks_newest_to_oldest(self):
        store = TraceStore(capacity=16)
        for i in range(5):
            store.add(make_trace(f"t{i}"))
        page1, cursor = store.summaries_page(limit=2)
        assert [s["trace_id"] for s in page1] == ["t4", "t3"]
        assert cursor
        page2, cursor = store.summaries_page(limit=2, cursor=cursor)
        assert [s["trace_id"] for s in page2] == ["t2", "t1"]
        page3, cursor = store.summaries_page(limit=2, cursor=cursor)
        assert [s["trace_id"] for s in page3] == ["t0"]
        assert cursor == ""

    def test_summaries_carry_seq_and_verdict(self):
        store = TraceStore(capacity=4)
        store.add(error_trace("bad"))
        (summary,), _ = store.summaries_page(limit=1)
        assert summary["verdict"] == "error"
        assert summary["seq"] == 1

    def test_set_retention_shrinks_an_over_capacity_reservoir(self):
        store = TraceStore(capacity=8, retention=RetentionPolicy(tail_capacity=4))
        for i in range(4):
            store.add(error_trace(f"bad-{i}"))
        prev = store.set_retention(RetentionPolicy(tail_capacity=1))
        assert prev.tail_capacity == 4
        assert store.get("bad-3") is not None
        assert store.get("bad-0") is None
        assert store.retention_stats()["pinned"] == 1
