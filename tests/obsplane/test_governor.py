"""Cardinality governor: deterministic admission up to a family's series
budget, exact-sum-preserving overflow into the `_other` child, drop
accounting, budget slots freed on remove(), and the policy-pin property
— same event stream + same budget = byte-identical exposition, and a
tampered budget provably changes the bytes."""
from nos_tpu.api.config import ObservabilityConfig
from nos_tpu.obsplane.apply import apply_observability
from nos_tpu.obsplane.governor import budgets_from, governor_report
from nos_tpu.util.metrics import (
    METRIC_SERIES_DROPPED_NAME,
    MetricsRegistry,
    OTHER_LABEL,
)


def budgeted_registry(budgets, default=None):
    reg = MetricsRegistry()
    reg.apply_series_budgets(budgets, default=default)
    return reg


class TestAdmission:
    def test_under_budget_all_exact(self):
        reg = budgeted_registry({"fam": 3})
        fam = reg.counter("fam")
        for who in ("a", "b", "c"):
            fam.labels(who=who).inc()
        assert 'who="a"' in reg.render()
        assert OTHER_LABEL not in reg.render()
        assert reg.series_report()["fam"]["dropped"] == 0

    def test_over_budget_folds_into_other(self):
        reg = budgeted_registry({"fam": 2})
        fam = reg.counter("fam")
        for i in range(5):
            fam.labels(who=f"w{i}").inc(2.0)
        rendered = reg.render()
        assert 'who="w0"' in rendered and 'who="w1"' in rendered
        assert 'who="w2"' not in rendered
        assert f'who="{OTHER_LABEL}"' in rendered

    def test_overflow_preserves_counter_sums_exactly(self):
        reg = budgeted_registry({"fam": 2})
        fam = reg.counter("fam")
        for i in range(10):
            fam.labels(who=f"w{i}").inc(1.5)
        assert fam.total == 10 * 1.5

    def test_dropped_counter_counts_distinct_refused_label_sets(self):
        reg = budgeted_registry({"fam": 2})
        fam = reg.counter("fam")
        for _ in range(3):  # repeats of one refused set count once
            fam.labels(who="w9").inc()
        fam.labels(who="a").inc()
        fam.labels(who="b").inc()
        fam.labels(who="c").inc()
        # w9 + c refused (a, b took the two slots... w9 was first, so
        # w9 + a admitted; b, c refused)
        report = reg.series_report()["fam"]
        assert report["exact"] == 2
        assert report["overflow"] == 1
        assert report["dropped"] == 2
        snap = reg.snapshot()
        assert snap[f'{METRIC_SERIES_DROPPED_NAME}{{family="fam"}}'] == 2.0

    def test_remove_frees_a_budget_slot(self):
        reg = budgeted_registry({"fam": 1})
        fam = reg.gauge("fam")
        fam.labels(who="a").set(1.0)
        fam.labels(who="b").set(9.0)  # refused -> _other
        assert reg.series_report()["fam"]["dropped"] == 1
        assert fam.remove(who="a")
        fam.labels(who="c").set(3.0)  # takes the freed slot
        assert 'who="c"' in reg.render()
        assert reg.series_report()["fam"]["exact"] == 1

    def test_drop_counter_family_is_never_budgeted(self):
        reg = budgeted_registry({METRIC_SERIES_DROPPED_NAME: 1}, default=1)
        fam = reg.counter("fam")
        for i in range(4):
            fam.labels(who=f"w{i}").inc()
        dropped = reg.series_report()[METRIC_SERIES_DROPPED_NAME]
        assert dropped["budget"] is None
        assert dropped["dropped"] == 0

    def test_histogram_overflow_preserves_count_and_sum(self):
        reg = budgeted_registry({"lat": 1})
        lat = reg.histogram("lat")
        for i in range(6):
            lat.labels(who=f"w{i}").observe(0.5)
        exact = lat.labels(who="w0")
        other = lat.labels(who=OTHER_LABEL)
        assert exact.count + other.count == 6
        assert exact.sum + other.sum == 3.0


class TestDeterminismPin:
    EVENTS = [(f"w{i % 7}", 1.0 + (i % 3)) for i in range(50)]

    @classmethod
    def run_stream(cls, budget):
        reg = budgeted_registry({"fam": budget})
        fam = reg.counter("fam")
        for who, amount in cls.EVENTS:
            fam.labels(who=who).inc(amount)
        return reg.render()

    def test_same_budget_same_bytes(self):
        assert self.run_stream(3) == self.run_stream(3)

    def test_tampered_budget_changes_the_bytes(self):
        """The determinism pin has teeth: a different policy cannot
        reproduce the committed exposition."""
        honest = self.run_stream(3)
        tampered = self.run_stream(4)
        assert honest != tampered
        # both still fold (7 distinct sets > either budget): the bytes
        # differ in which sets stayed exact, not in whether folding ran
        assert f'who="{OTHER_LABEL}"' in honest
        assert f'who="{OTHER_LABEL}"' in tampered

    def test_totals_identical_across_budgets(self):
        total = sum(amount for _, amount in self.EVENTS)
        for budget in (1, 3, 7):
            reg = budgeted_registry({"fam": budget})
            fam = reg.counter("fam")
            for who, amount in self.EVENTS:
                fam.labels(who=who).inc(amount)
            assert fam.total == total


class TestConfigPlumbing:
    def test_budgets_from_pulls_map_and_default(self):
        obs = ObservabilityConfig(
            series_budget={"fam": 10}, series_budget_default=512
        )
        budgets, default = budgets_from(obs)
        assert budgets == {"fam": 10}
        assert default == 512

    def test_zero_default_means_unbudgeted(self):
        obs = ObservabilityConfig(series_budget_default=0)
        assert budgets_from(obs) == ({}, None)

    def test_apply_observability_is_revertible(self):
        reg = MetricsRegistry()
        fam = reg.counter("fam")
        fam.labels(who="a").inc()

        class FakeTracer:
            class store:
                @staticmethod
                def set_retention(policy):
                    return policy

        revert = apply_observability(
            ObservabilityConfig(series_budget={"fam": 1}),
            registry=reg,
            tracer=FakeTracer(),
        )
        fam.labels(who="b").inc()  # refused under budget 1
        assert reg.series_report()["fam"]["dropped"] == 1
        revert()
        fam.labels(who="c").inc()  # admitted again, budget lifted
        assert 'who="c"' in reg.render()

    def test_governor_report_totals(self):
        reg = budgeted_registry({"fam": 1})
        fam = reg.counter("fam")
        fam.labels(who="a").inc()
        fam.labels(who="b").inc()
        report = governor_report(reg)
        assert report["over_budget"] == ["fam"]
        assert report["dropped_series"] == 1
        # a (exact) + _other + the drop counter's own child
        assert report["active_series"] == 3
