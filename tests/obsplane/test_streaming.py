"""Streaming debug plane: cursor pagination primitives, the ledger's
paged document + JSONL generator, the timeline's paged rollups, and the
HTTP layer end to end — ?limit=/?cursor= paging, ?format=jsonl chunked
streaming, and the 400 on a malformed limit."""
import http.client
import json

import pytest

from nos_tpu.api.v1alpha1 import constants
from nos_tpu.capacity import CapacityLedger
from nos_tpu.kube.store import KubeStore
from nos_tpu.obsplane.streaming import (
    jsonl_lines,
    page_envelope,
    page_params,
    paginate,
)
from nos_tpu.util.health import HealthServer

from tests.factory import build_pod, build_tpu_node


class TestPaginate:
    KEYS = ["a", "b", "c", "d", "e"]

    def test_no_limit_returns_everything(self):
        assert paginate(self.KEYS) == (self.KEYS, "")

    def test_limit_pages_with_cursor(self):
        page, cursor = paginate(self.KEYS, limit=2)
        assert page == ["a", "b"] and cursor == "b"
        page, cursor = paginate(self.KEYS, limit=2, cursor="b")
        assert page == ["c", "d"] and cursor == "d"
        page, cursor = paginate(self.KEYS, limit=2, cursor="d")
        assert page == ["e"] and cursor == ""

    def test_cursor_past_the_end_is_empty(self):
        assert paginate(self.KEYS, limit=2, cursor="z") == ([], "")

    def test_exact_final_page_has_no_cursor(self):
        page, cursor = paginate(["a", "b"], limit=2)
        assert page == ["a", "b"] and cursor == ""

    def test_vanished_cursor_key_resumes_after_its_sort_position(self):
        # "bb" was deleted between pages: paging resumes at "c", no skip
        page, _ = paginate(self.KEYS, limit=2, cursor="bb")
        assert page == ["c", "d"]


class TestPageParams:
    def test_defaults(self):
        assert page_params({}) == {
            "pool": "",
            "limit": 0,
            "cursor": "",
            "jsonl": False,
        }

    def test_explicit_values(self):
        out = page_params(
            {"pool": "p1", "limit": "5", "cursor": "n3", "format": "jsonl"},
            default_limit=100,
        )
        assert out == {"pool": "p1", "limit": 5, "cursor": "n3", "jsonl": True}

    def test_default_limit_applies_without_explicit_limit(self):
        assert page_params({}, default_limit=100)["limit"] == 100

    def test_malformed_limit_raises(self):
        with pytest.raises(ValueError):
            page_params({"limit": "abc"})
        with pytest.raises(ValueError):
            page_params({"limit": "-1"})

    def test_jsonl_lines_are_sorted_and_newline_terminated(self):
        lines = list(jsonl_lines([{"b": 1, "a": 2}]))
        assert lines == [b'{"a": 2, "b": 1}\n']

    def test_page_envelope(self):
        out = page_envelope({"x": 1}, "n5", 10, total=42)
        assert out["page"] == {"limit": 10, "next_cursor": "n5", "total": 42}


def make_ledger(n_nodes=6):
    store = KubeStore()
    ledger = CapacityLedger(store, metrics=False)
    for i in range(n_nodes):
        store.create(build_tpu_node(name=f"n{i}", chips=8))
    store.create(build_pod("w", {constants.RESOURCE_TPU: 4}, node="n0"))
    ledger.observe(1000.0)
    return ledger


class TestLedgerPaging:
    def test_paged_nodes_cover_everything_exactly_once(self):
        ledger = make_ledger()
        seen, cursor = [], ""
        while True:
            doc = ledger.debug_payload(limit=2, cursor=cursor)
            seen.extend(doc["nodes"])
            cursor = doc["page"]["next_cursor"]
            if not cursor:
                break
        assert seen == [f"n{i}" for i in range(6)]

    def test_cluster_rollup_ignores_paging(self):
        ledger = make_ledger()
        doc = ledger.debug_payload(limit=1)
        assert doc["cluster"]["total_chips"] == 48
        assert doc["page"]["total_nodes"] == 6

    def test_stream_yields_header_then_nodes_then_quotas(self):
        ledger = make_ledger(3)
        records = list(ledger.debug_stream())
        assert records[0]["record"] == "cluster"
        node_records = [r for r in records if r["record"] == "node"]
        assert [r["name"] for r in node_records] == ["n0", "n1", "n2"]
        assert node_records[0]["used_chips"] == 4

    def test_stream_pool_filter(self):
        ledger = make_ledger(3)
        records = list(ledger.debug_stream(pool="no-such-pool"))
        assert [r for r in records if r["record"] == "node"] == []


class TestTimelinePaging:
    def make_store(self, n_series=10):
        from nos_tpu.timeline.sizes import SizeRegistry
        from nos_tpu.timeline.store import TimelineStore
        from nos_tpu.timeline.watchdog import WedgeWatchdog

        values = {f"s{i:02d}": float(i) for i in range(n_series)}
        store = TimelineStore(
            clock=lambda: 1000.0,
            vitals=False,
            metrics_fn=lambda: dict(values),
            sizes=SizeRegistry(),
            watchdog=WedgeWatchdog(),
        )
        store.sample_once()
        return store

    def test_rollups_page_by_series_name(self):
        store = self.make_store()
        doc = store.debug_payload(limit=4)
        assert list(doc["rollups"]) == ["s00", "s01", "s02", "s03"]
        assert set(doc["sparklines"]) == set(doc["rollups"])
        next_doc = store.debug_payload(
            limit=4, cursor=doc["page"]["next_cursor"]
        )
        assert list(next_doc["rollups"]) == ["s04", "s05", "s06", "s07"]

    def test_unpaged_document_is_complete(self):
        store = self.make_store()
        doc = store.debug_payload()
        assert doc["page"]["next_cursor"] == ""
        assert len(doc["rollups"]) == doc["series_count"]


def _get(port, path, token="tok"):
    conn = http.client.HTTPConnection("127.0.0.1", port, timeout=5)
    headers = {"Authorization": f"Bearer {token}"} if token else {}
    conn.request("GET", path, headers=headers)
    resp = conn.getresponse()
    return resp.status, resp.read().decode(), dict(resp.getheaders())


class TestHttpStreaming:
    @pytest.fixture
    def server(self):
        ledger = make_ledger()
        server = HealthServer(
            port=0,
            metrics_token="tok",
            capacity_fn=ledger.debug_payload,
            capacity_stream_fn=ledger.debug_stream,
            debug_page_limit=2,
        )
        port = server.start()
        yield port
        server.stop()

    def test_default_page_limit_applies(self, server):
        status, body, _ = _get(server, "/debug/capacity")
        assert status == 200
        doc = json.loads(body)
        assert len(doc["nodes"]) == 2
        assert doc["page"]["next_cursor"] == "n1"

    def test_cursor_walks_the_node_table(self, server):
        _, body, _ = _get(server, "/debug/capacity?limit=4&cursor=n1")
        doc = json.loads(body)
        assert list(doc["nodes"]) == ["n2", "n3", "n4", "n5"]

    def test_limit_zero_is_unpaginated(self, server):
        _, body, _ = _get(server, "/debug/capacity?limit=0")
        assert len(json.loads(body)["nodes"]) == 6

    def test_malformed_limit_is_400(self, server):
        assert _get(server, "/debug/capacity?limit=banana")[0] == 400

    def test_jsonl_streams_chunked_one_record_per_line(self, server):
        status, body, headers = _get(server, "/debug/capacity?format=jsonl")
        assert status == 200
        assert headers.get("Transfer-Encoding") == "chunked"
        assert headers.get("Content-Type") == "application/x-ndjson"
        records = [json.loads(line) for line in body.splitlines()]
        assert records[0]["record"] == "cluster"
        assert sum(1 for r in records if r["record"] == "node") == 6

    def test_legacy_no_arg_capacity_fn_still_serves(self):
        server = HealthServer(
            port=0,
            metrics_token="tok",
            capacity_fn=lambda: {"legacy": True},
        )
        port = server.start()
        try:
            status, body, _ = _get(port, "/debug/capacity?limit=2")
            assert status == 200
            assert json.loads(body) == {"legacy": True}
        finally:
            server.stop()
