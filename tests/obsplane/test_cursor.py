"""Incremental registry snapshots: the first collect() primes with the
full snapshot, later collects return only touched series (O(changed),
not O(total)), removals win over concurrent changes, multiple cursors
are independent, and the timeline's cursor mode folds deltas into the
same frames a full-snapshot diff would produce."""
from nos_tpu.util.metrics import MetricsRegistry


class TestSnapshotCursor:
    def test_first_collect_is_the_full_snapshot(self):
        reg = MetricsRegistry()
        reg.counter("a").inc()
        reg.gauge("b").set(2.0)
        changed, removed = reg.cursor().collect()
        assert changed == reg.snapshot()
        assert removed == []

    def test_second_collect_holds_only_the_touched_series(self):
        reg = MetricsRegistry()
        a = reg.counter("a")
        b = reg.gauge("b")
        a.inc()
        b.set(1.0)
        cur = reg.cursor()
        cur.collect()
        a.inc()
        changed, removed = cur.collect()
        assert changed == {"a": 2.0}
        assert removed == []

    def test_untouched_window_collects_nothing(self):
        reg = MetricsRegistry()
        reg.counter("a").inc()
        cur = reg.cursor()
        cur.collect()
        assert cur.collect() == ({}, [])

    def test_labeled_children_report_their_own_keys(self):
        reg = MetricsRegistry()
        fam = reg.counter("fam")
        fam.labels(who="a").inc()
        cur = reg.cursor()
        cur.collect()
        fam.labels(who="b").inc(3.0)
        changed, _ = cur.collect()
        assert changed == {'fam{who="b"}': 3.0}

    def test_histogram_reports_its_snapshot_keys(self):
        reg = MetricsRegistry()
        h = reg.histogram("lat")
        cur = reg.cursor()
        cur.collect()
        h.observe(0.5)
        changed, _ = cur.collect()
        assert changed["lat_count"] == 1
        assert changed["lat_sum"] == 0.5
        assert changed["lat_p50"] == 0.5

    def test_removed_series_wins_over_its_own_change(self):
        reg = MetricsRegistry()
        fam = reg.gauge("fam")
        fam.labels(who="a").set(1.0)
        cur = reg.cursor()
        cur.collect()
        fam.labels(who="a").set(9.0)
        assert fam.remove(who="a")
        changed, removed = cur.collect()
        assert 'fam{who="a"}' not in changed
        assert removed == ['fam{who="a"}']

    def test_families_created_after_the_cursor_are_tracked(self):
        reg = MetricsRegistry()
        cur = reg.cursor()
        cur.collect()
        late = reg.counter("late")
        late.inc()
        changed, _ = cur.collect()
        assert changed == {"late": 1.0}

    def test_two_cursors_drain_independently(self):
        reg = MetricsRegistry()
        a = reg.counter("a")
        c1 = reg.cursor()
        c2 = reg.cursor()
        c1.collect()
        c2.collect()
        a.inc()
        assert c1.collect() == ({"a": 1.0}, [])
        # c2 still sees the same change in its own window
        assert c2.collect() == ({"a": 1.0}, [])
        # both drained: nothing left
        assert c1.collect() == ({}, [])
        assert c2.collect() == ({}, [])

    def test_closed_cursor_stops_accumulating(self):
        reg = MetricsRegistry()
        a = reg.counter("a")
        cur = reg.cursor()
        cur.collect()
        cur.close()
        a.inc()
        # collect after close: nothing was routed to this cursor
        assert cur.collect() == ({}, [])


class TestTimelineCursorMode:
    def make_cursor_store(self, registry):
        from nos_tpu.timeline.sizes import SizeRegistry
        from nos_tpu.timeline.store import TimelineStore
        from nos_tpu.timeline.watchdog import WedgeWatchdog

        clock = Clock()
        store = TimelineStore(
            clock=clock,
            vitals=False,
            registry=registry,
            sizes=SizeRegistry(),
            watchdog=WedgeWatchdog(),
        )
        return store, clock

    def test_cursor_mode_matches_full_snapshot_series(self):
        reg = MetricsRegistry()
        ctr = reg.counter("nos_test_ctr")
        ctr.inc()
        store, clock = self.make_cursor_store(reg)
        try:
            store.sample_once()
            ctr.inc(2.0)
            clock.advance()
            store.sample_once()
            assert store.series("nos_test_ctr") == [
                (1000.0, 1.0),
                (1001.0, 3.0),
            ]
        finally:
            store.close()

    def test_removed_series_writes_the_sentinel_in_cursor_mode(self):
        reg = MetricsRegistry()
        fam = reg.gauge("nos_test_fam")
        fam.labels(who="a").set(1.0)
        store, clock = self.make_cursor_store(reg)
        try:
            store.sample_once()
            assert fam.remove(who="a")
            clock.advance()
            store.sample_once()
            assert store.series('nos_test_fam{who="a"}') == [(1000.0, 1.0)]
            assert 'nos_test_fam{who="a"}' not in store.names()
        finally:
            store.close()

    def test_close_is_idempotent_and_sampling_survives_it(self):
        reg = MetricsRegistry()
        reg.counter("nos_test_ctr").inc()
        store, clock = self.make_cursor_store(reg)
        store.close()
        store.close()
        clock.advance()
        store.sample_once()  # falls back to full-snapshot diffing
        assert "nos_test_ctr" in store.names()


class Clock:
    def __init__(self, now=1000.0):
        self.now = now

    def __call__(self):
        return self.now

    def advance(self, seconds=1.0):
        self.now += seconds
