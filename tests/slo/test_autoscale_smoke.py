"""Autoscale-smoke gate: the full closed loop (diurnal workload ->
burn-rate signals -> ModelServing verdicts -> replica pods placed and
carved by the live SimCluster) at smoke scale, run twice in-process —
byte-identical reports at the pinned seed, and the committed
BENCH_autoscale.json must keep telling the acceptance story: SLOs met
at peak, one model scaled to zero with its chips reclaimed."""
import json
import os

import bench_autoscale


def _run(seed):
    # 80 virtual seconds: long enough for the cold model to idle out,
    # scale to zero, AND accrue grace chip-seconds before the trace ends.
    return bench_autoscale.run_bench(seed=seed, duration_s=80.0, rate_rps=14.0)


def test_closed_loop_is_bit_stable_and_scales_to_zero():
    first = _run(seed=0)
    second = _run(seed=0)
    body1 = json.dumps(first, indent=2, sort_keys=True)
    body2 = json.dumps(second, indent=2, sort_keys=True)
    # Fresh cluster + virtual clocks, same seed -> same bytes, even though
    # each run's scheduler/partitioner raced on its own wall clock.
    assert body1 == body2

    assert set(first) >= {
        "workload", "servings", "models", "timeline", "scale_events",
        "cold_start", "peak", "replicas", "capacity",
    }
    # The cold model's lifecycle completes inside even the smoke trace:
    # cold start at the first arrivals, scale-to-zero after the cutoff.
    assert first["scale_events"].get("cold-start", 0) >= 1
    assert first["scale_events"].get("scale-to-zero", 0) >= 1
    assert first["cold_start"]["count"] >= 1
    assert first["cold_start"]["ttft_penalty_s"]["p95"] > 0
    # Chips freed by scale-to-zero are booked to the grace bucket and
    # never leak into the gang-reservation bucket.
    idle = first["capacity"]["idle_chip_seconds"]
    assert idle["autoscaler-grace"] > 0
    assert idle["reserved-by-gang"] == 0
    assert first["capacity"]["busy_chip_seconds"] > 0


def test_seed_changes_the_bytes():
    base = json.dumps(_run(seed=0), sort_keys=True)
    other = json.dumps(_run(seed=1), sort_keys=True)
    assert base != other


def test_committed_bench_artifact_tells_the_story():
    path = os.path.join(os.path.dirname(bench_autoscale.__file__), "BENCH_autoscale.json")
    with open(path) as f:
        report = json.load(f)
    # Acceptance: all declared SLOs compliant at the diurnal peak...
    assert report["peak"]["slos_compliant"] is True
    # ...and run-level (slow-window) compliance for every declared SLO.
    for model, stats in report["models"].items():
        for slo in stats["slo"]:
            assert slo["compliant"], (model, slo)
    # ...at least one model scaled to zero with chips reclaimed: grace
    # chip-seconds accrued, then the board returns to no-demand rather
    # than leaking into reserved-by-gang.
    assert report["scale_events"]["scale-to-zero"] >= 1
    idle = report["capacity"]["idle_chip_seconds"]
    assert idle["autoscaler-grace"] > 0
    assert idle["no-demand"] > 0
    assert idle["reserved-by-gang"] == 0
    assert report["replicas"]["final"]["batch"] == 0
    # The hot model rode the wave: more replicas at peak than at the end.
    assert report["replicas"]["max_ready"]["chat"] > report["replicas"]["final"]["chat"]
