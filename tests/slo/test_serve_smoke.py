"""Seed-pinned serve-smoke gate: the open-loop bench config at smoke
scale, run twice in-process — the two reports must be byte-identical
(virtual clock => latencies are a pure function of the seed) and the
SLO verdicts stable."""
import argparse
import json

import bench_serve


def smoke_args(seed=0, duration=20.0):
    return argparse.Namespace(
        seed=seed,
        duration=duration,
        rate=3.0,
        slo=list(bench_serve.DEFAULT_SLOS),
        output=None,
    )


def test_smoke_report_is_bit_stable_and_well_formed():
    first = bench_serve.run(smoke_args())
    second = bench_serve.run(smoke_args())
    body1 = json.dumps(first, indent=2, sort_keys=True)
    body2 = json.dumps(second, indent=2, sort_keys=True)
    assert body1 == body2  # fresh engines, same seed -> same bytes

    # BENCH_serve.json shape: workload echo, per-model + aggregate stats,
    # SLO verdicts for every default spec.
    assert set(first) == {"workload", "models", "aggregate", "slo"}
    assert set(first["models"]) == {"hot", "cold"}
    aggregate = first["aggregate"]
    assert aggregate["requests"] > 0
    assert aggregate["tokens"] > 0
    assert (
        first["models"]["hot"]["requests"]
        > first["models"]["cold"]["requests"]
    )
    for key in ("ttft_s", "tpot_s", "e2e_s", "queue_wait_s"):
        assert set(aggregate[key]) == {"p50", "p95", "p99"}
        assert aggregate[key]["p50"] <= aggregate[key]["p99"]
    assert aggregate["ttft_s"]["p50"] > 0.0
    goodput = aggregate["goodput"]
    assert 0.0 <= goodput["request_fraction"] <= 1.0
    assert goodput["good_tokens_per_s"] > 0.0

    verdicts = first["slo"]["verdicts"]
    assert sorted(first["slo"]["specs"]) == sorted(bench_serve.DEFAULT_SLOS)
    assert len(verdicts) == len(bench_serve.DEFAULT_SLOS)
    for verdict in verdicts.values():
        assert isinstance(verdict["compliant"], bool)
        assert verdict["burn_rate_fast"] >= 0.0
        assert verdict["burn_rate_slow"] >= 0.0
        assert 0.0 <= verdict["error_budget_remaining"] <= 1.0


def test_seed_changes_the_report():
    # Not a fixed-point: a different seed yields a different arrival
    # schedule and therefore different latencies.
    a = bench_serve.run(smoke_args(seed=0, duration=8.0))
    b = bench_serve.run(smoke_args(seed=1, duration=8.0))
    assert a["aggregate"]["requests"] != b["aggregate"]["requests"] or (
        a["aggregate"]["ttft_s"] != b["aggregate"]["ttft_s"]
    )
