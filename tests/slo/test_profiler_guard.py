"""Profiler coverage of the serve loop: /debug/profile must decompose
serve time into the engine's span phases, at <= 2% sampling overhead."""
import pytest

from nos_tpu.util.profiling import PROFILER


@pytest.mark.slow
def test_profiler_decomposes_serve_time_within_overhead_budget():
    import bench_serve
    from tests.slo.test_serve_smoke import smoke_args

    PROFILER.stop()
    PROFILER.reset()
    assert PROFILER.start()
    try:
        report = bench_serve.run(smoke_args())
        assert report["aggregate"]["requests"] > 0
    finally:
        PROFILER.stop()

    overhead = PROFILER.overhead_fraction()
    assert overhead <= 0.02, f"sampling overhead {overhead:.4f} > 2%"

    # The driver registers each replica's drive loop, so the samples
    # land in the serve.* phases the engine spans publish — that is the
    # /debug/profile decomposition of serve time into admit / prefill /
    # decode.
    phases = PROFILER.phase_report()["phases"]
    serve_phases = {p for p in phases if p.startswith("serve.")}
    assert serve_phases, f"no serve.* phases in {sorted(phases)}"
    # The decode loop dominates wall time in the smoke workload; the
    # admission-side phases show up too across ~60 requests.
    assert any(
        p in serve_phases
        for p in ("serve.batch_decode", "serve.prefill", "serve.admit")
    ), sorted(serve_phases)

    payload = PROFILER.debug_payload()
    assert payload["attributed_fraction"] > 0.0
    assert payload["total_samples"] > 0
