"""Open-loop driver: arrival-schedule purity, percentile math, and a
small seed-pinned drive of the real engine on the virtual clock."""
import jax
import jax.numpy as jnp
import pytest

from nos_tpu.models.llama import init_llama_params, tiny_config
from nos_tpu.serve.engine import Engine
from nos_tpu.serve.telemetry import ServeTelemetry, VirtualServeClock
from nos_tpu.slo.driver import (
    ModelProfile,
    OpenLoopDriver,
    WorkloadConfig,
    build_arrivals,
    percentiles,
)


class TestBuildArrivals:
    def test_pure_function_of_config(self):
        config = WorkloadConfig(seed=11, duration_s=20.0, rate_rps=5.0)
        assert build_arrivals(config) == build_arrivals(config)
        other = WorkloadConfig(seed=12, duration_s=20.0, rate_rps=5.0)
        assert build_arrivals(other) != build_arrivals(config)

    def test_bounds_and_ordering(self):
        config = WorkloadConfig(
            seed=3, duration_s=10.0, rate_rps=20.0, vocab=64,
            models=(ModelProfile(name="m", prompt_tokens=(4, 9),
                                 max_new_tokens=(2, 5)),),
        )
        arrivals = build_arrivals(config)
        assert arrivals  # ~200 expected; at least some
        times = [a.t for a in arrivals]
        assert times == sorted(times)
        assert all(0.0 <= t < 10.0 for t in times)
        for a in arrivals:
            assert 4 <= len(a.prompt) <= 9
            assert 2 <= a.max_new_tokens <= 5
            assert all(0 <= tok < 64 for tok in a.prompt)

    def test_mean_rate_roughly_holds(self):
        config = WorkloadConfig(seed=0, duration_s=100.0, rate_rps=10.0)
        n = len(build_arrivals(config))
        # Poisson(1000): +/- 10% is ~3 sigma; the seed pins it anyway.
        assert 900 < n < 1100

    def test_hot_cold_skew(self):
        config = WorkloadConfig(
            seed=1, duration_s=50.0, rate_rps=10.0,
            models=(
                ModelProfile(name="hot", weight=0.8),
                ModelProfile(name="cold", weight=0.2),
            ),
        )
        arrivals = build_arrivals(config)
        hot = sum(1 for a in arrivals if a.model == "hot")
        cold = len(arrivals) - hot
        assert hot > 3 * cold > 0

    def test_diurnal_shaping_moves_mass_to_the_peak(self):
        # amplitude 1, period = duration: rate(t) rides a full sine —
        # above the mean in the first half, below in the second.
        config = WorkloadConfig(
            seed=2, duration_s=40.0, rate_rps=10.0,
            diurnal_amplitude=1.0, diurnal_period_s=40.0,
        )
        arrivals = build_arrivals(config)
        first = sum(1 for a in arrivals if a.t < 20.0)
        second = len(arrivals) - first
        assert first > 1.5 * second

    def test_diurnal_only_thins_never_reorders(self):
        flat = WorkloadConfig(seed=4, duration_s=30.0, rate_rps=8.0)
        shaped = WorkloadConfig(
            seed=4, duration_s=30.0, rate_rps=8.0,
            diurnal_amplitude=0.5, diurnal_period_s=30.0,
        )
        times = [a.t for a in build_arrivals(shaped)]
        assert times == sorted(times)
        # Thinning at the higher peak rate changes counts, not validity.
        assert build_arrivals(flat)

    def test_config_validation(self):
        with pytest.raises(ValueError, match="ModelProfile"):
            build_arrivals(WorkloadConfig(models=()))
        with pytest.raises(ValueError, match="amplitude"):
            build_arrivals(WorkloadConfig(diurnal_amplitude=1.5))
        with pytest.raises(ValueError, match="weights"):
            build_arrivals(
                WorkloadConfig(models=(ModelProfile(name="m", weight=0.0),))
            )


class TestPercentiles:
    def test_nearest_rank(self):
        values = [float(i) for i in range(1, 101)]
        assert percentiles(values) == {"p50": 50.0, "p95": 95.0, "p99": 99.0}

    def test_small_samples(self):
        assert percentiles([7.0]) == {"p50": 7.0, "p95": 7.0, "p99": 7.0}
        assert percentiles([]) == {"p50": 0.0, "p95": 0.0, "p99": 0.0}

    def test_order_independent(self):
        assert percentiles([3.0, 1.0, 2.0]) == percentiles([1.0, 2.0, 3.0])


@pytest.fixture(scope="module")
def model():
    config = tiny_config(dtype=jnp.float32)
    params = init_llama_params(jax.random.key(0), config)
    return config, params


def make_engine(model, name="m"):
    config, params = model
    telemetry = ServeTelemetry(
        model=name, clock=VirtualServeClock(), ttft_target_s=0.5,
        e2e_target_s=2.0,
    )
    return Engine(
        params, config, max_slots=2, max_len=128, ticks_per_sync=4,
        prefill_chunk=16, model=name, telemetry=telemetry,
    )


class TestOpenLoopDriver:
    def test_rejects_wall_clock_engine(self, model):
        config, params = model
        engine = Engine(params, config, max_slots=2, max_len=128)
        workload = WorkloadConfig(models=(ModelProfile(name="default"),))
        with pytest.raises(ValueError, match="VirtualServeClock"):
            OpenLoopDriver({"default": engine}, workload)

    def test_rejects_missing_engine(self, model):
        workload = WorkloadConfig(models=(ModelProfile(name="nope"),))
        with pytest.raises(ValueError, match="no engine"):
            OpenLoopDriver({}, workload)

    def test_drive_stamps_arrival_times(self, model):
        workload = WorkloadConfig(
            seed=5, duration_s=4.0, rate_rps=1.5, vocab=32,
            models=(ModelProfile(name="m", prompt_tokens=(4, 10),
                                 max_new_tokens=(3, 6)),),
        )
        arrivals = build_arrivals(workload)
        assert arrivals
        engine = make_engine(model)
        driver = OpenLoopDriver({"m": engine}, workload)
        report = driver.run()

        # Every arrival became exactly one completed record, and the
        # open-loop contract held: submit stamps are the *generated*
        # arrival times, not whenever the engine got around to them.
        records = driver.records["m"]
        assert len(records) == len(arrivals)
        assert sorted(r.submit_t for r in records) == pytest.approx(
            [a.t for a in arrivals]
        )
        assert not engine.busy
        for rec in records:
            assert rec.queue_wait_s is not None and rec.queue_wait_s >= 0.0
            assert rec.ttft_s is not None and rec.ttft_s > 0.0
            assert rec.e2e_s >= rec.ttft_s
            assert rec.tokens >= 1

        # Report shape (no SLO engine wired -> no slo section).
        assert set(report) == {"workload", "models", "aggregate"}
        stats = report["models"]["m"]
        assert stats["requests"] == len(arrivals)
        assert stats["tokens"] == sum(r.tokens for r in records)
        for key in ("ttft_s", "tpot_s", "e2e_s", "queue_wait_s"):
            assert set(stats[key]) == {"p50", "p95", "p99"}
        assert stats["goodput"]["good_requests"] <= stats["requests"]
