"""SLO spec grammar and burn-rate window math vs hand-computed fixtures."""
import pytest

from nos_tpu.serve.telemetry import RequestRecord
from nos_tpu.slo.engine import SLOEngine, SLOSpec
from nos_tpu.util import metrics


def make_record(
    rid,
    retire_t,
    ttft=0.05,
    tpot=0.005,
    queue_wait=0.0,
    tokens=10,
    good=True,
    model="m",
    trace_id="",
):
    """A retired request with exact stamps: submit at retire - e2e, first
    token at submit + ttft, e2e = ttft + tpot * (tokens - 1)."""
    e2e = ttft + tpot * (tokens - 1)
    submit = retire_t - e2e
    return RequestRecord(
        id=rid,
        model=model,
        adapter=0,
        bucket=8,
        prompt_tokens=4,
        max_new_tokens=tokens,
        submit_t=submit,
        trace_id=trace_id,
        admit_t=submit + queue_wait,
        first_token_t=submit + ttft,
        retire_t=retire_t,
        tokens=tokens,
        good=good,
    )


class TestSLOSpecParse:
    def test_latency_forms(self):
        spec = SLOSpec.parse("p95 ttft < 300ms")
        assert spec.metric == "ttft"
        assert spec.objective == pytest.approx(0.95)
        assert spec.threshold_s == pytest.approx(0.3)
        assert spec.name == "ttft_p95_lt_300ms"

        spec = SLOSpec.parse("p99 e2e < 2.5s")
        assert spec.metric == "e2e"
        assert spec.objective == pytest.approx(0.99)
        assert spec.threshold_s == pytest.approx(2.5)

        spec = SLOSpec.parse("p50 tpot < 40ms")
        assert spec.metric == "tpot"
        assert spec.threshold_s == pytest.approx(0.04)

        spec = SLOSpec.parse("p90 queue_wait < 1s")
        assert spec.metric == "queue_wait"
        assert spec.threshold_s == pytest.approx(1.0)

    def test_availability_form(self):
        spec = SLOSpec.parse("availability 99.9%")
        assert spec.metric == "availability"
        assert spec.objective == pytest.approx(0.999)
        assert spec.threshold_s is None
        assert spec.name == "availability_99.9"

    def test_case_and_whitespace_tolerant(self):
        spec = SLOSpec.parse("  P95 TTFT<300MS ")
        assert spec.threshold_s == pytest.approx(0.3)

    @pytest.mark.parametrize(
        "bad",
        [
            "p95 latency < 300ms",  # unknown metric
            "ttft < 300ms",  # no percentile
            "p95 ttft > 300ms",  # wrong comparator
            "p95 ttft < 300",  # no unit
            "availability 100%",  # no error budget at all
            "p0 ttft < 1s",  # degenerate percentile
            "",
        ],
    )
    def test_rejects_malformed(self, bad):
        with pytest.raises(ValueError):
            SLOSpec.parse(bad)

    def test_duplicate_names_rejected(self):
        with pytest.raises(ValueError, match="duplicate"):
            SLOEngine(["p95 ttft < 300ms", "p95 ttft < 300ms"])

    def test_latency_targets_take_tightest_threshold(self):
        engine = SLOEngine(
            ["p95 ttft < 300ms", "p99 ttft < 900ms", "p99 e2e < 5s",
             "availability 99%"]
        )
        assert engine.latency_targets() == {
            "ttft": pytest.approx(0.3),
            "e2e": pytest.approx(5.0),
        }


class TestBurnRateWindows:
    """Hand-computed fixture: 10 requests retire at t = 1..10 s. The
    last three (t = 8, 9, 10) have TTFT 200 ms; the rest 50 ms. Spec
    'p90 ttft < 100ms' allows a 10% bad fraction."""

    def _engine(self):
        engine = SLOEngine(
            ["p90 ttft < 100ms"], fast_window_s=3.0, slow_window_s=100.0
        )
        for i in range(1, 11):
            engine.record(
                make_record(i, retire_t=float(i),
                            ttft=0.2 if i >= 8 else 0.05)
            )
        return engine

    def test_fast_window_burn(self):
        # Fast window (7, 10]: 3 requests, all bad -> bad fraction 1.0,
        # burn = 1.0 / 0.1 = 10.
        out = self._engine().evaluate(now=10.0)
        slo = out["slos"][0]
        assert slo["fast"] == {
            "requests": 3, "bad": 3, "bad_fraction": 1.0, "burn_rate": 10.0,
        }

    def test_slow_window_burn_and_compliance(self):
        # Slow window: all 10, 3 bad -> 0.3 / 0.1 = 3.0 -> non-compliant,
        # budget fully burned.
        out = self._engine().evaluate(now=10.0)
        slo = out["slos"][0]
        assert slo["slow"] == {
            "requests": 10, "bad": 3, "bad_fraction": 0.3, "burn_rate": 3.0,
        }
        assert slo["compliant"] is False
        assert slo["error_budget_remaining"] == 0.0

    def test_windows_slide(self):
        # At now = 20 the fast window (17, 20] is empty: vacuous health.
        out = self._engine().evaluate(now=20.0)
        slo = out["slos"][0]
        assert slo["fast"] == {
            "requests": 0, "bad": 0, "bad_fraction": 0.0, "burn_rate": 0.0,
        }
        # Slow window still sees all 10 -> verdict unchanged.
        assert slo["slow"]["burn_rate"] == 3.0

    def test_burn_exactly_one_is_compliant(self):
        # 1 bad in 10 at a 10% budget: burn 1.0 burns the budget exactly
        # but does not exceed it.
        engine = SLOEngine(
            ["p90 ttft < 100ms"], fast_window_s=3.0, slow_window_s=100.0
        )
        for i in range(1, 11):
            engine.record(
                make_record(i, retire_t=float(i),
                            ttft=0.2 if i == 5 else 0.05)
            )
        slo = engine.evaluate(now=10.0)["slos"][0]
        assert slo["slow"]["burn_rate"] == 1.0
        assert slo["compliant"] is True
        assert slo["error_budget_remaining"] == 0.0

    def test_availability_counts_not_good(self):
        engine = SLOEngine(
            ["availability 90%"], fast_window_s=3.0, slow_window_s=100.0
        )
        for i in range(1, 11):
            engine.record(make_record(i, retire_t=float(i), good=i != 4))
        slo = engine.evaluate(now=10.0)["slos"][0]
        assert slo["slow"] == {
            "requests": 10, "bad": 1, "bad_fraction": 0.1, "burn_rate": 1.0,
        }
        assert slo["compliant"] is True

    def test_missing_stage_is_bad(self):
        # A request with no first token (e.g. failed before emit) is a
        # bad event for any ttft spec — the user saw the miss.
        engine = SLOEngine(["p90 ttft < 100ms"], slow_window_s=100.0)
        rec = make_record(1, retire_t=1.0)
        rec.first_token_t = None
        engine.record(rec)
        slo = engine.evaluate(now=1.0)["slos"][0]
        assert slo["slow"]["bad"] == 1

    def test_gauges_published(self):
        engine = SLOEngine(
            ["p90 ttft < 100ms"], fast_window_s=3.0, slow_window_s=100.0
        )
        for i in range(1, 11):
            engine.record(
                make_record(i, retire_t=float(i),
                            ttft=0.2 if i >= 8 else 0.05)
            )
        engine.evaluate(now=10.0)
        from nos_tpu.slo.engine import (
            SLO_BUDGET_REMAINING, SLO_BURN_RATE, SLO_COMPLIANT,
        )
        name = "ttft_p90_lt_100ms"
        assert SLO_BURN_RATE.labels(slo=name, window="fast").value == 10.0
        assert SLO_BURN_RATE.labels(slo=name, window="slow").value == 3.0
        assert SLO_COMPLIANT.labels(slo=name).value == 0.0
        assert SLO_BUDGET_REMAINING.labels(slo=name).value == 0.0
        # And they render through the registry (doc-drift names live).
        rendered = metrics.REGISTRY.render()
        assert "nos_tpu_slo_burn_rate" in rendered
        assert "nos_tpu_slo_compliant" in rendered
        assert "nos_tpu_slo_error_budget_remaining" in rendered

    def test_debug_payload_links_violations_to_traces(self):
        engine = SLOEngine(["p90 ttft < 100ms"], slow_window_s=100.0)
        engine.record(make_record(1, retire_t=1.0, ttft=0.05, trace_id="t9"))
        engine.record(make_record(2, retire_t=2.0, ttft=0.2, trace_id="tA"))
        payload = engine.debug_payload()
        assert payload["requests_seen"] == 2
        violations = payload["recent_violations"]
        assert len(violations) == 1
        assert violations[0]["request"] == 2
        assert violations[0]["slos"] == ["ttft_p90_lt_100ms"]
        assert violations[0]["trace"] == "/debug/traces?id=tA"
