"""PlacementForecaster wiring: publishing, the ledger-driven accuracy
join, staleness checks, and the flight-record replay round-trip (the
auditor recomputes every calibration payload bit-exactly)."""
import json
import time

from nos_tpu.capacity import CapacityLedger
from nos_tpu.forecast import PlacementForecaster, STAGE_FEASIBLE_NOW
from nos_tpu.kube.store import KubeStore
from nos_tpu.partitioning.core import ClusterState
from nos_tpu.partitioning.tpu import TpuSnapshotTaker
from nos_tpu.record import FlightRecorder
from nos_tpu.util.profiling import PROFILER
from nos_tpu.record.replay import ReplaySession

from tests.forecast.helpers import (
    T0,
    carved_node,
    gang_pod,
    make_planner,
    make_store,
)


def make_forecaster(store, **kwargs):
    return PlacementForecaster(
        store,
        ClusterState(),
        make_planner(store),
        TpuSnapshotTaker(),
        **kwargs,
    )


def feasible_cluster(store):
    """One carved node with two free 2x2 slices + a two-pod gang that
    fits them: forecast is feasible-now."""
    store.create(carved_node("n1", free={0: {"2x2": 2}}))
    pending = [gang_pod("g0"), gang_pod("g1")]
    for p in pending:
        store.create(p)
    return pending


class TestRunOnce:
    def test_publishes_gang_etas_and_stamps(self):
        store = make_store()
        pending = feasible_cluster(store)
        ledger = CapacityLedger(store, metrics=False)
        ledger.note_gang_arrival("default/big", T0 - 10.0)
        forecaster = make_forecaster(store, capacity_ledger=ledger)
        payload = forecaster.run_once(
            now=T0, pending=pending, cycle_seconds=2.0, reconfig_seconds=0.5
        )
        assert forecaster.runs == 1
        gang = payload["gangs"][0]
        assert gang["gang"] == "default/big"
        assert gang["stage"] == STAGE_FEASIBLE_NOW
        assert gang["eta_seconds"] == 2.0
        assert gang["wait_seconds"] == 10.0  # from the ledger's clock
        assert forecaster._outstanding["default/big"] == {
            "now": T0,
            "eta_seconds": 2.0,
            "stage": STAGE_FEASIBLE_NOW,
        }

    def test_run_once_is_deterministic(self):
        store = make_store()
        pending = feasible_cluster(store)
        store.create(carved_node("n2"))  # uncarved spare, advisor fodder
        forecaster = make_forecaster(store)
        first = forecaster.run_once(
            now=T0, pending=pending, cycle_seconds=1.0, reconfig_seconds=0.5
        )
        second = forecaster.run_once(
            now=T0, pending=pending, cycle_seconds=1.0, reconfig_seconds=0.5
        )
        assert json.dumps(first, sort_keys=True) == json.dumps(
            second, sort_keys=True
        )

    def test_reconfig_rate_comes_from_the_ledger(self):
        store = make_store()
        pending = feasible_cluster(store)
        ledger = CapacityLedger(store, metrics=False)
        forecaster = make_forecaster(
            store, capacity_ledger=ledger, default_reconfig_seconds=0.9
        )
        forecaster.run_once(now=T0, pending=pending)
        # No measured edges yet: the ledger falls back to our default.
        assert forecaster.debug_payload()["reconfig_seconds"] == 0.9


class TestAccuracyJoin:
    def test_gang_bound_joins_the_last_forecast(self):
        store = make_store()
        pending = feasible_cluster(store)
        ledger = CapacityLedger(store, metrics=False)
        ledger.note_gang_arrival("default/big", T0 - 10.0)
        forecaster = make_forecaster(store, capacity_ledger=ledger)
        forecaster.run_once(
            now=T0, pending=pending, cycle_seconds=2.0, reconfig_seconds=0.5
        )
        # The ledger observes the bind 3s later; its listener joins the
        # 2s ETA against the 3s actual without any forecaster plumbing.
        ledger.note_gang_bound("default/big", T0 + 3.0)
        calibration = forecaster.calibration.payload()
        assert calibration["joined"] == 1
        assert calibration["p50_error_seconds"] == 1.0
        assert calibration["p50_ratio"] == 1.0 / 13.0
        assert forecaster._outstanding == {}  # stamp consumed

    def test_unforecast_bind_is_counted_not_scored(self):
        store = make_store()
        ledger = CapacityLedger(store, metrics=False)
        ledger.note_gang_arrival("ml/ghost", T0)
        forecaster = make_forecaster(store, capacity_ledger=ledger)
        forecaster._outstanding["ml/ghost"] = {
            "now": T0,
            "eta_seconds": None,
            "stage": "blocked",
        }
        ledger.note_gang_bound("ml/ghost", T0 + 4.0)
        calibration = forecaster.calibration.payload()
        assert calibration["joined"] == 0
        assert calibration["unforecast"] == 1


class TestStaleness:
    def test_stale_feasible_now_flags_only_overdue_gangs(self):
        store = make_store()
        pending = feasible_cluster(store)
        forecaster = make_forecaster(store)
        forecaster.run_once(now=T0, pending=pending, cycle_seconds=1.0)
        assert forecaster.stale_feasible_now(T0 + 1.0) == []
        assert forecaster.stale_feasible_now(T0 + 100.0) == ["default/big"]
        # A later run still feasible-now keeps the ORIGINAL stamp: the
        # clock measures continuous feasibility, not recency.
        forecaster.run_once(now=T0 + 100.0, pending=pending)
        assert forecaster.stale_feasible_now(T0 + 104.0) == ["default/big"]

    def test_binding_clears_the_feasible_stamp(self):
        store = make_store()
        pending = feasible_cluster(store)
        ledger = CapacityLedger(store, metrics=False)
        ledger.note_gang_arrival("default/big", T0)
        forecaster = make_forecaster(store, capacity_ledger=ledger)
        forecaster.run_once(now=T0, pending=pending)
        ledger.note_gang_bound("default/big", T0 + 1.0)
        assert forecaster.stale_feasible_now(T0 + 100.0) == []


class TestDebugPayload:
    def test_shape_without_refresh(self):
        store = make_store()
        pending = feasible_cluster(store)
        forecaster = make_forecaster(store)
        forecaster.run_once(now=T0, pending=pending)
        payload = forecaster.debug_payload()
        assert payload["kind"] == "tpu"
        assert payload["runs"] == 1
        assert payload["outstanding"] == 1
        assert payload["forecast"]["gangs"][0]["gang"] == "default/big"
        assert payload["calibration"]["joined"] == 0


def recorded_forecast_run():
    """A live run with the recorder attached: two forecast cycles, then
    the gang binds and the outcome joins. Returns the flight record
    after a JSON round-trip, the framing the replay auditor consumes."""
    store = KubeStore()
    from nos_tpu.cmd.partitioner import register_indexers

    register_indexers(store)
    recorder = FlightRecorder()
    recorder.attach(store)
    ledger = CapacityLedger(store, flight_recorder=recorder, metrics=False)
    pending = feasible_cluster(store)
    ledger.note_gang_arrival("default/big", T0 - 10.0)
    forecaster = make_forecaster(
        store, capacity_ledger=ledger, flight_recorder=recorder
    )
    forecaster.run_once(
        now=T0, pending=pending, cycle_seconds=2.0, reconfig_seconds=0.5
    )
    forecaster.run_once(
        now=T0 + 2.0, pending=pending, cycle_seconds=2.0, reconfig_seconds=0.5
    )
    ledger.note_gang_bound("default/big", T0 + 3.0)
    recorder.detach()
    return [json.loads(line) for line in recorder.to_jsonl().splitlines()]


class TestReplayRoundTrip:
    def test_auditor_clean_on_replay(self):
        records = recorded_forecast_run()
        cycles = [r for r in records if r["kind"] == "forecast.cycle"]
        outcomes = [r for r in records if r["kind"] == "forecast.outcome"]
        assert len(cycles) == 2 and len(outcomes) == 1
        assert cycles[0]["gangs"][0]["stage"] == STAGE_FEASIBLE_NOW
        outcome = outcomes[0]
        assert outcome["gang"] == "default/big"
        # Joined against the SECOND forecast (stamps replace wholesale).
        assert outcome["actual_seconds"] == 1.0
        assert outcome["calibration"]["joined"] == 1

        report = ReplaySession(records).run()
        assert report.forecast_cycles == 2
        assert report.forecast_outcomes == 1
        assert report.drifts == []
        assert report.ok()
        assert "1 forecast outcome(s)" in report.render()

    def test_tampered_calibration_is_reported_as_drift(self):
        records = recorded_forecast_run()
        tampered = next(r for r in records if r["kind"] == "forecast.outcome")
        tampered["calibration"]["p50_error_seconds"] += 0.5
        report = ReplaySession(records).run()
        drifts = [d for d in report.drifts if d["kind"] == "forecast.outcome"]
        assert len(drifts) == 1
        assert drifts[0]["seq"] == tampered["seq"]
        assert drifts[0]["gang"] == "default/big"
        assert not report.ok()


class TestProfilerRegistration:
    def test_loop_thread_registers_with_sampling_profiler(self):
        """/debug/profile can only attribute forecast.* phases if the
        loop thread announces itself; pin the register/unregister pair."""
        store = make_store()
        feasible_cluster(store)
        forecaster = make_forecaster(store)
        forecaster.start()
        try:
            deadline = time.monotonic() + 5.0
            while time.monotonic() < deadline:
                if "forecast-tpu" in PROFILER.threads().values():
                    break
                time.sleep(0.01)
            assert "forecast-tpu" in PROFILER.threads().values()
        finally:
            forecaster.stop()
        assert "forecast-tpu" not in PROFILER.threads().values()
