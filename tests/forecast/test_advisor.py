"""DefragAdvisor: near-empty detection against board capacity, shadow-sim
validation of proposals, and the strictly-read-only contract."""
from nos_tpu.forecast import DefragAdvisor, STAGE_FEASIBLE_NOW, STAGE_RECARVE

from tests.factory import PodPhase
from tests.forecast.helpers import (
    T0,
    carved_node,
    gang_pod,
    make_engine,
    make_store,
    snapshot_fingerprint,
    take_snapshot,
)


def forecast_and_advise(store, pending, engine=None, **advisor_kwargs):
    engine = engine or make_engine(store)
    advisor = DefragAdvisor(engine, **advisor_kwargs)
    snapshot = take_snapshot(store)
    clocks = {"default/big": {"arrival": T0 - 10.0}}
    before = engine.forecast(snapshot, pending, T0, clocks=clocks).gangs
    return (
        snapshot,
        before,
        advisor.advise(snapshot, pending, before, T0, clocks=clocks),
    )


class TestNearEmptyDetection:
    def test_uncarved_nodes_qualify(self):
        """free_slices() is empty on a pristine node — the advisor must
        measure free against BOARD capacity or its prime candidates all
        read as zero free (the regression this class pins)."""
        store = make_store()
        store.create(carved_node("n1"))
        engine = make_engine(store)
        advisor = DefragAdvisor(engine)
        names = [n for n, _ in advisor._near_empty_nodes(take_snapshot(store))]
        assert names == ["n1"]

    def test_mostly_used_nodes_do_not_qualify(self):
        store = make_store()
        store.create(carved_node("n1", used={0: {"2x2": 1, "1x2": 1}}))
        store.create(
            gang_pod("b0", gang="old", node="n1", phase=PodPhase.RUNNING)
        )
        store.create(
            gang_pod(
                "b1", gang="old", profile="1x2", node="n1",
                phase=PodPhase.RUNNING,
            )
        )
        engine = make_engine(store)
        advisor = DefragAdvisor(engine)  # threshold 0.5, free is 2/8
        assert advisor._near_empty_nodes(take_snapshot(store)) == []

    def test_most_free_first_order(self):
        store = make_store()
        store.create(carved_node("a", used={0: {"1x2": 1}}))
        store.create(
            gang_pod(
                "b0", gang="old", profile="1x2", node="a",
                phase=PodPhase.RUNNING,
            )
        )
        store.create(carved_node("b"))
        engine = make_engine(store)
        advisor = DefragAdvisor(engine)
        out = advisor._near_empty_nodes(take_snapshot(store))
        assert out == [("b", 8), ("a", 6)]


class TestValidation:
    def test_validated_proposal_moves_gang_earlier(self):
        store = make_store()
        for i in range(3):
            store.create(carved_node(f"n{i}"))
        pending = [gang_pod(f"g{i}", size=4) for i in range(4)]
        for p in pending:
            store.create(p)
        _, before, advice = forecast_and_advise(store, pending)
        assert before[0].stage == STAGE_RECARVE
        assert advice["near_empty_nodes"] == ["n0", "n1", "n2"]
        assert advice["proposals"]
        first = advice["proposals"][0]
        assert first["node"] == "n0"
        assert first["geometry_after"] != first["geometry_before"]
        # The shadow sim re-forecast the gang against the hypothetical
        # geometry: it starts earlier, so the recommendation validates
        # with a positive chip-seconds saving.
        assert advice["validated"] is True
        assert advice["predicted_idle_savings_chip_seconds"] > 0
        shadow = advice["gangs"][0]
        assert shadow["stage_before"] == STAGE_RECARVE
        assert shadow["stage_after"] == STAGE_FEASIBLE_NOW
        assert shadow["eta_after"] < shadow["eta_before"]

    def test_no_pending_demand_proposes_nothing(self):
        store = make_store()
        store.create(carved_node("n1"))
        _, _, advice = forecast_and_advise(store, [])
        assert advice["proposals"] == []
        assert advice["validated"] is False

    def test_already_feasible_gang_does_not_validate(self):
        """Nothing to save: the queue's demand already places on current
        geometry, so a re-carve proposal must not claim savings."""
        store = make_store()
        store.create(carved_node("n1", free={0: {"2x2": 2}}))
        store.create(carved_node("n2"))
        pending = [gang_pod("g0"), gang_pod("g1")]
        for p in pending:
            store.create(p)
        _, before, advice = forecast_and_advise(store, pending)
        assert before[0].stage == STAGE_FEASIBLE_NOW
        assert advice["predicted_idle_savings_chip_seconds"] == 0.0
        assert advice["validated"] is False

    def test_proposal_cap(self):
        store = make_store()
        for i in range(5):
            store.create(carved_node(f"n{i}"))
        pending = [gang_pod(f"g{i}", size=4) for i in range(4)]
        for p in pending:
            store.create(p)
        _, _, advice = forecast_and_advise(store, pending, max_proposals=2)
        assert len(advice["proposals"]) == 2


class TestReadOnly:
    def test_advise_leaves_snapshot_and_store_untouched(self):
        store = make_store()
        for i in range(3):
            store.create(carved_node(f"n{i}"))
        pending = [gang_pod(f"g{i}", size=4) for i in range(4)]
        for p in pending:
            store.create(p)
        revision = store.revision
        snapshot, before, _ = forecast_and_advise(store, pending)
        fingerprint = snapshot_fingerprint(snapshot)
        engine = make_engine(store)
        DefragAdvisor(engine).advise(snapshot, pending, before, T0)
        assert snapshot_fingerprint(snapshot) == fingerprint
        assert snapshot._journals == []
        assert store.revision == revision  # nothing written, ever
