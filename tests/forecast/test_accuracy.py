"""CalibrationTracker: nearest-rank percentiles, the unforecast path,
the sliding window, and payload purity (replay recomputes it bit-exactly
from the add() history alone)."""
from nos_tpu.forecast import CalibrationTracker, nearest_rank


class TestNearestRank:
    def test_textbook_ranks(self):
        values = sorted(float(v) for v in range(1, 101))
        assert nearest_rank(values, 0.5) == 50.0
        assert nearest_rank(values, 0.95) == 95.0
        assert nearest_rank(values, 1.0) == 100.0

    def test_small_samples_clamp(self):
        assert nearest_rank([7.0], 0.5) == 7.0
        assert nearest_rank([7.0], 0.95) == 7.0
        assert nearest_rank([1.0, 9.0], 0.95) == 9.0


class TestCalibrationTracker:
    def test_join_produces_error_and_ratio(self):
        tracker = CalibrationTracker()
        sample = tracker.add(10.0, 12.0, 20.0, stage="recarve")
        assert sample == {
            "error_seconds": 2.0,
            "ratio": 0.1,
            "stage": "recarve",
        }
        payload = tracker.payload()
        assert payload["joined"] == 1 and payload["unforecast"] == 0
        assert payload["p50_error_seconds"] == 2.0
        assert payload["p95_error_seconds"] == 2.0
        assert payload["p50_ratio"] == 0.1

    def test_unforecast_eta_counts_without_a_sample(self):
        tracker = CalibrationTracker()
        assert tracker.add(None, 5.0, 5.0) is None
        payload = tracker.payload()
        assert payload["joined"] == 0 and payload["unforecast"] == 1
        assert payload["p50_error_seconds"] is None

    def test_zero_wait_ratio_is_zero_not_nan(self):
        tracker = CalibrationTracker()
        sample = tracker.add(1.0, 0.0, 0.0)
        assert sample["ratio"] == 0.0

    def test_window_slides(self):
        tracker = CalibrationTracker(window=3)
        for error in (100.0, 1.0, 2.0, 3.0):
            tracker.add(error, 0.0, 10.0)
        payload = tracker.payload()
        # The 100-second outlier aged out of the 3-sample window.
        assert payload["samples"] == 3 and payload["joined"] == 4
        assert payload["p95_error_seconds"] == 3.0

    def test_payload_is_pure_function_of_history(self):
        history = [
            (10.0, 12.0, 20.0, "feasible-now"),
            (None, 5.0, 5.0, "blocked"),
            (3.0, 1.0, 4.0, "recarve"),
            (0.5, 0.5, 2.0, "feasible-now"),
        ]
        a, b = CalibrationTracker(), CalibrationTracker()
        for eta, actual, wait, stage in history:
            a.add(eta, actual, wait, stage=stage)
            b.add(eta, actual, wait, stage=stage)
        assert a.payload() == b.payload()
