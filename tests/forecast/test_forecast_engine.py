"""ForecastEngine stage classification, blocking sets, the backfill
predicate, and the determinism / snapshot-preservation contracts."""
import json

from nos_tpu.forecast import (
    EXPECTED_COMPLETION_ANNOTATION,
    STAGE_BLOCKED,
    STAGE_FEASIBLE_NOW,
    STAGE_RECARVE,
)

from tests.factory import PodPhase
from tests.forecast.helpers import (
    T0,
    carved_node,
    gang_pod,
    make_engine,
    make_store,
    small_pod,
    snapshot_fingerprint,
    take_snapshot,
)


class TestStages:
    def test_feasible_now_on_carved_free_slices(self):
        store = make_store()
        store.create(carved_node("n1", free={0: {"2x2": 2}}))
        pending = [gang_pod("g0"), gang_pod("g1")]
        for p in pending:
            store.create(p)
        engine = make_engine(store)
        result = engine.forecast(
            take_snapshot(store), pending, T0, cycle_seconds=2.0
        )
        assert len(result.gangs) == 1
        gang = result.gangs[0]
        assert gang.gang == "default/big"
        assert gang.stage == STAGE_FEASIBLE_NOW
        assert gang.eta_seconds == 2.0  # the next plan/bind cycle
        assert gang.recarve == [] and gang.blocking == []
        assert gang.pending == ["default/g0", "default/g1"]

    def test_recarve_on_uncarved_capacity(self):
        store = make_store()
        store.create(carved_node("n1"))  # 8 chips, nothing carved
        pending = [gang_pod("g0"), gang_pod("g1")]
        for p in pending:
            store.create(p)
        engine = make_engine(store)
        result = engine.forecast(
            take_snapshot(store),
            pending,
            T0,
            cycle_seconds=1.0,
            reconfig_seconds=2.5,
        )
        gang = result.gangs[0]
        assert gang.stage == STAGE_RECARVE
        assert gang.recarve == ["n1"]
        # One cycle + ONE measured reconfig (re-carves actuate
        # concurrently), never reconfig * node count.
        assert gang.eta_seconds == 3.5

    def test_blocked_without_hints_has_no_eta(self):
        store = make_store()
        store.create(carved_node("n1", used={0: {"2x2": 2}}))
        blockers = [
            gang_pod("b0", gang="old", node="n1", phase=PodPhase.RUNNING),
            gang_pod("b1", gang="old", node="n1", phase=PodPhase.RUNNING),
        ]
        for p in blockers:
            store.create(p)
        pending = [gang_pod("g0"), gang_pod("g1")]
        for p in pending:
            store.create(p)
        engine = make_engine(store)
        result = engine.forecast(take_snapshot(store), pending, T0)
        gang = result.gangs[0]
        assert gang.stage == STAGE_BLOCKED
        assert gang.eta_seconds is None  # honest: no completion hints
        assert [b["pod"] for b in gang.blocking] == [
            "default/b0",
            "default/b1",
        ]
        assert gang.blocking[0]["explain"] == "/debug/explain?pod=default/b0"

    def test_blocked_with_hints_prices_the_slowest_blocker(self):
        store = make_store()
        store.create(carved_node("n1", used={0: {"2x2": 2}}))
        store.create(
            gang_pod(
                "b0", gang="old", node="n1", phase=PodPhase.RUNNING,
                annotations={EXPECTED_COMPLETION_ANNOTATION: str(T0 + 30)},
            )
        )
        store.create(
            gang_pod(
                "b1", gang="old", node="n1", phase=PodPhase.RUNNING,
                annotations={EXPECTED_COMPLETION_ANNOTATION: str(T0 + 50)},
            )
        )
        pending = [gang_pod("g0"), gang_pod("g1")]
        for p in pending:
            store.create(p)
        engine = make_engine(store)
        result = engine.forecast(
            take_snapshot(store), pending, T0, cycle_seconds=1.0
        )
        gang = result.gangs[0]
        assert gang.stage == STAGE_BLOCKED
        # Chips free when the SLOWEST blocker finishes + one plan cycle.
        assert gang.eta_seconds == 51.0
        completions = [
            b.get("expected_completion_ts") for b in gang.blocking
        ]
        assert completions == [T0 + 30, T0 + 50]

    def test_wait_seconds_comes_from_gang_clocks(self):
        store = make_store()
        store.create(carved_node("n1", free={0: {"2x2": 2}}))
        pending = [gang_pod("g0"), gang_pod("g1")]
        for p in pending:
            store.create(p)
        engine = make_engine(store)
        result = engine.forecast(
            take_snapshot(store),
            pending,
            T0,
            clocks={"default/big": {"arrival": T0 - 12.0}},
        )
        assert result.gangs[0].wait_seconds == 12.0

    def test_non_gang_pods_are_not_gangs(self):
        store = make_store()
        store.create(carved_node("n1", free={0: {"1x2": 4}}))
        pending = [small_pod("solo")]
        store.create(pending[0])
        engine = make_engine(store)
        result = engine.forecast(take_snapshot(store), pending, T0)
        assert result.gangs == [] and result.backfill == []


class TestBackfillPredicate:
    def test_taking_a_slice_the_gang_needs_is_unsafe(self):
        store = make_store()
        # 8 chips: two 1x2 slivers + one 2x2. The gang needs two 2x2s —
        # only a re-carve of the slivers makes the second one.
        store.create(carved_node("n1", free={0: {"1x2": 2, "2x2": 1}}))
        pending = [gang_pod("g0"), gang_pod("g1"), small_pod("tiny")]
        for p in pending:
            store.create(p)
        engine = make_engine(store)
        result = engine.forecast(
            take_snapshot(store),
            pending,
            T0,
            clocks={"default/big": {"arrival": T0 - 5.0}},
        )
        assert result.gangs[0].stage == STAGE_RECARVE
        assert len(result.backfill) == 1
        verdict = result.backfill[0]
        assert verdict.pod == "default/tiny" and verdict.node == "n1"
        # The sliver the small pod takes is re-carve feedstock: the gang
        # degrades recarve -> blocked, so the pair is unsafe.
        assert not verdict.safe
        assert "degrades" in verdict.reason
        assert result.unsafe_count == 1
        assert result.heatmap == {"n1": {"safe": 0, "unsafe": 1}}

    def test_taking_an_unneeded_slice_is_safe(self):
        store = make_store()
        store.create(carved_node("n1", free={0: {"2x2": 2}}))
        store.create(carved_node("n2", free={0: {"1x2": 4}}))
        pending = [gang_pod("g0"), gang_pod("g1"), small_pod("tiny")]
        for p in pending:
            store.create(p)
        engine = make_engine(store)
        result = engine.forecast(take_snapshot(store), pending, T0)
        assert result.gangs[0].stage == STAGE_FEASIBLE_NOW
        assert result.backfill and all(v.safe for v in result.backfill)
        assert result.heatmap["n2"]["safe"] >= 1
        assert result.unsafe_count == 0

    def test_pair_cap_bounds_the_trials(self):
        store = make_store()
        store.create(carved_node("n1", free={0: {"2x2": 2}}))
        store.create(carved_node("n2", free={0: {"1x2": 4}}))
        pending = [gang_pod("g0"), gang_pod("g1")] + [
            small_pod(f"tiny{i}") for i in range(6)
        ]
        for p in pending:
            store.create(p)
        engine = make_engine(store, max_backfill_pairs=3)
        result = engine.forecast(take_snapshot(store), pending, T0)
        assert len(result.backfill) == 3


class TestContracts:
    def test_forecast_is_deterministic(self):
        store = make_store()
        store.create(carved_node("n1", free={0: {"1x2": 2, "2x2": 1}}))
        store.create(carved_node("n2"))
        pending = [
            gang_pod("g0"),
            gang_pod("g1"),
            gang_pod("h0", gang="other", size=1, profile="1x2"),
            small_pod("tiny"),
        ]
        for p in pending:
            store.create(p)
        engine = make_engine(store)
        snapshot = take_snapshot(store)
        clocks = {"default/big": {"arrival": T0 - 9.0}}
        first = engine.forecast(snapshot, pending, T0, clocks=clocks)
        second = engine.forecast(snapshot, pending, T0, clocks=clocks)
        assert json.dumps(first.payload(), sort_keys=True) == json.dumps(
            second.payload(), sort_keys=True
        )

    def test_forecast_leaves_the_snapshot_untouched(self):
        store = make_store()
        store.create(carved_node("n1", free={0: {"1x2": 2, "2x2": 1}}))
        store.create(carved_node("n2"))
        pending = [gang_pod("g0"), gang_pod("g1"), small_pod("tiny")]
        for p in pending:
            store.create(p)
        engine = make_engine(store)
        snapshot = take_snapshot(store)
        before = snapshot_fingerprint(snapshot)
        engine.forecast(snapshot, pending, T0)
        assert snapshot_fingerprint(snapshot) == before
        assert snapshot._journals == []  # every fork reverted

    def test_gang_cap_applies_in_sorted_order(self):
        store = make_store()
        store.create(carved_node("n1", free={0: {"2x2": 2}}))
        pending = [
            gang_pod("a0", gang="alpha", size=1),
            gang_pod("b0", gang="beta", size=1),
            gang_pod("c0", gang="gamma", size=1),
        ]
        for p in pending:
            store.create(p)
        engine = make_engine(store, max_gangs=2)
        result = engine.forecast(take_snapshot(store), pending, T0)
        assert [g.gang for g in result.gangs] == [
            "default/alpha",
            "default/beta",
        ]
