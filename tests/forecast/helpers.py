"""Shared builders for the forecast suite: a store-backed snapshot plus
an engine-private planner, the same wiring the PlacementForecaster uses."""
from nos_tpu.api.v1alpha1 import annotations as annot
from nos_tpu.cmd.partitioner import build_sim_framework, register_indexers
from nos_tpu.forecast import ForecastEngine
from nos_tpu.kube.store import KubeStore
from nos_tpu.partitioning.core import ClusterState, Planner
from nos_tpu.partitioning.tpu import TpuSnapshotTaker
from nos_tpu.scheduler.plugins.gang import GANG_NAME_LABEL, GANG_SIZE_LABEL

from tests.factory import PodPhase, build_pod, build_tpu_node, slice_res

T0 = 1_000_000.0


def make_store() -> KubeStore:
    store = KubeStore()
    register_indexers(store)
    return store


def make_planner(store) -> Planner:
    return Planner(build_sim_framework(store))


def make_engine(store, **kwargs) -> ForecastEngine:
    return ForecastEngine(make_planner(store), **kwargs)


def take_snapshot(store):
    return TpuSnapshotTaker().take_snapshot(ClusterState(), store=store)


def carved_node(name, free=None, used=None, chips=8, topology="2x4"):
    """A TPU node whose agent has reported carved geometry."""
    return build_tpu_node(
        name=name,
        chips=chips,
        topology=topology,
        annotations=annot.status_from_devices(free=free or {}, used=used or {}),
    )


def gang_pod(name, profile="2x2", gang="big", size=2, ns="default", node="",
             phase=PodPhase.PENDING, annotations=None):
    pod = build_pod(
        name, requests={slice_res(profile): 1}, ns=ns, node=node, phase=phase
    )
    pod.metadata.labels[GANG_NAME_LABEL] = gang
    pod.metadata.labels[GANG_SIZE_LABEL] = str(size)
    if annotations:
        pod.metadata.annotations.update(annotations)
    return pod


def small_pod(name, profile="1x2", ns="default"):
    return build_pod(name, requests={slice_res(profile): 1}, ns=ns)


def snapshot_fingerprint(snapshot):
    """Geometry + placements of every node — asserting forecast trials
    left the snapshot bit-identical."""
    out = {}
    for name, node in snapshot.get_nodes().items():
        out[name] = (
            {b: dict(g) for b, g in node.partitionable.geometry().items()},
            dict(node.partitionable.free_slices()),
            sorted(p.namespaced_name for p in node.pods),
        )
    return out
