"""Forecast-smoke gate: the streaming calibration bench run twice
in-process — byte-identical reports at the pinned seed, the accuracy
auditor clean on replay — and the committed BENCH_forecast.json must
keep telling the acceptance story: p95 ETA error within 25% of actual
wait, at least one advisor recommendation validated by the shadow sim,
zero store writes from the forecaster."""
import json
import os

import bench_forecast
from nos_tpu.record.replay import ReplaySession


def test_bench_is_bit_stable_and_audits_clean():
    first, records = bench_forecast.run_bench(seed=bench_forecast.SEED)
    second, _ = bench_forecast.run_bench(seed=bench_forecast.SEED)
    body1 = json.dumps(first, indent=2, sort_keys=True)
    body2 = json.dumps(second, indent=2, sort_keys=True)
    # Fresh store + virtual clock, same seed -> same bytes.
    assert body1 == body2

    # The accuracy auditor replays clean: every recorded forecast.outcome
    # recomputes its calibration payload bit-exactly from the outcome
    # stream alone.
    report = ReplaySession(records).run()
    assert report.forecast_outcomes == first["workload"]["gangs"]
    assert report.drifts == []
    assert report.ok()

    assert first["accuracy"]["meets_target"] is True
    assert first["accuracy"]["joined"] == first["workload"]["gangs"]
    assert first["advisor"]["validated_cycles"] >= 1
    assert first["overhead"]["forecast_store_writes"] == 0
    # The stream exercised every stage, not just the easy one.
    assert set(first["stages"]) == {"feasible-now", "recarve", "blocked"}


def test_seed_changes_the_bytes():
    base, _ = bench_forecast.run_bench(seed=bench_forecast.SEED)
    other, _ = bench_forecast.run_bench(seed=bench_forecast.SEED + 1)
    assert json.dumps(base, sort_keys=True) != json.dumps(
        other, sort_keys=True
    )


def test_committed_bench_artifact_tells_the_story():
    path = os.path.join(
        os.path.dirname(bench_forecast.__file__), "BENCH_forecast.json"
    )
    with open(path) as f:
        report = json.load(f)
    # Acceptance: ETAs calibrated within the 25%-of-wait budget...
    assert report["accuracy"]["meets_target"] is True
    assert report["accuracy"]["p95_ratio"] <= 0.25
    assert report["accuracy"]["joined"] == report["workload"]["gangs"]
    # ...at least one defrag recommendation validated by the shadow sim
    # with predicted idle-chip-second savings...
    assert report["advisor"]["validated_cycles"] >= 1
    assert report["advisor"]["max_predicted_savings_chip_seconds"] > 0
    assert report["advisor"]["example"]["proposals"]
    # ...the forecaster stayed strictly read-only, and its flight
    # records replayed with zero drift.
    assert report["overhead"]["forecast_store_writes"] == 0
    assert report["overhead"]["within_budget"] is True
    assert report["replay"]["ok"] is True
    assert report["replay"]["drifts"] == 0
