"""Control-plane crash/restart recovery (SURVEY §5 failure detection).

The apiserver state and the silicon (native tpuctl slice store on disk)
both survive a control-plane crash; everything in-memory dies. A restarted
suite must rebuild its world from those two sources alone: keep running
workloads booked, finish interrupted handshakes, and serve new pods
without double-booking chips.
"""
import time


from nos_tpu.api.config import GpuPartitionerConfig, SchedulerConfig, TpuAgentConfig
from nos_tpu.api.v1alpha1 import annotations as annot
from nos_tpu.api.v1alpha1 import constants
from nos_tpu.cmd import build_cluster
from nos_tpu.kube.objects import PodPhase
from nos_tpu.kube.store import KubeStore

from tests.factory import build_pod, build_tpu_node

FAST = dict(
    partitioner_config=GpuPartitionerConfig(
        batch_window_timeout_seconds=0.3, batch_window_idle_seconds=0.05
    ),
    scheduler_config=SchedulerConfig(retry_seconds=0.1),
)


def wait_for(predicate, timeout=15.0, interval=0.05):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if predicate():
            return True
        time.sleep(interval)
    return False


def running(store, name, ns="ml"):
    pod = store.try_get("Pod", name, ns)
    return pod is not None and pod.status.phase == PodPhase.RUNNING


class TestCrashRecovery:
    def test_restart_preserves_bookings_and_serves_new_pods(self, tmp_path):
        store = KubeStore()

        # ---- life before the crash: pod A runs on carved silicon.
        first = build_cluster(
            store=store, device_backend="tpuctl", tpuctl_dir=str(tmp_path), **FAST
        )
        first.add_tpu_node(
            build_tpu_node(name="tpu-0"),
            agent_config=TpuAgentConfig(report_config_interval_seconds=0.1),
        )
        first.start()
        store.create(build_pod("job-a", {constants.RESOURCE_TPU: 4}, ns="ml"))
        assert wait_for(lambda: running(store, "job-a"))
        first.stop()  # CRASH — store + tpuctl disk survive, memory dies

        # ---- restart: a brand-new suite over the same store + silicon.
        second = build_cluster(
            store=store, device_backend="tpuctl", tpuctl_dir=str(tmp_path), **FAST
        )
        second.start_agent(
            "tpu-0", agent_config=TpuAgentConfig(report_config_interval_seconds=0.1)
        )
        second.start()
        try:
            # a NEW pod is served from the remaining capacity
            store.create(build_pod("job-b", {constants.RESOURCE_TPU: 4}, ns="ml"))
            assert wait_for(lambda: running(store, "job-b")), (
                store.get("Node", "tpu-0").metadata.annotations
            )
            # the pre-crash workload kept its booking (no double-carve)
            assert running(store, "job-a")
            a = store.get("Pod", "job-a", "ml")
            b = store.get("Pod", "job-b", "ml")
            assert a.spec.node_name == b.spec.node_name == "tpu-0"
            # handshake converged after restart
            node = store.get("Node", "tpu-0")
            assert (
                node.metadata.annotations[annot.STATUS_PARTITIONING_PLAN]
                == node.metadata.annotations[annot.SPEC_PARTITIONING_PLAN]
            )
        finally:
            second.stop()

    def test_restart_completes_orphaned_handshake(self, tmp_path):
        """A crash between writing the spec plan and the agent's
        confirmation leaves spec != status; the restarted agent must
        resolve the handshake so planning unblocks."""
        store = KubeStore()
        first = build_cluster(
            store=store, device_backend="tpuctl", tpuctl_dir=str(tmp_path), **FAST
        )
        first.add_tpu_node(
            build_tpu_node(name="tpu-0"),
            agent_config=TpuAgentConfig(report_config_interval_seconds=0.1),
        )
        first.start()
        store.create(build_pod("job-a", {constants.RESOURCE_TPU: 4}, ns="ml"))
        assert wait_for(lambda: running(store, "job-a"))
        first.stop()

        # Orphan the handshake: pretend the partitioner wrote a plan id the
        # (dead) agent never acknowledged.
        store.patch_annotations(
            "Node", "tpu-0", "",
            {annot.SPEC_PARTITIONING_PLAN: "orphan-99"},
        )

        second = build_cluster(
            store=store, device_backend="tpuctl", tpuctl_dir=str(tmp_path), **FAST
        )
        second.start_agent(
            "tpu-0", agent_config=TpuAgentConfig(report_config_interval_seconds=0.1)
        )
        second.start()
        try:
            # the agent confirms the orphaned plan id...
            assert wait_for(
                lambda: store.get("Node", "tpu-0").metadata.annotations.get(
                    annot.STATUS_PARTITIONING_PLAN
                )
                == "orphan-99"
            ), store.get("Node", "tpu-0").metadata.annotations
            # ...so planning unblocks and new work still schedules
            store.create(build_pod("job-b", {constants.RESOURCE_TPU: 4}, ns="ml"))
            assert wait_for(lambda: running(store, "job-b"))
        finally:
            second.stop()
