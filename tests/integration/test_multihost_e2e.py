"""Multi-host slice e2e (VERDICT round-2 #3 / BASELINE config #5).

One pod asks ``google.com/tpu: 32`` on a v5e fleet. The expander turns it
into a 4x8 slice — a gang of 4 per-host 2x4 board slices; the planner
carves every host; GangScheduling binds the gang atomically inside one
node pool; preemption frees all 32 chips as a unit; deleting the leader
garbage-collects its workers.
"""
import time

import pytest

from nos_tpu.api.config import GpuPartitionerConfig, SchedulerConfig, TpuAgentConfig
from nos_tpu.api.v1alpha1 import constants
from nos_tpu.api.v1alpha1.elasticquota import ElasticQuota, ElasticQuotaSpec
from nos_tpu.cmd import build_cluster
from nos_tpu.controllers.partitioner.multihost import (
    MULTIHOST_TOPOLOGY_ANNOTATION,
)
from nos_tpu.kube.objects import ObjectMeta, PodPhase
from nos_tpu.scheduler.plugins.gang import GANG_NAME_LABEL, GANG_SIZE_LABEL

from tests.factory import build_pod, build_tpu_node, slice_res

CHIPS = constants.RESOURCE_TPU_CHIPS


def wait_for(predicate, timeout=15.0, interval=0.05):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if predicate():
            return True
        time.sleep(interval)
    return False


@pytest.fixture
def cluster():
    c = build_cluster(
        partitioner_config=GpuPartitionerConfig(
            batch_window_timeout_seconds=0.3, batch_window_idle_seconds=0.05
        ),
        scheduler_config=SchedulerConfig(retry_seconds=0.1),
    )
    for i in range(4):
        node = build_tpu_node(name=f"tpu-{i}")
        node.metadata.labels["cloud.google.com/gke-nodepool"] = "pool-a"
        c.add_tpu_node(
            node, agent_config=TpuAgentConfig(report_config_interval_seconds=0.1)
        )
    yield c
    c.stop()


def gang_members(store, ns="ml"):
    return [
        p
        for p in store.list("Pod", namespace=ns)
        if p.metadata.labels.get(GANG_NAME_LABEL) == "big"
    ]


class TestMultihostSlice:
    def test_oversized_request_runs_as_full_gang(self, cluster):
        cluster.start()
        # Fragment the fleet first: small jobs leave every board carved as
        # 2x2 slices, so serving the multi-host gang REQUIRES the planner
        # to re-carve each host back to a full 2x4 board.
        for i in range(4):
            cluster.store.create(
                build_pod(f"small-{i}", {constants.RESOURCE_TPU: 4}, ns="ml")
            )

        def smalls_running():
            pods = [
                p
                for p in cluster.store.list("Pod", namespace="ml")
                if p.metadata.name.startswith("small-")
            ]
            return len(pods) == 4 and all(
                p.status.phase == PodPhase.RUNNING for p in pods
            )

        assert wait_for(smalls_running)
        for i in range(4):
            cluster.store.delete("Pod", f"small-{i}", "ml")
        plans_before = cluster.partitioner.plans_applied
        cluster.store.create(build_pod("big", {constants.RESOURCE_TPU: 32}, ns="ml"))

        # Expansion: leader rewritten + 3 workers, gang size 4, 4x8 shape.
        assert wait_for(lambda: len(gang_members(cluster.store)) == 4), (
            [p.metadata.name for p in cluster.store.list("Pod", namespace="ml")]
        )
        leader = cluster.store.get("Pod", "big", "ml")
        assert leader.metadata.annotations[MULTIHOST_TOPOLOGY_ANNOTATION] == "4x8"
        assert leader.metadata.labels[GANG_SIZE_LABEL] == "4"
        request = leader.spec.containers[0].requests
        assert constants.RESOURCE_TPU not in request
        assert request[slice_res("2x4")] == 1

        # The whole gang runs, one member per host — all 32 chips bound.
        def all_running():
            members = gang_members(cluster.store)
            return len(members) == 4 and all(
                m.status.phase == PodPhase.RUNNING and m.spec.node_name
                for m in members
            )

        assert wait_for(all_running), [
            (p.metadata.name, p.status.phase, p.spec.node_name)
            for p in gang_members(cluster.store)
        ]
        nodes_used = {m.spec.node_name for m in gang_members(cluster.store)}
        assert len(nodes_used) == 4  # one board slice per host
        # Every host was carved to a full-board slice by the plan(s).
        for node_name in nodes_used:
            assert cluster.pool.geometry(node_name).get(0, {}).get("2x4", 0) == 1
        assert cluster.partitioner.plans_applied > plans_before

    def test_leader_delete_garbage_collects_workers(self, cluster):
        cluster.start()
        cluster.store.create(build_pod("big", {constants.RESOURCE_TPU: 32}, ns="ml"))
        assert wait_for(lambda: len(gang_members(cluster.store)) == 4)
        cluster.store.delete("Pod", "big", "ml")
        assert wait_for(lambda: len(cluster.store.list("Pod", namespace="ml")) == 0), (
            [p.metadata.name for p in cluster.store.list("Pod", namespace="ml")]
        )

    def test_preempting_gang_frees_all_chips(self, cluster):
        # team-a's multi-host slice borrows past its guaranteed min;
        # team-b claiming its min preempts the gang as a unit — all 32
        # chips come back together, never a stranded partial slice.
        for ns, mn in (("team-a", 0), ("team-b", 32)):
            cluster.store.create(
                ElasticQuota(
                    metadata=ObjectMeta(name=f"eq-{ns}", namespace=ns),
                    spec=ElasticQuotaSpec(min={CHIPS: mn}, max={CHIPS: 32}),
                )
            )
        cluster.start()
        cluster.store.create(
            build_pod("big", {constants.RESOURCE_TPU: 32}, ns="team-a")
        )

        def gang_running(ns):
            members = [
                p
                for p in cluster.store.list("Pod", namespace=ns)
                if p.metadata.labels.get(GANG_NAME_LABEL)
            ]
            return len(members) == 4 and all(
                m.status.phase == PodPhase.RUNNING for m in members
            )

        assert wait_for(lambda: gang_running("team-a"))

        for i in range(4):
            cluster.store.create(
                build_pod(f"claim-{i}", {constants.RESOURCE_TPU: 8}, ns="team-b")
            )

        def team_b_running():
            pods = cluster.store.list("Pod", namespace="team-b")
            return sum(
                1 for p in pods if p.status.phase == PodPhase.RUNNING
            ) == 4

        assert wait_for(team_b_running, timeout=25.0), [
            (p.metadata.name, p.status.phase)
            for p in cluster.store.list("Pod", namespace="team-b")
        ]
        # the whole gang went together (no stranded members holding chips)
        leftovers = [
            p
            for p in cluster.store.list("Pod", namespace="team-a")
            if p.status.phase == PodPhase.RUNNING
        ]
        assert leftovers == []
