"""The FULL suite over the API-backed store: every reconcile round-trips
a real HTTP apiserver (stub) — informer event ordering, merge-patch
subresource routing, binding via /binding. The closest this image gets to
a kind cluster, and the test that caught the pod-before-node event race
in round 3.
"""
import time

import pytest

from nos_tpu.api.config import GpuPartitionerConfig, SchedulerConfig, TpuAgentConfig
from nos_tpu.api.v1alpha1 import constants, labels
from nos_tpu.cmd import build_cluster
from nos_tpu.kube.apiclient import ClusterCredentials, KubeApiClient
from nos_tpu.kube.apistore import KubeApiStore
from nos_tpu.kube.objects import (
    Container,
    Node,
    NodeStatus,
    ObjectMeta,
    Pod,
    PodSpec,
)
from nos_tpu.scheduler.plugins.gang import GANG_NAME_LABEL

from tests.kube.stub_apiserver import StubApiServer


def wait_for(predicate, timeout=40.0, interval=0.1):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if predicate():
            return True
        time.sleep(interval)
    return False


def tpu_node(name, pool="pool-a"):
    alloc = {constants.RESOURCE_TPU: 8, "cpu": 64, "memory": 256}
    return Node(
        metadata=ObjectMeta(name=name, labels={
            labels.GKE_TPU_ACCELERATOR_LABEL: "tpu-v5-lite-podslice",
            labels.GKE_TPU_TOPOLOGY_LABEL: "2x4",
            labels.PARTITIONING_LABEL: "tpu",
            "cloud.google.com/gke-nodepool": pool,
        }),
        status=NodeStatus(capacity=dict(alloc), allocatable=dict(alloc)),
    )


def chip_pod(name, ns, chips):
    return Pod(
        metadata=ObjectMeta(name=name, namespace=ns),
        spec=PodSpec(
            containers=[Container(requests={constants.RESOURCE_TPU: chips})],
            scheduler_name=constants.SCHEDULER_NAME,
        ),
    )


@pytest.fixture
def api_cluster():
    with StubApiServer() as api:
        store = KubeApiStore(
            KubeApiClient(ClusterCredentials(server=api.url), timeout=5.0)
        )
        store.start(sync_timeout_s=15.0)
        cluster = build_cluster(
            store=store,
            partitioner_config=GpuPartitionerConfig(
                batch_window_timeout_seconds=0.3, batch_window_idle_seconds=0.05
            ),
            scheduler_config=SchedulerConfig(retry_seconds=0.1),
        )
        yield api, store, cluster
        cluster.stop()
        store.stop()


class TestApiBackendEndToEnd:
    def test_carve_and_schedule_over_the_wire(self, api_cluster):
        """Pending chip pod → carve → bind → Running, every step observed
        in the apiserver itself (not the local cache)."""
        api, store, cluster = api_cluster
        cluster.add_tpu_node(
            tpu_node("tpu-0"),
            agent_config=TpuAgentConfig(report_config_interval_seconds=0.1),
        )
        cluster.start()
        store.create(chip_pod("train", "ml", 4))

        def running_in_apiserver():
            wire = api.read("pods", "ml", "train")
            return (
                wire is not None
                and (wire.get("status") or {}).get("phase") == "Running"
                and (wire.get("spec") or {}).get("nodeName") == "tpu-0"
            )

        assert wait_for(running_in_apiserver), api.read("pods", "ml", "train")

        # The annotation handshake lives on the wire too. Polled, not a
        # one-shot read: the partitioner may have just written a NEWER spec
        # plan the agent's next report tick has not acked yet.
        def handshake_acked():
            ann = api.read("nodes", "", "tpu-0")["metadata"]["annotations"]
            spec_plan = ann.get("nos.nebuly.com/spec-partitioning-plan")
            return spec_plan and spec_plan == ann.get(
                "nos.nebuly.com/status-partitioning-plan"
            )

        assert wait_for(handshake_acked, timeout=10.0), api.read(
            "nodes", "", "tpu-0"
        )["metadata"]["annotations"]

    def test_multihost_gang_over_the_wire(self, api_cluster):
        """A 32-chip request expands, carves 4 hosts, and binds atomically
        — leader + workers all Running in the apiserver, with the gang's
        headless Service created."""
        api, store, cluster = api_cluster
        for i in range(4):
            cluster.add_tpu_node(
                tpu_node(f"tpu-{i}"),
                agent_config=TpuAgentConfig(report_config_interval_seconds=0.1),
            )
        cluster.start()
        store.create(chip_pod("big", "ml", 32))

        def whole_gang_running():
            wires = [
                api.read("pods", "ml", name)
                for name in ("big", "big-w1", "big-w2", "big-w3")
            ]
            return all(
                w is not None
                and (w.get("status") or {}).get("phase") == "Running"
                and (w.get("spec") or {}).get("nodeName")
                for w in wires
            )

        assert wait_for(whole_gang_running), [
            (n, api.read("pods", "ml", n) and (api.read("pods", "ml", n).get("status") or {}).get("phase"))
            for n in ("big", "big-w1", "big-w2", "big-w3")
        ]
        leader = api.read("pods", "ml", "big")
        assert leader["metadata"]["labels"][GANG_NAME_LABEL] == "big"
        assert leader["metadata"]["annotations"][
            "nos.nebuly.com/multihost-topology"
        ] == "4x8"
        nodes = {
            api.read("pods", "ml", n)["spec"]["nodeName"]
            for n in ("big", "big-w1", "big-w2", "big-w3")
        }
        assert len(nodes) == 4
        svc = api.read("services", "ml", "big")
        assert svc and svc["spec"]["clusterIP"] == "None"
