"""Acceptance for the scheduling-diagnosis PR: a pod unschedulable on a
3-node cluster for two distinct reasons gets (1) the aggregated
kube-scheduler-style condition message naming per-plugin counts, (2) a
deduped FailedScheduling Event whose count keeps bumping across retry
cycles, and (3) a working /debug/explain returning the per-node
per-plugin ledger with the journey trace id."""
import http.client
import json
import time

import pytest

from nos_tpu.api.v1alpha1 import constants
from nos_tpu.cmd import build_cluster
from nos_tpu.kube.objects import Taint
from nos_tpu.util.health import HealthServer
from nos_tpu.util.tracing import TRACER

from tests.factory import build_pod, build_tpu_node


@pytest.fixture(autouse=True)
def clean_tracer():
    TRACER.reset()
    yield
    TRACER.reset()


@pytest.fixture
def cluster():
    c = build_cluster()
    yield c
    c.stop()


def wait_for(predicate, timeout=15.0, interval=0.05):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if predicate():
            return True
        time.sleep(interval)
    return False


EXPECTED_MESSAGE = (
    "0/3 nodes are available: "
    "2 untolerated taint dedicated=infra:NoSchedule, "
    "1 node is cordoned (unschedulable)."
)


@pytest.fixture
def stuck_pod(cluster):
    """3 nodes, none schedulable: two tainted, one cordoned — two
    DISTINCT per-plugin rejection reasons for one pod."""
    for name in ("tpu-a", "tpu-b"):
        node = build_tpu_node(name=name)
        node.spec.taints.append(
            Taint(key="dedicated", value="infra", effect="NoSchedule")
        )
        cluster.add_tpu_node(node)
    cordoned = build_tpu_node(name="tpu-c")
    cordoned.spec.unschedulable = True
    cluster.add_tpu_node(cordoned)
    cluster.start()
    pod = build_pod("stuck", {constants.RESOURCE_TPU: 4}, ns="ml")
    cluster.store.create(pod)
    return pod


class TestDiagnosisEndToEnd:
    def test_condition_carries_the_aggregated_per_plugin_message(
        self, cluster, stuck_pod
    ):
        def condition_message():
            pod = cluster.store.try_get("Pod", "stuck", "ml")
            for c in pod.status.conditions:
                if c.type == "PodScheduled" and c.status == "False":
                    return c.message
            return None

        assert wait_for(lambda: condition_message() == EXPECTED_MESSAGE), (
            f"PodScheduled condition message: {condition_message()!r}"
        )

    def test_failed_scheduling_event_dedups_and_bumps_across_retries(
        self, cluster, stuck_pod
    ):
        def failed_events():
            return [
                e
                for e in cluster.store.list("Event", namespace="ml")
                if e.reason == "FailedScheduling" and e.involved_name == "stuck"
            ]

        # Retry cycles keep failing identically: ONE Event object, count
        # climbing — never a duplicate per cycle.
        assert wait_for(lambda: any(e.count >= 2 for e in failed_events())), (
            f"events: {[(e.message, e.count) for e in failed_events()]}"
        )
        events = failed_events()
        assert len(events) == 1
        assert events[0].type == "Warning"
        assert events[0].message == EXPECTED_MESSAGE
        assert events[0].source_component == "nos-scheduler"
        assert events[0].last_timestamp >= events[0].first_timestamp

    def test_debug_explain_serves_the_per_node_ledger(self, cluster, stuck_pod):
        assert wait_for(lambda: cluster.scheduler.explain("ml/stuck") is not None)
        server = HealthServer(
            port=0, metrics_token="tok", explain_fn=cluster.scheduler.explain
        )
        port = server.start()
        try:
            assert self._get(port, "/debug/explain?pod=ml/stuck")[0] == 401
            assert self._get(port, "/debug/explain", "tok")[0] == 400
            assert (
                self._get(port, "/debug/explain?pod=ml/unknown", "tok")[0] == 404
            )

            status, body = self._get(port, "/debug/explain?pod=ml/stuck", "tok")
            assert status == 200
            diagnosis = json.loads(body)
            assert diagnosis["pod"] == "ml/stuck"
            assert diagnosis["message"] == EXPECTED_MESSAGE
            nodes = diagnosis["nodes"]
            assert set(nodes) == {"tpu-a", "tpu-b", "tpu-c"}
            for name in ("tpu-a", "tpu-b"):
                assert nodes[name]["plugin"] == "TaintToleration"
                assert (
                    nodes[name]["message"]
                    == "untolerated taint dedicated=infra:NoSchedule"
                )
            assert nodes["tpu-c"]["plugin"] == "NodeUnschedulable"
            assert nodes["tpu-c"]["message"] == "node is cordoned (unschedulable)"

            # The linked trace id is the pod's (still-open) journey root:
            # the same id /debug/traces will serve once the journey ends.
            root = TRACER.journey(("pod", "ml/stuck"))
            assert root is not None
            assert diagnosis["traceId"] == root.trace_id
            assert root.attributes.get("diagnosis") == EXPECTED_MESSAGE
            assert diagnosis["timestamp"] > 0
        finally:
            server.stop()

    def test_unschedulable_metric_counts_per_plugin_rejections(
        self, cluster, stuck_pod
    ):
        from nos_tpu.util.metrics import REGISTRY

        def series():
            snap = REGISTRY.snapshot()
            return {
                k: v
                for k, v in snap.items()
                if k.startswith("nos_tpu_scheduling_unschedulable_total{")
            }

        def has_both():
            s = series()
            return any("TaintToleration" in k for k in s) and any(
                "NodeUnschedulable" in k for k in s
            )

        assert wait_for(has_both), f"series: {series()}"
        for key, value in series().items():
            if "TaintToleration" in key:
                assert 'reason="untolerated taint dedicated=infra' in key
                assert value >= 2  # two tainted nodes per failed cycle
            if "NodeUnschedulable" in key:
                assert 'reason="node is cordoned (unschedulable)"' in key

    @staticmethod
    def _get(port, path, token=None):
        conn = http.client.HTTPConnection("127.0.0.1", port, timeout=2)
        headers = {"Authorization": f"Bearer {token}"} if token else {}
        conn.request("GET", path, headers=headers)
        resp = conn.getresponse()
        return resp.status, resp.read().decode()


class TestLifecycleEvents:
    def test_scheduled_event_on_bind(self, cluster):
        cluster.add_tpu_node(build_tpu_node(name="tpu-1"))
        cluster.start()
        cluster.store.create(build_pod("ok", {constants.RESOURCE_TPU: 4}, ns="ml"))

        def scheduled_events():
            return [
                e
                for e in cluster.store.list("Event", namespace="ml")
                if e.reason == "Scheduled" and e.involved_name == "ok"
            ]

        assert wait_for(lambda: len(scheduled_events()) == 1)
        ev = scheduled_events()[0]
        assert ev.type == "Normal"
        assert "ml/ok" in ev.message and "tpu-1" in ev.message

        # The Event links back to the decision journey that emitted it:
        # the trace-id annotation matches the pod's journey root, so an
        # operator can jump from `kubectl describe` to /debug/traces.
        from nos_tpu.kube.events import TRACE_ID_ANNOTATION

        trace_id = ev.metadata.annotations.get(TRACE_ID_ANNOTATION, "")
        assert trace_id.startswith("t"), ev.metadata.annotations
        # The bind closed the journey, so it isn't open anymore — but the
        # finished trace must be resolvable in the ring buffer the
        # /debug/traces endpoint serves.
        assert wait_for(lambda: TRACER.store.get(trace_id) is not None)

    def test_failed_scheduling_event_carries_trace_annotation(
        self, cluster, stuck_pod
    ):
        from nos_tpu.kube.events import TRACE_ID_ANNOTATION

        def failed():
            return [
                e
                for e in cluster.store.list("Event", namespace="ml")
                if e.reason == "FailedScheduling" and e.involved_name == "stuck"
            ]

        # Dedup bumps must RE-stamp the annotation (latest journey wins),
        # not drop it: wait for a count >= 2 repeat and check it's there.
        assert wait_for(lambda: any(e.count >= 2 for e in failed()))
        ev = failed()[0]
        assert ev.metadata.annotations.get(TRACE_ID_ANNOTATION, "").startswith("t")
