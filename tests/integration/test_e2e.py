"""End-to-end control-loop tests over the full in-process suite.

The envtest analogue (SURVEY.md §4): all components run as real controllers
against one store; assertions wait for convergence. The core scenario is
SURVEY.md §7 step 4 / BASELINE config #1: a pending Pod requesting
``google.com/tpu: 4`` on a virgin v5e node ends up Running on a
freshly-carved 2x2 slice with the full annotation handshake completed.
"""
import time

import pytest

from nos_tpu.api.v1alpha1 import annotations as annot
from nos_tpu.api.v1alpha1 import constants, labels
from nos_tpu.api.v1alpha1.elasticquota import ElasticQuota, ElasticQuotaSpec
from nos_tpu.cmd import build_cluster
from nos_tpu.kube.objects import ObjectMeta, PodPhase

from tests.factory import build_pod, build_tpu_node, slice_res

CHIPS = constants.RESOURCE_TPU_CHIPS


@pytest.fixture
def cluster():
    c = build_cluster()
    yield c
    c.stop()


def wait_for(predicate, timeout=10.0, interval=0.05):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if predicate():
            return True
        time.sleep(interval)
    return False


def pod_running_on(store, name, ns="default"):
    def check():
        pod = store.try_get("Pod", name, ns)
        return (
            pod is not None
            and pod.status.phase == PodPhase.RUNNING
            and bool(pod.spec.node_name)
        )

    return check


class TestEndToEnd:
    def test_pending_tpu_pod_triggers_carve_and_schedules(self, cluster):
        cluster.add_tpu_node(build_tpu_node(name="tpu-1"))
        cluster.start()
        cluster.store.create(build_pod("train", {constants.RESOURCE_TPU: 4}, ns="ml"))

        assert wait_for(pod_running_on(cluster.store, "train", "ml")), (
            "pod never scheduled; node: %s"
            % cluster.store.get("Node", "tpu-1").metadata.annotations
        )
        # The slice actually exists on the (simulated) silicon.
        geometry = cluster.pool.geometry("tpu-1")
        assert geometry[0].get("2x2", 0) >= 1
        # Handshake completed: status plan == spec plan.
        node = cluster.store.get("Node", "tpu-1")
        assert (
            node.metadata.annotations[annot.STATUS_PARTITIONING_PLAN]
            == node.metadata.annotations[annot.SPEC_PARTITIONING_PLAN]
        )
        # Node advertises the slice resource.
        assert node.status.allocatable.get(slice_res("2x2"), 0) >= 1

    def test_mixed_profiles_pack_one_node(self, cluster):
        cluster.add_tpu_node(build_tpu_node(name="tpu-1"))
        cluster.start()
        cluster.store.create(build_pod("big", {constants.RESOURCE_TPU: 4}, ns="ml"))
        cluster.store.create(build_pod("small-0", {constants.RESOURCE_TPU: 1}, ns="ml"))
        cluster.store.create(build_pod("small-1", {constants.RESOURCE_TPU: 1}, ns="ml"))

        for name in ("big", "small-0", "small-1"):
            assert wait_for(pod_running_on(cluster.store, name, "ml")), f"{name} stuck"
        used_chips = 4 + 1 + 1
        assert used_chips <= 8  # all fit the single 8-chip host

    def test_second_wave_recarves_freed_capacity(self, cluster):
        cluster.add_tpu_node(build_tpu_node(name="tpu-1"))
        cluster.start()
        cluster.store.create(build_pod("wave1", {constants.RESOURCE_TPU: 8}, ns="ml"))
        assert wait_for(pod_running_on(cluster.store, "wave1", "ml"))

        # Job finishes; a differently-shaped wave arrives.
        def finish(p):
            p.status.phase = PodPhase.SUCCEEDED

        cluster.store.patch_merge("Pod", "wave1", "ml", finish)
        for i in range(2):
            cluster.store.create(
                build_pod(f"wave2-{i}", {constants.RESOURCE_TPU: 4}, ns="ml")
            )
        for i in range(2):
            assert wait_for(
                pod_running_on(cluster.store, f"wave2-{i}", "ml"), timeout=15
            ), f"wave2-{i} stuck"
        assert cluster.pool.geometry("tpu-1")[0] == {"2x2": 2}

    def test_elastic_quota_labels_flow(self, cluster):
        cluster.store.create(
            ElasticQuota(
                metadata=ObjectMeta(name="q", namespace="ml"),
                spec=ElasticQuotaSpec(min={CHIPS: 4}, max={CHIPS: 8}),
            )
        )
        # Borrowing draws from OTHER quotas' unused guaranteed min
        # (reference aggregate check): an idle namespace lends its share.
        cluster.store.create(
            ElasticQuota(
                metadata=ObjectMeta(name="idle-q", namespace="idle"),
                spec=ElasticQuotaSpec(min={CHIPS: 4}),
            )
        )
        cluster.add_tpu_node(build_tpu_node(name="tpu-1"))
        cluster.start()
        cluster.store.create(build_pod("in-q", {constants.RESOURCE_TPU: 4}, ns="ml"))
        assert wait_for(pod_running_on(cluster.store, "in-q", "ml"))
        cluster.store.create(build_pod("over-q", {constants.RESOURCE_TPU: 4}, ns="ml"))
        assert wait_for(pod_running_on(cluster.store, "over-q", "ml"))

        def labeled():
            a = cluster.store.get("Pod", "in-q", "ml").metadata.labels.get(labels.CAPACITY_LABEL)
            b = cluster.store.get("Pod", "over-q", "ml").metadata.labels.get(labels.CAPACITY_LABEL)
            return a == labels.CAPACITY_IN_QUOTA and b == labels.CAPACITY_OVER_QUOTA

        assert wait_for(labeled)
        eq = cluster.store.get("ElasticQuota", "q", "ml")
        assert eq.status.used.get(CHIPS) == 8

    def test_gang_of_two_lands_together(self, cluster):
        from nos_tpu.scheduler.plugins.gang import GANG_NAME_LABEL, GANG_SIZE_LABEL

        for i in range(2):
            cluster.add_tpu_node(build_tpu_node(name=f"tpu-{i}"))
        cluster.start()
        for i in range(2):
            pod = build_pod(f"worker-{i}", {constants.RESOURCE_TPU: 8}, ns="ml")
            pod.metadata.labels[GANG_NAME_LABEL] = "llama"
            pod.metadata.labels[GANG_SIZE_LABEL] = "2"
            cluster.store.create(pod)
        for i in range(2):
            assert wait_for(
                pod_running_on(cluster.store, f"worker-{i}", "ml"), timeout=15
            ), f"worker-{i} stuck"
        nodes = {
            cluster.store.get("Pod", f"worker-{i}", "ml").spec.node_name for i in range(2)
        }
        assert nodes == {"tpu-0", "tpu-1"}


class TestSharingEndToEnd:
    """The MPS-analogue loop: pending pod requesting an HBM fraction →
    sharing controller plans → device-plugin ConfigMap + label flip →
    sim plugin re-advertises → pod schedules → reporter mirrors state."""

    def test_pending_shared_pod_triggers_config_and_schedules(self, cluster):
        cluster.add_sharing_node(
            build_tpu_node(name="shared-1", chips=4, partitioning="sharing")
        )
        cluster.start()
        mem8 = constants.tpu_shared_resource(8)
        cluster.store.create(build_pod("infer", {mem8: 1}, ns="ml"))

        assert wait_for(pod_running_on(cluster.store, "infer", "ml"), timeout=15), (
            "pod never scheduled; node: %s"
            % cluster.store.get("Node", "shared-1").metadata.labels
        )
        node = cluster.store.get("Node", "shared-1")
        # Actuation went through the device plugin, not spec annotations.
        assert annot.SPEC_PARTITIONING_PLAN not in node.metadata.annotations
        key = node.metadata.labels[labels.TPU_DEVICE_PLUGIN_CONFIG_LABEL]
        cm = cluster.store.get("ConfigMap", cluster.device_plugin_config_map)
        assert key in cm.data
        assert node.status.allocatable.get(mem8, 0) >= 1

        # Reporter mirrors usage into status annotations.
        def reported_used():
            n = cluster.store.get("Node", "shared-1")
            _, status = annot.parse_node_annotations(n.metadata.annotations)
            return any(s.status == "used" and s.profile == "8gb" for s in status)

        assert wait_for(reported_used, timeout=10)

    def test_shared_pods_pack_multiple_chips(self, cluster):
        cluster.add_sharing_node(
            build_tpu_node(name="shared-1", chips=2, partitioning="sharing")
        )
        cluster.start()
        mem8 = constants.tpu_shared_resource(8)
        for i in range(4):  # 4 × 8gb over 2 × 16GB chips
            cluster.store.create(build_pod(f"infer-{i}", {mem8: 1}, ns="ml"))
        for i in range(4):
            assert wait_for(
                pod_running_on(cluster.store, f"infer-{i}", "ml"), timeout=20
            ), f"infer-{i} stuck"
        alloc = cluster.store.get("Node", "shared-1").status.allocatable
        assert alloc.get(mem8, 0) == 4


class TestNativeBackend:
    def test_carve_and_schedule_through_tpuctl(self, tmp_path):
        """Same end-to-end loop, but slice state lives in the native C++
        tpuctl library (flock-guarded state file + concrete chip
        placement) instead of the in-memory sim pool."""
        pytest.importorskip("ctypes")
        from nos_tpu.device.tpuctl import TpuctlUnavailableError, build_library

        try:
            build_library()
        except TpuctlUnavailableError as e:
            pytest.skip(str(e))

        c = build_cluster(device_backend="tpuctl", tpuctl_dir=str(tmp_path))
        try:
            c.add_tpu_node(build_tpu_node(name="tpu-native"))
            c.start()
            c.store.create(build_pod("train", {constants.RESOURCE_TPU: 4}, ns="ml"))
            assert wait_for(pod_running_on(c.store, "train", "ml"), timeout=15)
            # slice exists in the native state with concrete chips
            chips = c._tpuctl_client.chip_assignment("tpu-native")
            slices = {d.profile for d in c._tpuctl_client.get_slices("tpu-native")}
            assert "2x2" in slices
            assert any(len(v) == 4 for v in chips.values())
        finally:
            c.stop()
