"""Chaos: repeated whole-control-plane crashes under live load.

Goes beyond the reference (SURVEY §5: no fault-injection harness there).
The invariants a dynamic-partitioning control plane must keep through
arbitrary crash/restart points:

1. **No double-booking** — at every moment, the chips of RUNNING pods on
   a node fit its boards (checked via the sim kubelet's OutOfTpu
   admission: a violation turns a pod FAILED, and we assert none are).
2. **Convergence** — once crashes stop, every surviving pending pod is
   eventually served (the level-triggered reconcile pattern rebuilds all
   in-memory state from the store + tpuctl disk).
3. **Monotone progress** — pods that were RUNNING before a crash are
   still booked after restart (no orphaned silicon).
"""
import random
import time

from nos_tpu.api.config import GpuPartitionerConfig, SchedulerConfig, TpuAgentConfig
from nos_tpu.api.v1alpha1 import constants
from nos_tpu.cmd import build_cluster
from nos_tpu.kube.objects import PodPhase
from nos_tpu.kube.store import KubeStore

from tests.factory import build_pod, build_tpu_node

FAST = dict(
    partitioner_config=GpuPartitionerConfig(
        batch_window_timeout_seconds=0.25, batch_window_idle_seconds=0.05
    ),
    scheduler_config=SchedulerConfig(retry_seconds=0.1),
)
AGENT = TpuAgentConfig(report_config_interval_seconds=0.1)


def wait_for(predicate, timeout=25.0, interval=0.05):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if predicate():
            return True
        time.sleep(interval)
    return False


def boot(store, tmp_path, n_nodes=2):
    cluster = build_cluster(
        store=store, device_backend="tpuctl", tpuctl_dir=str(tmp_path), **FAST
    )
    for i in range(n_nodes):
        name = f"tpu-{i}"
        if store.try_get("Node", name) is None:
            cluster.add_tpu_node(build_tpu_node(name=name), agent_config=AGENT)
        else:  # restart over a surviving store: node objects persist
            cluster.start_agent(name, agent_config=AGENT)
    cluster.start()
    return cluster


class TestChaos:
    def test_survives_repeated_crashes_under_load(self, tmp_path):
        rng = random.Random(7)
        store = KubeStore()
        cluster = boot(store, tmp_path)
        submitted = 0

        def submit_wave(n):
            nonlocal submitted
            for _ in range(n):
                submitted += 1
                store.create(
                    build_pod(
                        f"job-{submitted}",
                        {constants.RESOURCE_TPU: rng.choice([1, 2, 4, 8])},
                        ns="ml",
                    )
                )

        def pods():
            return store.list("Pod", namespace="ml")

        def finish_some():
            # complete a random subset of running pods (frees slices so
            # post-crash planners must re-carve)
            for pod in pods():
                if pod.status.phase == PodPhase.RUNNING and rng.random() < 0.5:
                    def fin(p):
                        p.status.phase = PodPhase.SUCCEEDED

                    store.patch_merge("Pod", pod.metadata.name, "ml", fin)

        try:
            # Three crash cycles, each at a different point in the flow:
            # mid-fill, right after a wave lands, and mid-drain.
            for cycle in range(3):
                submit_wave(4)
                # let some (maybe all, maybe none) of the wave schedule
                time.sleep(rng.uniform(0.1, 1.0))
                cluster.stop()  # CRASH: memory dies, store+disk survive

                # Bookings at the moment of death; the restarted suite
                # must preserve every one of them (invariant 3).
                down_bookings = {
                    p.metadata.name: p.spec.node_name
                    for p in pods()
                    if p.status.phase == PodPhase.RUNNING and p.spec.node_name
                }
                cluster = boot(store, tmp_path)
                time.sleep(0.5)  # give the reborn suite room to misbehave
                for name, node_name in down_bookings.items():
                    pod = store.get("Pod", name, "ml")
                    assert pod.status.phase == PodPhase.RUNNING, (cycle, name)
                    assert pod.spec.node_name == node_name, (cycle, name)
                if cycle == 1:
                    finish_some()

            # Chaos over: demand exceeds the 16 chips, so convergence
            # means the queue DRAINS — finishing the running generation
            # must let the next pending pods bind, every round, until
            # nothing pends (a stalled round = lost capacity somewhere).
            def pending():
                return [p for p in pods() if p.status.phase == PodPhase.PENDING]

            rounds = 0
            while pending():
                rounds += 1
                assert rounds <= 20, [
                    (p.metadata.name, p.status.phase) for p in pending()
                ]
                before = len(pending())
                for pod in pods():
                    if pod.status.phase == PodPhase.RUNNING:
                        def fin(p):
                            p.status.phase = PodPhase.SUCCEEDED

                        store.patch_merge("Pod", pod.metadata.name, "ml", fin)
                assert wait_for(
                    lambda: len(pending()) < before or not pending(), timeout=20.0
                ), [(p.metadata.name, p.status.phase) for p in pending()]
            # Invariant 1: the kubelet's double-booking guard never fired.
            assert not any(p.status.phase == PodPhase.FAILED for p in pods())
            assert getattr(cluster.kubelet, "admission_rejects", 0) == 0
            # Invariant 3: every running pod kept its node through crashes.
            for pod in pods():
                if pod.status.phase == PodPhase.RUNNING:
                    assert pod.spec.node_name, pod.metadata.name
        finally:
            cluster.stop()

    def test_rapid_restart_storm_keeps_capacity_accounting(self, tmp_path):
        """Five boot/kill cycles with zero dwell: restart storms must not
        leak slice bookings on disk (each boot rebuilds from tpuctl state
        and must come to the same answer)."""
        store = KubeStore()
        cluster = boot(store, tmp_path, n_nodes=1)
        store.create(build_pod("steady", {constants.RESOURCE_TPU: 4}, ns="ml"))
        assert wait_for(
            lambda: store.get("Pod", "steady", "ml").status.phase
            == PodPhase.RUNNING
        )
        try:
            for _ in range(5):
                cluster.stop()
                cluster = boot(store, tmp_path, n_nodes=1)
            # the steady pod stays booked, and the other half of the board
            # is still usable (no leaked bookings after 5 restarts)
            assert store.get("Pod", "steady", "ml").status.phase == PodPhase.RUNNING
            store.create(build_pod("late", {constants.RESOURCE_TPU: 4}, ns="ml"))
            assert wait_for(
                lambda: store.get("Pod", "late", "ml").status.phase
                == PodPhase.RUNNING
            ), store.get("Pod", "late", "ml").status
        finally:
            cluster.stop()
