"""Preempt → checkpoint → resume: the control plane meets the training
stack (VERDICT round-2 #8).

An over-quota training job is preempted by CapacityScheduling when the
guaranteed owner claims its min; the freed board is re-carved for the
claimant; the evicted workload restores from its orbax checkpoint onto the
SMALLER slice it can still get — cross-mesh — and training continues with
identical numerics. No reference feature matches this story: nos stops at
eviction, the workload side is the TPU build's own ground.
"""
import time

import jax
import numpy as np
import pytest

from nos_tpu.api.config import GpuPartitionerConfig, SchedulerConfig, TpuAgentConfig
from nos_tpu.api.v1alpha1 import constants
from nos_tpu.api.v1alpha1.elasticquota import ElasticQuota, ElasticQuotaSpec
from nos_tpu.cmd import build_cluster
from nos_tpu.kube.objects import ObjectMeta, PodPhase
from nos_tpu.models.llama import init_llama_params, tiny_config
from nos_tpu.parallel.checkpoint import Checkpointer
from nos_tpu.parallel.mesh import mesh_from_devices
from nos_tpu.parallel.train import make_train_step

from tests.factory import build_pod, build_tpu_node

CHIPS = constants.RESOURCE_TPU_CHIPS


def wait_for(predicate, timeout=15.0, interval=0.05):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if predicate():
            return True
        time.sleep(interval)
    return False


@pytest.fixture
def cluster():
    c = build_cluster(
        partitioner_config=GpuPartitionerConfig(
            batch_window_timeout_seconds=0.3, batch_window_idle_seconds=0.05
        ),
        scheduler_config=SchedulerConfig(retry_seconds=0.1),
    )
    c.add_tpu_node(
        build_tpu_node(name="tpu-0"),
        agent_config=TpuAgentConfig(report_config_interval_seconds=0.1),
    )
    yield c
    c.stop()


class TestPreemptCheckpointResume:
    def test_full_story(self, cluster, tmp_path):
        # Quotas: the claimant owns the node's guaranteed pool; the trainer
        # owns nothing and borrows all of it (the classic elastic-quota
        # posture: researchers borrow the production team's idle chips).
        for ns, mn in (("trainer", 0), ("claimant", 8)):
            cluster.store.create(
                ElasticQuota(
                    metadata=ObjectMeta(name=f"eq-{ns}", namespace=ns),
                    spec=ElasticQuotaSpec(min={CHIPS: mn}, max={CHIPS: 8}),
                )
            )
        cluster.start()

        # ---- phase 1: the training job runs on a full 2x4 board (8 chips,
        # borrowed) and checkpoints its sharded state.
        cluster.store.create(build_pod("train", {constants.RESOURCE_TPU: 8}, ns="trainer"))

        def running(name, ns):
            pod = cluster.store.try_get("Pod", name, ns)
            return pod is not None and pod.status.phase == PodPhase.RUNNING

        assert wait_for(lambda: running("train", "trainer"))

        # The workload side: 8-"chip" mesh (virtual CPU devices stand in),
        # dp×tp training with checkpoints.
        config = tiny_config()
        tokens = jax.random.randint(jax.random.key(1), (8, 16), 0, config.vocab_size)
        mesh8 = mesh_from_devices((4, 2), ("dp", "tp"), jax.devices()[:8])
        step8, shard8 = make_train_step(mesh8, config)
        state = shard8(init_llama_params(jax.random.key(0), config), donate=True)
        losses = []
        with Checkpointer(str(tmp_path / "ckpt")) as ckpt:
            for i in range(3):
                state, loss = step8(state, tokens)
                losses.append(float(loss))
            ckpt.save(3, state, force=True)
            ckpt.wait()
            reference_params = jax.tree.map(np.asarray, state[0])

        # ---- phase 2: the claimant takes its guaranteed min; the borrowed
        # board is preempted and re-carved.
        cluster.store.create(build_pod("claim", {constants.RESOURCE_TPU: 4}, ns="claimant"))
        assert wait_for(lambda: running("claim", "claimant"), timeout=20.0), (
            cluster.store.try_get("Pod", "claim", "claimant").status
        )
        assert wait_for(
            lambda: cluster.store.try_get("Pod", "train", "trainer") is None
            or cluster.store.get("Pod", "train", "trainer").status.phase
            != PodPhase.RUNNING
        ), "over-quota trainer survived the claim"

        # ---- phase 3: the trainer resubmits at the size that still fits
        # (4 chips), lands on the re-carved half, and resumes from the
        # checkpoint on a DIFFERENT mesh (cross-mesh restore).
        cluster.store.create(
            build_pod("train-resume", {constants.RESOURCE_TPU: 4}, ns="trainer")
        )
        assert wait_for(lambda: running("train-resume", "trainer"), timeout=20.0), (
            cluster.store.try_get("Pod", "train-resume", "trainer").status
        )

        mesh4 = mesh_from_devices((2, 2), ("dp", "tp"), jax.devices()[:4])
        step4, shard4 = make_train_step(mesh4, config)
        like = shard4(init_llama_params(jax.random.key(7), config), donate=True)
        with Checkpointer(str(tmp_path / "ckpt")) as ckpt:
            assert ckpt.latest_step() == 3
            restored, step = ckpt.restore(like)
            assert step == 3
        # exact continuity: restored params equal the preempted run's
        for a, b in zip(jax.tree.leaves(restored[0]), jax.tree.leaves(reference_params)):
            np.testing.assert_array_equal(np.asarray(a), b)
        # and training actually continues on the smaller slice
        restored, loss = step4(restored, tokens)
        assert float(loss) < losses[0]
