"""Acceptance for the observability PR: one scheduled Pod produces one
trace whose root covers observe→bind with child spans for quota, every
scheduler plugin, the plan (per-trial CoW cost), actuation, and the
agent reconfig — and the trace/metrics are reachable over HTTP behind
bearer auth."""
import http.client
import json
import time

import pytest

from nos_tpu.api.v1alpha1 import constants
from nos_tpu.cmd import build_cluster
from nos_tpu.kube.objects import PodPhase
from nos_tpu.util.health import HealthServer
from nos_tpu.util.tracing import TRACER

from tests.factory import build_pod, build_tpu_node


@pytest.fixture(autouse=True)
def clean_tracer():
    TRACER.reset()
    yield
    TRACER.reset()


@pytest.fixture
def cluster():
    c = build_cluster()
    yield c
    c.stop()


def wait_for(predicate, timeout=15.0, interval=0.05):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if predicate():
            return True
        time.sleep(interval)
    return False


def find_pod_trace(pod_key):
    for trace in TRACER.store.list():
        root = trace.root
        if (
            root is not None
            and root.name == "pod.journey"
            and root.attributes.get("pod") == pod_key
        ):
            return trace
    return None


def schedule_one(cluster, name="train", ns="ml"):
    cluster.add_tpu_node(build_tpu_node(name="tpu-1"))
    cluster.start()
    cluster.store.create(build_pod(name, {constants.RESOURCE_TPU: 4}, ns=ns))

    def running():
        pod = cluster.store.try_get("Pod", name, ns)
        return pod is not None and pod.status.phase == PodPhase.RUNNING

    assert wait_for(running), f"{ns}/{name} never reached Running"
    assert wait_for(lambda: find_pod_trace(f"{ns}/{name}") is not None), (
        "no finalized pod.journey trace for the scheduled pod"
    )
    return find_pod_trace(f"{ns}/{name}")


class TestPodJourneyTrace:
    def test_single_pod_produces_full_journey_trace(self, cluster):
        trace = schedule_one(cluster)
        root = trace.root
        assert root.ended
        assert root.attributes["namespace"] == "ml"
        assert root.attributes["node"] == "tpu-1"  # stamped at bind
        assert any(e[1] == "partitioner.observed" for e in root.events)

        names = {s.name for s in trace.spans}
        required = {
            "quota.admission",
            "scheduler.cycle",
            "scheduler.filter",
            "scheduler.score",
            "scheduler.bind",
            "partitioner.process",
            "snapshot.take",
            "partitioner.plan",
            "plan.trial",
            "partitioner.actuate",
            "actuator.apply_node",
            "tpuagent.reconfig",
        }
        missing = required - names
        assert not missing, f"journey trace missing spans: {sorted(missing)}"
        # Every span belongs to the one trace rooted at pod.journey.
        assert {s.trace_id for s in trace.spans} == {root.trace_id}

    def test_each_scheduler_plugin_gets_a_child_span(self, cluster):
        trace = schedule_one(cluster)
        plugin_spans = {s.name for s in trace.spans if s.name.startswith("plugin.")}
        # The default wiring: pre-filter capacity, the vanilla filters, and
        # the nos-specific filter plugins all show up by name.
        for expected in (
            "plugin.CapacityScheduling",
            "plugin.NodeResourcesFit",
            "plugin.NodeSelector",
            "plugin.TaintToleration",
            "plugin.NodeUnschedulable",
            "plugin.MultihostIci",
            "plugin.BoardReservation",
        ):
            assert expected in plugin_spans, (
                f"{expected} not in {sorted(plugin_spans)}"
            )
        points = {
            s.attributes.get("point")
            for s in trace.spans
            if s.name.startswith("plugin.")
        }
        assert {"pre_filter", "filter"} <= points

    def test_plan_trials_carry_cow_copy_cost(self, cluster):
        trace = schedule_one(cluster)
        trials = [s for s in trace.spans if s.name == "plan.trial"]
        assert trials, "plan ran without recording carve trials"
        for trial in trials:
            assert "nodes_copied" in trial.attributes
            assert trial.attributes["nodes_copied"] >= 0
            assert "committed" in trial.attributes
        plan = next(s for s in trace.spans if s.name == "partitioner.plan")
        assert plan.attributes["totals_calls"] == (
            plan.attributes["totals_recomputes"]
            + plan.attributes["totals_incremental"]
        )

    def test_kubelet_admission_appends_after_bind(self, cluster):
        trace = schedule_one(cluster)

        def admitted_span_present():
            t = find_pod_trace("ml/train")
            return t is not None and any(
                s.name == "kubelet.admit" and s.attributes.get("admitted") is True
                for s in t.spans
            )

        # The journey ends at bind; the sim kubelet's admission span lands
        # on the already-stored trace via the scheduler's link.
        assert wait_for(admitted_span_present), (
            "kubelet.admit never appended to the stored trace: %s"
            % sorted({s.name for s in trace.spans})
        )


class TestObservabilityOverHttp:
    @staticmethod
    def _get(port, path, token=None):
        conn = http.client.HTTPConnection("127.0.0.1", port, timeout=2)
        headers = {"Authorization": f"Bearer {token}"} if token else {}
        conn.request("GET", path, headers=headers)
        resp = conn.getresponse()
        return resp.status, resp.read().decode()

    def test_trace_export_and_labeled_metrics(self, cluster):
        trace = schedule_one(cluster)
        server = HealthServer(port=0, metrics_token="tok")
        port = server.start()
        try:
            assert self._get(port, "/debug/traces")[0] == 401
            status, body = self._get(port, "/debug/traces", "tok")
            assert status == 200
            doc = json.loads(body)
            assert any(
                s["trace_id"] == trace.trace_id for s in doc["traces"]
            )
            assert sum(doc["retention"]["seen"].values()) >= 1

            status, body = self._get(
                port, f"/debug/traces?id={trace.trace_id}", "tok"
            )
            assert status == 200
            chrome = json.loads(body)
            assert chrome["otherData"]["trace_id"] == trace.trace_id
            events = chrome["traceEvents"]
            assert {e["name"] for e in events} >= {
                "pod.journey",
                "scheduler.cycle",
                "partitioner.plan",
            }
            assert all(
                {"name", "ph", "ts", "pid", "tid"} <= set(e) for e in events
            )

            status, body = self._get(port, "/metrics", "tok")
            assert status == 200
            # The agent carved a 2x2 for the 4-chip request: the slice
            # counter serves a per-profile labeled series.
            assert 'nos_tpu_slices_created_total{profile="2x2"}' in body
            assert 'nos_tpu_pods_scheduled_total{namespace="ml"}' in body
        finally:
            server.stop()
