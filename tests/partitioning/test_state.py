from nos_tpu.api.v1alpha1 import annotations as annot
from nos_tpu.kube.objects import PodPhase
from nos_tpu.kube.store import KubeStore
from nos_tpu.partitioning.core import ClusterState
from nos_tpu.partitioning.tpu import TpuNodeInitializer, TpuPartitioner

from tests.factory import build_node, build_pod, build_tpu_node


class TestClusterState:
    def test_partitioning_enabled_counting(self):
        state = ClusterState()
        assert not state.is_partitioning_enabled("tpu")
        state.update_node(build_tpu_node(name="n1"), [])
        assert state.is_partitioning_enabled("tpu")
        state.delete_node("n1")
        assert not state.is_partitioning_enabled("tpu")

    def test_update_node_replaces_pods(self):
        state = ClusterState()
        state.update_node(build_node("n1"), [build_pod("a", node="n1")])
        state.update_node(build_node("n1"), [build_pod("b", node="n1")])
        assert [p.metadata.name for p in state.get_node("n1").pods] == ["b"]

    def test_pod_usage_binding_and_unbinding(self):
        state = ClusterState()
        state.update_node(build_node("n1"), [])
        pod = build_pod("p", {"cpu": 1}, node="n1", phase=PodPhase.RUNNING)
        state.update_pod_usage(pod)
        assert [p.metadata.name for p in state.get_node("n1").pods] == ["p"]
        pod.status.phase = PodPhase.SUCCEEDED
        state.update_pod_usage(pod)
        assert state.get_node("n1").pods == []

    def test_update_pod_usage_is_idempotent(self):
        state = ClusterState()
        state.update_node(build_node("n1"), [])
        pod = build_pod("p", {"cpu": 1}, node="n1", phase=PodPhase.RUNNING)
        state.update_pod_usage(pod)
        state.update_pod_usage(pod)
        assert len(state.get_node("n1").pods) == 1

    def test_delete_pod(self):
        state = ClusterState()
        pod = build_pod("p", node="n1", phase=PodPhase.RUNNING)
        state.update_node(build_node("n1"), [pod])
        state.delete_pod(pod)
        assert state.get_node("n1").pods == []

    def test_unknown_node_pod_ignored(self):
        state = ClusterState()
        state.update_pod_usage(build_pod("p", node="ghost", phase=PodPhase.RUNNING))
        assert state.get_nodes() == {}

    def test_get_node_returns_copy(self):
        state = ClusterState()
        state.update_node(build_node("n1"), [])
        info = state.get_node("n1")
        info.node.metadata.labels["x"] = "y"
        assert "x" not in state.get_node("n1").node.metadata.labels


class TestInitializer:
    def make(self, store):
        return TpuNodeInitializer(TpuPartitioner(store), plan_id_fn=lambda: "init-1")

    def test_virgin_node_initialized_with_whole_board_slice(self):
        store = KubeStore()
        node = build_tpu_node(name="n1")
        store.create(node)
        init = self.make(store)
        assert not init.is_initialized(node)
        assert init.init_node_partitioning(node)
        updated = store.get("Node", "n1")
        spec, _ = annot.parse_node_annotations(updated.metadata.annotations)
        assert annot.spec_geometries(spec) == {0: {"2x4": 1}}
        assert updated.metadata.annotations[annot.SPEC_PARTITIONING_PLAN] == "init-1"
        assert init.is_initialized(updated)

    def test_initialized_node_untouched(self):
        store = KubeStore()
        ann = annot.status_from_devices(free={0: {"2x2": 2}}, used={})
        node = build_tpu_node(name="n1", annotations=ann)
        store.create(node)
        init = self.make(store)
        assert init.is_initialized(node)
        assert not init.init_node_partitioning(node)

    def test_non_tpu_node_ignored(self):
        store = KubeStore()
        node = build_node("plain")
        store.create(node)
        assert not self.make(store).init_node_partitioning(node)
