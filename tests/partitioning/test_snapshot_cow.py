"""CoW snapshot engine vs deepcopy oracle.

Property test: randomized fork → mutate (geometry carve, add_pod) →
revert/commit sequences applied in lockstep to the journaled
ClusterSnapshot and to DeepcopyClusterSnapshot (the pre-CoW semantics kept
as an oracle). After every fork-ending op — and at the end — the two must
be byte-for-byte equivalent on every observable: geometry, free pool,
placed pods, candidate order, and the projected PartitioningState.

Plus a plan() regression: the full planner, run on both snapshot
implementations over randomized clusters and pending-pod batches
(including gangs), must produce identical PartitioningState and identical
placements.
"""
import random

import pytest

from nos_tpu.api.v1alpha1 import annotations as annot
from nos_tpu.api.v1alpha1 import constants
from nos_tpu.partitioning.core import (
    ClusterSnapshot,
    DeepcopyClusterSnapshot,
    Planner,
    SnapshotNode,
    partitioning_state_equal,
)
from nos_tpu.scheduler.framework import Framework, NodeResourcesFit, NodeSelectorFit
from nos_tpu.tpu.node import TpuNode

from tests.factory import build_pod, build_tpu_node, slice_res

PROFILES = ["1x1", "1x2", "2x2", "2x4"]


def build_cluster(rng, snapshot_cls):
    """Deterministic cluster from `rng`'s current state — call twice with
    identically-seeded rngs to get twin clusters."""
    nodes = {}
    for i in range(rng.randint(3, 6)):
        name = f"n{i}"
        style = rng.random()
        if style < 0.4:
            annotations = None  # virgin board
        elif style < 0.7:
            annotations = annot.status_from_devices(
                free={0: {rng.choice(PROFILES): 1}}, used={}
            )
        else:
            annotations = annot.status_from_devices(
                free={0: {"2x2": 1}}, used={0: {"2x2": 1}}
            )
        node = build_tpu_node(name=name, annotations=annotations)
        nodes[name] = SnapshotNode(partitionable=TpuNode(node))
    return snapshot_cls(nodes)


def canonical(snap):
    """Full observable state, in a canonically-ordered form."""
    out = {}
    for name in sorted(snap.get_nodes()):
        node = snap.get_nodes()[name]
        out[name] = (
            sorted(
                (i, tuple(sorted(g.items())))
                for i, g in node.partitionable.geometry().items()
            ),
            tuple(sorted(node.partitionable.free_slices().items())),
            tuple(p.namespaced_name for p in node.pods),
            node.frozen,
        )
    return (
        out,
        tuple(sorted(snap.free_slice_resources().items())),
        tuple(snap.get_candidate_nodes()),
    )


def assert_equivalent(cow, oracle, context=""):
    assert canonical(cow) == canonical(oracle), context
    assert partitioning_state_equal(
        cow.partitioning_state(), oracle.partitioning_state()
    ), context


def random_lacking(rng):
    return {slice_res(rng.choice(PROFILES)): rng.randint(1, 2)}


class TestCowPropertyVsDeepcopyOracle:
    @pytest.mark.parametrize("seed", range(12))
    def test_random_fork_mutate_revert_sequences(self, seed):
        rng_ops = random.Random(seed)
        cow = build_cluster(random.Random(1000 + seed), ClusterSnapshot)
        oracle = build_cluster(random.Random(1000 + seed), DeepcopyClusterSnapshot)
        assert_equivalent(cow, oracle, f"seed={seed} initial")

        depth = 0
        pod_serial = 0
        for step in range(60):
            context = f"seed={seed} step={step}"
            roll = rng_ops.random()
            if roll < 0.2 and depth < 3:
                cow.fork()
                oracle.fork()
                depth += 1
            elif roll < 0.35 and depth > 0:
                cow.revert()
                oracle.revert()
                depth -= 1
                assert_equivalent(cow, oracle, context + " after revert")
            elif roll < 0.45 and depth > 0:
                cow.commit()
                oracle.commit()
                depth -= 1
                assert_equivalent(cow, oracle, context + " after commit")
            elif roll < 0.75:
                name = f"n{rng_ops.randint(0, 7)}"  # may not exist: both no-op
                lacking = random_lacking(rng_ops)
                assert cow.update_geometry_for(
                    name, dict(lacking)
                ) == oracle.update_geometry_for(name, dict(lacking)), context
            else:
                name = f"n{rng_ops.randint(0, 7)}"
                profile = rng_ops.choice(PROFILES)
                pod_serial += 1
                pod = build_pod(f"p{pod_serial}", {slice_res(profile): 1})
                assert cow.add_pod(name, pod) == oracle.add_pod(
                    name, pod.deepcopy()
                ), context
            # Interleave reads so caches exist when forks end.
            cow.get_lacking_slices(build_pod("probe", {slice_res("2x2"): 1}))
            oracle.get_lacking_slices(build_pod("probe", {slice_res("2x2"): 1}))

        while depth > 0:
            cow.revert()
            oracle.revert()
            depth -= 1
        assert_equivalent(cow, oracle, f"seed={seed} final")

    def test_direct_node_mutation_after_fork_is_reverted(self):
        # Legacy contract: a node obtained from get_node() AFTER fork() may
        # be mutated directly; get_node journals on access.
        cow = build_cluster(random.Random(7), ClusterSnapshot)
        oracle = build_cluster(random.Random(7), DeepcopyClusterSnapshot)
        for snap in (cow, oracle):
            snap.fork()
            node = snap.get_node("n0")
            node.partitionable.update_geometry_for({slice_res("1x1"): 4})
            node.add_pod(build_pod("direct", {slice_res("1x1"): 1}))
            snap.revert()
        assert_equivalent(cow, oracle, "after direct-mutation revert")


def make_planner():
    return Planner(Framework(filter_plugins=[NodeResourcesFit(), NodeSelectorFit()]))


def random_pending_pods(rng):
    pods = []
    for i in range(rng.randint(2, 10)):
        style = rng.random()
        if style < 0.5:
            req = {slice_res(rng.choice(PROFILES)): 1}
        elif style < 0.8:
            req = {constants.RESOURCE_TPU: rng.choice([1, 2, 4, 8])}
        else:
            req = {slice_res("1x1"): 1, "cpu": 1}
        pod = build_pod(f"pend-{i}", req, priority=rng.choice([0, 0, 0, 10]))
        if rng.random() < 0.25:
            pod.metadata.labels["nos.nebuly.com/gang"] = f"g{rng.randint(0, 1)}"
            pod.metadata.labels["nos.nebuly.com/gang-size"] = str(rng.randint(1, 3))
        pods.append(pod)
    return pods


class TestPlanOutputUnchangedVsDeepcopyBaseline:
    @pytest.mark.parametrize("seed", range(10))
    def test_plan_identical_on_random_scenarios(self, seed):
        cow = build_cluster(random.Random(2000 + seed), ClusterSnapshot)
        base = build_cluster(random.Random(2000 + seed), DeepcopyClusterSnapshot)
        pods = random_pending_pods(random.Random(3000 + seed))
        plan_cow = make_planner().plan(cow, [p.deepcopy() for p in pods])
        plan_base = make_planner().plan(base, [p.deepcopy() for p in pods])
        assert partitioning_state_equal(plan_cow, plan_base), f"seed={seed}"
        placed_cow = {
            n: [p.namespaced_name for p in node.pods]
            for n, node in cow.get_nodes().items()
        }
        placed_base = {
            n: [p.namespaced_name for p in node.pods]
            for n, node in base.get_nodes().items()
        }
        assert placed_cow == placed_base, f"seed={seed}"
        # No fork left dangling by the planner.
        assert not cow.forked and not base.forked
