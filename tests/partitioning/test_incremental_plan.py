"""Incremental replanning equivalence (ISSUE 7 tentpole).

The contract under test: a planner warm-started from a persistent base
snapshot (``plan(..., dirty=...)`` after ``refresh_node`` deltas) produces
the IDENTICAL desired PartitioningState and unserved reasons as a fresh
planner replanning the same world from scratch — across randomized delta
sequences (node fill rotations, pending-set churn, gang pairs, aged
pods), and regardless of whether the cycle ran incrementally or fell
back. Also pinned here: the fallback triggers themselves (dirty fraction
over threshold, foreign snapshot object), base-snapshot preservation
(plan() must not leak trial mutations into the base), and the auditor's
incremental-vs-from-scratch shadow check catching a poisoned result.
"""
import random

import pytest

from nos_tpu.api.v1alpha1 import annotations as annot
from nos_tpu.api.v1alpha1 import constants
from nos_tpu.partitioning.core import (
    ClusterSnapshot,
    ClusterState,
    Planner,
    SnapshotNode,
    partitioning_state_equal,
)
from nos_tpu.record.audit import InvariantAuditor
from nos_tpu.scheduler.framework import (
    Framework,
    NodeResourcesFit,
    NodeSelectorFit,
)
from nos_tpu.scheduler.plugins.gang import GANG_NAME_LABEL, GANG_SIZE_LABEL
from nos_tpu.tpu.node import TpuNode

from tests.factory import build_pod, build_tpu_node, slice_res

# Node fill styles a delta sequence rotates nodes through. Each is the
# annotation state of one 2x4 (8-chip) board.
STYLES = [
    None,  # virgin board — fully carvable
    {"free": {0: {"1x1": 2}}, "used": {0: {"2x2": 1}}},
    {"free": {0: {"1x1": 1}}, "used": {0: {"2x2": 1, "1x1": 1}}},
    {"free": {0: {"2x2": 1}}, "used": {0: {"2x2": 1}}},
    {"free": {}, "used": {0: {"2x4": 1}}},  # fully allocated
]


def build_node(name, style_idx):
    style = STYLES[style_idx % len(STYLES)]
    annotations = (
        annot.status_from_devices(free=style["free"], used=style["used"])
        if style is not None
        else None
    )
    node = build_tpu_node(name=name, annotations=annotations)
    return SnapshotNode(partitionable=TpuNode(node))


def make_snapshot(styles):
    return ClusterSnapshot(
        {name: build_node(name, idx) for name, idx in sorted(styles.items())}
    )


def make_framework():
    return Framework(filter_plugins=[NodeResourcesFit(), NodeSelectorFit()])


def random_pod(rng, i):
    profile = rng.choice(["1x1", "1x1", "1x2", "2x2", "2x4"])
    return build_pod(f"p{i}", {slice_res(profile): 1})


def gang_pair(i):
    pods = []
    for member in range(2):
        pod = build_pod(f"g{i}-{member}", {slice_res("2x2"): 1})
        pod.metadata.labels[GANG_NAME_LABEL] = f"gang{i}"
        pod.metadata.labels[GANG_SIZE_LABEL] = "2"
        pods.append(pod)
    return pods


def from_scratch(styles, pods, ages):
    """The oracle: a fresh snapshot of the same world, a fresh planner,
    legacy full-mode plan()."""
    planner = Planner(make_framework())
    desired = planner.plan(make_snapshot(styles), list(pods), pending_ages=dict(ages))
    return desired, dict(planner.last_unserved)


class TestIncrementalMatchesFromScratch:
    @pytest.mark.parametrize("seed", range(10))
    def test_randomized_delta_sequences(self, seed):
        rng = random.Random(seed)
        names = [f"n{i:02d}" for i in range(12)]
        styles = {name: rng.randrange(len(STYLES)) for name in names}
        base = make_snapshot(styles)
        planner = Planner(make_framework())

        pods = [random_pod(rng, i) for i in range(8)]
        if seed % 2:
            pods += gang_pair(seed)
        ages = {p.namespaced_name: float(rng.randrange(0, 6)) for p in pods}

        # Cold start on a persistent base: dirty=all, planner has never
        # seen this snapshot object -> fallback, base preserved.
        planner.plan(base, pods, pending_ages=dict(ages), dirty=set(names))
        assert planner.last_plan_mode == "fallback"

        for step in range(6):
            # Node deltas: rotate 1-3 nodes' fill via refresh_node.
            dirty = set()
            for name in rng.sample(names, rng.randint(1, 3)):
                styles[name] += 1
                base.refresh_node(name, build_node(name, styles[name]))
                dirty.add(name)
            # Pending churn: retire old pods, admit new ones.
            if len(pods) > 4 and rng.random() < 0.5:
                gone = pods.pop(rng.randrange(len(pods)))
                ages.pop(gone.namespaced_name, None)
            if rng.random() < 0.7:
                new = random_pod(rng, 100 * (step + 1) + seed)
                pods.append(new)
                ages[new.namespaced_name] = float(rng.randrange(0, 6))

            before_state = base.partitioning_state()
            desired = planner.plan(
                base, pods, pending_ages=dict(ages), dirty=dirty
            )
            assert planner.last_plan_mode == "incremental", f"step={step}"

            oracle_desired, oracle_unserved = from_scratch(styles, pods, ages)
            assert partitioning_state_equal(desired, oracle_desired), (
                f"seed={seed} step={step}"
            )
            assert planner.last_unserved == oracle_unserved, (
                f"seed={seed} step={step}"
            )
            # Base preservation: the plan ran inside a reverted fork, so
            # the base still shows observed state and its incrementally
            # maintained free pool matches a recompute.
            assert partitioning_state_equal(
                base.partitioning_state(), before_state
            )
            assert base.free_slice_resources() == base._compute_free_pool()
            assert not base.forked

    def test_aged_rescue_path_matches(self):
        """Ages far over the rescue threshold exercise the dedicated-carve
        pass on both sides."""
        styles = {f"n{i}": 1 for i in range(6)}
        base = make_snapshot(styles)
        planner = Planner(make_framework())
        pods = [build_pod(f"p{i}", {slice_res("1x2"): 1}) for i in range(4)]
        ages = {p.namespaced_name: 30.0 for p in pods}
        planner.plan(base, pods, pending_ages=dict(ages), dirty=set(styles))
        styles["n0"] = 0
        base.refresh_node("n0", build_node("n0", 0))
        desired = planner.plan(base, pods, pending_ages=dict(ages), dirty={"n0"})
        assert planner.last_plan_mode == "incremental"
        oracle_desired, oracle_unserved = from_scratch(styles, pods, ages)
        assert partitioning_state_equal(desired, oracle_desired)
        assert planner.last_unserved == oracle_unserved


class TestFallbackTriggers:
    def test_dirty_fraction_over_threshold_falls_back_and_matches(self):
        styles = {f"n{i}": i % len(STYLES) for i in range(8)}
        base = make_snapshot(styles)
        planner = Planner(make_framework(), incremental_dirty_threshold=0.25)
        pods = [build_pod(f"p{i}", {slice_res("1x1"): 1}) for i in range(6)]
        ages = {p.namespaced_name: 0.0 for p in pods}
        planner.plan(base, pods, pending_ages=dict(ages), dirty=set(styles))

        dirty = set()
        for name in ["n0", "n1", "n2", "n3"]:  # 50% > 25% threshold
            styles[name] += 1
            base.refresh_node(name, build_node(name, styles[name]))
            dirty.add(name)
        desired = planner.plan(base, pods, pending_ages=dict(ages), dirty=dirty)
        assert planner.last_plan_mode == "fallback"
        oracle_desired, oracle_unserved = from_scratch(styles, pods, ages)
        assert partitioning_state_equal(desired, oracle_desired)
        assert planner.last_unserved == oracle_unserved
        # Fallback is still base-preserving.
        assert base.free_slice_resources() == base._compute_free_pool()

    def test_foreign_snapshot_object_falls_back(self):
        styles = {f"n{i}": 1 for i in range(4)}
        planner = Planner(make_framework())
        pods = [build_pod("p0", {slice_res("1x1"): 1})]
        planner.plan(make_snapshot(styles), pods, dirty={"n0"})
        assert planner.last_plan_mode == "fallback"
        # Same planner, ANOTHER snapshot object: memos keyed by a foreign
        # mutation clock must not be trusted.
        desired = planner.plan(make_snapshot(styles), pods, dirty={"n0"})
        assert planner.last_plan_mode == "fallback"
        oracle_desired, _ = from_scratch(styles, pods, {})
        assert partitioning_state_equal(desired, oracle_desired)

    def test_dirty_none_is_legacy_full_mode(self):
        styles = {f"n{i}": 1 for i in range(4)}
        base = make_snapshot(styles)
        planner = Planner(make_framework())
        planner.plan(base, [build_pod("p0", {slice_res("2x4"): 1})])
        assert planner.last_plan_mode == "full"
        # Legacy mode mutates the snapshot in place (no outer fork).
        assert not base.forked


class TestAuditorShadowCheck:
    def _incremental_plan(self):
        styles = {f"n{i}": (i % 3) + 1 for i in range(6)}
        base = make_snapshot(styles)
        planner = Planner(make_framework())
        pods = [build_pod(f"p{i}", {slice_res("1x1"): 1}) for i in range(3)] + [
            build_pod("big", {slice_res("2x4"): 1})
        ]
        ages = {p.namespaced_name: 0.0 for p in pods}
        planner.plan(base, pods, pending_ages=dict(ages), dirty=set(styles))
        base.refresh_node("n0", build_node("n0", 0))
        desired = planner.plan(base, pods, pending_ages=dict(ages), dirty={"n0"})
        assert planner.last_plan_mode == "incremental"
        return planner, base, pods, desired

    def test_clean_incremental_plan_passes(self):
        planner, base, pods, desired = self._incremental_plan()
        auditor = InvariantAuditor(sample_rate=1.0)
        assert auditor.check_incremental_plan(planner, base, pods, desired) == []

    def test_poisoned_desired_state_is_caught(self):
        planner, base, pods, desired = self._incremental_plan()
        poisoned = dict(desired)
        poisoned.pop(sorted(poisoned)[0])
        auditor = InvariantAuditor(sample_rate=1.0)
        violations = auditor.check_incremental_plan(planner, base, pods, poisoned)
        assert violations and violations[0].check == "incremental_plan"

    def test_check_idles_outside_incremental_mode(self):
        styles = {f"n{i}": 1 for i in range(3)}
        base = make_snapshot(styles)
        planner = Planner(make_framework())
        pods = [build_pod("p0", {slice_res("1x1"): 1})]
        desired = planner.plan(base, pods)  # legacy full mode
        auditor = InvariantAuditor(sample_rate=1.0)
        assert auditor.check_incremental_plan(planner, base, pods, desired) == []


class TestMaintainerDrivesEquivalence:
    """Store-delta level: the controller-side maintainer refreshes the
    base from watch events and the warm-started plan still equals a
    from-scratch snapshot+plan of the live store."""

    def _store(self, n=5):
        from nos_tpu.cmd.partitioner import register_indexers
        from nos_tpu.kube.store import KubeStore

        store = KubeStore()
        register_indexers(store)
        for i in range(n):
            node = build_tpu_node(name=f"n{i}")
            node.metadata.annotations.update(
                annot.status_from_devices(
                    free={0: {"1x1": 2}}, used={0: {"2x2": 1}}
                )
            )
            store.create(node)
        return store

    def test_refresh_matches_full_rebuild(self):
        from nos_tpu.controllers.partitioner.incremental import (
            IncrementalSnapshotMaintainer,
        )
        from nos_tpu.partitioning.tpu import TpuSnapshotTaker

        store = self._store()
        taker = TpuSnapshotTaker()
        maintainer = IncrementalSnapshotMaintainer(store, taker, kind="tpu")
        state = ClusterState()
        base, dirty = maintainer.snapshot(state)
        assert dirty == set(base.get_nodes())
        assert maintainer.full_rebuilds == 1

        # Bind a pod to n2: Pod event -> dirty {n2}, refreshed in place.
        bound = build_pod("w0", {slice_res("1x1"): 1}, node="n2")
        bound.status.phase = "Running"
        store.create(bound)
        base2, dirty2 = maintainer.snapshot(state)
        assert base2 is base and dirty2 == {"n2"}
        assert maintainer.full_rebuilds == 1

        fresh = taker.take_snapshot(state, store=store)
        assert partitioning_state_equal(
            base2.partitioning_state(), fresh.partitioning_state()
        )
        assert [p.metadata.name for p in base2.get_nodes()["n2"].pods] == ["w0"]

    def test_node_delete_forces_rebuild(self):
        from nos_tpu.controllers.partitioner.incremental import (
            IncrementalSnapshotMaintainer,
        )
        from nos_tpu.partitioning.tpu import TpuSnapshotTaker

        store = self._store()
        maintainer = IncrementalSnapshotMaintainer(
            store, TpuSnapshotTaker(), kind="tpu"
        )
        state = ClusterState()
        base, _ = maintainer.snapshot(state)
        store.delete("Node", "n1")
        base2, dirty2 = maintainer.snapshot(state)
        assert base2 is not base
        assert "n1" not in base2.get_nodes()
        assert dirty2 == set(base2.get_nodes())
        assert maintainer.full_rebuilds == 2

    def _quota(self, name="q", min_tpu=8, max_tpu=8):
        from nos_tpu.api.v1alpha1.elasticquota import (
            ElasticQuota,
            ElasticQuotaSpec,
        )
        from nos_tpu.kube.objects import ObjectMeta

        return ElasticQuota(
            metadata=ObjectMeta(name=name, namespace="default"),
            spec=ElasticQuotaSpec(
                min={constants.RESOURCE_TPU: min_tpu},
                max={constants.RESOURCE_TPU: max_tpu},
            ),
        )

    def test_status_only_quota_update_preserves_base(self):
        """The quota controller bumps status.used after every bind; that
        write is planner-neutral (the snapshot holds no quota state and
        CapacityScheduling re-reads the live store) and must NOT cost
        the base — or steady state would never exist."""
        from nos_tpu.controllers.partitioner.incremental import (
            IncrementalSnapshotMaintainer,
        )
        from nos_tpu.partitioning.tpu import TpuSnapshotTaker

        store = self._store()
        store.create(self._quota())
        maintainer = IncrementalSnapshotMaintainer(
            store, TpuSnapshotTaker(), kind="tpu"
        )
        state = ClusterState()
        base, _ = maintainer.snapshot(state)

        def bump(q):
            q.status.used = {constants.RESOURCE_TPU: 4}

        store.patch_merge("ElasticQuota", "q", "default", bump)
        base2, dirty2 = maintainer.snapshot(state)
        assert base2 is base and dirty2 == set()
        assert maintainer.full_rebuilds == 1

    def test_quota_spec_change_forces_rebuild(self):
        from nos_tpu.controllers.partitioner.incremental import (
            IncrementalSnapshotMaintainer,
        )
        from nos_tpu.partitioning.tpu import TpuSnapshotTaker

        store = self._store()
        store.create(self._quota())
        maintainer = IncrementalSnapshotMaintainer(
            store, TpuSnapshotTaker(), kind="tpu"
        )
        state = ClusterState()
        base, _ = maintainer.snapshot(state)

        def shrink(q):
            q.spec.max = {constants.RESOURCE_TPU: 4}

        store.patch_merge("ElasticQuota", "q", "default", shrink)
        base2, _ = maintainer.snapshot(state)
        assert base2 is not base
        assert maintainer.full_rebuilds == 2

        # New quota appearing and quota deletion are bound changes too.
        store.create(self._quota(name="q2"))
        maintainer.snapshot(state)
        assert maintainer.full_rebuilds == 3
        store.delete("ElasticQuota", "q2", "default")
        maintainer.snapshot(state)
        assert maintainer.full_rebuilds == 4
