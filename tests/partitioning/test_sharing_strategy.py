"""Sharing-strategy tests: snapshot taker, ConfigMap actuation, plugin sim,
reporter — the MPS-path test coverage of the reference
(internal/partitioning/mps/*_test.go, gpuagent/reporter_int_test.go)."""
import json

from nos_tpu.api.v1alpha1 import annotations as annot
from nos_tpu.api.v1alpha1 import constants, labels
from nos_tpu.controllers.sharingagent import SharingReporter
from nos_tpu.device.sharing import (
    SharedSliceClient,
    SimSharedDevicePlugin,
    load_plugin_config,
)
from nos_tpu.kube.controller import Request
from nos_tpu.kube.store import KubeStore
from nos_tpu.partitioning.core.partition_state import (
    BoardPartitioning,
    NodePartitioning,
)
from nos_tpu.partitioning.core.state import ClusterState
from nos_tpu.partitioning.sharing import (
    SharingPartitioner,
    SharingSnapshotTaker,
    plugin_config_from_partitioning,
)

from tests.factory import build_pod, build_tpu_node

CM = "nos-device-plugin-config"


def mem(gb: int) -> str:
    return constants.tpu_shared_resource(gb)


def sharing_node(name="shared-0", chips=4, annotations=None):
    return build_tpu_node(
        name=name,
        chips=chips,
        annotations=annotations,
        partitioning="sharing",
    )


def node_partitioning():
    return NodePartitioning(
        boards=[
            BoardPartitioning(board_index=0, resources={mem(8): 2}),
            BoardPartitioning(board_index=1, resources={mem(16): 1}),
        ]
    )


class TestSnapshotTaker:
    def test_only_sharing_nodes(self):
        state = ClusterState()
        state.update_node(sharing_node("s0"), [])
        state.update_node(build_tpu_node(name="t0"), [])
        snapshot = SharingSnapshotTaker().take_snapshot(state)
        assert list(snapshot.get_nodes()) == ["s0"]

    def test_snapshot_speaks_shared_codec(self):
        state = ClusterState()
        annotations = annot.status_from_devices(free={0: {"8gb": 1}}, used={})
        state.update_node(sharing_node(annotations=annotations), [])
        snapshot = SharingSnapshotTaker().take_snapshot(state)
        assert snapshot.free_slice_resources() == {mem(8): 1}
        assert snapshot.tracked(mem(8))
        assert not snapshot.tracked(constants.RESOURCE_TPU)


class TestSharingPartitioner:
    def test_writes_configmap_and_flips_label(self):
        store = KubeStore()
        store.create(sharing_node())
        SharingPartitioner(store, CM).apply_partitioning(
            "shared-0", "plan-1", node_partitioning()
        )
        cm = store.get("ConfigMap", CM)
        key = "shared-0-plan-1"
        assert key in cm.data
        config = json.loads(cm.data[key])
        renames = {r["rename"]: r["replicas"] for r in config["sharing"]["resources"]}
        assert renames == {mem(8): 2, mem(16): 1}
        node = store.get("Node", "shared-0")
        assert node.metadata.labels[labels.TPU_DEVICE_PLUGIN_CONFIG_LABEL] == key

    def test_supersedes_previous_plan_key(self):
        store = KubeStore()
        store.create(sharing_node())
        p = SharingPartitioner(store, CM)
        p.apply_partitioning("shared-0", "plan-1", node_partitioning())
        p.apply_partitioning("shared-0", "plan-2", node_partitioning())
        cm = store.get("ConfigMap", CM)
        assert list(cm.data) == ["shared-0-plan-2"]

    def test_other_nodes_keys_untouched(self):
        store = KubeStore()
        store.create(sharing_node("shared-0"))
        store.create(sharing_node("shared-1"))
        p = SharingPartitioner(store, CM)
        p.apply_partitioning("shared-0", "plan-1", node_partitioning())
        p.apply_partitioning("shared-1", "plan-1", node_partitioning())
        assert len(store.get("ConfigMap", CM).data) == 2

    def test_plugin_config_rendering(self):
        config = plugin_config_from_partitioning(node_partitioning())
        assert config["sharing"]["fail_requests_greater_than_one"] is True
        entry = config["sharing"]["resources"][0]
        assert entry["name"] == constants.RESOURCE_TPU
        assert entry["memory_gb"] == 8
        assert entry["chips"] == [0]


class TestSimSharedDevicePlugin:
    def _actuated(self):
        store = KubeStore()
        store.create(sharing_node())
        SharingPartitioner(store, CM).apply_partitioning(
            "shared-0", "plan-1", node_partitioning()
        )
        SimSharedDevicePlugin(store, CM).reconcile(Request(name="shared-0"))
        return store

    def test_advertises_shared_resources(self):
        store = self._actuated()
        alloc = store.get("Node", "shared-0").status.allocatable
        assert alloc[mem(8)] == 2
        assert alloc[mem(16)] == 1
        # Chips 0 and 1 are shared; 2 remain plain out of 4.
        assert alloc[constants.RESOURCE_TPU] == 2

    def test_load_plugin_config_roundtrip(self):
        store = self._actuated()
        config = load_plugin_config(store, "shared-0", CM)
        assert config is not None and len(config["sharing"]["resources"]) == 2

    def test_missing_key_keeps_last_advertised_state(self):
        # Regression: mid-rollover (label points at a retired key) the
        # plugin must keep serving its last state, not wipe allocatable.
        store = self._actuated()
        def drop_key(cm):
            cm.data.clear()
        store.patch_merge("ConfigMap", CM, "", drop_key)
        SimSharedDevicePlugin(store, CM).reconcile(Request(name="shared-0"))
        alloc = store.get("Node", "shared-0").status.allocatable
        assert alloc[mem(8)] == 2
        assert alloc[constants.RESOURCE_TPU] == 2

    def test_prefix_named_nodes_keep_their_keys(self):
        # Regression: cleaning node "pool-1" must not delete "pool-1-a"'s
        # live config entry.
        store = KubeStore()
        store.create(sharing_node("pool-1"))
        store.create(sharing_node("pool-1-a"))
        p = SharingPartitioner(store, CM)
        p.apply_partitioning("pool-1-a", "1000-1", node_partitioning())
        p.apply_partitioning("pool-1", "1000-2", node_partitioning())
        p.apply_partitioning("pool-1", "1000-3", node_partitioning())
        assert set(store.get("ConfigMap", CM).data) == {
            "pool-1-a-1000-1",
            "pool-1-1000-3",
        }


class TestSharedSliceClientAndReporter:
    def test_devices_track_pod_usage(self):
        store = self._actuated_with_pod()
        devices = SharedSliceClient(store, CM).get_devices("shared-0")
        used = [d for d in devices if d.status == "used"]
        free = [d for d in devices if d.status == "free"]
        assert len(used) == 1 and used[0].profile == "8gb"
        assert len(free) == 2

    def test_reporter_writes_status_annotations(self):
        store = self._actuated_with_pod()
        reporter = SharingReporter(
            store, SharedSliceClient(store, CM), "shared-0", 10.0
        )
        reporter.reconcile(Request(name="shared-0"))
        node = store.get("Node", "shared-0")
        _, status = annot.parse_node_annotations(node.metadata.annotations)
        by_key = {(s.board_index, s.profile, s.status): s.quantity for s in status}
        assert by_key[(0, "8gb", "used")] == 1
        assert by_key[(0, "8gb", "free")] == 1
        assert by_key[(1, "16gb", "free")] == 1

    def test_reporter_refuses_tpu_mode_node(self):
        store = KubeStore()
        store.create(build_tpu_node(name="t0"))
        reporter = SharingReporter(store, SharedSliceClient(store, CM), "t0", 10.0)
        reporter.reconcile(Request(name="t0"))
        node = store.get("Node", "t0")
        _, status = annot.parse_node_annotations(node.metadata.annotations)
        assert status == []

    @staticmethod
    def _actuated_with_pod():
        store = KubeStore()
        store.create(sharing_node())
        SharingPartitioner(store, CM).apply_partitioning(
            "shared-0", "plan-1", node_partitioning()
        )
        store.create(
            build_pod("user", {mem(8): 1}, ns="ml", node="shared-0", phase="Running")
        )
        return store
