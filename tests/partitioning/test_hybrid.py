"""Hybrid partitioning mode: one node, both passes.

The reference declares PartitioningKindHybrid (pkg/gpu/partitioning.go:91)
but neither the MIG nor MPS snapshot taker picks hybrid nodes up; here a
hybrid node genuinely splits its chips between the slice-carving pass and
the sharing pass (the highest-indexed ``nos.nebuly.com/shared-chips`` chips
share, the rest carve into boards).
"""
import time

import pytest

from nos_tpu.api.v1alpha1 import constants, labels
from nos_tpu.partitioning.core.state import ClusterState
from nos_tpu.partitioning.sharing import SharingSnapshotTaker
from nos_tpu.partitioning.tpu import TpuSnapshotTaker
from nos_tpu.tpu.node import TpuNode
from nos_tpu.tpu.sharing import SharingNode

from tests.factory import build_pod, build_tpu_node


def build_hybrid_node(name="hyb-1", chips=8, shared=4):
    node = build_tpu_node(name=name, chips=chips, partitioning="hybrid")
    node.metadata.labels[labels.SHARED_CHIPS_LABEL] = str(shared)
    return node


class TestKindHelpers:
    def test_hybrid_is_valid_kind(self):
        node = build_hybrid_node()
        assert labels.partitioning_kind(node) == labels.PartitioningKind.HYBRID

    def test_hybrid_matches_both_passes(self):
        node = build_hybrid_node()
        assert labels.is_tpu_partitioning_enabled(node)
        assert labels.is_sharing_partitioning_enabled(node)
        assert labels.kind_matches(node, labels.PartitioningKind.TPU)
        assert labels.kind_matches(node, labels.PartitioningKind.SHARING)
        assert not labels.kind_matches(node, labels.PartitioningKind.MIG)

    def test_exact_kinds_do_not_cross_match(self):
        tpu = build_tpu_node(partitioning="tpu")
        assert labels.is_tpu_partitioning_enabled(tpu)
        assert not labels.is_sharing_partitioning_enabled(tpu)

    def test_shared_chip_count_split(self):
        assert labels.shared_chip_count(build_hybrid_node(shared=4), 8) == 4
        # Clamped to the physical inventory.
        assert labels.shared_chip_count(build_hybrid_node(shared=99), 8) == 8
        # Unlabeled hybrid defaults to no sharing pool.
        node = build_tpu_node(partitioning="hybrid")
        assert labels.shared_chip_count(node, 8) == 0
        # Garbage label value is ignored, not fatal.
        node.metadata.labels[labels.SHARED_CHIPS_LABEL] = "many"
        assert labels.shared_chip_count(node, 8) == 0
        # Pure kinds: all or nothing.
        assert labels.shared_chip_count(build_tpu_node(partitioning="sharing"), 8) == 8
        assert labels.shared_chip_count(build_tpu_node(partitioning="tpu"), 8) == 0


class TestHybridNodeModels:
    def test_tpu_node_only_models_slicing_chips(self):
        node = build_hybrid_node(chips=8, shared=4)
        tpu_node = TpuNode(node)
        assert tpu_node.is_tpu_node
        assert sum(b.chips for b in tpu_node.boards) == 4

    def test_sharing_node_models_offset_chips(self):
        node = build_hybrid_node(chips=8, shared=4)
        sharing_node = SharingNode(node)
        assert sharing_node.is_sharing_node
        assert [c.index for c in sharing_node.chips] == [4, 5, 6, 7]

    def test_pools_cover_inventory_without_overlap(self):
        node = build_hybrid_node(chips=8, shared=4)
        tpu_chips = sum(b.chips for b in TpuNode(node).boards)
        share_chips = len(SharingNode(node).chips)
        assert tpu_chips + share_chips == 8

    def test_sharing_status_annotation_outside_pool_marks_inconsistent(self):
        from nos_tpu.api.v1alpha1 import annotations as annot

        node = build_hybrid_node(chips=8, shared=4)
        # Chip 0 belongs to the slicing pool; a sharing status entry there
        # is stale agent state the planner must refuse to model.
        entry = annot.StatusAnnotation(board_index=0, profile="8gb", status=annot.STATUS_FREE, quantity=1)
        node.metadata.annotations[entry.key] = "1"
        sharing_node = SharingNode(node)
        assert not sharing_node.consistent
        assert not sharing_node.has_free_capacity()


class TestHybridSnapshots:
    def test_both_takers_include_hybrid_node(self):
        state = ClusterState()
        state.update_node(build_hybrid_node(chips=8, shared=4), [])
        assert "hyb-1" in TpuSnapshotTaker().take_snapshot(state).get_nodes()
        assert "hyb-1" in SharingSnapshotTaker().take_snapshot(state).get_nodes()

    def test_state_enables_both_kinds(self):
        state = ClusterState()
        state.update_node(build_hybrid_node(), [])
        assert state.is_partitioning_enabled(labels.PartitioningKind.TPU)
        assert state.is_partitioning_enabled(labels.PartitioningKind.SHARING)
        assert not state.is_partitioning_enabled(labels.PartitioningKind.MIG)
        state.delete_node("hyb-1")
        assert not state.is_partitioning_enabled(labels.PartitioningKind.TPU)


class TestHybridEndToEnd:
    def wait_for(self, predicate, timeout=20.0, interval=0.05):
        deadline = time.monotonic() + timeout
        while time.monotonic() < deadline:
            if predicate():
                return True
            time.sleep(interval)
        return False

    @pytest.fixture
    def cluster(self):
        from nos_tpu.cmd import build_cluster

        c = build_cluster()
        yield c
        c.stop()

    def test_hybrid_node_serves_slice_and_shared_pods(self, cluster):
        from nos_tpu.kube.objects import PodPhase

        cluster.add_hybrid_node(build_hybrid_node(chips=8, shared=4))
        cluster.start()
        mem8 = constants.tpu_shared_resource(8)
        cluster.store.create(build_pod("train", {constants.RESOURCE_TPU: 4}, ns="ml"))
        cluster.store.create(build_pod("infer", {mem8: 1}, ns="ml"))

        def running(name):
            def check():
                pod = cluster.store.try_get("Pod", name, "ml")
                return (
                    pod is not None
                    and pod.status.phase == PodPhase.RUNNING
                    and pod.spec.node_name == "hyb-1"
                )

            return check

        assert self.wait_for(running("train")), (
            "slice pod stuck; node: %s"
            % cluster.store.get("Node", "hyb-1").metadata.annotations
        )
        assert self.wait_for(running("infer")), (
            "shared pod stuck; node labels: %s alloc: %s"
            % (
                cluster.store.get("Node", "hyb-1").metadata.labels,
                cluster.store.get("Node", "hyb-1").status.allocatable,
            )
        )
        alloc = cluster.store.get("Node", "hyb-1").status.allocatable
        # Hybrid nodes never advertise plain chips.
        assert alloc.get(constants.RESOURCE_TPU, 0) == 0
