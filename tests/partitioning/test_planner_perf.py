"""Perf smoke for the CoW planner (slow tier; tier-1 runs -m 'not slow').

Guards the headline of the copy-on-write snapshot engine: a 64-node /
200-pending-pod plan() — the BENCH_planner.json reference config, ~90ms
p50 on a dev box — must stay well under a generous wall-clock bound even
on loaded CI. The deepcopy baseline at this scale is ~0.9s/plan, so the
bound also catches an accidental return to O(cluster) forking.
"""
import time

import pytest

from bench_planner import make_cluster, make_pending
from nos_tpu.partitioning.core import ClusterSnapshot, Planner
from nos_tpu.scheduler.framework import Framework, NodeResourcesFit, NodeSelectorFit

PLAN_BOUND_SECONDS = 30.0


@pytest.mark.slow
def test_plan_64_nodes_200_pods_within_bound():
    planner = Planner(Framework(filter_plugins=[NodeResourcesFit(), NodeSelectorFit()]))
    # Warm parse/profile caches so the bound measures plan(), not imports.
    planner.plan(make_cluster(8, ClusterSnapshot), make_pending(10))

    snapshot = make_cluster(64, ClusterSnapshot)
    pods = make_pending(200)
    started = time.perf_counter()
    plan = planner.plan(snapshot, pods)
    elapsed = time.perf_counter() - started

    assert elapsed < PLAN_BOUND_SECONDS, f"plan() took {elapsed:.2f}s"
    assert plan is not None
    assert not snapshot.forked


@pytest.mark.slow
def test_verdict_cache_hit_rate_floor():
    """The equivalence-class verdict cache carries the simulation-path
    speedup, and its value is all in the hit rate: the 64x200 reference
    config measures ~0.86 (BENCH_planner.json). A drop below the floor
    means the key fragmented (a signature field that varies per trial) or
    invalidation went too wide (version stamps on untouched nodes)."""
    planner = Planner(Framework(filter_plugins=[NodeResourcesFit(), NodeSelectorFit()]))
    planner.plan(make_cluster(8, ClusterSnapshot), make_pending(10))  # warm-up

    snapshot = make_cluster(64, ClusterSnapshot)
    planner.plan(snapshot, make_pending(200))
    hits, misses, bypasses = planner.verdict_cache_stats()

    assert hits + misses > 0, "no cache-eligible trials — workload broke?"
    assert bypasses == 0, "plain bench pods must never bypass the cache"
    rate = hits / (hits + misses)
    assert rate >= 0.75, f"verdict-cache hit rate {rate:.3f} below the 0.75 floor"


@pytest.mark.slow
def test_incremental_replan_floor_1024_nodes():
    """The warm-start headline (ISSUE 7): at 1024 nodes / 800 pending with
    ≤5% of nodes dirtied per cycle, a steady-state incremental replan runs
    ~34ms p50 on a dev box against a ~107ms cold plan (BENCH_planner.json).
    Two floors guard it: a generous absolute wall-clock bound for loaded
    CI, and a relative one — replanning must stay at least 2x faster than
    the cold fallback plan, or cross-cycle cache retention has quietly
    stopped working (every cycle would pay from-scratch cost again)."""
    import statistics

    from bench_planner import build_steady_node, make_steady_cluster, make_steady_pending

    REPLAN_BOUND_SECONDS = 10.0

    planner = Planner(Framework(filter_plugins=[NodeResourcesFit(), NodeSelectorFit()]))
    snapshot = make_steady_cluster(1024)
    pods = make_steady_pending(800)

    started = time.perf_counter()
    planner.plan(snapshot, pods, dirty=set(snapshot.get_nodes()))
    cold = time.perf_counter() - started
    assert planner.last_plan_mode == "fallback"  # cold start on a new base

    dirty_per_cycle = 51  # 5% of 1024
    variant = {}
    samples = []
    for cycle in range(6):
        dirty = set()
        for j in range(dirty_per_cycle):
            name = f"node-{(cycle * dirty_per_cycle + j) % 1024:05d}"
            variant[name] = not variant.get(name, False)
            snapshot.refresh_node(name, build_steady_node(name, variant[name]))
            dirty.add(name)
        started = time.perf_counter()
        planner.plan(snapshot, pods, dirty=dirty)
        elapsed = time.perf_counter() - started
        assert planner.last_plan_mode == "incremental"
        if cycle > 0:  # first warm cycle still fills cross-cycle memos
            samples.append(elapsed)

    p50 = statistics.median(samples)
    assert p50 < REPLAN_BOUND_SECONDS, f"incremental replan p50 {p50:.3f}s"
    assert p50 * 2 < cold, (
        f"replan p50 {p50 * 1000:.1f}ms is not ≥2x faster than the cold plan "
        f"{cold * 1000:.1f}ms — cross-cycle cache retention has regressed"
    )
    assert not snapshot.forked


@pytest.mark.slow
def test_sharded_replan_floor_1024_nodes_8_pools():
    """The pool-sharded headline (ISSUE 13) at test scale: 1024 nodes in
    8 selector-pinned pools, 800 pending, 5% churn — the whole sharded
    cycle (per-pool incremental replans + cross-pool merge + invariant
    check) must stay under a generous wall bound, retain cross-cycle
    caches (≥2x faster than the sharded cold plan), and keep the merge
    overhead a small fraction of the cycle. bench_sharded itself raises
    if any pool leaves incremental mode or the merge invariants fail."""
    from bench_planner import bench_sharded

    row = bench_sharded(1024, 800, repeats=4, pools=8, parallelism="serial")
    assert row["p50_replan_ms"] < 10_000, row
    assert row["p50_replan_ms"] * 2 < row["cold_plan_ms"], (
        f"sharded replan p50 {row['p50_replan_ms']}ms is not ≥2x faster "
        f"than the sharded cold plan {row['cold_plan_ms']}ms — per-pool "
        f"cache retention has regressed"
    )
    assert row["p50_merge_ms"] < row["p50_replan_ms"], (
        "cross-pool merge dominates the sharded cycle"
    )


@pytest.mark.slow
def test_forecast_overhead_within_budget_1024_nodes():
    """The placement forecaster's acceptance budget: forecasting must add
    <=2% to the steady-state incremental replan p50 at the 1024x800
    config. By construction the forecaster owns its OWN planner and its
    own snapshot maintainer, so the only thing it adds to the control
    loop is notify_cycle() (stash the batch, wake the thread); the
    forecast itself runs off-path — here synchronously between replan
    cycles, where the background thread runs in production. The guard
    interleaves baseline and forecasted cycles over one churn stream and
    compares replan p50s."""
    import gc
    import statistics

    from bench_planner import build_steady_node, make_steady_cluster, make_steady_pending
    from nos_tpu.forecast import PlacementForecaster
    from nos_tpu.partitioning.core import ClusterState
    from nos_tpu.partitioning.tpu import TpuSnapshotTaker

    from tests.forecast.helpers import carved_node, gang_pod, make_planner, make_store

    planner = Planner(Framework(filter_plugins=[NodeResourcesFit(), NodeSelectorFit()]))
    snapshot = make_steady_cluster(1024)
    pods = make_steady_pending(800)
    planner.plan(snapshot, pods, dirty=set(snapshot.get_nodes()))  # cold start

    # The forecaster's own world: a small store-backed cluster with a
    # pending gang queue, the shape every partitioner cycle hands it.
    store = make_store()
    for i in range(4):
        store.create(carved_node(f"fc{i}", free={0: {"2x2": 2}}))
    queue = [gang_pod(f"q{i}-{k}", gang=f"q{i}", size=2) for i in range(3) for k in range(2)]
    for pod in queue:
        store.create(pod)
    forecaster = PlacementForecaster(
        store, ClusterState(), make_planner(store), TpuSnapshotTaker()
    )
    assert forecaster.engine.planner is not planner  # isolation is structural

    # Interleave baseline and forecasted cycles over the SAME churn
    # stream: alternating cycles see the same cache state and allocator
    # pressure, so the medians differ only by what forecasting adds.
    variant = {}
    dirty_per_cycle = 51  # 5% of 1024
    base_samples, fore_samples = [], []
    for cycle in range(22):
        dirty = set()
        for j in range(dirty_per_cycle):
            name = f"node-{(cycle * dirty_per_cycle + j) % 1024:05d}"
            variant[name] = not variant.get(name, False)
            snapshot.refresh_node(name, build_steady_node(name, variant[name]))
            dirty.add(name)
        with_forecast = cycle % 2 == 1
        # Collect outside the timed window so GC triggered by the
        # off-path forecast's garbage can't land inside a timed replan.
        gc.collect()
        started = time.perf_counter()
        if with_forecast:
            forecaster.notify_cycle(pods, now=float(cycle))
        planner.plan(snapshot, pods, dirty=dirty)
        elapsed = time.perf_counter() - started
        assert planner.last_plan_mode == "incremental"
        if cycle >= 2:  # first cycles still fill cross-cycle memos
            (fore_samples if with_forecast else base_samples).append(elapsed)
        if with_forecast:
            forecaster.run_once(now=float(cycle), pending=queue)

    baseline = statistics.median(base_samples)
    forecasted = statistics.median(fore_samples)
    assert forecaster.runs >= 5

    assert forecasted <= baseline * 1.02, (
        f"replan p50 with forecasting {forecasted * 1000:.1f}ms exceeds the "
        f"2% budget over the baseline {baseline * 1000:.1f}ms — the "
        f"forecaster has leaked work onto the plan path"
    )
    assert not snapshot.forked


@pytest.mark.slow
def test_tracing_overhead_within_allowance():
    """The planner is instrumented (a span per carve trial, suppressed
    plugin spans in simulation). With TRACER.enabled=False those calls are
    shared no-ops — that run is the baseline — and turning tracing on must
    stay within a modest allowance of it. Median-of-5 on the 64-node
    config keeps CI noise below the 15% bar."""
    import statistics

    from nos_tpu.util.tracing import TRACER

    planner = Planner(Framework(filter_plugins=[NodeResourcesFit(), NodeSelectorFit()]))
    planner.plan(make_cluster(8, ClusterSnapshot), make_pending(10))  # warm-up

    def timed_runs(runs=5):
        samples = []
        for _ in range(runs):
            snapshot = make_cluster(64, ClusterSnapshot)
            pods = make_pending(200)
            started = time.perf_counter()
            planner.plan(snapshot, pods)
            samples.append(time.perf_counter() - started)
        return statistics.median(samples)

    TRACER.reset()
    enabled_prev = TRACER.enabled
    try:
        TRACER.enabled = False
        baseline = timed_runs()
        TRACER.enabled = True
        traced = timed_runs()
    finally:
        TRACER.enabled = enabled_prev
        TRACER.reset()

    assert baseline < PLAN_BOUND_SECONDS
    assert traced < PLAN_BOUND_SECONDS
    overhead = (traced / baseline) - 1.0 if baseline else 0.0
    assert overhead < 0.15, (
        f"traced plan() {traced:.3f}s is {overhead:.1%} over the disabled "
        f"baseline {baseline:.3f}s — per-trial span cost has grown"
    )


@pytest.mark.slow
def test_profiler_overhead_within_allowance():
    """The always-on sampling profiler's acceptance budget: at the default
    100 Hz rate its measured duty cycle (sampler busy / wall enabled) must
    stay <= 2% while real plan() work runs on a registered thread, and a
    wall-clock comparison against a profiler-off baseline must stay within
    the same allowance band the tracing guard uses."""
    import statistics

    from nos_tpu.util.profiling import StackProfiler

    planner = Planner(Framework(filter_plugins=[NodeResourcesFit(), NodeSelectorFit()]))
    planner.plan(make_cluster(8, ClusterSnapshot), make_pending(10))  # warm-up

    def timed_runs(runs=5):
        samples = []
        for _ in range(runs):
            snapshot = make_cluster(64, ClusterSnapshot)
            pods = make_pending(200)
            started = time.perf_counter()
            planner.plan(snapshot, pods)
            samples.append(time.perf_counter() - started)
        return statistics.median(samples)

    baseline = timed_runs()

    prof = StackProfiler()  # default interval: 100 Hz
    prof.register_thread(name="perf-guard")
    prof.start()
    try:
        profiled = timed_runs()
    finally:
        prof.stop()
        prof.unregister_thread()

    assert prof.total_samples > 0, "sampler never saw the registered thread"
    duty = prof.overhead_fraction()
    assert duty <= 0.02, (
        f"profiler duty cycle {duty:.2%} exceeds the 2% budget at the "
        f"default rate — sample_once has grown too expensive"
    )
    assert baseline < PLAN_BOUND_SECONDS
    assert profiled < PLAN_BOUND_SECONDS
    overhead = (profiled / baseline) - 1.0 if baseline else 0.0
    assert overhead < 0.15, (
        f"profiled plan() {profiled:.3f}s is {overhead:.1%} over the "
        f"profiler-off baseline {baseline:.3f}s"
    )
