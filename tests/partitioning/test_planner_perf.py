"""Perf smoke for the CoW planner (slow tier; tier-1 runs -m 'not slow').

Guards the headline of the copy-on-write snapshot engine: a 64-node /
200-pending-pod plan() — the BENCH_planner.json reference config, ~90ms
p50 on a dev box — must stay well under a generous wall-clock bound even
on loaded CI. The deepcopy baseline at this scale is ~0.9s/plan, so the
bound also catches an accidental return to O(cluster) forking.
"""
import time

import pytest

from bench_planner import make_cluster, make_pending
from nos_tpu.partitioning.core import ClusterSnapshot, Planner
from nos_tpu.scheduler.framework import Framework, NodeResourcesFit, NodeSelectorFit

PLAN_BOUND_SECONDS = 30.0


@pytest.mark.slow
def test_plan_64_nodes_200_pods_within_bound():
    planner = Planner(Framework(filter_plugins=[NodeResourcesFit(), NodeSelectorFit()]))
    # Warm parse/profile caches so the bound measures plan(), not imports.
    planner.plan(make_cluster(8, ClusterSnapshot), make_pending(10))

    snapshot = make_cluster(64, ClusterSnapshot)
    pods = make_pending(200)
    started = time.perf_counter()
    plan = planner.plan(snapshot, pods)
    elapsed = time.perf_counter() - started

    assert elapsed < PLAN_BOUND_SECONDS, f"plan() took {elapsed:.2f}s"
    assert plan is not None
    assert not snapshot.forked
