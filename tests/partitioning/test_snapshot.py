import pytest

from nos_tpu.api.v1alpha1 import annotations as annot
from nos_tpu.api.v1alpha1 import constants
from nos_tpu.partitioning.core import ClusterSnapshot, ClusterState, SliceTracker, SnapshotNode
from nos_tpu.partitioning.tpu import TpuSnapshotTaker
from nos_tpu.tpu.node import TpuNode

from tests.factory import build_pod, build_tpu_node, build_node, slice_res


def snapshot_of(*nodes, pods_by_node=None):
    pods_by_node = pods_by_node or {}
    out = {}
    for n in nodes:
        t = TpuNode(n)
        out[n.metadata.name] = SnapshotNode(
            partitionable=t, pods=pods_by_node.get(n.metadata.name, [])
        )
    return ClusterSnapshot(out)


class TestForkCommitRevert:
    def test_revert_restores_state(self):
        snap = snapshot_of(build_tpu_node(name="n1"))
        snap.fork()
        node = snap.get_node("n1")
        assert node.partitionable.update_geometry_for({slice_res("2x2"): 2})
        snap.revert()
        assert snap.get_node("n1").partitionable.geometry() == {0: {}}

    def test_commit_keeps_state(self):
        snap = snapshot_of(build_tpu_node(name="n1"))
        snap.fork()
        snap.get_node("n1").partitionable.update_geometry_for({slice_res("2x2"): 2})
        snap.commit()
        assert snap.get_node("n1").partitionable.geometry() == {0: {"2x2": 2}}

    def test_nested_fork_revert_restores_each_level(self):
        # Forks nest (the gang trial wraps a whole plan pass in an outer
        # fork): inner revert restores the inner fork point, outer revert
        # restores the pristine state — including inner COMMITTED work.
        snap = snapshot_of(build_tpu_node(name="n1"))
        snap.fork()
        assert snap.update_geometry_for("n1", {slice_res("2x4"): 1})
        snap.fork()
        assert snap.update_geometry_for("n1", {slice_res("2x2"): 2})
        snap.revert()
        assert snap.get_node("n1").partitionable.geometry() == {0: {"2x4": 1}}
        snap.fork()
        assert snap.update_geometry_for("n1", {slice_res("2x2"): 2})
        snap.commit()
        assert snap.get_node("n1").partitionable.geometry() == {0: {"2x2": 2}}
        snap.revert()
        assert snap.get_node("n1").partitionable.geometry() == {0: {}}

    def test_revert_without_fork_raises(self):
        snap = snapshot_of(build_tpu_node(name="n1"))
        with pytest.raises(RuntimeError):
            snap.revert()

    def test_commit_without_fork_raises(self):
        snap = snapshot_of(build_tpu_node(name="n1"))
        with pytest.raises(RuntimeError):
            snap.commit()

    def test_free_pool_tracks_fork_lifecycle(self):
        # The incremental free pool must match a from-scratch recompute
        # across carve → revert and carve → commit.
        snap = snapshot_of(build_tpu_node(name="n1"))
        assert snap.free_slice_resources() == {}
        snap.fork()
        assert snap.update_geometry_for("n1", {slice_res("2x2"): 2})
        assert snap.free_slice_resources() == {slice_res("2x2"): 2}
        snap.revert()
        assert snap.free_slice_resources() == {}
        snap.fork()
        assert snap.update_geometry_for("n1", {slice_res("2x2"): 2})
        snap.commit()
        assert snap.free_slice_resources() == {slice_res("2x2"): 2}
        pod = build_pod("p", {slice_res("2x2"): 1})
        assert snap.add_pod("n1", pod)
        assert snap.free_slice_resources() == {slice_res("2x2"): 1}


class TestLackingSlices:
    def test_lacking_when_cluster_empty(self):
        snap = snapshot_of(build_tpu_node(name="n1"))
        pod = build_pod("p", {slice_res("2x2"): 1})
        assert snap.get_lacking_slices(pod) == {slice_res("2x2"): 1}

    def test_no_lacking_when_free_exists(self):
        ann = annot.status_from_devices(free={0: {"2x2": 1}}, used={})
        snap = snapshot_of(build_tpu_node(name="n1", annotations=ann))
        pod = build_pod("p", {slice_res("2x2"): 1})
        assert snap.get_lacking_slices(pod) == {}

    def test_partial_lack(self):
        ann = annot.status_from_devices(free={0: {"1x1": 1}}, used={})
        snap = snapshot_of(build_tpu_node(name="n1", annotations=ann))
        pod = build_pod("p", {slice_res("1x1"): 3})
        assert snap.get_lacking_slices(pod) == {slice_res("1x1"): 2}

    def test_plain_chip_request_stays_plain_when_uncovered(self):
        # The serving profile depends on which node gets carved, so the
        # cluster-level lack is expressed in chips.
        snap = snapshot_of(build_tpu_node(name="n1"))
        pod = build_pod("p", {constants.RESOURCE_TPU: 4})
        assert snap.get_lacking_slices(pod) == {constants.RESOURCE_TPU: 4}

    def test_plain_chip_request_covered_by_matching_free_profile(self):
        ann = annot.status_from_devices(free={0: {"2x2": 1}}, used={})
        snap = snapshot_of(build_tpu_node(name="n1", annotations=ann))
        pod = build_pod("p", {constants.RESOURCE_TPU: 4})
        assert snap.get_lacking_slices(pod) == {}

    def test_free_on_other_node_counts(self):
        ann = annot.status_from_devices(free={0: {"2x2": 1}}, used={})
        snap = snapshot_of(
            build_tpu_node(name="n1"),
            build_tpu_node(name="n2", annotations=ann),
        )
        pod = build_pod("p", {slice_res("2x2"): 1})
        assert snap.get_lacking_slices(pod) == {}


class TestCandidates:
    def test_sorted_by_name(self):
        snap = snapshot_of(build_tpu_node(name="b"), build_tpu_node(name="a"))
        assert snap.get_candidate_nodes() == ["a", "b"]

    def test_fully_used_node_excluded(self):
        ann = annot.status_from_devices(free={}, used={0: {"2x4": 1}})
        snap = snapshot_of(
            build_tpu_node(name="full", annotations=ann),
            build_tpu_node(name="virgin"),
        )
        assert snap.get_candidate_nodes() == ["virgin"]


class TestSnapshotTaker:
    def test_only_tpu_labeled_nodes(self):
        state = ClusterState()
        state.update_node(build_tpu_node(name="tpu1"), [])
        state.update_node(build_tpu_node(name="mig1", partitioning="mig"), [])
        state.update_node(build_node(name="plain"), [])
        snap = TpuSnapshotTaker().take_snapshot(state)
        assert list(snap.get_nodes()) == ["tpu1"]

    def test_pods_carried_into_snapshot(self):
        state = ClusterState()
        pod = build_pod("p", {"cpu": 1}, node="tpu1")
        state.update_node(build_tpu_node(name="tpu1"), [pod])
        snap = TpuSnapshotTaker().take_snapshot(state)
        assert [p.metadata.name for p in snap.get_node("tpu1").pods] == ["p"]

    def test_zero_capacity_node_skipped(self):
        state = ClusterState()
        state.update_node(build_tpu_node(name="empty", chips=0), [])
        snap = TpuSnapshotTaker().take_snapshot(state)
        assert snap.get_nodes() == {}


class TestTracker:
    def test_tracks_only_lacking_pods(self):
        ann = annot.status_from_devices(free={0: {"2x2": 1}}, used={})
        snap = snapshot_of(build_tpu_node(name="n1", annotations=ann))
        fits = build_pod("fits", {slice_res("2x2"): 1})
        lacks = build_pod("lacks", {slice_res("2x4"): 1})
        tracker = SliceTracker(snap, [fits, lacks])
        assert fits not in tracker
        assert lacks in tracker
        assert tracker.lacking_totals() == {slice_res("2x4"): 1}

    def test_remove(self):
        snap = snapshot_of(build_tpu_node(name="n1"))
        pod = build_pod("p", {slice_res("2x2"): 1})
        tracker = SliceTracker(snap, [pod])
        tracker.remove(pod)
        assert tracker.empty
