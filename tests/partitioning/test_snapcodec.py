"""Warm-state serialization (ISSUE 13 tentpole, satellite 4).

The contract: a restarted process that adopts persisted warm state plans
BYTE-IDENTICALLY to one that never restarted (and measurably warmer —
the adopted futility memos fire instead of being re-proven); and ANY
reason to distrust the file — codec version bump, slice-codec change,
node-state drift, corruption — degrades to a clean cold rebuild for the
affected scope, never a crash and never silently stale state.
"""
import json

from nos_tpu.api.v1alpha1 import annotations as annot
from nos_tpu.partitioning.core import ClusterSnapshot, Planner, SnapshotNode
from nos_tpu.partitioning.core.partition_state import (
    partitioning_state_to_dict,
)
from nos_tpu.partitioning.core.snapcodec import (
    SNAPSHOT_CODEC_VERSION,
    WarmStateCodec,
    node_state_signature,
)
from nos_tpu.scheduler.framework import (
    Framework,
    NodeResourcesFit,
    NodeSelectorFit,
)

from tests.factory import build_pod, build_tpu_node, slice_res


def make_framework():
    return Framework(filter_plugins=[NodeResourcesFit(), NodeSelectorFit()])


def make_world(n=6, carve_first=0):
    """n virgin 2x4 nodes (8 chips each); an unservable 4x4 request
    against them drives the carve-futility memo — the expensive state a
    warm boot exists to preserve."""
    from nos_tpu.tpu.node import TpuNode

    nodes = {}
    for i in range(n):
        annotations = None
        if i < carve_first:
            annotations = annot.status_from_devices(
                free={0: {"1x1": 2}}, used={0: {"2x2": 1}}
            )
        node = build_tpu_node(name=f"n{i}", annotations=annotations)
        nodes[f"n{i}"] = SnapshotNode(partitionable=TpuNode(node))
    return ClusterSnapshot(nodes)


def make_pending():
    return [
        build_pod("big", {slice_res("4x4"): 1}),   # unservable: futility
        build_pod("ok", {slice_res("2x2"): 1}),    # servable: real carve
    ]


def state_bytes(state):
    return json.dumps(partitioning_state_to_dict(state), sort_keys=True)


def zero_ages(pods):
    return {p.namespaced_name: 0.0 for p in pods}


def warmed_codec(path, snapshot=None, planner=None):
    """Plan once to populate memos, save, return (codec, desired)."""
    snapshot = snapshot or make_world()
    planner = planner or Planner(make_framework())
    pending = make_pending()
    desired = planner.plan(snapshot, pending, pending_ages=zero_ages(pending))
    codec = WarmStateCodec(str(path))
    assert codec.save(snapshot, planner, force=True)
    return codec, desired, snapshot, planner


class TestRoundTrip:
    def test_restart_warm_boot_is_byte_identical_and_warmer(self, tmp_path):
        # Commit-free workload: the unservable 4x4 builds futility memos
        # on every node but places nothing, so plan() leaves the base at
        # observed state — the saved signatures describe exactly what a
        # restarted process re-observes. (A served pod or committed carve
        # legitimately unmatches its node until actuation/binding is
        # observed; that path is test_geometry_drift_invalidates below.)
        path = tmp_path / "warm.json"
        pending = [build_pod("big", {slice_res("4x4"): 1})]
        world = make_world(carve_first=2)
        before_planner = Planner(make_framework())
        desired_before = before_planner.plan(
            world, pending, pending_ages=zero_ages(pending)
        )
        codec = WarmStateCodec(str(path))
        assert codec.save(world, before_planner, force=True)
        # "Restart": fresh snapshot of the same world, fresh planner,
        # fresh codec (no in-memory signature cache carried over).
        snapshot = make_world(carve_first=2)
        planner = Planner(make_framework())
        report = WarmStateCodec(str(path)).adopt(snapshot, planner)
        assert report.matched == len(snapshot.get_nodes())
        assert report.unmatched == set()
        assert report.adopted_entries > 0
        desired = planner.plan(
            snapshot,
            pending,
            dirty=set(report.unmatched),
            pending_ages=zero_ages(pending),
        )
        assert state_bytes(desired) == state_bytes(desired_before)
        # The adopted memos actually fired: the unservable pod's carve
        # trials were skipped, not re-proven node by node.
        assert planner._futility_hits > 0

    def test_save_rate_limited_and_atomic(self, tmp_path):
        path = tmp_path / "warm.json"
        snapshot = make_world(n=2)
        planner = Planner(make_framework())
        planner.plan(snapshot, make_pending(), pending_ages={})
        codec = WarmStateCodec(str(path), save_interval_seconds=3600.0)
        assert codec.save(snapshot, planner, now=1000.0, force=True)
        assert not codec.save(snapshot, planner, now=1001.0)
        assert not codec.due(now=1001.0)
        assert codec.due(now=5000.0)
        assert codec.save(snapshot, planner, now=5000.0)
        # Atomic write left no temp droppings.
        assert [p.name for p in tmp_path.iterdir()] == ["warm.json"]


class TestDistrustDegradesToCold:
    def test_codec_version_bump_is_clean_cold_rebuild(self, tmp_path):
        path = tmp_path / "warm.json"
        warmed_codec(path)
        doc = json.loads(path.read_text())
        doc["codec_version"] = SNAPSHOT_CODEC_VERSION + 1
        path.write_text(json.dumps(doc))
        snapshot = make_world()
        planner = Planner(make_framework())
        codec = WarmStateCodec(str(path))
        assert codec.load(expected_codec=type(snapshot.codec).__name__) is None
        report = codec.adopt(snapshot, planner)
        assert report.matched == 0
        assert report.unmatched == set(snapshot.get_nodes())
        # The cold path still plans fine — never a crash.
        pending = make_pending()
        desired = planner.plan(
            snapshot, pending, pending_ages=zero_ages(pending)
        )
        fresh = Planner(make_framework()).plan(
            make_world(), make_pending(), pending_ages=zero_ages(pending)
        )
        assert state_bytes(desired) == state_bytes(fresh)

    def test_slice_codec_mismatch_is_cold(self, tmp_path):
        path = tmp_path / "warm.json"
        warmed_codec(path)
        codec = WarmStateCodec(str(path))
        assert codec.load(expected_codec="SomeOtherCodec") is None

    def test_corrupt_file_is_cold(self, tmp_path):
        path = tmp_path / "warm.json"
        path.write_text("{not json")
        snapshot = make_world(n=2)
        codec = WarmStateCodec(str(path))
        report = codec.adopt(snapshot, Planner(make_framework()))
        assert report.matched == 0
        assert report.unmatched == set(snapshot.get_nodes())

    def test_absent_file_is_cold(self, tmp_path):
        codec = WarmStateCodec(str(tmp_path / "nope.json"))
        snapshot = make_world(n=2)
        report = codec.adopt(snapshot, Planner(make_framework()))
        assert report.unmatched == set(snapshot.get_nodes())

    def test_geometry_drift_invalidates_only_that_node(self, tmp_path):
        """One node restarted with different carved geometry: its
        signature no longer matches, so ONLY it is reported unmatched
        (planned dirty/cold); every other node's memos are adopted — and
        the warm plan still equals a from-scratch plan of the new world."""
        path = tmp_path / "warm.json"
        warmed_codec(path)
        # Same world except n0 comes back already carved.
        snapshot = make_world(carve_first=1)
        planner = Planner(make_framework())
        report = WarmStateCodec(str(path)).adopt(snapshot, planner)
        assert report.unmatched == {"n0"}
        assert report.matched == len(snapshot.get_nodes()) - 1
        pending = make_pending()
        desired = planner.plan(
            snapshot,
            pending,
            dirty=set(report.unmatched),
            pending_ages=zero_ages(pending),
        )
        fresh = Planner(make_framework()).plan(
            make_world(carve_first=1),
            make_pending(),
            pending_ages=zero_ages(pending),
        )
        assert state_bytes(desired) == state_bytes(fresh)

    def test_signature_covers_planner_inputs(self):
        """Every planner-relevant node input moves the signature; object
        identity does not."""
        from nos_tpu.tpu.node import TpuNode

        def sig(mutate=None):
            node = build_tpu_node(name="n")
            if mutate:
                mutate(node)
            return node_state_signature(
                SnapshotNode(partitionable=TpuNode(node))
            )

        base = sig()
        assert sig() == base  # deterministic across objects
        assert sig(lambda n: n.metadata.labels.update({"x": "y"})) != base
        assert sig(
            lambda n: n.status.allocatable.update({"cpu": 99})
        ) != base

        def cordon(n):
            n.spec.unschedulable = True

        assert sig(cordon) != base

        def carve(n):
            n.metadata.annotations.update(
                annot.status_from_devices(
                    free={0: {"1x1": 2}}, used={0: {"2x2": 1}}
                )
            )

        assert sig(carve) != base
