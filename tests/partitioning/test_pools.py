"""Pool-sharded planning (ISSUE 13 tentpole): partition correctness,
sharded-vs-unsharded equivalence, merge invariants, and pool-membership
stability across no-op maintainer cycles.

The contract: pools are seeded by the GKE node-pool label and merged by
every edge that couples planning decisions (multi-pool selectors, gangs,
borrowing quotas); anything cluster-wide degrades to one mega-pool; and
on pool-independent inputs (draw_decomposes holds) the merged sharded
plan is byte-identical to the unsharded planner's output.
"""
import json

from nos_tpu.api.v1alpha1 import annotations as annot
from nos_tpu.api.v1alpha1 import constants
from nos_tpu.api.v1alpha1.labels import GKE_NODEPOOL_LABEL
from nos_tpu.kube.objects import (
    NodeAffinity,
    NodeSelectorRequirement,
    NodeSelectorTerm,
)
from nos_tpu.partitioning.core import ClusterSnapshot, Planner, SnapshotNode
from nos_tpu.partitioning.core.partition_state import (
    partitioning_state_to_dict,
)
from nos_tpu.partitioning.core.pools import (
    MEGA_POOL,
    PoolPartition,
    check_merge_invariants,
    draw_decomposes,
    merge_pool_states,
    partition_pools,
    split_pending,
    split_snapshot,
)
from nos_tpu.scheduler.framework import (
    Framework,
    NodeResourcesFit,
    NodeSelectorFit,
)
from nos_tpu.scheduler.plugins.gang import GANG_NAME_LABEL, GANG_SIZE_LABEL

from tests.factory import build_pod, build_tpu_node, slice_res


def make_framework():
    return Framework(filter_plugins=[NodeResourcesFit(), NodeSelectorFit()])


def pool_node(name, pool=None, annotations=None):
    from nos_tpu.tpu.node import TpuNode

    node = build_tpu_node(name=name, annotations=annotations)
    if pool is not None:
        node.metadata.labels[GKE_NODEPOOL_LABEL] = pool
    return SnapshotNode(partitionable=TpuNode(node))


def make_snapshot(nodes):
    return ClusterSnapshot(dict(sorted(nodes.items())))


def pinned_pod(name, profile, pool):
    pod = build_pod(name, {slice_res(profile): 1})
    pod.spec.node_selector[GKE_NODEPOOL_LABEL] = pool
    return pod


def two_pool_world():
    """Two 2-node pools, partially carved so plans are non-trivial."""
    carved = annot.status_from_devices(free={0: {"1x1": 2}}, used={0: {"2x2": 1}})
    nodes = {
        "a0": pool_node("a0", "pool-a"),
        "a1": pool_node("a1", "pool-a", annotations=dict(carved)),
        "b0": pool_node("b0", "pool-b"),
        "b1": pool_node("b1", "pool-b", annotations=dict(carved)),
    }
    return make_snapshot(nodes)


def zero_ages(pods):
    return {p.namespaced_name: 0.0 for p in pods}


def plan_unsharded(snapshot, pending):
    planner = Planner(make_framework())
    return planner.plan(snapshot, list(pending), pending_ages=zero_ages(pending))


def plan_sharded(snapshot, pending, quotas=()):
    """The controller's sharded pipeline, inlined: partition, split,
    per-pool plan, invariant check, deterministic merge."""
    partition = partition_pools(snapshot, pending, quotas=quotas)
    pool_snaps = split_snapshot(snapshot, partition)
    pool_pending = split_pending(pending, partition)
    pool_desired, pool_current = {}, {}
    for pool in partition.pools:
        planner = Planner(make_framework())
        # Pre-plan state first: plan() commits carves into its base.
        pool_current[pool] = pool_snaps[pool].partitioning_state()
        pool_desired[pool] = planner.plan(
            pool_snaps[pool],
            pool_pending[pool],
            pending_ages=zero_ages(pool_pending[pool]),
        )
    assert check_merge_invariants(partition, pool_current, pool_desired) == []
    return merge_pool_states(pool_desired), partition


def state_bytes(state):
    return json.dumps(partitioning_state_to_dict(state), sort_keys=True)


class TestPartitionPools:
    def test_selector_pinned_pods_keep_pools_apart(self):
        snapshot = two_pool_world()
        pending = [
            pinned_pod("pa", "2x2", "pool-a"),
            pinned_pod("pb", "2x2", "pool-b"),
        ]
        partition = partition_pools(snapshot, pending)
        assert partition.pools == ("pool-a", "pool-b")
        assert partition.single_pool_reason == ""
        assert partition.node_pool == {
            "a0": "pool-a", "a1": "pool-a", "b0": "pool-b", "b1": "pool-b",
        }
        assert partition.pod_pool == {
            "default/pa": "pool-a", "default/pb": "pool-b",
        }

    def test_unpinned_pod_connects_every_pool(self):
        """An empty selector matches every pool: the planner must choose
        among all of them, so the whole graph collapses into one pool
        named after the smallest seed (stable id, not the mega-pool)."""
        snapshot = two_pool_world()
        pending = [build_pod("free", {slice_res("2x2"): 1})]
        partition = partition_pools(snapshot, pending)
        assert partition.pools == ("pool-a",)
        assert partition.single_pool_reason == ""
        assert set(partition.node_pool.values()) == {"pool-a"}
        assert partition.merged_from == {"pool-a": ("pool-a", "pool-b")}

    def test_gang_spanning_two_pools_forces_merge(self):
        snapshot = two_pool_world()
        members = []
        for i, pool in enumerate(["pool-a", "pool-b"]):
            pod = pinned_pod(f"g{i}", "2x2", pool)
            pod.metadata.labels[GANG_NAME_LABEL] = "g"
            pod.metadata.labels[GANG_SIZE_LABEL] = "2"
            members.append(pod)
        # A third, unrelated pinned pod shows the merge is the gang's
        # doing, not a global collapse.
        partition = partition_pools(snapshot, members)
        assert partition.pools == ("pool-a",)
        assert partition.pod_pool["default/g0"] == "pool-a"
        assert partition.pod_pool["default/g1"] == "pool-a"

    def test_gang_bound_member_pins_pending_member_to_its_pool(self):
        """A gang with one member already RUNNING in pool-b couples the
        still-pending member's pool (pool-a, by selector) to pool-b: the
        union joins both, so no pool can carve for a gang another pool
        already half-placed."""
        carved = annot.status_from_devices(
            free={0: {"1x1": 2}}, used={0: {"2x2": 1}}
        )
        bound = build_pod("g-bound", {slice_res("2x2"): 1}, node="b0")
        bound.status.phase = "Running"
        bound.metadata.labels[GANG_NAME_LABEL] = "g"
        bound.metadata.labels[GANG_SIZE_LABEL] = "2"
        from nos_tpu.tpu.node import TpuNode

        b0 = build_tpu_node(name="b0", annotations=dict(carved))
        b0.metadata.labels[GKE_NODEPOOL_LABEL] = "pool-b"
        nodes = {
            "a0": pool_node("a0", "pool-a"),
            "b0": SnapshotNode(partitionable=TpuNode(b0), pods=[bound]),
        }
        snapshot = make_snapshot(nodes)
        pending = pinned_pod("g-pend", "2x2", "pool-a")
        pending.metadata.labels[GANG_NAME_LABEL] = "g"
        pending.metadata.labels[GANG_SIZE_LABEL] = "2"
        partition = partition_pools(snapshot, [pending])
        assert partition.pools == ("pool-a",)
        assert partition.node_pool["b0"] == "pool-a"

    def test_required_node_affinity_degrades_to_mega_pool(self):
        snapshot = two_pool_world()
        pod = build_pod("aff", {slice_res("2x2"): 1})
        pod.spec.affinity = NodeAffinity(required_terms=[
            NodeSelectorTerm(match_expressions=[
                NodeSelectorRequirement(
                    key="pool", operator="In", values=["gold"]
                ),
            ])
        ])
        partition = partition_pools(snapshot, [pod])
        assert partition.pools == (MEGA_POOL,)
        assert "required node affinity" in partition.single_pool_reason
        assert set(partition.node_pool.values()) == {MEGA_POOL}

    def test_borrowing_quota_couples_namespaces(self):
        from nos_tpu.api.v1alpha1.elasticquota import (
            ElasticQuota,
            ElasticQuotaSpec,
        )
        from nos_tpu.kube.objects import ObjectMeta

        snapshot = two_pool_world()
        pending = [
            pinned_pod("pa", "2x2", "pool-a"),
            pinned_pod("pb", "2x2", "pool-b"),
        ]
        borrowing = ElasticQuota(
            metadata=ObjectMeta(name="q", namespace="default"),
            spec=ElasticQuotaSpec(
                min={constants.RESOURCE_TPU: 4},
                max={constants.RESOURCE_TPU: 8},
            ),
        )
        partition = partition_pools(snapshot, pending, quotas=[borrowing])
        assert partition.pools == ("pool-a",)
        # Fixed quotas (min == max) cannot displace anything: no edge.
        fixed = ElasticQuota(
            metadata=ObjectMeta(name="q", namespace="default"),
            spec=ElasticQuotaSpec(
                min={constants.RESOURCE_TPU: 8},
                max={constants.RESOURCE_TPU: 8},
            ),
        )
        partition = partition_pools(snapshot, pending, quotas=[fixed])
        assert partition.pools == ("pool-a", "pool-b")


class TestShardedEquivalence:
    def test_pool_independent_inputs_byte_identical(self):
        snapshot = two_pool_world()
        pending = [
            pinned_pod("pa0", "2x2", "pool-a"),
            pinned_pod("pa1", "1x1", "pool-a"),
            pinned_pod("pb0", "2x2", "pool-b"),
        ]
        partition = partition_pools(snapshot, pending)
        assert len(partition.pools) == 2
        assert draw_decomposes(snapshot, partition, pending)
        sharded, _ = plan_sharded(snapshot, pending)
        unsharded = plan_unsharded(two_pool_world(), pending)
        assert state_bytes(sharded) == state_bytes(unsharded)

    def test_connected_cluster_single_pool_byte_identical(self):
        """A connected pool graph (unpinned pods) must shard into ONE
        pool whose plan is byte-identical to the unsharded planner's --
        sharding degrades to a clone, never to a different answer."""
        snapshot = two_pool_world()
        pending = [
            build_pod("p0", {slice_res("2x2"): 1}),
            build_pod("p1", {slice_res("1x1"): 1}),
        ]
        sharded, partition = plan_sharded(snapshot, pending)
        assert partition.pools == ("pool-a",)
        unsharded = plan_unsharded(two_pool_world(), pending)
        assert state_bytes(sharded) == state_bytes(unsharded)

    def test_unlabeled_nodes_form_implicit_default_pool(self):
        nodes = {
            "n0": pool_node("n0"),
            "n1": pool_node("n1", "pool-b"),
        }
        snapshot = make_snapshot(nodes)
        pending = [pinned_pod("pb", "2x2", "pool-b")]
        partition = partition_pools(snapshot, pending)
        assert partition.pools == ("default", "pool-b")
        assert partition.node_pool["n0"] == "default"


class TestMergeInvariants:
    def _partition(self):
        return PoolPartition(
            pools=("pool-a", "pool-b"),
            node_pool={"a0": "pool-a", "b0": "pool-b"},
            pod_pool={},
            merged_from={},
            single_pool_reason="",
        )

    def _state_of(self, snapshot, names):
        full = snapshot.partitioning_state()
        return {name: full[name] for name in names}

    def test_clean_split_passes(self):
        snapshot = two_pool_world()
        partition = partition_pools(
            snapshot, [pinned_pod("pa", "2x2", "pool-a")]
        )
        pool_snaps = split_snapshot(snapshot, partition)
        states = {
            pool: snap.partitioning_state()
            for pool, snap in pool_snaps.items()
        }
        assert check_merge_invariants(partition, states, states) == []

    def test_node_claimed_twice_detected(self):
        snapshot = make_snapshot(
            {"a0": pool_node("a0", "pool-a"), "b0": pool_node("b0", "pool-b")}
        )
        partition = self._partition()
        current = {
            "pool-a": self._state_of(snapshot, ["a0"]),
            "pool-b": self._state_of(snapshot, ["b0"]),
        }
        desired = {
            "pool-a": self._state_of(snapshot, ["a0", "b0"]),
            "pool-b": self._state_of(snapshot, ["b0"]),
        }
        violations = check_merge_invariants(partition, current, desired)
        assert any("claimed by pools" in v for v in violations)

    def test_unplanned_node_detected(self):
        snapshot = make_snapshot(
            {"a0": pool_node("a0", "pool-a"), "b0": pool_node("b0", "pool-b")}
        )
        partition = self._partition()
        current = {
            "pool-a": self._state_of(snapshot, ["a0"]),
            "pool-b": self._state_of(snapshot, ["b0"]),
        }
        desired = {
            "pool-a": self._state_of(snapshot, ["a0"]),
            "pool-b": {},
        }
        violations = check_merge_invariants(partition, current, desired)
        assert any("missing from every pool plan" in v for v in violations)

    def test_chip_invariants_allow_recarve_but_not_minting(self):
        """Re-carving an observed board to a DIFFERENT chip total is
        legal — a replan after chip-loss faults tears a degraded board
        down and carves it back to full (the chaos sweep's seed-15
        world does exactly this) — so the chip invariant is the
        capacity ceiling, not per-board equality. Listing the same
        board twice or exceeding the node's physical capacity is merge
        corruption and must flag."""
        carved = annot.status_from_devices(
            free={0: {"1x1": 2}}, used={0: {"2x2": 1}}
        )
        snapshot = make_snapshot(
            {
                "a0": pool_node("a0", "pool-a", annotations=dict(carved)),
                "b0": pool_node("b0", "pool-b"),
            }
        )
        partition = self._partition()
        current = {
            "pool-a": self._state_of(snapshot, ["a0"]),
            "pool-b": self._state_of(snapshot, ["b0"]),
        }
        from nos_tpu.partitioning.core.partition_state import (
            BoardPartitioning,
            NodePartitioning,
        )

        # a0's board 0 shows 6 carved chips; replanning it to a single
        # 2x2 (4 chips, within the node's 8) is a legitimate re-carve.
        recarved = {
            "pool-a": {
                "a0": NodePartitioning(boards=[
                    BoardPartitioning(
                        board_index=0,
                        resources={slice_res("2x2"): 1},
                    )
                ])
            },
            "pool-b": current["pool-b"],
        }
        assert check_merge_invariants(
            partition, current, recarved, capacities={"a0": 8.0, "b0": 8.0}
        ) == []
        # Minting: carving the virgin b0 whole is legal, but a desired
        # total past its physical 8 chips is flagged once capacities are
        # supplied.
        minted = {
            "pool-a": current["pool-a"],
            "pool-b": {
                "b0": NodePartitioning(boards=[
                    BoardPartitioning(
                        board_index=0,
                        resources={slice_res("2x4"): 2},
                    )
                ])
            },
        }
        assert check_merge_invariants(partition, current, minted) == []
        violations = check_merge_invariants(
            partition, current, minted, capacities={"b0": 8.0}
        )
        assert any("exceeds capacity" in v for v in violations)
        # Merge corruption: the same board listed twice on one node.
        doubled = {
            "pool-a": current["pool-a"],
            "pool-b": {
                "b0": NodePartitioning(boards=[
                    BoardPartitioning(
                        board_index=0,
                        resources={slice_res("2x2"): 1},
                    ),
                    BoardPartitioning(
                        board_index=0,
                        resources={slice_res("2x2"): 1},
                    ),
                ])
            },
        }
        violations = check_merge_invariants(partition, current, doubled)
        assert any("twice" in v for v in violations)

    def test_merge_is_order_independent(self):
        snapshot = two_pool_world()
        partition = partition_pools(
            snapshot,
            [pinned_pod("pa", "2x2", "pool-a"), pinned_pod("pb", "2x2", "pool-b")],
        )
        pool_snaps = split_snapshot(snapshot, partition)
        states = {
            pool: snap.partitioning_state()
            for pool, snap in pool_snaps.items()
        }
        forward = merge_pool_states(dict(states))
        backward = merge_pool_states(dict(reversed(list(states.items()))))
        assert state_bytes(forward) == state_bytes(backward)
        assert list(forward) == sorted(forward)


class TestPoolStabilityAcrossCycles:
    """The PoolShardedMaintainer must NOT flush per-pool memos on no-op
    cycles: identical (snapshot shape, pending, quotas) must keep the
    same pool snapshot objects, with empty dirty sets."""

    def _store(self):
        from nos_tpu.cmd.partitioner import register_indexers
        from nos_tpu.kube.store import KubeStore

        store = KubeStore()
        register_indexers(store)
        for name, pool in [
            ("a0", "pool-a"), ("a1", "pool-a"),
            ("b0", "pool-b"), ("b1", "pool-b"),
        ]:
            node = build_tpu_node(name=name)
            node.metadata.labels[GKE_NODEPOOL_LABEL] = pool
            store.create(node)
        return store

    def _maintainer(self, store):
        from nos_tpu.controllers.partitioner.incremental import (
            PoolShardedMaintainer,
        )
        from nos_tpu.partitioning.tpu import TpuSnapshotTaker

        return PoolShardedMaintainer(store, TpuSnapshotTaker(), kind="tpu")

    def test_noop_cycles_keep_pool_snapshots(self):
        from nos_tpu.partitioning.core import ClusterState

        store = self._store()
        maintainer = self._maintainer(store)
        state = ClusterState()
        pending = [
            pinned_pod("pa", "2x2", "pool-a"),
            pinned_pod("pb", "2x2", "pool-b"),
        ]
        _, _, partition, pool_snaps, pool_dirty = maintainer.shard(
            state, pending
        )
        assert maintainer.last_rebuilt
        assert partition.pools == ("pool-a", "pool-b")
        assert pool_dirty == {
            "pool-a": {"a0", "a1"}, "pool-b": {"b0", "b1"},
        }
        for _ in range(3):
            _, _, partition2, pool_snaps2, pool_dirty2 = maintainer.shard(
                state, pending
            )
            assert not maintainer.last_rebuilt
            assert partition2.node_pool == partition.node_pool
            for pool in partition.pools:
                assert pool_snaps2[pool] is pool_snaps[pool]
            assert pool_dirty2 == {"pool-a": set(), "pool-b": set()}
        assert maintainer.pool_rebuilds == 1

    def test_dirty_node_refreshes_only_its_pool(self):
        from nos_tpu.partitioning.core import ClusterState

        store = self._store()
        maintainer = self._maintainer(store)
        state = ClusterState()
        pending = [
            pinned_pod("pa", "2x2", "pool-a"),
            pinned_pod("pb", "2x2", "pool-b"),
        ]
        _, _, _, pool_snaps, _ = maintainer.shard(state, pending)
        bound = build_pod("w0", {slice_res("1x1"): 1}, node="b1")
        bound.status.phase = "Running"
        store.create(bound)
        _, dirty, _, pool_snaps2, pool_dirty2 = maintainer.shard(
            state, pending
        )
        assert not maintainer.last_rebuilt
        assert dirty == {"b1"}
        assert pool_dirty2 == {"pool-a": set(), "pool-b": {"b1"}}
        assert pool_snaps2["pool-b"] is pool_snaps["pool-b"]
        assert [
            p.metadata.name
            for p in pool_snaps2["pool-b"].get_nodes()["b1"].pods
        ] == ["w0"]

    def test_partition_change_rebuilds_pools(self):
        from nos_tpu.partitioning.core import ClusterState

        store = self._store()
        maintainer = self._maintainer(store)
        state = ClusterState()
        pending = [
            pinned_pod("pa", "2x2", "pool-a"),
            pinned_pod("pb", "2x2", "pool-b"),
        ]
        _, _, _, pool_snaps, _ = maintainer.shard(state, pending)
        # A gang now spans the pools: the partition changes, pools rebuild.
        members = []
        for i, pool in enumerate(["pool-a", "pool-b"]):
            pod = pinned_pod(f"g{i}", "2x2", pool)
            pod.metadata.labels[GANG_NAME_LABEL] = "g"
            pod.metadata.labels[GANG_SIZE_LABEL] = "2"
            members.append(pod)
        _, _, partition2, pool_snaps2, pool_dirty2 = maintainer.shard(
            state, members
        )
        assert maintainer.last_rebuilt
        assert partition2.pools == ("pool-a",)
        assert pool_dirty2 == {"pool-a": {"a0", "a1", "b0", "b1"}}
        assert maintainer.pool_rebuilds == 2

    def test_force_rebuild_escape_hatch(self):
        from nos_tpu.partitioning.core import ClusterState

        store = self._store()
        maintainer = self._maintainer(store)
        state = ClusterState()
        pending = [pinned_pod("pa", "2x2", "pool-a")]
        maintainer.shard(state, pending)
        maintainer.force_rebuild()
        maintainer.shard(state, pending)
        assert maintainer.last_rebuilt
        assert maintainer.pool_rebuilds == 2
