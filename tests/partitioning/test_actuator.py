from nos_tpu.api.v1alpha1 import annotations as annot
from nos_tpu.kube.store import KubeStore
from nos_tpu.partitioning.core import (
    Actuator,
    BoardPartitioning,
    NodePartitioning,
    PartitioningPlan,
    partitioning_state_equal,
)
from nos_tpu.partitioning.tpu import TpuPartitioner

from tests.factory import build_tpu_node, slice_res


class RecordingPartitioner:
    def __init__(self):
        self.calls = []

    def apply_partitioning(self, node_name, plan_id, partitioning):
        self.calls.append((node_name, plan_id, partitioning))


def node_partitioning(**resources):
    return NodePartitioning(boards=[BoardPartitioning(0, dict(resources))])


class TestStateEquality:
    def test_equal_ignores_board_order_and_empties(self):
        a = {
            "n1": NodePartitioning(
                boards=[
                    BoardPartitioning(1, {slice_res("1x1"): 4}),
                    BoardPartitioning(0, {slice_res("2x2"): 1}),
                    BoardPartitioning(2, {}),
                ]
            )
        }
        b = {
            "n1": NodePartitioning(
                boards=[
                    BoardPartitioning(0, {slice_res("2x2"): 1}),
                    BoardPartitioning(1, {slice_res("1x1"): 4}),
                ]
            )
        }
        assert partitioning_state_equal(a, b)

    def test_not_equal(self):
        a = {"n1": node_partitioning(**{slice_res("2x2"): 1})}
        b = {"n1": node_partitioning(**{slice_res("2x2"): 2})}
        assert not partitioning_state_equal(a, b)


class TestActuator:
    def test_skips_when_equal(self):
        p = RecordingPartitioner()
        state = {"n1": node_partitioning(**{slice_res("2x2"): 2})}
        assert not Actuator(p).apply(state, PartitioningPlan(state, "1"))
        assert p.calls == []

    def test_skips_empty_desired(self):
        p = RecordingPartitioner()
        assert not Actuator(p).apply({}, PartitioningPlan({}, "1"))

    def test_applies_only_changed_nodes(self):
        p = RecordingPartitioner()
        current = {
            "same": node_partitioning(**{slice_res("2x2"): 2}),
            "changed": node_partitioning(**{slice_res("2x2"): 2}),
        }
        desired = {
            "same": node_partitioning(**{slice_res("2x2"): 2}),
            "changed": node_partitioning(**{slice_res("1x1"): 8}),
        }
        assert Actuator(p).apply(current, PartitioningPlan(desired, "42"))
        assert [(c[0], c[1]) for c in p.calls] == [("changed", "42")]


class TestTpuPartitioner:
    def test_writes_spec_annotations_and_plan_id(self):
        store = KubeStore()
        store.create(build_tpu_node(name="n1"))
        TpuPartitioner(store).apply_partitioning(
            "n1", "123", node_partitioning(**{slice_res("2x2"): 2})
        )
        node = store.get("Node", "n1")
        assert node.metadata.annotations[annot.SPEC_PARTITIONING_PLAN] == "123"
        spec, _ = annot.parse_node_annotations(node.metadata.annotations)
        assert annot.spec_geometries(spec) == {0: {"2x2": 2}}

    def test_replaces_previous_spec(self):
        store = KubeStore()
        store.create(
            build_tpu_node(
                name="n1", annotations=annot.spec_from_geometries({0: {"2x4": 1}})
            )
        )
        TpuPartitioner(store).apply_partitioning(
            "n1", "124", node_partitioning(**{slice_res("1x1"): 8})
        )
        spec, _ = annot.parse_node_annotations(
            store.get("Node", "n1").metadata.annotations
        )
        assert annot.spec_geometries(spec) == {0: {"1x1": 8}}

    def test_missing_node_tolerated(self):
        TpuPartitioner(KubeStore()).apply_partitioning(
            "ghost", "1", node_partitioning(**{slice_res("1x1"): 1})
        )
