from nos_tpu.api.v1alpha1 import annotations as annot
from nos_tpu.api.v1alpha1 import constants
from nos_tpu.partitioning.core import ClusterSnapshot, Planner, SnapshotNode
from nos_tpu.partitioning.core.planner import sort_candidate_pods
from nos_tpu.scheduler.framework import (
    Framework,
    NodeResourcesFit,
    NodeSelectorFit,
    Status,
)
from nos_tpu.tpu.node import TpuNode

from tests.factory import build_pod, build_tpu_node, slice_res


def make_framework():
    return Framework(filter_plugins=[NodeResourcesFit(), NodeSelectorFit()])


def snapshot_of(*nodes, pods_by_node=None):
    pods_by_node = pods_by_node or {}
    return ClusterSnapshot(
        {
            n.metadata.name: SnapshotNode(
                partitionable=TpuNode(n), pods=pods_by_node.get(n.metadata.name, [])
            )
            for n in nodes
        }
    )


class TestSortCandidatePods:
    def test_priority_desc_then_smallest_slice(self):
        small = build_pod("small", {slice_res("1x1"): 1})
        big = build_pod("big", {slice_res("2x4"): 1})
        vip = build_pod("vip", {slice_res("2x4"): 1}, priority=100)
        assert [p.metadata.name for p in sort_candidate_pods([big, small, vip])] == [
            "vip",
            "small",
            "big",
        ]

    def test_name_tiebreak(self):
        a = build_pod("a", {slice_res("1x1"): 1})
        b = build_pod("b", {slice_res("1x1"): 1})
        assert [p.metadata.name for p in sort_candidate_pods([b, a])] == ["a", "b"]


class TestPlanner:
    def test_carves_virgin_node_for_pending_pod(self):
        snap = snapshot_of(build_tpu_node(name="n1"))
        pod = build_pod("p", {slice_res("2x2"): 1})
        plan = Planner(make_framework()).plan(snap, [pod])
        geometry = {b.board_index: b.resources for b in plan["n1"].boards}
        assert geometry[0].get(slice_res("2x2"), 0) >= 1
        # the pod was placed in simulation
        assert [p.metadata.name for p in snap.get_node("n1").pods] == ["p"]

    def test_no_lacking_returns_current_state(self):
        ann = annot.status_from_devices(free={0: {"2x2": 2}}, used={})
        snap = snapshot_of(build_tpu_node(name="n1", annotations=ann))
        pod = build_pod("p", {slice_res("2x2"): 1})
        plan = Planner(make_framework()).plan(snap, [pod])
        assert {b.board_index: b.resources for b in plan["n1"].boards} == {
            0: {slice_res("2x2"): 2}
        }
        # nothing was simulated-placed: geometry already served the pod
        assert snap.get_node("n1").pods == []

    def test_reverts_when_no_pod_fits(self):
        # Node can be carved, but the pod's cpu request exceeds the node.
        snap = snapshot_of(build_tpu_node(name="n1"))
        pod = build_pod("p", {slice_res("2x2"): 1, "cpu": 999})
        plan = Planner(make_framework()).plan(snap, [pod])
        assert {b.board_index: b.resources for b in plan["n1"].boards} == {0: {}}

    def test_plain_chip_pod_normalized_and_placed(self):
        snap = snapshot_of(build_tpu_node(name="n1"))
        pod = build_pod("p", {constants.RESOURCE_TPU: 8})
        plan = Planner(make_framework()).plan(snap, [pod])
        geometry = {b.board_index: b.resources for b in plan["n1"].boards}
        assert geometry[0] == {slice_res("2x4"): 1}

    def test_multiple_pods_packed_on_one_node(self):
        snap = snapshot_of(build_tpu_node(name="n1"))
        pods = [build_pod(f"p{i}", {slice_res("1x1"): 1}) for i in range(8)]
        plan = Planner(make_framework()).plan(snap, [pods[0], *pods[1:]])
        geometry = {b.board_index: b.resources for b in plan["n1"].boards}
        assert geometry[0] == {slice_res("1x1"): 8}
        assert len(snap.get_node("n1").pods) == 8

    def test_spreads_over_two_nodes(self):
        snap = snapshot_of(build_tpu_node(name="n1"), build_tpu_node(name="n2"))
        pods = [build_pod(f"p{i}", {slice_res("2x4"): 1}) for i in range(2)]
        plan = Planner(make_framework()).plan(snap, pods)
        for name in ("n1", "n2"):
            geometry = {b.board_index: b.resources for b in plan[name].boards}
            assert geometry[0] == {slice_res("2x4"): 1}

    def test_high_priority_pod_wins_contention(self):
        snap = snapshot_of(build_tpu_node(name="n1"))
        low = build_pod("low", {slice_res("2x4"): 1}, priority=0)
        high = build_pod("high", {slice_res("2x4"): 1}, priority=10)
        Planner(make_framework()).plan(snap, [low, high])
        assert [p.metadata.name for p in snap.get_node("n1").pods] == ["high"]

    def test_used_slices_preserved(self):
        ann = annot.status_from_devices(free={}, used={0: {"2x2": 1}})
        running = build_pod("running", {slice_res("2x2"): 1}, node="n1")
        snap = snapshot_of(
            build_tpu_node(name="n1", annotations=ann),
            pods_by_node={"n1": [running]},
        )
        pod = build_pod("p", {slice_res("1x1"): 2})
        plan = Planner(make_framework()).plan(snap, [pod])
        geometry = {b.board_index: b.resources for b in plan["n1"].boards}
        assert geometry[0].get(slice_res("2x2"), 0) == 1
        assert geometry[0].get(slice_res("1x1"), 0) >= 2

    def test_unschedulable_filter_blocks_placement(self):
        class RejectAll:
            name = "RejectAll"

            def filter(self, state, pod, node_info):
                return Status.unschedulable("no", self.name)

        snap = snapshot_of(build_tpu_node(name="n1"))
        pod = build_pod("p", {slice_res("2x2"): 1})
        fw = Framework(filter_plugins=[RejectAll()])
        plan = Planner(fw).plan(snap, [pod])
        assert {b.board_index: b.resources for b in plan["n1"].boards} == {0: {}}
        assert snap.get_node("n1").pods == []


class TestPlannerRegressions:
    """Deadlock scenarios found in review: shared free pool, net-lacking
    double count, and mixed-generation normalization."""

    def test_two_pods_sharing_one_free_slice_get_second_carved(self):
        ann = annot.status_from_devices(free={0: {"2x2": 1}}, used={})
        snap = snapshot_of(build_tpu_node(name="n1", annotations=ann))
        pods = [build_pod(f"p{i}", {slice_res("2x2"): 1}) for i in range(2)]
        plan = Planner(make_framework()).plan(snap, pods)
        geometry = {b.board_index: b.resources for b in plan["n1"].boards}
        assert geometry[0].get(slice_res("2x2"), 0) == 2
        # p0 is served by the pre-existing free slice (the real scheduler
        # places it); only p1 needed planning.
        assert [p.metadata.name for p in snap.get_node("n1").pods] == ["p1"]

    def test_pod_wanting_more_than_net_delta_triggers_carve(self):
        ann = annot.status_from_devices(free={0: {"2x2": 1}}, used={})
        snap = snapshot_of(build_tpu_node(name="n1", annotations=ann))
        pod = build_pod("p", {slice_res("2x2"): 2})
        plan = Planner(make_framework()).plan(snap, [pod])
        geometry = {b.board_index: b.resources for b in plan["n1"].boards}
        assert geometry[0].get(slice_res("2x2"), 0) == 2
        assert len(snap.get_node("n1").pods) == 1

    def test_mixed_generation_cluster_serves_plain_chips(self):
        from nos_tpu.api.v1alpha1 import annotations as annot_api
        from tests.factory import V4
        # v4 node fully used; virgin v5e node must serve the 4-chip pod.
        full = annot_api.status_from_devices(free={}, used={0: {"2x2x1": 1}})
        snap = snapshot_of(
            build_tpu_node(name="v4-full", accelerator=V4, chips=4, annotations=full),
            build_tpu_node(name="v5e-virgin"),
        )
        pod = build_pod("p", {constants.RESOURCE_TPU: 4})
        plan = Planner(make_framework()).plan(snap, [pod])
        geometry = {b.board_index: b.resources for b in plan["v5e-virgin"].boards}
        assert geometry[0].get(slice_res("2x2"), 0) >= 1
        assert [p.metadata.name for p in snap.get_node("v5e-virgin").pods] == ["p"]
