from nos_tpu.api.v1alpha1 import annotations as annot
from nos_tpu.api.v1alpha1 import constants
from nos_tpu.partitioning.core import ClusterSnapshot, Planner, SnapshotNode
from nos_tpu.partitioning.core.planner import sort_candidate_pods
from nos_tpu.scheduler.framework import (
    Framework,
    NodeResourcesFit,
    NodeSelectorFit,
    Status,
)
from nos_tpu.tpu.node import TpuNode

from tests.factory import build_pod, build_tpu_node, slice_res


def make_framework():
    return Framework(filter_plugins=[NodeResourcesFit(), NodeSelectorFit()])


def snapshot_of(*nodes, pods_by_node=None):
    pods_by_node = pods_by_node or {}
    return ClusterSnapshot(
        {
            n.metadata.name: SnapshotNode(
                partitionable=TpuNode(n), pods=pods_by_node.get(n.metadata.name, [])
            )
            for n in nodes
        }
    )


class TestSortCandidatePods:
    def test_priority_desc_then_largest_slice(self):
        # First-fit-descending (TPU-first deviation from the reference's
        # smallest-first): board-sized requests place while boards are
        # still whole.
        small = build_pod("small", {slice_res("1x1"): 1})
        big = build_pod("big", {slice_res("2x4"): 1})
        vip = build_pod("vip", {slice_res("1x1"): 1}, priority=100)
        assert [p.metadata.name for p in sort_candidate_pods([big, small, vip])] == [
            "vip",
            "big",
            "small",
        ]

    def test_name_tiebreak(self):
        a = build_pod("a", {slice_res("1x1"): 1})
        b = build_pod("b", {slice_res("1x1"): 1})
        assert [p.metadata.name for p in sort_candidate_pods([b, a])] == ["a", "b"]

    def test_aging_promotes_starved_small_pod(self):
        # A 1-chip pod passed over for 8.5s of planner rounds (at 1
        # chip/s) must outrank a just-arrived 8-chip pod — FFD can't
        # re-sort it last forever.
        import time

        old_small = build_pod("old-small", {slice_res("1x1"): 1})
        fresh_big = build_pod("fresh-big", {slice_res("2x4"): 1})
        since = {old_small.namespaced_name: time.monotonic() - 8.5}
        order = [
            p.metadata.name
            for p in sort_candidate_pods([fresh_big, old_small], pending_since=since)
        ]
        assert order == ["old-small", "fresh-big"]
        # Aging disabled: pure FFD order.
        order = [
            p.metadata.name
            for p in sort_candidate_pods(
                [fresh_big, old_small], aging_chips_per_second=0.0,
                pending_since=since,
            )
        ]
        assert order == ["fresh-big", "old-small"]

    def test_first_consideration_is_not_aged(self):
        # Absent a pending_since entry (first time the planner sees the
        # pod), age is 0 regardless of creation time — arrival spread
        # inside one batch window must not FIFO-ify the packing order.
        import time

        old_small = build_pod("old-small", {slice_res("1x1"): 1})
        old_small.metadata.creation_timestamp = time.time() - 3600
        fresh_big = build_pod("fresh-big", {slice_res("2x4"): 1})
        order = [p.metadata.name for p in sort_candidate_pods([old_small, fresh_big])]
        assert order == ["fresh-big", "old-small"]

    def test_aging_never_crosses_priority(self):
        import time

        old_small = build_pod("old-small", {slice_res("1x1"): 1})
        vip = build_pod("vip", {slice_res("1x1"): 1}, priority=1)
        since = {old_small.namespaced_name: time.monotonic() - 3600}
        order = [
            p.metadata.name
            for p in sort_candidate_pods([old_small, vip], pending_since=since)
        ]
        assert order == ["vip", "old-small"]


class TestPlanner:
    def test_carves_virgin_node_for_pending_pod(self):
        snap = snapshot_of(build_tpu_node(name="n1"))
        pod = build_pod("p", {slice_res("2x2"): 1})
        plan = Planner(make_framework()).plan(snap, [pod])
        geometry = {b.board_index: b.resources for b in plan["n1"].boards}
        assert geometry[0].get(slice_res("2x2"), 0) >= 1
        # the pod was placed in simulation
        assert [p.metadata.name for p in snap.get_node("n1").pods] == ["p"]

    def test_no_lacking_returns_current_state(self):
        ann = annot.status_from_devices(free={0: {"2x2": 2}}, used={})
        snap = snapshot_of(build_tpu_node(name="n1", annotations=ann))
        pod = build_pod("p", {slice_res("2x2"): 1})
        plan = Planner(make_framework()).plan(snap, [pod])
        assert {b.board_index: b.resources for b in plan["n1"].boards} == {
            0: {slice_res("2x2"): 2}
        }
        # nothing was simulated-placed: geometry already served the pod
        assert snap.get_node("n1").pods == []

    def test_reverts_when_no_pod_fits(self):
        # Node can be carved, but the pod's cpu request exceeds the node.
        snap = snapshot_of(build_tpu_node(name="n1"))
        pod = build_pod("p", {slice_res("2x2"): 1, "cpu": 999})
        plan = Planner(make_framework()).plan(snap, [pod])
        assert {b.board_index: b.resources for b in plan["n1"].boards} == {0: {}}

    def test_plain_chip_pod_normalized_and_placed(self):
        snap = snapshot_of(build_tpu_node(name="n1"))
        pod = build_pod("p", {constants.RESOURCE_TPU: 8})
        plan = Planner(make_framework()).plan(snap, [pod])
        geometry = {b.board_index: b.resources for b in plan["n1"].boards}
        assert geometry[0] == {slice_res("2x4"): 1}

    def test_multiple_pods_packed_on_one_node(self):
        snap = snapshot_of(build_tpu_node(name="n1"))
        pods = [build_pod(f"p{i}", {slice_res("1x1"): 1}) for i in range(8)]
        plan = Planner(make_framework()).plan(snap, [pods[0], *pods[1:]])
        geometry = {b.board_index: b.resources for b in plan["n1"].boards}
        assert geometry[0] == {slice_res("1x1"): 8}
        assert len(snap.get_node("n1").pods) == 8

    def test_spreads_over_two_nodes(self):
        snap = snapshot_of(build_tpu_node(name="n1"), build_tpu_node(name="n2"))
        pods = [build_pod(f"p{i}", {slice_res("2x4"): 1}) for i in range(2)]
        plan = Planner(make_framework()).plan(snap, pods)
        for name in ("n1", "n2"):
            geometry = {b.board_index: b.resources for b in plan[name].boards}
            assert geometry[0] == {slice_res("2x4"): 1}

    def test_high_priority_pod_wins_contention(self):
        snap = snapshot_of(build_tpu_node(name="n1"))
        low = build_pod("low", {slice_res("2x4"): 1}, priority=0)
        high = build_pod("high", {slice_res("2x4"): 1}, priority=10)
        Planner(make_framework()).plan(snap, [low, high])
        assert [p.metadata.name for p in snap.get_node("n1").pods] == ["high"]

    def test_used_slices_preserved(self):
        ann = annot.status_from_devices(free={}, used={0: {"2x2": 1}})
        running = build_pod("running", {slice_res("2x2"): 1}, node="n1")
        snap = snapshot_of(
            build_tpu_node(name="n1", annotations=ann),
            pods_by_node={"n1": [running]},
        )
        pod = build_pod("p", {slice_res("1x1"): 2})
        plan = Planner(make_framework()).plan(snap, [pod])
        geometry = {b.board_index: b.resources for b in plan["n1"].boards}
        assert geometry[0].get(slice_res("2x2"), 0) == 1
        assert geometry[0].get(slice_res("1x1"), 0) >= 2

    def test_unschedulable_filter_blocks_placement(self):
        class RejectAll:
            name = "RejectAll"

            def filter(self, state, pod, node_info):
                return Status.unschedulable("no", self.name)

        snap = snapshot_of(build_tpu_node(name="n1"))
        pod = build_pod("p", {slice_res("2x2"): 1})
        fw = Framework(filter_plugins=[RejectAll()])
        plan = Planner(fw).plan(snap, [pod])
        assert {b.board_index: b.resources for b in plan["n1"].boards} == {0: {}}
        assert snap.get_node("n1").pods == []


class TestPlannerRegressions:
    """Deadlock scenarios found in review: shared free pool, net-lacking
    double count, and mixed-generation normalization."""

    def test_two_pods_sharing_one_free_slice_get_second_carved(self):
        ann = annot.status_from_devices(free={0: {"2x2": 1}}, used={})
        snap = snapshot_of(build_tpu_node(name="n1", annotations=ann))
        pods = [build_pod(f"p{i}", {slice_res("2x2"): 1}) for i in range(2)]
        plan = Planner(make_framework()).plan(snap, pods)
        geometry = {b.board_index: b.resources for b in plan["n1"].boards}
        assert geometry[0].get(slice_res("2x2"), 0) == 2
        # p0 is claim-placed onto the pre-existing free slice (so the carve
        # pass cannot destroy it); p1's slice was carved, and its simulated
        # placement follows.
        assert sorted(
            p.metadata.name for p in snap.get_node("n1").pods
        ) == ["p0", "p1"]

    def test_pod_wanting_more_than_net_delta_triggers_carve(self):
        ann = annot.status_from_devices(free={0: {"2x2": 1}}, used={})
        snap = snapshot_of(build_tpu_node(name="n1", annotations=ann))
        pod = build_pod("p", {slice_res("2x2"): 2})
        plan = Planner(make_framework()).plan(snap, [pod])
        geometry = {b.board_index: b.resources for b in plan["n1"].boards}
        assert geometry[0].get(slice_res("2x2"), 0) == 2
        assert len(snap.get_node("n1").pods) == 1

    def test_mixed_generation_cluster_serves_plain_chips(self):
        from nos_tpu.api.v1alpha1 import annotations as annot_api
        from tests.factory import V4
        # v4 node fully used; virgin v5e node must serve the 4-chip pod.
        full = annot_api.status_from_devices(free={}, used={0: {"2x2x1": 1}})
        snap = snapshot_of(
            build_tpu_node(name="v4-full", accelerator=V4, chips=4, annotations=full),
            build_tpu_node(name="v5e-virgin"),
        )
        pod = build_pod("p", {constants.RESOURCE_TPU: 4})
        plan = Planner(make_framework()).plan(snap, [pod])
        geometry = {b.board_index: b.resources for b in plan["v5e-virgin"].boards}
        assert geometry[0].get(slice_res("2x2"), 0) >= 1
        assert [p.metadata.name for p in snap.get_node("v5e-virgin").pods] == ["p"]


class TestPlannerSimulationFidelity:
    """VERDICT #5: the planner's embedded simulation runs the same vanilla
    predicates as the real scheduler (taints, affinity, cordon), so it
    never carves for a pod the scheduler would then refuse to place."""

    def test_declines_carve_for_untolerated_pod(self):
        from nos_tpu.kube.objects import Taint
        from nos_tpu.scheduler.framework import vanilla_filter_plugins

        node = build_tpu_node(name="n1")
        node.spec.taints = [Taint(key="maintenance", effect="NoSchedule")]
        snap = snapshot_of(node)
        pod = build_pod("p", {slice_res("2x2"): 1})
        planner = Planner(Framework(filter_plugins=vanilla_filter_plugins()))
        plan = planner.plan(snap, [pod])
        geometry = {b.board_index: b.resources for b in plan["n1"].boards}
        assert geometry[0].get(slice_res("2x2"), 0) == 0, geometry
        assert snap.get_node("n1").pods == []

    def test_carves_for_tolerated_pod(self):
        from nos_tpu.kube.objects import Taint, Toleration
        from nos_tpu.scheduler.framework import vanilla_filter_plugins

        node = build_tpu_node(name="n1")
        node.spec.taints = [Taint(key="maintenance", effect="NoSchedule")]
        snap = snapshot_of(node)
        pod = build_pod("p", {slice_res("2x2"): 1})
        pod.spec.tolerations = [Toleration(key="maintenance", operator="Exists")]
        planner = Planner(Framework(filter_plugins=vanilla_filter_plugins()))
        plan = planner.plan(snap, [pod])
        geometry = {b.board_index: b.resources for b in plan["n1"].boards}
        assert geometry[0].get(slice_res("2x2"), 0) >= 1

    def test_declines_carve_for_cordoned_node(self):
        from nos_tpu.scheduler.framework import vanilla_filter_plugins

        node = build_tpu_node(name="n1")
        node.spec.unschedulable = True
        snap = snapshot_of(node)
        pod = build_pod("p", {slice_res("2x2"): 1})
        planner = Planner(Framework(filter_plugins=vanilla_filter_plugins()))
        planner.plan(snap, [pod])
        assert snap.get_node("n1").pods == []

    def test_declines_carve_for_affinity_mismatch(self):
        from nos_tpu.kube.objects import (
            NodeAffinity,
            NodeSelectorRequirement,
            NodeSelectorTerm,
        )
        from nos_tpu.scheduler.framework import vanilla_filter_plugins

        snap = snapshot_of(build_tpu_node(name="n1"))
        pod = build_pod("p", {slice_res("2x2"): 1})
        pod.spec.affinity = NodeAffinity(required_terms=[
            NodeSelectorTerm(match_expressions=[
                NodeSelectorRequirement(key="pool", operator="In", values=["gold"]),
            ])
        ])
        planner = Planner(Framework(filter_plugins=vanilla_filter_plugins()))
        planner.plan(snap, [pod])
        assert snap.get_node("n1").pods == []

    def test_declines_carve_when_anti_affinity_violated(self):
        from nos_tpu.kube.objects import PodAffinityTerm
        from nos_tpu.scheduler.framework import vanilla_filter_plugins

        node = build_tpu_node(name="n1")
        node.metadata.labels["topology.kubernetes.io/zone"] = "zone-a"
        resident = build_pod("resident", {"cpu": 1})
        resident.metadata.labels["app"] = "web"
        snap = snapshot_of(node, pods_by_node={"n1": [resident]})
        pod = build_pod("web-new", {slice_res("2x2"): 1})
        pod.metadata.labels["app"] = "web"
        pod.spec.pod_anti_affinity = [PodAffinityTerm(
            topology_key="topology.kubernetes.io/zone",
            match_labels={"app": "web"},
        )]
        planner = Planner(Framework(filter_plugins=vanilla_filter_plugins()))
        planner.plan(snap, [pod])
        assert "web-new" not in [p.metadata.name for p in snap.get_node("n1").pods]

    def test_declines_carve_when_topology_spread_violated(self):
        from nos_tpu.kube.objects import TopologySpreadConstraint
        from nos_tpu.scheduler.framework import vanilla_filter_plugins

        zone_a = build_tpu_node(name="n-a")
        zone_a.metadata.labels["topology.kubernetes.io/zone"] = "zone-a"
        # A second domain exists with zero replicas, so adding a third
        # replica to zone-a would skew 3-0=3 > maxSkew 1. The zone-b node
        # is fully used (no boards to carve), so no placement satisfies
        # the constraint and the planner must not carve on zone-a.
        from nos_tpu.api.v1alpha1 import annotations as annot_api

        used = annot_api.status_from_devices(free={}, used={0: {"2x4": 1}})
        zone_b = build_tpu_node(name="n-b", annotations=used)
        zone_b.metadata.labels["topology.kubernetes.io/zone"] = "zone-b"
        running = []
        for i in range(2):
            r = build_pod(f"web-{i}", {"cpu": 1})
            r.metadata.labels["app"] = "web"
            running.append(r)
        snap = snapshot_of(zone_a, zone_b, pods_by_node={"n-a": running})
        pod = build_pod("web-new", {slice_res("2x2"): 1})
        pod.metadata.labels["app"] = "web"
        pod.spec.topology_spread_constraints = [
            TopologySpreadConstraint(
                topology_key="topology.kubernetes.io/zone",
                max_skew=1,
                match_labels={"app": "web"},
            )
        ]
        planner = Planner(Framework(filter_plugins=vanilla_filter_plugins()))
        planner.plan(snap, [pod])
        assert "web-new" not in [p.metadata.name for p in snap.get_node("n-a").pods]


class TestPlannerGangFidelity:
    """VERDICT #5: a half-formable gang triggers no carve (SURVEY §7 — a
    slice carved for a lone gang member is a slice the gang can never use)."""

    def _gang_pod(self, name, gang, size, res=None):
        pod = build_pod(name, res or {slice_res("2x2"): 1}, ns="team")
        pod.metadata.labels["nos.nebuly.com/gang"] = gang
        pod.metadata.labels["nos.nebuly.com/gang-size"] = str(size)
        return pod

    def test_half_formable_gang_triggers_no_carve(self):
        # gang of 3 but only 2 members pending and capacity for 2 -> the
        # gang can never complete; nothing may be carved for it.
        node = build_tpu_node(name="n1")  # one 2x4 board = 8 chips
        snap = snapshot_of(node)
        pods = [self._gang_pod(f"m{i}", "trainer", 3) for i in range(2)]
        planner = Planner(Framework(filter_plugins=[NodeResourcesFit(), NodeSelectorFit()]))
        plan = planner.plan(snap, pods)
        geometry = {b.board_index: b.resources for b in plan["n1"].boards}
        assert geometry[0].get(slice_res("2x2"), 0) == 0, geometry
        assert snap.get_node("n1").pods == []

    def test_fully_formable_gang_is_carved(self):
        node = build_tpu_node(name="n1")
        snap = snapshot_of(node)
        pods = [self._gang_pod(f"m{i}", "trainer", 2) for i in range(2)]
        planner = Planner(Framework(filter_plugins=[NodeResourcesFit(), NodeSelectorFit()]))
        plan = planner.plan(snap, pods)
        geometry = {b.board_index: b.resources for b in plan["n1"].boards}
        assert geometry[0].get(slice_res("2x2"), 0) >= 2
        assert len(snap.get_node("n1").pods) == 2

    def test_gang_exclusion_leaves_other_pods_served(self):
        node = build_tpu_node(name="n1")
        snap = snapshot_of(node)
        loner = build_pod("solo", {slice_res("2x2"): 1})
        gang = [self._gang_pod(f"m{i}", "trainer", 5) for i in range(2)]
        planner = Planner(Framework(filter_plugins=[NodeResourcesFit(), NodeSelectorFit()]))
        planner.plan(snap, gang + [loner])
        assert [p.metadata.name for p in snap.get_node("n1").pods] == ["solo"]

    def test_gang_counts_already_running_members(self):
        # 1 member already bound on the node + 1 pending = size 2: formable.
        node = build_tpu_node(name="n1")
        running = self._gang_pod("m0", "trainer", 2)
        running.spec.node_name = "n1"
        snap = snapshot_of(node, pods_by_node={"n1": [running]})
        pending = self._gang_pod("m1", "trainer", 2)
        planner = Planner(Framework(filter_plugins=[NodeResourcesFit(), NodeSelectorFit()]))
        planner.plan(snap, [pending])
        names = [p.metadata.name for p in snap.get_node("n1").pods]
        assert "m1" in names

    def test_gang_member_on_fully_carved_node_still_counts(self):
        # m0 runs on n1 whose board is fully carved (n1 is NOT a carve
        # candidate); m1 pending with room on n2. The gang (size 2) is
        # fully formable and must not be excluded.
        from nos_tpu.api.v1alpha1 import annotations as annot

        full_ann = annot.status_from_devices(free={}, used={0: {"2x4": 1}})
        n1 = build_tpu_node(name="n1", annotations=full_ann)
        running = self._gang_pod("m0", "trainer", 2, res={slice_res("2x4"): 1})
        running.spec.node_name = "n1"
        n2 = build_tpu_node(name="n2")
        snap = snapshot_of(n1, n2, pods_by_node={"n1": [running]})
        assert "n1" not in snap.get_candidate_nodes()  # premise of the test
        pending = self._gang_pod("m1", "trainer", 2)
        planner = Planner(Framework(filter_plugins=[NodeResourcesFit(), NodeSelectorFit()]))
        planner.plan(snap, [pending])
        assert [p.metadata.name for p in snap.get_node("n2").pods] == ["m1"]


class TestAgedRescue:
    """The aged-rescue pass: a starved small pod must win a dedicated
    carve of a contested free region BEFORE exact-fit pods claim it."""

    def aged_planner(self, *pods, age=10.0):
        import time

        planner = Planner(make_framework())
        now = time.monotonic()
        for pod in pods:
            planner._pending_seen[(pod.namespaced_name, pod.metadata.uid)] = (
                now - age,
                now,
            )
        return planner

    def test_aged_small_pod_wins_contested_free_slice(self):
        # One free 2x2 (rest of the board used), a fresh 4-chip pod that
        # fits it exactly, and a 1-chip pod aged past the rescue
        # threshold. Without the rescue the 2x2 goes whole to the 4-chip
        # pod every round (the free pool cannot serve 1 chip) and the
        # 1-chip pod starves forever.
        ann = annot.status_from_devices(free={0: {"2x2": 1}}, used={0: {"2x2": 1}})
        used_pod = build_pod("holder", {slice_res("2x2"): 1}, node="n1", phase="Running")
        snap = snapshot_of(
            build_tpu_node(name="n1", annotations=ann),
            pods_by_node={"n1": [used_pod]},
        )
        starved = build_pod("starved", {constants.RESOURCE_TPU: 1}, ns="ml")
        fresh = build_pod("fresh", {constants.RESOURCE_TPU: 4}, ns="ml")
        planner = self.aged_planner(starved)
        planner.plan(snap, [starved, fresh])
        placed = [p.metadata.name for p in snap.get_node("n1").pods]
        assert "starved" in placed, placed

    def test_fresh_small_pod_does_not_trigger_rescue(self):
        # Same shape but nobody is aged: pure FFD gives the free 2x2 to
        # the exact-fit 4-chip pod and the 1-chip pod waits (the normal
        # packing order the rescue must NOT disturb).
        ann = annot.status_from_devices(free={0: {"2x2": 1}}, used={0: {"2x2": 1}})
        used_pod = build_pod("holder", {slice_res("2x2"): 1}, node="n1", phase="Running")
        snap = snapshot_of(
            build_tpu_node(name="n1", annotations=ann),
            pods_by_node={"n1": [used_pod]},
        )
        small = build_pod("small", {constants.RESOURCE_TPU: 1}, ns="ml")
        fresh = build_pod("fresh", {constants.RESOURCE_TPU: 4}, ns="ml")
        planner = Planner(make_framework())
        planner.plan(snap, [small, fresh])
        placed = [p.metadata.name for p in snap.get_node("n1").pods]
        assert "fresh" in placed and "small" not in placed, placed
