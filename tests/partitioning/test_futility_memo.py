"""Carve-futility memo correctness: a (node, version, lacking-signature)
whose carve was a geometry no-op is never re-tried within the same plan,
and the memoized reason strings are the SAME strings ``last_unserved``
serves to the partitioner's CarveFailed Events.

The memo's exactness rides on the mutation clock: a failed
``update_geometry_for`` never stamps a node version and ``revert``
restores pre-fork versions, so a key that was futile once stays futile
until the node actually changes."""
import random

import pytest

from nos_tpu.api.v1alpha1 import annotations as annot
from nos_tpu.partitioning.core import (
    ClusterSnapshot,
    Planner,
    SnapshotNode,
    partitioning_state_equal,
)
from nos_tpu.tpu.node import TpuNode
from nos_tpu.util.metrics import REGISTRY

from tests.factory import build_pod, build_tpu_node, slice_res
from tests.partitioning.test_verdict_cache import (
    build_cluster,
    full_framework,
    node_local_framework,
    placements,
    random_pending_pods,
)


def snapshot_node(name, annotations=None):
    node = build_tpu_node(name=name, annotations=annotations)
    return SnapshotNode(partitionable=TpuNode(node))


def fragmented_node(name):
    """1 free chip, 7 used: a candidate node (free capacity exists) that
    can never yield a multi-chip slice — every carve toward one is a
    geometry no-op."""
    return snapshot_node(
        name,
        annot.status_from_devices(
            free={0: {"1x1": 1}}, used={0: {"2x2": 1, "1x2": 1, "1x1": 1}}
        ),
    )


def half_used_node(name):
    """4 free chips as one free 2x2 — re-carvable toward smaller slices."""
    return snapshot_node(
        name,
        annot.status_from_devices(free={0: {"2x2": 1}}, used={0: {"2x2": 1}}),
    )


def gang_pod(name, req):
    pod = build_pod(name, req)
    pod.metadata.labels["nos.nebuly.com/gang"] = name
    pod.metadata.labels["nos.nebuly.com/gang-size"] = "1"
    return pod


class TestFutilityMemoOnOffEquivalence:
    @pytest.mark.parametrize("seed", range(10))
    def test_plan_identical_with_and_without_memo(self, seed):
        on_snap = build_cluster(random.Random(2000 + seed))
        off_snap = build_cluster(random.Random(2000 + seed))
        pods = random_pending_pods(random.Random(3000 + seed), with_constraints=True)
        plan_on = Planner(full_framework(), futility_memo_enabled=True).plan(
            on_snap, [p.deepcopy() for p in pods]
        )
        plan_off = Planner(full_framework(), futility_memo_enabled=False).plan(
            off_snap, [p.deepcopy() for p in pods]
        )
        assert partitioning_state_equal(plan_on, plan_off), f"seed={seed}"
        assert placements(on_snap) == placements(off_snap), f"seed={seed}"
        assert not on_snap.forked and not off_snap.forked


class TestFutilityMemoHits:
    """The deterministic repeat-consultation scenario: a size-1 gang forces
    the two-pass path (reuse disabled), so the fragmented node's futile
    carve — memoized during the trial pass — is consulted again with the
    identical (node, version, lacking) key by the real pass."""

    def _cluster(self):
        return ClusterSnapshot(
            {"frag-0": fragmented_node("frag-0"), "half-0": half_used_node("half-0")}
        )

    def test_two_pass_gang_plan_hits_the_memo(self):
        snapshot = self._cluster()
        planner = Planner(node_local_framework(), reuse_gang_trial=False)
        # Best-fit order visits frag-0 (1 free chip) before half-0 (4):
        # the futile trial on frag-0 happens before the pod lands.
        assert planner._candidate_nodes(snapshot) == ["frag-0", "half-0"]
        before = REGISTRY.snapshot().get("nos_tpu_plan_carve_futility_total", 0.0)
        planner.plan(snapshot, [gang_pod("gm", {slice_res("1x2"): 1})])
        assert planner._futility_hits == 1
        assert placements(snapshot)["half-0"] == ["default/gm"]
        assert not snapshot.forked
        after = REGISTRY.snapshot().get("nos_tpu_plan_carve_futility_total", 0.0)
        assert after - before == 1

    def test_memo_off_re_runs_the_futile_trial(self):
        on_snap = self._cluster()
        off_snap = self._cluster()
        pod = gang_pod("gm", {slice_res("1x2"): 1})
        plan_on = Planner(
            node_local_framework(), reuse_gang_trial=False, futility_memo_enabled=True
        ).plan(on_snap, [pod.deepcopy()])
        off_planner = Planner(
            node_local_framework(), reuse_gang_trial=False, futility_memo_enabled=False
        )
        plan_off = off_planner.plan(off_snap, [pod.deepcopy()])
        assert off_planner._futility_hits == 0
        assert partitioning_state_equal(plan_on, plan_off)
        assert placements(on_snap) == placements(off_snap)

    def test_memoized_reason_is_the_canonical_lacking_reason(self):
        snapshot = self._cluster()
        planner = Planner(node_local_framework(), reuse_gang_trial=False)
        planner.plan(snapshot, [gang_pod("gm", {slice_res("1x2"): 1})])
        key = ("frag-0", 0, ((slice_res("1x2"), 1),))
        assert planner._futility_cache[key] == Planner._lacking_reason(
            {slice_res("1x2"): 1}
        )


class TestLastUnserved:
    """``last_unserved`` is the planner's diagnosis surface: served pods
    absent, unserved pods present with the same reason string the memo
    stores — the partitioner's CarveFailed Events read it verbatim."""

    def test_served_absent_unserved_present_with_lacking_reason(self):
        snapshot = ClusterSnapshot({"half-0": half_used_node("half-0")})
        planner = Planner(node_local_framework())
        ok = build_pod("ok", {slice_res("1x2"): 1}, ns="ml")
        big = build_pod("big", {slice_res("2x4"): 1}, ns="ml")
        planner.plan(snapshot, [ok, big])
        assert placements(snapshot)["half-0"] == ["ml/ok"]
        assert planner.last_unserved == {
            "ml/big": Planner._lacking_reason({slice_res("2x4"): 1})
        }

    def test_half_formable_gang_gets_the_gang_reason(self):
        snapshot = ClusterSnapshot({"frag-0": fragmented_node("frag-0")})
        planner = Planner(node_local_framework())
        planner.plan(snapshot, [gang_pod("gm", {slice_res("2x2"): 1})])
        assert planner.last_unserved == {
            "default/gm": (
                "gang default/gm cannot fully form; "
                "no slices are carved for partial gangs"
            )
        }

    def test_fully_served_plan_leaves_it_empty(self):
        # The free pool already holds the requested 2x2: nothing lacking,
        # the plan is a no-op, and the diagnosis surface must say so.
        snapshot = ClusterSnapshot({"half-0": half_used_node("half-0")})
        planner = Planner(node_local_framework())
        planner.plan(snapshot, [build_pod("fit", {slice_res("2x2"): 1}, ns="ml")])
        assert planner.last_unserved == {}
        assert not snapshot.forked
