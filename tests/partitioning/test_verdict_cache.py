"""Verdict-cache correctness: cached verdicts vs the fresh framework run.

The equivalence-class cache (verdict_cache.py) keys a PreFilter+Filter
verdict by (pod signature, node name, node mutation version). These tests
pin the three load-bearing guarantees:

- the mutation clock: every snapshot-level mutation stamps a fresh,
  never-repeating version on the node and the snapshot, and revert
  restores the pre-fork versions exactly (re-validating old entries);
- the property: a cached planner's ``_can_schedule`` answer equals a
  cache-disabled planner's answer for random (pod, node) probes across
  randomized fork/commit/revert + geometry-mutation + placement
  sequences, over pods spanning the signed field set (requests,
  nodeSelector, tolerations, node affinity) plus bypass-triggering
  anti-affinity pods;
- the plan: full plan() with the cache on equals plan() with it off, and
  the gang-trial-reuse shortcut equals the two-pass path, down to the
  projected PartitioningState and per-node placements.
"""
import random

import pytest

from nos_tpu.api.v1alpha1 import annotations as annot
from nos_tpu.api.v1alpha1 import constants, labels
from nos_tpu.kube.objects import (
    NodeAffinity,
    NodeSelectorRequirement,
    NodeSelectorTerm,
    PodAffinityTerm,
    Toleration,
    TopologySpreadConstraint,
)
from nos_tpu.partitioning.core import (
    ClusterSnapshot,
    Planner,
    SnapshotNode,
    VerdictCache,
    partitioning_state_equal,
)
from nos_tpu.partitioning.core.verdict_cache import (
    needs_cluster_context,
    pod_signature,
)
from nos_tpu.scheduler.framework import (
    Framework,
    InterPodAffinityFit,
    NodeAffinityFit,
    NodeResourcesFit,
    NodeSelectorFit,
    PodTopologySpreadFit,
    TaintTolerationFit,
)
from nos_tpu.tpu.node import TpuNode

from tests.factory import V5E, build_pod, build_tpu_node, slice_res

PROFILES = ["1x1", "1x2", "2x2", "2x4"]


def build_cluster(rng, n_min=3, n_max=6):
    """Deterministic mixed-fill cluster from `rng`'s current state — call
    twice with identically-seeded rngs to get twin clusters."""
    nodes = {}
    for i in range(rng.randint(n_min, n_max)):
        name = f"n{i}"
        style = rng.random()
        if style < 0.4:
            annotations = None  # virgin board
        elif style < 0.7:
            annotations = annot.status_from_devices(
                free={0: {rng.choice(PROFILES): 1}}, used={}
            )
        else:
            annotations = annot.status_from_devices(
                free={0: {"2x2": 1}}, used={0: {"2x2": 1}}
            )
        node = build_tpu_node(name=name, annotations=annotations)
        nodes[name] = SnapshotNode(partitionable=TpuNode(node))
    return ClusterSnapshot(nodes)


def node_local_framework():
    return Framework(
        filter_plugins=[
            NodeResourcesFit(),
            NodeSelectorFit(),
            NodeAffinityFit(),
            TaintTolerationFit(),
        ]
    )


def full_framework():
    """Every in-tree predicate, including the cross-node ones whose
    correctness rides on the planner's bypass condition."""
    return Framework(
        filter_plugins=[
            NodeResourcesFit(),
            NodeSelectorFit(),
            NodeAffinityFit(),
            TaintTolerationFit(),
            PodTopologySpreadFit(),
            InterPodAffinityFit(),
        ]
    )


def anti_affinity_term():
    return PodAffinityTerm(
        topology_key="kubernetes.io/hostname", match_labels={"app": "db"}
    )


def probe_pods():
    """Pods spanning the signed field set: request shapes, matching and
    non-matching nodeSelector, tolerations, required node affinity (both
    outcomes), and an anti-affinity pod that must bypass the cache."""
    pods = []
    for i, req in enumerate(
        [
            {slice_res("1x1"): 1},
            {slice_res("2x2"): 1},
            {slice_res("2x4"): 1},
            {constants.RESOURCE_TPU: 4},
            {constants.RESOURCE_TPU: 1},
        ]
    ):
        pods.append(build_pod(f"req-{i}", req))
    sel = build_pod("sel-match", {slice_res("1x1"): 1})
    sel.spec.node_selector = {labels.GKE_TPU_ACCELERATOR_LABEL: V5E}
    pods.append(sel)
    miss = build_pod("sel-miss", {slice_res("1x1"): 1})
    miss.spec.node_selector = {"topology.kubernetes.io/zone": "nowhere"}
    pods.append(miss)
    tol = build_pod("tolerant", {slice_res("1x1"): 1})
    tol.spec.tolerations = [
        Toleration(key="dedicated", operator="Equal", value="tpu", effect="NoSchedule")
    ]
    pods.append(tol)
    aff = build_pod("aff-match", {slice_res("1x1"): 1})
    aff.spec.affinity = NodeAffinity(
        required_terms=[
            NodeSelectorTerm(
                match_expressions=[
                    NodeSelectorRequirement(
                        key=labels.GKE_TPU_ACCELERATOR_LABEL,
                        operator="In",
                        values=[V5E],
                    )
                ]
            )
        ]
    )
    pods.append(aff)
    affmiss = build_pod("aff-miss", {slice_res("1x1"): 1})
    affmiss.spec.affinity = NodeAffinity(
        required_terms=[
            NodeSelectorTerm(
                match_expressions=[
                    NodeSelectorRequirement(
                        key=labels.GKE_TPU_ACCELERATOR_LABEL,
                        operator="In",
                        values=["some-other-generation"],
                    )
                ]
            )
        ]
    )
    pods.append(affmiss)
    anti = build_pod("anti", {slice_res("1x1"): 1})
    anti.spec.pod_anti_affinity = [anti_affinity_term()]
    pods.append(anti)
    return pods


class TestMutationClock:
    def test_mutations_stamp_unique_versions(self):
        snap = build_cluster(random.Random(1))
        node = snap.get_nodes()["n0"]
        assert node.version == 0 and snap.state_version == 0
        assert snap.update_geometry_for("n0", {slice_res("1x1"): 1})
        v_carve = node.version
        assert v_carve > 0 and snap.state_version == v_carve
        assert snap.add_pod("n0", build_pod("p1", {slice_res("1x1"): 1}))
        v_place = node.version
        assert v_place > v_carve and snap.state_version == v_place

    def test_revert_restores_versions_exactly(self):
        snap = build_cluster(random.Random(2))
        assert snap.update_geometry_for("n0", {slice_res("1x1"): 1})
        node = snap.get_nodes()["n0"]
        v_before, sv_before = node.version, snap.state_version
        snap.fork()
        assert snap.update_geometry_for("n0", {slice_res("1x2"): 1})
        assert snap.get_nodes()["n0"].version > v_before
        snap.revert()
        assert snap.get_nodes()["n0"].version == v_before
        assert snap.state_version == sv_before

    def test_commit_keeps_versions(self):
        snap = build_cluster(random.Random(3))
        snap.fork()
        assert snap.update_geometry_for("n0", {slice_res("1x1"): 1})
        v_mut, sv_mut = snap.get_nodes()["n0"].version, snap.state_version
        snap.commit()
        assert snap.get_nodes()["n0"].version == v_mut
        assert snap.state_version == sv_mut

    def test_versions_never_alias_across_revert(self):
        # The same mutation replayed after a revert reaches the same
        # geometry but must get a FRESH version — (name, version) may
        # never mean two different journal histories.
        snap = build_cluster(random.Random(4))
        snap.fork()
        assert snap.update_geometry_for("n0", {slice_res("1x1"): 1})
        v_first = snap.get_nodes()["n0"].version
        snap.revert()
        snap.fork()
        assert snap.update_geometry_for("n0", {slice_res("1x1"): 1})
        v_second = snap.get_nodes()["n0"].version
        snap.revert()
        assert v_second != v_first


class TestSignatureAndBypass:
    def test_signature_is_an_equivalence_class_not_an_identity(self):
        # Same spec, different name/uid -> same trial.
        a = build_pod("alpha", {slice_res("2x2"): 1})
        b = build_pod("beta", {slice_res("2x2"): 1})
        assert pod_signature(a) == pod_signature(b)

    @pytest.mark.parametrize(
        "mutate",
        [
            lambda p: p.spec.containers[0].requests.update({"cpu": 2}),
            lambda p: p.spec.node_selector.update({"zone": "a"}),
            lambda p: p.metadata.labels.update({"team": "ml"}),
            lambda p: p.spec.tolerations.append(
                Toleration(key="k", operator="Exists", effect="NoSchedule")
            ),
            lambda p: setattr(
                p.spec,
                "affinity",
                NodeAffinity(
                    required_terms=[
                        NodeSelectorTerm(
                            match_expressions=[
                                NodeSelectorRequirement(key="k", operator="Exists")
                            ]
                        )
                    ]
                ),
            ),
        ],
    )
    def test_signature_covers_every_signed_field(self, mutate):
        base = build_pod("base", {slice_res("2x2"): 1})
        other = build_pod("base", {slice_res("2x2"): 1})
        mutate(other)
        assert pod_signature(base) != pod_signature(other)

    def test_needs_cluster_context(self):
        plain = build_pod("plain", {slice_res("1x1"): 1})
        assert not needs_cluster_context(plain)
        anti = build_pod("anti", {slice_res("1x1"): 1})
        anti.spec.pod_anti_affinity = [anti_affinity_term()]
        assert needs_cluster_context(anti)
        spread = build_pod("spread", {slice_res("1x1"): 1})
        spread.spec.topology_spread_constraints = [
            TopologySpreadConstraint(
                topology_key="kubernetes.io/hostname", match_labels={"app": "x"}
            )
        ]
        assert needs_cluster_context(spread)

    def test_cache_counts_and_hit_rate(self):
        cache = VerdictCache()
        key = (("sig",), "n0", 1)
        assert cache.get(key) is None  # miss
        cache.put(key, False)
        assert cache.get(key) is False  # a cached False is a hit, not a miss
        cache.bypasses += 1
        assert cache.stats() == (1, 1, 1)
        assert cache.lookups == 3
        assert cache.hit_rate() == 0.5


class TestCachedVerdictEqualsFreshRun:
    """The property: across randomized fork/commit/revert + mutation
    sequences on ONE snapshot, a cache-enabled planner answers every
    (pod, node) schedulability probe identically to a cache-disabled one
    running the framework fresh."""

    @pytest.mark.parametrize("seed", range(10))
    def test_property_random_mutation_sequences(self, seed):
        rng = random.Random(4000 + seed)
        snapshot = build_cluster(random.Random(1000 + seed))
        framework = node_local_framework()
        cached = Planner(framework, verdict_cache_enabled=True)
        fresh = Planner(framework, verdict_cache_enabled=False)
        pods = probe_pods()
        names = list(snapshot.get_nodes())
        depth = 0
        serial = 0
        for step in range(40):
            context = f"seed={seed} step={step}"
            roll = rng.random()
            if roll < 0.15 and depth < 3:
                snapshot.fork()
                depth += 1
            elif roll < 0.3 and depth > 0:
                snapshot.revert()
                depth -= 1
            elif roll < 0.4 and depth > 0:
                snapshot.commit()
                depth -= 1
            elif roll < 0.7:
                snapshot.update_geometry_for(
                    rng.choice(names),
                    {slice_res(rng.choice(PROFILES)): rng.randint(1, 2)},
                )
            else:
                serial += 1
                pod = build_pod(
                    f"placed-{serial}", {slice_res(rng.choice(PROFILES)): 1}
                )
                if rng.random() < 0.1:
                    # Occasionally PLACE an anti-affinity pod: from then on
                    # (until a revert undoes it) every probe must take the
                    # snapshot-wide bypass, and the two planners must still
                    # agree.
                    pod.spec.pod_anti_affinity = [anti_affinity_term()]
                snapshot.add_pod(rng.choice(names), pod)
            for _ in range(3):
                pod = rng.choice(pods)
                node_name = rng.choice(names)
                assert cached._can_schedule(snapshot, node_name, pod) == (
                    fresh._can_schedule(snapshot, node_name, pod)
                ), f"{context} pod={pod.metadata.name} node={node_name}"
        while depth:
            snapshot.revert()
            depth -= 1
        # Every probe must have gone THROUGH the cache layer (hit, miss,
        # or counted bypass — a seed that places an anti-affinity pod
        # early legitimately bypasses from then on; the deterministic
        # hit/bypass assertions live in TestPlanCacheOnOffEquivalence).
        assert cached._verdict_cache.lookups > 0, f"seed={seed}"


def random_pending_pods(rng, with_constraints=False):
    pods = []
    for i in range(rng.randint(2, 10)):
        style = rng.random()
        if style < 0.5:
            req = {slice_res(rng.choice(PROFILES)): 1}
        elif style < 0.8:
            req = {constants.RESOURCE_TPU: rng.choice([1, 2, 4, 8])}
        else:
            req = {slice_res("1x1"): 1, "cpu": 1}
        pod = build_pod(f"pend-{i}", req, priority=rng.choice([0, 0, 0, 10]))
        if rng.random() < 0.25:
            pod.metadata.labels["nos.nebuly.com/gang"] = f"g{rng.randint(0, 1)}"
            pod.metadata.labels["nos.nebuly.com/gang-size"] = str(rng.randint(1, 3))
        if with_constraints:
            style = rng.random()
            if style < 0.15:
                pod.spec.node_selector = {labels.GKE_TPU_ACCELERATOR_LABEL: V5E}
            elif style < 0.25:
                pod.spec.pod_anti_affinity = [anti_affinity_term()]
            elif style < 0.35:
                pod.metadata.labels["app"] = "spreadme"
                pod.spec.topology_spread_constraints = [
                    TopologySpreadConstraint(
                        topology_key="kubernetes.io/hostname",
                        match_labels={"app": "spreadme"},
                    )
                ]
        pods.append(pod)
    return pods


def placements(snapshot):
    return {
        name: [p.namespaced_name for p in node.pods]
        for name, node in snapshot.get_nodes().items()
    }


class TestPlanCacheOnOffEquivalence:
    @pytest.mark.parametrize("seed", range(10))
    def test_plan_identical_with_and_without_cache(self, seed):
        on_snap = build_cluster(random.Random(2000 + seed))
        off_snap = build_cluster(random.Random(2000 + seed))
        pods = random_pending_pods(random.Random(3000 + seed), with_constraints=True)
        plan_on = Planner(full_framework(), verdict_cache_enabled=True).plan(
            on_snap, [p.deepcopy() for p in pods]
        )
        plan_off = Planner(full_framework(), verdict_cache_enabled=False).plan(
            off_snap, [p.deepcopy() for p in pods]
        )
        assert partitioning_state_equal(plan_on, plan_off), f"seed={seed}"
        assert placements(on_snap) == placements(off_snap), f"seed={seed}"
        assert not on_snap.forked and not off_snap.forked

    def test_plan_records_hits_no_bypass_on_plain_pods(self):
        # "mismatch" sorts first in best-fit order (2 free chips) and keeps
        # a free 1x2 every 1x1 claim probes and fails on: same signature
        # against an unchanged version, so each probe after the first is a
        # cache hit. (Exhausted nodes no longer produce repeat trials — the
        # claim pre-pass skips nodes with no free slices outright.)
        def steady(name, free):
            node = build_tpu_node(
                name=name,
                annotations=annot.status_from_devices(
                    free={0: free}, used={0: {"2x2": 1}}
                ),
            )
            return SnapshotNode(partitionable=TpuNode(node))

        snapshot = ClusterSnapshot(
            {
                "mismatch": steady("mismatch", {"1x2": 1}),
                "serving": steady("serving", {"1x1": 4}),
            }
        )
        planner = Planner(node_local_framework())
        # The lacking 2x4 pod keeps the tracker non-empty (an all-served
        # batch returns before any simulation runs).
        planner.plan(
            snapshot,
            [build_pod(f"p{i}", {slice_res("1x1"): 1}) for i in range(4)]
            + [build_pod("big", {slice_res("2x4"): 1})],
        )
        hits, _, bypasses = planner.verdict_cache_stats()
        assert hits > 0
        assert bypasses == 0

    def test_placed_anti_affinity_pod_forces_bypass(self):
        # Same workload as the hits test above, but with one RUNNING
        # anti-affinity pod on the cluster: its symmetric terms can reject
        # any incoming pod, so every trial must bypass the cache.
        snapshot = build_cluster(random.Random(42), n_min=6, n_max=6)
        anti = build_pod("anti", {}, node="n0")
        anti.spec.pod_anti_affinity = [anti_affinity_term()]
        snapshot.get_nodes()["n0"].pods.append(anti)
        planner = Planner(full_framework())
        planner.plan(
            snapshot,
            [build_pod(f"p{i}", {slice_res("1x1"): 1}) for i in range(12)],
        )
        hits, _, bypasses = planner.verdict_cache_stats()
        assert bypasses > 0
        assert hits == 0


class TestGangTrialReuse:
    """Regression for the reuse shortcut: when no gang is excluded the
    committed trial must be bit-identical to what the two-pass path (trial
    + revert + fresh real pass) produces."""

    @pytest.mark.parametrize("seed", range(10))
    def test_reuse_equals_two_pass(self, seed):
        reuse_snap = build_cluster(random.Random(2000 + seed))
        twopass_snap = build_cluster(random.Random(2000 + seed))
        rng = random.Random(3000 + seed)
        pods = random_pending_pods(rng)
        # Force at least one gang (also fully-formable: size 1) so the
        # trial path actually runs on every seed.
        anchor = build_pod("gang-anchor", {slice_res(rng.choice(PROFILES)): 1})
        anchor.metadata.labels["nos.nebuly.com/gang"] = "anchor"
        anchor.metadata.labels["nos.nebuly.com/gang-size"] = "1"
        pods.append(anchor)
        plan_reuse = Planner(node_local_framework(), reuse_gang_trial=True).plan(
            reuse_snap, [p.deepcopy() for p in pods]
        )
        plan_twopass = Planner(node_local_framework(), reuse_gang_trial=False).plan(
            twopass_snap, [p.deepcopy() for p in pods]
        )
        assert partitioning_state_equal(plan_reuse, plan_twopass), f"seed={seed}"
        assert placements(reuse_snap) == placements(twopass_snap), f"seed={seed}"
        # The reuse path commits the trial fork; nothing may stay forked.
        assert not reuse_snap.forked and not twopass_snap.forked
