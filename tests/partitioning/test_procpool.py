"""Snapcodec wire framing across the REAL worker transport (ISSUE 18
satellite): spawned worker processes fed framed JSON over a pipe — never
a pickled live snapshot — must round-trip save_entries()/adopt() warm
state, reject a codec-version mismatch so the parent cold-boots, and
turn a truncated frame into a clean respawn with zero state carried
over."""
import multiprocessing

import pytest

from nos_tpu.kube.serde import pod_to_wire
from nos_tpu.kube.store import KubeStore
from nos_tpu.partitioning.core import procpool
from nos_tpu.partitioning.core.codec import TpuSliceCodec
from nos_tpu.partitioning.core.planner import Planner
from nos_tpu.partitioning.core.procpool import (
    PoolWorkerPool,
    WorkerUnavailable,
    snapshot_node_to_wire,
)
from nos_tpu.partitioning.core.snapcodec import (
    SNAPSHOT_CODEC_VERSION,
    FrameError,
    WarmStateCodec,
    decode_frame,
    encode_frame,
)
from nos_tpu.partitioning.core.snapshot import ClusterSnapshot
from nos_tpu.partitioning.tpu import TpuSnapshotTaker

from tests.factory import build_pod, build_tpu_node, slice_res

SPEC = {"pre_filter": [], "filter": ["NodeResourcesFit", "NodeSelectorFit"]}
KNOBS = dict(
    aging_chips_per_second=0.0,
    verdict_cache_enabled=True,
    reuse_gang_trial=True,
    futility_memo_enabled=True,
    incremental_dirty_threshold=1.0,
)
# Generous: the CI box is one slow core and a worker spawn re-imports
# the world; these bound hangs, they are not perf assertions.
BOOT_TIMEOUT = 120.0
CYCLE_TIMEOUT = 60.0


def make_world(n=2):
    """(wire entries, {name: SnapshotNode}) for n empty v5e nodes."""
    taker = TpuSnapshotTaker()
    entries, nodes = [], {}
    for i in range(n):
        node = build_tpu_node(name=f"n{i}")
        snap = taker.take_snapshot_node(node, [])
        nodes[node.metadata.name] = snap
        entries.append(snapshot_node_to_wire(snap))
    return entries, nodes


def pending_pod(name="pod-a", profile="2x2"):
    return build_pod(name, {slice_res(profile): 1}, scheduler="")


def cycle_request(pods=(), deltas=()):
    return {
        "pool": "p",
        "deltas": list(deltas),
        "pending": [pod_to_wire(pod) for pod in pods],
        "ages": {},
        "external_usage": {},
    }


@pytest.fixture
def pool():
    wp = PoolWorkerPool(
        "tpu",
        "TpuSliceCodec",
        SPEC,
        dict(KNOBS),
        cycle_timeout_seconds=CYCLE_TIMEOUT,
        bootstrap_timeout_seconds=BOOT_TIMEOUT,
    )
    yield wp
    wp.close()


class TestFraming:
    def test_round_trip(self):
        doc = {"op": "cycle", "deltas": [], "ages": {"default/p": 1.5}}
        assert decode_frame(encode_frame(doc)) == doc

    def test_bad_magic_rejected_before_payload(self):
        data = bytearray(encode_frame({"op": "ping"}))
        data[:4] = b"XXXX"
        with pytest.raises(FrameError, match="magic"):
            decode_frame(bytes(data))

    def test_codec_version_mismatch_rejected(self):
        data = bytearray(encode_frame({"op": "ping"}))
        data[4:8] = (SNAPSHOT_CODEC_VERSION + 1).to_bytes(4, "big")
        with pytest.raises(FrameError, match="codec version"):
            decode_frame(bytes(data))

    def test_truncated_payload_rejected(self):
        data = encode_frame({"op": "ping"})
        with pytest.raises(FrameError, match="truncated"):
            decode_frame(data[:-3])

    def test_short_header_rejected(self):
        with pytest.raises(FrameError, match="short"):
            decode_frame(b"NOSW")

    def test_non_object_payload_rejected(self):
        import struct

        payload = b"[1,2]"
        header = struct.pack(
            ">4sII", b"NOSW", SNAPSHOT_CODEC_VERSION, len(payload)
        )
        with pytest.raises(FrameError, match="not object"):
            decode_frame(header + payload)

    def test_transport_never_pickles(self):
        """The pipe carries framed JSON only: no Connection.send()
        (which pickles its argument) and no pickle import anywhere in
        the transport module."""
        import pathlib
        import re

        text = pathlib.Path(procpool.__file__).read_text()
        assert "import pickle" not in text
        assert re.search(r"\bconn\.send\(", text) is None
        assert "send_bytes" in text


class TestWorkerTransport:
    def test_cycle_through_worker_matches_in_parent_plan(self, pool):
        entries, nodes = make_world(2)
        pool.sync_pools(["p"])
        pool.bootstrap("p", entries, [])
        pod = pending_pod()
        replies = pool.plan_cycle({"p": cycle_request([pod])})
        reply = replies["p"]
        assert isinstance(reply, dict), reply
        assert reply["touched"], "plan for a feasible pod touched no node"

        # The same world planned in-parent must produce the same boards.
        framework = procpool.build_framework_from_spec(SPEC, KubeStore())
        planner = Planner(framework, **KNOBS)
        base = ClusterSnapshot(nodes, codec=TpuSliceCodec())
        desired = planner.plan(
            base, [pod], dirty=set(nodes), pending_ages={}
        )
        for name, boards in reply["touched"].items():
            expected = {
                str(b.board_index): dict(b.resources)
                for b in desired[name].boards
            }
            assert boards == expected
        assert reply["unserved"] == dict(planner.last_unserved)

    def test_save_entries_adopt_round_trips_through_worker(self, pool, tmp_path):
        """Warm state persisted by an in-parent planner is adopted by a
        freshly spawned worker from the same file: the save_entries()
        document IS the wire vocabulary, so disk and pipe can't drift."""
        entries, nodes = make_world(2)
        framework = procpool.build_framework_from_spec(SPEC, KubeStore())
        planner = Planner(framework, **KNOBS)
        base = ClusterSnapshot(nodes, codec=TpuSliceCodec())
        # Commit-free workload: an unservable 4x4 request against 2x4
        # boards proves futility on every node but places nothing, so
        # the saved signatures describe exactly the observed state the
        # worker will rebuild from the wire image.
        unservable = pending_pod("big", "4x4")
        planner.plan(base, [unservable], dirty=set(nodes), pending_ages={})
        exported = planner.export_warm_state(base)
        assert exported, "no memos to round-trip — world setup regressed"
        warm_path = str(tmp_path / "warm-state.json")
        codec = WarmStateCodec(warm_path)
        assert codec.save_entries(base, exported, force=True)

        pool.warm_state_path = warm_path
        worker = procpool._Worker(
            multiprocessing.get_context("spawn"), "p", "tpu"
        )
        try:
            worker.send(
                {
                    "op": "bootstrap",
                    "seq": 1,
                    "codec_version": SNAPSHOT_CODEC_VERSION,
                    "geometry_overrides": {},
                    "pool": "p",
                    "slice_codec": "TpuSliceCodec",
                    "framework": SPEC,
                    "knobs": KNOBS,
                    "nodes": entries,
                    "quotas": [],
                    "warm_state_path": warm_path,
                }
            )
            reply = worker.recv(BOOT_TIMEOUT)
        finally:
            worker.kill()
        assert reply["op"] == "ready", reply
        assert reply["nodes"] == 2
        # Both nodes' memos matched by signature: the worker rebuilt the
        # exact node states the parent hashed, through wire alone.
        assert reply["adopted_entries"] > 0

    def test_codec_version_mismatch_rejects_then_parent_cold_boots(
        self, pool, monkeypatch
    ):
        entries, _ = make_world(1)
        pool.sync_pools(["p"])
        # The parent claims a vocabulary the worker's tree doesn't speak:
        # the worker must refuse to adopt (silent corruption otherwise).
        monkeypatch.setattr(
            procpool, "SNAPSHOT_CODEC_VERSION", SNAPSHOT_CODEC_VERSION + 7
        )
        with pytest.raises(WorkerUnavailable, match="rejected"):
            pool.bootstrap("p", entries, [])
        assert pool.needs_bootstrap("p")
        assert pool.restarts == 1
        monkeypatch.undo()
        # Parent cold-boots a fresh worker and the pool serves again.
        pool.bootstrap("p", entries, [])
        replies = pool.plan_cycle({"p": cycle_request([pending_pod()])})
        assert isinstance(replies["p"], dict), replies["p"]

    def test_truncated_frame_causes_clean_respawn(self, pool):
        entries, _ = make_world(1)
        pool.sync_pools(["p"])
        pool.bootstrap("p", entries, [])
        # Corrupt the transport mid-stream: the worker cannot trust its
        # state against the parent's any more and exits.
        pool._workers["p"].conn.send_bytes(b"NOSW\x00\x00")
        replies = pool.plan_cycle({"p": cycle_request([pending_pod()])})
        assert isinstance(replies["p"], WorkerUnavailable)
        assert pool.restarts == 1
        assert pool.needs_bootstrap("p")
        # Respawn from a fresh wire image: no state carried over, plan
        # serves cleanly.
        pool.bootstrap("p", entries, [])
        replies = pool.plan_cycle({"p": cycle_request([pending_pod()])})
        reply = replies["p"]
        assert isinstance(reply, dict), reply
        assert reply["touched"]
