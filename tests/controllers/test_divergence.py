"""Actuation-divergence feedback: a clamped (infeasible) plan must not
wedge planning until the next batch window — the partitioner replans the
moment an agent acknowledges a plan whose reported geometry differs from
spec (extends the plan gate of partitioner_controller.go:118-122,212-232).
"""
import time

from nos_tpu.api.v1alpha1 import annotations as annot
from nos_tpu.api.v1alpha1 import constants
from nos_tpu.controllers.partitioner.controller import PartitionerController
from nos_tpu.kube.controller import Request
from nos_tpu.kube.store import KubeStore
from nos_tpu.partitioning.core import ClusterState
from nos_tpu.util import metrics

from tests.factory import build_pod, build_tpu_node


def make_store():
    store = KubeStore()
    # Same wiring as cmd/partitioner.py: fetch_pending_pods reads pods
    # through the phase index.
    store.add_indexer("Pod", constants.INDEX_POD_PHASE, lambda p: [p.status.phase])
    return store


def add_pending_pod(store, name="pend"):
    store.create(build_pod(name, {constants.RESOURCE_TPU: 4}))


def make_controller(store):
    controller = PartitionerController(
        store=store,
        cluster_state=ClusterState(),
        snapshot_taker=None,
        planner=None,
        actuator=None,
        batch_timeout_seconds=60.0,
        batch_idle_seconds=60.0,
    )
    return controller


def set_annotations(store, name, spec_geoms, status_free, spec_plan, status_plan):
    def mutate(n):
        n.metadata.annotations.update(annot.spec_from_geometries(spec_geoms))
        n.metadata.annotations.update(
            annot.status_from_devices(free=status_free, used={})
        )
        n.metadata.annotations[annot.SPEC_PARTITIONING_PLAN] = spec_plan
        n.metadata.annotations[annot.STATUS_PARTITIONING_PLAN] = status_plan

    store.patch_merge("Node", name, None, mutate)


class TestDivergenceWatch:
    def test_acked_divergent_node_fires_immediate_replan(self):
        store = make_store()
        store.create(build_tpu_node(name="n1"))
        add_pending_pod(store)
        c = make_controller(store)
        # Agent acked plan p1 but reports one 2x2 where spec wants two.
        set_annotations(
            store, "n1", {0: {"2x2": 2}}, {0: {"2x2": 1}}, "p1", "p1"
        )
        c.batcher.start()
        try:
            before = metrics.DIVERGENCE_REPLANS.value
            c.reconcile_node_divergence(Request(name="n1"))
            assert c.batcher.ready(timeout=0.5) == []  # immediate empty trigger
            assert metrics.DIVERGENCE_REPLANS.value == before + 1
            # Same stale plan: only one immediate replan, no hot loop.
            c.reconcile_node_divergence(Request(name="n1"))
            assert c.batcher.ready(timeout=0.2) is None
        finally:
            c.batcher.stop()

    def test_handshake_in_flight_defers_to_plan_gate(self):
        store = make_store()
        store.create(build_tpu_node(name="n1"))
        c = make_controller(store)
        set_annotations(
            store, "n1", {0: {"2x2": 2}}, {0: {"2x2": 1}}, "p2", "p1"
        )
        c.batcher.start()
        try:
            c.reconcile_node_divergence(Request(name="n1"))
            assert c.batcher.ready(timeout=0.2) is None
        finally:
            c.batcher.stop()

    def test_converged_node_clears_memo(self):
        store = make_store()
        store.create(build_tpu_node(name="n1"))
        add_pending_pod(store)
        c = make_controller(store)
        set_annotations(
            store, "n1", {0: {"2x2": 2}}, {0: {"2x2": 1}}, "p1", "p1"
        )
        c.batcher.start()
        try:
            c.reconcile_node_divergence(Request(name="n1"))
            assert c.batcher.ready(timeout=0.5) == []
            # Convergence (e.g. after the replan) clears the memo, so a
            # LATER divergence on a new plan fires again.
            set_annotations(
                store, "n1", {0: {"2x2": 2}}, {0: {"2x2": 2}}, "p2", "p2"
            )
            c.reconcile_node_divergence(Request(name="n1"))
            assert c.batcher.ready(timeout=0.2) is None
            assert "n1" not in c._diverged
            set_annotations(
                store, "n1", {0: {"2x4": 1}}, {0: {"2x2": 2}}, "p3", "p3"
            )
            c.reconcile_node_divergence(Request(name="n1"))
            assert c.batcher.ready(timeout=0.5) == []
        finally:
            c.batcher.stop()

    def test_non_tpu_node_ignored(self):
        store = make_store()
        node = build_tpu_node(name="n1", partitioning=None)
        store.create(node)
        c = make_controller(store)
        c.batcher.start()
        try:
            c.reconcile_node_divergence(Request(name="n1"))
            assert c.batcher.ready(timeout=0.2) is None
        finally:
            c.batcher.stop()


class TestDivergenceAdoption:
    def test_no_pending_pods_spec_adopts_reported_geometry(self):
        """An acked-but-diverged node with nothing pending must not wedge:
        there is no demand to replan for, so the spec adopts the reported
        geometry instead of firing the (no-op) batcher. Found by the chaos
        harness: node-death mid-actuation left a clamped spec that the
        agent re-acked forever while the pending set had already drained."""
        store = make_store()
        store.create(build_tpu_node(name="n1"))
        c = make_controller(store)
        set_annotations(
            store, "n1", {0: {"2x2": 2}}, {0: {"2x2": 1}}, "p1", "p1"
        )
        c.batcher.start()
        try:
            before = metrics.DIVERGENCE_REPLANS.value
            c.reconcile_node_divergence(Request(name="n1"))
            assert metrics.DIVERGENCE_REPLANS.value == before + 1
            assert c.batcher.ready(timeout=0.2) is None  # no replan fired
            spec, status = annot.parse_node_annotations(
                store.get("Node", "n1").metadata.annotations
            )
            assert annot.spec_matches_status(spec, status)
            # Converged now: a second reconcile is a clean no-op.
            c.reconcile_node_divergence(Request(name="n1"))
            assert metrics.DIVERGENCE_REPLANS.value == before + 1
            assert c.batcher.ready(timeout=0.2) is None
        finally:
            c.batcher.stop()


class TestDivergenceRecoveryEndToEnd:
    def test_infeasible_spec_recovers_within_report_interval(self):
        """A stale infeasible spec (planned against lagging state) must not
        starve a pending pod until pods finish: agent clamps + acks,
        reporter publishes truth, divergence watch replans, pod schedules.
        Batch windows are set prohibitively long so only the divergence
        path can explain a prompt schedule."""
        from nos_tpu.api.config import GpuPartitionerConfig, TpuAgentConfig
        from nos_tpu.cmd import build_cluster
        from nos_tpu.kube.objects import PodPhase

        from tests.factory import build_pod

        cluster = build_cluster(
            partitioner_config=GpuPartitionerConfig(
                batch_window_timeout_seconds=30.0,
                batch_window_idle_seconds=30.0,
            )
        )
        cluster.add_tpu_node(
            build_tpu_node(name="tpu-1"),
            agent_config=TpuAgentConfig(report_config_interval_seconds=0.1),
        )
        cluster.start()
        try:
            # Seed an infeasible spec directly (planned against state that
            # lagged): 2x 2x4 = 16 chips on an 8-chip host.
            def set_stale(n):
                n.metadata.annotations.update(
                    {
                        **annot.spec_from_geometries({0: {"2x4": 2}}),
                        annot.SPEC_PARTITIONING_PLAN: "stale-1",
                    }
                )

            cluster.store.patch_merge("Node", "tpu-1", None, set_stale)
            # A pending pod that the stale spec can never serve as carved
            # (it COULD be served by one 2x4, but the clamp keeps only
            # what fits; the pod needs a fresh feasible plan).
            cluster.store.create(
                build_pod("train", {constants.RESOURCE_TPU: 4}, ns="ml")
            )
            deadline = time.monotonic() + 10.0
            scheduled = None
            while time.monotonic() < deadline:
                pod = cluster.store.try_get("Pod", "train", "ml")
                if (
                    pod is not None
                    and pod.status.phase == PodPhase.RUNNING
                    and pod.spec.node_name
                ):
                    scheduled = time.monotonic()
                    break
                time.sleep(0.05)
            assert scheduled is not None, (
                "pod never scheduled; node annotations: %s"
                % cluster.store.get("Node", "tpu-1").metadata.annotations
            )
        finally:
            cluster.stop()
