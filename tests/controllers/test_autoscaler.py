"""ModelServing autoscaler: the pure decision function, the reconciler's
pod/annotation writes, and the scale-to-zero edge cases the paper's
serving story hinges on (teardown races, cold-start onto a re-carving
board, min_replicas=0 under a standing SLO)."""
import pytest

from nos_tpu.api.config import AutoscalerConfig
from nos_tpu.api.v1alpha1 import annotations as annot
from nos_tpu.api.v1alpha1 import constants, labels
from nos_tpu.api.v1alpha1.modelserving import ModelServing, ModelServingSpec
from nos_tpu.controllers.autoscaler import (
    ModelServingReconciler,
    SignalRegistry,
    policy,
)
from nos_tpu.controllers.autoscaler.controller import replica_name, serving_key
from nos_tpu.controllers.autoscaler.signals import Signals
from nos_tpu.kube.controller import Request
from nos_tpu.kube.events import EventRecorder
from nos_tpu.kube.objects import ObjectMeta
from nos_tpu.kube.store import KubeStore

from tests.factory import build_tpu_node

CFG = AutoscalerConfig(
    scale_down_stable_seconds=30.0, recent_activity_seconds=10.0
)


def spec(**kw):
    base = dict(
        model="m", slice_profile="2x4", min_replicas=0, max_replicas=3,
        slos=["p95 ttft < 500ms"], scale_to_zero_idle_seconds=60.0,
        cold_start_grace_seconds=30.0, target_queue_depth=4,
    )
    base.update(kw)
    return ModelServingSpec(**base)


class TestDecide:
    def test_hold_inside_band(self):
        d = policy.decide(spec(), 1, Signals(last_request_t=95.0), CFG, 100.0)
        assert d.verdict == policy.VERDICT_HOLD and d.desired == 1

    def test_scale_up_on_fast_burn(self):
        sig = Signals(burn_fast=1.5, last_request_t=99.0)
        d = policy.decide(spec(), 1, sig, CFG, 100.0)
        assert d.verdict == policy.VERDICT_SCALE_UP and d.desired == 2

    def test_scale_up_on_backlog(self):
        sig = Signals(queue_depth=9, last_request_t=99.0)
        d = policy.decide(spec(), 2, sig, CFG, 100.0)
        assert d.verdict == policy.VERDICT_SCALE_UP and d.desired == 3

    def test_no_scale_up_past_max(self):
        sig = Signals(burn_fast=9.0, last_request_t=99.0)
        d = policy.decide(spec(max_replicas=2), 2, sig, CFG, 100.0)
        assert d.verdict == policy.VERDICT_HOLD and d.desired == 2

    def test_below_min_heals(self):
        d = policy.decide(spec(min_replicas=2), 1, Signals(), CFG, 100.0)
        assert d.verdict == policy.VERDICT_SCALE_UP and d.desired == 2

    def test_scale_down_needs_surplus_and_stability(self):
        calm = Signals(
            burn_fast=0.1, burn_slow=0.1, error_budget_remaining=0.9,
            last_request_t=99.0,
        )
        d = policy.decide(spec(), 2, calm, CFG, 100.0, last_transition_t=80.0)
        assert d.verdict == policy.VERDICT_HOLD  # only 20s stable of 30
        d = policy.decide(spec(), 2, calm, CFG, 120.0, last_transition_t=80.0)
        assert d.verdict == policy.VERDICT_SCALE_DOWN and d.desired == 1
        burnt = Signals(
            burn_fast=0.1, burn_slow=0.1, error_budget_remaining=0.2,
            last_request_t=119.0,
        )
        d = policy.decide(spec(), 2, burnt, CFG, 120.0, last_transition_t=80.0)
        assert d.verdict == policy.VERDICT_HOLD  # budget below surplus floor

    def test_one_transition_per_timestamp(self):
        sig = Signals(burn_fast=9.0, last_request_t=99.0)
        d = policy.decide(spec(), 2, sig, CFG, 100.0, last_transition_t=100.0)
        assert d.verdict == policy.VERDICT_HOLD and d.desired == 2

    def test_cold_start_jumps_to_min_floor(self):
        sig = Signals(queue_depth=3)
        d = policy.decide(spec(min_replicas=2), 0, sig, CFG, 100.0)
        assert d.verdict == policy.VERDICT_COLD_START and d.desired == 2

    def test_min_replicas_zero_with_standing_slo_scales_to_zero(self):
        # A declared SLO with zero traffic is vacuously compliant: burn 0,
        # full budget. That must NOT hold a replica alive past the idle
        # window — the budget-surplus scale-down gate is for fleets above
        # the floor, not for idle-out.
        idle = Signals(
            burn_fast=0.0, burn_slow=0.0, error_budget_remaining=1.0,
            queue_depth=0, last_request_t=10.0,
        )
        d = policy.decide(spec(), 1, idle, CFG, 100.0, last_transition_t=20.0)
        assert d.verdict == policy.VERDICT_SCALE_TO_ZERO and d.desired == 0

    def test_min_replicas_floor_blocks_scale_to_zero(self):
        idle = Signals(last_request_t=10.0)
        d = policy.decide(
            spec(min_replicas=1), 1, idle, CFG, 500.0, last_transition_t=20.0
        )
        assert d.verdict == policy.VERDICT_HOLD and d.desired == 1


class _Rig:
    def __init__(self, ms_spec=None):
        self.t = 0.0
        self.store = KubeStore()
        self.signals = SignalRegistry(now_fn=lambda: self.t)
        self.recorder = EventRecorder(
            self.store, component="nos-autoscaler", clock=lambda: self.t
        )
        self.reconciler = ModelServingReconciler(
            self.store, CFG, signals=self.signals, recorder=self.recorder
        )
        self.ms = ModelServing(
            metadata=ObjectMeta(name="svc", namespace="default"),
            spec=ms_spec or spec(),
        )
        self.store.create(self.ms)
        for i in range(3):
            self.store.create(build_tpu_node(name=f"n{i}"))

    def reconcile(self):
        self.reconciler.reconcile(Request(name="svc", namespace="default"))

    def pods(self):
        key = serving_key(self.ms)
        return sorted(
            p.metadata.name
            for p in self.store.list("Pod", namespace="default")
            if p.metadata.labels.get(labels.MODEL_SERVING_LABEL) == key
        )

    def bind(self, pod_name, node_name):
        def mutate(p):
            p.spec.node_name = node_name

        self.store.patch_merge("Pod", pod_name, "default", mutate)

    def status(self):
        return self.store.get("ModelServing", "svc", "default").status


class TestReconciler:
    def test_cold_start_creates_dense_replicas_and_events(self):
        rig = _Rig()
        rig.t = 100.0
        rig.signals.note_arrival("m", 99.0, queue_depth=5)
        rig.reconcile()
        assert rig.pods() == [replica_name("svc", 0)]
        st = rig.status()
        assert st.desired_replicas == 1
        assert st.last_verdict == policy.VERDICT_COLD_START
        assert st.cold_starts == 1
        reasons = {e.reason for e in rig.store.list("Event")}
        assert constants.EVENT_REASON_COLD_START in reasons
        assert constants.EVENT_REASON_SCALED_UP in reasons

    def test_scale_up_is_idempotent_at_one_timestamp(self):
        rig = _Rig()
        rig.t = 100.0
        rig.signals.note_arrival("m", 99.0, queue_depth=5)
        rig.reconcile()
        rig.reconcile()  # watch replay at the same instant
        assert rig.pods() == [replica_name("svc", 0)]

    def test_scale_down_deletes_top_and_reserves_boards(self):
        rig = _Rig()
        rig.t = 100.0
        rig.signals.note_arrival("m", 99.0, queue_depth=5)
        rig.reconcile()
        rig.bind(replica_name("svc", 0), "n1")
        # Idle out past the window: teardown to zero with a grace hold.
        rig.t = 300.0
        rig.signals.update("m", queue_depth=0)
        rig.reconcile()
        assert rig.pods() == []
        st = rig.status()
        assert st.desired_replicas == 0
        assert st.last_verdict == policy.VERDICT_SCALE_TO_ZERO
        node = rig.store.get("Node", "n1")
        assert node.metadata.annotations[annot.AUTOSCALER_RESERVED] == "default.svc"
        until = float(node.metadata.annotations[annot.AUTOSCALER_RESERVED_UNTIL])
        assert until == pytest.approx(330.0)
        reasons = {e.reason for e in rig.store.list("Event")}
        assert constants.EVENT_REASON_SCALED_TO_ZERO in reasons

    def test_grace_reservation_expires_on_its_own_clock(self):
        rig = _Rig()
        rig.t = 100.0
        rig.signals.note_arrival("m", 99.0, queue_depth=5)
        rig.reconcile()
        rig.bind(replica_name("svc", 0), "n1")
        rig.t = 300.0
        rig.signals.update("m", queue_depth=0)
        rig.reconcile()
        rig.t = 331.0  # past the 30s grace
        rig.reconcile()
        node = rig.store.get("Node", "n1")
        assert annot.AUTOSCALER_RESERVED not in node.metadata.annotations
        assert annot.AUTOSCALER_RESERVED_UNTIL not in node.metadata.annotations

    def test_request_arriving_during_teardown_cold_starts_again(self):
        # Edge case: demand lands between the scale-to-zero write and the
        # next resync. The very next reconcile must flip straight back to
        # a cold start (fresh pod) and release the grace hold so the
        # scheduler is free to use the board for it.
        rig = _Rig()
        rig.t = 100.0
        rig.signals.note_arrival("m", 99.0, queue_depth=5)
        rig.reconcile()
        rig.bind(replica_name("svc", 0), "n1")
        rig.t = 300.0
        rig.signals.update("m", queue_depth=0)
        rig.reconcile()
        assert rig.pods() == []
        rig.t = 301.0
        rig.signals.note_arrival("m", 300.5, queue_depth=2)
        rig.reconcile()
        assert rig.pods() == [replica_name("svc", 0)]
        st = rig.status()
        assert st.last_verdict == policy.VERDICT_COLD_START
        assert st.cold_starts == 2
        node = rig.store.get("Node", "n1")
        assert annot.AUTOSCALER_RESERVED not in node.metadata.annotations

    def test_cold_start_with_board_mid_recarve(self):
        # Edge case: the freed board was already handed to the partitioner
        # when demand returns — the node is gone from the store (drained
        # for re-carve) at cold-start time. The reconciler must still
        # create the replica pod and sweep cleanly (NotFound on the
        # reservation patch is not an error); the pod simply pends until
        # a board exists again.
        rig = _Rig()
        rig.t = 100.0
        rig.signals.note_arrival("m", 99.0, queue_depth=5)
        rig.reconcile()
        rig.bind(replica_name("svc", 0), "n1")
        rig.t = 300.0
        rig.signals.update("m", queue_depth=0)
        rig.reconcile()
        rig.store.delete("Node", "n1")  # mid-re-carve: board vanishes
        rig.t = 302.0
        rig.signals.note_arrival("m", 301.0, queue_depth=1)
        rig.reconcile()
        assert rig.pods() == [replica_name("svc", 0)]
        assert rig.status().last_verdict == policy.VERDICT_COLD_START

    def test_standing_slo_does_not_hold_replicas(self):
        # Edge case: min_replicas=0 and a declared SLO, traffic long gone.
        # Vacuous compliance (burn 0, budget 1.0) must not pin the fleet.
        rig = _Rig(ms_spec=spec(slos=["p95 ttft < 100ms", "availability 99.9%"]))
        rig.t = 100.0
        rig.signals.note_arrival("m", 99.0, queue_depth=5)
        rig.reconcile()
        rig.bind(replica_name("svc", 0), "n0")
        rig.t = 500.0
        rig.signals.update(
            "m", queue_depth=0, burn_fast=0.0, burn_slow=0.0,
            error_budget_remaining=1.0,
        )
        rig.reconcile()
        assert rig.pods() == []
        assert rig.status().last_verdict == policy.VERDICT_SCALE_TO_ZERO

    def test_deleted_modelserving_collects_orphans(self):
        rig = _Rig()
        rig.t = 100.0
        rig.signals.note_arrival("m", 99.0, queue_depth=5)
        rig.reconcile()
        assert rig.pods()
        rig.store.delete("ModelServing", "svc", "default")
        rig.reconcile()
        assert rig.pods() == []

    def test_replica_pods_are_gangs_of_one_requesting_chips(self):
        rig = _Rig()
        rig.t = 100.0
        rig.signals.note_arrival("m", 99.0, queue_depth=5)
        rig.reconcile()
        pod = rig.store.get("Pod", replica_name("svc", 0), "default")
        from nos_tpu.scheduler.plugins.gang import GANG_NAME_LABEL, GANG_SIZE_LABEL

        assert pod.metadata.labels[GANG_SIZE_LABEL] == "1"
        assert pod.metadata.labels[GANG_NAME_LABEL] == replica_name("svc", 0)
        assert pod.spec.containers[0].requests[constants.RESOURCE_TPU] == 8


def test_cluster_wiring_places_min_replicas():
    """The async component (build_cluster + watches): a min_replicas=1
    ModelServing becomes a bound, carved replica pod with no bench in the
    loop at all."""
    import time

    from nos_tpu.api.config import GpuPartitionerConfig, SchedulerConfig
    from nos_tpu.cmd.cluster import build_cluster

    cluster = build_cluster(
        partitioner_config=GpuPartitionerConfig(
            batch_window_timeout_seconds=1.0, batch_window_idle_seconds=0.05
        ),
        scheduler_config=SchedulerConfig(retry_seconds=0.1),
        autoscaler_config=AutoscalerConfig(resync_seconds=0.2),
    )
    cluster.add_tpu_node(build_tpu_node(name="tpu-0"))
    cluster.store.create(
        ModelServing(
            metadata=ObjectMeta(name="svc", namespace="default"),
            spec=spec(min_replicas=1, max_replicas=1),
        )
    )
    cluster.start()
    try:
        deadline = time.monotonic() + 20.0
        pod = None
        while time.monotonic() < deadline:
            pod = cluster.store.try_get("Pod", replica_name("svc", 0), "default")
            if pod is not None and pod.spec.node_name:
                break
            time.sleep(0.05)
        assert pod is not None and pod.spec.node_name == "tpu-0"
        st = cluster.store.get("ModelServing", "svc", "default").status
        assert st.desired_replicas == 1
        assert cluster.autoscaler is not None
        payload = cluster.autoscaler.debug_payload()
        assert payload["servings"]["default/svc"]["ready_replicas"] == 1
    finally:
        cluster.stop()
