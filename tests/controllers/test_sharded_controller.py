"""End-to-end pool-sharded partitioner controller (ISSUE 13 tentpole):
process_pending_pods with pool_sharding=True shards the cluster, plans
pools independently, merges under invariants, actuates, and persists /
adopts warm state across a simulated restart.
"""
import json

from nos_tpu.api.v1alpha1 import annotations as annot
from nos_tpu.api.v1alpha1.labels import GKE_NODEPOOL_LABEL
from nos_tpu.cmd.partitioner import register_indexers
from nos_tpu.controllers.partitioner.controller import PartitionerController
from nos_tpu.kube.store import KubeStore
from nos_tpu.partitioning.core import Actuator, ClusterState, Planner
from nos_tpu.partitioning.tpu import TpuPartitioner, TpuSnapshotTaker
from nos_tpu.record.audit import InvariantAuditor
from nos_tpu.scheduler.framework import (
    Framework,
    NodeResourcesFit,
    NodeSelectorFit,
)
from nos_tpu.util import metrics

from tests.factory import build_pod, build_tpu_node, slice_res


def make_store(pools=("pool-a", "pool-b"), nodes_per_pool=2):
    store = KubeStore()
    register_indexers(store)
    for pool in pools:
        for i in range(nodes_per_pool):
            node = build_tpu_node(name=f"{pool}-n{i}")
            node.metadata.labels[GKE_NODEPOOL_LABEL] = pool
            store.create(node)
    return store


def pinned_pod(name, profile, pool):
    pod = build_pod(name, {slice_res(profile): 1}, scheduler="")
    pod.spec.node_selector[GKE_NODEPOOL_LABEL] = pool
    return pod


def make_controller(store, auditor=None, warm_state_path="", **kwargs):
    framework = Framework(
        filter_plugins=[NodeResourcesFit(), NodeSelectorFit()]
    )
    return PartitionerController(
        store=store,
        cluster_state=ClusterState(),
        snapshot_taker=TpuSnapshotTaker(),
        planner=Planner(framework),
        actuator=Actuator(TpuPartitioner(store)),
        kind="tpu",
        batch_timeout_seconds=60.0,
        batch_idle_seconds=60.0,
        auditor=auditor,
        incremental_planning=True,
        incremental_dirty_threshold=1.0,
        pool_sharding=True,
        warm_state_path=warm_state_path,
        **kwargs,
    )


class TestShardedController:
    def test_sharded_cycle_plans_and_actuates_per_pool(self):
        store = make_store()
        auditor = InvariantAuditor(sample_rate=1.0)
        controller = make_controller(store, auditor=auditor)
        store.create(pinned_pod("pa", "2x2", "pool-a"))
        store.create(pinned_pod("pb", "1x2", "pool-b"))
        applied = controller.process_pending_pods()
        assert applied >= 2  # one carve per pool
        assert auditor.violations_total == 0
        assert metrics.PLAN_POOL_COUNT.labels(kind="tpu").value == 2
        # Each pool's carve landed on that pool's nodes only.
        carved = {
            name: annot.parse_node_annotations(node.metadata.annotations)[0]
            for name, node in (
                (n, store.get("Node", n))
                for n in [f"{p}-n{i}" for p in ("pool-a", "pool-b") for i in range(2)]
            )
            if annot.SPEC_PARTITIONING_PLAN in node.metadata.annotations
        }
        assert any(name.startswith("pool-a") for name in carved)
        assert any(name.startswith("pool-b") for name in carved)

    def test_steady_state_keeps_pools_and_audits_clean(self):
        store = make_store()
        auditor = InvariantAuditor(sample_rate=1.0)
        controller = make_controller(store, auditor=auditor)
        store.create(pinned_pod("pa", "2x2", "pool-a"))
        store.create(pinned_pod("pb", "2x2", "pool-b"))
        controller.process_pending_pods()
        maintainer = controller._shard_maintainer
        assert maintainer.pool_rebuilds == 1
        # Further cycles with an unchanged world: no pool rebuilds, no
        # memo flush, per-pool incremental replans, shadow oracle clean.
        for _ in range(3):
            controller.process_pending_pods()
            assert not maintainer.last_rebuilt
        assert maintainer.pool_rebuilds == 1
        assert auditor.violations_total == 0
        for pool, planner in controller._pool_planners.items():
            assert planner.last_plan_mode == "incremental"

    def test_unpinned_pod_collapses_to_single_pool(self):
        store = make_store()
        controller = make_controller(store)
        store.create(build_pod("free", {slice_res("2x2"): 1}, scheduler=""))
        applied = controller.process_pending_pods()
        assert applied >= 1
        assert metrics.PLAN_POOL_COUNT.labels(kind="tpu").value == 1

    def test_warm_state_saved_and_adopted_after_restart(self, tmp_path):
        path = str(tmp_path / "warm.json")
        store = make_store()
        controller = make_controller(store, warm_state_path=path)
        # Unservable requests: futility memos everywhere, nothing placed,
        # so the observed world at "restart" equals the saved one.
        store.create(pinned_pod("pa", "4x4", "pool-a"))
        store.create(pinned_pod("pb", "4x4", "pool-b"))
        controller.process_pending_pods()
        doc = json.loads((tmp_path / "warm.json").read_text())
        assert set(doc["nodes"]) == {
            "pool-a-n0", "pool-a-n1", "pool-b-n0", "pool-b-n1",
        }
        assert any(
            entry["futility"] for entry in doc["nodes"].values()
        )
        # Restart: a brand-new controller over the same store adopts the
        # warm state and its first sharded plan runs warm (empty dirty
        # sets -> incremental mode) with identical unserved verdicts.
        before = metrics.WARM_BOOT_OUTCOME.labels(outcome="adopted").value
        restarted = make_controller(store, warm_state_path=path)
        restarted.process_pending_pods()
        assert (
            metrics.WARM_BOOT_OUTCOME.labels(outcome="adopted").value
            == before + 1
        )
        for pool, planner in restarted._pool_planners.items():
            assert planner.last_plan_mode == "incremental"
            assert planner._futility_hits > 0
            assert set(planner.last_unserved) == {
                "default/pa" if pool == "pool-a" else "default/pb"
            }
