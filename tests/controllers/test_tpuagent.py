from nos_tpu.api.v1alpha1 import annotations as annot
from nos_tpu.controllers.tpuagent import (
    SharedState,
    TpuActuator,
    TpuReporter,
    compute_plan,
)
from nos_tpu.device import (
    SimDevicePlugin,
    SimDevicePool,
    SimPodResourcesClient,
    SimTpuDeviceClient,
    TpuClient,
)
from nos_tpu.device.types import DeviceStatus, TpuSliceDevice
from nos_tpu.kube.controller import Request
from nos_tpu.kube.store import KubeStore

from tests.factory import build_pod, build_tpu_node, slice_res


def make_agent_env(node_name="n1", node=None):
    store = KubeStore()
    store.create(node or build_tpu_node(name=node_name))
    pool = SimDevicePool()
    client = TpuClient(SimTpuDeviceClient(pool), SimPodResourcesClient(store, pool.get))
    plugin = SimDevicePlugin(store, pool)
    shared = SharedState()
    reporter = TpuReporter(store, client, node_name, shared, report_interval_seconds=10)
    actuator = TpuActuator(store, client, plugin, node_name, shared)
    return store, pool, client, plugin, shared, reporter, actuator


def dev(device_id, board, profile, status=DeviceStatus.FREE):
    return TpuSliceDevice(device_id=device_id, board_index=board, profile=profile, status=status)


class TestComputePlan:
    def test_create_from_scratch(self):
        plan = compute_plan([], {0: {"2x2": 2}})
        assert plan.deletes == []
        assert [(c.board_index, c.profile, c.quantity) for c in plan.creates] == [(0, "2x2", 2)]

    def test_delete_profiles_absent_from_spec(self):
        plan = compute_plan([dev("d1", 0, "2x4")], {0: {"1x1": 8}})
        assert [d.device_id for d in plan.deletes] == ["d1"]
        assert [(c.profile, c.quantity) for c in plan.creates] == [("1x1", 8)]

    def test_no_ops_when_converged(self):
        plan = compute_plan([dev("d1", 0, "2x2"), dev("d2", 0, "2x2")], {0: {"2x2": 2}})
        assert plan.empty

    def test_used_devices_never_deleted(self):
        plan = compute_plan([dev("d1", 0, "2x4", DeviceStatus.USED)], {0: {"1x1": 8}})
        assert plan.deletes == []
        # creates still requested; actuation converges after the pod leaves
        assert [(c.profile, c.quantity) for c in plan.creates] == [("1x1", 8)]

    def test_partial_excess_deletes_free_first(self):
        devices = [
            dev("d1", 0, "2x2", DeviceStatus.USED),
            dev("d2", 0, "2x2", DeviceStatus.FREE),
        ]
        plan = compute_plan(devices, {0: {"2x2": 1}})
        assert [d.device_id for d in plan.deletes] == ["d2"]


class TestActuatorReporterLoop:
    def test_spec_to_devices_to_status_handshake(self):
        store, pool, client, plugin, shared, reporter, actuator = make_agent_env()
        # control plane writes spec
        store.patch_annotations(
            "Node", "n1", "",
            {**annot.spec_from_geometries({0: {"2x2": 2}}), annot.SPEC_PARTITIONING_PLAN: "7"},
        )
        # actuator gated until a report happens
        result = actuator.reconcile(Request(name="n1"))
        assert result is not None and result.requeue_after > 0
        assert pool.get("n1") == []

        reporter.reconcile(Request(name="n1"))  # report empty state
        actuator.reconcile(Request(name="n1"))  # now actuates
        assert pool.geometry("n1") == {0: {"2x2": 2}}

        # device plugin re-advertised slice resources on the node
        node = store.get("Node", "n1")
        assert node.status.allocatable[slice_res("2x2")] == 2
        assert node.status.allocatable["google.com/tpu"] == 0

        # next report publishes status + acknowledges the plan
        reporter.reconcile(Request(name="n1"))
        node = store.get("Node", "n1")
        _, status = annot.parse_node_annotations(node.metadata.annotations)
        assert annot.status_geometries(status) == {0: {"2x2": 2}}
        assert node.metadata.annotations[annot.STATUS_PARTITIONING_PLAN] == "7"

    def test_reporter_marks_used_devices(self):
        store, pool, client, plugin, shared, reporter, actuator = make_agent_env()
        pool.create("n1", 0, "2x2", 2)
        store.create(build_pod("p", {slice_res("2x2"): 1}, node="n1", phase="Running"))
        reporter.reconcile(Request(name="n1"))
        _, status = annot.parse_node_annotations(store.get("Node", "n1").metadata.annotations)
        by_status = {(s.status, s.profile): s.quantity for s in status}
        assert by_status[("used", "2x2")] == 1
        assert by_status[("free", "2x2")] == 1

    def test_reconverge_after_spec_change(self):
        store, pool, client, plugin, shared, reporter, actuator = make_agent_env()
        store.patch_annotations(
            "Node", "n1", "",
            {**annot.spec_from_geometries({0: {"2x4": 1}}), annot.SPEC_PARTITIONING_PLAN: "1"},
        )
        reporter.reconcile(Request(name="n1"))
        actuator.reconcile(Request(name="n1"))
        assert pool.geometry("n1") == {0: {"2x4": 1}}
        # new plan arrives: re-carve into 1x1s
        node = store.get("Node", "n1")
        patch = annot.strip_spec_annotations(node.metadata.annotations)
        patch.update(annot.spec_from_geometries({0: {"1x1": 8}}))
        patch[annot.SPEC_PARTITIONING_PLAN] = "2"
        store.patch_annotations("Node", "n1", "", patch)
        reporter.reconcile(Request(name="n1"))
        actuator.reconcile(Request(name="n1"))
        assert pool.geometry("n1") == {0: {"1x1": 8}}
        reporter.reconcile(Request(name="n1"))
        assert (
            store.get("Node", "n1").metadata.annotations[annot.STATUS_PARTITIONING_PLAN]
            == "2"
        )

    def test_actuator_ignores_other_nodes(self):
        store, pool, client, plugin, shared, reporter, actuator = make_agent_env()
        assert actuator.reconcile(Request(name="other")) is None


class TestCapacityClamp:
    """A spec planned against stale state can demand more chips than the
    board has (spec plus still-used slices); the actuator must refuse the
    impossible creates like real silicon would, and let the loop
    re-converge from the next report."""

    def test_creates_clamped_when_spec_exceeds_board(self):
        store, pool, client, plugin, shared, reporter, actuator = make_agent_env()
        # Two used 2x2 slices occupy the whole 8-chip board.
        pool.create("n1", 0, "2x2", 2)
        store.create(build_pod("a", {slice_res("2x2"): 1}, node="n1", phase="Running"))
        store.create(build_pod("b", {slice_res("2x2"): 1}, node="n1", phase="Running"))
        # Stale spec: keep one 2x2 and add two 1x2 (would be 12 chips).
        def set_spec(n):
            n.metadata.annotations.update(
                {
                    **annot.spec_from_geometries({0: {"2x2": 1, "1x2": 2}}),
                    annot.SPEC_PARTITIONING_PLAN: "p1",
                }
            )

        store.patch_merge("Node", "n1", None, set_spec)
        shared.on_report()
        actuator.reconcile(Request(name="n1"))
        geometry = pool.geometry("n1")
        total_chips = sum(
            {"1x1": 1, "1x2": 2, "2x2": 4, "2x4": 8}[p] * q
            for p, q in geometry.get(0, {}).items()
        )
        assert total_chips <= 8, geometry
        # Used devices were never deleted.
        assert geometry[0].get("2x2", 0) == 2

    def test_fully_clamped_plan_skips_plugin_restart_and_acks(self):
        """A spec clamped to a complete no-op must not churn the device
        plugin, must still acknowledge the plan id (so the control-plane
        gate opens and the divergence watch can replan), and must not
        spam error logs on every agent reconcile."""
        store, pool, client, plugin, shared, reporter, actuator = make_agent_env()
        restarts = {"n": 0}
        real_restart = plugin.restart

        def counting_restart(node_name):
            restarts["n"] += 1
            real_restart(node_name)

        plugin.restart = counting_restart
        pool.create("n1", 0, "2x2", 2)
        store.create(build_pod("a", {slice_res("2x2"): 1}, node="n1", phase="Running"))
        store.create(build_pod("b", {slice_res("2x2"): 1}, node="n1", phase="Running"))

        def set_spec(n):
            n.metadata.annotations.update(
                {
                    # board is full with used 2x2s: the extra 1x2s can never fit
                    **annot.spec_from_geometries({0: {"2x2": 2, "1x2": 2}}),
                    annot.SPEC_PARTITIONING_PLAN: "p1",
                }
            )

        store.patch_merge("Node", "n1", None, set_spec)
        for _ in range(5):
            shared.on_report()
            actuator.reconcile(Request(name="n1"))
        assert restarts["n"] == 0, "no device change -> no plugin restart"
        assert pool.geometry("n1")[0] == {"2x2": 2}
        # Plan acknowledged: the reporter will publish status plan == spec.
        reporter.reconcile(Request(name="n1"))
        node = store.get("Node", "n1")
        assert node.metadata.annotations[annot.STATUS_PARTITIONING_PLAN] == "p1"

    def test_clamp_log_throttled_per_plan(self, caplog):
        import logging

        store, pool, client, plugin, shared, reporter, actuator = make_agent_env()
        pool.create("n1", 0, "2x2", 2)
        store.create(build_pod("a", {slice_res("2x2"): 1}, node="n1", phase="Running"))
        store.create(build_pod("b", {slice_res("2x2"): 1}, node="n1", phase="Running"))

        def set_spec(n):
            n.metadata.annotations.update(
                {
                    **annot.spec_from_geometries({0: {"2x2": 2, "1x2": 2}}),
                    annot.SPEC_PARTITIONING_PLAN: "p1",
                }
            )

        store.patch_merge("Node", "n1", None, set_spec)
        with caplog.at_level(logging.ERROR, logger="nos_tpu.tpuagent"):
            for _ in range(6):
                shared.on_report()
                actuator.reconcile(Request(name="n1"))
        clamp_errors = [
            r for r in caplog.records if "clamping" in r.getMessage()
        ]
        assert len(clamp_errors) <= 2, (
            f"{len(clamp_errors)} error-level clamp logs for one stale plan"
        )


class TestKubeletAdmission:
    """The sim kubelet arbitrates admission against device truth — the
    backstop for a bind racing a re-carve (real kubelet: OutOfcpu-style
    terminal rejection)."""

    def _kubelet_env(self):
        from nos_tpu.sim import SimKubelet

        store = KubeStore()
        store.create(build_tpu_node(name="n1"))
        pool = SimDevicePool()
        kubelet = SimKubelet(store, geometry_fn=pool.geometry)
        return store, pool, kubelet

    def test_second_pod_on_single_slice_rejected(self):
        store, pool, kubelet = self._kubelet_env()
        pool.create("n1", 0, "2x2", 1)
        for name in ("a", "b"):
            store.create(build_pod(name, {"google.com/tpu": 4}, node="n1"))
        kubelet.reconcile(Request(name="a", namespace="default"))
        kubelet.reconcile(Request(name="b", namespace="default"))
        phases = {
            name: store.get("Pod", name, "default").status.phase for name in ("a", "b")
        }
        assert phases["a"] == "Running"
        assert phases["b"] == "Failed"
        assert kubelet.admission_rejects == 1

    def test_fitting_pods_admitted(self):
        store, pool, kubelet = self._kubelet_env()
        pool.create("n1", 0, "2x2", 2)
        for name in ("a", "b"):
            store.create(build_pod(name, {"google.com/tpu": 4}, node="n1"))
        kubelet.reconcile(Request(name="a", namespace="default"))
        kubelet.reconcile(Request(name="b", namespace="default"))
        assert all(
            store.get("Pod", n, "default").status.phase == "Running" for n in ("a", "b")
        )

    def test_non_tpu_node_always_admits(self):
        from nos_tpu.sim import SimKubelet
        from tests.factory import build_node

        store = KubeStore()
        store.create(build_node(name="plain"))
        kubelet = SimKubelet(store, geometry_fn=lambda n: {})
        store.create(build_pod("p", {"cpu": 1}, node="plain"))
        kubelet.reconcile(Request(name="p", namespace="default"))
        assert store.get("Pod", "p", "default").status.phase == "Running"
