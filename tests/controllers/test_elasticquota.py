import pytest

from nos_tpu.api.v1alpha1 import constants, labels
from nos_tpu.api.v1alpha1.elasticquota import (
    CompositeElasticQuota,
    CompositeElasticQuotaSpec,
    ElasticQuota,
    ElasticQuotaSpec,
)
from nos_tpu.controllers.elasticquota import (
    CompositeElasticQuotaReconciler,
    ElasticQuotaReconciler,
    register_elasticquota_webhooks,
)
from nos_tpu.kube.controller import Request
from nos_tpu.kube.objects import ObjectMeta
from nos_tpu.kube.store import AdmissionError, KubeStore

from tests.factory import build_pod


def make_eq(name="quota", ns="team-a", min=None, max=None):
    return ElasticQuota(
        metadata=ObjectMeta(name=name, namespace=ns),
        spec=ElasticQuotaSpec(min=min or {}, max=max or {}),
    )


def make_ceq(name="composite", namespaces=None, min=None, max=None):
    return CompositeElasticQuota(
        metadata=ObjectMeta(name=name, namespace="default"),
        spec=CompositeElasticQuotaSpec(
            namespaces=namespaces or [], min=min or {}, max=max or {}
        ),
    )


class TestElasticQuotaReconciler:
    def test_used_and_labels(self):
        store = KubeStore()
        store.create(make_eq(min={constants.RESOURCE_TPU_CHIPS: 8}))
        early = build_pod("early", {constants.RESOURCE_TPU: 8}, ns="team-a", phase="Running")
        late = build_pod("late", {constants.RESOURCE_TPU: 4}, ns="team-a", phase="Running")
        late.metadata.creation_timestamp = early.metadata.creation_timestamp + 10
        store.create(early)
        store.create(late)
        ElasticQuotaReconciler(store).reconcile(Request(name="quota", namespace="team-a"))

        assert (
            store.get("Pod", "early", "team-a").metadata.labels[labels.CAPACITY_LABEL]
            == labels.CAPACITY_IN_QUOTA
        )
        assert (
            store.get("Pod", "late", "team-a").metadata.labels[labels.CAPACITY_LABEL]
            == labels.CAPACITY_OVER_QUOTA
        )
        eq = store.get("ElasticQuota", "quota", "team-a")
        assert eq.status.used == {constants.RESOURCE_TPU_CHIPS: 12}

    def test_only_min_resources_tracked(self):
        store = KubeStore()
        store.create(make_eq(min={"cpu": 4}))
        store.create(build_pod("p", {"cpu": 2, "memory": 64}, ns="team-a", phase="Running"))
        ElasticQuotaReconciler(store).reconcile(Request(name="quota", namespace="team-a"))
        assert store.get("ElasticQuota", "quota", "team-a").status.used == {"cpu": 2}

    def test_pending_pods_not_counted(self):
        store = KubeStore()
        store.create(make_eq(min={"cpu": 4}))
        store.create(build_pod("p", {"cpu": 2}, ns="team-a", phase="Pending"))
        ElasticQuotaReconciler(store).reconcile(Request(name="quota", namespace="team-a"))
        assert store.get("ElasticQuota", "quota", "team-a").status.used == {}

    def test_label_flips_back_in_quota(self):
        store = KubeStore()
        store.create(make_eq(min={"cpu": 2}))
        a = build_pod("a", {"cpu": 2}, ns="team-a", phase="Running")
        b = build_pod("b", {"cpu": 2}, ns="team-a", phase="Running")
        b.metadata.creation_timestamp = a.metadata.creation_timestamp + 5
        store.create(a)
        store.create(b)
        r = ElasticQuotaReconciler(store)
        r.reconcile(Request(name="quota", namespace="team-a"))
        assert (
            store.get("Pod", "b", "team-a").metadata.labels[labels.CAPACITY_LABEL]
            == labels.CAPACITY_OVER_QUOTA
        )
        store.delete("Pod", "a", "team-a")
        r.reconcile(Request(name="quota", namespace="team-a"))
        assert (
            store.get("Pod", "b", "team-a").metadata.labels[labels.CAPACITY_LABEL]
            == labels.CAPACITY_IN_QUOTA
        )


class TestCompositeElasticQuota:
    def test_accounts_across_namespaces(self):
        store = KubeStore()
        store.create(make_ceq(namespaces=["a", "b"], min={"cpu": 4}))
        store.create(build_pod("p1", {"cpu": 2}, ns="a", phase="Running"))
        store.create(build_pod("p2", {"cpu": 3}, ns="b", phase="Running"))
        CompositeElasticQuotaReconciler(store).reconcile(
            Request(name="composite", namespace="default")
        )
        ceq = store.get("CompositeElasticQuota", "composite", "default")
        assert ceq.status.used == {"cpu": 5}
        in_q = store.get("Pod", "p1", "a").metadata.labels[labels.CAPACITY_LABEL]
        over_q = store.get("Pod", "p2", "b").metadata.labels[labels.CAPACITY_LABEL]
        assert (in_q, over_q) == (labels.CAPACITY_IN_QUOTA, labels.CAPACITY_OVER_QUOTA)

    def test_deletes_overlapping_eqs(self):
        store = KubeStore()
        store.create(make_eq(name="old", ns="a", min={"cpu": 1}))
        store.create(make_ceq(namespaces=["a"], min={"cpu": 4}))
        CompositeElasticQuotaReconciler(store).reconcile(
            Request(name="composite", namespace="default")
        )
        assert store.try_get("ElasticQuota", "old", "a") is None


class TestWebhooks:
    def make_store(self):
        store = KubeStore()
        register_elasticquota_webhooks(store)
        return store

    def test_one_eq_per_namespace(self):
        store = self.make_store()
        store.create(make_eq(name="first"))
        with pytest.raises(AdmissionError):
            store.create(make_eq(name="second"))

    def test_eq_rejected_in_ceq_namespace(self):
        store = self.make_store()
        store.create(make_ceq(namespaces=["team-a"]))
        with pytest.raises(AdmissionError):
            store.create(make_eq(ns="team-a"))

    def test_ceq_overlap_rejected(self):
        store = self.make_store()
        store.create(make_ceq(name="c1", namespaces=["a", "b"]))
        with pytest.raises(AdmissionError):
            store.create(make_ceq(name="c2", namespaces=["b", "c"]))

    def test_min_above_max_rejected(self):
        store = self.make_store()
        with pytest.raises(AdmissionError):
            store.create(make_eq(min={"cpu": 4}, max={"cpu": 2}))

    def test_valid_quota_admitted(self):
        store = self.make_store()
        store.create(make_eq(min={"cpu": 2}, max={"cpu": 4}))
