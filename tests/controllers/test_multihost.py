"""MultihostExpander unit tests: request rewriting, idempotency, worker
GC, and the hard ICI-domain filter."""
import pytest

from nos_tpu.api.v1alpha1 import constants
from nos_tpu.controllers.partitioner.multihost import (
    MULTIHOST_ROLE_LABEL,
    MULTIHOST_TOPOLOGY_ANNOTATION,
    MultihostExpander,
    ROLE_LEADER,
    ROLE_WORKER,
)
from nos_tpu.kube.controller import Request
from nos_tpu.kube.store import KubeStore
from nos_tpu.scheduler.plugins.gang import GANG_NAME_LABEL, GANG_SIZE_LABEL

from tests.factory import build_pod, build_tpu_node, slice_res


@pytest.fixture
def store():
    s = KubeStore()
    s.create(build_tpu_node(name="tpu-0"))
    return s


def reconcile(store, name, ns="default"):
    MultihostExpander(store).reconcile(Request(name=name, namespace=ns))


class TestExpansion:
    def test_oversized_request_becomes_gang(self, store):
        store.create(build_pod("big", {constants.RESOURCE_TPU: 16}))
        reconcile(store, "big")
        leader = store.get("Pod", "big", "default")
        assert leader.metadata.labels[GANG_NAME_LABEL] == "big"
        assert leader.metadata.labels[GANG_SIZE_LABEL] == "2"
        assert leader.metadata.labels[MULTIHOST_ROLE_LABEL] == ROLE_LEADER
        assert leader.metadata.annotations[MULTIHOST_TOPOLOGY_ANNOTATION] == "4x4"
        request = leader.spec.containers[0].requests
        assert constants.RESOURCE_TPU not in request
        assert request[slice_res("2x4")] == 1
        worker = store.get("Pod", "big-w1", "default")
        assert worker.metadata.labels[MULTIHOST_ROLE_LABEL] == ROLE_WORKER
        assert worker.spec.containers[0].requests[slice_res("2x4")] == 1
        assert worker.metadata.owner_references[0].name == "big"

    def test_single_host_request_untouched(self, store):
        store.create(build_pod("small", {constants.RESOURCE_TPU: 4}))
        reconcile(store, "small")
        pod = store.get("Pod", "small", "default")
        assert GANG_NAME_LABEL not in pod.metadata.labels
        assert pod.spec.containers[0].requests == {constants.RESOURCE_TPU: 4}
        assert store.list("Pod") == [pod]

    def test_slice_request_untouched(self, store):
        store.create(build_pod("sliced", {slice_res("2x2"): 1}))
        reconcile(store, "sliced")
        pod = store.get("Pod", "sliced", "default")
        assert GANG_NAME_LABEL not in pod.metadata.labels

    def test_reconcile_is_idempotent(self, store):
        store.create(build_pod("big", {constants.RESOURCE_TPU: 32}))
        reconcile(store, "big")
        reconcile(store, "big")  # leader path: only ensures workers
        pods = store.list("Pod")
        assert len(pods) == 4  # leader + 3 workers, no duplicates
        leader = store.get("Pod", "big", "default")
        assert leader.spec.containers[0].requests[slice_res("2x4")] == 1

    def test_request_beyond_all_topologies_left_alone(self, store):
        store.create(build_pod("huge", {constants.RESOURCE_TPU: 4096}))
        reconcile(store, "huge")
        pod = store.get("Pod", "huge", "default")
        assert GANG_NAME_LABEL not in pod.metadata.labels  # warned, skipped

    def test_worker_gc_when_leader_gone(self, store):
        store.create(build_pod("big", {constants.RESOURCE_TPU: 16}))
        reconcile(store, "big")
        store.delete("Pod", "big", "default")
        reconcile(store, "big-w1")
        assert store.try_get("Pod", "big-w1", "default") is None

    def test_worker_kept_while_leader_alive(self, store):
        store.create(build_pod("big", {constants.RESOURCE_TPU: 16}))
        reconcile(store, "big")
        reconcile(store, "big-w1")
        assert store.try_get("Pod", "big-w1", "default") is not None


class TestMultihostIciFilter:
    def _member(self, name, gang="g1", node=""):
        pod = build_pod(name, {slice_res("2x4"): 1})
        pod.metadata.labels[GANG_NAME_LABEL] = gang
        pod.metadata.labels[GANG_SIZE_LABEL] = "2"
        pod.metadata.annotations[MULTIHOST_TOPOLOGY_ANNOTATION] = "4x4"
        pod.spec.node_name = node
        if node:
            pod.status.phase = "Running"
        return pod

    def _node(self, name, pool):
        node = build_tpu_node(name=name)
        node.metadata.labels["cloud.google.com/gke-nodepool"] = pool
        return node

    def test_members_pinned_to_first_pool(self):
        from nos_tpu.scheduler.framework import CycleState, NodeInfo
        from nos_tpu.scheduler.plugins.topology import MultihostIciFilter

        store = KubeStore()
        store.create(self._node("a1", "pool-a"))
        store.create(self._node("b1", "pool-b"))
        store.create(self._member("m0", node="a1"))
        f = MultihostIciFilter(store)
        pending = self._member("m1")
        ok = f.filter(CycleState(), pending, NodeInfo(node=store.get("Node", "a1")))
        blocked = f.filter(CycleState(), pending, NodeInfo(node=store.get("Node", "b1")))
        assert ok.success
        assert not blocked.success and "pinned" in blocked.message

    def test_permit_reserved_members_pin_too(self):
        from nos_tpu.scheduler.framework import CycleState, NodeInfo
        from nos_tpu.scheduler.plugins.gang import GangScheduling
        from nos_tpu.scheduler.plugins.topology import MultihostIciFilter

        store = KubeStore()
        store.create(self._node("a1", "pool-a"))
        store.create(self._node("b1", "pool-b"))
        gang = GangScheduling(store)
        m0 = self._member("m0")
        store.create(m0)
        gang.permit(CycleState(), m0, "a1")  # reserved, not bound
        f = MultihostIciFilter(store, gang)
        blocked = f.filter(
            CycleState(), self._member("m1"), NodeInfo(node=store.get("Node", "b1"))
        )
        assert not blocked.success

    def test_non_multihost_pods_unconstrained(self):
        from nos_tpu.scheduler.framework import CycleState, NodeInfo
        from nos_tpu.scheduler.plugins.topology import MultihostIciFilter

        store = KubeStore()
        store.create(self._node("b1", "pool-b"))
        pod = build_pod("plain", {slice_res("2x2"): 1})
        f = MultihostIciFilter(store)
        assert f.filter(CycleState(), pod, NodeInfo(node=store.get("Node", "b1"))).success


class TestAdmissionMutation:
    """The mutating-webhook path: JSONPatch expansion at pod admission,
    preserving every unmodeled field (real clusters reject post-create
    label/request/env rewrites, so this is the production expansion path)."""

    def _wire_pod(self, chips=32):
        return {
            "apiVersion": "v1", "kind": "Pod",
            "metadata": {"name": "big", "namespace": "ml",
                         "labels": {"team": "research"}},
            "spec": {
                "serviceAccountName": "train-sa",
                "volumes": [{"name": "data", "emptyDir": {}}],
                "containers": [{
                    "name": "main",
                    "image": "trainer:1",
                    "volumeMounts": [{"name": "data", "mountPath": "/data"}],
                    "env": [
                        {"name": "NODE_NAME",
                         "valueFrom": {"fieldRef": {"fieldPath": "spec.nodeName"}}},
                        {"name": "MODE", "value": "train"},
                    ],
                    "resources": {"requests": {"google.com/tpu": str(chips)},
                                  "limits": {"google.com/tpu": str(chips)}},
                }],
            },
            "status": {"phase": "Pending"},
        }

    def test_jsonpatch_expands_and_preserves_unmodeled_fields(self, store):
        from nos_tpu.controllers.partitioner.multihost import admission_mutate_pod

        ops = admission_mutate_pod(self._wire_pod(), store)
        assert ops, "oversized pod must be patched"
        by_path = {op["path"]: op for op in ops}
        labels_value = by_path["/metadata/labels"]["value"]
        assert labels_value["team"] == "research"  # user labels survive
        assert labels_value[GANG_SIZE_LABEL] == "4"
        containers = by_path["/spec/containers"]["value"]
        main = containers[0]
        assert main["volumeMounts"] == [{"name": "data", "mountPath": "/data"}]
        env_names = [e["name"] for e in main["env"]]
        assert "NODE_NAME" in env_names  # valueFrom entry kept
        assert "MODE" in env_names
        assert "NOS_TPU_PROCESS_ID" in env_names
        assert main["resources"]["requests"] == {slice_res("2x4"): "1"}
        assert main["resources"]["limits"] == {slice_res("2x4"): "1"}
        assert by_path["/spec/hostname"]["value"] == "big"
        assert by_path["/spec/subdomain"]["value"] == "big"

    def test_small_pod_gets_no_patch(self, store):
        from nos_tpu.controllers.partitioner.multihost import admission_mutate_pod

        wire = self._wire_pod(chips=4)
        assert admission_mutate_pod(wire, store) is None

    def test_mutation_over_tls(self, store):
        """End to end through the webhook server: AdmissionReview in,
        base64 JSONPatch out."""
        import base64
        import json as _json
        import ssl
        import urllib.request

        from nos_tpu.kube.webhook import (
            PATH_MUTATE_POD,
            build_elasticquota_webhook_server,
        )

        server = build_elasticquota_webhook_server(store, port=0, host="127.0.0.1")
        server.start()
        try:
            ctx = ssl.create_default_context(cadata=server.cert_pem.decode())
            ctx.check_hostname = False
            body = _json.dumps({
                "apiVersion": "admission.k8s.io/v1", "kind": "AdmissionReview",
                "request": {"uid": "m1", "object": self._wire_pod()},
            }).encode()
            req = urllib.request.Request(
                f"https://127.0.0.1:{server.port}{PATH_MUTATE_POD}",
                data=body, headers={"Content-Type": "application/json"})
            with urllib.request.urlopen(req, context=ctx, timeout=5) as resp:
                review = _json.loads(resp.read())
            response = review["response"]
            assert response["allowed"] is True
            assert response["patchType"] == "JSONPatch"
            ops = _json.loads(base64.b64decode(response["patch"]))
            assert any(op["path"] == "/spec/containers" for op in ops)
        finally:
            server.stop()


class TestWorkerWireFidelity:
    def test_workers_inherit_unmodeled_spec_over_api_store(self):
        """Against a live apiserver, workers clone the leader's RAW wire:
        volumes/probes/serviceAccount survive into every gang member."""
        from nos_tpu.kube.apiclient import ClusterCredentials, KubeApiClient
        from nos_tpu.kube.apistore import KubeApiStore
        from nos_tpu.kube.controller import Request
        from tests.kube.stub_apiserver import StubApiServer

        with StubApiServer() as api:
            store = KubeApiStore(
                KubeApiClient(ClusterCredentials(server=api.url), timeout=5.0),
                kinds=("Pod", "Node", "Service"),
            )
            store.start(sync_timeout_s=10.0)
            try:
                store.create(build_tpu_node(name="tpu-0"))
                # an "already expanded" leader (as the mutating webhook
                # would admit it) with unmodeled spec fields
                wire = {
                    "apiVersion": "v1", "kind": "Pod",
                    "metadata": {"name": "big", "namespace": "ml", "labels": {
                        GANG_NAME_LABEL: "big", GANG_SIZE_LABEL: "2",
                        MULTIHOST_ROLE_LABEL: ROLE_LEADER}},
                    "spec": {
                        "serviceAccountName": "train-sa",
                        "volumes": [{"name": "data", "emptyDir": {}}],
                        "hostname": "big", "subdomain": "big",
                        "containers": [{
                            "name": "main",
                            "resources": {"requests": {slice_res("2x4"): "1"}},
                        }],
                    },
                }
                api.inject("pods", wire)
                import time as _t
                deadline = _t.monotonic() + 5
                while _t.monotonic() < deadline and not store.try_get("Pod", "big", "ml"):
                    _t.sleep(0.02)
                MultihostExpander(store).reconcile(Request(name="big", namespace="ml"))
                worker_wire = api.read("pods", "ml", "big-w1")
                assert worker_wire, "worker not created"
                assert worker_wire["spec"]["serviceAccountName"] == "train-sa"
                assert worker_wire["spec"]["volumes"] == [{"name": "data", "emptyDir": {}}]
                assert worker_wire["spec"]["hostname"] == "big-w1"
                env = {e["name"]: e.get("value") for e in
                       worker_wire["spec"]["containers"][0].get("env") or []}
                assert env.get("NOS_TPU_PROCESS_ID") == "1"
            finally:
                store.stop()
