"""MultihostExpander unit tests: request rewriting, idempotency, worker
GC, and the hard ICI-domain filter."""
import pytest

from nos_tpu.api.v1alpha1 import constants, labels
from nos_tpu.controllers.partitioner.multihost import (
    MULTIHOST_ROLE_LABEL,
    MULTIHOST_TOPOLOGY_ANNOTATION,
    MultihostExpander,
    ROLE_LEADER,
    ROLE_WORKER,
)
from nos_tpu.kube.controller import Request
from nos_tpu.kube.store import KubeStore
from nos_tpu.scheduler.plugins.gang import GANG_NAME_LABEL, GANG_SIZE_LABEL

from tests.factory import build_pod, build_tpu_node, slice_res


@pytest.fixture
def store():
    s = KubeStore()
    s.create(build_tpu_node(name="tpu-0"))
    return s


def reconcile(store, name, ns="default"):
    MultihostExpander(store).reconcile(Request(name=name, namespace=ns))


class TestExpansion:
    def test_oversized_request_becomes_gang(self, store):
        store.create(build_pod("big", {constants.RESOURCE_TPU: 16}))
        reconcile(store, "big")
        leader = store.get("Pod", "big", "default")
        assert leader.metadata.labels[GANG_NAME_LABEL] == "big"
        assert leader.metadata.labels[GANG_SIZE_LABEL] == "2"
        assert leader.metadata.labels[MULTIHOST_ROLE_LABEL] == ROLE_LEADER
        assert leader.metadata.annotations[MULTIHOST_TOPOLOGY_ANNOTATION] == "4x4"
        request = leader.spec.containers[0].requests
        assert constants.RESOURCE_TPU not in request
        assert request[slice_res("2x4")] == 1
        worker = store.get("Pod", "big-w1", "default")
        assert worker.metadata.labels[MULTIHOST_ROLE_LABEL] == ROLE_WORKER
        assert worker.spec.containers[0].requests[slice_res("2x4")] == 1
        assert worker.metadata.owner_references[0].name == "big"

    def test_single_host_request_untouched(self, store):
        store.create(build_pod("small", {constants.RESOURCE_TPU: 4}))
        reconcile(store, "small")
        pod = store.get("Pod", "small", "default")
        assert GANG_NAME_LABEL not in pod.metadata.labels
        assert pod.spec.containers[0].requests == {constants.RESOURCE_TPU: 4}
        assert store.list("Pod") == [pod]

    def test_slice_request_untouched(self, store):
        store.create(build_pod("sliced", {slice_res("2x2"): 1}))
        reconcile(store, "sliced")
        pod = store.get("Pod", "sliced", "default")
        assert GANG_NAME_LABEL not in pod.metadata.labels

    def test_reconcile_is_idempotent(self, store):
        store.create(build_pod("big", {constants.RESOURCE_TPU: 32}))
        reconcile(store, "big")
        reconcile(store, "big")  # leader path: only ensures workers
        pods = store.list("Pod")
        assert len(pods) == 4  # leader + 3 workers, no duplicates
        leader = store.get("Pod", "big", "default")
        assert leader.spec.containers[0].requests[slice_res("2x4")] == 1

    def test_request_beyond_all_topologies_left_alone(self, store):
        store.create(build_pod("huge", {constants.RESOURCE_TPU: 4096}))
        reconcile(store, "huge")
        pod = store.get("Pod", "huge", "default")
        assert GANG_NAME_LABEL not in pod.metadata.labels  # warned, skipped

    def test_worker_gc_when_leader_gone(self, store):
        store.create(build_pod("big", {constants.RESOURCE_TPU: 16}))
        reconcile(store, "big")
        store.delete("Pod", "big", "default")
        reconcile(store, "big-w1")
        assert store.try_get("Pod", "big-w1", "default") is None

    def test_worker_kept_while_leader_alive(self, store):
        store.create(build_pod("big", {constants.RESOURCE_TPU: 16}))
        reconcile(store, "big")
        reconcile(store, "big-w1")
        assert store.try_get("Pod", "big-w1", "default") is not None


class TestMultihostIciFilter:
    def _member(self, name, gang="g1", node=""):
        pod = build_pod(name, {slice_res("2x4"): 1})
        pod.metadata.labels[GANG_NAME_LABEL] = gang
        pod.metadata.labels[GANG_SIZE_LABEL] = "2"
        pod.metadata.annotations[MULTIHOST_TOPOLOGY_ANNOTATION] = "4x4"
        pod.spec.node_name = node
        if node:
            pod.status.phase = "Running"
        return pod

    def _node(self, name, pool):
        node = build_tpu_node(name=name)
        node.metadata.labels["cloud.google.com/gke-nodepool"] = pool
        return node

    def test_members_pinned_to_first_pool(self):
        from nos_tpu.scheduler.framework import CycleState, NodeInfo
        from nos_tpu.scheduler.plugins.topology import MultihostIciFilter

        store = KubeStore()
        store.create(self._node("a1", "pool-a"))
        store.create(self._node("b1", "pool-b"))
        store.create(self._member("m0", node="a1"))
        f = MultihostIciFilter(store)
        pending = self._member("m1")
        ok = f.filter(CycleState(), pending, NodeInfo(node=store.get("Node", "a1")))
        blocked = f.filter(CycleState(), pending, NodeInfo(node=store.get("Node", "b1")))
        assert ok.success
        assert not blocked.success and "pinned" in blocked.message

    def test_permit_reserved_members_pin_too(self):
        from nos_tpu.scheduler.framework import CycleState, NodeInfo
        from nos_tpu.scheduler.plugins.gang import GangScheduling
        from nos_tpu.scheduler.plugins.topology import MultihostIciFilter

        store = KubeStore()
        store.create(self._node("a1", "pool-a"))
        store.create(self._node("b1", "pool-b"))
        gang = GangScheduling(store)
        m0 = self._member("m0")
        store.create(m0)
        gang.permit(CycleState(), m0, "a1")  # reserved, not bound
        f = MultihostIciFilter(store, gang)
        blocked = f.filter(
            CycleState(), self._member("m1"), NodeInfo(node=store.get("Node", "b1"))
        )
        assert not blocked.success

    def test_non_multihost_pods_unconstrained(self):
        from nos_tpu.scheduler.framework import CycleState, NodeInfo
        from nos_tpu.scheduler.plugins.topology import MultihostIciFilter

        store = KubeStore()
        store.create(self._node("b1", "pool-b"))
        pod = build_pod("plain", {slice_res("2x2"): 1})
        f = MultihostIciFilter(store)
        assert f.filter(CycleState(), pod, NodeInfo(node=store.get("Node", "b1"))).success
