"""CarveFailed/PartitioningApplied recording: the partitioner's plan loop
re-derives the same verdict every few hundred ms, so events are recorded
only when a pod's verdict CHANGES — messages carry no plan id (a per-plan
id would defeat the recorder's dedup and drain the pod's rate-limit
bucket, silently dropping the eventual PartitioningApplied)."""
from nos_tpu.controllers.partitioner.controller import PartitionerController
from nos_tpu.kube.events import EventRecorder
from nos_tpu.kube.store import KubeStore
from nos_tpu.partitioning.core import ClusterState

from tests.factory import build_pod


class PlannerStub:
    def __init__(self):
        self.last_unserved = {}


def make_controller(store, recorder):
    controller = PartitionerController(
        store=store,
        cluster_state=ClusterState(),
        snapshot_taker=None,
        planner=PlannerStub(),
        actuator=None,
        batch_timeout_seconds=60.0,
        batch_idle_seconds=60.0,
        recorder=recorder,
    )
    return controller


class TestRecordPlanEvents:
    def setup_method(self):
        self.store = KubeStore()
        self.recorder = EventRecorder(self.store, component="test")
        self.controller = make_controller(self.store, self.recorder)
        self.pod = build_pod("train", {}, ns="ml")

    def events(self, reason):
        return [
            e
            for e in self.store.list("Event", namespace="ml")
            if e.reason == reason and e.involved_name == "train"
        ]

    def test_unchanged_reason_records_once(self):
        self.controller.planner.last_unserved = {"ml/train": "lacking 2x2"}
        for _ in range(5):
            self.controller._record_plan_events([self.pod], applied=0)
        events = self.events("CarveFailed")
        assert len(events) == 1
        assert events[0].count == 1
        assert events[0].message == "cannot carve slices for ml/train: lacking 2x2"

    def test_changed_reason_records_again(self):
        self.controller.planner.last_unserved = {"ml/train": "lacking 2x2"}
        self.controller._record_plan_events([self.pod], applied=0)
        self.controller.planner.last_unserved = {"ml/train": "lacking 2x4"}
        self.controller._record_plan_events([self.pod], applied=0)
        assert len(self.events("CarveFailed")) == 2

    def test_served_pod_gets_applied_event_and_memo_clears(self):
        self.controller.planner.last_unserved = {"ml/train": "lacking 2x2"}
        self.controller._record_plan_events([self.pod], applied=0)
        # The next plan serves the pod by re-partitioning a node.
        self.controller.planner.last_unserved = {}
        self.controller._record_plan_events([self.pod], applied=2)
        applied = self.events("PartitioningApplied")
        assert len(applied) == 1
        assert applied[0].message == "re-partitioned 2 node(s) to serve ml/train"
        # Memo cleared: the same verdict returning later is news again.
        self.controller.planner.last_unserved = {"ml/train": "lacking 2x2"}
        self.controller._record_plan_events([self.pod], applied=0)
        assert self.events("CarveFailed")[0].count == 2

    def test_no_plan_application_means_no_applied_event(self):
        self.controller.planner.last_unserved = {}
        self.controller._record_plan_events([self.pod], applied=0)
        assert self.events("PartitioningApplied") == []

    def test_memo_pruned_to_live_pending_set(self):
        self.controller.planner.last_unserved = {"ml/train": "lacking 2x2"}
        self.controller._record_plan_events([self.pod], applied=0)
        other = build_pod("other", {}, ns="ml")
        self.controller.planner.last_unserved = {}
        self.controller._record_plan_events([other], applied=0)
        assert self.controller._last_carve_reason == {}
