"""`make procpool-smoke` gate (ISSUE 18): the process pool backend on a
real 2-pool controller world must produce node geometry byte-identical
to the serial backend at identical seeds/inputs, and a worker killed
mid-stream must escalate in-parent, respawn, and converge back with
zero drift and zero audit violations.

Kept tier-1 (not slow): two spawned workers on a 2-pool / 4-node world
is seconds, and this is the only end-to-end check that the cross-process
delta protocol composes with the full controller loop (actuation, agent
reports, warm mirrors) rather than just with a bare PoolWorkerPool.
"""
from nos_tpu.api.v1alpha1.labels import GKE_NODEPOOL_LABEL
from nos_tpu.cmd.partitioner import register_indexers
from nos_tpu.controllers.partitioner.controller import PartitionerController
from nos_tpu.kube.store import KubeStore
from nos_tpu.partitioning.core import Actuator, ClusterState, Planner
from nos_tpu.partitioning.tpu import TpuPartitioner, TpuSnapshotTaker
from nos_tpu.record.audit import InvariantAuditor
from nos_tpu.scheduler.framework import (
    Framework,
    NodeResourcesFit,
    NodeSelectorFit,
)
from nos_tpu.util import metrics

from tests.factory import build_pod, build_tpu_node, slice_res

POOLS = ("pool-a", "pool-b")
NODES_PER_POOL = 2


def make_store():
    store = KubeStore()
    register_indexers(store)
    for pool in POOLS:
        for i in range(NODES_PER_POOL):
            node = build_tpu_node(name=f"{pool}-n{i}")
            node.metadata.labels[GKE_NODEPOOL_LABEL] = pool
            store.create(node)
    return store


def pinned_pod(name, profile, pool):
    pod = build_pod(name, {slice_res(profile): 1}, scheduler="")
    pod.spec.node_selector[GKE_NODEPOOL_LABEL] = pool
    return pod


def make_controller(store, **kwargs):
    framework = Framework(
        filter_plugins=[NodeResourcesFit(), NodeSelectorFit()]
    )
    return PartitionerController(
        store=store,
        cluster_state=ClusterState(),
        snapshot_taker=TpuSnapshotTaker(),
        planner=Planner(framework),
        actuator=Actuator(TpuPartitioner(store)),
        kind="tpu",
        batch_timeout_seconds=60.0,
        batch_idle_seconds=60.0,
        incremental_planning=True,
        incremental_dirty_threshold=1.0,
        pool_sharding=True,
        **kwargs,
    )


def geometry(store):
    """Every node's actuated annotations, minus plan-id stamps (they
    embed wall-clock timestamps and can never be identical across two
    controllers)."""
    out = {}
    for pool in POOLS:
        for i in range(NODES_PER_POOL):
            node = store.get("Node", f"{pool}-n{i}")
            out[f"{pool}-n{i}"] = {
                key: value
                for key, value in sorted(node.metadata.annotations.items())
                if "plan" not in key
            }
    return out


def test_process_backend_is_byte_identical_to_serial_and_survives_kill():
    serial_store, proc_store = make_store(), make_store()
    serial = make_controller(serial_store)
    auditor = InvariantAuditor(sample_rate=1.0)
    proc = make_controller(
        proc_store, pool_backend="process", auditor=auditor
    )
    try:
        for store in (serial_store, proc_store):
            store.create(pinned_pod("pa", "2x2", "pool-a"))
            store.create(pinned_pod("pb", "1x2", "pool-b"))
        applied_serial = serial.process_pending_pods()
        applied_proc = proc.process_pending_pods()
        assert applied_serial == applied_proc >= 2
        assert geometry(serial_store) == geometry(proc_store)
        assert proc._worker_pool is not None, (
            "process backend never spawned workers — the A/B compared "
            "serial against itself"
        )

        # Steady state: delta-fed cycles keep tracking serial exactly.
        for _ in range(3):
            serial.process_pending_pods()
            proc.process_pending_pods()
        assert geometry(serial_store) == geometry(proc_store)
        assert proc._worker_pool.restarts == 0
        assert auditor.violations_total == 0

        # Kill a worker mid-stream without telling the parent: the next
        # cycle must notice the dead pipe, plan that pool in-parent
        # (escalated), respawn from a fresh wire image, and re-converge
        # with the serial twin — zero drift, zero audit violations.
        escalated_before = metrics.PLAN_BACKEND.labels(
            backend="escalated"
        ).value
        killed = proc._worker_pool.chaos_kill_one()
        assert killed in POOLS
        for store in (serial_store, proc_store):
            store.create(pinned_pod("pc", "1x2", "pool-a"))
        for _ in range(2):
            serial.process_pending_pods()
            proc.process_pending_pods()
        assert geometry(serial_store) == geometry(proc_store)
        assert proc._worker_pool.restarts == 1
        escalated_after = metrics.PLAN_BACKEND.labels(
            backend="escalated"
        ).value
        assert escalated_after > escalated_before, (
            "killed worker's pool never escalated to in-parent planning"
        )
        assert auditor.violations_total == 0
    finally:
        proc.stop()
        serial.stop()
