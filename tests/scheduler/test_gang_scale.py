"""Gang scheduling at scale (VERDICT round-2 #7): an 8-member gang under
chip contention, two gangs racing one slice, and preemption evicting a
full gang including still-pending members."""
import time


from nos_tpu.api.v1alpha1 import constants
from nos_tpu.api.v1alpha1.elasticquota import ElasticQuota, ElasticQuotaSpec
from nos_tpu.kube.controller import Request
from nos_tpu.kube.objects import ObjectMeta, PodPhase
from nos_tpu.kube.store import KubeStore
from nos_tpu.scheduler.plugins.gang import GANG_NAME_LABEL, GANG_SIZE_LABEL
from nos_tpu.scheduler.scheduler import Scheduler, new_framework

from tests.factory import build_pod, build_tpu_node, slice_res

CHIPS = constants.RESOURCE_TPU_CHIPS


def make_scheduler(store, gang_timeout=0.5):
    fw, capacity, gang = new_framework(store, gang_timeout_seconds=gang_timeout)
    return Scheduler(store, fw, capacity=capacity, gang=gang, retry_seconds=0.05)


def gang_pod(name, gang, size, requests=None, ns="default", priority=0):
    pod = build_pod(name, requests or {slice_res("2x4"): 1}, ns=ns, priority=priority)
    pod.metadata.labels[GANG_NAME_LABEL] = gang
    pod.metadata.labels[GANG_SIZE_LABEL] = str(size)
    return pod


def sched(s, store, pod):
    store.create(pod)
    return s.reconcile(Request(name=pod.metadata.name, namespace=pod.metadata.namespace))


def tpu_node(name):
    """A node advertising one free full-board 2x4 slice."""
    node = build_tpu_node(name=name)
    node.status.allocatable = {slice_res("2x4"): 1, "cpu": 8}
    return node


class TestEightMemberGang:
    def test_binds_only_when_all_eight_fit(self):
        store = KubeStore()
        for i in range(8):
            store.create(tpu_node(f"n{i}"))
        s = make_scheduler(store)
        # 7 members arrive: everyone waits in Permit, nobody binds.
        for i in range(7):
            sched(s, store, gang_pod(f"m{i}", "big", 8))
        assert all(
            store.get("Pod", f"m{i}", "default").spec.node_name == ""
            for i in range(7)
        )
        # The 8th arrives: the whole gang binds in one stroke.
        sched(s, store, gang_pod("m7", "big", 8))
        bound = [store.get("Pod", f"m{i}", "default").spec.node_name for i in range(8)]
        assert all(bound), bound
        assert len(set(bound)) == 8  # one board each

    def test_contention_starves_gang_until_capacity_frees(self):
        store = KubeStore()
        for i in range(8):
            store.create(tpu_node(f"n{i}"))
        # an unrelated pod occupies one of the 8 boards
        squatter = build_pod("squatter", {slice_res("2x4"): 1})
        squatter.spec.node_name = "n0"
        squatter.status.phase = PodPhase.RUNNING
        store.create(squatter)
        s = make_scheduler(store, gang_timeout=0.2)
        for i in range(8):
            sched(s, store, gang_pod(f"m{i}", "big", 8))
        # only 7 boards free: the gang cannot complete and times out as a
        # unit — no member may hold a board afterwards.
        time.sleep(0.25)
        s.reconcile(Request(name="m0", namespace="default"))  # drives timeout sweep
        assert all(
            store.get("Pod", f"m{i}", "default").spec.node_name == ""
            for i in range(8)
        )
        # capacity frees -> the gang forms on retry
        store.delete("Pod", "squatter", "default")
        for i in range(8):
            s.reconcile(Request(name=f"m{i}", namespace="default"))
        bound = [store.get("Pod", f"m{i}", "default").spec.node_name for i in range(8)]
        assert all(bound), bound


class TestTwoGangsRacingOneSlice:
    def test_one_wins_atomically_loser_unreserves(self):
        store = KubeStore()
        for i in range(2):
            store.create(tpu_node(f"n{i}"))
        s = make_scheduler(store, gang_timeout=0.2)
        # Interleave arrivals: a0, b0, a1, b1. Two boards total; each gang
        # needs both. First-complete wins; the loser must fully unwind.
        sched(s, store, gang_pod("a0", "alpha", 2))
        sched(s, store, gang_pod("b0", "beta", 2))
        sched(s, store, gang_pod("a1", "alpha", 2))
        sched(s, store, gang_pod("b1", "beta", 2))
        time.sleep(0.25)
        for name in ("a0", "a1", "b0", "b1"):
            s.reconcile(Request(name=name, namespace="default"))

        def nodes_of(gang):
            return [
                store.get("Pod", f"{gang}{i}", "default").spec.node_name
                for i in range(2)
            ]

        alpha, beta = nodes_of("a"), nodes_of("b")
        winner, loser = (alpha, beta) if all(alpha) else (beta, alpha)
        assert all(winner), (alpha, beta)   # exactly one gang fully bound
        assert not any(loser), (alpha, beta)  # the other holds NOTHING
        # the loser eventually forms once the winner finishes
        for i in range(2):
            w = store.get("Pod", f"{'a' if winner is alpha else 'b'}{i}", "default")
            w.status.phase = PodPhase.SUCCEEDED
            store.update(w)
        loser_prefix = "b" if winner is alpha else "a"
        for i in range(2):
            s.reconcile(Request(name=f"{loser_prefix}{i}", namespace="default"))
        assert all(
            store.get("Pod", f"{loser_prefix}{i}", "default").spec.node_name
            for i in range(2)
        )


class TestGangPreemptionIncludesPendingMembers:
    def test_full_gang_evicted_with_unbound_member(self):
        """An over-quota gang with one member still Pending/unbound is
        evicted WHOLE — the pending member must not survive to deadlock a
        quorum that can never re-form (preemption round-1 advisory)."""
        store = KubeStore()
        for i in range(2):
            store.create(tpu_node(f"n{i}"))
        store.create(
            ElasticQuota(
                metadata=ObjectMeta(name="eq-a", namespace="team-a"),
                spec=ElasticQuotaSpec(min={CHIPS: 0}, max={CHIPS: 16}),
            )
        )
        store.create(
            ElasticQuota(
                metadata=ObjectMeta(name="eq-b", namespace="team-b"),
                spec=ElasticQuotaSpec(min={CHIPS: 16}, max={CHIPS: 16}),
            )
        )
        # team-a's gang of 3: two members bound (borrowing over min=0),
        # the third exists but never bound. The operator normally stamps
        # the over-quota capacity label; set it here (no operator running).
        from nos_tpu.api.v1alpha1 import labels as l

        for i, node in ((0, "n0"), (1, "n1")):
            m = gang_pod(f"g{i}", "loadjob", 3, ns="team-a")
            m.metadata.labels[l.CAPACITY_LABEL] = l.CAPACITY_OVER_QUOTA
            m.spec.node_name = node
            m.status.phase = PodPhase.RUNNING
            store.create(m)
        straggler = gang_pod("g2", "loadjob", 3, ns="team-a")
        straggler.metadata.labels[l.CAPACITY_LABEL] = l.CAPACITY_OVER_QUOTA
        store.create(straggler)

        s = make_scheduler(store)
        # team-b claims its guaranteed min -> preemption targets the gang.
        claim = build_pod("claim", {slice_res("2x4"): 1}, ns="team-b")
        sched(s, store, claim)
        for _ in range(3):
            s.reconcile(Request(name="claim", namespace="team-b"))
            if store.get("Pod", "claim", "team-b").spec.node_name:
                break
        remaining = [
            p.metadata.name for p in store.list("Pod", namespace="team-a")
        ]
        assert remaining == [], remaining  # bound AND pending members gone
        assert store.get("Pod", "claim", "team-b").spec.node_name
