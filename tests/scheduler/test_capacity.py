from nos_tpu.api.v1alpha1 import constants
from nos_tpu.api.v1alpha1.elasticquota import (
    CompositeElasticQuota,
    CompositeElasticQuotaSpec,
    ElasticQuota,
    ElasticQuotaSpec,
)
from nos_tpu.kube.objects import ObjectMeta
from nos_tpu.kube.store import KubeStore
from nos_tpu.scheduler.framework import CycleState
from nos_tpu.scheduler.plugins.capacity import (
    CapacityScheduling,
    ElasticQuotaInfo,
    ElasticQuotaInfos,
    build_quota_infos,
)

from tests.factory import build_pod

CHIPS = constants.RESOURCE_TPU_CHIPS


def eq(ns, min=None, max=None, name="quota"):
    return ElasticQuota(
        metadata=ObjectMeta(name=name, namespace=ns),
        spec=ElasticQuotaSpec(min=min or {}, max=max or {}),
    )


def info(name, ns, min=None, max=None, used=None):
    i = ElasticQuotaInfo(name, {ns}, min or {}, max)
    i.used = dict(used or {})
    return i


class TestElasticQuotaInfo:
    def test_used_over_min_with(self):
        i = info("a", "a", min={CHIPS: 8}, used={CHIPS: 6})
        assert not i.used_over_min_with({CHIPS: 2})
        assert i.used_over_min_with({CHIPS: 3})

    def test_used_over_max_with(self):
        i = info("a", "a", min={CHIPS: 4}, max={CHIPS: 8}, used={CHIPS: 6})
        assert not i.used_over_max_with({CHIPS: 2})
        assert i.used_over_max_with({CHIPS: 3})

    def test_no_max_is_unlimited(self):
        i = info("a", "a", min={CHIPS: 4}, used={CHIPS: 100})
        assert not i.used_over_max_with({CHIPS: 100})

    def test_add_remove_pod_idempotent(self):
        i = info("a", "a", min={CHIPS: 8})
        i.add_pod("ns/p", {CHIPS: 4})
        i.add_pod("ns/p", {CHIPS: 4})
        assert i.used == {CHIPS: 4}
        i.remove_pod("ns/p", {CHIPS: 4})
        i.remove_pod("ns/p", {CHIPS: 4})
        assert i.used == {CHIPS: 0}


class TestGuaranteedOverquota:
    def test_fair_share_math(self):
        # Reference elasticquotainfo.go:81-152:
        # guaranteed_i = floor(min_i/Σmin · Σ_j max(0, min_j-used_j))
        infos = ElasticQuotaInfos(
            [
                info("a", "a", min={CHIPS: 6}, used={CHIPS: 6}),
                info("b", "b", min={CHIPS: 2}, used={CHIPS: 0}),
                info("c", "c", min={CHIPS: 4}, used={CHIPS: 1}),
            ]
        )
        # unused = 0 + 2 + 3 = 5; Σmin = 12
        assert infos.guaranteed_overquota("a", CHIPS) == 2  # floor(6/12*5)
        assert infos.guaranteed_overquota("b", CHIPS) == 0  # floor(2/12*5)
        assert infos.guaranteed_overquota("c", CHIPS) == 1  # floor(4/12*5)

    def test_aggregated_used_over_min(self):
        infos = ElasticQuotaInfos(
            [
                info("a", "a", min={CHIPS: 4}, used={CHIPS: 4}),
                info("b", "b", min={CHIPS: 4}, used={CHIPS: 3}),
            ]
        )
        assert not infos.aggregated_used_over_min_with({CHIPS: 1})
        assert infos.aggregated_used_over_min_with({CHIPS: 2})

    def test_within_guaranteed_with(self):
        infos = ElasticQuotaInfos(
            [
                info("a", "a", min={CHIPS: 4}, used={CHIPS: 2}),
                info("b", "b", min={CHIPS: 4}, used={CHIPS: 0}),
            ]
        )
        assert infos.within_guaranteed_with("a", {CHIPS: 2})
        # beyond min but within min + floor(4/8 * unused 6) = 4+3
        assert infos.within_guaranteed_with("a", {CHIPS: 5})
        assert not infos.within_guaranteed_with("a", {CHIPS: 6})


class TestBuildQuotaInfos:
    def test_ceq_shadows_eq(self):
        store = KubeStore()
        store.create(eq("a", min={CHIPS: 2}))
        store.create(
            CompositeElasticQuota(
                metadata=ObjectMeta(name="c", namespace="default"),
                spec=CompositeElasticQuotaSpec(namespaces=["a", "b"], min={CHIPS: 8}),
            )
        )
        infos = build_quota_infos(store)
        assert infos.for_namespace("a").name == "ceq/c"
        assert infos.for_namespace("b").name == "ceq/c"

    def test_usage_from_bound_pods(self):
        store = KubeStore()
        store.create(eq("a", min={CHIPS: 8}))
        store.create(build_pod("p", {constants.RESOURCE_TPU: 4}, ns="a", node="n1", phase="Running"))
        store.create(build_pod("unbound", {constants.RESOURCE_TPU: 2}, ns="a"))
        infos = build_quota_infos(store)
        assert infos.for_namespace("a").used == {
            CHIPS: 4,
            constants.RESOURCE_TPU: 4,
            constants.RESOURCE_TPU_MEMORY: 4 * constants.DEFAULT_TPU_CHIP_MEMORY_GB,
        }


class TestPreFilter:
    def test_no_quota_passes(self):
        plugin = CapacityScheduling(KubeStore())
        assert plugin.pre_filter(CycleState(), build_pod("p", {CHIPS: 4})).success

    def test_max_enforced(self):
        store = KubeStore()
        store.create(eq("a", min={CHIPS: 4}, max={CHIPS: 8}))
        store.create(build_pod("running", {constants.RESOURCE_TPU: 8}, ns="a", node="n", phase="Running"))
        plugin = CapacityScheduling(store)
        status = plugin.pre_filter(CycleState(), build_pod("p", {constants.RESOURCE_TPU: 1}, ns="a"))
        assert not status.success
        assert "max" in status.message

    def test_borrowing_allowed_within_aggregate_min(self):
        store = KubeStore()
        store.create(eq("a", min={CHIPS: 4}, max={CHIPS: 16}))
        store.create(eq("b", min={CHIPS: 8}))
        store.create(build_pod("running", {constants.RESOURCE_TPU: 4}, ns="a", node="n", phase="Running"))
        plugin = CapacityScheduling(store)
        # a over min (4+4>4) but aggregate used 4+4=8 ≤ Σmin 12 -> borrow ok
        status = plugin.pre_filter(CycleState(), build_pod("p", {constants.RESOURCE_TPU: 4}, ns="a"))
        assert status.success

    def test_borrowing_rejected_when_pool_exhausted(self):
        store = KubeStore()
        store.create(eq("a", min={CHIPS: 4}, max={CHIPS: 16}))
        store.create(eq("b", min={CHIPS: 4}))
        store.create(build_pod("ra", {constants.RESOURCE_TPU: 4}, ns="a", node="n", phase="Running"))
        store.create(build_pod("rb", {constants.RESOURCE_TPU: 3}, ns="b", node="n", phase="Running"))
        plugin = CapacityScheduling(store)
        # a wants 2 over min; aggregate used 7+2=9 > Σmin 8 -> reject
        status = plugin.pre_filter(CycleState(), build_pod("p", {constants.RESOURCE_TPU: 2}, ns="a"))
        assert not status.success

    def test_reserve_counts_until_forgotten(self):
        store = KubeStore()
        store.create(eq("a", min={CHIPS: 4}, max={CHIPS: 4}))
        plugin = CapacityScheduling(store)
        pod = build_pod("p", {constants.RESOURCE_TPU: 4}, ns="a")
        state = CycleState()
        assert plugin.pre_filter(state, pod).success
        plugin.reserve(state, pod, "n1")
        # second pod exceeds max because of the in-flight reservation
        second = build_pod("q", {constants.RESOURCE_TPU: 1}, ns="a")
        assert not plugin.pre_filter(CycleState(), second).success
        plugin.unreserve(state, pod, "n1")
        assert plugin.pre_filter(CycleState(), second).success
