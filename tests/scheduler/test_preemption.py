"""Victim-unit preemption: gang atomicity and PDB-aware reprieve.

The reference selects victims pod-by-pod (capacity_scheduling.go:468-675);
the TPU build's SelectVictimsOnNode works on atomic units so a multi-host
gang is never half-evicted (SURVEY.md §7 hard part), and mirrors the
reference's PDB-aware reprieve (:626-674).
"""
from nos_tpu.api.v1alpha1 import constants, labels
from nos_tpu.api.v1alpha1.elasticquota import ElasticQuota, ElasticQuotaSpec
from nos_tpu.kube.objects import (
    ObjectMeta,
    PodDisruptionBudget,
    PodDisruptionBudgetSpec,
)
from nos_tpu.kube.store import KubeStore
from nos_tpu.scheduler.plugins.gang import GANG_NAME_LABEL, GANG_SIZE_LABEL

from tests.factory import build_node, build_pod
from tests.scheduler.test_scheduler import make_scheduler, sched_pod

CHIPS = constants.RESOURCE_TPU_CHIPS


def quota(ns, name="q", min_chips=4, max_chips=16):
    return ElasticQuota(
        metadata=ObjectMeta(name=name, namespace=ns),
        spec=ElasticQuotaSpec(min={CHIPS: min_chips}, max={CHIPS: max_chips}),
    )


def over_quota_pod(name, chips, ns, node, gang=None, gang_size=None, extra_labels=None):
    pod = build_pod(name, {CHIPS: chips}, ns=ns, node=node, phase="Running")
    pod.metadata.labels[labels.CAPACITY_LABEL] = labels.CAPACITY_OVER_QUOTA
    if gang:
        pod.metadata.labels[GANG_NAME_LABEL] = gang
        pod.metadata.labels[GANG_SIZE_LABEL] = str(gang_size or 2)
    for k, v in (extra_labels or {}).items():
        pod.metadata.labels[k] = v
    return pod


class TestGangAtomicPreemption:
    def make_store(self):
        store = KubeStore()
        store.create(build_node("n1", alloc={CHIPS: 8, "cpu": 64}))
        store.create(build_node("n2", alloc={CHIPS: 8, "cpu": 64}))
        store.create(quota("team-a"))
        store.create(quota("team-b"))
        return store

    def test_evicting_gang_member_cascades_to_whole_gang(self):
        store = self.make_store()
        # team-b gang spans both nodes, borrowing beyond min (over-quota).
        store.create(over_quota_pod("g0", 8, "team-b", "n1", gang="trainer"))
        store.create(over_quota_pod("g1", 8, "team-b", "n2", gang="trainer"))
        s = make_scheduler(store)
        result = sched_pod(s, store, build_pod("p", {CHIPS: 4}, ns="team-a"))
        assert result is not None
        # BOTH members evicted even though the preemptor needs one node:
        # the survivor would deadlock holding chips it can never use.
        assert store.try_get("Pod", "g0", "team-b") is None
        assert store.try_get("Pod", "g1", "team-b") is None
        assert store.get("Pod", "p", "team-a").status.nominated_node_name

    def test_gang_with_ineligible_member_is_untouchable(self):
        store = self.make_store()
        store.create(over_quota_pod("g0", 8, "team-b", "n1", gang="trainer"))
        # second member is in-quota → the gang as a unit is not reclaimable
        in_q = build_pod("g1", {CHIPS: 8}, ns="team-b", node="n2", phase="Running")
        in_q.metadata.labels[labels.CAPACITY_LABEL] = labels.CAPACITY_IN_QUOTA
        in_q.metadata.labels[GANG_NAME_LABEL] = "trainer"
        in_q.metadata.labels[GANG_SIZE_LABEL] = "2"
        store.create(in_q)
        s = make_scheduler(store)
        sched_pod(s, store, build_pod("p", {CHIPS: 4}, ns="team-a"))
        assert store.try_get("Pod", "g0", "team-b") is not None
        assert store.try_get("Pod", "g1", "team-b") is not None
        assert store.get("Pod", "p", "team-a").spec.node_name == ""

    def test_singleton_preferred_over_gang(self):
        """Fewest-evictions node choice: a node whose victims are one solo
        pod beats one that would cost a whole 2-pod gang."""
        store = KubeStore()
        store.create(build_node("n1", alloc={CHIPS: 8, "cpu": 64}))
        store.create(build_node("n2", alloc={CHIPS: 8, "cpu": 64}))
        store.create(quota("team-a", min_chips=8))
        store.create(quota("team-b"))
        store.create(over_quota_pod("solo", 8, "team-b", "n1"))
        store.create(over_quota_pod("g0", 8, "team-b", "n2", gang="trainer"))
        g1 = over_quota_pod("g1", 4, "team-b", "n2", gang="trainer")
        # keep both gang members on n2 (8+4 > 8 chips won't fit; use cpu-only second member)
        g1.spec.containers[0].requests = {"cpu": 1}
        store.create(g1)
        s = make_scheduler(store)
        sched_pod(s, store, build_pod("p", {CHIPS: 8}, ns="team-a"))
        assert store.try_get("Pod", "solo", "team-b") is None
        assert store.try_get("Pod", "g0", "team-b") is not None
        assert store.get("Pod", "p", "team-a").status.nominated_node_name == "n1"


class TestCrossQuotaEligibility:
    def test_borrower_cannot_evict_beyond_guaranteed_share(self):
        """A preemptor already past min + fair share cannot reclaim another
        borrower's pods (reference :543-564 is a conjunction — the
        victim-borrowing branch :566-581 only applies to preemptors still
        within their min)."""
        store = KubeStore()
        store.create(build_node("n1", alloc={CHIPS: 8, "cpu": 64}))
        store.create(quota("team-a", min_chips=4))
        store.create(quota("team-b", min_chips=4))
        store.create(over_quota_pod("borrower", 8, "team-b", "n1"))
        s = make_scheduler(store)
        # team-a asks for 8: min 4 + fair share 2 = 6 < 8 → not entitled.
        sched_pod(s, store, build_pod("p", {CHIPS: 8}, ns="team-a"))
        assert store.try_get("Pod", "borrower", "team-b") is not None
        assert store.get("Pod", "p", "team-a").spec.node_name == ""


class TestPdbAwarePreemption:
    def make_store(self):
        store = KubeStore()
        store.create(build_node("n1", alloc={CHIPS: 8, "cpu": 64}))
        store.create(build_node("n2", alloc={CHIPS: 8, "cpu": 64}))
        # team-a's min covers the preemptor, so admission rides the
        # guaranteed path and the tests exercise only PDB preferences.
        store.create(quota("team-a", min_chips=8))
        store.create(quota("team-b"))
        return store

    def test_prefers_node_without_pdb_violation(self):
        store = self.make_store()
        store.create(
            over_quota_pod("protected", 8, "team-b", "n1", extra_labels={"app": "svc"})
        )
        store.create(over_quota_pod("plain", 8, "team-b", "n2"))
        # PDB: all "app=svc" pods must stay up.
        store.create(
            PodDisruptionBudget(
                metadata=ObjectMeta(name="pdb", namespace="team-b"),
                spec=PodDisruptionBudgetSpec(selector={"app": "svc"}, min_available=1),
            )
        )
        s = make_scheduler(store)
        sched_pod(s, store, build_pod("p", {CHIPS: 8}, ns="team-a"))
        assert store.try_get("Pod", "protected", "team-b") is not None
        assert store.try_get("Pod", "plain", "team-b") is None
        assert store.get("Pod", "p", "team-a").status.nominated_node_name == "n2"

    def test_pdb_violation_still_allowed_as_last_resort(self):
        store = self.make_store()
        store.create(
            over_quota_pod("protected", 8, "team-b", "n1", extra_labels={"app": "svc"})
        )
        store.create(
            PodDisruptionBudget(
                metadata=ObjectMeta(name="pdb", namespace="team-b"),
                spec=PodDisruptionBudgetSpec(selector={"app": "svc"}, min_available=1),
            )
        )
        s = make_scheduler(store)
        # Only one node can serve the pod; the PDB-violating eviction is the
        # last resort and still happens (reference semantics: PDBs shape
        # preference, not a hard bar).
        store.delete("Node", "n2")
        sched_pod(s, store, build_pod("p", {CHIPS: 8}, ns="team-a"))
        assert store.try_get("Pod", "protected", "team-b") is None

    def test_cumulative_pdb_budget_counts_second_eviction_as_violation(self):
        """Two victims that each fit a budget of one are NOT both
        violation-free: the classification pass charges the shared budget
        cumulatively (reference filterPodsWithPDBViolation semantics)."""
        store = self.make_store()
        # n1 holds two svc pods, both needed to fit the preemptor.
        store.create(
            over_quota_pod("svc-0", 4, "team-b", "n1", extra_labels={"app": "svc"})
        )
        store.create(
            over_quota_pod("svc-1", 4, "team-b", "n1", extra_labels={"app": "svc"})
        )
        # n2 holds two plain pods: same eviction count, no PDB involvement.
        store.create(over_quota_pod("plain-0", 4, "team-b", "n2"))
        store.create(over_quota_pod("plain-1", 4, "team-b", "n2"))
        store.create(
            PodDisruptionBudget(
                metadata=ObjectMeta(name="pdb", namespace="team-b"),
                spec=PodDisruptionBudgetSpec(selector={"app": "svc"}, max_unavailable=1),
            )
        )
        s = make_scheduler(store)
        sched_pod(s, store, build_pod("p", {CHIPS: 8}, ns="team-a"))
        # evicting both svc pods would violate the budget; the plain node wins
        assert store.try_get("Pod", "svc-0", "team-b") is not None
        assert store.try_get("Pod", "svc-1", "team-b") is not None
        assert store.get("Pod", "p", "team-a").status.nominated_node_name == "n2"

    def test_pdb_budget_allows_disruption_within_allowance(self):
        store = self.make_store()
        store.create(
            over_quota_pod("svc-0", 8, "team-b", "n1", extra_labels={"app": "svc"})
        )
        store.create(
            over_quota_pod("svc-1", 8, "team-b", "n2", extra_labels={"app": "svc"})
        )
        store.create(
            PodDisruptionBudget(
                metadata=ObjectMeta(name="pdb", namespace="team-b"),
                spec=PodDisruptionBudgetSpec(selector={"app": "svc"}, max_unavailable=1),
            )
        )
        s = make_scheduler(store)
        sched_pod(s, store, build_pod("p", {CHIPS: 8}, ns="team-a"))
        # exactly one eviction: within the PDB allowance, no violation
        survivors = [
            store.try_get("Pod", "svc-0", "team-b"),
            store.try_get("Pod", "svc-1", "team-b"),
        ]
        assert sum(1 for x in survivors if x is None) == 1


class TestSpreadAwarePreemption:
    """Cross-node gang evictions must be visible to the topology-spread
    predicate during victim trials: the published (pre-eviction) counts for
    remote nodes would otherwise report a resolvable skew that the real
    post-eviction cluster does not have — destroying a gang for a
    nomination the next cycle rejects."""

    def test_gang_not_destroyed_when_remote_evictions_break_spread(self):
        from nos_tpu.kube.objects import TopologySpreadConstraint

        store = KubeStore()
        n1 = build_node("n1", alloc={CHIPS: 4, "cpu": 64})
        n1.metadata.labels["topology.kubernetes.io/zone"] = "zone-a"
        n1.metadata.labels["pool"] = "a"
        store.create(n1)
        n2 = build_node("n2", alloc={CHIPS: 8, "cpu": 64})
        n2.metadata.labels["topology.kubernetes.io/zone"] = "zone-b"
        store.create(n2)
        store.create(quota("team-a"))
        store.create(quota("team-b"))

        # team-b web gang: one member on n1, two on n2 (all over-quota).
        store.create(
            over_quota_pod("w0", 4, "team-b", "n1", gang="trainer", gang_size=3,
                           extra_labels={"app": "web"})
        )
        for i, name in enumerate(("w1", "w2")):
            store.create(
                over_quota_pod(name, 4, "team-b", "n2", gang="trainer", gang_size=3,
                               extra_labels={"app": "web"})
            )
        # Two high-priority non-victim web replicas on n1 (cpu-only).
        for i in range(2):
            anchor = build_pod(f"anchor-{i}", {"cpu": 1}, ns="team-a",
                               node="n1", phase="Running", priority=100)
            anchor.metadata.labels["app"] = "web"
            store.create(anchor)

        preemptor = build_pod("p", {CHIPS: 4}, ns="team-a")
        preemptor.metadata.labels["app"] = "web"
        preemptor.spec.node_selector = {"pool": "a"}  # only n1 is a candidate
        preemptor.spec.topology_spread_constraints = [
            TopologySpreadConstraint(
                topology_key="topology.kubernetes.io/zone",
                max_skew=1,
                match_labels={"app": "web"},
            )
        ]
        s = make_scheduler(store)
        sched_pod(s, store, preemptor)
        # True post-eviction counts: zone-a 2 anchors + preemptor = 3,
        # zone-b 0 -> skew 3 > 1: infeasible. The stale published view
        # (zone-b still 2) would wrongly report skew 1 and evict the gang.
        assert store.try_get("Pod", "w0", "team-b") is not None
        assert store.try_get("Pod", "w1", "team-b") is not None
        assert store.try_get("Pod", "w2", "team-b") is not None
        pod = store.get("Pod", "p", "team-a")
        assert pod.status.nominated_node_name == ""


class TestAffinityAwarePreemption:
    def test_eviction_resolves_anti_affinity_violation(self):
        """The victim trial (candidate pods minus victims) is what the
        inter-pod affinity predicate must see: evicting the only
        conflicting pod makes the node feasible, so preemption must
        nominate instead of leaving the pod pending forever on a stale
        pre-eviction index."""
        from nos_tpu.kube.objects import PodAffinityTerm

        store = KubeStore()
        n1 = build_node("n1", alloc={CHIPS: 8, "cpu": 64})
        n1.metadata.labels["topology.kubernetes.io/zone"] = "zone-a"
        store.create(n1)
        store.create(quota("team-a"))
        store.create(quota("team-b"))
        blocker = over_quota_pod("blocker", 8, "team-b", "n1",
                                 extra_labels={"app": "web"})
        store.create(blocker)
        s = make_scheduler(store)
        preemptor = build_pod("p", {CHIPS: 4}, ns="team-a")
        preemptor.metadata.labels["app"] = "web"
        preemptor.spec.pod_anti_affinity = [PodAffinityTerm(
            topology_key="topology.kubernetes.io/zone",
            match_labels={"app": "web"},
        )]
        sched_pod(s, store, preemptor)
        assert store.try_get("Pod", "blocker", "team-b") is None
        assert store.get("Pod", "p", "team-a").status.nominated_node_name == "n1"
