"""AutoscalerGraceScoring: grace-held boards deterministically lose
ties to unreserved nodes for unrelated pods, and win them for the
returning model's own replicas — placement (and hence the capacity
ledger's bucket attribution) stays reproducible around scale-to-zero."""
from nos_tpu.api.v1alpha1 import annotations as annot
from nos_tpu.api.v1alpha1 import labels
from nos_tpu.kube.objects import ObjectMeta, Pod, PodSpec
from nos_tpu.scheduler.framework import CycleState, NodeInfo
from nos_tpu.scheduler.plugins.reservation import AutoscalerGraceScoring

from tests.factory import build_tpu_node


def pod(serving_key=None):
    meta = ObjectMeta(name="p", namespace="default")
    if serving_key:
        meta.labels[labels.MODEL_SERVING_LABEL] = serving_key
    return Pod(metadata=meta, spec=PodSpec())


def node_info(reserved_for=None):
    annotations = {}
    if reserved_for:
        annotations[annot.AUTOSCALER_RESERVED] = reserved_for
        annotations[annot.AUTOSCALER_RESERVED_UNTIL] = "1000.0"
    return NodeInfo(build_tpu_node(name="n", annotations=annotations))


def test_unreserved_node_scores_neutral():
    plugin = AutoscalerGraceScoring()
    assert plugin.score(CycleState(), pod(), node_info()) == 30


def test_holder_model_prefers_its_grace_board():
    plugin = AutoscalerGraceScoring()
    own = plugin.score(
        CycleState(), pod("default.svc"), node_info(reserved_for="default.svc")
    )
    neutral = plugin.score(CycleState(), pod("default.svc"), node_info())
    assert own > neutral  # cold start lands back on the still-carved board


def test_foreign_pod_avoids_grace_boards():
    plugin = AutoscalerGraceScoring()
    foreign = plugin.score(
        CycleState(), pod("default.other"), node_info(reserved_for="default.svc")
    )
    plain = plugin.score(CycleState(), pod(), node_info(reserved_for="default.svc"))
    assert foreign == plain == 0  # soft steering: score, not filter


def test_plugin_is_wired_into_the_default_framework():
    from nos_tpu.kube.store import KubeStore
    from nos_tpu.scheduler.scheduler import new_framework

    framework, _, _ = new_framework(KubeStore())
    names = [type(p).__name__ for p in framework.score_plugins]
    assert "AutoscalerGraceScoring" in names
