import time

from nos_tpu.api.v1alpha1 import constants, labels
from nos_tpu.api.v1alpha1.elasticquota import ElasticQuota, ElasticQuotaSpec
from nos_tpu.kube.controller import Request
from nos_tpu.kube.objects import ObjectMeta, PodPhase
from nos_tpu.kube.store import KubeStore
from nos_tpu.scheduler.plugins.gang import GANG_NAME_LABEL, GANG_SIZE_LABEL
from nos_tpu.scheduler.scheduler import Scheduler, new_framework

from tests.factory import build_node, build_pod, build_tpu_node, slice_res

CHIPS = constants.RESOURCE_TPU_CHIPS


def make_scheduler(store, gang_timeout=0.3):
    fw, capacity, gang = new_framework(store, gang_timeout_seconds=gang_timeout)
    return Scheduler(store, fw, capacity=capacity, gang=gang, retry_seconds=0.05)


def sched_pod(scheduler, store, pod):
    store.create(pod)
    return scheduler.reconcile(Request(name=pod.metadata.name, namespace=pod.metadata.namespace))


class TestBasicScheduling:
    def test_binds_to_fitting_node(self):
        store = KubeStore()
        store.create(build_node("n1", alloc={"cpu": 4}))
        s = make_scheduler(store)
        sched_pod(s, store, build_pod("p", {"cpu": 2}))
        assert store.get("Pod", "p", "default").spec.node_name == "n1"

    def test_unschedulable_marks_condition(self):
        store = KubeStore()
        store.create(build_node("n1", alloc={"cpu": 1}))
        s = make_scheduler(store)
        result = sched_pod(s, store, build_pod("p", {"cpu": 2}))
        pod = store.get("Pod", "p", "default")
        assert pod.spec.node_name == ""
        assert pod.unschedulable()
        assert result is not None and result.requeue_after > 0

    def test_prefers_exact_slice_fit(self):
        store = KubeStore()
        # n-exact advertises a free 2x2; n-big advertises a 2x4.
        exact = build_tpu_node(name="n-exact")
        exact.status.allocatable = {slice_res("2x2"): 1, "cpu": 8}
        store.create(exact)
        big = build_tpu_node(name="n-big")
        big.status.allocatable = {slice_res("2x2"): 1, slice_res("2x4"): 1, "cpu": 8}
        store.create(big)
        s = make_scheduler(store)
        sched_pod(s, store, build_pod("p", {slice_res("2x2"): 1}))
        # consolidation: n-exact is fully consumed by the pod; n-big strands a 2x4
        assert store.get("Pod", "p", "default").spec.node_name == "n-exact"

    def test_already_bound_pod_ignored(self):
        store = KubeStore()
        store.create(build_node("n1"))
        s = make_scheduler(store)
        pod = build_pod("p", {"cpu": 1}, node="n1")
        store.create(pod)
        assert s.reconcile(Request(name="p", namespace="default")) is None


class TestPreemptionFlow:
    def make_cluster(self):
        store = KubeStore()
        store.create(build_node("n1", alloc={CHIPS: 8, "cpu": 64}))
        store.create(
            ElasticQuota(
                metadata=ObjectMeta(name="qa", namespace="team-a"),
                spec=ElasticQuotaSpec(min={CHIPS: 4}, max={CHIPS: 8}),
            )
        )
        store.create(
            ElasticQuota(
                metadata=ObjectMeta(name="qb", namespace="team-b"),
                spec=ElasticQuotaSpec(min={CHIPS: 4}, max={CHIPS: 8}),
            )
        )
        return store

    def test_over_quota_pod_preempted_by_guaranteed_claim(self):
        store = self.make_cluster()
        # team-b borrowed the whole node: 8 chips (4 over min), over-quota labeled.
        borrower = build_pod("borrower", {CHIPS: 8}, ns="team-b", node="n1", phase="Running")
        borrower.metadata.labels[labels.CAPACITY_LABEL] = labels.CAPACITY_OVER_QUOTA
        store.create(borrower)
        s = make_scheduler(store)
        sched_pod(s, store, build_pod("p", {CHIPS: 4}, ns="team-a"))
        # borrower evicted, node nominated
        assert store.try_get("Pod", "borrower", "team-b") is None
        assert store.get("Pod", "p", "team-a").status.nominated_node_name == "n1"
        # next cycle binds
        s.reconcile(Request(name="p", namespace="team-a"))
        assert store.get("Pod", "p", "team-a").spec.node_name == "n1"

    def test_in_quota_pod_not_preempted(self):
        store = self.make_cluster()
        holder = build_pod("holder", {CHIPS: 8}, ns="team-b", node="n1", phase="Running")
        holder.metadata.labels[labels.CAPACITY_LABEL] = labels.CAPACITY_IN_QUOTA
        store.create(holder)
        s = make_scheduler(store)
        sched_pod(s, store, build_pod("p", {CHIPS: 4}, ns="team-a"))
        assert store.try_get("Pod", "holder", "team-b") is not None
        assert store.get("Pod", "p", "team-a").spec.node_name == ""

    def test_same_namespace_priority_preemption(self):
        store = self.make_cluster()
        low = build_pod("low", {CHIPS: 8}, ns="team-a", node="n1", phase="Running", priority=0)
        store.create(low)
        s = make_scheduler(store)
        vip = build_pod("vip", {CHIPS: 8}, ns="team-a", priority=100)
        sched_pod(s, store, vip)
        assert store.try_get("Pod", "low", "team-a") is None

    def test_lower_priority_preemptor_cannot_evict(self):
        store = self.make_cluster()
        high = build_pod("high", {CHIPS: 8}, ns="team-a", node="n1", phase="Running", priority=100)
        store.create(high)
        s = make_scheduler(store)
        sched_pod(s, store, build_pod("p", {CHIPS: 8}, ns="team-a", priority=0))
        assert store.try_get("Pod", "high", "team-a") is not None


class TestGangScheduling:
    def gang_pod(self, name, size=2, requests=None):
        pod = build_pod(name, requests or {"cpu": 1}, ns="ml")
        pod.metadata.labels[GANG_NAME_LABEL] = "job"
        pod.metadata.labels[GANG_SIZE_LABEL] = str(size)
        return pod

    def test_gang_binds_together(self):
        store = KubeStore()
        store.create(build_node("n1", alloc={"cpu": 4}))
        store.create(build_node("n2", alloc={"cpu": 4}))
        s = make_scheduler(store)
        sched_pod(s, store, self.gang_pod("w0"))
        # first member waits
        assert store.get("Pod", "w0", "ml").spec.node_name == ""
        sched_pod(s, store, self.gang_pod("w1"))
        # quorum reached: both bound
        assert store.get("Pod", "w0", "ml").spec.node_name != ""
        assert store.get("Pod", "w1", "ml").spec.node_name != ""

    def test_gang_timeout_releases_reservations(self):
        store = KubeStore()
        store.create(build_node("n1", alloc={"cpu": 2}))
        s = make_scheduler(store, gang_timeout=0.05)
        sched_pod(s, store, self.gang_pod("w0", size=2, requests={"cpu": 2}))
        assert store.get("Pod", "w0", "ml").spec.node_name == ""
        time.sleep(0.1)
        s._handle_gang_timeouts()
        assert s.gang.waiting_count() == 0
        assert store.get("Pod", "w0", "ml").unschedulable()
        # the freed reservation lets an ordinary pod through
        sched_pod(s, store, build_pod("solo", {"cpu": 2}))
        assert store.get("Pod", "solo", "default").spec.node_name == "n1"

    def test_partial_gang_counts_bound_members(self):
        store = KubeStore()
        store.create(build_node("n1", alloc={"cpu": 4}))
        s = make_scheduler(store)
        bound = self.gang_pod("w0")
        bound.spec.node_name = "n1"
        bound.status.phase = PodPhase.RUNNING
        store.create(bound)
        sched_pod(s, store, self.gang_pod("w1"))
        assert store.get("Pod", "w1", "ml").spec.node_name == "n1"


class TestReviewRegressions:
    def test_quota_only_preemption_on_roomy_node(self):
        """Node has resource headroom; only the quota blocks the pod. The
        over-quota borrower must still be evicted (quota-aware reprieve)."""
        store = KubeStore()
        store.create(build_node("n1", alloc={CHIPS: 16, "cpu": 64}))
        for ns in ("team-a", "team-b"):
            store.create(
                ElasticQuota(
                    metadata=ObjectMeta(name=f"q-{ns}", namespace=ns),
                    spec=ElasticQuotaSpec(min={CHIPS: 4}, max={CHIPS: 16}),
                )
            )
        borrower = build_pod("borrower", {CHIPS: 8}, ns="team-b", node="n1", phase="Running")
        borrower.metadata.labels[labels.CAPACITY_LABEL] = labels.CAPACITY_OVER_QUOTA
        store.create(borrower)
        s = make_scheduler(store)
        # team-a claims 6: within min 4 + fair share of unused min.
        sched_pod(s, store, build_pod("p", {CHIPS: 6}, ns="team-a"))
        assert store.try_get("Pod", "borrower", "team-b") is None
        assert store.get("Pod", "p", "team-a").status.nominated_node_name == "n1"

    def test_waiting_gang_member_not_marked_unschedulable(self):
        store = KubeStore()
        store.create(build_node("n1", alloc={"cpu": 2}))
        s = make_scheduler(store, gang_timeout=5)
        pod = build_pod("w0", {"cpu": 2}, ns="ml")
        pod.metadata.labels[GANG_NAME_LABEL] = "job"
        pod.metadata.labels[GANG_SIZE_LABEL] = "2"
        sched_pod(s, store, pod)
        # retry reconcile while waiting must not run a full cycle against
        # the pod's own assumed reservation
        s.reconcile(Request(name="w0", namespace="ml"))
        got = store.get("Pod", "w0", "ml")
        assert not got.unschedulable()
        assert got.spec.node_name == ""


class TestVanillaPredicates:
    """Taints/tolerations, required node affinity, and cordon — the in-tree
    predicate subset VERDICT #5 requires in the real scheduler."""

    def test_skips_tainted_node_binds_tolerating_pod(self):
        from nos_tpu.kube.objects import Taint, Toleration

        store = KubeStore()
        tainted = build_node("n-tainted", alloc={"cpu": 8})
        tainted.spec.taints = [Taint(key="dedicated", value="infra", effect="NoSchedule")]
        store.create(tainted)
        s = make_scheduler(store)

        sched_pod(s, store, build_pod("plain", {"cpu": 2}))
        assert store.get("Pod", "plain", "default").spec.node_name == ""

        tolerant = build_pod("tolerant", {"cpu": 2})
        tolerant.spec.tolerations = [
            Toleration(key="dedicated", operator="Equal", value="infra")
        ]
        sched_pod(s, store, tolerant)
        assert store.get("Pod", "tolerant", "default").spec.node_name == "n-tainted"

    def test_prefer_no_schedule_taint_does_not_filter(self):
        from nos_tpu.kube.objects import Taint

        store = KubeStore()
        soft = build_node("n-soft", alloc={"cpu": 8})
        soft.spec.taints = [Taint(key="spot", effect="PreferNoSchedule")]
        store.create(soft)
        s = make_scheduler(store)
        sched_pod(s, store, build_pod("p", {"cpu": 2}))
        assert store.get("Pod", "p", "default").spec.node_name == "n-soft"

    def test_cordoned_node_admits_nothing(self):
        store = KubeStore()
        cordoned = build_node("n-cordoned", alloc={"cpu": 8})
        cordoned.spec.unschedulable = True
        store.create(cordoned)
        free = build_node("n-free", alloc={"cpu": 8})
        store.create(free)
        s = make_scheduler(store)
        sched_pod(s, store, build_pod("p", {"cpu": 2}))
        assert store.get("Pod", "p", "default").spec.node_name == "n-free"

    def test_required_node_affinity(self):
        from nos_tpu.kube.objects import (
            NodeAffinity,
            NodeSelectorRequirement,
            NodeSelectorTerm,
        )

        store = KubeStore()
        gold = build_node("n-gold", alloc={"cpu": 8})
        gold.metadata.labels["pool"] = "gold"
        store.create(gold)
        store.create(build_node("n-plain", alloc={"cpu": 64}))
        s = make_scheduler(store)
        pod = build_pod("p", {"cpu": 2})
        pod.spec.affinity = NodeAffinity(required_terms=[
            NodeSelectorTerm(match_expressions=[
                NodeSelectorRequirement(key="pool", operator="In", values=["gold"])
            ])
        ])
        sched_pod(s, store, pod)
        assert store.get("Pod", "p", "default").spec.node_name == "n-gold"

    def _spread_pod(self, name, zone_key="topology.kubernetes.io/zone"):
        from nos_tpu.kube.objects import TopologySpreadConstraint

        pod = build_pod(name, {"cpu": 1})
        pod.metadata.labels["app"] = "web"
        pod.spec.topology_spread_constraints = [
            TopologySpreadConstraint(
                topology_key=zone_key, max_skew=1, match_labels={"app": "web"}
            )
        ]
        return pod

    def test_topology_spread_prefers_empty_zone(self):
        store = KubeStore()
        for name, zone in (("n-a", "zone-a"), ("n-b", "zone-b")):
            node = build_node(name, alloc={"cpu": 8})
            node.metadata.labels["topology.kubernetes.io/zone"] = zone
            store.create(node)
        # Two replicas already running in zone-a.
        for i in range(2):
            running = build_pod(f"web-{i}", {"cpu": 1}, node="n-a", phase=PodPhase.RUNNING)
            running.metadata.labels["app"] = "web"
            store.create(running)
        s = make_scheduler(store)
        sched_pod(s, store, self._spread_pod("web-new"))
        # zone-a would skew 3-0=3 > 1; only zone-b satisfies the constraint.
        assert store.get("Pod", "web-new", "default").spec.node_name == "n-b"

    def test_topology_spread_unschedulable_when_all_zones_skewed(self):
        store = KubeStore()
        node = build_node("n-a", alloc={"cpu": 8})
        node.metadata.labels["topology.kubernetes.io/zone"] = "zone-a"
        store.create(node)
        # A zone-b domain exists with zero replicas but no capacity, so the
        # only fitting node (zone-a, 2 replicas) violates maxSkew=1.
        full = build_node("n-b", alloc={"cpu": 1})
        full.metadata.labels["topology.kubernetes.io/zone"] = "zone-b"
        store.create(full)
        filler = build_pod("filler", {"cpu": 1}, node="n-b", phase=PodPhase.RUNNING)
        store.create(filler)
        for i in range(2):
            running = build_pod(f"web-{i}", {"cpu": 1}, node="n-a", phase=PodPhase.RUNNING)
            running.metadata.labels["app"] = "web"
            store.create(running)
        s = make_scheduler(store)
        sched_pod(s, store, self._spread_pod("web-new"))
        pod = store.get("Pod", "web-new", "default")
        assert pod.spec.node_name == ""
        assert pod.unschedulable()

    def test_topology_spread_trial_view_overrides_published(self):
        # Preemption hands the filter a trial NodeInfo with victims
        # removed; the trial's counts must win over the published view or
        # eviction could never resolve a skew violation.
        from nos_tpu.kube.objects import TopologySpreadConstraint
        from nos_tpu.scheduler.framework import (
            CycleState,
            NodeInfo,
            PodTopologySpreadFit,
            TOPOLOGY_NODE_INFOS_KEY,
        )

        def zone_node(name, zone):
            node = build_node(name, alloc={"cpu": 8})
            node.metadata.labels["topology.kubernetes.io/zone"] = zone
            return node

        def web_pod(name):
            p = build_pod(name, {"cpu": 1}, phase=PodPhase.RUNNING)
            p.metadata.labels["app"] = "web"
            return p

        published_a = NodeInfo(zone_node("n-a", "zone-a"), [web_pod("w1"), web_pod("w2")])
        published_b = NodeInfo(zone_node("n-b", "zone-b"), [])
        state = CycleState()
        state[TOPOLOGY_NODE_INFOS_KEY] = [published_a, published_b]
        incoming = self._spread_pod("web-new")
        plugin = PodTopologySpreadFit()
        # Published view: zone-a already has 2, zone-b 0 -> n-a violates.
        assert not plugin.filter(state, incoming, published_a).success
        # Trial view of n-a with both victims evicted: skew resolves.
        trial = NodeInfo(published_a.node, [])
        assert plugin.filter(state, incoming, trial).success

    def test_topology_spread_nil_selector_is_noop(self):
        from nos_tpu.kube.objects import TopologySpreadConstraint

        store = KubeStore()
        node = build_node("n-a", alloc={"cpu": 8})
        node.metadata.labels["topology.kubernetes.io/zone"] = "zone-a"
        store.create(node)
        crowded = build_node("n-b", alloc={"cpu": 8})
        crowded.metadata.labels["topology.kubernetes.io/zone"] = "zone-b"
        store.create(crowded)
        for i in range(3):
            store.create(build_pod(f"other-{i}", {"cpu": 1}, node="n-b", phase=PodPhase.RUNNING))
        s = make_scheduler(store)
        pod = build_pod("p", {"cpu": 1})
        pod.spec.topology_spread_constraints = [
            TopologySpreadConstraint(topology_key="topology.kubernetes.io/zone")
        ]
        sched_pod(s, store, pod)
        # Upstream nil-selector matches no pods: the constraint is a no-op
        # and must not reject the (otherwise skewed-looking) zones.
        assert store.get("Pod", "p", "default").spec.node_name != ""

    def test_topology_spread_requires_topology_label(self):
        store = KubeStore()
        unlabeled = build_node("n-bare", alloc={"cpu": 8})
        store.create(unlabeled)
        zoned = build_node("n-zoned", alloc={"cpu": 8})
        zoned.metadata.labels["topology.kubernetes.io/zone"] = "zone-a"
        store.create(zoned)
        s = make_scheduler(store)
        sched_pod(s, store, self._spread_pod("web-new"))
        # Nodes without the topology key cannot host DoNotSchedule spreads.
        assert store.get("Pod", "web-new", "default").spec.node_name == "n-zoned"


class TestSoftScoring:
    def test_prefer_no_schedule_steers_away_when_alternative_exists(self):
        from nos_tpu.kube.objects import Taint

        store = KubeStore()
        soft = build_node("n-soft", alloc={"cpu": 8})
        soft.spec.taints = [Taint(key="spot", effect="PreferNoSchedule")]
        store.create(soft)
        store.create(build_node("n-clean", alloc={"cpu": 8}))
        s = make_scheduler(store)
        sched_pod(s, store, build_pod("p", {"cpu": 2}))
        # both pass the filter; the soft taint demotes n-soft in scoring
        assert store.get("Pod", "p", "default").spec.node_name == "n-clean"

    def test_schedule_anyway_spread_prefers_empty_zone_without_blocking(self):
        from nos_tpu.kube.objects import TopologySpreadConstraint

        store = KubeStore()
        for name, zone in (("n-a", "zone-a"), ("n-b", "zone-b")):
            node = build_node(name, alloc={"cpu": 8})
            node.metadata.labels["topology.kubernetes.io/zone"] = zone
            store.create(node)
        # Crowd zone-b: the scheduler's name tiebreak alone would pick n-b
        # (max on names), so the assertion below only holds when the spread
        # scorer actually demotes the crowded zone.
        for i in range(2):
            running = build_pod(f"web-{i}", {"cpu": 1}, node="n-b", phase=PodPhase.RUNNING)
            running.metadata.labels["app"] = "web"
            store.create(running)
        s = make_scheduler(store)
        pod = build_pod("web-new", {"cpu": 1})
        pod.metadata.labels["app"] = "web"
        pod.spec.topology_spread_constraints = [
            TopologySpreadConstraint(
                topology_key="topology.kubernetes.io/zone",
                max_skew=1,
                when_unsatisfiable="ScheduleAnyway",
                match_labels={"app": "web"},
            )
        ]
        sched_pod(s, store, pod)
        assert store.get("Pod", "web-new", "default").spec.node_name == "n-a"

    def test_schedule_anyway_never_blocks_single_zone(self):
        from nos_tpu.kube.objects import TopologySpreadConstraint

        store = KubeStore()
        node = build_node("n-a", alloc={"cpu": 8})
        node.metadata.labels["topology.kubernetes.io/zone"] = "zone-a"
        store.create(node)
        for i in range(3):
            running = build_pod(f"web-{i}", {"cpu": 1}, node="n-a", phase=PodPhase.RUNNING)
            running.metadata.labels["app"] = "web"
            store.create(running)
        s = make_scheduler(store)
        pod = build_pod("web-new", {"cpu": 1})
        pod.metadata.labels["app"] = "web"
        pod.spec.topology_spread_constraints = [
            TopologySpreadConstraint(
                topology_key="topology.kubernetes.io/zone",
                max_skew=1,
                when_unsatisfiable="ScheduleAnyway",
                match_labels={"app": "web"},
            )
        ]
        sched_pod(s, store, pod)
        # soft constraint: heavily skewed but the only node still binds
        assert store.get("Pod", "web-new", "default").spec.node_name == "n-a"


class TestInterPodAffinity:
    def zone_node(self, store, name, zone, cpu=8):
        node = build_node(name, alloc={"cpu": cpu})
        node.metadata.labels["topology.kubernetes.io/zone"] = zone
        store.create(node)
        return node

    def web_pod(self, name, node, labels_=None):
        pod = build_pod(name, {"cpu": 1}, node=node, phase=PodPhase.RUNNING)
        for k, v in (labels_ or {"app": "web"}).items():
            pod.metadata.labels[k] = v
        return pod

    def test_affinity_co_locates_with_matching_pod(self):
        from nos_tpu.kube.objects import PodAffinityTerm

        store = KubeStore()
        self.zone_node(store, "n-a", "zone-a")
        self.zone_node(store, "n-b", "zone-b")
        store.create(self.web_pod("cache", "n-b", {"app": "cache"}))
        s = make_scheduler(store)
        pod = build_pod("worker", {"cpu": 1})
        pod.spec.pod_affinity = [PodAffinityTerm(
            topology_key="topology.kubernetes.io/zone",
            match_labels={"app": "cache"},
        )]
        sched_pod(s, store, pod)
        assert store.get("Pod", "worker", "default").spec.node_name == "n-b"

    def test_affinity_bootstrap_self_match(self):
        from nos_tpu.kube.objects import PodAffinityTerm

        store = KubeStore()
        self.zone_node(store, "n-a", "zone-a")
        s = make_scheduler(store)
        pod = build_pod("first", {"cpu": 1})
        pod.metadata.labels["app"] = "group"
        pod.spec.pod_affinity = [PodAffinityTerm(
            topology_key="topology.kubernetes.io/zone",
            match_labels={"app": "group"},
        )]
        # no matching pod exists anywhere, but the term matches the
        # incoming pod itself: the first replica must be schedulable
        sched_pod(s, store, pod)
        assert store.get("Pod", "first", "default").spec.node_name == "n-a"

    def test_anti_affinity_spreads_replicas(self):
        from nos_tpu.kube.objects import PodAffinityTerm

        store = KubeStore()
        self.zone_node(store, "n-a", "zone-a")
        self.zone_node(store, "n-b", "zone-b")
        store.create(self.web_pod("web-0", "n-a"))
        s = make_scheduler(store)
        pod = build_pod("web-1", {"cpu": 1})
        pod.metadata.labels["app"] = "web"
        pod.spec.pod_anti_affinity = [PodAffinityTerm(
            topology_key="topology.kubernetes.io/zone",
            match_labels={"app": "web"},
        )]
        sched_pod(s, store, pod)
        assert store.get("Pod", "web-1", "default").spec.node_name == "n-b"

    def test_existing_pods_anti_affinity_is_symmetric(self):
        from nos_tpu.kube.objects import PodAffinityTerm

        store = KubeStore()
        self.zone_node(store, "n-a", "zone-a")
        # the RESIDENT declares anti-affinity against app=web pods; an
        # incoming web pod with NO terms of its own must still be rejected
        # from zone-a (upstream symmetry)
        resident = self.web_pod("landlord", "n-a", {"app": "landlord"})
        resident.spec.pod_anti_affinity = [PodAffinityTerm(
            topology_key="topology.kubernetes.io/zone",
            match_labels={"app": "web"},
        )]
        store.create(resident)
        self.zone_node(store, "n-b", "zone-b")
        s = make_scheduler(store)
        incoming = build_pod("web-new", {"cpu": 1})
        incoming.metadata.labels["app"] = "web"
        sched_pod(s, store, incoming)
        assert store.get("Pod", "web-new", "default").spec.node_name == "n-b"

    def test_match_expressions_terms_enforced(self):
        from nos_tpu.kube.objects import NodeSelectorRequirement, PodAffinityTerm

        store = KubeStore()
        self.zone_node(store, "n-a", "zone-a")
        self.zone_node(store, "n-b", "zone-b")
        store.create(self.web_pod("web-0", "n-a"))
        s = make_scheduler(store)
        pod = build_pod("web-1", {"cpu": 1})
        pod.metadata.labels["app"] = "web"
        # matchExpressions-only selector (operator In) — previously dropped
        # at ingest; must spread like the matchLabels equivalent
        pod.spec.pod_anti_affinity = [PodAffinityTerm(
            topology_key="topology.kubernetes.io/zone",
            match_expressions=[NodeSelectorRequirement(
                key="app", operator="In", values=["web"],
            )],
        )]
        sched_pod(s, store, pod)
        assert store.get("Pod", "web-1", "default").spec.node_name == "n-b"

    def test_namespace_scoping_defaults_to_own_namespace(self):
        from nos_tpu.kube.objects import PodAffinityTerm

        store = KubeStore()
        # n-a is the ONLY node: if the foreign-namespace pod wrongly
        # triggered the anti-affinity, web-1 would be unschedulable — the
        # bind below can only happen when namespace scoping works.
        self.zone_node(store, "n-a", "zone-a")
        foreign = build_pod("web-0", {"cpu": 1}, ns="other", node="n-a",
                            phase=PodPhase.RUNNING)
        foreign.metadata.labels["app"] = "web"
        store.create(foreign)
        s = make_scheduler(store)
        pod = build_pod("web-1", {"cpu": 1})  # ns=default
        pod.metadata.labels["app"] = "web"
        pod.spec.pod_anti_affinity = [PodAffinityTerm(
            topology_key="topology.kubernetes.io/zone",
            match_labels={"app": "web"},
        )]
        sched_pod(s, store, pod)
        assert store.get("Pod", "web-1", "default").spec.node_name == "n-a"


class TestSchedulerNameCoexistence:
    """The nos scheduler only claims pods that opt in via
    spec.schedulerName (reference cmd/scheduler/scheduler.go:43-59: the
    nos profile is one kube-scheduler profile, selected per pod) —
    deployed beside the default scheduler it must never double-bind."""

    def make_named_scheduler(self, store):
        fw, capacity, gang = new_framework(store, gang_timeout_seconds=0.3)
        return Scheduler(
            store, fw, capacity=capacity, gang=gang, retry_seconds=0.05,
            scheduler_name=constants.SCHEDULER_NAME,
        )

    def test_ignores_default_scheduler_pods(self):
        store = KubeStore()
        store.create(build_node("n1", alloc={"cpu": 4}))
        s = self.make_named_scheduler(store)
        result = sched_pod(
            s, store, build_pod("foreign", {"cpu": 1}, scheduler="default-scheduler")
        )
        pod = store.get("Pod", "foreign", "default")
        assert pod.spec.node_name == ""          # left for the default scheduler
        assert not pod.unschedulable()           # and not marked by us either
        assert result is None                    # no retry churn on foreign pods

    def test_schedules_opted_in_pods(self):
        store = KubeStore()
        store.create(build_node("n1", alloc={"cpu": 4}))
        s = self.make_named_scheduler(store)
        sched_pod(s, store, build_pod("ours", {"cpu": 1}))  # factory default opts in
        assert store.get("Pod", "ours", "default").spec.node_name == "n1"

    def test_coexists_with_competing_default_scheduler(self):
        """A simulated default scheduler binds its own pods concurrently;
        capacity accounting on both sides stays consistent and no pod is
        bound twice."""
        store = KubeStore()
        store.create(build_node("n1", alloc={"cpu": 4}))
        nos = self.make_named_scheduler(store)

        # Competitor: a second (unfiltered-by-name) scheduler playing the
        # default one — it claims only default-scheduler pods.
        competitor = Scheduler(
            store, new_framework(store, gang_timeout_seconds=0.3)[0],
            retry_seconds=0.05, scheduler_name="default-scheduler",
        )

        ours = build_pod("ours", {"cpu": 2})
        theirs = build_pod("theirs", {"cpu": 2}, scheduler="default-scheduler")
        store.create(ours)
        store.create(theirs)

        # Each scheduler sweeps every pending pod (as its informer would).
        for s in (nos, competitor, nos, competitor):
            for p in list(store.list("Pod")):
                if p.status.phase == PodPhase.PENDING and not p.spec.node_name:
                    s.reconcile(Request(name=p.metadata.name,
                                        namespace=p.metadata.namespace))

        assert store.get("Pod", "ours", "default").spec.node_name == "n1"
        assert store.get("Pod", "theirs", "default").spec.node_name == "n1"
        # Node holds 4 cpu, both 2-cpu pods fit exactly — a double-bind or
        # shared-capacity miscount would have left one unschedulable.
        third = build_pod("overflow", {"cpu": 1})
        sched_pod(nos, store, third)
        assert store.get("Pod", "overflow", "default").unschedulable()
