"""LoRA adapters: zero-init identity, merge/attach parity, frozen base,
and composition with the serving stack."""
import jax
import jax.numpy as jnp
import numpy as np
import pytest

from nos_tpu.models.generate import generate
from nos_tpu.models.llama import init_llama_params, llama_forward, tiny_config
from nos_tpu.models.lora import (
    LoraConfig,
    attach_lora,
    init_lora_params,
    make_lora_train_step,
    merge_lora,
)


@pytest.fixture(scope="module")
def setup():
    config = tiny_config()
    params = init_llama_params(jax.random.key(0), config)
    lora = LoraConfig(rank=4, alpha=8.0)
    adapters = init_lora_params(jax.random.key(1), config, lora)
    tokens = jax.random.randint(jax.random.key(2), (2, 12), 0, config.vocab_size)
    return config, params, lora, adapters, tokens


class TestLora:
    def test_zero_init_is_identity(self, setup):
        config, params, lora, adapters, tokens = setup
        base = llama_forward(params, tokens, config)
        adapted = llama_forward(attach_lora(params, adapters, lora), tokens, config)
        np.testing.assert_array_equal(np.asarray(base), np.asarray(adapted))

    def test_merge_matches_attach(self, setup):
        config, params, lora, adapters, tokens = setup
        # give the adapters real content
        trained = jax.tree.map(
            lambda x: x + 0.01 * jax.random.normal(jax.random.key(3), x.shape, x.dtype),
            adapters,
        )
        attached = llama_forward(attach_lora(params, trained, lora), tokens, config)
        merged = llama_forward(merge_lora(params, trained, lora), tokens, config)
        np.testing.assert_allclose(
            np.asarray(attached), np.asarray(merged), atol=5e-2, rtol=5e-2
        )

    def test_training_updates_only_adapters(self, setup):
        from nos_tpu.parallel.mesh import mesh_from_devices
        from nos_tpu.parallel.sharding import llama_param_sharding

        config, params, lora, adapters, tokens = setup
        mesh = mesh_from_devices((2, 2), ("dp", "tp"), jax.devices()[:4])
        step, shard = make_lora_train_step(mesh, config, lora, learning_rate=3e-3)
        base = jax.device_put(params, llama_param_sharding(mesh, config))
        base_before = np.asarray(base["layers"][0]["wq"]).copy()
        state = shard(adapters)
        losses = []
        for _ in range(5):
            state, loss = step(state, base, tokens)
            losses.append(float(loss))
        assert losses[-1] < losses[0], losses
        # the base never moved; the adapters did
        np.testing.assert_array_equal(
            np.asarray(base["layers"][0]["wq"]), base_before
        )
        b = np.asarray(state[0]["layers"][0]["wq"]["b"])
        assert np.abs(b).max() > 0

    def test_trainable_fraction_is_tiny(self, setup):
        config, params, lora, adapters, _ = setup
        n_base = sum(x.size for x in jax.tree.leaves(params))
        n_lora = sum(x.size for x in jax.tree.leaves(adapters))
        assert n_lora < 0.1 * n_base

    def test_merged_model_composes_with_serving_stack(self, setup):
        from nos_tpu.models.quantize import quantize_params

        config, params, lora, adapters, _ = setup
        merged = merge_lora(params, adapters, lora)
        prompt = jnp.asarray([[1, 2, 3, 4]], jnp.int32)
        out = generate(quantize_params(merged), prompt, config, max_new_tokens=4)
        assert out.shape == (1, 4)

    def test_adapted_generation_runs_unmerged(self, setup):
        config, params, lora, adapters, _ = setup
        adapted = attach_lora(params, adapters, lora)
        prompt = jnp.asarray([[1, 2, 3, 4]], jnp.int32)
        want = generate(params, prompt, config, max_new_tokens=4)
        got = generate(adapted, prompt, config, max_new_tokens=4)
        # zero adapters: the cache path through LoraLinear is the base model
        np.testing.assert_array_equal(np.asarray(want), np.asarray(got))

    def test_trained_adapters_apply_through_cached_generation(self, setup):
        """Non-vacuous adapter coverage of the KV-cache decode path: a
        NONZERO delta served unmerged must equal the merged-dense serve —
        if generate's projections stopped routing through _mm (or the
        delta term dropped), these would silently diverge."""
        config, params, lora, adapters, _ = setup
        trained = jax.tree.map(
            lambda x: x + 0.05 * jax.random.normal(jax.random.key(8), x.shape, x.dtype),
            adapters,
        )
        prompt = jnp.asarray([[5, 6, 7, 8, 9]], jnp.int32)
        unmerged = generate(attach_lora(params, trained, lora), prompt, config,
                            max_new_tokens=6)
        merged = generate(merge_lora(params, trained, lora), prompt, config,
                          max_new_tokens=6)
        np.testing.assert_array_equal(np.asarray(unmerged), np.asarray(merged))
        # and the delta actually changes behavior vs the base
        base = generate(params, prompt, config, max_new_tokens=6)
        assert not np.array_equal(np.asarray(base), np.asarray(unmerged))

    def test_unknown_target_rejected(self, setup):
        config, params, _, _, _ = setup
        with pytest.raises(ValueError):
            init_lora_params(
                jax.random.key(0), config, LoraConfig(targets=("embed",))
            )

    def test_mismatched_layer_counts_rejected(self, setup):
        config, params, lora, _, _ = setup
        small = init_lora_params(
            jax.random.key(0), tiny_config(n_layers=1), lora
        )
        with pytest.raises(ValueError):
            attach_lora(params, small, lora)
        with pytest.raises(ValueError):
            merge_lora(params, small, lora)

    def test_adapters_stay_float32(self, setup):
        config, _, lora, adapters, _ = setup
        ab = adapters["layers"][0]["wq"]
        assert ab["a"].dtype == jnp.float32 and ab["b"].dtype == jnp.float32
