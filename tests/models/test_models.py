import jax
import jax.numpy as jnp
import pytest

from nos_tpu.models.llama import (
    init_llama_params,
    llama_forward,
    tiny_config,
)
from nos_tpu.models.resnet import (
    init_resnet_params,
    resnet_forward,
    tiny_resnet_config,
)
from nos_tpu.parallel.mesh import mesh_for_slice, mesh_from_devices
from nos_tpu.parallel.train import make_train_step


class TestLlama:
    def test_forward_shapes_and_dtype(self):
        config = tiny_config()
        params = init_llama_params(jax.random.key(0), config)
        tokens = jnp.zeros((2, 8), jnp.int32)
        logits = jax.jit(lambda p, t: llama_forward(p, t, config))(params, tokens)
        assert logits.shape == (2, 8, config.vocab_size)
        assert logits.dtype == jnp.float32

    def test_causality(self):
        # Changing a future token must not change past logits.
        config = tiny_config()
        params = init_llama_params(jax.random.key(0), config)
        a = jnp.array([[1, 2, 3, 4]], jnp.int32)
        b = jnp.array([[1, 2, 3, 9]], jnp.int32)
        la = llama_forward(params, a, config)
        lb = llama_forward(params, b, config)
        assert jnp.allclose(la[:, :3], lb[:, :3], atol=1e-5)
        assert not jnp.allclose(la[:, 3], lb[:, 3], atol=1e-5)

    def test_loss_decreases_under_training(self):
        config = tiny_config()
        params = init_llama_params(jax.random.key(1), config)
        mesh = mesh_from_devices((1, 1), ("dp", "tp"), jax.devices()[:1])
        train_step, shard_state = make_train_step(mesh, config, learning_rate=0.1)
        # state buffers are donated each step: thread them, never reuse.
        state = shard_state(params)
        tokens = jax.random.randint(jax.random.key(2), (4, 16), 0, config.vocab_size)
        losses = []
        for _ in range(12):
            state, loss = train_step(state, tokens)
            losses.append(float(loss))
        assert losses[-1] < losses[0]


class TestShardedTraining:
    def test_dp_tp_mesh_step(self):
        config = tiny_config()
        params = init_llama_params(jax.random.key(0), config)
        mesh = mesh_from_devices((4, 2), ("dp", "tp"))
        train_step, shard_state = make_train_step(mesh, config)
        state = shard_state(params)
        tokens = jnp.zeros((8, 16), jnp.int32)
        state, loss = train_step(state, tokens)
        assert jnp.isfinite(loss)

    def test_sharded_matches_single_device(self):
        config = tiny_config()
        tokens = jax.random.randint(jax.random.key(1), (8, 16), 0, config.vocab_size)

        # Fresh (deterministic) params per mesh: step donation consumes them.
        mesh1 = mesh_from_devices((1, 1), ("dp", "tp"), jax.devices()[:1])
        step1, shard1 = make_train_step(mesh1, config)
        _, loss1 = step1(shard1(init_llama_params(jax.random.key(0), config)), tokens)

        mesh8 = mesh_from_devices((4, 2), ("dp", "tp"))
        step8, shard8 = make_train_step(mesh8, config)
        _, loss8 = step8(shard8(init_llama_params(jax.random.key(0), config)), tokens)
        assert abs(float(loss1) - float(loss8)) < 2e-2

    def test_mesh_for_slice(self):
        mesh = mesh_for_slice("2x4")
        assert mesh.shape == {"dp": 2, "tp": 4}
        mesh = mesh_for_slice("2x4", dp=4)
        assert mesh.shape == {"dp": 4, "tp": 2}
        with pytest.raises(ValueError):
            mesh_for_slice("2x4", dp=3)


class TestResNet:
    def test_forward(self):
        config = tiny_resnet_config()
        params = init_resnet_params(jax.random.key(0), config)
        images = jnp.zeros((2, 32, 32, 3), jnp.float32)
        logits = jax.jit(lambda p, x: resnet_forward(p, x, config))(params, images)
        assert logits.shape == (2, config.num_classes)
        assert bool(jnp.all(jnp.isfinite(logits)))


class TestGraftEntry:
    def test_entry_compiles(self):
        import __graft_entry__ as graft

        fn, args = graft.entry()
        out = jax.jit(fn)(*args)
        assert out.shape[0] == 2

    def test_dryrun_multichip(self):
        import __graft_entry__ as graft

        graft.dryrun_multichip(8)
