"""int8 weight-only serving: parity against the fake-quant oracle, byte
budget, and the full KV-cache generation path running quantized."""
import jax
import jax.numpy as jnp
import numpy as np

from nos_tpu.models.generate import generate, prefill
from nos_tpu.models.llama import (
    init_llama_params,
    llama_forward,
    tiny_config,
)
from nos_tpu.models.quantize import (
    QuantizedEmbedding,
    QuantizedLinear,
    dequantize_params,
    quantize_params,
    weight_bytes,
)


def setup_module(module):
    module.config = tiny_config()
    module.params = init_llama_params(jax.random.key(0), module.config)
    module.qparams = quantize_params(module.params)


class TestQuantization:
    def test_leaf_types_and_dtypes(self):
        assert isinstance(qparams["embed"], QuantizedEmbedding)
        assert isinstance(qparams["lm_head"], QuantizedLinear)
        layer = qparams["layers"][0]
        for key in ("wq", "wk", "wv", "wo", "w_gate", "w_up", "w_down"):
            assert isinstance(layer[key], QuantizedLinear), key
            assert layer[key].q.dtype == jnp.int8
        # norms stay dense
        assert layer["attn_norm"].dtype == config.dtype

    def test_weight_bytes_shrink(self):
        # bf16 -> int8 + f32 scales: close to half; well under 0.6.
        assert weight_bytes(qparams) < 0.6 * weight_bytes(params)

    def test_forward_matches_fake_quant_oracle(self):
        tokens = jax.random.randint(jax.random.key(1), (2, 16), 0, config.vocab_size)
        got = llama_forward(qparams, tokens, config)
        oracle = llama_forward(dequantize_params(params=quantize_params(params)), tokens, config)
        np.testing.assert_allclose(np.asarray(got), np.asarray(oracle), atol=0.15, rtol=0.05)

    def test_forward_close_to_full_precision(self):
        tokens = jax.random.randint(jax.random.key(2), (2, 16), 0, config.vocab_size)
        full = np.asarray(llama_forward(params, tokens, config))
        quant = np.asarray(llama_forward(qparams, tokens, config))
        # int8 noise is small relative to the logit scale
        corr = np.corrcoef(full.ravel(), quant.ravel())[0, 1]
        assert corr > 0.999, corr

    def test_roundtrip_dequantize_requantize_fixed_point(self):
        # quantize(dequantize(quantize(w))) == quantize(w): rounding has
        # converged after one trip, so serving artifacts are stable.
        q1 = quantize_params(params)
        q2 = quantize_params(dequantize_params(q1))
        a = q1["layers"][0]["wq"]
        b = q2["layers"][0]["wq"]
        np.testing.assert_array_equal(np.asarray(a.q), np.asarray(b.q))


class TestQuantizedGeneration:
    def test_kv_generate_runs_and_matches_quantized_prefill(self):
        prompt = jax.random.randint(jax.random.key(3), (2, 8), 0, config.vocab_size)
        out = generate(qparams, prompt, config, max_new_tokens=6)
        assert out.shape == (2, 6)
        # greedy first token == argmax of the quantized prefill logits
        logits, _ = prefill(qparams, prompt, config, max_len=8)
        first = jnp.argmax(logits[:, -1], axis=-1)
        np.testing.assert_array_equal(np.asarray(out[:, 0]), np.asarray(first))

    def test_left_padded_quantized_generation(self):
        pad = 0
        prompt = jnp.array([[pad, pad, 5, 6], [1, 2, 3, 4]], jnp.int32)
        out = generate(qparams, prompt, config, max_new_tokens=4, pad_id=pad)
        assert out.shape == (2, 4)

    def test_greedy_tokens_mostly_agree_with_full_precision(self):
        prompt = jax.random.randint(jax.random.key(4), (4, 8), 0, config.vocab_size)
        full = np.asarray(generate(params, prompt, config, max_new_tokens=8))
        quant = np.asarray(generate(qparams, prompt, config, max_new_tokens=8))
        agreement = (full == quant).mean()
        # random tiny models have near-uniform logits (worst case for
        # argmax stability); real checkpoints agree far more
        assert agreement >= 0.5, agreement
