"""int8 weight-only serving: parity against the fake-quant oracle, byte
budget, and the full KV-cache generation path running quantized."""
import jax
import jax.numpy as jnp
import numpy as np

from nos_tpu.models.generate import generate, prefill
from nos_tpu.models.llama import (
    init_llama_params,
    llama_forward,
    tiny_config,
)
from nos_tpu.models.quantize import (
    QuantizedEmbedding,
    QuantizedLinear,
    dequantize_params,
    quantize_params,
    weight_bytes,
)


def setup_module(module):
    module.config = tiny_config()
    module.params = init_llama_params(jax.random.key(0), module.config)
    module.qparams = quantize_params(module.params)


class TestQuantization:
    def test_leaf_types_and_dtypes(self):
        assert isinstance(qparams["embed"], QuantizedEmbedding)
        assert isinstance(qparams["lm_head"], QuantizedLinear)
        layer = qparams["layers"][0]
        for key in ("wq", "wk", "wv", "wo", "w_gate", "w_up", "w_down"):
            assert isinstance(layer[key], QuantizedLinear), key
            assert layer[key].q.dtype == jnp.int8
        # norms stay dense
        assert layer["attn_norm"].dtype == config.dtype

    def test_weight_bytes_shrink(self):
        # bf16 -> int8 + f32 scales: close to half; well under 0.6.
        assert weight_bytes(qparams) < 0.6 * weight_bytes(params)

    def test_forward_matches_fake_quant_oracle(self):
        tokens = jax.random.randint(jax.random.key(1), (2, 16), 0, config.vocab_size)
        got = llama_forward(qparams, tokens, config)
        oracle = llama_forward(dequantize_params(params=quantize_params(params)), tokens, config)
        np.testing.assert_allclose(np.asarray(got), np.asarray(oracle), atol=0.15, rtol=0.05)

    def test_forward_close_to_full_precision(self):
        tokens = jax.random.randint(jax.random.key(2), (2, 16), 0, config.vocab_size)
        full = np.asarray(llama_forward(params, tokens, config))
        quant = np.asarray(llama_forward(qparams, tokens, config))
        # int8 noise is small relative to the logit scale
        corr = np.corrcoef(full.ravel(), quant.ravel())[0, 1]
        assert corr > 0.999, corr

    def test_roundtrip_dequantize_requantize_fixed_point(self):
        # quantize(dequantize(quantize(w))) == quantize(w): rounding has
        # converged after one trip, so serving artifacts are stable.
        q1 = quantize_params(params)
        q2 = quantize_params(dequantize_params(q1))
        a = q1["layers"][0]["wq"]
        b = q2["layers"][0]["wq"]
        np.testing.assert_array_equal(np.asarray(a.q), np.asarray(b.q))


class TestQuantizedGeneration:
    def test_kv_generate_runs_and_matches_quantized_prefill(self):
        prompt = jax.random.randint(jax.random.key(3), (2, 8), 0, config.vocab_size)
        out = generate(qparams, prompt, config, max_new_tokens=6)
        assert out.shape == (2, 6)
        # greedy first token == argmax of the quantized prefill logits
        logits, _ = prefill(qparams, prompt, config, max_len=8)
        first = jnp.argmax(logits[:, -1], axis=-1)
        np.testing.assert_array_equal(np.asarray(out[:, 0]), np.asarray(first))

    def test_left_padded_quantized_generation(self):
        pad = 0
        prompt = jnp.array([[pad, pad, 5, 6], [1, 2, 3, 4]], jnp.int32)
        out = generate(qparams, prompt, config, max_new_tokens=4, pad_id=pad)
        assert out.shape == (2, 4)

    def test_greedy_tokens_mostly_agree_with_full_precision(self):
        prompt = jax.random.randint(jax.random.key(4), (4, 8), 0, config.vocab_size)
        full = np.asarray(generate(params, prompt, config, max_new_tokens=8))
        quant = np.asarray(generate(qparams, prompt, config, max_new_tokens=8))
        agreement = (full == quant).mean()
        # random tiny models have near-uniform logits (worst case for
        # argmax stability); real checkpoints agree far more
        assert agreement >= 0.5, agreement


class TestMoeQuantization:
    """Parity is asserted at the moe_mlp level with IDENTICAL inputs: in a
    full multi-layer forward, upstream bf16 rounding differences flip
    near-tie top-k routing decisions, sending a few tokens to different
    experts — a routing discontinuity, not a quantization error. With the
    same input x, the f32 router is bit-identical on both sides and the
    comparison isolates the quantized expert-matmul path."""

    @staticmethod
    def _moe_setup():
        from nos_tpu.models.moe import init_moe_params

        moe_config = tiny_config(n_experts=4, moe_top_k=2).moe_config()
        moe_params = init_moe_params(jax.random.key(5), moe_config)
        x = jax.random.normal(jax.random.key(6), (2, 16, moe_config.d_model), jnp.bfloat16)
        return moe_config, moe_params, x

    @staticmethod
    def _quantize_moe(moe_params):
        from nos_tpu.models.quantize import quantize_expert_stack

        return {
            "router": moe_params["router"],
            "w_gate": quantize_expert_stack(moe_params["w_gate"]),
            "w_up": quantize_expert_stack(moe_params["w_up"]),
            "w_down": quantize_expert_stack(moe_params["w_down"]),
        }

    def test_full_tree_quantizes_expert_stacks(self):
        from nos_tpu.models.quantize import QuantizedExpertStack

        moe_config = tiny_config(n_experts=4, moe_top_k=2)
        q = quantize_params(init_llama_params(jax.random.key(5), moe_config))
        moe = q["layers"][0]["moe"]
        assert isinstance(moe["w_gate"], QuantizedExpertStack)
        assert moe["w_gate"].q.dtype == jnp.int8
        assert moe["router"].dtype == jnp.float32  # routing stays f32
        # the quantized tree still runs end to end
        tokens = jax.random.randint(jax.random.key(6), (2, 8), 0, moe_config.vocab_size)
        out = llama_forward(q, tokens, moe_config)
        assert np.isfinite(np.asarray(out)).all()

    def test_moe_mlp_matches_fake_quant_oracle(self):
        from nos_tpu.models.moe import moe_mlp

        moe_config, moe_params, x = self._moe_setup()
        q = self._quantize_moe(moe_params)
        got = moe_mlp(q, x, moe_config)
        oracle = moe_mlp(dequantize_params(self._quantize_moe(moe_params)), x, moe_config)
        np.testing.assert_allclose(
            np.asarray(got, np.float32), np.asarray(oracle, np.float32),
            atol=0.05, rtol=0.05,
        )

    def test_moe_mlp_quantized_sharded_matches_unsharded(self):
        from nos_tpu.models.moe import moe_mlp
        from nos_tpu.parallel.mesh import mesh_from_devices
        from nos_tpu.parallel.sharding import llama_quantized_sharding

        moe_config, moe_params, x = self._moe_setup()
        q = self._quantize_moe(moe_params)
        want = moe_mlp(q, x, moe_config)
        llama_cfg = tiny_config(n_experts=4, moe_top_k=2)
        mesh = mesh_from_devices((2, 2), ("dp", "ep"), jax.devices()[:4])
        sharding = llama_quantized_sharding(mesh, llama_cfg)["layers"][0]["moe"]
        sharded = jax.device_put(q, sharding)
        got = jax.jit(lambda p, a: moe_mlp(p, a, moe_config, mesh))(sharded, x)
        np.testing.assert_allclose(
            np.asarray(want, np.float32), np.asarray(got, np.float32),
            atol=2e-2, rtol=2e-2,
        )


class TestInt4:
    """int4 group-wise weight quantization: packing round-trip, byte
    budget, fake-quant oracle parity, and end-to-end serving."""

    def test_pack_unpack_roundtrip(self):
        from nos_tpu.models.quantize import quantize_linear4

        w = jax.random.normal(jax.random.key(0), (64, 32), jnp.float32)
        q = quantize_linear4(w, group=16)
        assert q.q.shape == (4, 8, 32) and q.q.dtype == jnp.uint8
        assert q.scale.shape == (4, 32)
        deq = q._dequant(jnp.float32)
        # 4-bit absmax per group of 16: worst-case step is absmax/7
        err = jnp.abs(deq - w)
        bound = jnp.repeat(q.scale, q.group, axis=0) * 0.5 + 1e-6
        assert bool(jnp.all(err <= bound)), float((err - bound).max())

    def test_matmul_matches_dequant_oracle(self):
        from nos_tpu.models.quantize import quantize_linear4

        w = jax.random.normal(jax.random.key(1), (64, 48), jnp.float32)
        x = jax.random.normal(jax.random.key(2), (4, 64), jnp.float32)
        q = quantize_linear4(w, group=32)
        got = q.matmul(x)
        want = x @ q._dequant(jnp.float32)
        assert jnp.allclose(got, want, atol=1e-5)

    def test_weight_bytes_quarter_of_bf16(self):
        from nos_tpu.models.quantize import quantize_params_int4

        config = tiny_config()
        params = init_llama_params(jax.random.key(0), config)
        q4 = quantize_params_int4(params, group=32)
        lin = q4["layers"][0]["wq"]
        dense_bytes = config.d_model * config.d_model * 2  # bf16 wq
        packed = lin.q.size * 1 + lin.scale.size * 4
        assert packed < dense_bytes * 0.6  # nibbles + group scales

    def test_int4_generation_matches_fake_quant_oracle(self):
        from nos_tpu.models.generate import generate
        from nos_tpu.models.quantize import dequantize_params, quantize_params_int4

        config = tiny_config(dtype=jnp.float32)
        params = init_llama_params(jax.random.key(0), config)
        q4 = quantize_params_int4(params, group=16)
        prompt = jnp.asarray([[3, 7, 11, 2]], jnp.int32)
        got = generate(q4, prompt, config, max_new_tokens=6)
        oracle = generate(
            dequantize_params(q4, jnp.float32), prompt, config, max_new_tokens=6
        )
        assert jnp.array_equal(got, oracle)

    def test_int4_tied_gemma_serves(self):
        from nos_tpu.models.generate import generate
        from nos_tpu.models.quantize import quantize_params_int4

        config = tiny_config(
            dtype=jnp.float32, hidden_act="gelu", norm_offset=True,
            scale_embeddings=True, tie_embeddings=True,
        )
        params = init_llama_params(jax.random.key(0), config)
        q4 = quantize_params_int4(params, group=16)
        assert "lm_head" not in q4
        out = generate(q4, jnp.asarray([[3, 7]], jnp.int32), config, max_new_tokens=4)
        assert out.shape == (1, 4)
