"""Continuous-batching engine vs the per-request oracle.

The contract: whatever mix of prompt lengths, budgets, and arrival times
share the slots, every request's greedy tokens equal a solo generate()
run — batching and slot reuse must be invisible to each tenant.
"""
import jax
import jax.numpy as jnp
import numpy as np
import pytest

from nos_tpu.models.generate import generate
from nos_tpu.models.llama import init_llama_params, tiny_config
from nos_tpu.serve import Engine, GenRequest


@pytest.fixture(scope="module")
def setup():
    config = tiny_config()
    params = init_llama_params(jax.random.key(0), config)
    return config, params


def solo(params, config, prompt, n):
    row = jnp.asarray([prompt], jnp.int32)
    return np.asarray(generate(params, row, config, max_new_tokens=n))[0].tolist()


def rand_prompt(key, n, vocab):
    return np.asarray(
        jax.random.randint(key, (n,), 1, vocab)
    ).tolist()


class TestEngineParity:
    def test_mixed_lengths_match_solo_generation(self, setup):
        config, params = setup
        eng = Engine(params, config, max_slots=3, max_len=64)
        prompts = [
            rand_prompt(jax.random.key(i), n, config.vocab_size)
            for i, n in enumerate((5, 11, 3, 17, 8))
        ]
        ids = [eng.submit(GenRequest(prompt=p, max_new_tokens=6)) for p in prompts]
        results = eng.run()
        for rid, p in zip(ids, prompts):
            assert results[rid] == solo(params, config, p, 6), f"request {rid}"

    def test_slot_reuse_and_staggered_arrivals(self, setup):
        config, params = setup
        eng = Engine(params, config, max_slots=2, max_len=64)
        p1 = rand_prompt(jax.random.key(10), 4, config.vocab_size)
        p2 = rand_prompt(jax.random.key(11), 9, config.vocab_size)
        id1 = eng.submit(GenRequest(prompt=p1, max_new_tokens=3))
        id2 = eng.submit(GenRequest(prompt=p2, max_new_tokens=10))
        # let the short request finish and free its slot mid-flight
        for _ in range(5):
            eng.step()
        p3 = rand_prompt(jax.random.key(12), 6, config.vocab_size)
        id3 = eng.submit(GenRequest(prompt=p3, max_new_tokens=4))
        results = eng.run()
        assert results[id1] == solo(params, config, p1, 3)
        assert results[id2] == solo(params, config, p2, 10)
        assert results[id3] == solo(params, config, p3, 4)

    def test_more_requests_than_slots_all_complete(self, setup):
        config, params = setup
        eng = Engine(params, config, max_slots=2, max_len=64)
        reqs = {
            eng.submit(GenRequest(
                prompt=rand_prompt(jax.random.key(20 + i), 3 + i, config.vocab_size),
                max_new_tokens=4,
            )): None
            for i in range(6)
        }
        results = eng.run()
        assert set(results) == set(reqs)
        assert all(len(t) == 4 for t in results.values())

    def test_eos_frees_slot_early(self, setup):
        config, params = setup
        p = rand_prompt(jax.random.key(30), 6, config.vocab_size)
        free = solo(params, config, p, 8)
        eos = free[2]  # third emitted token
        eng = Engine(params, config, max_slots=1, max_len=64)
        rid = eng.submit(GenRequest(prompt=p, max_new_tokens=8, eos_id=eos))
        results = eng.run()
        assert results[rid] == free[:3]  # stops AT the eos token

    def test_oversized_request_rejected(self, setup):
        config, params = setup
        eng = Engine(params, config, max_slots=1, max_len=32)
        with pytest.raises(ValueError):
            eng.submit(GenRequest(prompt=[1] * 20, max_new_tokens=20))
        # over-long prompt must be rejected at submit, not crash mid-run
        # (the bucket clamp would otherwise wave it through)
        with pytest.raises(ValueError):
            eng.submit(GenRequest(prompt=[1] * 40, max_new_tokens=1))
        # degenerate requests fail loudly at submit, not mid-batch
        with pytest.raises(ValueError):
            eng.submit(GenRequest(prompt=[], max_new_tokens=4))
        with pytest.raises(ValueError):
            eng.submit(GenRequest(prompt=[1, 2], max_new_tokens=0))

    def test_chunked_prefill_matches_solo(self, setup):
        """Long prompts admit via fixed-size decode_chunk pieces (no
        one-shot prefill, no left pad); tokens must still be identical to
        solo generation. Lengths cover mid-chunk, exact-multiple, and
        shorter-than-chunk (which takes the padded prefill path)."""
        config, params = setup
        eng = Engine(params, config, max_slots=2, max_len=64, prefill_chunk=8)
        prompts = [
            rand_prompt(jax.random.key(50 + i), n, config.vocab_size)
            for i, n in enumerate((10, 16, 21, 5))
        ]
        ids = [eng.submit(GenRequest(prompt=p, max_new_tokens=5)) for p in prompts]
        results = eng.run()
        for rid, p in zip(ids, prompts):
            assert results[rid] == solo(params, config, p, 5), f"request {rid}"

    def test_long_prompt_capacity_uses_raw_length_not_bucket(self, setup):
        """A prompt past max_len/2 must still admit on the chunked path:
        its frontier is the raw length, not the power-of-two bucket."""
        config, params = setup
        eng = Engine(params, config, max_slots=1, max_len=64, prefill_chunk=8,
                     ticks_per_sync=4)
        p = rand_prompt(jax.random.key(60), 40, config.vocab_size)  # bucket=64
        rid = eng.submit(GenRequest(prompt=p, max_new_tokens=5))  # 40+8 <= 64
        results = eng.run()
        assert results[rid] == solo(params, config, p, 5)

    def test_quantized_engine_runs(self, setup):
        from nos_tpu.models.quantize import quantize_params

        config, params = setup
        eng = Engine(quantize_params(params), config, max_slots=2, max_len=64)
        rid = eng.submit(GenRequest(
            prompt=rand_prompt(jax.random.key(40), 5, config.vocab_size),
            max_new_tokens=4,
        ))
        results = eng.run()
        assert len(results[rid]) == 4


class TestChunkChaining:
    """run() chains decode chunks between host syncs (_sync_horizon);
    chained dispatch must be invisible: same tokens as stepping one
    chunk at a time, mixed budgets and early EOS included."""

    def test_chained_run_matches_single_chunk_stepping(self, setup):
        config, params = setup

        def submit_all(eng):
            ids = []
            for i, (n, budget) in enumerate(((5, 9), (11, 3), (7, 6), (4, 12))):
                p = rand_prompt(jax.random.key(40 + i), n, config.vocab_size)
                ids.append(eng.submit(GenRequest(prompt=p, max_new_tokens=budget)))
            return ids

        chained = Engine(params, config, max_slots=2, max_len=64,
                         ticks_per_sync=2)
        ids_a = submit_all(chained)
        got_a = chained.run()

        stepped = Engine(params, config, max_slots=2, max_len=64,
                         ticks_per_sync=2)
        ids_b = submit_all(stepped)
        while stepped._queue or any(s is not None for s in stepped._slots):
            stepped.step(chunks=1)
        got_b = {c.id: c.tokens for c in stepped._done}
        assert [got_a[i] for i in ids_a] == [got_b[i] for i in ids_b]

    def test_eos_mid_horizon_rides_then_trims(self, setup):
        config, params = setup
        p = rand_prompt(jax.random.key(50), 6, config.vocab_size)
        # Oracle is the ENGINE's own eos-free stream (not solo generate:
        # the tiny random model has near-tie logits where one bf16 ulp
        # of scan-fusion difference flips an argmax on some backends —
        # the contract under test is trimming, not tie-breaking).
        ref = Engine(params, config, max_slots=1, max_len=64,
                     ticks_per_sync=2)
        rid0 = ref.submit(GenRequest(prompt=p, max_new_tokens=12))
        free = ref.run()[rid0]
        # eos must not already occur earlier in the stream, or the
        # engine legitimately stops sooner and the expectation is wrong
        cut = next(i for i in range(2, 12) if free[i] not in free[:i])
        eos = free[cut]
        # queue empty -> horizon spans several chunks; the EOS finishes
        # the request mid-horizon and the surplus ticks must be
        # trimmed, not emitted
        eng = Engine(params, config, max_slots=1, max_len=64,
                     ticks_per_sync=2)
        rid = eng.submit(GenRequest(prompt=p, max_new_tokens=12, eos_id=eos))
        assert eng.run()[rid] == free[:cut + 1]

    def test_horizon_bounds(self, setup):
        config, params = setup
        eng = Engine(params, config, max_slots=2, max_len=64,
                     ticks_per_sync=4)
        # no live slots -> 1
        assert eng._sync_horizon() == 1
        eng.submit(GenRequest(prompt=[3, 4], max_new_tokens=9))
        eng.step(chunks=1)  # admit + first chunk (1 admission + 4 ticks)
        # 9 - 5 = 4 remaining, queue empty -> ceil(4/4) = 1
        assert eng._sync_horizon() == 1
        eng._slots[0].request.max_new_tokens = 21  # 16 remaining -> 4 chunks
        assert eng._sync_horizon() == 4
        # a queued request with an EOS-capable tenant bounds it to 1
        eng._slots[0].request.eos_id = 0
        eng.submit(GenRequest(prompt=[5], max_new_tokens=2))
        assert eng._sync_horizon() == 1
        eng.run()


class TestEngineSampling:
    def test_top_k_one_sampled_rows_match_greedy(self, setup):
        """temperature > 0 with top_k=1 collapses to greedy — the sampled
        path's parity anchor, exercised alongside plain greedy rows in
        the same batch."""
        config, params = setup
        eng = Engine(params, config, max_slots=2, max_len=64)
        p1 = rand_prompt(jax.random.key(70), 6, config.vocab_size)
        p2 = rand_prompt(jax.random.key(71), 9, config.vocab_size)
        id1 = eng.submit(GenRequest(prompt=p1, max_new_tokens=6,
                                    temperature=0.8, top_k=1))
        id2 = eng.submit(GenRequest(prompt=p2, max_new_tokens=6))  # greedy
        results = eng.run()
        assert results[id1] == solo(params, config, p1, 6)
        assert results[id2] == solo(params, config, p2, 6)

    def test_sampled_streams_reproducible_per_seed(self, setup):
        config, params = setup

        def run_once(seed):
            eng = Engine(params, config, max_slots=1, max_len=64, seed=seed)
            rid = eng.submit(GenRequest(
                prompt=[3, 5, 7, 9], max_new_tokens=8,
                temperature=1.0, top_p=0.9,
            ))
            return eng.run()[rid]

        assert run_once(1) == run_once(1)  # deterministic per seed
        a, b = run_once(1), run_once(2)
        assert len(a) == len(b) == 8
        assert a != b  # the seed actually drives the stream

    def test_sampled_stream_independent_of_cotenants(self, setup):
        """A request's sampled tokens derive from (engine seed, request
        id) only — co-tenant traffic, slot placement, and arrival order
        must not perturb them."""
        config, params = setup
        prompt = rand_prompt(jax.random.key(80), 6, config.vocab_size)

        def tokens_of(with_noise):
            eng = Engine(params, config, max_slots=2, max_len=64, seed=3)
            if with_noise:
                # id 0 consumed by a noisy sampled co-tenant admitted first
                eng.submit(GenRequest(
                    prompt=rand_prompt(jax.random.key(81), 9, config.vocab_size),
                    max_new_tokens=9, temperature=1.3,
                ))
            else:
                eng.submit(GenRequest(prompt=[1], max_new_tokens=1))  # burn id 0
            rid = eng.submit(GenRequest(
                prompt=prompt, max_new_tokens=6, temperature=0.9, top_k=32,
            ))
            return eng.run()[rid]

        assert tokens_of(False) == tokens_of(True)


class TestEngineMetrics:
    def test_serving_counters_advance(self, setup):
        from nos_tpu.util import metrics

        config, params = setup
        req0 = metrics.SERVE_REQUESTS.value
        tok0 = metrics.SERVE_TOKENS.value
        tick0 = metrics.SERVE_TICKS.value
        active0 = metrics.SERVE_SLOT_TICKS_ACTIVE.value
        eng = Engine(params, config, max_slots=2, max_len=64)
        _ids = [
            eng.submit(GenRequest(
                prompt=rand_prompt(jax.random.key(90 + i), 5, config.vocab_size),
                max_new_tokens=4,
            ))
            for i in range(3)
        ]
        eng.run()
        assert metrics.SERVE_REQUESTS.value - req0 == 3
        assert metrics.SERVE_TOKENS.value - tok0 == 12
        assert metrics.SERVE_TICKS.value > tick0
        assert metrics.SERVE_SLOTS.value == 2
        # occupancy numerator never exceeds this engine's ticks * slots
        tick_delta = metrics.SERVE_TICKS.value - tick0
        active_delta = metrics.SERVE_SLOT_TICKS_ACTIVE.value - active0
        assert 0 < active_delta <= tick_delta * 2


class TestPrefixCache:
    """Prefix caching (chunked path): shared prompt prefixes skip their
    prefill, bitwise-identically — greedy outputs must not change."""

    def test_shared_prefix_matches_solo_and_counts_hits(self, setup):
        from nos_tpu.util import metrics

        config, params = setup
        hits0 = metrics.SERVE_PREFIX_HITS.value
        reused0 = metrics.SERVE_PREFIX_TOKENS_REUSED.value
        eng = Engine(params, config, max_slots=2, max_len=128,
                     prefill_chunk=16, prefix_cache_entries=4)
        system = rand_prompt(jax.random.key(70), 40, config.vocab_size)
        prompts = [system + rand_prompt(jax.random.key(71 + i), 5,
                                        config.vocab_size) for i in range(3)]
        ids = [eng.submit(GenRequest(prompt=p, max_new_tokens=4))
               for p in prompts]
        results = eng.run()
        for rid, p in zip(ids, prompts):
            assert results[rid] == solo(params, config, p, 4), f"request {rid}"
        # prompts share the first 2 chunk boundaries (40 tokens -> 32
        # aligned); later admissions must have hit
        assert metrics.SERVE_PREFIX_HITS.value - hits0 >= 2
        assert metrics.SERVE_PREFIX_TOKENS_REUSED.value - reused0 >= 2 * 32

    def test_padded_path_unaffected(self, setup):
        config, params = setup
        eng = Engine(params, config, max_slots=2, max_len=64,
                     prefix_cache_entries=4)
        p = rand_prompt(jax.random.key(80), 6, config.vocab_size)
        rid = eng.submit(GenRequest(prompt=p, max_new_tokens=3))
        assert eng.run()[rid] == solo(params, config, p, 3)
        assert not eng._prefix_cache  # short prompts take the padded path

    def test_lru_eviction_bounds_entries(self, setup):
        config, params = setup
        eng = Engine(params, config, max_slots=1, max_len=128,
                     prefill_chunk=16, prefix_cache_entries=2)
        for i in range(4):  # 4 distinct long prompts -> 4 insertions
            p = rand_prompt(jax.random.key(90 + i), 40, config.vocab_size)
            eng.submit(GenRequest(prompt=p, max_new_tokens=2))
            eng.run()
        assert len(eng._prefix_cache) <= 2

    def test_disabled_by_default(self, setup):
        config, params = setup
        eng = Engine(params, config, max_slots=1, max_len=128,
                     prefill_chunk=16)
        p = rand_prompt(jax.random.key(95), 40, config.vocab_size)
        rid = eng.submit(GenRequest(prompt=p, max_new_tokens=3))
        assert eng.run()[rid] == solo(params, config, p, 3)
        assert not eng._prefix_cache


class TestMoEServing:
    def test_moe_params_match_solo_generation(self, setup):
        """Routed-MoE checkpoints serve through the slot engine: decode
        dispatches each block's FFN to the mixture, and the tokens must
        equal a solo generate() run on the same params (f32 keeps the
        routing argmaxes clear of reduction-order drift). Capacity is
        overflow-free (factor 4): static capacity depends on the call's
        token count, so the padded-prefill and solo paths only promise
        exact equality when no expert overflows — the documented serving
        contract. Pad columns never claim capacity at ANY factor
        (moe_mlp token_mask; pinned separately in test_moe.py)."""
        config = tiny_config(
            n_experts=4, dtype=jnp.float32, moe_capacity_factor=4.0
        )
        params = init_llama_params(jax.random.key(3), config)
        eng = Engine(params, config, max_slots=2, max_len=64,
                     ticks_per_sync=4)
        p = rand_prompt(jax.random.key(4), 6, config.vocab_size)
        rid = eng.submit(GenRequest(prompt=p, max_new_tokens=6))
        rid2 = eng.submit(GenRequest(prompt=p[:3], max_new_tokens=4))
        got = eng.run()
        assert got[rid] == solo(params, config, p, 6)
        assert got[rid2] == solo(params, config, p[:3], 4)

    def test_idle_slots_claim_no_expert_capacity(self, setup):
        """DEFAULT capacity factor, one request in a 4-slot engine: the
        3 idle rows decode garbage and must not compete for expert
        capacity (decode_step derives a row mask from key_valid), so
        the lone tenant matches solo exactly even where capacity
        binds."""
        config = tiny_config(n_experts=4, dtype=jnp.float32)
        params = init_llama_params(jax.random.key(5), config)
        p = rand_prompt(jax.random.key(6), 8, config.vocab_size)
        eng = Engine(params, config, max_slots=4, max_len=64,
                     ticks_per_sync=4)
        rid = eng.submit(GenRequest(prompt=p, max_new_tokens=8))
        assert eng.run()[rid] == solo(params, config, p, 8)


class TestStreaming:
    def test_on_token_streams_exactly_the_final_tokens(self, setup):
        """Streamed tokens equal the returned completion — order
        preserved, trimmed ride-along surplus never delivered — for both
        the base engine and the speculative engine."""
        from nos_tpu.serve import SpecEngine

        config, params = setup
        p = rand_prompt(jax.random.key(70), 6, config.vocab_size)
        for make in (
            lambda cb: Engine(params, config, max_slots=2, max_len=64,
                              ticks_per_sync=4),
            lambda cb: SpecEngine(
                params, config, params, config, k=3,
                max_slots=2, max_len=64,
            ),
        ):
            streamed = {}
            eng = make(None)
            def cb(rid, tok):
                streamed.setdefault(rid, []).append(tok)
            r1 = eng.submit(GenRequest(prompt=p, max_new_tokens=9,
                                       on_token=cb))
            r2 = eng.submit(GenRequest(prompt=p[:3], max_new_tokens=5,
                                       on_token=cb))
            got = eng.run()
            assert streamed[r1] == got[r1] and len(got[r1]) == 9
            assert streamed[r2] == got[r2] and len(got[r2]) == 5

    def test_on_token_with_eos_stops_stream(self, setup):
        config, params = setup
        p = rand_prompt(jax.random.key(71), 5, config.vocab_size)
        ref = Engine(params, config, max_slots=1, max_len=64,
                     ticks_per_sync=2)
        r0 = ref.submit(GenRequest(prompt=p, max_new_tokens=10))
        free = ref.run()[r0]
        cut = next(i for i in range(2, 10) if free[i] not in free[:i])
        streamed = []
        eng = Engine(params, config, max_slots=1, max_len=64,
                     ticks_per_sync=2)
        rid = eng.submit(GenRequest(
            prompt=p, max_new_tokens=10, eos_id=free[cut],
            on_token=lambda _, t: streamed.append(t),
        ))
        assert eng.run()[rid] == streamed == free[:cut + 1]

    def test_streaming_bounds_sync_horizon(self, setup):
        """A streaming slot must not receive its whole completion in one
        terminal burst: with queue empty the horizon caps at 4 chunks,
        so a 32-token budget at ticks_per_sync=2 syncs at least 4
        times."""
        config, params = setup
        p = rand_prompt(jax.random.key(72), 4, config.vocab_size)
        bursts = []
        eng = Engine(params, config, max_slots=1, max_len=64,
                     ticks_per_sync=2)
        seen = 0
        orig_step = eng.step
        def counting_step(chunks=1):
            nonlocal seen
            orig_step(chunks=chunks)
            live = [s for s in eng._slots if s is not None]
            n = sum(len(s.out) for s in live) + seen
            bursts.append(n)
        eng.step = counting_step
        eng.submit(GenRequest(prompt=p, max_new_tokens=32,
                              on_token=lambda r, t: None))
        eng.run()
        # >= 4 decode syncs (32 tokens / (4 chunks * 2 ticks) = 4)
        assert len(bursts) >= 4, bursts
