"""Tensor-parallel serving: the sharded engine must be invisible.

A tp-sharded Engine's completions are compared against an unsharded one:
the contract is that placement (Megatron param sharding + head-sharded
KV cache) changes nothing observable. f32 params keep reduction-order
noise far below any argmax gap, so greedy token parity is exact-stable
across mesh shapes.
"""
import jax
import jax.numpy as jnp
import numpy as np
import pytest

from nos_tpu.models.llama import init_llama_params, tiny_config
from nos_tpu.models.quantize import quantize_params, quantize_params_int4
from nos_tpu.parallel.mesh import mesh_from_devices
from nos_tpu.serve import Engine, GenRequest, kv_cache_sharding, shard_for_serving


@pytest.fixture(scope="module")
def setup():
    config = tiny_config(dtype=jnp.float32)
    params = init_llama_params(jax.random.key(0), config)
    return config, params


def prompts_for(config, n):
    return [
        np.asarray(
            jax.random.randint(jax.random.key(100 + i), (4 + 3 * i,), 1,
                               config.vocab_size)
        ).tolist()
        for i in range(n)
    ]


def run_workload(eng, prompts):
    ids = [
        eng.submit(GenRequest(prompt=p, max_new_tokens=5 + i))
        for i, p in enumerate(prompts)
    ]
    got = eng.run()
    return [got[rid] for rid in ids]


class TestShardedServing:
    @pytest.mark.parametrize("tp", [2, 4])
    def test_tp_engine_matches_unsharded(self, setup, tp):
        config, params = setup
        prompts = prompts_for(config, 4)
        base = Engine(params, config, max_slots=2, max_len=64,
                      ticks_per_sync=4)
        want = run_workload(base, prompts)

        mesh = mesh_from_devices((tp,), ("tp",), jax.devices()[:tp])
        sharded = shard_for_serving(params, mesh, config)
        eng = Engine(sharded, config, max_slots=2, max_len=64,
                     ticks_per_sync=4, mesh=mesh)
        assert run_workload(eng, prompts) == want

    def test_dp_tp_mesh_degrades_gracefully(self, setup):
        """A ('dp','tp') serving mesh replicates over dp (no batch axis
        in the cache sharding) and shards over tp."""
        config, params = setup
        prompts = prompts_for(config, 2)
        base = Engine(params, config, max_slots=2, max_len=64,
                      ticks_per_sync=4)
        want = run_workload(base, prompts)
        mesh = mesh_from_devices((2, 4), ("dp", "tp"), jax.devices()[:8])
        sharded = shard_for_serving(params, mesh, config)
        eng = Engine(sharded, config, max_slots=2, max_len=64,
                     ticks_per_sync=4, mesh=mesh)
        assert run_workload(eng, prompts) == want

    def test_cache_sharding_validates_head_divisibility(self, setup):
        config, _ = setup
        mesh = mesh_from_devices((3,), ("tp",), jax.devices()[:3])
        with pytest.raises(ValueError, match="divide"):
            kv_cache_sharding(mesh, config)

    def test_quantized_int8_tp_engine_serves(self, setup):
        """int8 weight-only + tp: quantized trees shard with their
        scales riding the output axis; the engine must complete the
        workload (token parity vs the unsharded QUANTIZED engine — the
        quantization itself changes tokens vs f32, placement must not)."""
        config, params = setup
        qparams = jax.jit(quantize_params)(params)
        prompts = prompts_for(config, 3)
        base = Engine(qparams, config, max_slots=2, max_len=64,
                      ticks_per_sync=4)
        want = run_workload(base, prompts)
        mesh = mesh_from_devices((4,), ("tp",), jax.devices()[:4])
        qsharded = shard_for_serving(qparams, mesh, config)
        eng = Engine(qsharded, config, max_slots=2, max_len=64,
                     ticks_per_sync=4, mesh=mesh)
        assert run_workload(eng, prompts) == want

    def test_quantized_int4_tp_engine_serves(self, setup):
        config, params = setup
        q4 = jax.jit(lambda p: quantize_params_int4(p, group=16))(params)
        prompts = prompts_for(config, 2)
        base = Engine(q4, config, max_slots=2, max_len=64, ticks_per_sync=4)
        want = run_workload(base, prompts)
        mesh = mesh_from_devices((2,), ("tp",), jax.devices()[:2])
        q4s = shard_for_serving(q4, mesh, config)
        eng = Engine(q4s, config, max_slots=2, max_len=64,
                     ticks_per_sync=4, mesh=mesh)
        assert run_workload(eng, prompts) == want
