"""HF → nos-tpu conversion: torch transformers forward is the oracle.

A randomly initialized tiny transformers Llama (no network needed) runs
through both stacks on identical weights — bitwise-independent
implementations agreeing on logits is the strongest correctness evidence
the model code has.
"""
import jax.numpy as jnp
import numpy as np
import pytest

torch = pytest.importorskip("torch")
transformers = pytest.importorskip("transformers")

from nos_tpu.models.convert import load_hf_llama, params_from_hf_state_dict
from nos_tpu.models.generate import generate
from nos_tpu.models.llama import llama_forward


@pytest.fixture(scope="module")
def hf_model():
    from transformers import LlamaConfig, LlamaForCausalLM

    torch.manual_seed(0)
    config = LlamaConfig(
        vocab_size=128,
        hidden_size=64,
        intermediate_size=128,
        num_hidden_layers=2,
        num_attention_heads=8,
        num_key_value_heads=4,  # exercises GQA head-ordering
        max_position_embeddings=64,
        rope_theta=10000.0,
        attention_dropout=0.0,
    )
    model = LlamaForCausalLM(config)
    model.eval()
    return model


class TestConversion:
    def test_logits_match_torch(self, hf_model):
        params, config = load_hf_llama(hf_model, dtype=jnp.float32)
        tokens_np = np.array([[1, 5, 9, 42, 17, 99, 3, 64]], dtype=np.int64)
        with torch.no_grad():
            want = hf_model(torch.from_numpy(tokens_np)).logits.numpy()
        got = np.asarray(llama_forward(params, jnp.asarray(tokens_np), config))
        np.testing.assert_allclose(got, want, atol=2e-4)

    def test_greedy_generation_matches_torch(self, hf_model):
        params, config = load_hf_llama(hf_model, dtype=jnp.float32)
        prompt_np = np.array([[2, 11, 23, 5]], dtype=np.int64)
        with torch.no_grad():
            want = hf_model.generate(
                torch.from_numpy(prompt_np),
                max_new_tokens=8,
                do_sample=False,
                num_beams=1,
            ).numpy()[:, prompt_np.shape[1]:]
        got = np.asarray(
            generate(params, jnp.asarray(prompt_np), config, max_new_tokens=8)
        )
        np.testing.assert_array_equal(got, want)

    def test_tied_embeddings_materialize_lm_head(self, hf_model):
        sd = {k: v for k, v in hf_model.state_dict().items() if k != "lm_head.weight"}
        params, config = load_hf_llama(hf_model, dtype=jnp.float32)
        tied = params_from_hf_state_dict(sd, config)
        assert tied["lm_head"].shape == params["lm_head"].shape
        np.testing.assert_array_equal(
            np.asarray(tied["lm_head"]), np.asarray(tied["embed"]).T
        )
        # and the tied tree actually forwards
        out = llama_forward(tied, jnp.asarray([[1, 2, 3]]), config)
        assert np.isfinite(np.asarray(out)).all()

    def test_unknown_weights_rejected(self, hf_model):
        sd = dict(hf_model.state_dict())
        sd["model.layers.0.self_attn.q_proj.bias"] = torch.zeros(64)
        _, config = load_hf_llama(hf_model, dtype=jnp.float32)
        with pytest.raises(ValueError, match="unconverted weights"):
            params_from_hf_state_dict(sd, config)

    def test_unsupported_rope_scaling_rejected(self, hf_model):
        from nos_tpu.models.convert import config_from_hf

        hf_cfg = hf_model.config
        hf_cfg.rope_scaling = {"rope_type": "yarn", "factor": 8.0}
        try:
            with pytest.raises(ValueError, match="rope_scaling"):
                config_from_hf(hf_cfg)
        finally:
            hf_cfg.rope_scaling = None

    def test_llama3_rope_scaling_logits_match_torch(self):
        """Llama-3.1-style scaled RoPE: transformers applies its own
        implementation; ours must produce the same logits."""
        from transformers import LlamaConfig as HFConfig
        from transformers import LlamaForCausalLM

        torch.manual_seed(1)
        hf_cfg = HFConfig(
            vocab_size=128, hidden_size=64, intermediate_size=128,
            num_hidden_layers=2, num_attention_heads=8, num_key_value_heads=4,
            max_position_embeddings=512, rope_theta=10000.0,
            attention_dropout=0.0,
            # original_max=128 puts wavelength 62.8 inside the [32, 128]
            # medium band, so the smooth-interpolation branch is exercised
            # (not just keep / divide-by-factor).
            rope_scaling={
                "rope_type": "llama3", "factor": 8.0,
                "low_freq_factor": 1.0, "high_freq_factor": 4.0,
                "original_max_position_embeddings": 128,
            },
        )
        model = LlamaForCausalLM(hf_cfg)
        model.eval()
        params, config = load_hf_llama(model, dtype=jnp.float32)
        assert config.rope_scaling is not None
        tokens_np = np.arange(48, dtype=np.int64)[None, :] % 128  # spans bands
        with torch.no_grad():
            want = model(torch.from_numpy(tokens_np)).logits.numpy()
        got = np.asarray(llama_forward(params, jnp.asarray(tokens_np), config))
        np.testing.assert_allclose(got, want, atol=3e-4)

    def test_dtype_conversion(self, hf_model):
        params, config = load_hf_llama(hf_model)  # default bf16
        assert params["layers"][0]["wq"].dtype == jnp.bfloat16
        assert config.dtype == jnp.bfloat16
