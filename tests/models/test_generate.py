"""KV-cache generation vs the cache-free oracle."""
import jax
import jax.numpy as jnp
import numpy as np
import pytest

from nos_tpu.models.generate import (
    decode_step,
    generate,
    prefill,
    reference_generate,
)
from nos_tpu.models.llama import init_llama_params, llama_forward, tiny_config


@pytest.fixture(scope="module")
def setup():
    config = tiny_config()
    params = init_llama_params(jax.random.key(0), config)
    prompt = jax.random.randint(jax.random.key(1), (2, 8), 0, config.vocab_size)
    return config, params, prompt


class TestPrefill:
    def test_prefill_logits_match_forward(self, setup):
        config, params, prompt = setup
        logits, cache = prefill(params, prompt, config, max_len=16)
        want = llama_forward(params, prompt, config)
        np.testing.assert_allclose(
            np.asarray(logits), np.asarray(want), atol=1e-2
        )
        assert cache[0]["k"].shape == (2, 16, config.n_kv_heads, config.head_dim)


class TestDecode:
    def test_decode_logits_match_full_forward(self, setup):
        """Step t's cached-decode logits equal the full forward's logits at
        position t — the cache IS the context."""
        config, params, prompt = setup
        b, s = prompt.shape
        _, cache = prefill(params, prompt, config, max_len=s + 4)
        extra = jax.random.randint(jax.random.key(2), (b, 4), 0, config.vocab_size)
        seq = prompt
        for i in range(4):
            token = extra[:, i]
            logits, cache = decode_step(
                params, cache, jnp.asarray(s + i), token, config
            )
            seq = jnp.concatenate([seq, token[:, None]], axis=1)
            want = llama_forward(params, seq, config)[:, -1]
            np.testing.assert_allclose(
                np.asarray(logits), np.asarray(want), atol=2e-2
            )

    def test_greedy_generate_matches_oracle(self, setup):
        config, params, prompt = setup
        got = generate(params, prompt, config, max_new_tokens=6)
        want = reference_generate(params, prompt, config, max_new_tokens=6)
        np.testing.assert_array_equal(np.asarray(got), np.asarray(want))

    def test_generate_is_jittable(self, setup):
        config, params, prompt = setup
        fn = jax.jit(
            lambda p, t: generate(p, t, config, max_new_tokens=4)
        )
        out = fn(params, prompt)
        assert out.shape == (2, 4)
        # same compiled program serves a second prompt of the same shape
        out2 = fn(params, prompt + 1)
        assert out2.shape == (2, 4)

    def test_sampling_respects_temperature(self, setup):
        config, params, prompt = setup
        a = generate(params, prompt, config, 8, temperature=1.0,
                     rng=jax.random.key(1))
        b = generate(params, prompt, config, 8, temperature=1.0,
                     rng=jax.random.key(2))
        assert a.shape == b.shape == (2, 8)
        assert not np.array_equal(np.asarray(a), np.asarray(b))  # stochastic

    def test_cache_rejects_overlong_prompt(self, setup):
        config, params, prompt = setup
        with pytest.raises(ValueError):
            prefill(params, prompt, config, max_len=4)


class TestFlashPrefill:
    def test_flash_prefill_matches_dense_prefill(self, setup):
        config, params, prompt = setup
        flash_cfg = tiny_config(attention="flash")
        l_dense, cache_d = prefill(params, prompt, config, max_len=16)
        l_flash, cache_f = prefill(params, prompt, flash_cfg, max_len=16)
        # bf16 model: dense rounds probs to bf16 pre-PV, flash accumulates
        # f32 — logits agree to bf16 noise, distributions tightly (the
        # same contract as the llama forward flash test).
        np.testing.assert_allclose(
            np.asarray(l_dense), np.asarray(l_flash), atol=1e-1
        )
        pd = jax.nn.softmax(l_dense, axis=-1)
        pf = jax.nn.softmax(l_flash, axis=-1)
        assert float(jnp.abs(pd - pf).max()) < 3e-3
        # layer-0 K is computed before any attention ran: exact. Deeper
        # layers inherit the paths' bf16 activation noise: tolerance.
        np.testing.assert_array_equal(
            np.asarray(cache_d[0]["k"]), np.asarray(cache_f[0]["k"])
        )
        for cd, cf in zip(cache_d[1:], cache_f[1:]):
            np.testing.assert_allclose(
                np.asarray(cd["k"], np.float32),
                np.asarray(cf["k"], np.float32),
                atol=5e-2,
            )

    def test_flash_generate_matches_oracle(self, setup):
        config, params, prompt = setup
        flash_cfg = tiny_config(attention="flash")
        got = generate(params, prompt, flash_cfg, max_new_tokens=6)
        want = reference_generate(params, prompt, config, max_new_tokens=6)
        np.testing.assert_array_equal(np.asarray(got), np.asarray(want))


class TestLeftPaddedBatching:
    def test_padded_batch_matches_per_row_generation(self, setup):
        """The serving contract: batching variable-length prompts with
        left padding produces exactly what each row would generate alone."""
        config, params, _ = setup
        PAD = 0
        rows = [
            jax.random.randint(jax.random.key(3), (5,), 1, config.vocab_size),
            jax.random.randint(jax.random.key(4), (8,), 1, config.vocab_size),
            jax.random.randint(jax.random.key(5), (3,), 1, config.vocab_size),
        ]
        width = max(r.shape[0] for r in rows)
        padded = jnp.stack([
            jnp.concatenate([jnp.full((width - r.shape[0],), PAD, r.dtype), r])
            for r in rows
        ])
        batched = generate(params, padded, config, max_new_tokens=6, pad_id=PAD)
        for i, row in enumerate(rows):
            solo = generate(params, row[None], config, max_new_tokens=6)
            np.testing.assert_array_equal(
                np.asarray(batched[i]), np.asarray(solo[0]),
                err_msg=f"row {i} (len {row.shape[0]})",
            )

    def test_unpadded_rows_unaffected_by_pad_id(self, setup):
        config, params, prompt = setup
        plain = generate(params, prompt, config, max_new_tokens=5)
        with_pad = generate(
            params, prompt, config, max_new_tokens=5, pad_id=255
        )  # 255 absent from the prompt
        np.testing.assert_array_equal(np.asarray(plain), np.asarray(with_pad))


class TestEosStopping:
    def test_rows_freeze_after_eos(self, setup):
        config, params, prompt = setup
        # find some eos id the greedy run actually emits
        free = generate(params, prompt, config, max_new_tokens=8)
        eos = int(np.asarray(free)[0, 2])  # 3rd token of row 0
        stopped = generate(params, prompt, config, max_new_tokens=8, eos_id=eos)
        row = np.asarray(stopped)[0]
        first = int(np.argmax(row == eos))
        assert (row[first:] == eos).all()  # frozen after first eos
        # tokens before eos are unchanged vs the free run
        np.testing.assert_array_equal(row[:first], np.asarray(free)[0, :first])

    def test_eos_never_emitted_is_noop(self, setup):
        config, params, prompt = setup
        free = generate(params, prompt, config, max_new_tokens=6)
        emitted = set(np.asarray(free).ravel().tolist())
        unused = next(t for t in range(config.vocab_size) if t not in emitted)
        stopped = generate(params, prompt, config, max_new_tokens=6, eos_id=unused)
        np.testing.assert_array_equal(np.asarray(free), np.asarray(stopped))


class TestShardedServing:
    def test_tp_sharded_params_generate_identically(self, setup):
        """Serving on a carved slice: shard the params over tp (and dp for
        the moments of batch) and jit — XLA propagates the shardings
        through prefill and the decode scan; tokens are identical to the
        unsharded run."""
        from nos_tpu.parallel.mesh import mesh_from_devices
        from nos_tpu.parallel.sharding import llama_param_sharding

        config, params, prompt = setup
        want = generate(params, prompt, config, max_new_tokens=6)
        mesh = mesh_from_devices((1, 4), ("dp", "tp"), jax.devices()[:4])
        sharded = jax.device_put(params, llama_param_sharding(mesh, config))
        got = jax.jit(lambda p, t: generate(p, t, config, max_new_tokens=6))(
            sharded, prompt
        )
        np.testing.assert_array_equal(np.asarray(got), np.asarray(want))

    def test_tp_sharded_int8_generate_identically(self, setup):
        """The serving matrix closes: int8-quantized weights shard over tp
        with their scales along the output axis (dequant stays local), and
        sharded quantized generation matches the unsharded quantized run
        token for token."""
        from nos_tpu.models.quantize import quantize_params
        from nos_tpu.parallel.mesh import mesh_from_devices
        from nos_tpu.parallel.sharding import llama_quantized_sharding

        config, params, prompt = setup
        qparams = quantize_params(params)
        want = generate(qparams, prompt, config, max_new_tokens=6)
        mesh = mesh_from_devices((1, 4), ("dp", "tp"), jax.devices()[:4])
        sharded = jax.device_put(qparams, llama_quantized_sharding(mesh, config))
        got = jax.jit(lambda p, t: generate(p, t, config, max_new_tokens=6))(
            sharded, prompt
        )
        np.testing.assert_array_equal(np.asarray(got), np.asarray(want))

    def test_tp_sharded_int4_generate_identically(self, setup):
        """Same contract at 4 bits: packed nibbles shard along the
        (halved) contraction axis, group scales alongside it."""
        from nos_tpu.models.quantize import quantize_params_int4
        from nos_tpu.parallel.mesh import mesh_from_devices
        from nos_tpu.parallel.sharding import llama_quantized_sharding

        config, params, prompt = setup
        q4 = quantize_params_int4(params, group=16)
        # jit both sides: eager-vs-jit bf16 fusion drift (unrelated to
        # int4 — dequant and matmul are bitwise equal under sharding) can
        # flip near-tied argmaxes in the tiny test vocab.
        gen6 = jax.jit(lambda p, t: generate(p, t, config, max_new_tokens=6))
        want = gen6(q4, prompt)
        mesh = mesh_from_devices((1, 4), ("dp", "tp"), jax.devices()[:4])
        sharded = jax.device_put(
            q4, llama_quantized_sharding(mesh, config, bits=4, group=16)
        )
        got = gen6(sharded, prompt)
        np.testing.assert_array_equal(np.asarray(got), np.asarray(want))


class TestSamplingFilters:
    def test_top_k_one_equals_greedy(self, setup):
        config, params, prompt = setup
        greedy = generate(params, prompt, config, max_new_tokens=6)
        top1 = generate(
            params, prompt, config, max_new_tokens=6,
            temperature=0.7, top_k=1, rng=jax.random.key(9),
        )
        np.testing.assert_array_equal(np.asarray(greedy), np.asarray(top1))

    def test_tiny_top_p_equals_greedy(self, setup):
        config, params, prompt = setup
        greedy = generate(params, prompt, config, max_new_tokens=6)
        nucleus = generate(
            params, prompt, config, max_new_tokens=6,
            temperature=0.7, top_p=1e-6, rng=jax.random.key(10),
        )
        # the nucleus always keeps the first (highest-prob) token
        np.testing.assert_array_equal(np.asarray(greedy), np.asarray(nucleus))

    def test_top_k_samples_stay_in_top_k_set(self, setup):
        from nos_tpu.models.generate import _filter_logits

        config, params, prompt = setup
        logits = jax.random.normal(jax.random.key(2), (3, config.vocab_size))
        k = 5
        filtered = _filter_logits(logits, top_k=k, top_p=1.0)
        allowed = jax.lax.top_k(logits, k)[1]
        draws = jax.vmap(
            lambda key: jax.random.categorical(key, filtered, axis=-1)
        )(jax.random.split(jax.random.key(3), 64))  # [64, 3]
        for row in range(3):
            assert set(np.asarray(draws[:, row])) <= set(np.asarray(allowed[row]))

    def test_top_p_keeps_minimal_prefix(self):
        from nos_tpu.models.generate import _filter_logits

        # probs 0.5, 0.3, 0.15, 0.05 -> top_p=0.6 keeps {0, 1}: mass before
        # token 1 is 0.5 < 0.6 (kept, crossing the threshold), before
        # token 2 is 0.8 >= 0.6 (dropped).
        probs = jnp.array([[0.5, 0.3, 0.15, 0.05]])
        logits = jnp.log(probs)
        filtered = np.asarray(_filter_logits(logits, top_k=0, top_p=0.6))
        assert np.isfinite(filtered[0, :2]).all()
        assert np.isneginf(filtered[0, 2:]).all()

    def test_filters_compose_under_jit(self, setup):
        config, params, prompt = setup
        out = jax.jit(
            lambda p, t, r: generate(
                p, t, config, max_new_tokens=4,
                temperature=0.9, top_k=8, top_p=0.9, rng=r,
            )
        )(params, prompt, jax.random.key(4))
        assert out.shape == (2, 4)
        assert (np.asarray(out) >= 0).all()


class TestMoeServing:
    def test_moe_kv_generation_matches_cache_free_oracle(self):
        """MoE checkpoints serve through the same KV-cache path (prefill +
        decode dispatch to moe_mlp like llama_forward's block). Two
        divergence sources are controlled so the comparison is exact and
        meaningful: router weights are scaled to make routing decisive
        (bf16 near-ties are a routing discontinuity, not a serving bug),
        and capacity_factor=2 with top_k=2/E=4 gives cap >= T, so NEITHER
        path overflows — decode pools capacity over B tokens per step and
        can never drop, while a full forward pools over B*S and can, so
        parity only holds (and should only be asserted) overflow-free."""
        from nos_tpu.models.generate import reference_generate
        from nos_tpu.models.llama import init_llama_params, tiny_config

        config = tiny_config(n_experts=4, moe_top_k=2, moe_capacity_factor=2.0)
        params = init_llama_params(jax.random.key(11), config)
        for layer in params["layers"]:
            layer["moe"]["router"] = layer["moe"]["router"] * 8.0
        prompt = jax.random.randint(jax.random.key(12), (2, 8), 0, config.vocab_size)
        want = reference_generate(params, prompt, config, max_new_tokens=6)
        got = generate(params, prompt, config, max_new_tokens=6)
        np.testing.assert_array_equal(np.asarray(want), np.asarray(got))

    def test_quantized_moe_generation_runs(self):
        from nos_tpu.models.llama import init_llama_params, tiny_config
        from nos_tpu.models.quantize import quantize_params

        config = tiny_config(n_experts=4, moe_top_k=2)
        params = init_llama_params(jax.random.key(13), config)
        out = generate(quantize_params(params), prompt=jnp.zeros((1, 4), jnp.int32),
                       config=config, max_new_tokens=4)
        assert out.shape == (1, 4)


class TestDecodeChunk:
    def test_chunk_matches_sequential_decode_steps(self, setup):
        """decode_chunk(m tokens) == m sequential decode_steps: same
        logits at every position, same cache contents."""
        from nos_tpu.models.generate import decode_chunk

        config, params, prompt = setup
        b, s = prompt.shape
        m = 4
        _, cache_a = prefill(params, prompt, config, max_len=s + m)
        _, cache_b = prefill(params, prompt, config, max_len=s + m)
        extra = jax.random.randint(jax.random.key(21), (b, m), 0, config.vocab_size)

        chunk_logits, cache_a = decode_chunk(
            params, cache_a, jnp.full((b,), s, jnp.int32), extra, config
        )
        for i in range(m):
            step_logits, cache_b = decode_step(
                params, cache_b, jnp.asarray(s + i), extra[:, i], config
            )
            np.testing.assert_allclose(
                np.asarray(chunk_logits[:, i]), np.asarray(step_logits),
                atol=2e-2, err_msg=f"position {i}",
            )
        for la, lb in zip(cache_a, cache_b):
            np.testing.assert_allclose(
                np.asarray(la["k"], np.float32), np.asarray(lb["k"], np.float32),
                atol=1e-2,
            )

    def test_write_mask_redirects_to_trash_slot(self, setup):
        from nos_tpu.models.generate import decode_chunk

        config, params, prompt = setup
        b, s = prompt.shape
        m = 4
        # +1 sacrificial trailing slot
        _, cache = prefill(params, prompt, config, max_len=s + m + 1)
        before = np.asarray(cache[0]["k"]).copy()
        mask = jnp.asarray([[True, True, False, False]] * b)
        extra = jax.random.randint(jax.random.key(22), (b, m), 0, config.vocab_size)
        _, cache = decode_chunk(
            params, cache, jnp.full((b,), s, jnp.int32), extra, config,
            write_mask=mask,
        )
        after = np.asarray(cache[0]["k"])
        # masked positions s+2, s+3 unchanged; writes landed at s, s+1, trash
        np.testing.assert_array_equal(after[:, s + 2], before[:, s + 2])
        np.testing.assert_array_equal(after[:, s + 3], before[:, s + 3])
        assert not np.array_equal(after[:, s], before[:, s])
