"""Rolling sliding-window cache: O(window) memory for unbounded streams.

Oracle is the NON-rolling windowed engine with a cache big enough to
hold everything physically: the rolling layout changes storage only —
attention semantics (last-W keys) are identical, so tokens must match
exactly. The headline test serves prompt+budget several times the
rolling engine's max_len.
"""
import jax
import jax.numpy as jnp
import numpy as np
import pytest

from nos_tpu.models.llama import init_llama_params, tiny_config
from nos_tpu.serve import Engine, GenRequest, SpecEngine


W = 16


@pytest.fixture(scope="module")
def setup():
    config = tiny_config(dtype=jnp.float32, sliding_window=W)
    params = init_llama_params(jax.random.key(0), config)
    return config, params


def rand_prompt(key, n, vocab):
    return np.asarray(jax.random.randint(key, (n,), 1, vocab)).tolist()


def big_oracle(params, config, reqs, max_len=512):
    eng = Engine(params, config, max_slots=2, max_len=max_len,
                 ticks_per_sync=4, prefill_chunk=8)
    ids = [eng.submit(GenRequest(**r)) for r in reqs]
    got = eng.run()
    return [got[i] for i in ids]


class TestRollingCache:
    def test_matches_physical_layout_within_bounds(self, setup):
        """Workload that fits BOTH layouts: rolling must be invisible."""
        config, params = setup
        reqs = [
            dict(prompt=rand_prompt(jax.random.key(i), n, config.vocab_size),
                 max_new_tokens=m)
            for i, (n, m) in enumerate(((5, 9), (20, 6), (11, 12)))
        ]
        want = big_oracle(params, config, [dict(r) for r in reqs])
        eng = Engine(params, config, max_slots=2, max_len=33,
                     ticks_per_sync=4, prefill_chunk=8, rolling=True)
        ids = [eng.submit(GenRequest(**r)) for r in reqs]
        got = eng.run()
        assert [got[i] for i in ids] == want

    def test_stream_far_past_max_len(self, setup):
        """The point of the feature: 40-token prompt + 150 generated
        through a 33-slot cache (window 16) — logical positions reach
        ~6x the physical cache."""
        config, params = setup
        p = rand_prompt(jax.random.key(9), 40, config.vocab_size)
        want = big_oracle(
            params, config, [dict(prompt=p, max_new_tokens=150)],
            max_len=512,
        )[0]
        eng = Engine(params, config, max_slots=1, max_len=33,
                     ticks_per_sync=4, prefill_chunk=8, rolling=True)
        rid = eng.submit(GenRequest(prompt=p, max_new_tokens=150))
        got = eng.run()[rid]
        assert len(got) == 150
        assert got == want

    def test_slot_reuse_and_mixed_depths(self, setup):
        """Requests retiring and re-admitting into wrapped rows: the
        fresh tenant's ingest overwrites whatever logical residue the
        previous stream left."""
        config, params = setup
        prompts = [rand_prompt(jax.random.key(20 + i), 6 + 7 * i,
                               config.vocab_size) for i in range(5)]
        reqs = [dict(prompt=p, max_new_tokens=30 + 5 * i)
                for i, p in enumerate(prompts)]
        want = big_oracle(params, config, [dict(r) for r in reqs])
        eng = Engine(params, config, max_slots=2, max_len=33,
                     ticks_per_sync=4, prefill_chunk=8, rolling=True)
        ids = [eng.submit(GenRequest(**r)) for r in reqs]
        got = eng.run()
        assert [got[i] for i in ids] == want

    def test_validation(self, setup):
        config, params = setup
        # needs a window config
        dense_cfg = tiny_config(dtype=jnp.float32)
        with pytest.raises(ValueError, match="sliding_window"):
            Engine(init_llama_params(jax.random.key(1), dense_cfg),
                   dense_cfg, max_len=64, rolling=True)
        # cache must exceed window + minimum piece
        with pytest.raises(ValueError, match="max_len"):
            Engine(params, config, max_len=W + 4, rolling=True)
        # prefix cache is physical==logical only
        with pytest.raises(ValueError, match="prefix"):
            Engine(params, config, max_len=64, rolling=True,
                   prefix_cache_entries=2)
        # speculation excluded
        draft_cfg = tiny_config(n_layers=1, dtype=jnp.float32,
                                sliding_window=W)
        with pytest.raises(ValueError, match="rolling"):
            SpecEngine(params, config,
                       init_llama_params(jax.random.key(2), draft_cfg),
                       draft_cfg, max_len=64, rolling=True)
