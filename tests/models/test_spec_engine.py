"""Speculative continuous batching vs the plain engine oracle.

The contract stacks both invisibilities: batching must be invisible
(any slot mix yields each request's solo tokens) AND speculation must
be invisible (committed tokens are the TARGET's greedy stream — the
draft only changes speed). So every SpecEngine completion is compared
against the base Engine on the same target params; f32 keeps
chunk-vs-step reduction drift far below any argmax gap.
"""
import jax
import jax.numpy as jnp
import numpy as np
import pytest

from nos_tpu.models.llama import init_llama_params, tiny_config
from nos_tpu.serve import Engine, GenRequest, SpecEngine


@pytest.fixture(scope="module")
def setup():
    config = tiny_config(dtype=jnp.float32)
    target = init_llama_params(jax.random.key(0), config)
    draft_cfg = tiny_config(n_layers=1, dtype=jnp.float32)
    draft = init_llama_params(jax.random.key(7), draft_cfg)
    return config, target, draft_cfg, draft


def rand_prompt(key, n, vocab):
    return np.asarray(jax.random.randint(key, (n,), 1, vocab)).tolist()


def run_workload(eng, reqs):
    ids = [eng.submit(GenRequest(**r)) for r in reqs]
    got = eng.run()
    return [got[rid] for rid in ids]


class TestSpecEngine:
    def test_matches_plain_engine_mixed_workload(self, setup):
        config, target, draft_cfg, draft = setup
        reqs = [
            dict(prompt=rand_prompt(jax.random.key(200 + i), n, config.vocab_size),
                 max_new_tokens=m)
            for i, (n, m) in enumerate(((5, 9), (17, 4), (8, 12), (3, 7), (11, 6)))
        ]
        base = Engine(target, config, max_slots=2, max_len=64, ticks_per_sync=4)
        want = run_workload(base, [dict(r) for r in reqs])
        spec = SpecEngine(target, config, draft, draft_cfg, k=3,
                          max_slots=2, max_len=64)
        got = run_workload(spec, [dict(r) for r in reqs])
        assert got == want
        st = spec.stats()
        assert st["rounds"] > 0 and 0.0 <= st["mean_accepted"] <= 3.0

    def test_perfect_draft_accepts_everything(self, setup):
        config, target, _, _ = setup
        p = rand_prompt(jax.random.key(210), 6, config.vocab_size)
        spec = SpecEngine(target, config, target, config, k=4,
                          max_slots=1, max_len=64)
        rid = spec.submit(GenRequest(prompt=p, max_new_tokens=11))
        got = spec.run()[rid]
        base = Engine(target, config, max_slots=1, max_len=64)
        rid2 = base.submit(GenRequest(prompt=p, max_new_tokens=11))
        assert got == base.run()[rid2]
        # target-as-draft: every draft matches, so acceptance is k
        assert spec.stats()["mean_accepted"] == pytest.approx(4.0, abs=1.0)

    def test_eos_mid_round_trims(self, setup):
        config, target, draft_cfg, draft = setup
        p = rand_prompt(jax.random.key(220), 7, config.vocab_size)
        base = Engine(target, config, max_slots=1, max_len=64)
        rid = base.submit(GenRequest(prompt=p, max_new_tokens=12))
        free = base.run()[rid]
        cut = next(i for i in range(2, 12) if free[i] not in free[:i])
        spec = SpecEngine(target, config, draft, draft_cfg, k=3,
                          max_slots=1, max_len=64)
        rid = spec.submit(
            GenRequest(prompt=p, max_new_tokens=12, eos_id=free[cut])
        )
        assert spec.run()[rid] == free[:cut + 1]

    def test_slot_reuse_staggered(self, setup):
        config, target, draft_cfg, draft = setup
        p1 = rand_prompt(jax.random.key(230), 4, config.vocab_size)
        p2 = rand_prompt(jax.random.key(231), 9, config.vocab_size)
        p3 = rand_prompt(jax.random.key(232), 6, config.vocab_size)
        base = Engine(target, config, max_slots=2, max_len=64)
        b1 = base.submit(GenRequest(prompt=p1, max_new_tokens=3))
        b2 = base.submit(GenRequest(prompt=p2, max_new_tokens=10))
        b3 = base.submit(GenRequest(prompt=p3, max_new_tokens=5))
        want = base.run()
        spec = SpecEngine(target, config, draft, draft_cfg, k=2,
                          max_slots=2, max_len=64)
        s1 = spec.submit(GenRequest(prompt=p1, max_new_tokens=3))
        s2 = spec.submit(GenRequest(prompt=p2, max_new_tokens=10))
        spec.step()  # first round; third request arrives mid-flight
        s3 = spec.submit(GenRequest(prompt=p3, max_new_tokens=5))
        got = spec.run()
        assert [got[s1], got[s2], got[s3]] == [want[b1], want[b2], want[b3]]

    def test_sampling_rejected(self, setup):
        config, target, draft_cfg, draft = setup
        spec = SpecEngine(target, config, draft, draft_cfg,
                          max_slots=1, max_len=64)
        with pytest.raises(ValueError, match="argmax"):
            spec.submit(GenRequest(prompt=[3], max_new_tokens=4,
                                   temperature=0.5))

    def test_capacity_accounts_for_overshoot(self, setup):
        config, target, draft_cfg, draft = setup
        spec = SpecEngine(target, config, draft, draft_cfg, k=4,
                          max_slots=1, max_len=32)
        # 20 + 8 + 4 + 1 = 33 > 32: must reject at submit
        with pytest.raises(ValueError, match="cache slots"):
            spec.submit(GenRequest(prompt=[1] * 20, max_new_tokens=8))
        # 18 + 8 + 4 + 1 = 31 <= 32: fits, and completes
        rid = spec.submit(GenRequest(prompt=[1] * 18, max_new_tokens=8))
        assert len(spec.run()[rid]) == 8


class TestSpecEngineComposition:
    def test_tp_sharded_spec_engine_parity(self, setup):
        """Speculation composes with tensor parallelism: sharded target
        AND draft trees, head-sharded target cache (the draft cache
        stays replicated — the draft is small by design). Tokens must
        match the unsharded SpecEngine exactly."""
        from nos_tpu.parallel.mesh import mesh_from_devices
        from nos_tpu.serve import shard_for_serving

        config, target, draft_cfg, draft = setup
        p = rand_prompt(jax.random.key(240), 6, config.vocab_size)
        base = SpecEngine(target, config, draft, draft_cfg, k=3,
                          max_slots=2, max_len=64)
        r0 = base.submit(GenRequest(prompt=p, max_new_tokens=8))
        want = base.run()[r0]
        mesh = mesh_from_devices((2,), ("tp",), jax.devices()[:2])
        spec = SpecEngine(
            shard_for_serving(target, mesh, config), config,
            shard_for_serving(draft, mesh, draft_cfg), draft_cfg,
            k=3, max_slots=2, max_len=64, mesh=mesh,
        )
        r1 = spec.submit(GenRequest(prompt=p, max_new_tokens=8))
        assert spec.run()[r1] == want


class TestSpecEngineTelemetry:
    def test_serve_metrics_parity_with_accept_rate(self, setup):
        """The spec engine feeds the same SERVE_* telemetry as the plain
        engine (requests/tokens/latency records) PLUS the speculative
        accept-rate counters, so dashboards can put accepted/draft next
        to TTFT for either engine."""
        from nos_tpu.util import metrics

        config, target, draft_cfg, draft = setup
        spec = SpecEngine(target, config, draft, draft_cfg, k=3,
                          max_slots=2, max_len=64, model="spec-par")
        before = {
            "requests": metrics.SERVE_REQUESTS.value,
            "tokens": metrics.SERVE_TOKENS.value,
            "rounds": metrics.SERVE_SPEC_ROUNDS.value,
            "draft": metrics.SERVE_SPEC_DRAFT_TOKENS.value,
            "accepted": metrics.SERVE_SPEC_ACCEPTED_TOKENS.value,
        }
        reqs = [
            dict(prompt=rand_prompt(jax.random.key(300 + i), n, config.vocab_size),
                 max_new_tokens=m)
            for i, (n, m) in enumerate(((5, 8), (9, 6), (4, 10)))
        ]
        outs = run_workload(spec, reqs)
        total_tokens = sum(len(o) for o in outs)

        assert metrics.SERVE_REQUESTS.value - before["requests"] == 3
        assert metrics.SERVE_TOKENS.value - before["tokens"] == total_tokens
        rounds = metrics.SERVE_SPEC_ROUNDS.value - before["rounds"]
        draft_toks = metrics.SERVE_SPEC_DRAFT_TOKENS.value - before["draft"]
        accepted = metrics.SERVE_SPEC_ACCEPTED_TOKENS.value - before["accepted"]
        assert rounds > 0
        assert draft_toks == rounds * spec.k
        assert 0 <= accepted <= draft_toks
        # Counter deltas agree with the engine's own stats() view (the
        # counter counts per-ROW rounds: each live row's share of a
        # batched round, the denominator of the accept rate).
        assert spec.stats()["mean_accepted"] == pytest.approx(
            accepted / rounds
        )

        # Per-request telemetry parity: every request has the full stamp
        # set and landed in the latency histograms under this model label.
        for rid in list(spec.telemetry.completed):
            rec = spec.telemetry.record(rid)
            assert rec.model == "spec-par"
            assert rec.ttft_s is not None and rec.ttft_s >= 0.0
            assert rec.e2e_s >= rec.ttft_s
        rendered = metrics.REGISTRY.render()
        assert 'model="spec-par"' in rendered
        assert metrics.SERVE_QUEUE_DEPTH.value == 0
