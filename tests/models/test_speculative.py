"""Speculative decoding vs plain greedy generation.

The whole point is EXACTNESS: whatever the draft proposes, the committed
sequence equals the target's own greedy output — a perfect draft only
makes it faster, a terrible draft only makes it slower.
"""
import jax
import numpy as np
import pytest

from nos_tpu.models.generate import generate
from nos_tpu.models.llama import init_llama_params, tiny_config
from nos_tpu.models.speculative import speculative_generate


@pytest.fixture(scope="module")
def setup():
    config = tiny_config()
    target = init_llama_params(jax.random.key(0), config)
    draft_cfg = tiny_config(n_layers=1)
    draft = init_llama_params(jax.random.key(7), draft_cfg)
    prompt = jax.random.randint(jax.random.key(1), (2, 8), 0, config.vocab_size)
    return config, target, draft_cfg, draft, prompt


class TestSpeculativeExactness:
    def test_perfect_draft_matches_and_accepts_everything(self, setup):
        """Draft == target: every proposal accepted, output still exact."""
        config, target, _, _, prompt = setup
        want = np.asarray(generate(target, prompt, config, max_new_tokens=10))
        got, stats = speculative_generate(
            target, target, prompt, config, config, max_new_tokens=10, k=4
        )
        np.testing.assert_array_equal(np.asarray(got), want)
        assert stats["mean_accepted"] == pytest.approx(4.0), stats

    def test_unrelated_draft_still_exact(self, setup):
        """A draft that knows nothing about the target still yields the
        target's exact greedy tokens — only the acceptance rate drops."""
        config, target, draft_cfg, draft, prompt = setup
        want = np.asarray(generate(target, prompt, config, max_new_tokens=10))
        got, stats = speculative_generate(
            target, draft, prompt, config, draft_cfg, max_new_tokens=10, k=4
        )
        np.testing.assert_array_equal(np.asarray(got), want)
        assert 0.0 <= stats["mean_accepted"] <= 4.0

    @pytest.mark.parametrize("k", [1, 3, 5])
    def test_exact_for_any_lookahead(self, setup, k):
        config, target, draft_cfg, draft, prompt = setup
        want = np.asarray(generate(target, prompt, config, max_new_tokens=7))
        got, _ = speculative_generate(
            target, draft, prompt, config, draft_cfg, max_new_tokens=7, k=k
        )
        np.testing.assert_array_equal(np.asarray(got), want)

    def test_eos_freezes_rows(self, setup):
        config, target, draft_cfg, draft, prompt = setup
        free = np.asarray(generate(target, prompt, config, max_new_tokens=8))
        eos = int(free[0, 2])
        want = np.asarray(
            generate(target, prompt, config, max_new_tokens=8, eos_id=eos)
        )
        got, _ = speculative_generate(
            target, draft, prompt, config, draft_cfg,
            max_new_tokens=8, k=3, eos_id=eos,
        )
        np.testing.assert_array_equal(np.asarray(got), want)
