"""Gemma family: the same decoder skeleton as Llama with four dialect
switches — gelu gated MLP, (1 + w) RMSNorm, sqrt(d_model)-scaled
embeddings, tied unembedding — plus MQA and an explicit head dim.
A randomly initialized tiny transformers Gemma is the parity oracle
(same strategy as tests/models/test_convert.py for Llama; reference has
no model stack, SURVEY.md §5)."""
import jax
import jax.numpy as jnp
import numpy as np
import pytest

torch = pytest.importorskip("torch")
transformers = pytest.importorskip("transformers")

from nos_tpu.models.convert import config_from_hf, params_from_hf_state_dict
from nos_tpu.models.generate import generate
from nos_tpu.models.llama import (
    gemma_2b_config,
    init_llama_params,
    llama_forward,
    llama_loss,
    tiny_config,
)


@pytest.fixture(scope="module")
def hf_gemma():
    from transformers import GemmaConfig, GemmaForCausalLM

    torch.manual_seed(0)
    config = GemmaConfig(
        vocab_size=128,
        hidden_size=64,
        intermediate_size=128,
        num_hidden_layers=2,
        num_attention_heads=4,
        num_key_value_heads=1,     # Gemma-2B-style MQA
        head_dim=32,               # != hidden/heads (=16): explicit dim
        max_position_embeddings=64,
        rope_theta=10000.0,
        attention_dropout=0.0,
        hidden_act="gelu_pytorch_tanh",
        hidden_activation="gelu_pytorch_tanh",
    )
    model = GemmaForCausalLM(config)
    model.eval()
    return model


def gemma_tiny_config(**overrides):
    """Gemma dialect on test-sized dims."""
    defaults = dict(
        hidden_act="gelu",
        norm_offset=True,
        scale_embeddings=True,
        tie_embeddings=True,
    )
    defaults.update(overrides)
    return tiny_config(**defaults)


class TestGemmaParity:
    def test_config_mapping(self, hf_gemma):
        config = config_from_hf(hf_gemma.config, jnp.float32)
        assert config.hidden_act == "gelu"
        assert config.norm_offset and config.scale_embeddings
        assert config.tie_embeddings
        assert config.head_dim == 32 and config.n_kv_heads == 1

    def test_logits_match_torch(self, hf_gemma):
        config = config_from_hf(hf_gemma.config, jnp.float32)
        params = params_from_hf_state_dict(hf_gemma.state_dict(), config)
        assert "lm_head" not in params  # tied: no separate matrix
        tokens_np = np.array([[1, 5, 9, 42, 17, 99, 3, 64]], dtype=np.int64)
        with torch.no_grad():
            want = hf_gemma(torch.from_numpy(tokens_np)).logits.numpy()
        got = np.asarray(llama_forward(params, jnp.asarray(tokens_np), config))
        np.testing.assert_allclose(got, want, atol=3e-4)

    def test_greedy_generation_matches_torch(self, hf_gemma):
        config = config_from_hf(hf_gemma.config, jnp.float32)
        params = params_from_hf_state_dict(hf_gemma.state_dict(), config)
        prompt_np = np.array([[2, 11, 23, 5]], dtype=np.int64)
        with torch.no_grad():
            want = hf_gemma.generate(
                torch.from_numpy(prompt_np),
                max_new_tokens=6,
                do_sample=False,
                num_beams=1,
            ).numpy()[:, prompt_np.shape[1]:]
        got = np.asarray(
            generate(params, jnp.asarray(prompt_np), config, max_new_tokens=6)
        )
        np.testing.assert_array_equal(got, want)


class TestGemmaDialect:
    def test_flagship_config_shapes(self):
        # 2B init is too big for a unit test; config invariants only.
        config = gemma_2b_config()
        assert config.head_dim == 256
        assert config.n_kv_heads == 1
        assert config.tie_embeddings and config.scale_embeddings
        assert config.norm_offset and config.hidden_act == "gelu"

    def test_tied_params_have_no_lm_head(self):
        config = gemma_tiny_config()
        params = init_llama_params(jax.random.key(0), config)
        assert "lm_head" not in params
        logits = llama_forward(params, jnp.zeros((2, 8), jnp.int32), config)
        assert logits.shape == (2, 8, config.vocab_size)

    def test_trains_end_to_end(self):
        config = gemma_tiny_config(dtype=jnp.float32)
        params = init_llama_params(jax.random.key(0), config)
        tokens = jax.random.randint(jax.random.key(1), (2, 16), 0, config.vocab_size)
        loss, grads = jax.jit(
            jax.value_and_grad(lambda p: llama_loss(p, tokens, config))
        )(params)
        assert jnp.isfinite(loss)
        # tied: embedding grads accumulate both embed and unembed terms
        assert float(jnp.abs(grads["embed"]).max()) > 0

    def test_kv_generation_matches_forward_argmax(self):
        config = gemma_tiny_config(dtype=jnp.float32)
        params = init_llama_params(jax.random.key(0), config)
        prompt = jnp.asarray([[3, 7, 11, 2]], jnp.int32)
        out = generate(params, prompt, config, max_new_tokens=4)
        # oracle: recompute each step with the cache-free forward
        seq = prompt
        for _ in range(4):
            logits = llama_forward(params, seq, config)
            nxt = jnp.argmax(logits[:, -1], axis=-1)[:, None].astype(jnp.int32)
            seq = jnp.concatenate([seq, nxt], axis=1)
        np.testing.assert_array_equal(np.asarray(out), np.asarray(seq[:, 4:]))

    def test_quantized_tied_serving(self):
        from nos_tpu.models.quantize import quantize_params, weight_bytes

        config = gemma_tiny_config(dtype=jnp.float32)
        params = init_llama_params(jax.random.key(0), config)
        qparams = quantize_params(params)
        assert "lm_head" not in qparams
        assert weight_bytes(qparams) < weight_bytes(params)
        prompt = jnp.asarray([[3, 7, 11, 2]], jnp.int32)
        out = generate(params, prompt, config, max_new_tokens=4)
        qout = generate(qparams, prompt, config, max_new_tokens=4)
        assert np.asarray(out).shape == np.asarray(qout).shape

    def test_quantized_tied_pipeline_forward(self):
        """Regression (review): tied + quantized params through the
        pipeline path must not crash on the transposed unembedding."""
        from nos_tpu.models.quantize import quantize_params
        from nos_tpu.parallel.mesh import mesh_from_devices
        from nos_tpu.parallel.pipeline import (
            pipeline_llama_forward,
            stack_layer_params,
        )

        config = gemma_tiny_config(dtype=jnp.float32, n_layers=2)
        params = init_llama_params(jax.random.key(0), config)
        qparams = quantize_params(params)
        mesh = mesh_from_devices((2,), ("pp",), jax.devices()[:2])
        stacked = dict(qparams)
        stacked["layers"] = stack_layer_params(params)["layers"]  # bf16 layers
        tokens = jnp.zeros((2, 8), jnp.int32)
        # full tree quantized layers don't stack (pytree leaves differ);
        # exercise the unembed path with the plain stacked tree + tied
        # quantized embed/unembed.
        stacked["embed"] = qparams["embed"]
        logits = pipeline_llama_forward(stacked, tokens, config, mesh)
        assert logits.shape == (2, 8, config.vocab_size)

    def test_gemma_bf16_norm_offset_not_quantized_away(self):
        """Regression (review): (1 + w) must be applied in f32 — in bf16 a
        0.01 norm weight would round into ~0.0078 steps around 1.0."""
        from nos_tpu.models.llama import _rms_norm

        x = jnp.full((1, 4, 64), 3.0, jnp.bfloat16)
        w_small = jnp.full((64,), 0.01, jnp.bfloat16)
        with_offset = _rms_norm(x, w_small, 1e-6, offset=True)
        plain = _rms_norm(x, jnp.zeros((64,), jnp.bfloat16), 1e-6, offset=True)
        # the 1% weight must actually move the output
        assert float(jnp.abs(
            with_offset.astype(jnp.float32) - plain.astype(jnp.float32)
        ).max()) > 0
