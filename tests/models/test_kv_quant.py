"""int8 KV cache: half the cache bytes, bounded quality loss.

KV quantization is LOSSY by design, so the contract is different from
every other serving feature: byte halving is exact (asserted), logits
stay close to the bf16-cache engine (asserted with tolerance), and the
decode paths (padded + chunked admission, slot reuse, rolling) must
run and produce plausible streams — token-exactness is NOT promised.
"""
import jax
import jax.numpy as jnp
import numpy as np
import pytest

from nos_tpu.models.generate import (
    decode_step,
    init_kv_cache,
    prefill,
)
from nos_tpu.models.llama import init_llama_params, tiny_config
from nos_tpu.serve import Engine, GenRequest, SpecEngine


@pytest.fixture(scope="module")
def setup():
    config = tiny_config(dtype=jnp.float32)
    params = init_llama_params(jax.random.key(0), config)
    return config, params


def cache_bytes(cache):
    return sum(
        arr.size * arr.dtype.itemsize for l in cache for arr in l.values()
    )


class TestKvQuant:
    def test_cache_bytes_roughly_halve(self, setup):
        config, _ = setup
        full = init_kv_cache(config, 4, 128)
        q8 = init_kv_cache(config, 4, 128, quant=True)
        # f32 reference: int8 cuts 4x on values, scales add 1/hd overhead
        ratio = cache_bytes(q8) / cache_bytes(full)
        assert ratio < 0.5, ratio

    def test_decode_logits_close_to_full_precision(self, setup):
        """One prefill + one decode step, quantized vs full cache: the
        logits must agree to the ~1% KV-quant noise floor — enough that
        most argmaxes survive."""
        config, params = setup
        prompt = jnp.asarray(
            [np.random.RandomState(0).randint(1, 256, 24).tolist()], jnp.int32
        )
        logits_f, cache_f = prefill(params, prompt, config, 64)
        logits_q, cache_q = prefill(params, prompt, config, 64, quant=True)
        # prefill logits are computed from the exact fresh K/V: identical
        assert jnp.allclose(logits_f, logits_q), "prefill must stay exact"
        tok = jnp.argmax(logits_f[:, -1], axis=-1).astype(jnp.int32)
        pos = jnp.asarray([24], jnp.int32)
        lf, _ = decode_step(params, cache_f, pos, tok, config)
        lq, _ = decode_step(params, cache_q, pos, tok, config)
        scale = float(jnp.max(jnp.abs(lf)))
        err = float(jnp.max(jnp.abs(lf - lq))) / scale
        assert err < 0.05, f"relative logit error {err:.3f}"

    def test_engine_serves_mixed_workload(self, setup):
        config, params = setup
        eng = Engine(params, config, max_slots=2, max_len=64,
                     ticks_per_sync=4, prefill_chunk=8, kv_quant=True)
        prompts = [
            np.random.RandomState(i).randint(1, 256, n).tolist()
            for i, n in enumerate((5, 20, 11))
        ]
        ids = [eng.submit(GenRequest(prompt=p, max_new_tokens=6))
               for p in prompts]
        got = eng.run()
        assert all(len(got[i]) == 6 for i in ids)
        assert all(0 <= t < config.vocab_size for i in ids for t in got[i])

    def test_rolling_composes_with_kv_quant(self, setup):
        config, _ = setup
        wcfg = tiny_config(dtype=jnp.float32, sliding_window=16)
        params = init_llama_params(jax.random.key(0), wcfg)
        eng = Engine(params, wcfg, max_slots=1, max_len=33,
                     ticks_per_sync=4, prefill_chunk=8,
                     rolling=True, kv_quant=True)
        p = np.random.RandomState(3).randint(1, 256, 30).tolist()
        rid = eng.submit(GenRequest(prompt=p, max_new_tokens=60))
        got = eng.run()[rid]
        assert len(got) == 60

    def test_guards(self, setup):
        config, params = setup
        draft_cfg = tiny_config(n_layers=1, dtype=jnp.float32)
        draft = init_llama_params(jax.random.key(1), draft_cfg)
        with pytest.raises(ValueError, match="KV cache"):
            SpecEngine(params, config, draft, draft_cfg, max_len=64,
                       kv_quant=True)

    def test_solo_generate_kv_quant(self, setup):
        """generate(kv_quant=True) runs the whole solo path on an int8
        cache (decode_step auto-detects); lengths and vocab bounds hold,
        and the stream tracks the full-precision run closely."""
        config, params = setup
        prompt = jnp.asarray(
            [np.random.RandomState(7).randint(1, 256, 12).tolist()],
            jnp.int32,
        )
        from nos_tpu.models.generate import generate

        full = np.asarray(generate(params, prompt, config, max_new_tokens=10))
        q8 = np.asarray(
            generate(params, prompt, config, max_new_tokens=10, kv_quant=True)
        )
        assert q8.shape == (1, 10)
        assert ((0 <= q8) & (q8 < config.vocab_size)).all()
        agree = (full == q8).mean()
        assert agree >= 0.5, f"only {agree:.0%} token agreement"
