"""Sliding-window attention (Mistral family): torch transformers is the
oracle, and the cached serving paths must agree with the windowed
forward."""
import jax
import jax.numpy as jnp
import numpy as np
import pytest

torch = pytest.importorskip("torch")
transformers = pytest.importorskip("transformers")

from nos_tpu.models.convert import load_hf_llama
from nos_tpu.models.generate import generate, prefill, reference_generate
from nos_tpu.models.llama import init_llama_params, llama_forward, tiny_config

WINDOW = 6


@pytest.fixture(scope="module")
def hf_mistral():
    from transformers import MistralConfig, MistralForCausalLM

    torch.manual_seed(0)
    config = MistralConfig(
        vocab_size=128,
        hidden_size=64,
        intermediate_size=128,
        num_hidden_layers=2,
        num_attention_heads=8,
        num_key_value_heads=4,
        max_position_embeddings=64,
        rope_theta=10000.0,
        sliding_window=WINDOW,
        attention_dropout=0.0,
    )
    model = MistralForCausalLM(config)
    model.eval()
    return model


class TestSlidingWindow:
    def test_window_wider_than_sequence_is_full_attention(self):
        config = tiny_config()
        windowed = tiny_config(sliding_window=64)
        params = init_llama_params(jax.random.key(0), config)
        tokens = jax.random.randint(jax.random.key(1), (2, 12), 0, config.vocab_size)
        np.testing.assert_array_equal(
            np.asarray(llama_forward(params, tokens, config)),
            np.asarray(llama_forward(params, tokens, windowed)),
        )

    def test_window_changes_logits_beyond_band(self):
        config = tiny_config()
        windowed = tiny_config(sliding_window=4)
        params = init_llama_params(jax.random.key(0), config)
        tokens = jax.random.randint(jax.random.key(1), (1, 16), 0, config.vocab_size)
        full = np.asarray(llama_forward(params, tokens, config))
        band = np.asarray(llama_forward(params, tokens, windowed))
        # inside the band identical, beyond it different
        np.testing.assert_allclose(full[:, :4], band[:, :4], atol=1e-5)
        assert not np.allclose(full[:, -1], band[:, -1])

    def test_mistral_logits_match_torch(self, hf_mistral):
        params, config = load_hf_llama(hf_mistral, dtype=jnp.float32)
        assert config.sliding_window == WINDOW
        # sequence twice the window so the band actually truncates
        tokens_np = np.array(
            [[1, 5, 9, 42, 17, 99, 3, 64, 7, 21, 88, 120, 2, 33, 54, 76]],
            dtype=np.int64,
        )
        with torch.no_grad():
            want = hf_mistral(torch.from_numpy(tokens_np)).logits.numpy()
        got = np.asarray(llama_forward(params, jnp.asarray(tokens_np), config))
        np.testing.assert_allclose(got, want, atol=2e-4)

    def test_windowed_kv_generation_matches_cache_free_oracle(self, hf_mistral):
        params, config = load_hf_llama(hf_mistral, dtype=jnp.float32)
        prompt = jnp.asarray([[2, 11, 23, 5, 77, 41, 8, 19, 101, 64]], jnp.int32)
        want = reference_generate(params, prompt, config, max_new_tokens=8)
        got = generate(params, prompt, config, max_new_tokens=8)
        np.testing.assert_array_equal(np.asarray(want), np.asarray(got))

    def test_windowed_generation_matches_torch(self, hf_mistral):
        params, config = load_hf_llama(hf_mistral, dtype=jnp.float32)
        prompt_np = np.array([[2, 11, 23, 5, 77, 41, 8, 19]], dtype=np.int64)
        with torch.no_grad():
            want = hf_mistral.generate(
                torch.from_numpy(prompt_np),
                max_new_tokens=8,
                do_sample=False,
                num_beams=1,
            ).numpy()[:, prompt_np.shape[1]:]
        got = generate(params, jnp.asarray(prompt_np), config, max_new_tokens=8)
        np.testing.assert_array_equal(np.asarray(got), want)

    def test_flash_window_matches_dense_window(self):
        # The kernel's banded mask must agree with the dense windowed path
        # on the whole forward (the band is where blockwise skipping beats
        # dense masking at long context).
        dense_cfg = tiny_config(sliding_window=4, dtype=jnp.float32)
        flash_cfg = tiny_config(
            sliding_window=4, attention="flash", dtype=jnp.float32
        )
        params = init_llama_params(jax.random.key(0), dense_cfg)
        tokens = jax.random.randint(
            jax.random.key(1), (2, 16), 0, dense_cfg.vocab_size
        )
        want = llama_forward(params, tokens, dense_cfg)
        got = llama_forward(params, tokens, flash_cfg)
        assert jnp.allclose(got, want, atol=2e-4), float(jnp.abs(got - want).max())

    def test_left_padded_prefill_rejected(self):
        config = tiny_config(sliding_window=4)
        params = init_llama_params(jax.random.key(0), config)
        with pytest.raises(ValueError):
            prefill(params, jnp.zeros((1, 8), jnp.int32), config, 16, pad_id=0)

    def test_engine_serves_windowed_config(self):
        from nos_tpu.serve import Engine, GenRequest

        config = tiny_config(sliding_window=6)
        params = init_llama_params(jax.random.key(0), config)
        prompt = np.asarray(
            jax.random.randint(jax.random.key(2), (10,), 1, config.vocab_size)
        ).tolist()
        want = np.asarray(
            generate(params, jnp.asarray([prompt], jnp.int32), config, max_new_tokens=5)
        )[0].tolist()
        eng = Engine(params, config, max_slots=2, max_len=64)
        rid = eng.submit(GenRequest(prompt=prompt, max_new_tokens=5))
        assert eng.run()[rid] == want
