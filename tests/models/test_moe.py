"""MoE layer: routing exactness, capacity drops, expert-parallel training."""
import jax
import jax.numpy as jnp

from nos_tpu.models.llama import init_llama_params, tiny_config
from nos_tpu.models.moe import (
    MoeConfig,
    capacity_per_expert,
    init_moe_params,
    moe_mlp,
)
from nos_tpu.parallel.mesh import mesh_from_devices
from nos_tpu.parallel.train import make_train_step


def f32_config(**kw):
    defaults = dict(d_model=16, d_ff=32, n_experts=4, top_k=2, dtype=jnp.float32)
    defaults.update(kw)
    return MoeConfig(**defaults)


def reference_moe(params, x, config):
    """Per-token loop: softmax-route, run the top-k experts densely, no
    capacity limit — ground truth when nothing is dropped."""
    c = config
    b, s, d = x.shape
    flat = x.reshape(-1, d)
    logits = flat.astype(jnp.float32) @ params["router"]
    probs = jax.nn.softmax(logits, axis=-1)
    top_p, top_e = jax.lax.top_k(probs, c.top_k)
    top_p = top_p / jnp.sum(top_p, axis=-1, keepdims=True)

    def expert(e, t):
        gate = flat[t] @ params["w_gate"][e]
        up = flat[t] @ params["w_up"][e]
        return (jax.nn.silu(gate) * up) @ params["w_down"][e]

    out = jnp.zeros_like(flat)
    for t in range(flat.shape[0]):
        for j in range(c.top_k):
            out = out.at[t].add(top_p[t, j] * expert(top_e[t, j], t))
    return out.reshape(b, s, d)


class TestMoeMlp:
    def test_matches_reference_when_capacity_ample(self):
        config = f32_config(capacity_factor=8.0)  # nothing dropped
        params = init_moe_params(jax.random.key(0), config)
        x = jax.random.normal(jax.random.key(1), (2, 4, config.d_model), jnp.float32)
        got = moe_mlp(params, x, config)
        want = reference_moe(params, x, config)
        assert jnp.allclose(got, want, atol=1e-5), float(jnp.abs(got - want).max())

    def test_capacity_drops_are_bounded_and_finite(self):
        config = f32_config(capacity_factor=0.25)  # forced overflow
        params = init_moe_params(jax.random.key(0), config)
        x = jax.random.normal(jax.random.key(2), (2, 8, config.d_model), jnp.float32)
        out = moe_mlp(params, x, config)
        assert out.shape == x.shape
        assert bool(jnp.all(jnp.isfinite(out)))
        # a dropped token contributes zero, not garbage
        assert float(jnp.abs(out).max()) < 1e3

    def test_capacity_math(self):
        assert capacity_per_expert(8, f32_config(capacity_factor=1.0)) == 4
        assert capacity_per_expert(1, f32_config(capacity_factor=0.01)) == 1

    def test_aux_loss_uniform_vs_collapsed(self):
        """Balanced routing scores ~1; a router collapsed onto one expert
        scores ~E — the signal that keeps static capacity effective."""
        config = f32_config(capacity_factor=8.0)
        params = init_moe_params(jax.random.key(0), config)
        x = jax.random.normal(jax.random.key(6), (2, 16, config.d_model), jnp.float32)
        _, aux_balanced = moe_mlp(params, x, config, return_aux=True)

        collapsed = dict(params)
        collapsed["router"] = jnp.zeros_like(params["router"]).at[:, 0].set(10.0)
        forced = x.at[..., :].set(jnp.abs(x))  # keep router input nonzero
        _, aux_collapsed = moe_mlp(collapsed, forced, config, return_aux=True)

        assert float(aux_balanced) < 2.0
        assert float(aux_collapsed) > 0.8 * config.n_experts

    def test_llama_loss_includes_aux_term(self):
        from nos_tpu.models.llama import llama_loss

        base = tiny_config(n_experts=4, moe_capacity_factor=8.0)
        no_aux = tiny_config(n_experts=4, moe_capacity_factor=8.0, moe_aux_coef=0.0)
        params = init_llama_params(jax.random.key(0), base)
        tokens = jax.random.randint(jax.random.key(7), (2, 16), 0, base.vocab_size)
        with_aux = float(llama_loss(params, tokens, base))
        without = float(llama_loss(params, tokens, no_aux))
        assert with_aux > without

    def test_gradients_flow_to_router_and_experts(self):
        config = f32_config(capacity_factor=4.0)
        params = init_moe_params(jax.random.key(0), config)
        x = jax.random.normal(jax.random.key(3), (1, 4, config.d_model), jnp.float32)

        def loss(p):
            return jnp.sum(moe_mlp(p, x, config) ** 2)

        grads = jax.grad(loss)(params)
        for name in ("router", "w_gate", "w_up", "w_down"):
            assert float(jnp.abs(grads[name]).max()) > 0, name


class TestExpertParallelTraining:
    def test_dp_ep_mesh_step(self):
        config = tiny_config(n_experts=4, moe_capacity_factor=2.0)
        params = init_llama_params(jax.random.key(0), config)
        mesh = mesh_from_devices((2, 4), ("dp", "ep"))
        step, shard_state = make_train_step(mesh, config)
        state = shard_state(params)
        tokens = jax.random.randint(jax.random.key(4), (4, 16), 0, config.vocab_size)
        state, loss = step(state, tokens)
        assert jnp.isfinite(loss)
        # expert weights actually sharded over ep
        w = state[0]["layers"][0]["moe"]["w_gate"]
        assert w.sharding.spec[0] == "ep"

    def test_ep_loss_matches_single_device(self):
        config = tiny_config(n_experts=4, moe_capacity_factor=8.0)
        tokens = jax.random.randint(jax.random.key(5), (4, 16), 0, config.vocab_size)

        mesh1 = mesh_from_devices((1, 1), ("dp", "tp"), jax.devices()[:1])
        step1, shard1 = make_train_step(mesh1, config)
        _, loss1 = step1(shard1(init_llama_params(jax.random.key(0), config)), tokens)

        mesh_ep = mesh_from_devices((2, 4), ("dp", "ep"))
        step2, shard2 = make_train_step(mesh_ep, config)
        _, loss2 = step2(shard2(init_llama_params(jax.random.key(0), config)), tokens)
        assert abs(float(loss1) - float(loss2)) < 3e-2


class TestTokenMask:
    """token_mask: padding columns are invisible to the mixture — no
    capacity claims, zero output, no aux-loss contribution."""

    def test_masked_columns_output_zero_and_dont_perturb_real_tokens(self):
        cfg = MoeConfig(d_model=16, d_ff=32, n_experts=4, top_k=2,
                        capacity_factor=8.0, dtype=jnp.float32)
        params = init_moe_params(jax.random.key(0), cfg)
        h = jax.random.normal(jax.random.key(1), (1, 6, 16), jnp.float32)
        mask = jnp.asarray([[True, True, True, True, False, False]])
        out = moe_mlp(params, h, cfg, token_mask=mask)
        assert jnp.all(out[0, 4:] == 0), "masked columns must output zero"
        # overflow-free capacity: real tokens must be bit-identical to a
        # call that never saw the pad columns
        out_ref = moe_mlp(params, h[:, :4], cfg)
        assert jnp.array_equal(out[0, :4], out_ref[0]), (
            "pad columns perturbed real tokens"
        )

    def test_pads_claim_no_capacity_when_it_binds(self):
        """With capacity 1 and pads routed FIRST (cumsum order), an
        unmasked pad would displace the real token behind it; the mask
        must keep the real token dispatched."""
        cfg = MoeConfig(d_model=16, d_ff=32, n_experts=2, top_k=1,
                        capacity_factor=0.01, dtype=jnp.float32)  # cap=1
        params = init_moe_params(jax.random.key(2), cfg)
        h = jax.random.normal(jax.random.key(3), (1, 3, 16), jnp.float32)
        # duplicate column 2's embedding into cols 0/1 so all three route
        # to the same expert; cols 0/1 are pads
        h = h.at[:, 0].set(h[:, 2]).at[:, 1].set(h[:, 2])
        mask = jnp.asarray([[False, False, True]])
        out = moe_mlp(params, h, cfg, token_mask=mask)
        unpadded = moe_mlp(params, h[:, 2:], cfg)
        assert jnp.array_equal(out[0, 2], unpadded[0, 0]), (
            "pad displaced the real token from expert capacity"
        )
        assert jnp.any(out[0, 2] != 0)

    def test_aux_loss_excludes_masked_tokens(self):
        cfg = MoeConfig(d_model=16, d_ff=32, n_experts=4, top_k=2,
                        capacity_factor=8.0, dtype=jnp.float32)
        params = init_moe_params(jax.random.key(4), cfg)
        h = jax.random.normal(jax.random.key(5), (1, 6, 16), jnp.float32)
        mask = jnp.asarray([[True] * 4 + [False] * 2])
        _, aux_masked = moe_mlp(params, h, cfg, return_aux=True,
                                token_mask=mask)
        _, aux_ref = moe_mlp(params, h[:, :4], cfg, return_aux=True)
        assert jnp.allclose(aux_masked, aux_ref, atol=1e-6)
