"""Seeded randomized workloads across engine feature combinations.

Directed tests pin each feature's contract; this fuzz drives the
INTERACTIONS (sampling rows next to greedy eos rows over stacked
adapters; rolling + streaming + mid-run submits) and checks the
invariants that must hold for any workload:
  - every submitted request completes exactly once,
  - lengths respect budgets (== without eos, <= with),
  - streamed tokens equal returned completions,
  - tokens stay in-vocab,
  - the engine ends drained (no live slots, queue empty).
Deterministic per seed — failures reproduce.
"""
import jax
import jax.numpy as jnp
import numpy as np
import pytest

from nos_tpu.models.llama import init_llama_params, tiny_config
from nos_tpu.models.lora import LoraConfig, init_lora_params, stack_lora_adapters
from nos_tpu.serve import Engine, GenRequest


@pytest.fixture(scope="module")
def base():
    config = tiny_config(dtype=jnp.float32)
    params = init_llama_params(jax.random.key(0), config)
    return config, params


def run_fuzz(eng, config, rng, n_req, adapters=0, mid_run_submits=True,
             allow_sampling=True):
    streamed = {}

    def cb(rid, tok):
        streamed.setdefault(rid, []).append(tok)

    def make_request():
        n = int(rng.integers(1, 28))
        budget = int(rng.integers(1, 20))
        req = GenRequest(
            prompt=rng.integers(1, config.vocab_size, n).tolist(),
            max_new_tokens=budget,
        )
        if rng.random() < 0.3:
            req.eos_id = int(rng.integers(1, config.vocab_size))
        if allow_sampling and rng.random() < 0.3:
            req.temperature = float(rng.random() * 1.2)
            req.top_k = int(rng.integers(0, 50))
            req.top_p = float(0.5 + rng.random() * 0.5)
        if rng.random() < 0.4:
            req.on_token = cb
        if adapters and rng.random() < 0.6:
            req.adapter = int(rng.integers(0, adapters + 1))
        return req

    ids, budgets, has_eos, wants_stream = [], {}, {}, {}

    def submit(r):
        rid = eng.submit(r)
        ids.append(rid)
        budgets[rid] = r.max_new_tokens
        has_eos[rid] = r.eos_id is not None
        wants_stream[rid] = r.on_token is not None
        return rid

    for _ in range(n_req):
        submit(make_request())
    if mid_run_submits:
        eng.step(chunks=None)
        for _ in range(3):
            submit(make_request())
    got = eng.run()
    assert sorted(got) == sorted(ids), "every request completes exactly once"
    for rid in ids:
        toks = got[rid]
        if has_eos[rid]:
            assert 1 <= len(toks) <= budgets[rid], (rid, len(toks))
        else:
            assert len(toks) == budgets[rid], (rid, len(toks))
        assert all(0 <= t < config.vocab_size for t in toks)
        if wants_stream[rid]:
            # unconditional: a dead streaming path must fail, not skip
            assert streamed.get(rid, []) == toks, f"stream diverged for {rid}"
    assert not eng._queue and all(s is None for s in eng._slots)


class TestEngineFuzz:
    @pytest.mark.parametrize("seed", [0, 1])
    def test_plain_engine(self, base, seed):
        config, params = base
        rng = np.random.default_rng(seed)
        eng = Engine(params, config, max_slots=3, max_len=64,
                     ticks_per_sync=int(rng.integers(2, 6)),
                     prefill_chunk=8)
        run_fuzz(eng, config, rng, n_req=8)

    def test_multi_lora_mixed(self, base):
        config, params = base
        rng = np.random.default_rng(7)
        lora = LoraConfig(rank=4)
        ads = [init_lora_params(jax.random.key(90 + i), config, lora)
               for i in range(2)]
        stacked = stack_lora_adapters(params, ads, lora, rows=3)
        eng = Engine(stacked, config, max_slots=3, max_len=64,
                     ticks_per_sync=4, prefill_chunk=8)
        run_fuzz(eng, config, rng, n_req=8, adapters=2)

    def test_rolling_windowed(self, base):
        config, _ = base
        wcfg = tiny_config(dtype=jnp.float32, sliding_window=16)
        params = init_llama_params(jax.random.key(0), wcfg)
        rng = np.random.default_rng(11)
        eng = Engine(params, wcfg, max_slots=2, max_len=33,
                     ticks_per_sync=4, prefill_chunk=8, rolling=True)
        run_fuzz(eng, wcfg, rng, n_req=6)

    def test_kv_quant(self, base):
        config, params = base
        rng = np.random.default_rng(13)
        eng = Engine(params, config, max_slots=2, max_len=64,
                     ticks_per_sync=4, prefill_chunk=8, kv_quant=True)
        run_fuzz(eng, config, rng, n_req=6)

    def test_spec_engine(self, base):
        """Speculative engine under a randomized greedy workload (spec
        rejects sampling at submit; eos/streaming/mid-run all apply)."""
        from nos_tpu.serve import SpecEngine

        config, params = base
        draft_cfg = tiny_config(n_layers=1, dtype=jnp.float32)
        draft = init_llama_params(jax.random.key(77), draft_cfg)
        rng = np.random.default_rng(17)
        eng = SpecEngine(params, config, draft, draft_cfg, k=3,
                         max_slots=2, max_len=64)
        run_fuzz(eng, config, rng, n_req=6, allow_sampling=False)
