"""Multi-tenant LoRA serving: per-request adapters over one shared base.

Oracle: for each request, a plain Engine over merge_lora(base, its
adapter) — co-tenants running DIFFERENT adapters in the same batch must
each see exactly their own fine-tune (and adapter 0 the bare base).
"""
import jax
import jax.numpy as jnp
import numpy as np
import pytest

from nos_tpu.models.llama import init_llama_params, tiny_config
from nos_tpu.models.lora import (
    LoraConfig,
    init_lora_params,
    merge_lora,
    stack_lora_adapters,
)
from nos_tpu.serve import Engine, GenRequest, SpecEngine


@pytest.fixture(scope="module")
def setup():
    config = tiny_config(dtype=jnp.float32)
    base = init_llama_params(jax.random.key(0), config)
    lora = LoraConfig(rank=4, targets=("wq", "wv", "w_down"))
    adapters = []
    for i in range(2):
        ad = init_lora_params(jax.random.key(10 + i), config, lora)
        # b initializes to zero (identity); give each adapter a distinct
        # non-trivial delta so the fine-tunes actually diverge
        ad = jax.tree.map(
            lambda x: x + 0.05 * (i + 1) * jnp.sign(jnp.sin(jnp.arange(x.size, dtype=jnp.float32).reshape(x.shape))),
            ad,
        )
        adapters.append(ad)
    return config, base, lora, adapters


def rand_prompt(key, n, vocab):
    return np.asarray(jax.random.randint(key, (n,), 1, vocab)).tolist()


def oracle(params, config, prompt, n):
    eng = Engine(params, config, max_slots=1, max_len=64, ticks_per_sync=4)
    rid = eng.submit(GenRequest(prompt=prompt, max_new_tokens=n))
    return eng.run()[rid]


class TestMultiLoraServing:
    def test_cotenants_each_get_their_own_adapter(self, setup):
        config, base, lora, adapters = setup
        stacked = stack_lora_adapters(base, adapters, lora, rows=3)
        prompts = [rand_prompt(jax.random.key(30 + i), 5 + 3 * i, config.vocab_size)
                   for i in range(3)]
        wants = [
            oracle(base, config, prompts[0], 7),                        # adapter 0
            oracle(merge_lora(base, adapters[0], lora), config, prompts[1], 7),
            oracle(merge_lora(base, adapters[1], lora), config, prompts[2], 7),
        ]
        # adapters must actually change the output, or the test is vacuous
        assert wants[1] != wants[0] or wants[2] != wants[0]
        eng = Engine(stacked, config, max_slots=3, max_len=64,
                     ticks_per_sync=4)
        ids = [eng.submit(GenRequest(prompt=p, max_new_tokens=7, adapter=a))
               for p, a in zip(prompts, (0, 1, 2))]
        got = eng.run()
        assert [got[i] for i in ids] == wants

    def test_slot_reuse_switches_adapters(self, setup):
        """A slot serving adapter 1 then re-admitting adapter 2: the
        selector must follow the tenant, not the slot's history."""
        config, base, lora, adapters = setup
        stacked = stack_lora_adapters(base, adapters, lora, rows=1)
        p = rand_prompt(jax.random.key(40), 6, config.vocab_size)
        w1 = oracle(merge_lora(base, adapters[0], lora), config, p, 5)
        w2 = oracle(merge_lora(base, adapters[1], lora), config, p, 5)
        eng = Engine(stacked, config, max_slots=1, max_len=64,
                     ticks_per_sync=4)
        r1 = eng.submit(GenRequest(prompt=p, max_new_tokens=5, adapter=1))
        r2 = eng.submit(GenRequest(prompt=p, max_new_tokens=5, adapter=2))
        got = eng.run()
        assert got[r1] == w1 and got[r2] == w2

    def test_chunked_admission_applies_adapter(self, setup):
        config, base, lora, adapters = setup
        stacked = stack_lora_adapters(base, adapters, lora, rows=2)
        p = rand_prompt(jax.random.key(41), 20, config.vocab_size)
        want = oracle(merge_lora(base, adapters[1], lora), config, p, 6)
        eng = Engine(stacked, config, max_slots=2, max_len=64,
                     ticks_per_sync=4, prefill_chunk=8)
        rid = eng.submit(GenRequest(prompt=p, max_new_tokens=6, adapter=2))
        assert eng.run()[rid] == want

    def test_adapter_validation(self, setup):
        config, base, lora, adapters = setup
        stacked = stack_lora_adapters(base, adapters, lora, rows=1)
        eng = Engine(stacked, config, max_slots=1, max_len=64)
        with pytest.raises(ValueError, match="adapter"):
            eng.submit(GenRequest(prompt=[3], max_new_tokens=2, adapter=5))
        # plain tree: any non-zero adapter is an error
        plain = Engine(base, config, max_slots=1, max_len=64)
        with pytest.raises(ValueError, match="adapter"):
            plain.submit(GenRequest(prompt=[3], max_new_tokens=2, adapter=1))
        # speculation rejects stacked trees
        draft_cfg = tiny_config(n_layers=1, dtype=jnp.float32)
        draft = init_llama_params(jax.random.key(1), draft_cfg)
        with pytest.raises(ValueError, match="LoRA"):
            SpecEngine(stacked, config, draft, draft_cfg, max_len=64)
