"""Mixtral (routed-MoE Mistral) conversion parity against torch.

The converter maps block_sparse_moe (router gate + per-expert w1/w3/w2)
onto this stack's stacked-expert MoE layer. Routing math differs only
syntactically (mistral-inference: top-k then softmax; HF transformers
and this stack: softmax then top-k renormalize — identical by
monotonicity), so logits must match torch to
float tolerance WHEN no expert overflows — parity runs with a generous
capacity factor (static capacity is this stack's own TPU discipline;
torch gathers densely).
"""
import dataclasses

import jax.numpy as jnp
import numpy as np
import pytest
import torch

from nos_tpu.models.convert import load_hf_llama
from nos_tpu.models.llama import llama_forward
from nos_tpu.models.generate import generate


@pytest.fixture(scope="module")
def hf_mixtral():
    from transformers import MixtralConfig, MixtralForCausalLM

    torch.manual_seed(0)
    config = MixtralConfig(
        vocab_size=128,
        hidden_size=64,
        intermediate_size=96,
        num_hidden_layers=2,
        num_attention_heads=8,
        num_key_value_heads=4,
        num_local_experts=4,
        num_experts_per_tok=2,
        max_position_embeddings=64,
        rope_theta=10000.0,
        sliding_window=None,
        attention_dropout=0.0,
    )
    model = MixtralForCausalLM(config)
    model.eval()
    return model


@pytest.fixture(scope="module")
def converted(hf_mixtral):
    params, config = load_hf_llama(hf_mixtral, dtype=jnp.float32)
    # torch gathers every routed token densely; overflow-free capacity is
    # the documented parity precondition for the static-capacity MoE
    config = dataclasses.replace(config, moe_capacity_factor=8.0)
    return params, config


class TestMixtralConversion:
    def test_config_carries_moe(self, converted):
        _, config = converted
        assert config.n_experts == 4 and config.moe_top_k == 2

    def test_logits_match_torch(self, hf_mixtral, converted):
        params, config = converted
        tokens_np = np.random.RandomState(0).randint(1, 128, (2, 12))
        got = np.asarray(
            llama_forward(params, jnp.asarray(tokens_np, jnp.int32), config)
        )
        with torch.no_grad():
            want = hf_mixtral(torch.from_numpy(tokens_np)).logits.numpy()
        np.testing.assert_allclose(got, want, atol=3e-4)

    def test_greedy_generation_matches_torch(self, hf_mixtral, converted):
        params, config = converted
        prompt_np = np.random.RandomState(1).randint(1, 128, (1, 7))
        got = np.asarray(
            generate(params, jnp.asarray(prompt_np, jnp.int32), config,
                     max_new_tokens=8)
        )[0].tolist()
        with torch.no_grad():
            out = hf_mixtral.generate(
                torch.from_numpy(prompt_np), max_new_tokens=8,
                do_sample=False,
            )
        assert got == out[0, 7:].tolist()

    def test_serves_through_engine(self, converted):
        from nos_tpu.serve import Engine, GenRequest

        params, config = converted
        eng = Engine(params, config, max_slots=2, max_len=64,
                     ticks_per_sync=4)
        p = np.random.RandomState(2).randint(1, 128, 9).tolist()
        rid = eng.submit(GenRequest(prompt=p, max_new_tokens=6))
        solo = np.asarray(
            generate(params, jnp.asarray([p], jnp.int32), config,
                     max_new_tokens=6)
        )[0].tolist()
        assert eng.run()[rid] == solo

    def test_int8_quantized_mixtral_serves(self, converted):
        """Converted Mixtral + weight-only int8 (expert stacks quantize
        per-(expert, channel)) through the engine."""
        from nos_tpu.models.quantize import quantize_params
        from nos_tpu.serve import Engine, GenRequest

        params, config = converted
        qparams = quantize_params(params)
        eng = Engine(qparams, config, max_slots=2, max_len=64,
                     ticks_per_sync=4)
        p = np.random.RandomState(9).randint(1, 128, 6).tolist()
        rid = eng.submit(GenRequest(prompt=p, max_new_tokens=5))
        got = eng.run()[rid]
        assert len(got) == 5
        assert all(0 <= t < config.vocab_size for t in got)
