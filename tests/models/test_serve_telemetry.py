"""Request-journey telemetry on the real engine: TTFT stamping semantics
(first *emitted* token, not admission), prefix-restore accounting, queue
wait, goodput verdicts, and the journey span taxonomy.

All on the virtual cost clock (tick = 8 ms, prefill token = 0.2 ms) with
explicit ``step(chunks=1)`` rounds, so every latency is exact arithmetic:
a deferred first token pays its prefill PLUS the first decode chunk's
sync; an eagerly-resolved one (budget 1) pays only its prefill.
"""
import jax
import jax.numpy as jnp
import pytest

from nos_tpu.models.llama import init_llama_params, tiny_config
from nos_tpu.serve.engine import Engine, GenRequest
from nos_tpu.serve.telemetry import ServeTelemetry, VirtualServeClock
from nos_tpu.util import metrics
from nos_tpu.util.tracing import TRACER

TICK = 0.008
TOK = 0.0002
TICKS_PER_SYNC = 4
CHUNK_S = TICKS_PER_SYNC * TICK  # one decode chunk between syncs


@pytest.fixture(scope="module")
def setup():
    config = tiny_config(dtype=jnp.float32)
    params = init_llama_params(jax.random.key(0), config)
    return config, params


@pytest.fixture(scope="module")
def engine(setup):
    config, params = setup
    telemetry = ServeTelemetry(model="tm", clock=VirtualServeClock())
    return Engine(
        params, config, max_slots=2, max_len=128,
        ticks_per_sync=TICKS_PER_SYNC, prefill_chunk=16,
        model="tm", telemetry=telemetry,
    )


def drain(engine):
    while engine.busy:
        engine.step(chunks=1)


def prompt_of(n):
    return [(i % 50) + 1 for i in range(n)]


class TestTTFTStamping:
    def test_deferred_first_token_pays_the_decode_chunk(self, engine):
        # 20-token prompt > prefill_chunk -> chunked admission; budget > 1
        # and no eos -> the first token defers into the round's single
        # end-of-chunk pull. TTFT = 20 * 0.2ms prefill + one 4-tick chunk.
        rid = engine.submit(GenRequest(prompt=prompt_of(20), max_new_tokens=6))
        drain(engine)
        rec = engine.telemetry.record(rid)
        assert rec.queue_wait_s == pytest.approx(0.0, abs=1e-12)
        assert rec.ttft_s == pytest.approx(20 * TOK + CHUNK_S)
        # Budget 6 = deferred first + 4 chunk tokens + 1 from a second
        # chunk: retire exactly one chunk after the first token.
        assert rec.tokens == 6
        assert rec.e2e_s == pytest.approx(20 * TOK + 2 * CHUNK_S)
        assert rec.tpot_s == pytest.approx(CHUNK_S / 5)

    def test_eager_first_token_is_prefill_only(self, engine):
        # Budget 1 forces eager resolution: the admission's token is
        # pulled BEFORE any decode chunk runs, so TTFT excludes tick cost.
        rid = engine.submit(GenRequest(prompt=prompt_of(20), max_new_tokens=1))
        drain(engine)
        rec = engine.telemetry.record(rid)
        assert rec.ttft_s == pytest.approx(20 * TOK)
        assert rec.tokens == 1
        assert rec.tpot_s == 0.0
        assert rec.retire_t >= rec.first_token_t

    def test_padded_prefill_costs_the_bucket(self, engine):
        # Short prompt takes the left-padded path: prefill runs the
        # whole pow2 bucket, and the cost model charges what actually ran.
        rid = engine.submit(GenRequest(prompt=prompt_of(5), max_new_tokens=3))
        bucket = engine.telemetry.record(rid).bucket
        assert bucket <= 16  # padded path, not chunked
        drain(engine)
        rec = engine.telemetry.record(rid)
        assert rec.ttft_s == pytest.approx(bucket * TOK + CHUNK_S)

    def test_queue_wait_measured_for_the_request_that_waited(self, engine):
        # 3 requests into 2 slots: the third queues until a slot frees at
        # the first chunk boundary; its wait is real clock time, and its
        # TTFT includes it implicitly (submit -> first token).
        rids = [
            engine.submit(GenRequest(prompt=prompt_of(20), max_new_tokens=4))
            for _ in range(3)
        ]
        drain(engine)
        recs = [engine.telemetry.record(r) for r in rids]
        assert recs[0].queue_wait_s == pytest.approx(0.0, abs=1e-12)
        # Second admits in the same round, after the first's prefill.
        assert recs[1].queue_wait_s == pytest.approx(20 * TOK)
        assert recs[2].queue_wait_s >= CHUNK_S  # waited out a full chunk
        assert recs[2].ttft_s >= recs[2].queue_wait_s + 20 * TOK


class TestPrefixRestoreTTFT:
    def test_prefix_hit_shrinks_ttft_and_is_traced(self, setup):
        config, params = setup
        telemetry = ServeTelemetry(model="pm", clock=VirtualServeClock())
        engine = Engine(
            params, config, max_slots=2, max_len=128,
            ticks_per_sync=TICKS_PER_SYNC, prefill_chunk=16,
            prefix_cache_entries=2, model="pm", telemetry=telemetry,
        )
        prompt = prompt_of(20)
        reused_before = metrics.SERVE_PREFIX_TOKENS_REUSED.value

        cold = engine.submit(GenRequest(prompt=list(prompt), max_new_tokens=4))
        drain(engine)
        hit = engine.submit(GenRequest(prompt=list(prompt), max_new_tokens=4))
        drain(engine)

        cold_rec = telemetry.record(cold)
        hit_rec = telemetry.record(hit)
        # Cold: full 20-token ingest. Hit: 16 tokens restored from cache
        # (the chunk-boundary prefix), only the 4-token tail re-ingested.
        assert cold_rec.ttft_s == pytest.approx(20 * TOK + CHUNK_S)
        assert hit_rec.ttft_s == pytest.approx(4 * TOK + CHUNK_S)
        assert hit_rec.ttft_s < cold_rec.ttft_s
        assert metrics.SERVE_PREFIX_TOKENS_REUSED.value - reused_before == 16

        # The journey shows the restore: a serve.prefix_restore span with
        # the reused token count, alongside the tail's serve.prefill.
        trace = TRACER.store.get(hit_rec.trace_id)
        assert trace is not None
        by_name = {}
        for span in trace.spans:
            by_name.setdefault(span.name, []).append(span)
        assert by_name["serve.prefix_restore"][0].attributes["reused_tokens"] == 16
        assert by_name["serve.prefill"][0].attributes["tokens"] == 4
        # And the cold journey has no restore span.
        cold_trace = TRACER.store.get(cold_rec.trace_id)
        assert all(s.name != "serve.prefix_restore" for s in cold_trace.spans)


class TestJourneySpans:
    def test_full_stage_taxonomy(self, engine):
        rid = engine.submit(GenRequest(prompt=prompt_of(20), max_new_tokens=4))
        drain(engine)
        rec = engine.telemetry.record(rid)
        trace = TRACER.store.get(rec.trace_id)
        assert trace is not None
        names = {s.name for s in trace.spans}
        assert {
            "serve.request", "serve.submit", "serve.queue", "serve.admit",
            "serve.prefill", "serve.decode", "serve.retire",
        } <= names
        root = trace.root
        assert root.name == "serve.request"
        assert root.status == "ok"
        assert root.attributes["request"] == rid
        assert root.attributes["tokens"] == 4
        assert root.attributes["ttft_s"] == pytest.approx(
            rec.ttft_s, abs=1e-6
        )
        # Stage spans nest under the journey root (Dapper-style), so the
        # trace summary decomposes the request's wall time by stage.
        stages = trace.summary()["stages"]
        assert "serve.queue" in stages and "serve.admit" in stages

    def test_record_survives_in_completed_ring(self, engine):
        rid = engine.submit(GenRequest(prompt=prompt_of(8), max_new_tokens=2))
        drain(engine)
        assert rid in engine.telemetry.completed
        assert engine.telemetry.record(rid).tokens == 2


class TestGoodputAndHistograms:
    def test_late_request_counts_against_goodput(self, engine):
        telemetry = engine.telemetry
        late_before = metrics.SERVE_GOODPUT_REQUESTS.labels(
            model="tm", verdict="late"
        ).value
        good_before = metrics.SERVE_GOODPUT_REQUESTS.labels(
            model="tm", verdict="good"
        ).value
        telemetry.ttft_target_s = 1e-6  # unmeetable: one chunk > 1 us
        try:
            rid = engine.submit(
                GenRequest(prompt=prompt_of(20), max_new_tokens=4)
            )
            drain(engine)
        finally:
            telemetry.ttft_target_s = None
        assert telemetry.record(rid).good is False
        late = metrics.SERVE_GOODPUT_REQUESTS.labels(model="tm", verdict="late")
        good = metrics.SERVE_GOODPUT_REQUESTS.labels(model="tm", verdict="good")
        assert late.value - late_before == 1
        assert good.value == good_before

    def test_latency_histograms_labeled_by_model_and_bucket(self, engine):
        rid = engine.submit(GenRequest(prompt=prompt_of(20), max_new_tokens=4))
        drain(engine)
        rec = engine.telemetry.record(rid)
        labels = dict(model="tm", adapter="0", bucket=str(rec.bucket))
        ttft = metrics.SERVE_TTFT.labels(**labels)
        assert ttft.count > 0
        rendered = metrics.REGISTRY.render()
        assert 'nos_tpu_serve_ttft_seconds_count{adapter="0"' in rendered
        assert "nos_tpu_serve_tpot_seconds" in rendered
        assert "nos_tpu_serve_queue_wait_seconds" in rendered
        assert "nos_tpu_serve_goodput_tokens_total" in rendered
