"""Sharing-domain tests (geometry math mirrors reference
pkg/gpu/slicing/gpu_test.go + node_test.go scenarios)."""
from nos_tpu.api.v1alpha1 import annotations as annot
from nos_tpu.api.v1alpha1 import constants, labels
from nos_tpu.kube.objects import Container, Node, NodeStatus, ObjectMeta, Pod, PodSpec
from nos_tpu.tpu.sharing import SharedChip, SharingNode


def mem(gb: int) -> str:
    return constants.tpu_shared_resource(gb)


def sharing_node(
    chips: int = 4,
    accelerator: str = "tpu-v5-lite-podslice",
    annotations: dict | None = None,
) -> Node:
    alloc = {constants.RESOURCE_TPU: chips}
    return Node(
        metadata=ObjectMeta(
            name="shared-0",
            labels={
                labels.GKE_TPU_ACCELERATOR_LABEL: accelerator,
                labels.PARTITIONING_LABEL: "sharing",
            },
            annotations=annotations or {},
        ),
        status=NodeStatus(capacity=dict(alloc), allocatable=dict(alloc)),
    )


def pod_requesting(resources: dict) -> Pod:
    return Pod(
        metadata=ObjectMeta(name="p", namespace="ns"),
        spec=PodSpec(containers=[Container(requests=resources)]),
    )


class TestSharedChip:
    def test_create_from_spare_memory(self):
        chip = SharedChip(0, hbm_gb=16)
        assert chip.update_geometry_for({"8gb": 2})
        assert chip.free == {"8gb": 2}
        assert chip.spare_memory_gb() == 0

    def test_partial_create_when_budget_short(self):
        chip = SharedChip(0, hbm_gb=16)
        assert chip.update_geometry_for({"8gb": 3})
        assert chip.free == {"8gb": 2}

    def test_never_deletes_used_slices(self):
        chip = SharedChip(0, hbm_gb=16, used={"8gb": 1})
        assert chip.update_geometry_for({"16gb": 1}) is False
        assert chip.used == {"8gb": 1}

    def test_sacrifices_free_slices_for_required_profile(self):
        chip = SharedChip(0, hbm_gb=16, free={"8gb": 2})
        assert chip.update_geometry_for({"16gb": 1})
        assert chip.free.get("16gb", 0) == 1
        # The original free 8gb slices no longer fit and stay gone.
        assert chip.free.get("8gb", 0) == 0

    def test_restores_free_slices_that_still_fit(self):
        chip = SharedChip(0, hbm_gb=16, free={"4gb": 3})
        assert chip.update_geometry_for({"8gb": 1})
        assert chip.free.get("8gb", 0) == 1
        # 8 GB remain: two of the three original 4gb slices come back.
        assert chip.free.get("4gb", 0) == 2

    def test_smaller_profiles_served_first(self):
        chip = SharedChip(0, hbm_gb=16)
        assert chip.update_geometry_for({"12gb": 1, "4gb": 1})
        assert chip.free == {"4gb": 1, "12gb": 1}

    def test_trade_preserves_required_smaller_profiles(self):
        # Regression: trading for 8gb must not destroy the 4gb slices the
        # same requirement set still needs (the reference algorithm does).
        chip = SharedChip(0, hbm_gb=16, used={"8gb": 1}, free={"4gb": 1})
        chip.update_geometry_for({"4gb": 2, "8gb": 1})
        assert chip.free.get("4gb", 0) == 2

    def test_trade_sacrifices_excess_of_required_profile(self):
        chip = SharedChip(0, hbm_gb=16, free={"4gb": 4})
        assert chip.update_geometry_for({"4gb": 1, "8gb": 1})
        assert chip.free.get("8gb", 0) == 1
        assert chip.free.get("4gb", 0) >= 1

    def test_allocate_moves_free_to_used(self):
        chip = SharedChip(0, hbm_gb=16, free={"8gb": 1})
        assert chip.allocate("8gb")
        assert chip.used == {"8gb": 1}
        assert chip.free == {}
        assert not chip.allocate("8gb")


class TestSharingNode:
    def test_builds_chips_from_capacity(self):
        node = SharingNode(sharing_node(chips=4))
        assert node.is_sharing_node
        assert len(node.chips) == 4
        assert node.chips[0].hbm_gb == 16

    def test_v4_hbm_budget(self):
        node = SharingNode(sharing_node(chips=4, accelerator="tpu-v4-podslice"))
        assert node.chips[0].hbm_gb == 32

    def test_unknown_accelerator_no_chips(self):
        node = SharingNode(sharing_node(accelerator="gpu-h100"))
        assert not node.is_sharing_node

    def test_status_annotations_restore_state(self):
        annotations = annot.status_from_devices(
            free={0: {"8gb": 1}}, used={1: {"16gb": 1}}
        )
        node = SharingNode(sharing_node(chips=2, annotations=annotations))
        assert node.chips[0].free == {"8gb": 1}
        assert node.chips[1].used == {"16gb": 1}
        assert node.free_slices() == {"8gb": 1}

    def test_inconsistent_on_out_of_range_chip(self):
        annotations = annot.status_from_devices(free={9: {"8gb": 1}}, used={})
        node = SharingNode(sharing_node(chips=2, annotations=annotations))
        assert not node.consistent
        assert not node.has_free_capacity()

    def test_update_geometry_spreads_across_chips(self):
        node = SharingNode(sharing_node(chips=2))
        assert node.update_geometry_for({mem(16): 2})
        geometry = node.geometry()
        assert geometry[0] == {"16gb": 1}
        assert geometry[1] == {"16gb": 1}

    def test_add_pod_consumes_free_slices(self):
        annotations = annot.status_from_devices(free={0: {"8gb": 2}}, used={})
        node = SharingNode(sharing_node(chips=1, annotations=annotations))
        assert node.add_pod(pod_requesting({mem(8): 2}))
        assert node.chips[0].used == {"8gb": 2}
        assert not node.add_pod(pod_requesting({mem(8): 1}))

    def test_scalar_resources(self):
        annotations = annot.status_from_devices(
            free={0: {"8gb": 1}}, used={0: {"8gb": 1}}
        )
        node = SharingNode(sharing_node(chips=2, annotations=annotations))
        assert node.scalar_resources() == {mem(8): 2}

    def test_to_sim_node_hides_shared_chips(self):
        annotations = annot.status_from_devices(free={0: {"8gb": 2}}, used={})
        node = SharingNode(sharing_node(chips=2, annotations=annotations))
        sim = node.to_sim_node()
        assert sim.status.allocatable[mem(8)] == 2
        # Chip 0 carries slices; chip 1 stays plain-requestable.
        assert sim.status.allocatable[constants.RESOURCE_TPU] == 1
