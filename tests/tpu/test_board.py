import pytest

from nos_tpu.tpu.board import TpuBoard
from nos_tpu.tpu.known import allowed_geometries, set_known_geometries


V5E = "tpu-v5-lite-podslice"


@pytest.fixture(autouse=True)
def clear_overrides():
    yield
    set_known_geometries(None)


class TestInitGeometry:
    def test_virgin_board_gets_fewest_slices_geometry(self):
        b = TpuBoard(0, V5E)
        assert b.init_geometry()
        assert b.geometry == {"2x4": 1}
        assert b.free == {"2x4": 1}

    def test_non_virgin_board_untouched(self):
        b = TpuBoard(0, V5E, free={"2x2": 2})
        assert not b.init_geometry()
        assert b.geometry == {"2x2": 2}

    def test_unknown_accelerator_rejected(self):
        with pytest.raises(ValueError):
            TpuBoard(0, "tpu-v99")


class TestAllocate:
    def test_allocate_moves_free_to_used(self):
        b = TpuBoard(0, V5E, free={"2x2": 2})
        assert b.allocate("2x2")
        assert b.used == {"2x2": 1}
        assert b.free == {"2x2": 1}

    def test_allocate_insufficient(self):
        b = TpuBoard(0, V5E, free={"2x2": 1})
        assert not b.allocate("2x2", 2)
        assert b.used == {}


class TestUpdateGeometryFor:
    def test_virgin_board_carved_for_lacking(self):
        b = TpuBoard(0, V5E)
        assert b.update_geometry_for({"2x2": 2})
        assert b.free == {"2x2": 2}

    def test_respects_used_slices(self):
        b = TpuBoard(0, V5E, used={"2x2": 1})
        assert b.update_geometry_for({"1x1": 4})
        # used 2x2 preserved; remaining 4 chips re-carved into 1x1s
        assert b.used == {"2x2": 1}
        assert b.free == {"1x1": 4}

    def test_fully_used_board_cannot_change(self):
        b = TpuBoard(0, V5E, used={"2x4": 1})
        assert not b.update_geometry_for({"1x1": 1})
        assert b.geometry == {"2x4": 1}

    def test_no_improvement_returns_false(self):
        b = TpuBoard(0, V5E, free={"1x1": 8})
        assert not b.update_geometry_for({"1x1": 2})
        assert b.free == {"1x1": 8}

    def test_prefers_least_fragmentation_on_ties(self):
        b = TpuBoard(0, V5E)
        assert b.update_geometry_for({"2x2": 1})
        # {2x2:2} and {2x2:1,1x1:4} both provide one 2x2; fewest slices wins.
        assert b.free == {"2x2": 2}

    def test_empty_lacking_is_noop(self):
        b = TpuBoard(0, V5E)
        assert not b.update_geometry_for({})

    def test_mixed_profiles(self):
        b = TpuBoard(0, V5E)
        assert b.update_geometry_for({"2x2": 1, "1x1": 4})
        assert b.free == {"2x2": 1, "1x1": 4}

    def test_geometry_override_limits_search(self):
        set_known_geometries({V5E: [{"2x4": 1}, {"1x1": 8}]})
        b = TpuBoard(0, V5E)
        assert b.update_geometry_for({"2x2": 1}) is False
        assert b.update_geometry_for({"1x1": 1})
        assert b.free == {"1x1": 8}


class TestCapacity:
    def test_has_free_capacity_with_free_slices(self):
        assert TpuBoard(0, V5E, free={"1x1": 1}).has_free_capacity()

    def test_has_free_capacity_virgin(self):
        assert TpuBoard(0, V5E).has_free_capacity()

    def test_no_free_capacity_fully_used(self):
        assert not TpuBoard(0, V5E, used={"2x4": 1}).has_free_capacity()

    def test_chip_accounting(self):
        b = TpuBoard(0, V5E, used={"2x2": 1}, free={"1x2": 2})
        assert b.used_chips == 4
        assert b.free_chips == 4
        assert b.chips == 8


class TestAllowedGeometries:
    def test_unknown_accelerator_empty(self):
        assert allowed_geometries("nope") == []

    def test_returned_geometries_are_copies(self):
        g = allowed_geometries(V5E)[0]
        g["2x4"] = 99
        assert allowed_geometries(V5E)[0] == {"2x4": 1}
