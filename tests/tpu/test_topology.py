import pytest

from nos_tpu.tpu.topology import Topology, enumerate_tilings


class TestTopology:
    @pytest.mark.parametrize(
        "spec,dims,chips",
        [
            ("1x1", (1, 1), 1),
            ("2x4", (2, 4), 8),
            ("2x2x1", (2, 2, 1), 4),
            ("4x4x4", (4, 4, 4), 64),
        ],
    )
    def test_parse_and_chips(self, spec, dims, chips):
        t = Topology(spec)
        assert t.dims == dims
        assert t.chips == chips
        assert str(t) == spec

    @pytest.mark.parametrize("bad", ["", "x", "2x", "0x2", "ax2", "2x-1"])
    def test_invalid_specs(self, bad):
        with pytest.raises(ValueError):
            Topology(bad)

    def test_orientations(self):
        assert Topology("1x2").orientations() == [(1, 2), (2, 1)]
        assert Topology("2x2").orientations() == [(2, 2)]
        assert len(Topology("1x2x1").orientations()) == 3


class TestEnumerateTilings:
    def test_v5e_board_full_search_space(self):
        geos = enumerate_tilings("2x4", ("1x1", "1x2", "2x2", "2x4"))
        keys = {tuple(sorted(g.items())) for g in geos}
        # Exact multiset tilings of a 2x4 grid by 1x1/1x2 (either
        # orientation)/2x2/2x4 rectangles.
        expected = {
            (("2x4", 1),),
            (("2x2", 2),),
            (("1x2", 2), ("2x2", 1)),
            (("1x1", 2), ("1x2", 1), ("2x2", 1)),
            (("1x1", 4), ("2x2", 1)),
            (("1x2", 4),),
            (("1x1", 2), ("1x2", 3)),
            (("1x1", 4), ("1x2", 2)),
            (("1x1", 6), ("1x2", 1)),
            (("1x1", 8),),
        }
        assert keys == expected

    def test_every_tiling_covers_all_chips(self):
        for g in enumerate_tilings("2x4", ("1x1", "1x2", "2x2", "2x4")):
            chips = sum(Topology(p).chips * n for p, n in g.items())
            assert chips == 8

    def test_fewest_slices_first_ordering(self):
        geos = enumerate_tilings("2x4", ("1x1", "1x2", "2x2", "2x4"))
        counts = [sum(g.values()) for g in geos]
        assert counts == sorted(counts)
        assert geos[0] == {"2x4": 1}

    def test_3d_v4_board(self):
        geos = enumerate_tilings("2x2x1", ("1x1x1", "1x2x1", "2x2x1"))
        keys = {tuple(sorted(g.items())) for g in geos}
        assert keys == {
            (("2x2x1", 1),),
            (("1x2x1", 2),),
            (("1x1x1", 2), ("1x2x1", 1)),
            (("1x1x1", 4),),
        }

    def test_orientation_matters_for_coverage(self):
        # A 1x2 domino must be placeable along both axes: a 2x2 grid is
        # tileable by two dominoes in two ways but yields ONE geometry.
        geos = enumerate_tilings("2x2", ("1x2",))
        assert geos == ({"1x2": 2},)

    def test_rank_mismatch_raises(self):
        with pytest.raises(ValueError):
            enumerate_tilings("2x4", ("1x1x1",))

    def test_non_tiling_shapes_yield_nothing(self):
        # 2x2 squares cannot exactly tile 2x3.
        assert enumerate_tilings("2x3", ("2x2",)) == ()
