
from nos_tpu.api.v1alpha1 import annotations as annot
from nos_tpu.api.v1alpha1 import constants, labels
from nos_tpu.kube.objects import (
    Container,
    Node,
    NodeStatus,
    ObjectMeta,
    Pod,
    PodSpec,
)
from nos_tpu.kube.objects import PodPhase
from nos_tpu.tpu.node import TpuNode

from tests.factory import build_pod, build_tpu_node

V5E = "tpu-v5-lite-podslice"


def make_tpu_node(
    name="n1", accelerator=V5E, chips=8, annotations=None, extra_alloc=None
):
    alloc = {constants.RESOURCE_TPU: chips, "cpu": 8, "memory": 128}
    alloc.update(extra_alloc or {})
    return Node(
        metadata=ObjectMeta(
            name=name,
            labels={
                labels.GKE_TPU_ACCELERATOR_LABEL: accelerator,
                labels.GKE_TPU_TOPOLOGY_LABEL: "2x4",
                labels.PARTITIONING_LABEL: "tpu",
            },
            annotations=annotations or {},
        ),
        status=NodeStatus(capacity=dict(alloc), allocatable=dict(alloc)),
    )


def make_pod(name, requests, ns="default"):
    return Pod(
        metadata=ObjectMeta(name=name, namespace=ns),
        spec=PodSpec(containers=[Container(requests=requests)]),
    )


class TestBuild:
    def test_non_tpu_node(self):
        node = Node(metadata=ObjectMeta(name="plain"))
        t = TpuNode(node)
        assert not t.is_tpu_node
        assert t.boards == []

    def test_virgin_tpu_node_one_board(self):
        t = TpuNode(make_tpu_node())
        assert t.is_tpu_node
        assert len(t.boards) == 1
        assert t.boards[0].geometry == {}

    def test_multi_board_node(self):
        t = TpuNode(make_tpu_node(chips=16))
        assert len(t.boards) == 2

    def test_geometry_from_status_annotations(self):
        ann = annot.status_from_devices(
            free={0: {"2x2": 1}}, used={0: {"2x2": 1}}
        )
        t = TpuNode(make_tpu_node(annotations=ann))
        assert t.boards[0].free == {"2x2": 1}
        assert t.boards[0].used == {"2x2": 1}
        assert t.geometry() == {0: {"2x2": 2}}


class TestAddPod:
    def test_slice_request_consumes_free_slice(self):
        ann = annot.status_from_devices(free={0: {"2x2": 2}}, used={})
        t = TpuNode(make_tpu_node(annotations=ann))
        pod = make_pod("p", {constants.tpu_slice_resource("2x2"): 1})
        assert t.add_pod(pod)
        assert t.boards[0].used == {"2x2": 1}

    def test_plain_chip_request_normalized_to_slice(self):
        ann = annot.status_from_devices(free={0: {"2x2": 2}}, used={})
        t = TpuNode(make_tpu_node(annotations=ann))
        assert t.add_pod(make_pod("p", {constants.RESOURCE_TPU: 4}))
        assert t.boards[0].used == {"2x2": 1}

    def test_chip_request_rounds_up_to_next_profile(self):
        ann = annot.status_from_devices(free={0: {"2x2": 2}}, used={})
        t = TpuNode(make_tpu_node(annotations=ann))
        # 3 chips -> smallest profile ≥ 3 = 2x2
        assert t.add_pod(make_pod("p", {constants.RESOURCE_TPU: 3}))
        assert t.boards[0].used == {"2x2": 1}

    def test_does_not_fit_leaves_node_untouched(self):
        ann = annot.status_from_devices(free={0: {"1x1": 1}}, used={})
        t = TpuNode(make_tpu_node(annotations=ann))
        assert not t.add_pod(make_pod("p", {constants.tpu_slice_resource("2x2"): 1}))
        assert t.boards[0].used == {}
        assert t.boards[0].free == {"1x1": 1}

    def test_non_tpu_pod_always_fits(self):
        t = TpuNode(make_tpu_node())
        assert t.add_pod(make_pod("p", {"cpu": 2}))

    def test_spreads_across_boards(self):
        ann = annot.status_from_devices(
            free={0: {"2x2": 1}, 1: {"2x2": 1}}, used={}
        )
        t = TpuNode(make_tpu_node(chips=16, annotations=ann))
        pod = make_pod("p", {constants.tpu_slice_resource("2x2"): 2})
        assert t.add_pod(pod)
        assert t.boards[0].used == {"2x2": 1}
        assert t.boards[1].used == {"2x2": 1}


class TestUpdateGeometryFor:
    def test_carve_virgin_node(self):
        t = TpuNode(make_tpu_node())
        lacking = {constants.tpu_slice_resource("2x2"): 2}
        assert t.update_geometry_for(lacking)
        assert t.boards[0].free == {"2x2": 2}

    def test_already_satisfied_no_change(self):
        ann = annot.status_from_devices(free={0: {"2x2": 2}}, used={})
        t = TpuNode(make_tpu_node(annotations=ann))
        assert not t.update_geometry_for({constants.tpu_slice_resource("2x2"): 1})

    def test_second_board_serves_remainder(self):
        t = TpuNode(make_tpu_node(chips=16))
        lacking = {constants.tpu_slice_resource("2x4"): 2}
        assert t.update_geometry_for(lacking)
        assert t.boards[0].free == {"2x4": 1}
        assert t.boards[1].free == {"2x4": 1}

    def test_ignores_non_slice_resources(self):
        t = TpuNode(make_tpu_node())
        assert not t.update_geometry_for({"cpu": 4})


class TestProjections:
    def test_scalar_resources(self):
        ann = annot.status_from_devices(
            free={0: {"2x2": 1, "1x1": 4}}, used={}
        )
        t = TpuNode(make_tpu_node(annotations=ann))
        assert t.scalar_resources() == {
            constants.tpu_slice_resource("2x2"): 1,
            constants.tpu_slice_resource("1x1"): 4,
        }

    def test_to_sim_node_swaps_tpu_for_slices(self):
        ann = annot.status_from_devices(free={0: {"2x4": 1}}, used={})
        t = TpuNode(make_tpu_node(annotations=ann))
        sim = t.to_sim_node()
        assert constants.RESOURCE_TPU not in sim.status.allocatable
        assert sim.status.allocatable[constants.tpu_slice_resource("2x4")] == 1
        assert sim.status.allocatable["cpu"] == 8

    def test_clone_is_independent(self):
        t = TpuNode(make_tpu_node())
        c = t.clone()
        c.boards[0].init_geometry()
        assert t.boards[0].geometry == {}


class TestOversizedRequests:
    def test_multi_host_sized_request_rejected_at_node_level(self):
        ann = annot.status_from_devices(free={0: {"2x4": 1}}, used={})
        t = TpuNode(make_tpu_node(annotations=ann))
        assert not t.add_pod(make_pod("big", {constants.RESOURCE_TPU: 16}))
        assert t.boards[0].used == {}


class TestBoardLayout:
    def test_undersized_v5e_host_is_2x2_board(self):
        t = TpuNode(make_tpu_node(chips=4))
        assert len(t.boards) == 1
        assert t.boards[0].board_topology == "2x2"
        assert t.boards[0].chips == 4
        # carving is bounded by the real 4 chips
        assert t.update_geometry_for({constants.tpu_slice_resource("1x1"): 8})
        assert t.boards[0].free == {"1x1": 4}

    def test_zero_capacity_no_phantom_board(self):
        t = TpuNode(make_tpu_node(chips=0))
        assert t.boards == []
        assert not t.is_tpu_node
        assert not t.has_free_capacity()

    def test_unmodelable_capacity_no_boards(self):
        t = TpuNode(make_tpu_node(chips=3))
        assert t.boards == []

    def test_out_of_range_status_annotation_marks_inconsistent(self):
        ann = annot.status_from_devices(free={}, used={1: {"2x2": 1}})
        t = TpuNode(make_tpu_node(chips=8, annotations=ann))
        assert not t.consistent
        assert not t.has_free_capacity()
        assert not t.update_geometry_for({constants.tpu_slice_resource("1x1"): 1})


class TestSharingAnnotationTolerance:
    def test_gb_status_annotations_ignored(self):
        # Regression: stale sharing-mode ("<N>gb") status annotations on a
        # node relabeled to tpu mode must not enter board geometry (they
        # would crash topology math).
        from nos_tpu.api.v1alpha1 import annotations as annot
        from tests.factory import build_tpu_node

        annotations = annot.status_from_devices(
            free={0: {"8gb": 1, "2x2": 1}}, used={}
        )
        node = TpuNode(build_tpu_node(annotations=annotations))
        assert node.consistent
        assert node.boards[0].free == {"2x2": 1}
        assert node.has_free_capacity()


class TestRebuildUsageFromPods:
    """The planner must plan against live pod bindings, not the reporter's
    (lag-prone) used/free split — a stale 'free' lets the planner carve a
    slice a just-bound pod occupies (the scheduler then double-books the
    board's chips)."""

    def test_bound_pod_claims_reportedly_free_slice(self):
        ann = annot.status_from_devices(free={0: {"2x2": 2}}, used={})
        node = TpuNode(build_tpu_node(annotations=ann))
        pod = build_pod("w", {constants.RESOURCE_TPU: 4}, node="tpu-node")
        node.rebuild_usage_from_pods([pod])
        assert node.boards[0].used == {"2x2": 1}
        assert node.boards[0].free == {"2x2": 1}

    def test_stale_used_without_pods_becomes_free(self):
        ann = annot.status_from_devices(free={}, used={0: {"2x2": 2}})
        node = TpuNode(build_tpu_node(annotations=ann))
        node.rebuild_usage_from_pods([])
        assert node.boards[0].used == {}
        assert node.boards[0].free == {"2x2": 2}

    def test_unattributable_demand_marks_inconsistent(self):
        # A bound pod whose profile has no device: mid-transition node.
        ann = annot.status_from_devices(free={0: {"2x2": 1}}, used={})
        node = TpuNode(build_tpu_node(annotations=ann))
        pods = [
            build_pod("a", {constants.RESOURCE_TPU: 4}, node="tpu-node"),
            build_pod("b", {constants.RESOURCE_TPU: 4}, node="tpu-node"),
        ]
        node.rebuild_usage_from_pods(pods)
        assert not node.consistent
        assert not node.has_free_capacity()

    def test_terminal_pods_hold_nothing(self):
        ann = annot.status_from_devices(free={0: {"2x2": 2}}, used={})
        node = TpuNode(build_tpu_node(annotations=ann))
        pod = build_pod(
            "done", {constants.RESOURCE_TPU: 4}, node="tpu-node",
            phase=PodPhase.SUCCEEDED,
        )
        node.rebuild_usage_from_pods([pod])
        assert node.boards[0].used == {}
