"""Shared object builders (reference pkg/test/factory/core_factory.go)."""
from nos_tpu.api.v1alpha1 import constants, labels
from nos_tpu.kube.objects import (
    Container,
    Node,
    NodeStatus,
    ObjectMeta,
    Pod,
    PodCondition,
    PodPhase,
    PodSpec,
)

V5E = "tpu-v5-lite-podslice"
V4 = "tpu-v4-podslice"


def build_tpu_node(
    name="tpu-node",
    accelerator=V5E,
    chips=8,
    topology="2x4",
    annotations=None,
    extra_alloc=None,
    partitioning="tpu",
):
    alloc = {constants.RESOURCE_TPU: chips, "cpu": 8, "memory": 128}
    alloc.update(extra_alloc or {})
    node_labels = {
        labels.GKE_TPU_ACCELERATOR_LABEL: accelerator,
        labels.GKE_TPU_TOPOLOGY_LABEL: topology,
    }
    if partitioning:
        node_labels[labels.PARTITIONING_LABEL] = partitioning
    return Node(
        metadata=ObjectMeta(name=name, labels=node_labels, annotations=annotations or {}),
        status=NodeStatus(capacity=dict(alloc), allocatable=dict(alloc)),
    )


def build_node(name="node", alloc=None):
    alloc = alloc or {"cpu": 8, "memory": 128}
    return Node(
        metadata=ObjectMeta(name=name),
        status=NodeStatus(capacity=dict(alloc), allocatable=dict(alloc)),
    )


def build_pod(
    name,
    requests=None,
    ns="default",
    priority=0,
    phase=PodPhase.PENDING,
    node="",
    scheduler=constants.SCHEDULER_NAME,
):
    pod = Pod(
        metadata=ObjectMeta(name=name, namespace=ns),
        spec=PodSpec(
            containers=[Container(requests=dict(requests or {}))],
            priority=priority,
            node_name=node,
            scheduler_name=scheduler,
        ),
    )
    pod.status.phase = phase
    return pod


def mark_unschedulable(pod):
    pod.status.conditions.append(
        PodCondition(type="PodScheduled", status="False", reason="Unschedulable")
    )
    return pod


def slice_res(topology):
    return constants.tpu_slice_resource(topology)
