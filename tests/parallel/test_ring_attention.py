"""Ring attention: exactness vs dense attention, gradients, and the full
dp×sp×tp training step (the long-context surface of the framework)."""
import math

import jax
import jax.numpy as jnp
import pytest

from nos_tpu.models.llama import init_llama_params, llama_loss, tiny_config
from nos_tpu.parallel.mesh import default_training_mesh, mesh_from_devices
from nos_tpu.parallel.ring_attention import ring_attention
from nos_tpu.parallel.train import make_train_step


def dense_reference(q, k, v, causal=True):
    """Straightforward GQA attention in float32: the ground truth."""
    b, s, hq, hd = q.shape
    hkv = k.shape[2]
    group = hq // hkv
    qg = q.reshape(b, s, hkv, group, hd).astype(jnp.float32)
    scores = jnp.einsum("bsKgh,btKh->bKgst", qg, k.astype(jnp.float32)) / math.sqrt(hd)
    if causal:
        mask = jnp.tril(jnp.ones((s, s), bool))
        scores = jnp.where(mask[None, None, None], scores, -jnp.inf)
    probs = jax.nn.softmax(scores, axis=-1)
    out = jnp.einsum("bKgst,btKh->bsKgh", probs, v.astype(jnp.float32))
    return out.reshape(b, s, hq * hd)


def random_qkv(key, b=2, s=16, hq=4, hkv=2, hd=8):
    kq, kk, kv = jax.random.split(key, 3)
    q = jax.random.normal(kq, (b, s, hq, hd), jnp.float32)
    k = jax.random.normal(kk, (b, s, hkv, hd), jnp.float32)
    v = jax.random.normal(kv, (b, s, hkv, hd), jnp.float32)
    return q, k, v


class TestRingAttentionExactness:
    @pytest.mark.parametrize("n_sp", [2, 4, 8])
    def test_matches_dense_causal(self, n_sp):
        mesh = mesh_from_devices((n_sp,), ("sp",), jax.devices()[:n_sp])
        q, k, v = random_qkv(jax.random.key(0))
        got = jax.jit(lambda q, k, v: ring_attention(q, k, v, mesh))(q, k, v)
        want = dense_reference(q, k, v)
        assert jnp.allclose(got, want, atol=1e-5), float(jnp.abs(got - want).max())

    def test_matches_dense_non_causal(self):
        mesh = mesh_from_devices((4,), ("sp",), jax.devices()[:4])
        q, k, v = random_qkv(jax.random.key(1))
        got = ring_attention(q, k, v, mesh, causal=False)
        want = dense_reference(q, k, v, causal=False)
        assert jnp.allclose(got, want, atol=1e-5)

    def test_composes_with_dp_and_tp(self):
        mesh = mesh_from_devices((2, 2, 2), ("dp", "sp", "tp"))
        q, k, v = random_qkv(jax.random.key(2), b=4, s=8, hq=4, hkv=2, hd=8)
        got = jax.jit(lambda q, k, v: ring_attention(q, k, v, mesh))(q, k, v)
        want = dense_reference(q, k, v)
        assert jnp.allclose(got, want, atol=1e-5)

    def test_gradients_match_dense(self):
        mesh = mesh_from_devices((4,), ("sp",), jax.devices()[:4])
        q, k, v = random_qkv(jax.random.key(3), s=8)

        def ring_sum(q, k, v):
            return ring_attention(q, k, v, mesh).sum()

        def dense_sum(q, k, v):
            return dense_reference(q, k, v).sum()

        g_ring = jax.grad(ring_sum, argnums=(0, 1, 2))(q, k, v)
        g_dense = jax.grad(dense_sum, argnums=(0, 1, 2))(q, k, v)
        for gr, gd in zip(g_ring, g_dense):
            assert jnp.allclose(gr, gd, atol=1e-4), float(jnp.abs(gr - gd).max())


class TestSequenceParallelTraining:
    def test_dp_sp_tp_step_matches_single_device(self):
        config = tiny_config()
        tokens = jax.random.randint(jax.random.key(1), (8, 16), 0, config.vocab_size)

        mesh1 = mesh_from_devices((1, 1), ("dp", "tp"), jax.devices()[:1])
        step1, shard1 = make_train_step(mesh1, config)
        _, loss1 = step1(shard1(init_llama_params(jax.random.key(0), config)), tokens)

        mesh8 = default_training_mesh()
        assert mesh8.shape == {"dp": 2, "sp": 2, "tp": 2}
        step8, shard8 = make_train_step(mesh8, config)
        _, loss8 = step8(shard8(init_llama_params(jax.random.key(0), config)), tokens)
        assert abs(float(loss1) - float(loss8)) < 2e-2

    def test_ring_loss_matches_dense_loss(self):
        """Same params/tokens: the sp forward path must agree with the
        dense path to float tolerance."""
        config = tiny_config()
        params = init_llama_params(jax.random.key(0), config)
        tokens = jax.random.randint(jax.random.key(2), (2, 16), 0, config.vocab_size)
        dense = jax.jit(lambda p, t: llama_loss(p, t, config))(params, tokens)
        mesh = mesh_from_devices((1, 4, 1), ("dp", "sp", "tp"), jax.devices()[:4])
        ring = jax.jit(lambda p, t: llama_loss(p, t, config, mesh))(params, tokens)
        assert abs(float(dense) - float(ring)) < 2e-2


class TestRingFlashAttention:
    """Kernel-backed ring attention vs the dense oracle — forward and the
    hand-written ring backward."""

    def test_forward_matches_dense(self):
        from nos_tpu.parallel.ring_attention import ring_flash_attention

        q, k, v = random_qkv(jax.random.key(30), b=2, s=32, hq=4, hkv=2, hd=16)
        mesh = mesh_from_devices((4,), ("sp",), jax.devices()[:4])
        got = jax.jit(lambda q, k, v: ring_flash_attention(q, k, v, mesh))(q, k, v)
        want = dense_reference(q, k, v, causal=True)
        assert jnp.allclose(got, want, atol=1e-4), float(jnp.abs(got - want).max())

    def test_forward_non_causal(self):
        from nos_tpu.parallel.ring_attention import ring_flash_attention

        q, k, v = random_qkv(jax.random.key(31), b=1, s=16, hq=2, hkv=2, hd=8)
        mesh = mesh_from_devices((4,), ("sp",), jax.devices()[:4])
        got = jax.jit(
            lambda q, k, v: ring_flash_attention(q, k, v, mesh, causal=False)
        )(q, k, v)
        want = dense_reference(q, k, v, causal=False)
        assert jnp.allclose(got, want, atol=1e-4)

    def test_grads_match_dense(self):
        from nos_tpu.parallel.ring_attention import ring_flash_attention

        q, k, v = random_qkv(jax.random.key(32), b=1, s=32, hq=2, hkv=2, hd=8)
        mesh = mesh_from_devices((4,), ("sp",), jax.devices()[:4])
        seed = jax.random.normal(jax.random.key(33), (1, 32, 16))

        def f_ring(q, k, v):
            return jnp.sum(ring_flash_attention(q, k, v, mesh) * seed)

        def f_dense(q, k, v):
            return jnp.sum(dense_reference(q, k, v, causal=True) * seed)

        g_ring = jax.jit(jax.grad(f_ring, argnums=(0, 1, 2)))(q, k, v)
        g_dense = jax.grad(f_dense, argnums=(0, 1, 2))(q, k, v)
        for name, a, b in zip("qkv", g_ring, g_dense):
            assert jnp.allclose(a, b, atol=1e-4), (
                name, float(jnp.abs(a - b).max()))

    def test_composes_with_dp_and_tp(self):
        from nos_tpu.parallel.ring_attention import ring_flash_attention

        q, k, v = random_qkv(jax.random.key(34), b=2, s=16, hq=4, hkv=4, hd=8)
        mesh = mesh_from_devices((2, 2, 2), ("dp", "sp", "tp"))
        got = jax.jit(lambda q, k, v: ring_flash_attention(q, k, v, mesh))(q, k, v)
        want = dense_reference(q, k, v, causal=True)
        assert jnp.allclose(got, want, atol=1e-4)

    def test_llama_sp_flash_training_matches_dense(self):
        """The full long-context training path: llama over a dp×sp×tp mesh
        with attention="flash" (ring of Pallas kernels) — loss and grads
        match single-device dense."""
        from nos_tpu.models.llama import init_llama_params, llama_loss, tiny_config

        dense_cfg = tiny_config()
        flash_cfg = tiny_config(attention="flash")
        params = init_llama_params(jax.random.key(0), dense_cfg)
        tokens = jax.random.randint(jax.random.key(1), (2, 32), 0, dense_cfg.vocab_size)
        mesh = mesh_from_devices((2, 2, 2), ("dp", "sp", "tp"))

        l_d, g_d = jax.jit(
            jax.value_and_grad(lambda p: llama_loss(p, tokens, dense_cfg))
        )(params)
        l_f, g_f = jax.jit(
            jax.value_and_grad(lambda p: llama_loss(p, tokens, flash_cfg, mesh))
        )(params)
        assert abs(float(l_d) - float(l_f)) < 2e-2
        a = jnp.asarray(g_d["layers"][0]["wq"], jnp.float32)
        b = jnp.asarray(g_f["layers"][0]["wq"], jnp.float32)
        assert jnp.allclose(a, b, atol=3e-2), float(jnp.abs(a - b).max())


class TestSlidingWindowSequenceParallel:
    """The Mistral band across the SP strategies: every path must agree
    with the dense windowed oracle, and banded ring hops must skip."""

    def windowed_oracle(self, q, k, v, window):
        b, s, hq, hd = q.shape
        hkv = k.shape[2]
        g = hq // hkv
        qg = q.reshape(b, s, hkv, g, hd)
        scores = jnp.einsum(
            "bsKgh,btKh->bKgst", qg, k, preferred_element_type=jnp.float32
        ) / (hd ** 0.5)
        pos = jnp.arange(s)
        mask = (pos[None, :] <= pos[:, None]) & (
            pos[:, None] - pos[None, :] < window
        )
        scores = jnp.where(mask[None, None, None], scores, -jnp.inf)
        probs = jax.nn.softmax(scores, axis=-1).astype(q.dtype)
        return jnp.einsum("bKgst,btKh->bsKgh", probs, v).reshape(b, s, hq * hd)

    def test_jnp_ring_windowed_matches_dense(self):
        q, k, v = random_qkv(jax.random.key(70), b=1, s=32, hq=4, hkv=2, hd=8)
        mesh = mesh_from_devices((4,), ("sp",), jax.devices()[:4])
        for window in (3, 8, 100):  # intra-block, cross-block, > S
            got = jax.jit(
                lambda q, k, v, w=window: ring_attention(q, k, v, mesh, window=w)
            )(q, k, v)
            want = self.windowed_oracle(q, k, v, window)
            assert jnp.allclose(got, want, atol=1e-5), (
                window, float(jnp.abs(got - want).max())
            )

    def test_kernel_ring_windowed_matches_dense(self):
        from nos_tpu.parallel.ring_attention import ring_flash_attention

        q, k, v = random_qkv(jax.random.key(71), b=1, s=32, hq=4, hkv=2, hd=8)
        mesh = mesh_from_devices((4,), ("sp",), jax.devices()[:4])
        got = jax.jit(
            lambda q, k, v: ring_flash_attention(q, k, v, mesh, window=6)
        )(q, k, v)
        want = self.windowed_oracle(q, k, v, 6)
        assert jnp.allclose(got, want, atol=1e-4), float(jnp.abs(got - want).max())

    def test_kernel_ring_windowed_grads_match_dense(self):
        from nos_tpu.parallel.ring_attention import ring_flash_attention

        q, k, v = random_qkv(jax.random.key(72), b=1, s=16, hq=2, hkv=2, hd=8)
        mesh = mesh_from_devices((4,), ("sp",), jax.devices()[:4])
        seed = jax.random.normal(jax.random.key(73), (1, 16, 16))

        def f_ring(q, k, v):
            return jnp.sum(ring_flash_attention(q, k, v, mesh, window=5) * seed)

        def f_dense(q, k, v):
            return jnp.sum(self.windowed_oracle(q, k, v, 5) * seed)

        g_r = jax.jit(jax.grad(f_ring, argnums=(0, 1, 2)))(q, k, v)
        g_d = jax.grad(f_dense, argnums=(0, 1, 2))(q, k, v)
        for a, b_ in zip(g_r, g_d):
            assert jnp.allclose(a, b_, atol=1e-4), float(jnp.abs(a - b_).max())

    def test_ulysses_windowed_matches_dense(self):
        from nos_tpu.parallel.ulysses import ulysses_attention

        q, k, v = random_qkv(jax.random.key(74), b=1, s=32, hq=8, hkv=4, hd=8)
        mesh = mesh_from_devices((4,), ("sp",), jax.devices()[:4])
        got = jax.jit(
            lambda q, k, v: ulysses_attention(q, k, v, mesh, window=6)
        )(q, k, v)
        want = self.windowed_oracle(q, k, v, 6)
        assert jnp.allclose(got, want, atol=1e-5), float(jnp.abs(got - want).max())

    def test_windowed_model_loss_matches_single_device(self):
        # Whole-model check: the Mistral config trains identically on the
        # sp mesh and a single device.
        from nos_tpu.models.llama import init_llama_params, llama_loss, tiny_config

        config = tiny_config(sliding_window=6, dtype=jnp.float32)
        params = init_llama_params(jax.random.key(0), config)
        tokens = jax.random.randint(jax.random.key(1), (2, 16), 0, config.vocab_size)
        single = jax.jit(lambda p, t: llama_loss(p, t, config))(params, tokens)
        mesh = mesh_from_devices((1, 4, 1), ("dp", "sp", "tp"), jax.devices()[:4])
        ring = jax.jit(lambda p, t: llama_loss(p, t, config, mesh))(params, tokens)
        assert abs(float(single) - float(ring)) < 1e-4
