"""FSDP: parameters and optimizer state shard over dp (VERDICT round-1 #5).

A Llama-3-8B train state (~32 GB with momentum in bf16) cannot fit one
v5e chip's 16 GB HBM; chip-count-fractional parameter storage is what
makes BASELINE config #5 (auto-carved 4x4 slice) runnable. These tests
pin the memory contract and the numerics.
"""
import jax
import jax.numpy as jnp
import numpy as np

from nos_tpu.models.llama import init_llama_params, tiny_config
from nos_tpu.parallel.mesh import mesh_from_devices
from nos_tpu.parallel.train import make_train_step


def _local_bytes(tree) -> int:
    total = 0
    for leaf in jax.tree.leaves(tree):
        for shard in leaf.addressable_shards:
            total += shard.data.size * shard.data.dtype.itemsize
    return total


def _global_bytes(tree) -> int:
    return sum(x.size * x.dtype.itemsize for x in jax.tree.leaves(tree))


class TestFsdp:
    def test_param_bytes_shard_over_mesh(self):
        devices = jax.devices()[:8]
        mesh = mesh_from_devices((4, 2), ("dp", "tp"), devices)
        config = tiny_config()
        _, shard_state = make_train_step(mesh, config)
        params, velocity = shard_state(init_llama_params(jax.random.key(0), config))

        global_bytes = _global_bytes(params)
        local = _local_bytes(params)
        # Each device holds ~1/8th; 1-D norm scales stay replicated, so
        # allow their slack: bound by 1/8 of global + full replicated bytes.
        replicated = sum(
            x.size * x.dtype.itemsize for x in jax.tree.leaves(params) if x.ndim == 1
        )
        assert local <= global_bytes + 7 * replicated  # sanity: all shards
        per_dev = local / len(devices)
        assert per_dev <= global_bytes / 8 + replicated, (
            f"per-device {per_dev} vs fully-sharded {global_bytes / 8} "
            f"+ replicated {replicated}"
        )
        # Optimizer state shards identically.
        assert _local_bytes(velocity) == local

    def test_fsdp_loss_matches_single_device(self):
        config = tiny_config()
        params = init_llama_params(jax.random.key(0), config)
        tokens = jax.random.randint(jax.random.key(1), (4, 16), 0, config.vocab_size)

        mesh1 = mesh_from_devices((1, 1), ("dp", "tp"), jax.devices()[:1])
        step1, shard1 = make_train_step(mesh1, config)
        state1, loss1 = step1(shard1(params), tokens)

        mesh8 = mesh_from_devices((4, 2), ("dp", "tp"), jax.devices()[:8])
        step8, shard8 = make_train_step(mesh8, config)
        state8, loss8 = step8(shard8(params), tokens)

        np.testing.assert_allclose(float(loss1), float(loss8), rtol=2e-2)
        # Updated params agree too (momentum-SGD step is deterministic).
        p1 = jax.tree.leaves(state1[0])[0]
        p8 = jax.tree.leaves(state8[0])[0]
        np.testing.assert_allclose(
            np.asarray(p1, np.float32), np.asarray(p8, np.float32), atol=3e-2
        )


class TestOptaxOptimizer:
    def test_adamw_state_shards_like_params(self):
        import optax

        devices = jax.devices()[:8]
        mesh = mesh_from_devices((4, 2), ("dp", "tp"), devices)
        config = tiny_config()
        opt = optax.adamw(1e-3)
        step, shard_state = make_train_step(mesh, config, optimizer=opt)
        params, opt_state = shard_state(init_llama_params(jax.random.key(0), config))
        # adam's mu/nu shard exactly like the params: per-device moment
        # bytes == per-device param bytes (two moments).
        p_local = _local_bytes(params)
        mu_nu_local = _local_bytes(opt_state[0].mu) + _local_bytes(opt_state[0].nu)
        assert mu_nu_local == 2 * p_local
        # and a step actually runs
        tokens = jax.random.randint(jax.random.key(1), (4, 16), 0, config.vocab_size)
        (params, opt_state), loss = step((params, opt_state), tokens)
        assert jnp.isfinite(loss)

    def test_adamw_loss_matches_single_device(self):
        import optax
        import numpy as np

        config = tiny_config()
        params = init_llama_params(jax.random.key(0), config)
        tokens = jax.random.randint(jax.random.key(1), (4, 16), 0, config.vocab_size)
        opt = optax.adamw(1e-3)

        mesh1 = mesh_from_devices((1, 1), ("dp", "tp"), jax.devices()[:1])
        step1, shard1 = make_train_step(mesh1, config, optimizer=opt)
        state1, loss1 = step1(shard1(params), tokens)

        mesh8 = mesh_from_devices((4, 2), ("dp", "tp"), jax.devices()[:8])
        step8, shard8 = make_train_step(mesh8, config, optimizer=opt)
        state8, loss8 = step8(shard8(params), tokens)

        np.testing.assert_allclose(float(loss1), float(loss8), rtol=2e-2)
        p1 = jax.tree.leaves(state1[0])[0]
        p8 = jax.tree.leaves(state8[0])[0]
        np.testing.assert_allclose(
            np.asarray(p1, np.float32), np.asarray(p8, np.float32), atol=3e-2
        )
