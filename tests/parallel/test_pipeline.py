"""Pipeline parallelism: schedule exactness vs the sequential stack,
composition with dp, and trainability through the pipeline."""
import jax
import jax.numpy as jnp
import pytest

from nos_tpu.models.llama import (
    init_llama_params,
    llama_forward,
    llama_loss,
    tiny_config,
)
from nos_tpu.parallel.mesh import mesh_from_devices
from nos_tpu.parallel.pipeline import (
    pipeline_llama_forward,
    pipeline_llama_loss,
    pipeline_param_sharding,
    stack_layer_params,
)


def setup(n_layers=4, **mesh_kw):
    config = tiny_config(n_layers=n_layers)
    params = init_llama_params(jax.random.key(0), config)
    tokens = jax.random.randint(jax.random.key(1), (8, 16), 0, config.vocab_size)
    return config, params, tokens


def assert_logits_match(got, want):
    """bf16 activations: scan-stacked layers round differently than the
    unrolled stack, so logits agree to bf16 noise; the predicted
    distributions must agree tightly (float32 comparison is exact — see
    the f32 sanity run in the module below)."""
    assert jnp.allclose(got, want, atol=1e-1), float(jnp.abs(got - want).max())
    pa = jax.nn.softmax(got, axis=-1)
    pb = jax.nn.softmax(want, axis=-1)
    assert float(jnp.abs(pa - pb).max()) < 5e-3


class TestPipelineForward:
    @pytest.mark.parametrize("pp", [2, 4])
    def test_matches_sequential(self, pp):
        config, params, tokens = setup(n_layers=4)
        mesh = mesh_from_devices((pp,), ("pp",), jax.devices()[:pp])
        stacked = stack_layer_params(params)
        got = jax.jit(
            lambda p, t: pipeline_llama_forward(p, t, config, mesh)
        )(stacked, tokens)
        want = llama_forward(params, tokens, config)
        assert_logits_match(got, want)

    def test_more_microbatches_than_stages(self):
        config, params, tokens = setup(n_layers=2)
        mesh = mesh_from_devices((2,), ("pp",), jax.devices()[:2])
        stacked = stack_layer_params(params)
        got = jax.jit(
            lambda p, t: pipeline_llama_forward(p, t, config, mesh, n_microbatches=8)
        )(stacked, tokens)
        want = llama_forward(params, tokens, config)
        assert_logits_match(got, want)

    def test_composes_with_dp(self):
        config, params, tokens = setup(n_layers=4)
        mesh = mesh_from_devices((2, 4), ("dp", "pp"))
        stacked = stack_layer_params(params)
        got = jax.jit(
            lambda p, t: pipeline_llama_forward(p, t, config, mesh)
        )(stacked, tokens)
        want = llama_forward(params, tokens, config)
        assert_logits_match(got, want)

    def test_exact_in_float32(self):
        """With f32 activations the schedule is bit-for-bit faithful to the
        sequential stack (no tolerance games)."""
        config = tiny_config(n_layers=2, dtype=jnp.float32)
        params = init_llama_params(jax.random.key(0), config)
        tokens = jax.random.randint(jax.random.key(1), (4, 8), 0, config.vocab_size)
        mesh = mesh_from_devices((2,), ("pp",), jax.devices()[:2])
        got = jax.jit(
            lambda p, t: pipeline_llama_forward(p, t, config, mesh)
        )(stack_layer_params(params), tokens)
        want = llama_forward(params, tokens, config)
        assert jnp.allclose(got, want, atol=1e-5), float(jnp.abs(got - want).max())

    def test_rejects_indivisible_layers(self):
        config, params, tokens = setup(n_layers=3)
        mesh = mesh_from_devices((2,), ("pp",), jax.devices()[:2])
        with pytest.raises(ValueError):
            pipeline_llama_forward(stack_layer_params(params), tokens, config, mesh)


class TestPipelineTraining:
    def test_loss_and_grads(self):
        config, params, tokens = setup(n_layers=4)
        mesh = mesh_from_devices((4,), ("pp",), jax.devices()[:4])
        stacked = stack_layer_params(params)
        sharding = pipeline_param_sharding(mesh, config)
        stacked = jax.device_put(stacked, sharding)

        loss, grads = jax.jit(
            jax.value_and_grad(lambda p: pipeline_llama_loss(p, tokens, config, mesh))
        )(stacked)
        seq_loss = llama_loss(params, tokens, config)
        assert abs(float(loss) - float(seq_loss)) < 2e-2
        # gradients reach every stage's stacked layers
        g = grads["layers"]["wq"]
        assert g.shape[0] == config.n_layers
        per_layer = jnp.abs(g).reshape(config.n_layers, -1).max(axis=1)
        assert bool(jnp.all(per_layer > 0))

    def test_stacked_sharding_spec(self):
        config, params, _ = setup(n_layers=4)
        mesh = mesh_from_devices((2, 2, 2), ("dp", "pp", "tp"))
        sharding = pipeline_param_sharding(mesh, config)
        assert sharding["layers"]["wq"].spec == ("pp", "dp", "tp")
        assert sharding["embed"].spec[0] == "tp"

    def test_loss_with_per_tick_remat_matches(self):
        """config.remat checkpoints each (microbatch, stage) application;
        numerics are identical, memory is bounded by the carries."""
        config = tiny_config(n_layers=2)
        config_r = tiny_config(n_layers=2, remat=True)
        params = init_llama_params(jax.random.key(0), config)
        tokens = jax.random.randint(jax.random.key(1), (4, 16), 0, config.vocab_size)
        mesh = mesh_from_devices((2,), ("pp",), jax.devices()[:2])
        stacked = stack_layer_params(params)
        # jit is mandatory for remat-inside-shard_map (and is how training
        # always runs anyway).
        l1, g1 = jax.jit(jax.value_and_grad(
            lambda p: pipeline_llama_loss(p, tokens, config, mesh)
        ))(stacked)
        l2, g2 = jax.jit(jax.value_and_grad(
            lambda p: pipeline_llama_loss(p, tokens, config_r, mesh)
        ))(stacked)
        # bit-identity between remat and non-remat graphs is
        # backend-dependent (XLA may reorder the replayed forward); the
        # numerics contract is tolerance-level equality.
        assert jnp.allclose(l1, l2, atol=1e-6), (float(l1), float(l2))
        a = jnp.asarray(g1["layers"]["wq"], jnp.float32)
        b = jnp.asarray(g2["layers"]["wq"], jnp.float32)
        assert jnp.allclose(a, b, atol=1e-6)

    def test_loss_composes_with_dp(self):
        config, params, tokens = setup(n_layers=2)
        mesh = mesh_from_devices((2, 2), ("dp", "pp"))
        stacked = stack_layer_params(params)
        got = jax.jit(
            lambda p, t: pipeline_llama_loss(p, t, config, mesh)
        )(stacked, tokens)
        want = llama_loss(params, tokens, config)
        assert abs(float(got) - float(want)) < 2e-2

    def test_moe_layers_pipeline(self):
        """MoE blocks ride the pipeline: routed FFN per stage, loss matches
        the sequential forward (sans the balance aux term, which the
        pipeline loss does not thread)."""
        from nos_tpu.models.llama import llama_forward, next_token_nll

        config = tiny_config(n_layers=2, n_experts=4)
        params = init_llama_params(jax.random.key(0), config)
        tokens = jax.random.randint(jax.random.key(1), (4, 16), 0, config.vocab_size)
        mesh = mesh_from_devices((2,), ("pp",), jax.devices()[:2])
        got = jax.jit(
            lambda p, t: pipeline_llama_loss(p, t, config, mesh)
        )(stack_layer_params(params), tokens)
        want = next_token_nll(llama_forward(params, tokens, config), tokens)
        assert abs(float(got) - float(want)) < 2e-2, (float(got), float(want))
