"""Checkpoint/resume: round-trip, cross-mesh resharding, and resumed
training continuity — the preempt-and-reschedule story end to end."""
import jax
import jax.numpy as jnp
import pytest

from nos_tpu.models.llama import init_llama_params, tiny_config
from nos_tpu.parallel.checkpoint import (
    latest_step,
    restore_checkpoint,
    save_checkpoint,
)
from nos_tpu.parallel.mesh import mesh_from_devices
from nos_tpu.parallel.train import make_train_step


def make_tokens():
    return jax.random.randint(jax.random.key(9), (8, 16), 0, 256)


class TestCheckpointResume:
    def test_round_trip_same_mesh(self, tmp_path):
        config = tiny_config()
        mesh = mesh_from_devices((2, 2), ("dp", "tp"), jax.devices()[:4])
        step_fn, shard_state = make_train_step(mesh, config)
        state = shard_state(init_llama_params(jax.random.key(0), config))
        state, _ = step_fn(state, make_tokens())

        save_checkpoint(str(tmp_path / "ckpt"), state, step=1)
        assert latest_step(str(tmp_path / "ckpt")) == 1
        restored, step = restore_checkpoint(str(tmp_path / "ckpt"), state)
        assert step == 1
        for a, b in zip(jax.tree.leaves(state), jax.tree.leaves(restored)):
            assert a.dtype == b.dtype
            assert jnp.array_equal(
                jnp.asarray(a, jnp.float32), jnp.asarray(b, jnp.float32)
            )

    def test_restore_onto_different_mesh(self, tmp_path):
        """The preemption story: a job checkpointed on a 2x2 slice resumes
        on a 1x8-shaped mesh; orbax reshards onto the new NamedShardings."""
        config = tiny_config()
        mesh_a = mesh_from_devices((2, 2), ("dp", "tp"), jax.devices()[:4])
        step_a, shard_a = make_train_step(mesh_a, config)
        state = shard_a(init_llama_params(jax.random.key(0), config))
        state, loss_a = step_a(state, make_tokens())
        save_checkpoint(str(tmp_path / "ckpt"), state, step=5)

        mesh_b = mesh_from_devices((4, 2), ("dp", "tp"))
        step_b, shard_b = make_train_step(mesh_b, config)
        target = shard_b(init_llama_params(jax.random.key(1), config))
        restored, step = restore_checkpoint(str(tmp_path / "ckpt"), target)
        assert step == 5
        # restored arrays carry mesh_b shardings
        leaf = jax.tree.leaves(restored)[0]
        assert leaf.sharding.mesh.shape == {"dp": 4, "tp": 2}
        # and training continues where it left off
        restored, loss_b = step_b(restored, make_tokens())
        assert jnp.isfinite(loss_b)
        assert float(loss_b) < float(loss_a) + 0.5

    def test_async_checkpointer_loop(self, tmp_path):
        """The training-loop form: async saves overlap steps, restore sees
        the latest after close."""
        from nos_tpu.parallel.checkpoint import Checkpointer

        config = tiny_config()
        mesh = mesh_from_devices((2, 2), ("dp", "tp"), jax.devices()[:4])
        step_fn, shard_state = make_train_step(mesh, config)
        state = shard_state(init_llama_params(jax.random.key(0), config))
        with Checkpointer(str(tmp_path / "ckpt"), max_to_keep=2) as ckpt:
            for i in range(3):
                state, _ = step_fn(state, make_tokens())
                ckpt.save(i, state)
            ckpt.wait()
            assert ckpt.latest_step() == 2
            restored, step = ckpt.restore(state)
            assert step == 2
            with pytest.raises(RuntimeError):
                ckpt.save(1, state)  # stale step must not be silent

    def test_missing_checkpoint_raises(self, tmp_path):
        config = tiny_config()
        mesh = mesh_from_devices((1, 1), ("dp", "tp"), jax.devices()[:1])
        _, shard_state = make_train_step(mesh, config)
        state = shard_state(init_llama_params(jax.random.key(0), config))
        assert latest_step(str(tmp_path / "nope")) is None
        with pytest.raises(FileNotFoundError):
            restore_checkpoint(str(tmp_path / "empty"), state)
