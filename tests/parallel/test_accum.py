"""Gradient accumulation: N micro-batches == one large batch.

With equal micro sizes, the mean of micro gradients equals the full-batch
gradient, so the accumulated step must land on (numerically) the same
parameters — effective batch grows without growing activation memory.
"""
import jax
import jax.numpy as jnp
import numpy as np
import pytest

from nos_tpu.models.llama import init_llama_params, tiny_config
from nos_tpu.parallel.mesh import mesh_from_devices
from nos_tpu.parallel.train import make_train_step


@pytest.fixture(scope="module")
def setup():
    config = tiny_config()
    mesh = mesh_from_devices((2, 2), ("dp", "tp"), jax.devices()[:4])
    tokens = jax.random.randint(jax.random.key(1), (8, 16), 0, config.vocab_size)
    return config, mesh, tokens


def flat(tree):
    return np.concatenate(
        [np.asarray(x, np.float32).ravel() for x in jax.tree.leaves(tree)]
    )


class TestGradAccumulation:
    def test_accumulated_step_matches_full_batch(self, setup):
        config, mesh, tokens = setup
        step1, shard1 = make_train_step(mesh, config)
        stepN, shardN = make_train_step(mesh, config, accum_steps=4)
        state1 = shard1(init_llama_params(jax.random.key(0), config))
        stateN = shardN(init_llama_params(jax.random.key(0), config))
        state1, loss1 = step1(state1, tokens)
        stateN, lossN = stepN(stateN, tokens)
        assert abs(float(loss1) - float(lossN)) < 5e-3
        np.testing.assert_allclose(
            flat(state1[0]), flat(stateN[0]), atol=2e-2, rtol=2e-2
        )

    def test_accum_with_optax_two_steps_stable_dtypes(self, setup):
        import optax

        config, mesh, tokens = setup
        step, shard = make_train_step(
            mesh, config, learning_rate=1e-3, momentum=0.9, optimizer=None,
            accum_steps=2,
        )
        state = shard(init_llama_params(jax.random.key(0), config))
        state, l0 = step(state, tokens)
        state, l1 = step(state, tokens)  # second step: same trace, no dtype flip
        assert np.isfinite(float(l0)) and np.isfinite(float(l1))

        opt = optax.adamw(1e-3)
        step_o, shard_o = make_train_step(mesh, config, optimizer=opt, accum_steps=2)
        state_o = shard_o(init_llama_params(jax.random.key(0), config))
        state_o, a = step_o(state_o, tokens)
        state_o, b = step_o(state_o, tokens)
        assert float(b) < float(a) + 1.0  # trains without blowing up

    def test_indivisible_batch_rejected(self, setup):
        config, mesh, _ = setup
        step, shard = make_train_step(mesh, config, accum_steps=3)
        state = shard(init_llama_params(jax.random.key(0), config))
        bad = jnp.zeros((8, 16), jnp.int32)  # 8 % 3 != 0
        with pytest.raises(ValueError):
            step(state, bad)
