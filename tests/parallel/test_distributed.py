"""Distributed bootstrap: env coordinates, single-process no-op, and the
expander's stamping of gang members."""
import pytest

from nos_tpu.parallel.distributed import (
    COORDINATOR_ENV,
    NUM_PROCESSES_ENV,
    PROCESS_ID_ENV,
    env_coordinates,
    gang_member_env,
    initialize,
)


class TestEnvCoordinates:
    def test_roundtrip(self):
        env = gang_member_env("big", "ml", rank=2, size=4)
        assert env_coordinates(env) == ("big.big.ml.svc:8476", 4, 2)

    @pytest.mark.parametrize(
        "env",
        [
            {},
            {COORDINATOR_ENV: "x:1"},  # missing rank/size
            {COORDINATOR_ENV: "x:1", NUM_PROCESSES_ENV: "4", PROCESS_ID_ENV: "9"},
            {COORDINATOR_ENV: "x:1", NUM_PROCESSES_ENV: "bad", PROCESS_ID_ENV: "0"},
            {COORDINATOR_ENV: "", NUM_PROCESSES_ENV: "4", PROCESS_ID_ENV: "0"},
        ],
    )
    def test_invalid_coordinates(self, env):
        assert env_coordinates(env) is None

    def test_initialize_is_noop_without_coordinates(self):
        assert initialize({}) is False

    def test_initialize_is_noop_for_size_one(self):
        env = gang_member_env("solo", "ml", rank=0, size=1)
        assert initialize(env) is False


class TestExpanderStampsCoordinates:
    def test_gang_members_carry_ranks(self):
        from nos_tpu.api.v1alpha1 import constants
        from nos_tpu.controllers.partitioner.multihost import MultihostExpander
        from nos_tpu.kube.controller import Request
        from nos_tpu.kube.store import KubeStore
        from tests.factory import build_pod, build_tpu_node

        store = KubeStore()
        store.create(build_tpu_node(name="tpu-0"))
        store.create(build_pod("big", {constants.RESOURCE_TPU: 32}))
        MultihostExpander(store).reconcile(Request(name="big", namespace="default"))

        leader = store.get("Pod", "big", "default")
        assert env_coordinates(leader.spec.containers[0].env) == (
            "big.big.default.svc:8476", 4, 0,
        )
        for i in range(1, 4):
            worker = store.get("Pod", f"big-w{i}", "default")
            coords = env_coordinates(worker.spec.containers[0].env)
            assert coords == ("big.big.default.svc:8476", 4, i)
