"""Ulysses all-to-all sequence parallelism: exactness vs the dense
oracle, gradient parity, model integration, and the loud-rejection
contracts for shapes only the ring can serve."""
import jax
import jax.numpy as jnp
import pytest

from nos_tpu.models.llama import init_llama_params, llama_loss, tiny_config
from nos_tpu.parallel.mesh import mesh_from_devices
from nos_tpu.parallel.ulysses import _dense_causal, ulysses_attention


def random_qkv(key, b, s, hq, hkv, hd, dtype=jnp.float32):
    kq, kk, kv = jax.random.split(key, 3)
    return (
        jax.random.normal(kq, (b, s, hq, hd), dtype),
        jax.random.normal(kk, (b, s, hkv, hd), dtype),
        jax.random.normal(kv, (b, s, hkv, hd), dtype),
    )


def dense_oracle(q, k, v, causal=True):
    b, s, hq, hd = q.shape
    return _dense_causal(q, k, v, causal).reshape(b, s, hq * hd)


class TestUlyssesExactness:
    def test_forward_matches_dense(self):
        q, k, v = random_qkv(jax.random.key(0), b=2, s=32, hq=8, hkv=4, hd=16)
        mesh = mesh_from_devices((4,), ("sp",), jax.devices()[:4])
        got = jax.jit(lambda q, k, v: ulysses_attention(q, k, v, mesh))(q, k, v)
        want = dense_oracle(q, k, v)
        assert jnp.allclose(got, want, atol=1e-5), float(jnp.abs(got - want).max())

    def test_forward_non_causal(self):
        q, k, v = random_qkv(jax.random.key(1), b=1, s=16, hq=4, hkv=4, hd=8)
        mesh = mesh_from_devices((4,), ("sp",), jax.devices()[:4])
        got = jax.jit(
            lambda q, k, v: ulysses_attention(q, k, v, mesh, causal=False)
        )(q, k, v)
        want = dense_oracle(q, k, v, causal=False)
        assert jnp.allclose(got, want, atol=1e-5)

    def test_gradients_match_dense(self):
        q, k, v = random_qkv(jax.random.key(2), b=1, s=16, hq=8, hkv=4, hd=8)
        mesh = mesh_from_devices((4,), ("sp",), jax.devices()[:4])
        seed = jax.random.normal(jax.random.key(3), (1, 16, 64))

        def f_u(q, k, v):
            return jnp.sum(ulysses_attention(q, k, v, mesh) * seed)

        def f_d(q, k, v):
            return jnp.sum(dense_oracle(q, k, v) * seed)

        g_u = jax.jit(jax.grad(f_u, argnums=(0, 1, 2)))(q, k, v)
        g_d = jax.grad(f_d, argnums=(0, 1, 2))(q, k, v)
        for a, b_ in zip(g_u, g_d):
            assert jnp.allclose(a, b_, atol=1e-5), float(jnp.abs(a - b_).max())

    def test_composes_with_dp_and_tp(self):
        # ('dp','sp','tp') mesh: heads over tp, sequence over sp.
        q, k, v = random_qkv(jax.random.key(4), b=2, s=16, hq=8, hkv=8, hd=8)
        mesh = mesh_from_devices((2, 2, 2), ("dp", "sp", "tp"), jax.devices()[:8])
        got = jax.jit(lambda q, k, v: ulysses_attention(q, k, v, mesh))(q, k, v)
        want = dense_oracle(q, k, v)
        assert jnp.allclose(got, want, atol=1e-5), float(jnp.abs(got - want).max())

    def test_flash_backend_matches_dense(self):
        q, k, v = random_qkv(
            jax.random.key(5), b=1, s=32, hq=4, hkv=2, hd=16, dtype=jnp.bfloat16
        )
        mesh = mesh_from_devices((2,), ("sp",), jax.devices()[:2])
        got = jax.jit(
            lambda q, k, v: ulysses_attention(q, k, v, mesh, attention="flash")
        )(q, k, v)
        want = dense_oracle(q, k, v)
        assert jnp.allclose(
            got.astype(jnp.float32), want.astype(jnp.float32), atol=3e-2
        )


class TestUlyssesContracts:
    def test_rejects_indivisible_heads(self):
        q, k, v = random_qkv(jax.random.key(6), b=1, s=16, hq=2, hkv=1, hd=8)
        mesh = mesh_from_devices((4,), ("sp",), jax.devices()[:4])
        with pytest.raises(ValueError, match="ring attention"):
            ulysses_attention(q, k, v, mesh)

    def test_rejects_kv_heads_below_sp_degree(self):
        # 2 kv heads cannot split over sp=8 (the same divisibility check
        # also guarantees head chunks never split a GQA group).
        q, k, v = random_qkv(jax.random.key(7), b=1, s=16, hq=8, hkv=8, hd=8)
        k = k[:, :, :2]
        v = v[:, :, :2]
        mesh = mesh_from_devices((8,), ("sp",), jax.devices()[:8])
        with pytest.raises(ValueError, match="ring attention"):
            ulysses_attention(q, k, v, mesh)

    def test_rejects_missing_sp_axis(self):
        q, k, v = random_qkv(jax.random.key(8), b=1, s=16, hq=4, hkv=4, hd=8)
        mesh = mesh_from_devices((2,), ("dp",), jax.devices()[:2])
        with pytest.raises(ValueError, match="no sequence axis"):
            ulysses_attention(q, k, v, mesh)


class TestUlyssesModelIntegration:
    def test_llama_loss_matches_dense_loss(self):
        config = tiny_config(sp_strategy="ulysses", dtype=jnp.float32)
        params = init_llama_params(jax.random.key(0), config)
        tokens = jax.random.randint(jax.random.key(2), (2, 16), 0, config.vocab_size)
        dense = jax.jit(
            lambda p, t: llama_loss(p, t, tiny_config(dtype=jnp.float32))
        )(params, tokens)
        mesh = mesh_from_devices((1, 4, 1), ("dp", "sp", "tp"), jax.devices()[:4])
        ulysses = jax.jit(lambda p, t: llama_loss(p, t, config, mesh))(params, tokens)
        assert abs(float(dense) - float(ulysses)) < 1e-4

    def test_train_step_runs_on_dp_sp_tp(self):
        from nos_tpu.parallel.train import make_train_step

        config = tiny_config(sp_strategy="ulysses")
        mesh = mesh_from_devices((2, 2, 2), ("dp", "sp", "tp"), jax.devices()[:8])
        step, shard = make_train_step(mesh, config)
        state = shard(init_llama_params(jax.random.key(0), config))
        tokens = jax.random.randint(jax.random.key(1), (8, 16), 0, config.vocab_size)
        state, loss = step(state, tokens)
        assert jnp.isfinite(loss)

    def test_window_contracts_enforced(self):
        # Shared contract (review): SP entries reject the same invalid
        # windows the kernel does — no silent ignore, no 0/0 NaN.
        from nos_tpu.parallel.ring_attention import (
            ring_attention,
            ring_flash_attention,
        )

        q, k, v = random_qkv(jax.random.key(9), b=1, s=16, hq=4, hkv=4, hd=8)
        mesh = mesh_from_devices((4,), ("sp",), jax.devices()[:4])
        for fn in (ulysses_attention, ring_attention, ring_flash_attention):
            with pytest.raises(ValueError, match="causal"):
                fn(q, k, v, mesh, causal=False, window=4)
            with pytest.raises(ValueError, match=">= 1"):
                fn(q, k, v, mesh, window=0)
