"""CapacityLedger unit coverage: integration math, idle attribution,
fragmentation, gang clocks, quota posture, and the self-check shadow."""
import pytest

from nos_tpu.api.v1alpha1 import annotations as annot
from nos_tpu.api.v1alpha1 import constants
from nos_tpu.capacity import (
    BUCKET_NO_DEMAND,
    BUCKET_PENDING,
    BUCKET_RECONFIG,
    BUCKET_RESERVED,
    CapacityLedger,
    cluster_fragmentation_index,
    fragmentation_from_annotations,
    largest_profile_chips,
)
from nos_tpu.capacity.ledger import dominant_unserved_reason, state_from_store
from nos_tpu.kube.store import KubeStore

from tests.factory import V5E, build_pod, build_tpu_node

T0 = 1_000_000.0


def make_ledger(metrics=False):
    store = KubeStore()
    return store, CapacityLedger(store, metrics=metrics)


class TestIntegration:
    def test_busy_and_idle_chip_seconds(self):
        store, ledger = make_ledger()
        store.create(build_tpu_node(name="n1", chips=8))
        store.create(build_tpu_node(name="n2", chips=8))
        store.create(build_pod("w", {constants.RESOURCE_TPU: 4}, node="n1"))
        ledger.observe(T0)
        ledger.observe(T0 + 10)
        t = ledger.totals()
        assert t["total"] == 160.0  # 16 chips x 10 s
        assert t["busy"] == 40.0  # 4 bound chips x 10 s
        assert t["idle"][BUCKET_NO_DEMAND] == 120.0
        assert ledger.utilization() == pytest.approx(0.25)

    def test_interval_integrates_pre_drain_state(self):
        # A pod bound DURING the interval contributes nothing to that
        # interval: transitions become visible at the end of it.
        store, ledger = make_ledger()
        store.create(build_tpu_node(name="n1", chips=8))
        ledger.observe(T0)
        store.create(build_pod("w", {constants.RESOURCE_TPU: 8}, node="n1"))
        ledger.observe(T0 + 5)  # interval [T0, T0+5) was all idle
        assert ledger.totals()["busy"] == 0.0
        ledger.observe(T0 + 15)  # now the binding is in effect
        assert ledger.totals()["busy"] == 80.0

    def test_pending_coverage_rule(self):
        # 8 idle chips, 4 pending chips: only min(idle, pending) counts as
        # scheduling inefficiency; the rest is genuine no-demand idle.
        store, ledger = make_ledger()
        store.create(build_tpu_node(name="n1", chips=8))
        store.create(build_pod("pend", {constants.RESOURCE_TPU: 4}))
        ledger.observe(T0, unserved={"default/pend": "insufficient capacity: 4"})
        ledger.observe(T0 + 10)
        t = ledger.totals()
        assert t["idle"][BUCKET_PENDING] == 40.0
        assert t["idle"][BUCKET_NO_DEMAND] == 40.0
        assert t["reasons"] == {"insufficient capacity": 40.0}
        assert ledger.idle_pending_fraction() == pytest.approx(0.5)

    def test_frozen_node_idles_into_reconfig(self):
        store, ledger = make_ledger()
        node = build_tpu_node(
            name="n1",
            chips=8,
            annotations={
                annot.SPEC_PARTITIONING_PLAN: "plan-2",
                annot.STATUS_PARTITIONING_PLAN: "plan-1",
            },
        )
        store.create(node)
        # Pending demand exists, but a frozen node is not schedulable
        # inefficiency — it is actively being repartitioned.
        store.create(build_pod("pend", {constants.RESOURCE_TPU: 4}))
        ledger.observe(T0)
        ledger.observe(T0 + 10)
        t = ledger.totals()
        assert t["idle"][BUCKET_RECONFIG] == 80.0
        assert t["idle"][BUCKET_PENDING] == 0.0

    def test_reserved_node_idles_into_reserved_bucket(self):
        store, ledger = make_ledger()
        node = build_tpu_node(
            name="n1",
            chips=8,
            annotations={annot.PREFIX + "reserved-for": "ml/gang-leader"},
        )
        store.create(node)
        ledger.observe(T0)
        ledger.observe(T0 + 10)
        assert ledger.totals()["idle"][BUCKET_RESERVED] == 80.0

    def test_autoscaler_grace_hold_idles_into_its_own_bucket(self):
        # A board vacated by scale-to-zero carries the autoscaler's grace
        # annotations: that idle window is the cost of instant cold
        # starts, not unexplained no-demand waste — and when the hold is
        # released the same chips flow back to no-demand.
        from nos_tpu.capacity import BUCKET_AUTOSCALER

        store, ledger = make_ledger()
        node = build_tpu_node(
            name="n1",
            chips=8,
            annotations={
                annot.AUTOSCALER_RESERVED: "default.svc",
                annot.AUTOSCALER_RESERVED_UNTIL: str(T0 + 60),
            },
        )
        store.create(node)
        ledger.observe(T0)
        ledger.observe(T0 + 10)
        assert ledger.totals()["idle"][BUCKET_AUTOSCALER] == 80.0
        store.patch_annotations(
            "Node", "n1", "",
            {annot.AUTOSCALER_RESERVED: None, annot.AUTOSCALER_RESERVED_UNTIL: None},
        )
        ledger.observe(T0 + 20)  # interval [10, 20) still held (pre-drain)
        ledger.observe(T0 + 30)
        t = ledger.totals()
        assert t["idle"][BUCKET_AUTOSCALER] == 160.0
        assert t["idle"][BUCKET_NO_DEMAND] == 80.0

    def test_namespace_and_pool_rollups(self):
        store, ledger = make_ledger()
        store.create(build_tpu_node(name="n1", chips=8))
        store.create(build_pod("a", {constants.RESOURCE_TPU: 2}, ns="ml", node="n1"))
        store.create(build_pod("b", {constants.RESOURCE_TPU: 4}, ns="batch", node="n1"))
        ledger.observe(T0)
        ledger.observe(T0 + 10)
        t = ledger.totals()
        assert t["namespaces"] == {"ml": 20.0, "batch": 40.0}
        assert t["pools"]["tpu"] == {"total": 80.0, "busy": 60.0}

    def test_finished_pod_stops_accruing(self):
        store, ledger = make_ledger()
        store.create(build_tpu_node(name="n1", chips=8))
        pod = build_pod("w", {constants.RESOURCE_TPU: 8}, node="n1")
        store.create(pod)
        ledger.observe(T0)
        ledger.observe(T0 + 10)
        assert ledger.totals()["busy"] == 80.0
        done = build_pod("w", {constants.RESOURCE_TPU: 8}, node="n1")
        done.status.phase = "Succeeded"
        store.update(done)
        ledger.observe(T0 + 11)  # drains the phase change
        ledger.observe(T0 + 21)
        assert ledger.totals()["busy"] == 80.0 + 8.0  # one more second, then idle

    def test_node_delete_drops_from_accounting(self):
        store, ledger = make_ledger()
        store.create(build_tpu_node(name="n1", chips=8))
        ledger.observe(T0)
        store.delete("Node", "n1")
        ledger.observe(T0 + 10)
        ledger.observe(T0 + 20)
        assert ledger.totals()["total"] == 80.0  # only the first interval

    def test_busy_capped_at_capacity(self):
        # Double-booked chips (mid-preemption) never integrate above the
        # node's physical capacity.
        store, ledger = make_ledger()
        store.create(build_tpu_node(name="n1", chips=8))
        store.create(build_pod("a", {constants.RESOURCE_TPU: 8}, node="n1"))
        store.create(build_pod("b", {constants.RESOURCE_TPU: 8}, node="n1"))
        ledger.observe(T0)
        ledger.observe(T0 + 10)
        assert ledger.totals()["busy"] == 80.0


class TestHeartbeat:
    def test_accrues_without_control_loop_observes(self):
        # A quiet steady-state cluster (no plan cycles, no explicit
        # observes) must still integrate chip-seconds.
        import time

        store, ledger = make_ledger()
        store.create(build_tpu_node(name="n1", chips=8))
        ledger.start_heartbeat(interval_seconds=0.05)
        try:
            deadline = time.time() + 5.0
            while time.time() < deadline and ledger.totals()["total"] <= 0:
                time.sleep(0.02)
        finally:
            ledger.stop_heartbeat()
        assert ledger.observes >= 2
        assert ledger.totals()["total"] > 0
        assert ledger.self_check() == []

    def test_start_and_stop_are_idempotent(self):
        _, ledger = make_ledger()
        ledger.start_heartbeat(interval_seconds=60.0)
        thread = ledger._hb_thread
        ledger.start_heartbeat(interval_seconds=60.0)
        assert ledger._hb_thread is thread  # second start is a no-op
        ledger.stop_heartbeat()
        ledger.stop_heartbeat()
        assert ledger._hb_thread is None


class TestReasons:
    def test_dominant_reason_majority_and_prefix(self):
        assert (
            dominant_unserved_reason(
                {
                    "a": "insufficient google.com/tpu: needs 8",
                    "b": "insufficient google.com/tpu: needs 4",
                    "c": "untolerated taint: k=v",
                }
            )
            == "insufficient google.com/tpu"
        )

    def test_dominant_reason_tie_is_lexicographic(self):
        assert dominant_unserved_reason({"a": "beta", "b": "alpha"}) == "alpha"

    def test_empty_unserved_is_none(self):
        assert dominant_unserved_reason({}) is None


class TestFragmentation:
    def test_no_free_chips_is_not_fragmented(self):
        assert fragmentation_from_annotations({}, V5E) == (0.0, 0, 0)
        ann = annot.status_from_devices(free={}, used={0: {"2x4": 1}})
        assert fragmentation_from_annotations(ann, V5E) == (0.0, 0, 0)

    def test_whole_board_free_is_not_fragmented(self):
        ann = annot.status_from_devices(free={0: {"2x4": 1}}, used={})
        index, largest, free = fragmentation_from_annotations(ann, V5E)
        assert (index, largest, free) == (0.0, 8, 8)

    def test_scattered_singles_are_fragmented(self):
        # 3 free chips as 1x1s: the largest V5E shape fitting is a 1x2
        # (2 chips), so a third of the free capacity is uncarveable.
        ann = annot.status_from_devices(free={0: {"1x1": 3}}, used={0: {"1x1": 5}})
        index, largest, free = fragmentation_from_annotations(ann, V5E)
        assert (largest, free) == (2, 3)
        assert index == pytest.approx(1.0 - 2.0 / 3.0)

    def test_free_split_across_boards_cannot_merge(self):
        # 16-chip node (two boards), each board has a free 2x2: largest
        # single carve is 4 chips out of 8 free — index 0.5.
        ann = annot.status_from_devices(
            free={0: {"2x2": 1}, 1: {"2x2": 1}}, used={0: {"2x2": 1}, 1: {"2x2": 1}}
        )
        index, largest, free = fragmentation_from_annotations(ann, V5E)
        assert (largest, free) == (4, 8)
        assert index == pytest.approx(0.5)

    def test_cluster_index_is_not_the_weighted_node_mean(self):
        """Regression for the bench_capacity report of fragmentation 0.0
        at 81.85% utilization with a 2-chip largest free slice out of
        1487 free chips: the free-weighted mean of per-node indices goes
        to 0.0 exactly when every node is reduced to slivers. Hand-made
        3-node fixture: each node's free capacity is one 1x2 (2 chips),
        so every per-node index is 0.0 (largest carve == node free), the
        old rollup reported 0.0 — but cluster-wide the best carve is 2
        chips against min(6 free, 8 largest-profile) askable:
        index = 1 - 2/6 ≈ 0.667."""
        sliver = annot.status_from_devices(
            free={0: {"1x2": 1}}, used={0: {"1x2": 3}}
        )
        per_node = fragmentation_from_annotations(dict(sliver), V5E)
        assert per_node == (0.0, 2, 2)
        assert largest_profile_chips(V5E) == 8
        assert cluster_fragmentation_index(6, 2, 8) == pytest.approx(2.0 / 3.0)
        # End to end through the ledger's /debug rollup.
        store, ledger = make_ledger()
        for i in range(3):
            store.create(
                build_tpu_node(name=f"n{i}", chips=8, annotations=dict(sliver))
            )
        ledger.observe(T0)
        cluster = ledger.debug_payload()["cluster"]
        assert cluster["fragmentation"] == pytest.approx(2.0 / 3.0)
        assert cluster["largest_free_slice_chips"] == 2
        # The bench shape itself: best carve 2 chips, free total huge, so
        # the askable bound is the 8-chip largest profile -> 0.75.
        assert cluster_fragmentation_index(1487, 2, 8) == pytest.approx(0.75)

    def test_cluster_index_zero_when_nothing_free_or_biggest_fits(self):
        assert cluster_fragmentation_index(0, 0, 8) == 0.0
        # A whole board free somewhere: the largest askable slice fits.
        assert cluster_fragmentation_index(24, 8, 8) == 0.0
        # Unknown accelerator (no profile table): fall back to free total.
        assert cluster_fragmentation_index(6, 2, 0) == pytest.approx(2.0 / 3.0)


class TestGangClocks:
    def test_arrival_feasible_bound_flow(self):
        _, ledger = make_ledger()
        ledger.note_gang_arrival("ml/g1", T0)
        ledger.note_gang_arrival("ml/g1", T0 + 1)  # idempotent
        ledger.note_gang_feasible("ml/g1", T0 + 2)
        ledger.note_gang_feasible("ml/g1", T0 + 3)  # first one wins
        ledger.note_gang_bound("ml/g1", T0 + 4)
        recent = ledger.debug_payload()["gangs"]["recent"]
        assert recent == [
            {"gang": "ml/g1", "wait_seconds": 4.0, "feasible_after": 2.0}
        ]
        # Bound pops the clock: a repeat is a no-op, not a double-observe.
        ledger.note_gang_bound("ml/g1", T0 + 9)
        assert len(ledger.debug_payload()["gangs"]["recent"]) == 1

    def test_timeout_drops_clock(self):
        _, ledger = make_ledger()
        ledger.note_gang_arrival("ml/g1", T0)
        ledger.drop_gang("ml/g1")
        payload = ledger.debug_payload()["gangs"]
        assert payload["waiting"] == {} and payload["recent"] == []


class TestSelfCheck:
    def test_clean_after_observe(self):
        store, ledger = make_ledger()
        store.create(build_tpu_node(name="n1", chips=8))
        store.create(build_pod("w", {constants.RESOURCE_TPU: 4}, node="n1"))
        store.create(build_pod("pend", {constants.RESOURCE_TPU: 4}))
        ledger.observe(T0)
        assert ledger.self_check() == []

    def test_skips_when_store_moved_past_watermark(self):
        store, ledger = make_ledger()
        store.create(build_tpu_node(name="n1", chips=8))
        ledger.observe(T0)
        store.create(build_pod("racer", {constants.RESOURCE_TPU: 1}))
        # The store moved; a diff now would be racy, so the check skips.
        assert ledger.self_check() == []
        ledger.observe(T0 + 1)
        assert ledger.self_check() == []

    def test_detects_corrupted_incremental_state(self):
        store, ledger = make_ledger()
        store.create(build_tpu_node(name="n1", chips=8))
        ledger.observe(T0)
        ledger._bound["default/ghost"] = ("n1", 4, "default")  # corrupt
        diffs = ledger.self_check()
        assert diffs and "bound[default/ghost]" in diffs[0]

    def test_state_from_store_matches_full_lifecycle(self):
        store, ledger = make_ledger()
        store.create(build_tpu_node(name="n1", chips=8))
        store.create(build_tpu_node(name="n2", chips=16, topology="4x4"))
        store.create(build_pod("a", {constants.RESOURCE_TPU: 4}, node="n1"))
        store.create(build_pod("b", {constants.RESOURCE_TPU: 8}, ns="ml"))
        store.delete("Node", "n2")
        ledger.observe(T0)
        assert ledger._canonical_state() == state_from_store(store)


class TestQuotas:
    def test_borrowed_and_starved_in_debug_payload(self):
        from nos_tpu.api.v1alpha1.elasticquota import ElasticQuota, ElasticQuotaSpec
        from nos_tpu.kube.objects import ObjectMeta

        store, ledger = make_ledger()
        store.create(build_tpu_node(name="n1", chips=16, topology="4x4"))
        borrower = ElasticQuota(
            metadata=ObjectMeta(name="q-ml", namespace="ml"),
            spec=ElasticQuotaSpec(
                min={constants.RESOURCE_TPU_CHIPS: 4},
                max={constants.RESOURCE_TPU_CHIPS: 16},
            ),
        )
        borrower.status.used = {constants.RESOURCE_TPU_CHIPS: 10}
        starved = ElasticQuota(
            metadata=ObjectMeta(name="q-batch", namespace="batch"),
            spec=ElasticQuotaSpec(
                min={constants.RESOURCE_TPU_CHIPS: 8},
                max={constants.RESOURCE_TPU_CHIPS: 8},
            ),
        )
        starved.status.used = {constants.RESOURCE_TPU_CHIPS: 2}
        store.create(borrower)
        store.create(starved)
        # batch has queued demand, so its unused min counts as starvation.
        store.create(build_pod("pend", {constants.RESOURCE_TPU: 4}, ns="batch"))
        ledger.observe(T0)
        quotas = ledger.debug_payload()["quotas"]
        assert quotas["ml/q-ml"]["borrowed_chips"] == 6
        assert quotas["ml/q-ml"]["starved_chips"] == 0
        assert quotas["batch/q-batch"]["borrowed_chips"] == 0
        assert quotas["batch/q-batch"]["starved_chips"] == 6
        assert ledger.self_check() == []


class TestDebugPayload:
    def test_document_shape_and_links(self):
        store, ledger = make_ledger()
        store.create(build_tpu_node(name="n1", chips=8))
        store.create(build_pod("w", {constants.RESOURCE_TPU: 4}, node="n1"))
        store.create(build_pod("pend", {constants.RESOURCE_TPU: 2}, ns="ml"))
        ledger.observe(T0, unserved={"ml/pend": "insufficient capacity: 2"})
        ledger.observe(T0 + 10, unserved={"ml/pend": "insufficient capacity: 2"})
        doc = ledger.debug_payload()
        assert doc["revision"] == store.revision
        assert doc["window_seconds"] == 10.0
        cluster = doc["cluster"]
        assert cluster["total_chips"] == 8
        assert cluster["used_chips"] == 4
        assert cluster["pending_chips"] == 2
        assert cluster["utilization"] == pytest.approx(0.5)
        assert cluster["chip_seconds"]["idle"][BUCKET_PENDING] == 20.0
        assert doc["nodes"]["n1"]["utilization"] == pytest.approx(0.5)
        pend = doc["pending_pods"][0]
        assert pend["pod"] == "ml/pend"
        assert pend["reason"] == "insufficient capacity: 2"
        assert pend["links"]["explain"] == "/debug/explain?pod=ml/pend"
        assert doc["links"]["vars"] == "/debug/vars"


class TestAuditorIntegration:
    def test_audit_plan_runs_capacity_ledger_check(self):
        from nos_tpu.record.audit import InvariantAuditor

        store, ledger = make_ledger()
        store.create(build_tpu_node(name="n1", chips=8))
        ledger.observe(T0)
        ledger._bound["default/ghost"] = ("n1", 4, "default")
        auditor = InvariantAuditor(sample_rate=1.0)
        violations = [
            v
            for v in auditor.check_capacity_ledger(ledger)
            if v.check == "capacity_ledger"
        ]
        assert violations and "ghost" in violations[0].detail
        assert auditor.check_capacity_ledger(None) == []


class TestChaosOracle:
    def test_ledger_consistent_oracle(self):
        import time

        from nos_tpu.chaos import oracles

        class FakePartitioner:
            capacity_ledger = None

        store, ledger = make_ledger()
        store.create(build_tpu_node(name="n1", chips=8))
        p = FakePartitioner()
        assert oracles.ledger_consistent(p, store) == []  # no ledger: skip
        p.capacity_ledger = ledger
        assert oracles.ledger_consistent(p, store) == []
        ledger._pending["ml/ghost"] = (4, "ml")
        time.sleep(0.001)
        out = oracles.ledger_consistent(p, store)
        assert out and out[0].startswith("ledger-consistent:")


class TestReasonTieBreak:
    """The dominant reason must be a pure function of the reason COUNTS —
    never of dict insertion order — because forecast records and replay
    drift comparisons inherit the field verbatim."""

    def test_multiway_tie_every_insertion_order(self):
        import itertools

        pods = [
            ("p1", "beta"),
            ("p2", "alpha"),
            ("p3", "beta"),
            ("p4", "alpha"),
            ("p5", "gamma"),
        ]
        # alpha and beta tie at 2 (gamma trails): alpha wins every order.
        for perm in itertools.permutations(pods):
            assert dominant_unserved_reason(dict(perm)) == "alpha"

    def test_count_beats_lexicographic_order(self):
        assert (
            dominant_unserved_reason({"a": "zzz", "b": "zzz", "c": "aaa"})
            == "zzz"
        )


def _gang_pod(name, gang="big", size=2, node=""):
    from nos_tpu.scheduler.plugins.gang import GANG_NAME_LABEL, GANG_SIZE_LABEL

    pod = build_pod(name, {constants.RESOURCE_TPU: 4}, node=node)
    pod.metadata.labels[GANG_NAME_LABEL] = gang
    pod.metadata.labels[GANG_SIZE_LABEL] = str(size)
    return pod


class TestGangClockResets:
    """Wait clocks across the ugly lifecycles: members deleted before
    the gang ever binds, and preempt-then-resubmit. A same-named
    re-arrival must always start from a FRESH arrival stamp — the
    forecast accuracy join reads these waits as ground truth."""

    def test_deleted_before_bound_drops_clock(self):
        store, ledger = make_ledger()
        store.create(build_tpu_node(name="n1", chips=8))
        store.create(_gang_pod("g0"))
        store.create(_gang_pod("g1"))
        ledger.observe(T0)
        ledger.note_gang_arrival("default/big", T0)
        ledger.note_gang_feasible("default/big", T0 + 2)
        assert "default/big" in ledger.gang_clocks()
        # One member deleted: the gang still exists, the clock survives.
        store.delete("Pod", "g0", "default")
        ledger.observe(T0 + 3)
        assert "default/big" in ledger.gang_clocks()
        # Last member deleted before bound: the clock must go with it.
        store.delete("Pod", "g1", "default")
        ledger.observe(T0 + 4)
        assert ledger.gang_clocks() == {}
        # A late bound observation is a no-op, not a bogus recent entry.
        ledger.note_gang_bound("default/big", T0 + 5)
        assert ledger.debug_payload()["gangs"]["recent"] == []

    def test_same_named_rearrival_gets_fresh_clock(self):
        store, ledger = make_ledger()
        store.create(_gang_pod("g0"))
        ledger.observe(T0)
        ledger.note_gang_arrival("default/big", T0)
        store.delete("Pod", "g0", "default")
        ledger.observe(T0 + 5)
        assert ledger.gang_clocks() == {}
        # Resubmission under the same gang name: arrival restarts at the
        # new time, and the full arrival→feasible→bound flow is coherent.
        store.create(_gang_pod("g0"))
        store.create(_gang_pod("g1"))
        ledger.observe(T0 + 10)
        ledger.note_gang_arrival("default/big", T0 + 10)
        assert ledger.gang_clocks()["default/big"]["arrival"] == T0 + 10
        ledger.note_gang_feasible("default/big", T0 + 11)
        ledger.note_gang_bound("default/big", T0 + 12)
        recent = ledger.debug_payload()["gangs"]["recent"]
        assert recent == [
            {"gang": "default/big", "wait_seconds": 2.0, "feasible_after": 1.0}
        ]

    def test_preempt_then_resubmit_measures_two_waits(self):
        store, ledger = make_ledger()
        store.create(build_tpu_node(name="n1", chips=8))
        store.create(_gang_pod("g0", node="n1"))
        store.create(_gang_pod("g1", node="n1"))
        ledger.observe(T0)
        ledger.note_gang_arrival("default/big", T0)
        ledger.note_gang_feasible("default/big", T0 + 1)
        ledger.note_gang_bound("default/big", T0 + 2)
        # Preemption: both members evicted, gang resubmitted pending.
        store.delete("Pod", "g0", "default")
        store.delete("Pod", "g1", "default")
        ledger.observe(T0 + 20)
        store.create(_gang_pod("g0"))
        store.create(_gang_pod("g1"))
        ledger.observe(T0 + 21)
        ledger.note_gang_arrival("default/big", T0 + 21)
        clock = ledger.gang_clocks()["default/big"]
        assert clock == {"arrival": T0 + 21}  # no stale feasible stamp
        ledger.note_gang_feasible("default/big", T0 + 24)
        ledger.note_gang_bound("default/big", T0 + 26)
        recent = ledger.debug_payload()["gangs"]["recent"]
        assert [r["wait_seconds"] for r in recent] == [2.0, 5.0]
        assert [r["feasible_after"] for r in recent] == [1.0, 3.0]

    def test_gang_bound_listener_fires_with_wait(self):
        _, ledger = make_ledger()
        calls = []
        ledger.add_gang_bound_listener(
            lambda gang, now, wait: calls.append((gang, now, wait))
        )
        ledger.note_gang_arrival("ml/g", T0)
        ledger.note_gang_bound("ml/g", T0 + 5)
        assert calls == [("ml/g", T0 + 5, 5.0)]
        # A raising listener is logged, never propagated.
        ledger.add_gang_bound_listener(lambda *a: 1 / 0)
        ledger.note_gang_arrival("ml/g2", T0)
        ledger.note_gang_bound("ml/g2", T0 + 1)
        assert calls[-1] == ("ml/g2", T0 + 1, 1.0)


class TestReconfigRate:
    """Frozen-edge timing: the measured re-carve latency the forecaster
    prices recarve ETAs with."""

    def test_frozen_edges_measure_reconfig_seconds(self):
        store, ledger = make_ledger()
        store.create(build_tpu_node(name="n1", chips=8))
        ledger.observe(T0)
        assert ledger.mean_reconfig_seconds(default=0.7) == 0.7
        node = store.get("Node", "n1")
        node.metadata.annotations[annot.SPEC_PARTITIONING_PLAN] = "p1"
        store.update(node)
        ledger.observe(T0 + 1)  # rising edge: reconfig starts
        assert ledger.reconfig_stats()["in_flight"] == ["n1"]
        node = store.get("Node", "n1")
        node.metadata.annotations[annot.STATUS_PARTITIONING_PLAN] = "p1"
        store.update(node)
        ledger.observe(T0 + 4)  # falling edge: 3 s reconfig
        assert ledger.mean_reconfig_seconds() == 3.0
        stats = ledger.reconfig_stats()
        assert stats == {"count": 1, "seconds_total": 3.0, "in_flight": []}
        # Reconfig stats stay OUT of the replay-compared totals payload.
        assert "reconfig_count" not in ledger.totals()

    def test_node_deleted_mid_reconfig_drops_the_edge(self):
        store, ledger = make_ledger()
        store.create(build_tpu_node(name="n1", chips=8))
        ledger.observe(T0)
        node = store.get("Node", "n1")
        node.metadata.annotations[annot.SPEC_PARTITIONING_PLAN] = "p1"
        store.update(node)
        ledger.observe(T0 + 1)
        store.delete("Node", "n1")
        ledger.observe(T0 + 2)
        assert ledger.reconfig_stats() == {
            "count": 0,
            "seconds_total": 0.0,
            "in_flight": [],
        }
        assert ledger.mean_reconfig_seconds(default=0.5) == 0.5
