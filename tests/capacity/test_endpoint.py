"""/debug/capacity endpoint: bearer gate, rollup document, index entry."""
import http.client
import json

from nos_tpu.api.v1alpha1 import constants
from nos_tpu.capacity import CapacityLedger
from nos_tpu.kube.store import KubeStore
from nos_tpu.util.health import HealthServer

from tests.factory import build_pod, build_tpu_node


def _get(port, path, token=None):
    conn = http.client.HTTPConnection("127.0.0.1", port, timeout=2)
    headers = {"Authorization": f"Bearer {token}"} if token else {}
    conn.request("GET", path, headers=headers)
    resp = conn.getresponse()
    return resp.status, resp.read().decode()


def _make_ledger():
    store = KubeStore()
    ledger = CapacityLedger(store, metrics=False)
    store.create(build_tpu_node(name="n1", chips=8))
    store.create(build_pod("w", {constants.RESOURCE_TPU: 4}, node="n1"))
    store.create(build_pod("pend", {constants.RESOURCE_TPU: 2}, ns="ml"))
    ledger.observe(1000.0, unserved={"ml/pend": "insufficient capacity: 2"})
    ledger.observe(1010.0, unserved={"ml/pend": "insufficient capacity: 2"})
    return ledger


class TestDebugCapacityEndpoint:
    def test_serves_rollup_behind_bearer_gate(self):
        ledger = _make_ledger()
        server = HealthServer(
            port=0, metrics_token="s3cret", capacity_fn=ledger.debug_payload
        )
        port = server.start()
        try:
            assert _get(port, "/debug/capacity")[0] == 401
            assert _get(port, "/debug/capacity", "wrong")[0] == 401
            status, body = _get(port, "/debug/capacity", "s3cret")
            assert status == 200
            doc = json.loads(body)
            assert doc["cluster"]["total_chips"] == 8
            assert doc["cluster"]["used_chips"] == 4
            assert doc["cluster"]["utilization"] == 0.5
            assert doc["nodes"]["n1"]["free_chips"] == 4
            assert doc["pending_pods"][0]["pod"] == "ml/pend"
            assert doc["pending_pods"][0]["links"]["explain"] == (
                "/debug/explain?pod=ml/pend"
            )
        finally:
            server.stop()

    def test_404_when_no_ledger_is_wired(self):
        server = HealthServer(port=0)
        port = server.start()
        try:
            assert _get(port, "/debug/capacity")[0] == 404
        finally:
            server.stop()

    def test_debug_index_lists_capacity_when_wired(self):
        ledger = _make_ledger()
        server = HealthServer(port=0, capacity_fn=ledger.debug_payload)
        port = server.start()
        try:
            status, body = _get(port, "/debug/")
            assert status == 200
            endpoints = json.loads(body)["endpoints"]
            assert "/debug/capacity" in endpoints
        finally:
            server.stop()

    def test_debug_index_omits_capacity_when_absent(self):
        server = HealthServer(port=0)
        port = server.start()
        try:
            endpoints = json.loads(_get(port, "/debug/")[1])["endpoints"]
            assert "/debug/capacity" not in endpoints
        finally:
            server.stop()
