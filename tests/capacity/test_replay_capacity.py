"""Recorded capacity observes replay with zero drift — and tampered
records are caught, proving the comparison has teeth."""
import json

from nos_tpu.api.v1alpha1 import constants
from nos_tpu.capacity import CapacityLedger
from nos_tpu.kube.store import KubeStore
from nos_tpu.record import FlightRecorder
from nos_tpu.record.replay import ReplaySession

from tests.factory import PodPhase, build_pod, build_tpu_node

T0 = 1_000_000.0


def recorded_run():
    """A short live run with the recorder attached: nodes arrive, pods
    pend, bind, and finish, with an integrating observe between each
    transition. Returns the flight record after a JSON round-trip — the
    same framing `python -m nos_tpu replay` consumes."""
    store = KubeStore()
    recorder = FlightRecorder()
    # Both the recorder and the ledger subscribe before any traffic, the
    # same construction order run.py uses, so replay sees every delta.
    recorder.attach(store)
    ledger = CapacityLedger(store, flight_recorder=recorder, metrics=False)
    store.create(build_tpu_node(name="n1", chips=8))
    store.create(build_tpu_node(name="n2", chips=8))
    store.create(build_pod("pend", {constants.RESOURCE_TPU: 4}, ns="ml"))
    ledger.observe(T0, unserved={"ml/pend": "insufficient capacity: 4"})
    ledger.observe(T0 + 5, unserved={"ml/pend": "insufficient capacity: 4"})
    bound = build_pod("pend", {constants.RESOURCE_TPU: 4}, ns="ml", node="n1")
    store.update(bound)
    ledger.observe(T0 + 8, unserved={})
    done = build_pod(
        "pend", {constants.RESOURCE_TPU: 4}, ns="ml", node="n1",
        phase=PodPhase.SUCCEEDED,
    )
    store.update(done)
    store.delete("Node", "n2")
    ledger.observe(T0 + 12, unserved={})
    recorder.detach()
    return [json.loads(line) for line in recorder.to_jsonl().splitlines()]


class TestReplayCapacity:
    def test_zero_drift(self):
        records = recorded_run()
        observes = [r for r in records if r["kind"] == "capacity.observe"]
        assert len(observes) == 4
        assert observes[0]["reason"] == "insufficient capacity"
        assert observes[-1]["reason"] is None  # demand drained
        # The recorded integrals carry real chip-seconds, not zeros.
        assert observes[-1]["totals"]["total"] > 0
        assert observes[-1]["totals"]["idle"]["pending-unschedulable"] > 0

        report = ReplaySession(records).run()
        assert report.capacity_observes == 4
        assert report.drifts == []
        assert report.violations == []
        assert report.ok()
        assert "4 capacity observe(s)" in report.render()

    def test_tampered_totals_are_reported_as_drift(self):
        records = recorded_run()
        tampered = next(
            r
            for r in records
            if r["kind"] == "capacity.observe" and r["totals"]["busy"] > 0
        )
        tampered["totals"]["busy"] += 1.0
        report = ReplaySession(records).run()
        drifts = [d for d in report.drifts if d["kind"] == "capacity.observe"]
        assert len(drifts) == 1
        assert drifts[0]["seq"] == tampered["seq"]
        assert not report.ok()
