"""Committed chaos fixtures replay as regression tests.

Every ``tests/chaos/fixtures/*.jsonl`` is auto-discovered: its filename
encodes the failure signature the chaos driver minimized it down to
(``chaos-seed<N>-<oracle-names>.jsonl``, ``full`` = no replayable
signature), and replaying it must keep producing EXACTLY that signature.
Drop a new fixture in the directory and it becomes a test case — no
registration step.
"""
import os

import pytest

from nos_tpu.chaos.minimize import failure_signature, signature_names
from nos_tpu.record import ReplaySession
from nos_tpu.record.recorder import load_jsonl

FIXTURES_DIR = os.path.join(os.path.dirname(__file__), "fixtures")

# Oracle base names a fixture filename may carry (chaos/oracles.py plus
# the minimizer's crash sentinel).
KNOWN_NAMES = (
    "actuation-converged",
    "auditor-clean",
    "no-orphaned-reservations",
    "pending-settled",
    "replay-clean",
    "replay-crash",
    "timeline-clean",
)


def expected_names(stem: str):
    """Parse the oracle names out of a driver-style fixture filename."""
    tail = stem.split("-", 2)[2] if stem.count("-") >= 2 else ""
    if not tail or tail == "full":
        return []
    names = []
    while tail:
        for name in KNOWN_NAMES:
            if tail == name or tail.startswith(name + "-"):
                names.append(name)
                tail = tail[len(name) + 1 :]
                break
        else:
            raise ValueError(f"fixture name segment {tail!r} is not an oracle name")
    return sorted(names)


def _fixtures():
    if not os.path.isdir(FIXTURES_DIR):
        return []
    return sorted(f for f in os.listdir(FIXTURES_DIR) if f.endswith(".jsonl"))


@pytest.mark.parametrize("filename", _fixtures())
def test_fixture_reproduces_its_signature(filename):
    path = os.path.join(FIXTURES_DIR, filename)
    records = load_jsonl(path)
    assert records, f"{filename} is empty"
    signature = failure_signature(records)
    assert signature_names(signature) == expected_names(filename[: -len(".jsonl")])


@pytest.mark.parametrize("filename", _fixtures())
def test_fixture_replay_is_deterministic(filename):
    path = os.path.join(FIXTURES_DIR, filename)
    first = ReplaySession(load_jsonl(path)).run()
    second = ReplaySession(load_jsonl(path)).run()
    assert first.drifts == second.drifts
    assert first.violations == second.violations
    assert (first.cycles, first.plans, first.skips) == (
        second.cycles, second.plans, second.skips,
    )


def test_discovery_found_the_committed_fixtures():
    """The repo ships at least one clean pin and one drift repro; if this
    fails the fixtures directory went missing from the checkout."""
    names = _fixtures()
    assert any("full" in n for n in names), names
    assert any("replay-clean" in n for n in names), names
