"""The slow chaos soak (`make chaos` in test form): many seeds, both
backends, every burst must converge and every log must replay with zero
drift and zero auditor violations. Marked slow — the tier-1 suite runs
the single-seed smoke instead (test_smoke.py).
"""
import pytest

from nos_tpu.chaos.driver import ChaosConfig, ChaosDriver

pytestmark = pytest.mark.slow

MEMORY_SEEDS = range(0, 25)
APISERVER_SEEDS = range(0, 4)


@pytest.mark.parametrize("seed", MEMORY_SEEDS)
def test_memory_seed_converges_and_replays_clean(seed):
    report = ChaosDriver(
        ChaosConfig(
            seed=seed, bursts=2, nodes=3, backend="memory",
            burst_s=0.4, convergence_timeout_s=30.0, minimize=False,
        )
    ).run()
    assert report.ok(), report.render()


@pytest.mark.parametrize("seed", APISERVER_SEEDS)
def test_apiserver_seed_converges_and_replays_clean(seed):
    report = ChaosDriver(
        ChaosConfig(
            seed=seed, bursts=2, nodes=3, backend="apiserver",
            burst_s=1.0, convergence_timeout_s=30.0, minimize=False,
        )
    ).run()
    assert report.ok(), report.render()


# Seed chosen so the schedule fires worker-kill in BOTH bursts (burst 0
# usually lands before the lazily-spawned workers exist — the recorded
# no-op path — burst 1 on a live worker mid-run).
PROCESS_SEED = 3


def test_process_backend_seed_survives_worker_kill():
    from nos_tpu.chaos import faults as F

    config = ChaosConfig(
        seed=PROCESS_SEED, bursts=2, nodes=3, backend="memory",
        burst_s=0.4, convergence_timeout_s=30.0, minimize=False,
        pool_backend="process",
    )
    driver = ChaosDriver(config)
    kills = [
        f for burst in driver.schedule for f in burst.faults
        if f.kind == F.WORKER_KILL
    ]
    assert len(kills) == 2, "seed no longer schedules worker-kill twice"
    report = driver.run()
    assert report.ok(), report.render()
