"""The autoscaler-settled oracle: a healed cluster's replica fleets must
match what the pure decision function says, and the sweep's driver wires
the live autoscaler into every convergence poll."""
from nos_tpu.api.config import AutoscalerConfig
from nos_tpu.api.v1alpha1 import labels
from nos_tpu.api.v1alpha1.modelserving import ModelServing, ModelServingSpec
from nos_tpu.chaos import oracles
from nos_tpu.controllers.autoscaler import ModelServingReconciler, SignalRegistry
from nos_tpu.controllers.autoscaler.controller import serving_key
from nos_tpu.kube.controller import Request
from nos_tpu.kube.objects import ObjectMeta
from nos_tpu.kube.store import KubeStore

from tests.factory import build_tpu_node


def _rig(min_replicas=1):
    store = KubeStore()
    clock = {"t": 100.0}
    signals = SignalRegistry(now_fn=lambda: clock["t"])
    autoscaler = ModelServingReconciler(
        store, AutoscalerConfig(), signals=signals
    )
    store.create(build_tpu_node(name="n0"))
    store.create(
        ModelServing(
            metadata=ObjectMeta(name="svc", namespace="default"),
            spec=ModelServingSpec(
                model="svc", min_replicas=min_replicas, max_replicas=2,
                slos=["p95 ttft < 1s"],
            ),
        )
    )
    return store, clock, autoscaler


def test_settled_fleet_passes():
    store, clock, autoscaler = _rig()
    autoscaler.reconcile(Request(name="svc", namespace="default"))
    assert oracles.autoscaler_settled(store, autoscaler) == []


def test_wedged_reconciler_is_flagged():
    # Status says one replica is desired but no pod exists: a burst ate
    # the replica and the reconciler never actuated the verdict.
    store, clock, autoscaler = _rig()
    autoscaler.reconcile(Request(name="svc", namespace="default"))
    store.delete("Pod", "svc-replica-0", "default")
    clock["t"] = 200.0
    violations = oracles.autoscaler_settled(store, autoscaler)
    assert violations and violations[0].startswith(oracles.AUTOSCALER_SETTLED)
    # ...and healing it (one reconcile) clears the oracle.
    autoscaler.reconcile(Request(name="svc", namespace="default"))
    assert oracles.autoscaler_settled(store, autoscaler) == []


def test_terminating_replicas_are_not_settled():
    store, clock, autoscaler = _rig()
    autoscaler.reconcile(Request(name="svc", namespace="default"))

    def mark(p):
        p.metadata.deletion_timestamp = 123.0

    store.patch_merge("Pod", "svc-replica-0", "default", mark)
    violations = oracles.autoscaler_settled(store, autoscaler)
    assert violations and "tearing down" in violations[0]


def test_check_convergence_includes_the_autoscaler():
    store, clock, autoscaler = _rig()
    autoscaler.reconcile(Request(name="svc", namespace="default"))
    ms = store.get("ModelServing", "svc", "default")
    pod = store.get("Pod", "svc-replica-0", "default")
    assert pod.metadata.labels[labels.MODEL_SERVING_LABEL] == serving_key(ms)
    store.delete("Pod", "svc-replica-0", "default")
    clock["t"] = 200.0
    # Replica pods pend-free here (deleted), so the only violations come
    # from the autoscaler oracle — and only when it is passed in.
    assert oracles.check_convergence(store) == []
    out = oracles.check_convergence(store, autoscaler=autoscaler)
    assert oracles.failing_oracles(out) == [oracles.AUTOSCALER_SETTLED]


def test_chaos_driver_builds_with_the_autoscaler():
    from nos_tpu.chaos.driver import MODEL_SERVING_NAME, ChaosConfig, ChaosDriver

    driver = ChaosDriver(
        ChaosConfig(seed=3, bursts=1, nodes=2, backend="memory", burst_s=0.2)
    )
    # The sweep rides this same _build path for all 50 seeds: the
    # autoscaler component and its ModelServing are part of every run.
    driver._build()
    try:
        assert driver.cluster.autoscaler is not None
        assert (
            driver.cluster.store.try_get(
                "ModelServing", MODEL_SERVING_NAME, "default"
            )
            is not None
        )
    finally:
        driver.cluster.stop()
