"""`make chaos-smoke` in test form: one fixed-seed memory-backend run
must converge every burst, replay with zero drift, and (run twice) fire
the exact same fault schedule — the determinism the fixtures depend on.
"""
from nos_tpu.chaos.driver import ChaosConfig, ChaosDriver
from nos_tpu.chaos.faults import build_schedule

SMOKE = dict(seed=7, bursts=2, nodes=2, backend="memory", burst_s=0.4)


def _config(**overrides):
    kw = dict(SMOKE, convergence_timeout_s=30.0, minimize=False)
    kw.update(overrides)
    return ChaosConfig(**kw)


def test_smoke_seed_converges_and_replays_clean():
    report = ChaosDriver(_config()).run()
    assert report.ok(), report.render()
    assert len(report.bursts) == 2
    for burst in report.bursts:
        assert burst.converged, report.render()
    assert report.replay_ok, report.render()
    assert report.records > 0
    # The schedule fired real faults and the ledger kept count.
    assert report.fault_counts, report.render()


def test_same_seed_same_fault_schedule():
    a = ChaosDriver(_config())
    b = ChaosDriver(_config())
    assert [
        [(f.kind, f.target, f.param, f.at) for f in burst.faults]
        for burst in a.schedule
    ] == [
        [(f.kind, f.target, f.param, f.at) for f in burst.faults]
        for burst in b.schedule
    ]
    # And it is exactly the pure-function schedule: the driver adds nothing.
    pure = build_schedule(7, 2, ["chaos-node-0", "chaos-node-1"], "memory", 0.4)
    assert [
        [(f.kind, f.at) for f in burst.faults] for burst in a.schedule
    ] == [[(f.kind, f.at) for f in burst.faults] for burst in pure]


def test_cli_smoke_exits_zero(capsys):
    from nos_tpu.cmd.chaos import main

    rc = main(
        [
            "--seed", "7",
            "--bursts", "1",
            "--nodes", "2",
            "--burst-seconds", "0.4",
            "--timeout", "30",
        ]
    )
    out = capsys.readouterr().out
    assert rc == 0, out
    assert "replay: clean" in out
