"""Determinism and seam contracts of the fault vocabulary.

The chaos harness's whole value rests on ``build_schedule`` being a pure
function of its arguments — same seed, same story — and on the injector
firing from deterministic per-seam counters rather than shared RNG state.
"""
import pytest

from nos_tpu.chaos import faults as F
from nos_tpu.chaos.faults import FaultInjector, build_schedule
from nos_tpu.kube.store import ConflictError

NODES = ["n0", "n1", "n2"]


def _flat(schedule):
    return [
        (b.index, b.duration_s, tuple(b.pods), tuple(
            (f.kind, f.target, f.param, f.at) for f in b.faults
        ))
        for b in schedule
    ]


def test_same_seed_same_schedule():
    a = build_schedule(42, 4, NODES, backend="apiserver", burst_s=2.0)
    b = build_schedule(42, 4, NODES, backend="apiserver", burst_s=2.0)
    assert _flat(a) == _flat(b)


def test_different_seeds_diverge():
    flats = {tuple(_flat(build_schedule(s, 3, NODES))) for s in range(8)}
    assert len(flats) > 1


def test_schedule_is_pure_of_global_rng():
    import random

    a = build_schedule(7, 3, NODES)
    random.seed(999)
    random.random()
    b = build_schedule(7, 3, NODES)
    assert _flat(a) == _flat(b)


def test_memory_backend_excludes_http_faults():
    schedule = build_schedule(3, 20, NODES, backend="memory")
    kinds = {f.kind for b in schedule for f in b.faults}
    assert kinds.isdisjoint({F.WATCH_SEVER, F.API_ERRORS, F.API_LATENCY})
    assert kinds  # something still fires


def test_every_burst_has_faults_and_pods():
    for burst in build_schedule(11, 6, NODES, backend="apiserver"):
        assert 2 <= len(burst.faults) <= 4
        assert 2 <= len(burst.pods) <= 4
        assert all(f.at <= burst.duration_s for f in burst.faults)
        for f in burst.faults:
            if f.kind in (F.NODE_DEATH, F.NODE_CORDON_FLAP, F.AGENT_RESTART):
                assert f.target in NODES


def test_conflict_injection_every_nth_write():
    inj = FaultInjector()
    inj.arm_conflicts(2)
    fired = []
    for i in range(6):
        try:
            inj.on_store_write("Pod", f"p{i}")
        except ConflictError:
            fired.append(i)
    assert fired == [1, 3, 5]
    assert inj.counts[F.CONFLICT_WRITES] == 3


def test_suspended_writes_bypass_injection():
    inj = FaultInjector()
    inj.arm_conflicts(1)
    with inj.suspended():
        inj.on_store_write("Pod", "driver-pod")  # must not raise
    with pytest.raises(ConflictError):
        inj.on_store_write("Pod", "victim")


def test_events_never_conflict():
    inj = FaultInjector()
    inj.arm_conflicts(1)
    inj.on_store_write("Event", "telemetry")  # must not raise


def test_error_injection_every_nth_request():
    inj = FaultInjector()
    inj.arm_errors(3)
    results = [inj.on_request("GET", "/api/v1/pods") for _ in range(6)]
    assert [r for r in results if r] == [(503, "ServiceUnavailable")] * 2


def test_sever_budget_is_finite_and_additive():
    inj = FaultInjector()
    inj.arm_sever(2)
    inj.arm_sever(1)
    assert [inj.take_sever() for _ in range(5)] == [True, True, True, False, False]


def test_clear_disarms_everything():
    inj = FaultInjector()
    inj.arm_conflicts(1)
    inj.arm_errors(1)
    inj.arm_sever(5)
    inj.arm_latency(0.5)
    inj.arm_clock_skew(2.0)
    inj.clear()
    inj.on_store_write("Pod", "p")  # no raise
    assert inj.on_request("GET", "/") is None
    assert not inj.take_sever()
    assert inj.skew_seconds() == 0.0


def test_clock_skew_shifts_wall_clock_only():
    """Armed skew pushes the wall-clock seam ahead; heal (clear) snaps it
    back. Monotonic time is never touched — the fault models wall/mono
    divergence, the thing lease stamps and heartbeat ages must survive."""
    import time

    inj = FaultInjector()
    assert abs(inj.wall_clock() - time.time()) < 0.25
    inj.arm_clock_skew(2.0)
    assert inj.skew_seconds() == 2.0
    ahead = inj.wall_clock() - time.time()
    assert 1.75 < ahead < 2.25
    inj.clear()  # heal: wall time snaps BACK — integrators must shrug it off
    assert abs(inj.wall_clock() - time.time()) < 0.25


def test_clock_skew_appears_in_schedules():
    """CLOCK_SKEW is part of the fault vocabulary on every backend and
    always carries a positive jump size."""
    seen = []
    for seed in range(20):
        for burst in build_schedule(seed, 2, NODES, backend="memory"):
            seen.extend(f for f in burst.faults if f.kind == F.CLOCK_SKEW)
    assert seen, "no seed in 0..19 scheduled a clock-skew fault"
    assert all(f.param in (0.5, 1.0, 2.0) for f in seen)
