"""ddmin and failure signatures: the auto-minimizer must isolate a known
failing subset, pin the session header, preserve the exact failure
signature, and round-trip through the JSONL fixture format.
"""
import copy
import json
import time

import pytest

from nos_tpu.chaos import oracles
from nos_tpu.chaos.minimize import (
    ddmin,
    failure_signature,
    minimize_records,
    signature_names,
)
from nos_tpu.record import ReplaySession
from nos_tpu.record.recorder import load_jsonl

HEADER = {"kind": "session.start", "seq": 0, "revision": 0}


def _synthetic(n=24):
    return [dict(HEADER)] + [{"kind": "delta", "seq": i, "i": i} for i in range(n)]


class TestDdmin:
    def test_isolates_known_failing_pair(self):
        """Predicate: fails iff records 3 AND 11 are both present — the
        classic ddmin exercise; the minimum is exactly that pair."""
        records = _synthetic()

        def predicate(subset):
            have = {r.get("i") for r in subset}
            return {3, 11} <= have

        minimal, probes = ddmin(records, predicate)
        body = [r for r in minimal if r["kind"] != "session.start"]
        assert sorted(r["i"] for r in body) == [3, 11]
        assert probes > 0

    def test_header_is_pinned(self):
        records = _synthetic(8)
        minimal, _ = ddmin(records, lambda subset: True)
        assert any(r["kind"] == "session.start" for r in minimal)

    def test_budget_bounds_probe_count(self):
        records = _synthetic(64)
        minimal, probes = ddmin(
            records, lambda subset: {3, 11} <= {r.get("i") for r in subset},
            budget=5,
        )
        assert probes <= 5
        # Best-so-far still fails the predicate (never a healthy result).
        have = {r.get("i") for r in minimal}
        assert {3, 11} <= have

    def test_single_record_input_returns_unchanged(self):
        records = [dict(HEADER), {"kind": "delta", "seq": 1, "i": 0}]
        minimal, _ = ddmin(records, lambda subset: True)
        assert len(minimal) == 2


def _record_healthy_session():
    """A short real cluster session under the recorder (one node, two
    pods, everything binds) — the healthy substrate the tampering tests
    break in controlled ways."""
    from nos_tpu.api.config import (
        GpuPartitionerConfig,
        SchedulerConfig,
        TpuAgentConfig,
    )
    from nos_tpu.cmd.cluster import build_cluster
    from nos_tpu.cmd.run import seed_node, seed_pod
    from nos_tpu.record import FlightRecorder

    fr = FlightRecorder()
    cluster = build_cluster(
        partitioner_config=GpuPartitionerConfig(
            batch_window_timeout_seconds=1.0,
            batch_window_idle_seconds=0.05,
        ),
        scheduler_config=SchedulerConfig(retry_seconds=0.2),
        flight_recorder=fr,
    )
    fr.attach(cluster.store)
    cluster.add_tpu_node(
        seed_node({"name": "node-1", "chips": 8, "topology": "2x4"}),
        TpuAgentConfig(report_config_interval_seconds=0.2),
    )
    cluster.store.create(seed_pod({"name": "w1", "chips": 4}))
    cluster.store.create(seed_pod({"name": "w2", "chips": 4}))
    cluster.start()
    deadline = time.time() + 30
    while time.time() < deadline:
        pods = cluster.store.list("Pod")
        if pods and all(
            p.spec.node_name and p.status.phase == "Running" for p in pods
        ):
            break
        time.sleep(0.2)
    cluster.wait_idle(10)
    cluster.stop()
    fr.detach()
    assert all(p.spec.node_name for p in cluster.store.list("Pod"))
    return fr.records()


@pytest.fixture(scope="module")
def healthy_records():
    return _record_healthy_session()


class TestFailureSignature:
    def test_healthy_log_has_empty_signature(self, healthy_records):
        assert failure_signature(copy.deepcopy(healthy_records)) == frozenset()

    def test_minimize_returns_healthy_input_untouched(self, healthy_records):
        records = copy.deepcopy(healthy_records)
        minimal, signature, probes = minimize_records(records)
        assert signature == frozenset()
        assert probes == 0
        assert minimal is records


class TestBrokenBuildMinimization:
    """The acceptance drill: a deliberately broken recording must shrink
    to a small repro that still fails the SAME way, and the written
    fixture must reproduce after a JSONL round trip."""

    def _tamper(self, records):
        """Flip one recorded bind to 'fail' — the recorded world claims
        the scheduler refused a pod that replay (same inputs) binds: a
        guaranteed replay-clean violation, the signature a regressed
        scheduler build would produce."""
        records = copy.deepcopy(records)
        cycle = next(
            r for r in records
            if r["kind"] == "scheduler.cycle" and r["decision"] == "bind"
        )
        cycle["decision"] = "fail"
        cycle["node"] = ""
        cycle["bound"] = []
        return records

    def test_tampered_log_minimizes_to_small_repro(self, healthy_records, tmp_path):
        tampered = self._tamper(healthy_records)
        minimal, signature, probes = minimize_records(tampered)
        assert oracles.REPLAY_CLEAN in signature_names(signature)
        # The signature pins the exact drifting record, not just the
        # oracle name — ddmin must reproduce THIS drift, not any drift.
        assert any("scheduler.cycle" in s for s in signature)
        assert probes > 0
        body = [r for r in minimal if r["kind"] != "session.start"]
        # The acceptance bound: a handful of deltas + the flipped cycle,
        # not the whole session.
        assert len(body) <= 25, f"minimized to {len(body)} records"
        assert len(minimal) < len(tampered)
        # The minimal subset still fails in exactly the original way.
        assert failure_signature(copy.deepcopy(minimal)) == signature

        # Fixture round trip: dump JSONL, reload, drift still reproduces.
        path = tmp_path / "fixture.jsonl"
        path.write_text(
            "\n".join(json.dumps(r, sort_keys=True) for r in minimal) + "\n"
        )
        reloaded = load_jsonl(str(path))
        assert failure_signature(reloaded) == signature
        report = ReplaySession(load_jsonl(str(path))).run()
        assert report.drifts, report.render()
